"""Test harness: virtual 8-device CPU mesh.

SURVEY.md §4: the JAX analogue of Spark's local-cluster test mode is
``--xla_force_host_platform_device_count=8`` on the CPU backend — every
sharding/psum path becomes testable without TPU hardware, and sharded fits
can be asserted equal to single-device fits.

Must set the env vars before jax initializes, hence module-level here.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The image's sitecustomize imports jax (axon TPU platform) before pytest
# runs, so env vars alone are too late; the config route still works
# because backends are initialized lazily.
jax.config.update("jax_platforms", "cpu")
try:
    # jax ≥ 0.5 route; 0.4.x doesn't know the option and raises
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # the XLA_FLAGS fallback above covers 0.4.x

assert jax.device_count() == 8, (
    f"virtual 8-device CPU mesh not in effect (got {jax.device_count()} "
    "devices) — every sharding/psum test below would silently degrade"
)

# Persistent compilation cache: the suite is XLA-compile-bound on a 1-core
# host (every estimator family compiles per-shape executables), and the
# programs are identical run to run — a warm cache cuts the full suite
# from ~12 min to a fraction.  Opt out with JAX_TEST_CACHE=0 (e.g. when
# bisecting a compiler-level issue).
if os.environ.get("JAX_TEST_CACHE", "1") != "0":
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("JAX_TEST_CACHE_DIR", "/tmp/cmlhn_jax_test_cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

try:  # installed copy (pip install -e .) takes precedence
    import clustermachinelearningforhospitalnetworks_apache_spark_tpu  # noqa: F401
except ImportError:  # running from a raw checkout
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_numpy_rank_promotion", "raise")

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht  # noqa: E402
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils.logging import (  # noqa: E402
    configure_logging,
)

configure_logging(level="warning")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "fast: cross-subsystem smoke subset (python -m pytest tests/ -m fast, "
        "~2 min on the CPU mesh; full suite: -n 4 via pytest-xdist)",
    )
    config.addinivalue_line(
        "markers",
        "slow: long-running tests excluded from the tier-1 budget "
        "(tier-1 runs -m 'not slow')",
    )
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection kill-and-resume tests "
        "(tools/run_chaos.sh runs just these with a per-site table)",
    )
    config.addinivalue_line(
        "markers",
        "quality: data-quality firewall tests — row validation, schema "
        "drift, quarantine, PSI drift (python -m pytest tests/ -m quality)",
    )
    config.addinivalue_line(
        "markers",
        "perf: performance-contract tests — pipelined-vs-serial parity, "
        "donation/zero-recompile, bench plumbing (pytest -m perf)",
    )
    config.addinivalue_line(
        "markers",
        "lifecycle: continuous-learning loop tests — drift-triggered "
        "retrain, shadow/canary promotion, journal recovery "
        "(pytest -m lifecycle)",
    )
    config.addinivalue_line(
        "markers",
        "farm: model-farm tests — vmapped per-tenant fits, looped-baseline "
        "bit-parity, tenant routing, drifted-subset refit (pytest -m farm)",
    )
    config.addinivalue_line(
        "markers",
        "fleet: serving-fleet tests — placement, tenant routing, SLO "
        "admission, atomic promotion, replica chaos (pytest -m fleet)",
    )
    config.addinivalue_line(
        "markers",
        "lint: framework-invariant-linter tests — per-rule fixtures, "
        "suppression/baseline machinery, the tier-1 repo-clean meta-test "
        "(pytest -m lint)",
    )
    config.addinivalue_line(
        "markers",
        "federated: cross-silo federated-fit tests — partials/pooled "
        "bit-parity per family, quorum/dropout ladder, round-journal "
        "resume (pytest -m federated)",
    )
    config.addinivalue_line(
        "markers",
        "soak: compressed-production-day chaos soak tests — the smoke "
        "run's machine-checked SoakReport, schedule replayability, "
        "report CRC discipline (pytest -m soak; tools/soak.py --full "
        "for the slow shape)",
    )
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel import (  # noqa: E402
    build_mesh,
    set_default_mesh,
    single_device_mesh,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.config import MeshConfig  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    """8-device (data=8, model=1) mesh."""
    return build_mesh(MeshConfig(data=8, model=1))


@pytest.fixture(scope="session")
def mesh42():
    """2-D mesh: data=4, model=2 — exercises the model-axis shardings."""
    return build_mesh(MeshConfig(data=4, model=2))


@pytest.fixture(scope="session")
def mesh1():
    return single_device_mesh()


@pytest.fixture(autouse=True)
def _default_mesh(mesh8):
    set_default_mesh(mesh8)
    yield
    set_default_mesh(None)


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture
def hospital_table(rng):
    """Synthetic hospital-event table matching the reference schema
    (mllearnforhospitalnetwork.py:64-72), with a known linear LOS signal."""
    n = 400
    admission = rng.integers(0, 50, n)
    occupancy = rng.integers(20, 400, n)
    emergency = rng.integers(0, 30, n)
    season = rng.uniform(0.5, 1.5, n)
    noise = rng.normal(0, 0.1, n)
    los = (
        0.05 * admission + 0.01 * occupancy + 0.08 * emergency + 1.5 * season + noise
    )
    base = np.datetime64("2025-03-31T22:00:00")
    times = base + np.arange(n).astype("timedelta64[s]")
    return ht.Table.from_dict(
        {
            "hospital_id": np.array([f"H{int(i) % 5:02d}" for i in range(n)], dtype=object),
            "event_time": times,
            "admission_count": admission,
            "current_occupancy": occupancy,
            "emergency_visits": emergency,
            "seasonality_index": season,
            "length_of_stay": los,
        },
        ht.hospital_event_schema(),
    )
