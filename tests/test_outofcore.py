"""Out-of-core (rows ≫ HBM) fit paths — HostDataset block streaming.

SURVEY.md §7 hard part 3: Spark fits run over disk-backed RDD partitions of
any size (reference ``mllearnforhospitalnetwork.py:146-158``); the TPU
analogue streams ``max_device_rows`` blocks through the mesh and
accumulates the same psum'd sufficient statistics.  The contract under
test: a fit with an artificially small row budget (many blocks) matches
the HBM-resident fit on the same data.
"""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.outofcore import (
    HostDataset,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.sharding import (
    device_dataset,
)


def _int_blobs(n, d, k, seed=0):
    """Integer-valued clustered data: every Lloyd sufficient statistic
    (one-hot sums of small ints) is exactly representable in f32, so the
    resident and blockwise accumulation orders give BIT-IDENTICAL sums —
    the strongest possible equality check."""
    rng = np.random.default_rng(seed)
    centers = rng.integers(-40, 40, size=(k, d))
    x = centers[rng.integers(0, k, size=n)] + rng.integers(-3, 4, size=(n, d))
    return x.astype(np.float32)


@pytest.mark.fast
class TestHostDataset:
    def test_block_shape_and_iteration(self, mesh8):
        hd = HostDataset(x=np.ones((1000, 4), np.float32), max_device_rows=256)
        n_blocks, b = hd.block_shape(mesh8)
        assert b % 8 == 0 and b <= 256 + 7
        blocks = list(hd.blocks(mesh8))
        assert len(blocks) == n_blocks
        # total valid weight across blocks == n (pad rows are w=0)
        assert sum(float(blk.count()) for blk in blocks) == 1000.0

    def test_empty_dataset_yields_no_blocks(self, mesh8):
        hd = HostDataset(x=np.empty((0, 4), np.float32))
        assert list(hd.blocks(mesh8)) == []
        assert hd.block_shape(mesh8)[0] == 0

    def test_weights_and_labels_stream_through(self, mesh8):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(100, 3)).astype(np.float32)
        y = rng.normal(size=100).astype(np.float32)
        w = rng.uniform(0.5, 2.0, size=100).astype(np.float32)
        hd = HostDataset(x=x, y=y, w=w, max_device_rows=32)
        ys, ws = [], []
        for blk in hd.blocks(mesh8):
            wb = np.asarray(blk.w)
            ys.append(np.asarray(blk.y)[wb > 0])
            ws.append(wb[wb > 0])
        np.testing.assert_allclose(np.concatenate(ys), y, rtol=1e-6)
        np.testing.assert_allclose(np.concatenate(ws), w, rtol=1e-6)

    def test_shape_validation(self):
        with pytest.raises(ValueError):
            HostDataset(x=np.ones((10,), np.float32))
        with pytest.raises(ValueError):
            HostDataset(x=np.ones((10, 2), np.float32), y=np.ones(5))
        with pytest.raises(ValueError):
            HostDataset(x=np.ones((10, 2), np.float32), max_device_rows=0)


class TestKMeansOutOfCore:
    def test_bit_equal_to_resident_on_exact_data(self, mesh8):
        x = _int_blobs(4096, 4, k=5)
        est = ht.KMeans(k=5, max_iter=8, seed=3)
        resident = est.fit(device_dataset(x, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x=x, max_device_rows=512), mesh=mesh8)
        # integer-exact sums ⇒ identical assignments/updates every
        # iteration ⇒ bit-identical centers and counts
        np.testing.assert_array_equal(
            resident.cluster_centers, ooc.cluster_centers
        )
        np.testing.assert_array_equal(resident.cluster_sizes, ooc.cluster_sizes)
        assert resident.n_iter == ooc.n_iter
        np.testing.assert_allclose(
            resident.training_cost, ooc.training_cost, rtol=1e-6
        )

    def test_float_data_close(self, mesh8, rng):
        x = (rng.normal(size=(3000, 6)) + 5 * rng.integers(0, 4, size=(3000, 1))).astype(
            np.float32
        )
        est = ht.KMeans(k=4, max_iter=10, seed=0)
        resident = est.fit(device_dataset(x, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x=x, max_device_rows=640), mesh=mesh8)
        np.testing.assert_allclose(
            np.sort(resident.cluster_centers, axis=0),
            np.sort(ooc.cluster_centers, axis=0),
            rtol=1e-4, atol=1e-4,
        )

    def test_cosine_mode(self, mesh8, rng):
        x = rng.normal(size=(1024, 5)).astype(np.float32)
        est = ht.KMeans(k=3, max_iter=6, seed=1, distance_measure="cosine")
        resident = est.fit(device_dataset(x, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x=x, max_device_rows=256), mesh=mesh8)
        np.testing.assert_allclose(
            resident.cluster_centers, ooc.cluster_centers, rtol=1e-4, atol=1e-5
        )

    def test_weighted_rows(self, mesh8, rng):
        x = _int_blobs(2048, 3, k=3, seed=1)
        w = rng.integers(1, 4, size=2048).astype(np.float32)
        est = ht.KMeans(k=3, max_iter=5, seed=0)
        resident = est.fit(
            device_dataset(x, mesh=mesh8, weights=w), mesh=mesh8
        )
        ooc = est.fit(HostDataset(x=x, w=w, max_device_rows=300), mesh=mesh8)
        np.testing.assert_array_equal(
            resident.cluster_centers, ooc.cluster_centers
        )

    def test_memmap_input(self, mesh8, tmp_path):
        """np.memmap streams from disk — the literal rows-bigger-than-
        memory shape."""
        x = _int_blobs(2000, 4, k=3, seed=2)
        p = tmp_path / "rows.npy"
        np.save(p, x)
        xm = np.load(p, mmap_mode="r")
        est = ht.KMeans(k=3, max_iter=5, seed=0)
        resident = est.fit(device_dataset(x, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x=xm, max_device_rows=256), mesh=mesh8)
        np.testing.assert_array_equal(
            resident.cluster_centers, ooc.cluster_centers
        )

    def test_model_axis_sharding(self, mesh42):
        """2-D (data=4, model=2) mesh: the block-stats step's centroid-axis
        all_gather path."""
        x = _int_blobs(1600, 4, k=6, seed=4)
        est = ht.KMeans(k=6, max_iter=5, seed=0)
        resident = est.fit(device_dataset(x, mesh=mesh42), mesh=mesh42)
        ooc = est.fit(HostDataset(x=x, max_device_rows=400), mesh=mesh42)
        np.testing.assert_array_equal(
            resident.cluster_centers, ooc.cluster_centers
        )

    def test_checkpoint_preempt_resume_exact(self, mesh8, tmp_path):
        """checkpoint_dir composes with out-of-core fits (VERDICT r3 #5):
        a fit preempted between iteration-boundary commits resumes from
        the last commit and lands bit-identically (integer-exact sums) on
        the uninterrupted result."""

        class Preempt(RuntimeError):
            pass

        x = _int_blobs(2048, 4, k=4, seed=7)
        hd = HostDataset(x=x, max_device_rows=512)
        base = dict(k=4, seed=0, max_iter=20, tol=0.0)
        uninterrupted = ht.KMeans(**base).fit(
            HostDataset(x=x, max_device_rows=512), mesh=mesh8
        )

        est = ht.KMeans(
            checkpoint_dir=str(tmp_path / "km"), checkpoint_every=1, **base
        )

        def bomb(it, cost, move):
            if it == 2:
                raise Preempt()

        with pytest.raises(Preempt):
            est.fit(hd, mesh=mesh8, on_iteration=bomb)
        seen = []
        resumed = est.fit(
            hd, mesh=mesh8, on_iteration=lambda it, c, m: seen.append(it)
        )
        assert seen[0] == 3  # resumed from the commit at it=2
        np.testing.assert_array_equal(
            resumed.cluster_centers, uninterrupted.cluster_centers
        )
        np.testing.assert_allclose(
            resumed.training_cost, uninterrupted.training_cost, rtol=1e-6
        )
        # if the fit converged exactly at the preempt point, the resumed
        # run needs one extra (no-op) iteration to observe convergence
        assert uninterrupted.n_iter <= resumed.n_iter <= uninterrupted.n_iter + 1

    def test_checkpoint_refuses_different_data(self, mesh8, tmp_path):
        x1 = _int_blobs(512, 3, k=2, seed=1)
        x2 = _int_blobs(512, 3, k=2, seed=2)
        est = ht.KMeans(
            k=2, seed=0, max_iter=3,
            checkpoint_dir=str(tmp_path / "km2"), checkpoint_every=1,
        )
        est.fit(HostDataset(x=x1, max_device_rows=128), mesh=mesh8)
        with pytest.raises(ValueError, match="signature mismatch"):
            est.fit(HostDataset(x=x2, max_device_rows=128), mesh=mesh8)

    def test_checkpoint_ooc_vs_resident_signatures_distinct(
        self, mesh8, tmp_path
    ):
        """An out-of-core checkpoint must not silently resume a RESIDENT
        fit of the same data (different storage signature)."""
        x = _int_blobs(512, 3, k=2, seed=3)
        ckdir = str(tmp_path / "km3")
        est = ht.KMeans(k=2, seed=0, max_iter=3, checkpoint_dir=ckdir,
                        checkpoint_every=1)
        est.fit(HostDataset(x=x, max_device_rows=128), mesh=mesh8)
        with pytest.raises(ValueError, match="signature mismatch"):
            est.fit(device_dataset(x, mesh=mesh8), mesh=mesh8)

    def test_on_iteration_hook(self, mesh8):
        x = _int_blobs(512, 3, k=2)
        seen = []
        ht.KMeans(k=2, max_iter=4, seed=0).fit(
            HostDataset(x=x, max_device_rows=128),
            mesh=mesh8,
            on_iteration=lambda it, cost, move: seen.append((it, cost, move)),
        )
        assert seen and seen[0][0] == 1 and all(np.isfinite(c) for _, c, _ in seen)


class TestLinearRegressionOutOfCore:
    def test_matches_resident_wls(self, mesh8, rng):
        n, d = 5000, 6
        x = rng.normal(size=(n, d)).astype(np.float32)
        beta = rng.normal(size=d)
        y = (x @ beta + 2.5 + rng.normal(0, 0.1, size=n)).astype(np.float32)
        est = ht.LinearRegression()
        resident = est.fit(device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x=x, y=y, max_device_rows=700), mesh=mesh8)
        np.testing.assert_allclose(
            np.asarray(resident.coefficients), np.asarray(ooc.coefficients),
            rtol=2e-4, atol=2e-4,
        )
        np.testing.assert_allclose(
            float(resident.intercept), float(ooc.intercept), rtol=2e-4, atol=2e-4
        )

    def test_shifted_features_stay_stable(self, mesh8, rng):
        """Features with a huge mean (a year column) — the recentering
        shift must keep the f32 Gram from cancelling catastrophically."""
        n = 4096
        x = np.stack(
            [rng.normal(2025.0, 1.0, n), rng.normal(0.0, 1.0, n)], axis=1
        ).astype(np.float32)
        y = (0.5 * (x[:, 0] - 2025.0) + 2.0 * x[:, 1] + 7.0).astype(np.float32)
        ooc = ht.LinearRegression().fit(
            HostDataset(x=x, y=y, max_device_rows=512), mesh=mesh8
        )
        coef = np.asarray(ooc.coefficients)
        np.testing.assert_allclose(coef, [0.5, 2.0], rtol=1e-2, atol=1e-2)

    def test_elastic_net_path(self, mesh8, rng):
        n, d = 4096, 8
        x = rng.normal(size=(n, d)).astype(np.float32)
        beta = np.zeros(d)
        beta[:3] = [2.0, -1.5, 1.0]       # sparse truth
        y = (x @ beta + rng.normal(0, 0.05, size=n)).astype(np.float32)
        est = ht.LinearRegression(reg_param=0.1, elastic_net_param=1.0)
        resident = est.fit(device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x=x, y=y, max_device_rows=600), mesh=mesh8)
        np.testing.assert_allclose(
            np.asarray(resident.coefficients), np.asarray(ooc.coefficients),
            rtol=5e-3, atol=5e-3,
        )
        # lasso still produces exact zeros on the noise coefficients
        assert np.sum(np.abs(np.asarray(ooc.coefficients)) < 1e-6) >= 3

    def test_no_intercept(self, mesh8, rng):
        n, d = 2048, 4
        x = rng.normal(size=(n, d)).astype(np.float32)
        beta = rng.normal(size=d)
        y = (x @ beta).astype(np.float32)
        est = ht.LinearRegression(fit_intercept=False)
        resident = est.fit(device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x=x, y=y, max_device_rows=512), mesh=mesh8)
        np.testing.assert_allclose(
            np.asarray(resident.coefficients), np.asarray(ooc.coefficients),
            rtol=2e-4, atol=2e-4,
        )
        assert float(ooc.intercept) == 0.0

    def test_all_zero_weights_finite(self, mesh8, rng):
        """All sample weights zero: resident WLS returns finite zeros —
        the OOC path must match, not emit NaN from an empty-sample shift."""
        x = rng.normal(size=(128, 3)).astype(np.float32)
        y = rng.normal(size=128).astype(np.float32)
        w = np.zeros(128, np.float32)
        m = ht.LinearRegression().fit(
            HostDataset(x=x, y=y, w=w, max_device_rows=32), mesh=mesh8
        )
        assert np.all(np.isfinite(np.asarray(m.coefficients)))
        assert np.isfinite(float(m.intercept))

    def test_requires_labels(self, mesh8):
        with pytest.raises(ValueError, match="labels"):
            ht.LinearRegression().fit(
                HostDataset(x=np.ones((64, 2), np.float32)), mesh=mesh8
            )

    def test_summary_unavailable(self, mesh8, rng):
        x = rng.normal(size=(256, 3)).astype(np.float32)
        y = rng.normal(size=256).astype(np.float32)
        m = ht.LinearRegression().fit(
            HostDataset(x=x, y=y, max_device_rows=64), mesh=mesh8
        )
        assert not m.has_summary


class TestGMMOutOfCore:
    def test_matches_resident(self, mesh8, rng):
        # well-separated blobs: blockwise f32 accumulation order differences
        # must not change the converged parameters materially
        k, d, n = 3, 4, 3000
        centers = np.array(
            [[0, 0, 0, 0], [12, 12, 0, 0], [-12, 8, 6, 0]], dtype=np.float64
        )
        x = (
            centers[rng.integers(0, k, size=n)] + rng.normal(size=(n, d))
        ).astype(np.float32)
        est = ht.GaussianMixture(k=k, max_iter=15, seed=0)
        resident = est.fit(device_dataset(x, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x=x, max_device_rows=512), mesh=mesh8)
        order_r = np.argsort(resident.means[:, 0])
        order_o = np.argsort(ooc.means[:, 0])
        np.testing.assert_allclose(
            resident.means[order_r], ooc.means[order_o], rtol=1e-3, atol=1e-3
        )
        np.testing.assert_allclose(
            resident.weights[order_r], ooc.weights[order_o], rtol=1e-3, atol=1e-3
        )
        np.testing.assert_allclose(
            resident.log_likelihood, ooc.log_likelihood, rtol=1e-4
        )

    def test_single_block_nearly_identical(self, mesh8, rng):
        """max_device_rows ≥ n: one block — same pass structure as
        resident, so parameters agree tightly."""
        k, n, d = 2, 1024, 3
        x = np.concatenate(
            [
                rng.normal(0, 1, size=(n // 2, d)),
                rng.normal(8, 1, size=(n // 2, d)),
            ]
        ).astype(np.float32)
        est = ht.GaussianMixture(k=k, max_iter=10, seed=0)
        resident = est.fit(device_dataset(x, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x=x, max_device_rows=n), mesh=mesh8)
        o_r = np.argsort(resident.means[:, 0])
        o_o = np.argsort(ooc.means[:, 0])
        np.testing.assert_allclose(
            resident.means[o_r], ooc.means[o_o], rtol=1e-4, atol=1e-4
        )

    def test_empty_raises(self, mesh8):
        with pytest.raises(ValueError, match="empty"):
            ht.GaussianMixture(k=2).fit(
                HostDataset(x=np.empty((0, 3), np.float32)), mesh=mesh8
            )

    def test_checkpoint_preempt_resume(self, mesh8, rng, tmp_path):
        """GMM out-of-core + checkpoint_dir (VERDICT r3 #5): preempt
        between commits, resume from the last commit, converge to the
        uninterrupted parameters."""

        class Preempt(RuntimeError):
            pass

        k, d, n = 2, 3, 1024
        x = np.concatenate(
            [rng.normal(0, 1, size=(n // 2, d)), rng.normal(9, 1, size=(n // 2, d))]
        ).astype(np.float32)
        hd = HostDataset(x=x, max_device_rows=256)
        base = dict(k=k, seed=1, max_iter=10, tol=0.0)
        uninterrupted = ht.GaussianMixture(**base).fit(
            HostDataset(x=x, max_device_rows=256), mesh=mesh8
        )
        est = ht.GaussianMixture(
            checkpoint_dir=str(tmp_path / "gmm"), checkpoint_every=2, **base
        )

        def bomb(it, ll):
            if it == 4:
                raise Preempt()

        with pytest.raises(Preempt):
            est.fit(hd, mesh=mesh8, on_iteration=bomb)
        seen = []
        resumed = est.fit(hd, mesh=mesh8, on_iteration=lambda it, ll: seen.append(it))
        assert seen[0] == 5  # commit at it=4
        np.testing.assert_allclose(resumed.means, uninterrupted.means, atol=1e-4)
        np.testing.assert_allclose(
            resumed.weights, uninterrupted.weights, atol=1e-5
        )


class TestTreesOutOfCore:
    """grow_forest_outofcore: level-order growth as streamed sufficient-
    stat passes (VERDICT r3 next #4).  Integer labels make the histogram
    sums f32-exact, so splits are bit-identical to the resident engine."""

    def _int_reg(self, n=4096, d=6, seed=0):
        rng = np.random.default_rng(seed)
        x = rng.integers(0, 24, size=(n, d)).astype(np.float32)
        y = (x @ rng.integers(1, 4, size=d)).astype(np.float32) % 23
        return x, y

    def test_dt_regressor_identical_splits(self, mesh8):
        x, y = self._int_reg()
        est = ht.DecisionTreeRegressor(max_depth=4, seed=3)
        res = est.fit(device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x, y, max_device_rows=640), mesh=mesh8)
        np.testing.assert_array_equal(res.split_feat, ooc.split_feat)
        np.testing.assert_array_equal(res.threshold, ooc.threshold)
        np.testing.assert_allclose(res.value, ooc.value, rtol=1e-6)
        np.testing.assert_allclose(
            res.feature_importances, ooc.feature_importances, rtol=1e-6
        )

    def test_dt_classifier_identical_splits(self, mesh8):
        x, y = self._int_reg(seed=1)
        yb = (y > np.median(y)).astype(np.float32)
        est = ht.DecisionTreeClassifier(max_depth=4, seed=0)
        res = est.fit(device_dataset(x, yb, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x, yb, max_device_rows=512), mesh=mesh8)
        np.testing.assert_array_equal(res.split_feat, ooc.split_feat)
        np.testing.assert_array_equal(res.threshold, ooc.threshold)

    def test_rf_bootstrap_quality(self, mesh8):
        """Bootstrap draws differ per-block vs resident (documented), so
        the check is statistical: the out-of-core forest predicts the
        signal as well as the resident one."""
        rng = np.random.default_rng(0)
        n, d = 6000, 5
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (x @ np.array([3, -2, 1, 0, 0], np.float32)
             + 0.1 * rng.normal(size=n)).astype(np.float32)
        est = ht.RandomForestRegressor(num_trees=8, max_depth=5, seed=0,
                                       feature_subset_strategy="all")
        res = est.fit(device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x, y, max_device_rows=1024), mesh=mesh8)
        def r2(m):
            p = np.asarray(m.predict_numpy(x))
            return 1 - np.sum((y - p) ** 2) / np.sum((y - y.mean()) ** 2)
        assert r2(ooc) > 0.9
        assert abs(r2(ooc) - r2(res)) < 0.03

    def test_rf_no_bootstrap_identical(self, mesh8):
        """subsampling off ⇒ identical weights ⇒ identical forests."""
        x, y = self._int_reg(seed=2)
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.tree.engine import (
            grow_forest, grow_forest_outofcore,
        )
        kw = dict(task="regression", num_trees=4, max_depth=3,
                  bootstrap=False, seed=5, mesh=None)
        res = grow_forest(device_dataset(x, y, mesh=mesh8), mesh=mesh8,
                          task="regression", num_trees=4, max_depth=3,
                          bootstrap=False, seed=5)
        ooc = grow_forest_outofcore(HostDataset(x, y, max_device_rows=700),
                                    mesh=mesh8, task="regression",
                                    num_trees=4, max_depth=3,
                                    bootstrap=False, seed=5)
        np.testing.assert_array_equal(res.split_feat, ooc.split_feat)
        np.testing.assert_array_equal(res.split_bin, ooc.split_bin)

    def test_feature_subset_identical(self, mesh8):
        """The per-node feature-subset draw is keyed on (seed, depth) —
        identical across both drivers."""
        x, y = self._int_reg(seed=3)
        est = ht.RandomForestRegressor(
            num_trees=3, max_depth=3, seed=7,
            feature_subset_strategy="sqrt", subsampling_rate=1.0,
        )
        # bootstrap streams differ; compare via engine with bootstrap off
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.tree.engine import (
            grow_forest, grow_forest_outofcore,
        )
        res = grow_forest(device_dataset(x, y, mesh=mesh8), mesh=mesh8,
                          task="regression", num_trees=3, max_depth=3,
                          feature_subset_size=2, bootstrap=False, seed=7)
        ooc = grow_forest_outofcore(HostDataset(x, y, max_device_rows=512),
                                    mesh=mesh8, task="regression",
                                    num_trees=3, max_depth=3,
                                    feature_subset_size=2, bootstrap=False,
                                    seed=7)
        np.testing.assert_array_equal(res.split_feat, ooc.split_feat)

    def test_categorical_splits(self, mesh8):
        """Unordered-set categorical splits survive the streamed path."""
        rng = np.random.default_rng(4)
        n = 3000
        cat = rng.integers(0, 6, size=n).astype(np.float32)
        x2 = rng.integers(0, 10, size=n).astype(np.float32)
        x = np.stack([cat, x2], axis=1)
        y = np.where(np.isin(cat, [1.0, 4.0]), 10.0, 0.0).astype(np.float32)
        est = ht.DecisionTreeRegressor(
            max_depth=2, seed=0, categorical_features={0: 6}
        )
        res = est.fit(device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x, y, max_device_rows=512), mesh=mesh8)
        np.testing.assert_array_equal(res.split_feat, ooc.split_feat)
        np.testing.assert_array_equal(res.split_catmask, ooc.split_catmask)
        # the categorical root split isolates {1, 4} exactly
        p = np.asarray(ooc.predict_numpy(x))
        np.testing.assert_allclose(p, y, atol=1e-5)

    def test_requires_labels(self, mesh8):
        with pytest.raises(ValueError, match="labels"):
            ht.DecisionTreeRegressor().fit(
                HostDataset(np.ones((64, 2), np.float32)), mesh=mesh8
            )

    def test_empty_raises(self, mesh8):
        with pytest.raises(ValueError, match="empty"):
            ht.DecisionTreeRegressor().fit(
                HostDataset(
                    np.ones((8, 2), np.float32),
                    np.ones(8, np.float32),
                    np.zeros(8, np.float32),
                ),
                mesh=mesh8,
            )


class TestLogisticOutOfCore:
    def test_binomial_matches_resident(self, mesh8, rng):
        n, d = 6000, 4
        x = rng.normal(size=(n, d)).astype(np.float32)
        p = 1 / (1 + np.exp(-(x @ [1.0, -2.0, 0.5, 0.3] + 0.2)))
        y = (rng.uniform(size=n) < p).astype(np.float32)
        est = ht.LogisticRegression(max_iter=50)
        res = est.fit(device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x, y, max_device_rows=1000), mesh=mesh8)
        np.testing.assert_allclose(
            np.asarray(ooc.coefficients), np.asarray(res.coefficients),
            rtol=1e-4, atol=1e-5,
        )
        np.testing.assert_allclose(
            float(ooc.intercept), float(res.intercept), rtol=1e-4, atol=1e-5
        )
        assert res.n_iter == ooc.n_iter
        assert not ooc.has_summary   # OOC fits don't pin the dataset

    def test_binomial_regularized_standardized(self, mesh8, rng):
        """reg_param > 0 exercises the streamed moments → standardized-L2
        ridge path (Spark's standardization semantics)."""
        n, d = 4000, 3
        x = (rng.normal(size=(n, d)) * [1.0, 10.0, 0.1]).astype(np.float32)
        p = 1 / (1 + np.exp(-(x @ [1.0, -0.1, 5.0])))
        y = (rng.uniform(size=n) < p).astype(np.float32)
        est = ht.LogisticRegression(max_iter=50, reg_param=0.05)
        res = est.fit(device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x, y, max_device_rows=640), mesh=mesh8)
        np.testing.assert_allclose(
            np.asarray(ooc.coefficients), np.asarray(res.coefficients),
            rtol=1e-3, atol=1e-4,
        )

    def test_multinomial_matches_resident(self, mesh8, rng):
        n, d, k = 6000, 4, 3
        x = rng.normal(size=(n, d)).astype(np.float32)
        beta = rng.normal(size=(k, d))
        y = np.argmax(x @ beta.T + rng.gumbel(size=(n, k)), axis=1).astype(
            np.float32
        )
        est = ht.LogisticRegression(max_iter=50, reg_param=0.01)
        res = est.fit(device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x, y, max_device_rows=1000), mesh=mesh8)
        np.testing.assert_allclose(
            np.asarray(ooc.coefficient_matrix),
            np.asarray(res.coefficient_matrix),
            rtol=1e-3, atol=1e-4,
        )

    def test_binomial_on_multiclass_raises(self, mesh8, rng):
        x = rng.normal(size=(256, 2)).astype(np.float32)
        y = rng.integers(0, 3, size=256).astype(np.float32)
        with pytest.raises(ValueError, match="binomial"):
            ht.LogisticRegression(family="binomial").fit(
                HostDataset(x, y, max_device_rows=64), mesh=mesh8
            )

    def test_requires_labels(self, mesh8):
        with pytest.raises(ValueError, match="labels"):
            ht.LogisticRegression().fit(
                HostDataset(np.ones((64, 2), np.float32)), mesh=mesh8
            )


class TestGBTOutOfCore:
    def test_regressor_identical_splits(self, mesh8):
        rng = np.random.default_rng(0)
        n, d = 4000, 5
        x = rng.integers(0, 30, size=(n, d)).astype(np.float32)
        y = (x @ np.array([2, 1, 3, 1, 2], np.float32)).astype(np.float32)
        est = ht.GBTRegressor(max_iter=5, max_depth=3, seed=0)
        res = est.fit(device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x, y, max_device_rows=1024), mesh=mesh8)
        np.testing.assert_array_equal(res.split_feat, ooc.split_feat)
        np.testing.assert_allclose(
            np.asarray(res.predict_numpy(x[:256])),
            np.asarray(ooc.predict_numpy(x[:256])),
            rtol=1e-5,
        )

    def test_classifier_agreement(self, mesh8):
        rng = np.random.default_rng(1)
        n, d = 4000, 4
        x = rng.integers(0, 30, size=(n, d)).astype(np.float32)
        y = ((x @ np.ones(d, np.float32)) > 58).astype(np.float32)
        est = ht.GBTClassifier(max_iter=4, max_depth=3, seed=0)
        res = est.fit(device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        ooc = est.fit(HostDataset(x, y, max_device_rows=1024), mesh=mesh8)
        a = np.asarray(res.predict_numpy(x))
        b = np.asarray(ooc.predict_numpy(x))
        assert np.mean(a == b) > 0.999

    def test_validation_col_rejected(self, mesh8):
        with pytest.raises(ValueError, match="validation_indicator_col"):
            ht.GBTRegressor(validation_indicator_col="v").fit(
                HostDataset(
                    np.ones((64, 2), np.float32), np.ones(64, np.float32)
                ),
                mesh=mesh8,
            )

    def test_classifier_label_validation(self, mesh8):
        x = np.ones((64, 2), np.float32)
        y = np.full(64, 3.0, np.float32)
        with pytest.raises(ValueError, match="binary"):
            ht.GBTClassifier().fit(HostDataset(x, y), mesh=mesh8)


class TestNaiveBayesOutOfCore:
    """Round-5 (VERDICT r4 #5): one psum'd stats pass over blocks — the
    easiest possible out-of-core case, and exactly equal to resident."""

    def test_discrete_types_match_resident(self, mesh8, rng):
        n, d, k = 3000, 6, 3
        x = rng.poisson(3.0, size=(n, d)).astype(np.float32)
        y = rng.integers(0, k, size=n).astype(np.float32)
        for mt in ("multinomial", "complement"):
            res = ht.NaiveBayes(model_type=mt).fit((x, y), mesh=mesh8)
            ooc = ht.NaiveBayes(model_type=mt).fit(
                HostDataset(x=x, y=y, max_device_rows=256), mesh=mesh8
            )
            np.testing.assert_allclose(ooc.pi, res.pi, rtol=1e-6)
            np.testing.assert_allclose(ooc.theta, res.theta, rtol=1e-5)

    def test_bernoulli_matches_and_validates(self, mesh8, rng):
        n, d = 2000, 5
        x = (rng.uniform(size=(n, d)) < 0.4).astype(np.float32)
        y = rng.integers(0, 2, size=n).astype(np.float32)
        res = ht.NaiveBayes(model_type="bernoulli").fit((x, y), mesh=mesh8)
        ooc = ht.NaiveBayes(model_type="bernoulli").fit(
            HostDataset(x=x, y=y, max_device_rows=300), mesh=mesh8
        )
        np.testing.assert_allclose(ooc.theta, res.theta, rtol=1e-5)
        with pytest.raises(ValueError, match="0/1"):
            ht.NaiveBayes(model_type="bernoulli").fit(
                HostDataset(x=x + 0.5, y=y, max_device_rows=300), mesh=mesh8
            )

    def test_gaussian_centered_two_pass(self, mesh8, rng):
        """The out-of-core gaussian path centers at a first-pass global
        mean; a huge common offset must not cost variance accuracy."""
        n, d, k = 2500, 4, 2
        x = (rng.normal(size=(n, d)) + 1.0e6).astype(np.float32)
        y = rng.integers(0, k, size=n).astype(np.float32)
        res = ht.NaiveBayes(model_type="gaussian").fit((x, y), mesh=mesh8)
        ooc = ht.NaiveBayes(model_type="gaussian").fit(
            HostDataset(x=x, y=y, max_device_rows=256), mesh=mesh8
        )
        np.testing.assert_allclose(ooc.theta, res.theta, rtol=1e-4)
        np.testing.assert_allclose(ooc.sigma, res.sigma, rtol=1e-3)

    def test_requires_labels(self, mesh8):
        with pytest.raises(ValueError, match="labels"):
            ht.NaiveBayes().fit(
                HostDataset(np.ones((8, 2), np.float32)), mesh=mesh8
            )


class TestGLMOutOfCore:
    """Round-5 (VERDICT r4 #5): streaming IRLS — per-pass (X'OX, X'Oz)
    statistics over blocks, identical damped solve."""

    def _xy(self, rng, fam, n=4000, d=4):
        x = rng.normal(size=(n, d)).astype(np.float32)
        eta = 0.4 * x[:, 0] - 0.3 * x[:, 1] + 0.5
        if fam == "gaussian":
            return x, (eta + 0.1 * rng.normal(size=n)).astype(np.float32)
        if fam == "poisson":
            return x, rng.poisson(np.exp(eta)).astype(np.float32)
        if fam == "binomial":
            return x, (rng.uniform(size=n) < 1 / (1 + np.exp(-eta))).astype(
                np.float32
            )
        return x, rng.gamma(2.0, np.exp(eta) / 2.0).astype(np.float32)

    @pytest.mark.parametrize("fam", ["gaussian", "poisson", "binomial", "gamma"])
    def test_matches_resident(self, mesh8, rng, fam):
        x, y = self._xy(rng, fam)
        kw = dict(family=fam, max_iter=30)
        if fam == "gamma":
            kw["link"] = "log"
        res = ht.GeneralizedLinearRegression(**kw).fit((x, y), mesh=mesh8)
        ooc = ht.GeneralizedLinearRegression(**kw).fit(
            HostDataset(x=x, y=y, max_device_rows=512), mesh=mesh8
        )
        np.testing.assert_allclose(
            np.asarray(ooc.coefficients), np.asarray(res.coefficients),
            rtol=2e-3, atol=2e-4,
        )
        np.testing.assert_allclose(ooc.intercept, res.intercept, rtol=2e-3, atol=2e-4)
        np.testing.assert_allclose(ooc.deviance, res.deviance, rtol=2e-3)
        assert ooc.n_iter >= 1

    def test_tweedie_and_regularized(self, mesh8, rng):
        x, y = self._xy(rng, "gamma")
        res = ht.GeneralizedLinearRegression(
            family="tweedie", variance_power=1.5, link_power=0.0,
            reg_param=0.1, max_iter=30,
        ).fit((x, y), mesh=mesh8)
        ooc = ht.GeneralizedLinearRegression(
            family="tweedie", variance_power=1.5, link_power=0.0,
            reg_param=0.1, max_iter=30,
        ).fit(HostDataset(x=x, y=y, max_device_rows=512), mesh=mesh8)
        np.testing.assert_allclose(
            np.asarray(ooc.coefficients), np.asarray(res.coefficients),
            rtol=2e-3, atol=2e-4,
        )

    def test_offset_col_rejected_and_label_validation(self, mesh8, rng):
        x, y = self._xy(rng, "poisson")
        with pytest.raises(ValueError, match="offset_col"):
            ht.GeneralizedLinearRegression(
                family="poisson", offset_col="exposure"
            ).fit(HostDataset(x=x, y=y), mesh=mesh8)
        with pytest.raises(ValueError, match="non-negative"):
            ht.GeneralizedLinearRegression(family="poisson").fit(
                HostDataset(x=x, y=y - 10.0), mesh=mesh8
            )
        # summary unavailable on the streaming path
        m = ht.GeneralizedLinearRegression(family="poisson", max_iter=10).fit(
            HostDataset(x=x, y=y, max_device_rows=512), mesh=mesh8
        )
        with pytest.raises(RuntimeError):
            _ = m.summary


class TestMLPFMOutOfCore:
    """Round-5 (VERDICT r4 #5): streaming minibatch Adam — converges to
    the resident optimizer's quality (documented: not step-for-step)."""

    def test_fm_regressor_converges(self, mesh8, rng):
        n, d = 3000, 5
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (
            x @ np.array([1.0, -0.5, 0.3, 0.2, 0.1])
            + 0.5 * x[:, 0] * x[:, 1]
            + 0.05 * rng.normal(size=n)
        ).astype(np.float32)
        m = ht.FMRegressor(factor_size=3, max_iter=40, step_size=0.05, seed=0).fit(
            HostDataset(x=x, y=y, max_device_rows=512), mesh=mesh8
        )
        pred = np.asarray(m.predict_numpy(x))
        assert 1 - np.mean((pred - y) ** 2) / np.var(y) > 0.9

    def test_fm_classifier_and_validation(self, mesh8, rng):
        n, d = 2000, 4
        x = rng.normal(size=(n, d)).astype(np.float32)
        yb = (x @ np.array([1.0, -1.0, 0.5, 0.2]) > 0).astype(np.float32)
        m = ht.FMClassifier(factor_size=2, max_iter=30, seed=0).fit(
            HostDataset(x=x, y=yb, max_device_rows=512), mesh=mesh8
        )
        assert np.mean(np.asarray(m.predict_numpy(x)) == yb) > 0.9
        with pytest.raises(ValueError, match="binary"):
            ht.FMClassifier().fit(
                HostDataset(x=x, y=yb + 2.0, max_device_rows=512), mesh=mesh8
            )

    def test_mlp_converges_and_validates(self, mesh8, rng):
        n, d = 2500, 5
        x = rng.normal(size=(n, d)).astype(np.float32)
        yb = (x @ np.array([1.0, -1.0, 0.5, 0.2, 0.1]) > 0).astype(np.float32)
        m = ht.MultilayerPerceptronClassifier(
            layers=(d, 8, 2), max_iter=40, seed=0
        ).fit(HostDataset(x=x, y=yb, max_device_rows=512), mesh=mesh8)
        assert np.mean(np.asarray(m.predict_numpy(x)) == yb) > 0.93
        with pytest.raises(ValueError, match="integers"):
            ht.MultilayerPerceptronClassifier(layers=(d, 4, 2)).fit(
                HostDataset(x=x, y=yb + 5.0), mesh=mesh8
            )
        with pytest.raises(ValueError, match="labels"):
            ht.MultilayerPerceptronClassifier(layers=(d, 4, 2)).fit(
                HostDataset(x=x), mesh=mesh8
            )


def test_fm_mlp_empty_dataset_raises(mesh8):
    """Review regression: empty out-of-core inputs must fail loudly, not
    return a random-init model."""
    ex = np.empty((0, 5), np.float32)
    ey = np.empty((0,), np.float32)
    with pytest.raises(ValueError, match="empty"):
        ht.FMRegressor().fit(HostDataset(x=ex, y=ey), mesh=mesh8)
    with pytest.raises(ValueError, match="empty"):
        ht.MultilayerPerceptronClassifier(layers=(5, 4, 2)).fit(
            HostDataset(x=ex, y=ey), mesh=mesh8
        )


def test_minibatch_paths_shuffle_blocks(mesh8, rng):
    """Review regression: label-SORTED host data (every epoch would end
    on the same class without shuffling) must still converge."""
    n, d = 2000, 4
    x = rng.normal(size=(n, d)).astype(np.float32)
    yb = (x @ np.array([1.0, -1.0, 0.5, 0.2]) > 0).astype(np.float32)
    order = np.argsort(yb, kind="stable")
    xs, ys = x[order], yb[order]      # all class-0 rows first
    m = ht.MultilayerPerceptronClassifier(layers=(d, 8, 2), max_iter=40, seed=0).fit(
        HostDataset(x=xs, y=ys, max_device_rows=256), mesh=mesh8
    )
    assert np.mean(np.asarray(m.predict_numpy(xs)) == ys) > 0.9


class TestSVCAFTOutOfCore:
    """Round-5 completion of the out-of-core family sweep (VERDICT r4
    weak #4): SVC streams exact Newton statistics; AFT streams minibatch
    Adam on the censored Weibull likelihood."""

    def test_svc_matches_resident(self, mesh8, rng):
        n, d = 3000, 4
        x = rng.normal(size=(n, d)).astype(np.float32)
        yb = (x @ np.array([1.0, -1.0, 0.5, 0.2]) + 0.3 * rng.normal(size=n) > 0
              ).astype(np.float32)
        res = ht.LinearSVC(reg_param=0.01, max_iter=40).fit((x, yb), mesh=mesh8)
        ooc = ht.LinearSVC(reg_param=0.01, max_iter=40).fit(
            HostDataset(x=x, y=yb, max_device_rows=512), mesh=mesh8
        )
        np.testing.assert_allclose(
            np.asarray(ooc.coefficients), np.asarray(res.coefficients),
            rtol=5e-3, atol=5e-4,
        )
        np.testing.assert_allclose(ooc.intercept, res.intercept, rtol=5e-3,
                                   atol=5e-4)

    def test_svc_validation(self, mesh8, rng):
        x = np.ones((32, 2), np.float32)
        with pytest.raises(ValueError, match="labels"):
            ht.LinearSVC().fit(HostDataset(x=x), mesh=mesh8)
        with pytest.raises(ValueError, match="binary"):
            ht.LinearSVC().fit(
                HostDataset(x=x, y=np.full(32, 3.0, np.float32)), mesh=mesh8
            )

    def test_aft_converges_to_resident(self, mesh8, rng):
        n, d = 3000, 3
        x = rng.normal(size=(n, d)).astype(np.float32)
        eta = x @ np.array([0.5, -0.3, 0.2]) + 1.0
        sigma = 0.4
        t = np.exp(eta + sigma * np.log(-np.log(rng.uniform(size=n))))
        cen = (rng.uniform(size=n) < 0.8).astype(np.float32)  # 80% observed
        y = np.maximum(t, 1e-3).astype(np.float32)
        res = ht.AFTSurvivalRegression(max_iter=100).fit(
            (x, y), mesh=mesh8, censor=cen
        )
        ooc = ht.AFTSurvivalRegression(max_iter=60).fit(
            HostDataset(x=x, y=y, max_device_rows=512), mesh=mesh8, censor=cen
        )
        np.testing.assert_allclose(
            np.asarray(ooc.coefficients), np.asarray(res.coefficients),
            atol=0.05,
        )
        np.testing.assert_allclose(ooc.scale, res.scale, rtol=0.1)

    def test_aft_validation(self, mesh8, rng):
        x = np.ones((32, 2), np.float32)
        y = np.ones((32,), np.float32)
        with pytest.raises(ValueError, match="censor="):
            ht.AFTSurvivalRegression().fit(HostDataset(x=x, y=y), mesh=mesh8)
        with pytest.raises(ValueError, match="entries"):
            ht.AFTSurvivalRegression().fit(
                HostDataset(x=x, y=y), mesh=mesh8, censor=np.ones(8, np.float32)
            )
        with pytest.raises(ValueError, match="0.0"):
            ht.AFTSurvivalRegression().fit(
                HostDataset(x=x, y=y), mesh=mesh8,
                censor=np.full(32, 0.5, np.float32),
            )


def test_one_vs_rest_streams_through_inner_estimator(mesh8, rng):
    """OneVsRest composes with out-of-core: each one-vs-all fit streams
    blocks through the inner estimator's own HostDataset path."""
    n, d = 2400, 4
    x = rng.normal(size=(n, d)).astype(np.float32)
    z = x @ np.array([1.0, -1.0, 0.5, 0.2])
    y3 = np.digitize(z, np.quantile(z, [0.33, 0.66])).astype(np.float32)
    res = ht.OneVsRest(classifier=ht.LinearSVC(max_iter=30)).fit(
        (x, y3), mesh=mesh8
    )
    ooc = ht.OneVsRest(classifier=ht.LinearSVC(max_iter=30)).fit(
        HostDataset(x=x, y=y3, max_device_rows=512), mesh=mesh8
    )
    pr = np.asarray(res.predict_numpy(x))
    po = np.asarray(ooc.predict_numpy(x))
    assert np.mean(pr == po) > 0.99
    assert np.mean(po == y3) > 0.8


def test_constant_feature_ridge_matches_resident(mesh8, rng):
    """Review regression: the shared streamed standardization must apply
    weighted_moments' constant-feature rule (std 1.0) so a constant
    column is penalized at full strength, exactly like the resident
    fit."""
    n = 2000
    x = np.column_stack([
        rng.normal(size=n), np.full(n, 7.0), rng.normal(size=n)
    ]).astype(np.float32)
    yb = (x[:, 0] - x[:, 2] > 0).astype(np.float32)
    for est in (ht.LinearSVC(reg_param=0.5, max_iter=30),
                ht.LogisticRegression(reg_param=0.5, max_iter=30)):
        res = est.fit((x, yb), mesh=mesh8)
        ooc = est.fit(HostDataset(x=x, y=yb, max_device_rows=512), mesh=mesh8)
        np.testing.assert_allclose(
            np.asarray(ooc.coefficients), np.asarray(res.coefficients),
            rtol=5e-3, atol=5e-4,
        )


class TestBisectingOutOfCore:
    """Round-5: the last family without a streaming path.  Host-carried
    leaf assignments + streamed Lloyd/stats sweeps walk the same split
    tree as the resident shard_map loop."""

    def _blobs(self, rng, n_per=400, k=6, d=4):
        cs = rng.normal(0, 8, size=(k, d))
        return np.concatenate(
            [rng.normal(c, 0.5, size=(n_per, d)) for c in cs]
        ).astype(np.float32)

    @pytest.mark.parametrize("strategy", ["level", "sequential"])
    @pytest.mark.parametrize("dm", ["euclidean", "cosine"])
    def test_matches_resident(self, mesh8, rng, strategy, dm):
        x = self._blobs(rng)
        res = ht.BisectingKMeans(
            k=6, seed=0, strategy=strategy, distance_measure=dm
        ).fit(x, mesh=mesh8)
        ooc = ht.BisectingKMeans(
            k=6, seed=0, strategy=strategy, distance_measure=dm
        ).fit(HostDataset(x=x, max_device_rows=300), mesh=mesh8)
        a = np.asarray(sorted(res.cluster_centers.tolist()))
        b = np.asarray(sorted(ooc.cluster_centers.tolist()))
        assert a.shape == b.shape
        np.testing.assert_allclose(a, b, atol=2e-2)
        np.testing.assert_allclose(
            res.training_cost, ooc.training_cost, rtol=1e-3
        )

    def test_weights_and_min_divisible(self, mesh8, rng):
        x = self._blobs(rng, n_per=200, k=4)
        w = rng.uniform(0.5, 2.0, size=len(x)).astype(np.float32)
        res = ht.BisectingKMeans(
            k=4, seed=1, min_divisible_cluster_size=50.0
        ).fit((x, None, w), mesh=mesh8)       # resident WEIGHTED baseline
        ooc = ht.BisectingKMeans(
            k=4, seed=1, min_divisible_cluster_size=50.0
        ).fit(HostDataset(x=x, w=w, max_device_rows=256), mesh=mesh8)
        a = np.asarray(sorted(res.cluster_centers.tolist()))
        b = np.asarray(sorted(ooc.cluster_centers.tolist()))
        assert a.shape == b.shape
        np.testing.assert_allclose(a, b, atol=5e-2)
        np.testing.assert_allclose(ooc.cluster_sizes.sum(), w.sum(), rtol=1e-4)

    def test_zero_row_dataset_raises(self, mesh8):
        with pytest.raises(ValueError, match="empty"):
            ht.BisectingKMeans(k=2).fit(
                HostDataset(x=np.zeros((0, 3), np.float32)), mesh=mesh8
            )

    def test_empty_raises_bkm(self, mesh8):
        with pytest.raises(ValueError, match="empty"):
            ht.BisectingKMeans(k=2).fit(
                HostDataset(
                    x=np.ones((4, 2), np.float32),
                    w=np.zeros((4,), np.float32),
                ),
                mesh=mesh8,
            )


def test_isotonic_hostdataset_identical(mesh8, rng):
    """Isotonic consumes one 1-D column; the HostDataset path slices it
    host-side with zero device staging and must match exactly."""
    n = 3000
    x = rng.normal(size=(n, 3)).astype(np.float32)
    y = (np.sort(rng.normal(size=n)) + 0.1 * rng.normal(size=n)).astype(np.float32)
    x[:, 1] = np.sort(x[:, 1])     # monotone-ish feature 1
    res = ht.IsotonicRegression(feature_index=1).fit((x, y), mesh=mesh8)
    ooc = ht.IsotonicRegression(feature_index=1).fit(
        HostDataset(x=x, y=y, max_device_rows=256), mesh=mesh8
    )
    np.testing.assert_array_equal(res.boundaries, ooc.boundaries)
    np.testing.assert_array_equal(res.predictions, ooc.predictions)
    with pytest.raises(ValueError, match="labels"):
        ht.IsotonicRegression().fit(HostDataset(x=x), mesh=mesh8)


def test_hostdataset_negative_weights_rejected():
    """Review regression: the device staging path rejects negative
    weights; HostDataset must enforce the same contract at construction
    for every estimator's streaming path at once."""
    with pytest.raises(ValueError, match="non-negative"):
        HostDataset(
            x=np.ones((4, 2), np.float32),
            w=np.array([1.0, -1.0, 1.0, 1.0], np.float32),
        )


def test_outofcore_kmeans_fused_stats(rng, mesh8):
    """fused_stats must actually reach the out-of-core block kernel (it
    was silently dropped there once): streamed fused fit matches the
    resident fused fit."""
    import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht

    k, d, n = 4, 5, 2000
    centers = rng.normal(scale=5.0, size=(k, d))
    x = (centers[rng.integers(0, k, n)] + rng.normal(scale=0.3, size=(n, d))).astype(
        np.float32
    )
    est = ht.KMeans(k=k, seed=0, matmul_precision="bf16", fused_stats=True)
    resident = est.fit(x, mesh=mesh8)
    streamed = est.fit(ht.HostDataset(x=x, max_device_rows=256), mesh=mesh8)
    dist = np.linalg.norm(
        resident.cluster_centers[:, None] - streamed.cluster_centers[None],
        axis=2,
    )
    assert dist.min(axis=1).max() < 0.05
