"""BinaryClassificationEvaluator: ROC/PR AUC vs sklearn, tie exactness,
weights, and the LogisticRegression score path."""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.evaluation import (
    BinaryClassificationEvaluator,
)


pytestmark = pytest.mark.fast


def test_roc_auc_matches_sklearn(rng):
    from sklearn.metrics import roc_auc_score

    y = rng.integers(0, 2, 500).astype(float)
    s = y * 0.5 + rng.normal(0, 0.7, 500)  # informative, continuous scores
    ours = BinaryClassificationEvaluator("areaUnderROC").evaluate(s, y)
    assert ours == pytest.approx(roc_auc_score(y, s), abs=1e-6)


def test_roc_auc_weighted_matches_sklearn(rng):
    from sklearn.metrics import roc_auc_score

    y = rng.integers(0, 2, 400).astype(float)
    s = y * 0.8 + rng.normal(0, 1.0, 400)
    w = rng.uniform(0.1, 3.0, 400)
    ours = BinaryClassificationEvaluator("areaUnderROC").evaluate(s, y, w)
    assert ours == pytest.approx(roc_auc_score(y, s, sample_weight=w), abs=1e-5)


def test_roc_auc_tie_exactness():
    # hand-computable: scores {1: pos, 1: neg, 0: neg}
    # pairs: (pos,neg@1) tie → 0.5 ; (pos,neg@0) win → 1.0 ; AUC = 1.5/2
    s = np.array([1.0, 1.0, 0.0])
    y = np.array([1.0, 0.0, 0.0])
    ours = BinaryClassificationEvaluator("areaUnderROC").evaluate(s, y)
    assert ours == pytest.approx(0.75, abs=1e-6)


def test_pr_auc_matches_sklearn_trapezoid(rng):
    from sklearn.metrics import auc, precision_recall_curve

    y = rng.integers(0, 2, 500).astype(float)
    s = y * 0.9 + rng.normal(0, 0.8, 500)
    ours = BinaryClassificationEvaluator("areaUnderPR").evaluate(s, y)
    prec, rec, _ = precision_recall_curve(y, s)
    # sklearn's curve is threshold-descending with an extra (0, 1) anchor;
    # trapezoid over it differs from ours only in that anchor's treatment
    assert ours == pytest.approx(auc(rec, prec), abs=0.02)


def test_auc_on_logistic_scores(rng, mesh8):
    x = rng.normal(size=(1500, 4))
    logits = x @ np.array([2.0, -1.0, 0.5, 0.0])
    y = (rng.random(1500) < 1 / (1 + np.exp(-logits))).astype(float)
    model = ht.LogisticRegression().fit((x, y), mesh=mesh8)
    import jax.numpy as jnp

    scores = np.asarray(model.predict_proba(jnp.asarray(x)))
    auc_ = BinaryClassificationEvaluator().evaluate(scores, y)
    assert auc_ > 0.8
    # AUC is rank-invariant: margins give the same value as probabilities
    margins = np.asarray(model.predict_raw(jnp.asarray(x)))
    auc_m = BinaryClassificationEvaluator().evaluate(margins, y)
    assert auc_ == pytest.approx(auc_m, abs=1e-6)


def test_transform_proba_prediction_result_path(rng, mesh8):
    """The PredictionResult route must carry scores (transform_proba), and
    give the same AUC as the explicit-arrays route."""
    x = rng.normal(size=(800, 4))
    logits = x @ np.array([2.0, -1.0, 0.5, 0.0])
    y = (rng.random(800) < 1 / (1 + np.exp(-logits))).astype(float)
    model = ht.LogisticRegression().fit((x, y), mesh=mesh8)
    pred = model.transform_proba((x, y), mesh=mesh8)
    auc_pr_result = BinaryClassificationEvaluator().evaluate(pred)
    import jax.numpy as jnp

    scores = np.asarray(model.predict_proba(jnp.asarray(x)))
    auc_arrays = BinaryClassificationEvaluator().evaluate(scores, y)
    assert auc_pr_result == pytest.approx(auc_arrays, abs=1e-6)
    assert auc_pr_result > 0.8


def test_unknown_metric_raises():
    with pytest.raises(ValueError, match="unknown metric"):
        BinaryClassificationEvaluator("f1").evaluate(np.ones(3), np.ones(3))
