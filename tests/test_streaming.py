"""Streaming subsystem: file source, watermark, unbounded table, exactly-once
micro-batch loop, crash/resume (SURVEY.md §4 integration tier)."""

import json
import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming import (
    FileStreamSource,
    StreamCheckpoint,
    StreamExecution,
    UnboundedTable,
    WatermarkTracker,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import write_csv


def _event_csv(path, start_minute, n, hospital="H01"):
    base = np.datetime64("2025-03-31T22:00:00") + np.timedelta64(start_minute, "m")
    t = ht.Table.from_dict(
        {
            "hospital_id": np.array([hospital] * n, dtype=object),
            "event_time": base + np.arange(n).astype("timedelta64[s]"),
            "admission_count": np.arange(n),
            "current_occupancy": np.full(n, 100),
            "emergency_visits": np.full(n, 5),
            "seasonality_index": np.full(n, 1.0),
            "length_of_stay": np.full(n, 4.0),
        },
        ht.hospital_event_schema(),
    )
    write_csv(t, path)
    return t


def _stream(tmp_path, foreach=None, watermark_minutes=10.0):
    incoming = tmp_path / "incoming"
    incoming.mkdir(exist_ok=True)
    src = FileStreamSource(str(incoming), ht.hospital_event_schema())
    sink = UnboundedTable(str(tmp_path / "table"), ht.hospital_event_schema())
    ckpt = StreamCheckpoint(str(tmp_path / "ckpt"))
    wm = WatermarkTracker("event_time", watermark_minutes)
    return incoming, StreamExecution(
        source=src, sink=sink, checkpoint=ckpt, watermark=wm, foreach_batch=foreach
    )


def test_stream_basic_ingest(tmp_path):
    incoming, exec_ = _stream(tmp_path)
    _event_csv(str(incoming / "a.csv"), 0, 50)
    info = exec_.run_once()
    assert info.num_input_rows == 50 and info.num_appended_rows == 50
    assert exec_.run_once() is None  # no new files
    _event_csv(str(incoming / "b.csv"), 1, 30)
    info2 = exec_.run_once()
    assert info2.batch_id == 1 and info2.num_appended_rows == 30
    snap = exec_.sink.read()
    assert snap.num_rows == 80
    assert "ingest_time" in snap.schema  # :82 parity


def test_stream_watermark_drops_late(tmp_path):
    incoming, exec_ = _stream(tmp_path, watermark_minutes=10.0)
    _event_csv(str(incoming / "a.csv"), 60, 10)     # advances watermark to 60m-10m
    exec_.run_once()
    _event_csv(str(incoming / "late.csv"), 0, 5)    # 50 min before watermark
    info = exec_.run_once()
    assert info.num_late_rows == 5 and info.num_appended_rows == 0
    _event_csv(str(incoming / "ok.csv"), 55, 5)     # within the 10-minute slack
    info2 = exec_.run_once()
    assert info2.num_late_rows == 0 and info2.num_appended_rows == 5


@pytest.mark.fast
def test_stream_exactly_once_resume(tmp_path):
    """Crash between offsets and commit → replay same batch, no duplicates."""
    incoming, exec_ = _stream(tmp_path)
    _event_csv(str(incoming / "a.csv"), 0, 20)
    exec_.run_once()

    # simulate crash mid-batch: write offsets for batch 1 but no commit
    _event_csv(str(incoming / "b.csv"), 1, 30)
    files = exec_.source.poll()
    exec_.checkpoint.write_offsets(1, files, exec_.watermark.state())

    # "restart": brand-new execution over the same dirs
    src = FileStreamSource(str(incoming), ht.hospital_event_schema())
    sink = UnboundedTable(str(tmp_path / "table"), ht.hospital_event_schema())
    ckpt = StreamCheckpoint(str(tmp_path / "ckpt"))
    exec2 = StreamExecution(
        source=src,
        sink=sink,
        checkpoint=ckpt,
        watermark=WatermarkTracker("event_time", 10.0),
    )
    info = exec2.run_once()
    assert info.batch_id == 1 and info.num_appended_rows == 30
    assert exec2.sink.read().num_rows == 50
    # replaying again changes nothing
    assert exec2.run_once() is None
    assert exec2.sink.read().num_rows == 50


def test_stream_commit_replay_idempotent(tmp_path):
    """A batch committed twice (double replay) must not duplicate rows."""
    incoming, exec_ = _stream(tmp_path)
    t = _event_csv(str(incoming / "a.csv"), 0, 25)
    exec_.run_once()
    # forcibly re-append the same batch id (as a replay would)
    exec_.sink.append_batch(exec_.sink.read(), 0)
    assert exec_.sink.read().num_rows == 25


def test_stream_foreach_batch_hook(tmp_path):
    """The working version of the reference's dead ML() hook (C6/D2):
    per-batch incremental training."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models import (
        StreamingKMeans,
    )

    skm = StreamingKMeans(k=2, seed=0)
    calls = []

    def hook(batch, batch_id):
        calls.append((batch_id, batch.num_rows))
        if batch.num_rows:
            skm.update(
                batch.numeric_matrix(list(ht.FEATURE_COLS)), mesh=None
            )

    incoming, exec_ = _stream(tmp_path, foreach=hook)
    _event_csv(str(incoming / "a.csv"), 0, 40)
    _event_csv(str(incoming / "b.csv"), 1, 40)
    exec_.run_once()
    # second file may land in batch 0 or 1 depending on poll timing
    exec_.run(max_batches=1, timeout_s=1.0)
    assert sum(n for _, n in calls) == 80
    assert skm.latest_model.cluster_centers.shape[0] == 2


def test_stream_window_extraction_parity(tmp_path):
    """End-to-end: ingest → unbounded table → BETWEEN window query (:123-128)."""
    incoming, exec_ = _stream(tmp_path)
    _event_csv(str(incoming / "a.csv"), 0, 60)     # 22:00:00..22:00:59
    _event_csv(str(incoming / "b.csv"), 90, 60)    # 23:30:00..
    exec_.run(max_batches=2, timeout_s=2.0)
    snap = exec_.sink.read()
    window = snap.between(
        "event_time", "2025-03-31 22:00:00", "2025-03-31 23:00:00"
    ).na_drop()
    assert window.num_rows == 60


class TestWalTornTail:
    """Crash mid-append must never corrupt earlier entries or merge lines."""

    def test_append_repairs_torn_tail(self, tmp_path):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming.wal import (
            append_line,
            read_lines,
        )

        log = str(tmp_path / "w.log")
        append_line(log, {"batch_id": 0})
        # simulate a crash mid-write: partial JSON, no trailing newline
        with open(log, "a") as f:
            f.write('{"batch_id": 1, "fi')
        # the torn tail is skipped, not fatal, and doesn't stop the read
        assert read_lines(log) == [{"batch_id": 0}]
        # the next append must start on a fresh line, not merge into the tear
        append_line(log, {"batch_id": 1})
        assert read_lines(log) == [{"batch_id": 0}, {"batch_id": 1}]

    def test_commit_log_tolerates_torn_tail(self, tmp_path):
        import numpy as np

        import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming.unbounded_table import (
            COMMIT_LOG,
            UnboundedTable,
        )

        schema = ht.Schema([ht.Field("a", "float")])
        t = ht.Table.from_dict({"a": np.arange(4.0)}, schema)
        ut = UnboundedTable(str(tmp_path / "ut"), schema)
        ut.append_batch(t, 0)
        with open(str(tmp_path / "ut" / COMMIT_LOG), "a") as f:
            f.write('{"batch_id": 1, "file": "par')  # torn commit
        assert ut.num_rows() == 4  # readable despite the tear
        ut.append_batch(t, 1)  # replay of the torn batch
        assert ut.num_rows() == 8
