"""serve/fleet — placement, routing, admission, atomic promotion, chaos.

The subsystem's contracts (ISSUE 12):

1. placement is explicit and real — each replica's executables are
   committed to its assigned device slice, not wherever jax defaults;
2. router invariants — consistent-hash reshuffle stays ≤ 1/N on a
   single add/remove, a breaker-OPEN replica is never picked, tenant
   stickiness survives a fleet-wide hot swap;
3. admission — per-tenant quotas shed the noisy hospital only, and the
   SLO ladder sheds best_effort before batch before interactive;
4. promotion is atomic fleet-wide — a failure while ANY replica
   prepares leaves EVERY replica on the old model;
5. a replica killed mid-load answers or cleanly sheds every in-flight
   request (zero unhandled) and the router reroutes around it;
6. fleet health() merges replica snapshots through the obs registry
   pull-collector path with a PINNED key set.
"""

import threading

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve import (
    STATUS_INVALID_INPUT,
    STATUS_REJECTED,
    NotRoutableError,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve import (
    fleet as F,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils import (
    faults,
)

pytestmark = [pytest.mark.fast, pytest.mark.fleet]

D = 4
BUCKETS = (1, 8)


@pytest.fixture
def xy(rng):
    n = 128
    x = rng.normal(size=(n, D)).astype(np.float32)
    y = (x @ np.array([1.0, -2.0, 0.5, 0.25], np.float32) + 0.3).astype(
        np.float32
    )
    return x, y


@pytest.fixture
def model(xy):
    return ht.LinearRegression().fit(xy)


def make_fleet(model, n=3, **kw):
    kw.setdefault("max_queue_rows", 256)
    fs = F.ReplicaSet(n_replicas=n, **kw)
    fs.add_model("los", model, buckets=BUCKETS)
    return fs


# =========================================================================
# placement
# =========================================================================


class _Dev:
    def __init__(self, i):
        self.id = i

    def __repr__(self):
        return f"dev{self.id}"


def test_even_placement_splits_contiguously():
    devs = [_Dev(i) for i in range(8)]
    slices = F.EvenPlacement().assign(4, devs)
    assert [s.replica_id for s in slices] == [0, 1, 2, 3]
    assert [len(s.devices) for s in slices] == [2, 2, 2, 2]
    flat = [d for s in slices for d in s.devices]
    assert flat == devs  # full coverage, no overlap, order preserved
    assert slices[0].primary is devs[0]
    # remainder spreads over the first replicas
    slices = F.EvenPlacement().assign(3, devs)
    assert [len(s.devices) for s in slices] == [3, 3, 2]


def test_even_placement_oversubscribes_round_robin():
    devs = [_Dev(i) for i in range(2)]
    slices = F.EvenPlacement().assign(5, devs)
    assert [s.primary.id for s in slices] == [0, 1, 0, 1, 0]


def test_pinned_placement_validates():
    devs = [_Dev(i) for i in range(4)]
    slices = F.PinnedPlacement({0: (3, 2), 1: (0,), 2: (1,)}).assign(3, devs)
    assert slices[0].primary.id == 3 and len(slices[0].devices) == 2
    with pytest.raises(ValueError, match="missing replicas"):
        F.PinnedPlacement({0: (0,)}).assign(2, devs)
    with pytest.raises(ValueError, match="pinned to both"):
        F.PinnedPlacement({0: (0, 1), 1: (1,)}).assign(2, devs)
    with pytest.raises(ValueError, match="outside"):
        F.PinnedPlacement({0: (9,)}).assign(1, devs)


def test_replicas_pinned_to_distinct_devices(model):
    """Placement is real: each replica's ServingModel is committed to its
    slice's primary device, and its executable output lands THERE."""
    import jax

    fs = make_fleet(model, n=4)
    primaries = [r.slice.primary for r in fs.replicas]
    assert len(set(primaries)) == 4  # distinct devices on the 8-dev mesh
    with fs:
        for r in fs.replicas:
            sm = r.server.registry.get("los")
            assert sm.device is r.slice.primary
            out = sm._jitted(sm._put(np.zeros((1, D), np.float32)))
            assert next(iter(out.devices())) is r.slice.primary
            jax.block_until_ready(out)


# =========================================================================
# router invariants
# =========================================================================


def _owners(ring, keys):
    return {k: ring.owner(k) for k in keys}


def test_consistent_hash_reshuffle_bounded():
    """Single replica add/remove moves ≤ 1/N of the tenant space, and
    ONLY keys owned by the changed replica move (everyone else's warm
    slice is untouched).  Deterministic — the ring has no RNG."""
    keys = [f"hospital-{i}" for i in range(2000)]
    ring = F.ConsistentHashRing(vnodes=160)
    for rid in range(4):
        ring.add(rid)
    before = _owners(ring, keys)
    ring.add(4)  # 4 -> 5
    after = _owners(ring, keys)
    moved = [k for k in keys if before[k] != after[k]]
    assert len(moved) / len(keys) <= 1 / 4
    assert all(after[k] == 4 for k in moved)  # moves only ONTO the new one
    ring.remove(4)  # 5 -> 4: exactly the new replica's keys move back
    restored = _owners(ring, keys)
    assert restored == before
    moved_back = [k for k in keys if after[k] != restored[k]]
    assert len(moved_back) / len(keys) <= 1 / 4
    assert all(after[k] == 4 for k in moved_back)


class _StubReplica:
    def __init__(self, index, load=0, healthy=True, open_models=()):
        self.index = index
        self._load = load
        self._healthy = healthy
        self._open = set(open_models)

    def healthy(self):
        return self._healthy

    def load_rows(self):
        return self._load

    def breaker_open(self, model):
        return model in self._open


def test_least_loaded_picks_min_and_skips_open_breaker():
    reps = [
        _StubReplica(0, load=50),
        _StubReplica(1, load=5),
        _StubReplica(2, load=20),
    ]
    router = F.Router(reps, policy=F.POLICY_LEAST_LOADED)
    assert router.route(model="m").index == 1
    reps[1]._open.add("m")
    for _ in range(50):
        assert router.route(model="m").index != 1
    # a different model's breaker state is independent
    assert router.route(model="other").index == 1


def test_router_skips_unhealthy_and_raises_when_none_left():
    reps = [_StubReplica(0), _StubReplica(1)]
    router = F.Router(reps, policy=F.POLICY_CONSISTENT_HASH)
    reps[0]._healthy = False
    for t in ("a", "b", "c", "d"):
        assert router.route(tenant_id=t).index == 1
    reps[1]._healthy = False
    with pytest.raises(F.NoReplicaAvailable):
        router.route(tenant_id="a")


def test_sticky_failover_returns_home():
    """A dead replica's tenants land on their ring successor (the SAME
    one every time), and return to the home replica when it revives."""
    reps = [_StubReplica(i) for i in range(4)]
    router = F.Router(reps, policy=F.POLICY_CONSISTENT_HASH)
    tenants = [f"t{i}" for i in range(200)]
    home = {t: router.route(tenant_id=t).index for t in tenants}
    victims = [t for t in tenants if home[t] == 2]
    assert victims  # hash spreads over 4 replicas
    reps[2]._healthy = False
    over = {t: router.route(tenant_id=t).index for t in tenants}
    for t in tenants:
        if home[t] != 2:
            assert over[t] == home[t]  # unaffected tenants do not move
    assert all(over[t] != 2 for t in victims)
    # failover is deterministic: same successor on a second ask
    again = {t: router.route(tenant_id=t).index for t in victims}
    assert again == {t: over[t] for t in victims}
    reps[2]._healthy = True
    assert {t: router.route(tenant_id=t).index for t in tenants} == home


def test_sticky_affinity_survives_fleet_swap(model, xy):
    x, y = xy
    fs = make_fleet(model, n=3)
    with fs:
        tenants = [f"H{i:03d}" for i in range(40)]
        before = {
            t: fs.router.route(tenant_id=t, model="los").index
            for t in tenants
        }
        successor = ht.LinearRegression(reg_param=0.7).fit((x, y))
        fs.swap_model("los", successor)
        after = {
            t: fs.router.route(tenant_id=t, model="los").index
            for t in tenants
        }
        assert after == before
        # and the swap really changed the served model everywhere
        for r in fs.replicas:
            assert r.server.registry.get("los").model is successor


# =========================================================================
# admission: quotas + SLO ladder
# =========================================================================


def test_token_bucket_refills_on_injected_clock():
    now = [0.0]
    b = F.TokenBucket(rate=100.0, burst=50.0, clock=lambda: now[0])
    assert b.take(50)
    assert not b.take(1)
    now[0] += 0.25  # refill 25 rows
    assert b.take(25)
    assert not b.take(1)


def test_admission_ladder_orders_sheds_by_class():
    ctl = F.AdmissionController()
    for load, expect in (
        (0.10, {"best_effort": True, "batch": True, "interactive": True}),
        (0.30, {"best_effort": False, "batch": True, "interactive": True}),
        (0.60, {"best_effort": False, "batch": False, "interactive": True}),
        (1.00, {"best_effort": False, "batch": False, "interactive": False}),
    ):
        for slo, admitted in expect.items():
            d = ctl.admit("t", slo, 8, load)
            assert d.admitted == admitted, (load, slo)
            if not d.admitted:
                assert d.reason == f"slo_load:{slo}"
    with pytest.raises(ValueError, match="unknown SLO class"):
        ctl.admit("t", "platinum", 1, 0.0)


def test_quota_sheds_only_the_noisy_tenant(model):
    now = [0.0]
    ctl = F.AdmissionController(
        tenant_quotas={"noisy": (100.0, 16.0)}, clock=lambda: now[0]
    )
    fs = make_fleet(model, n=2, admission=ctl)
    with fs:
        ok_noisy = shed_noisy = 0
        for _ in range(8):
            r = fs.predict("los", np.zeros((8, D), np.float32),
                           tenant_id="noisy")
            if r.status == STATUS_REJECTED:
                shed_noisy += 1
                assert "quota:noisy" in r.detail
            else:
                ok_noisy += 1
        # burst 16 rows admits the first two 8-row requests, sheds the rest
        assert ok_noisy == 2 and shed_noisy == 6
        # the quiet hospital is untouched by its neighbor's flood
        for _ in range(8):
            assert fs.predict(
                "los", np.zeros((8, D), np.float32), tenant_id="quiet"
            ).ok
        h = fs.health()
        assert h["shed_quota"] == 6
        assert h["shed"]["interactive"] == 6


def test_unknown_slo_rejected_before_counting(model):
    """Caller-supplied SLO strings are metric labels AND intern keys:
    garbage is refused up front, with no counter minted for it — in
    both admission modes."""
    for admission in (F.DEFAULT_ADMISSION, None):
        fs = make_fleet(model, n=1, admission=admission)
        with fs:
            with pytest.raises(ValueError, match="unknown SLO class"):
                fs.predict("los", np.zeros((1, D), np.float32),
                           slo="platinum")
        assert "platinum" not in str(fs.metrics.counters)
        assert fs.metrics.counters.get("fleet.requests", 0) == 0


def test_latency_histogram_excludes_shed_answers(model):
    """Sheds answer in ~0 s; folding them into the per-class latency
    histogram would make p99 read healthiest during an outage — only
    OK answers are observed."""
    ctl = F.AdmissionController(tenant_quotas={"t": (1.0, 8.0)})
    fs = make_fleet(model, n=1, admission=ctl)
    with fs:
        assert fs.predict("los", np.zeros((8, D), np.float32),
                          tenant_id="t").ok
        for _ in range(3):  # bucket drained: these shed at the door
            assert not fs.predict("los", np.zeros((8, D), np.float32),
                                  tenant_id="t").ok
        h = fs.metrics.histograms['fleet.latency_seconds{slo="interactive"}']
        assert h.count == 1  # the one OK answer; zero shed samples


# =========================================================================
# atomic fleet-wide promotion
# =========================================================================


def test_swap_flips_every_replica_or_none(model, xy):
    x, y = xy
    probe = x[:4]
    old_pred = np.asarray(model.predict(probe))
    successor = ht.LinearRegression(reg_param=2.0).fit((x, y))
    new_pred = np.asarray(successor.predict(probe))
    assert not np.allclose(old_pred, new_pred)

    fs = make_fleet(model, n=3)
    with fs:
        # phase-1 failure on the LAST replica's prepare: replicas 0 and 1
        # already prepared successfully — none may flip
        plan = faults.FaultPlan().fail(
            "fleet.swap.prepare", after=2,
            error=lambda: RuntimeError("injected prepare failure"),
        )
        faults.install(plan)
        try:
            with pytest.raises(RuntimeError, match="injected"):
                fs.swap_model("los", successor)
        finally:
            faults.clear()
        for r in fs.replicas:  # all-or-none: everyone still on the old model
            assert r.server.registry.get("los").model is model
            np.testing.assert_allclose(
                r.server.predict("los", probe).value, old_pred, rtol=1e-5
            )
        # clean swap: every replica flips
        fs.swap_model("los", successor)
        for r in fs.replicas:
            np.testing.assert_allclose(
                r.server.predict("los", probe).value, new_pred, rtol=1e-5
            )
        assert fs.health()["promotions"] == 1


def test_swap_resets_breakers_fleet_wide(model, xy):
    """The promotion contract a lifecycle PROMOTED transition relies on:
    commit resets every replica's breaker (opens accumulated against the
    predecessor say nothing about the successor)."""
    fs = make_fleet(model, n=2)
    with fs:
        for r in fs.replicas:
            r.server._breaker_for("los").trip("test drift")
            assert r.breaker_open("los")
        fs.swap_model("los", model)
        for r in fs.replicas:
            assert not r.breaker_open("los")


def test_fleet_exposes_the_lifecycle_controller_surface(model):
    """lifecycle/controller.py drives promotion through server.swap_model
    / add_model / registry.names() / attach_lifecycle — the fleet serves
    the same surface, so a controller promotes all replicas atomically
    without knowing it holds a fleet."""
    fs = make_fleet(model, n=2)
    assert fs.registry.names() == ["los"]
    for attr in ("add_model", "swap_model", "attach_lifecycle"):
        assert callable(getattr(fs, attr))
    sentinel = object()
    fs.attach_lifecycle(sentinel)
    for r in fs.replicas:
        assert r.server._lifecycle is sentinel


# =========================================================================
# fleet health through the collector path
# =========================================================================

#: the pinned fleet-health schema (PR 8 discipline): a key added or
#: renamed without updating this pin is a deliberate decision, not drift
HEALTH_KEYS = {
    "status", "started", "replicas", "models_serving", "requests",
    "served_requests", "shed", "shed_quota", "shed_load", "no_replica",
    "rerouted", "promotions", "replicas_killed", "replicas_revived",
    "fallback_answers", "drift_trips", "queue_rows_total", "load_factor",
}

REPLICA_KEYS = {"state", "queue_rows", "breakers"}


def test_health_key_set_pinned_and_merged_via_collectors(model):
    fs = make_fleet(model, n=2)
    with fs:
        for _ in range(3):
            assert fs.predict("los", np.zeros((4, D), np.float32)).ok
        fs.replicas[1].server._breaker_for("los").trip("drifted")
        h = fs.health()
    assert set(h) == HEALTH_KEYS
    assert set(h["replicas"]) == {"r00", "r01"}
    for rep in h["replicas"].values():
        assert set(rep) == REPLICA_KEYS
    # merged THROUGH the registry collectors: per-replica serve counters
    # summed into the fleet total, breaker state decoded from the gauge
    assert h["served_requests"] >= 3
    assert h["replicas"]["r01"]["breakers"]["los"] == "open"
    assert h["status"] == "degraded"
    assert h["requests"] == 3
    # the raw collect() carries the per-replica labeled series themselves
    snap = fs.stats()
    assert 'fleet.replica_state{replica="r00"}' in snap["gauges"]
    assert (
        'fleet.breaker_state{model="los",replica="r01"}' in snap["gauges"]
    )


def test_replica_label_is_bounded():
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.obs.registry import (
        replica_label,
    )

    assert replica_label(0) == "r00"
    assert replica_label(31) == "r31"
    with pytest.raises(ValueError):
        replica_label(-1)
    with pytest.raises(ValueError):
        replica_label(100000)


# =========================================================================
# load generator
# =========================================================================


def _profile(**kw):
    kw.setdefault("base_rate_rps", 200.0)
    kw.setdefault("tenants", (
        F.TenantMix("A", 2.0, "interactive", 2),
        F.TenantMix("B", 1.0, "batch", 4),
        F.TenantMix("C", 1.0, "best_effort", 8),
    ))
    return F.LoadProfile(**kw)


def test_schedule_is_replayable_bit_for_bit():
    p = _profile(seed=7, diurnal_amplitude=0.4, diurnal_period_s=2.0,
                 burst_start_s=0.5, burst_dur_s=0.25, burst_mult=2.0)
    s1 = F.build_schedule(p, 2.0)
    s2 = F.build_schedule(p, 2.0)
    assert s1 == s2
    assert s1 != F.build_schedule(_profile(seed=8), 2.0)
    assert all(0.0 <= a.t < 2.0 for a in s1)
    assert {a.tenant_id for a in s1} == {"A", "B", "C"}


def test_burst_and_diurnal_shape_the_rate():
    p = _profile(seed=3, base_rate_rps=400.0,
                 burst_start_s=1.0, burst_dur_s=0.5, burst_mult=3.0)
    s = F.build_schedule(p, 2.0)
    in_burst = sum(1 for a in s if 1.0 <= a.t < 1.5)
    before = sum(1 for a in s if 0.5 <= a.t < 1.0)
    assert in_burst > 2.0 * before  # 3x nominal, noisy Poisson slack
    assert p.rate_at(1.2) == pytest.approx(1200.0)
    assert p.rate_at(0.2) == pytest.approx(400.0)


def test_replay_answers_everything_and_tallies_by_class(model):
    fs = make_fleet(model, n=2)
    sched = F.build_schedule(_profile(seed=1, base_rate_rps=300.0), 1.0)
    with fs:
        rep = F.replay(
            lambda a: fs.submit("los", np.zeros((a.rows, D), np.float32),
                                tenant_id=a.tenant_id, slo=a.slo),
            sched, speed=2.0,
        )
    assert rep["unanswered"] == 0
    assert rep["offered_requests"] == len(sched)
    total = sum(
        c["ok_rows"] + c["shed_rows"] + c["deadline_rows"] + c["other_rows"]
        for c in rep["per_class"].values()
    )
    assert total == rep["offered_rows"]  # every row accounted for
    assert set(rep["per_class"]) <= set(F.SLO_SHED_ORDER)
    r = rep["reports"]["interactive"]
    assert r.in_slo(10.0)["rows"] <= r.ok_rows


# =========================================================================
# chaos: replica death mid-load
# =========================================================================


@pytest.mark.chaos
def test_replica_kill_mid_load_zero_unhandled(model):
    """Kill a replica while the fleet is under open-loop load: every
    in-flight request is answered or cleanly shed (zero unhandled, zero
    stranded waits), the router reroutes around the corpse, and traffic
    AFTER the kill is served by the survivors."""
    fs = make_fleet(model, n=3, max_queue_rows=512)
    sched = F.build_schedule(_profile(seed=5, base_rate_rps=400.0), 1.5)
    victim = 1
    killed = threading.Event()

    def kill():
        fs.kill_replica(victim)
        killed.set()

    with fs:
        rep = F.replay(
            lambda a: fs.submit("los", np.zeros((a.rows, D), np.float32),
                                tenant_id=a.tenant_id, slo=a.slo),
            sched, speed=1.5, mid_hook=kill,
        )
        assert killed.is_set()
        # post-kill, the fleet still answers (survivors took the tenants)
        for t in ("A", "B", "C", "D", "E"):
            res = fs.predict("los", np.zeros((2, D), np.float32), tenant_id=t)
            assert res.ok, res.status
        h = fs.health()
    assert rep["unanswered"] == 0  # nobody stranded: answered or shed
    assert h["replicas"]["r01"]["state"] == "dead"
    assert h["replicas_killed"] == 1
    assert h["status"] == "degraded"
    # the schedule kept being served: ok rows on both sides of the kill
    assert rep["ok_rows"] > 0


@pytest.mark.chaos
def test_drain_replica_answers_everything_then_stops(model):
    fs = make_fleet(model, n=2)
    with fs:
        reqs = [
            fs.submit("los", np.zeros((2, D), np.float32), tenant_id=f"t{i}")
            for i in range(20)
        ]
        assert fs.drain_replica(0, timeout_s=5.0)
        for req in reqs:
            res = req.wait(5.0)
            assert res.status in ("ok", "shutdown", "rejected")
        assert fs.replicas[0].state == "dead"
        # survivors keep serving
        assert fs.predict("los", np.zeros((2, D), np.float32)).ok


@pytest.mark.chaos
def test_revive_replica_serves_current_model_and_tenants_come_home(model, xy):
    """ISSUE 17: the recovery half of the kill chaos surface.  Kill a
    replica, hot-swap the fleet WHILE it is dead, then revive it: the
    revived replica rebuilds from the fleet's model specs (it serves the
    post-kill swap, not the model it died with), rejoins the hash ring
    so failed-over tenants come home, and health counts the revival."""
    x, y = xy
    fs = make_fleet(model, n=3)
    with fs:
        tenants = [f"H{i:03d}" for i in range(60)]
        home = {
            t: fs.router.route(tenant_id=t, model="los").index
            for t in tenants
        }
        victims = [t for t in tenants if home[t] == 1]
        assert victims  # hash spreads over 3 replicas
        fs.kill_replica(1)
        over = {
            t: fs.router.route(tenant_id=t, model="los").index
            for t in tenants
        }
        assert all(over[t] != 1 for t in victims)
        successor = ht.LinearRegression(reg_param=0.7).fit((x, y))
        fs.swap_model("los", successor)  # promotes around the corpse
        fs.revive_replica(1)
        assert fs.replicas[1].state == "live"
        assert fs.replicas[1].server.registry.get("los").model is successor
        back = {
            t: fs.router.route(tenant_id=t, model="los").index
            for t in tenants
        }
        assert back == home  # every failed-over tenant came home
        res = fs.predict(
            "los", np.zeros((2, D), np.float32), tenant_id=victims[0]
        )
        assert res.ok, res.status
        h = fs.health()
        assert h["replicas"]["r01"]["state"] == "live"
        assert h["replicas_killed"] == 1
        assert h["replicas_revived"] == 1
        assert h["status"] == "ok"
        # revive is only defined for dead replicas — a live one refuses
        with pytest.raises(ValueError, match="not dead"):
            fs.revive_replica(1)


def test_replay_events_fire_once_in_schedule_order(model):
    """The seeded-chaos lever ISSUE 17 adds to the load generator:
    ``events`` are (t, fn) in schedule time, fired exactly once each,
    deterministically interleaved with arrivals — and events past the
    last arrival still fire before harvest."""
    fs = make_fleet(model, n=2)
    sched = F.build_schedule(_profile(seed=2, base_rate_rps=200.0), 1.0)
    fired: list = []
    events = [
        (0.25, lambda: fired.append(0.25)),
        (0.5, lambda: fired.append(0.5)),
        (0.0, lambda: fired.append(0.0)),
        (99.0, lambda: fired.append(99.0)),  # after the last arrival
    ]
    with fs:
        rep = F.replay(
            lambda a: fs.submit("los", np.zeros((a.rows, D), np.float32),
                                tenant_id=a.tenant_id, slo=a.slo),
            sched, speed=4.0, events=events,
        )
    assert fired == [0.0, 0.25, 0.5, 99.0]  # sorted, each exactly once
    assert rep["unanswered"] == 0


# =========================================================================
# predict_tenant / NotRoutableError (ISSUE 12 satellite)
# =========================================================================


def test_not_routable_is_typed_and_answers_invalid_input(model):
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve import (
        InferenceServer,
    )

    with InferenceServer() as srv:
        srv.add_model("plain", model, buckets=BUCKETS)
        # path 1: the typed error from the routing primitive
        with pytest.raises(NotRoutableError) as ei:
            srv.route_tenant("plain", "H001", np.zeros((2, D), np.float32))
        assert ei.value.model_name == "plain"
        assert isinstance(ei.value, TypeError)  # legacy catch keeps working
        # path 2: the serving surface answers a 400, never a 500
        res = srv.predict_tenant("plain", "H001", np.zeros((2, D), np.float32))
        assert res.status == STATUS_INVALID_INPUT
        assert not res.ok and not res.degraded
        assert "plain" in res.detail
        c = srv.metrics.registry.counters
        assert c.get("serve.not_routable", 0) == 1
        assert c.get("serve.status.invalid_input", 0) == 1
        # the breaker never saw it: a client error is not a model failure
        assert c.get("serve.primary_failures", 0) == 0


def test_fleet_predict_tenant_not_routable(model):
    fs = make_fleet(model, n=2)
    with fs:
        res = fs.predict_tenant("los", "H001", np.zeros((2, D), np.float32))
        assert res.status == STATUS_INVALID_INPUT


def test_fleet_predict_tenant_routes_farm_sticky(rng):
    """Farm + fleet: the SAME tenant key drives the consistent-hash
    replica choice and the in-band slice gather — int and str forms of a
    tenant id land identically (farm.affinity_key normalization)."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.farm import (
        FarmLinearRegression,
    )

    data = {
        str(t): (
            rng.normal(size=(12, D)).astype(np.float32),
            rng.normal(size=(12,)).astype(np.float32),
        )
        for t in range(6)
    }
    farm = FarmLinearRegression().fit(data)
    fs = F.ReplicaSet(n_replicas=2, max_queue_rows=256)
    fs.add_model("farm", farm, buckets=BUCKETS)
    with fs:
        x = data["3"][0][:2]
        res = fs.predict_tenant("farm", 3, x)
        assert res.ok
        np.testing.assert_allclose(
            res.value, farm.predict_tenant("3", x), atol=1e-5
        )
        assert farm.affinity_key(3) == farm.affinity_key("3")


# =========================================================================
# SLO-ordered degradation under real saturation (small-scale)
# =========================================================================


def test_best_effort_sheds_before_interactive_under_load(model):
    """With the routed replica's queue half full, a best_effort (and
    batch) request sheds at the door while an interactive request is
    still admitted and answered — degradation ordered by class, not
    arrival.  The queue depth is pinned by overriding the replica's
    load accessor, so the ladder decision itself is what's under test."""
    fs = F.ReplicaSet(n_replicas=1, max_queue_rows=64)
    fs.add_model("los", model, buckets=BUCKETS)
    with fs:
        fs.replicas[0].load_rows = lambda: 32  # load factor 0.5, pinned
        be = fs.predict("los", np.zeros((1, D), np.float32),
                        tenant_id="t", slo="best_effort")
        assert be.status == STATUS_REJECTED
        assert "slo_load:best_effort" in be.detail
        # 0.5 ≥ the 0.45 batch threshold: batch sheds here too
        batch = fs.predict("los", np.zeros((1, D), np.float32),
                           tenant_id="t", slo="batch")
        assert batch.status == STATUS_REJECTED
        inter = fs.predict("los", np.zeros((1, D), np.float32),
                           tenant_id="t", slo="interactive")
        assert inter.ok  # admitted AND answered
        h = fs.health()
        assert h["shed"]["best_effort"] == 1
        assert h["shed"]["batch"] == 1
        assert h["shed"]["interactive"] == 0
