"""MultilayerPerceptronClassifier, FMRegressor/FMClassifier,
AFTSurvivalRegression — the round-4 pyspark.ml estimator-family
completions (classification.MLP/FM, regression.FM/AFT).

Oracles: problems with known structure a linear model provably cannot
fit (XOR for the MLP, a pure interaction term for FM) and a Weibull AFT
draw with known coefficients under ~40% right-censoring."""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht


class TestMLP:
    def test_xor_beats_linear(self, rng, mesh8):
        n = 2000
        x = rng.uniform(-1, 1, size=(n, 2)).astype(np.float32)
        y = ((x[:, 0] * x[:, 1]) > 0).astype(np.float32)
        m = ht.MultilayerPerceptronClassifier(
            layers=(2, 16, 2), max_iter=200, seed=0
        ).fit(ht.device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        acc = np.mean(np.asarray(m.predict_numpy(x)) == y)
        assert acc > 0.95
        lin = ht.LogisticRegression(max_iter=50).fit(
            ht.device_dataset(x, y, mesh=mesh8), mesh=mesh8
        )
        assert np.mean(np.asarray(lin.predict_numpy(x)) == y) < 0.7
        proba = np.asarray(m.predict_proba(x[:16]))
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)

    def test_multiclass(self, rng, mesh8):
        n = 3000
        x = rng.normal(size=(n, 2)).astype(np.float32)
        y = (np.arctan2(x[:, 1], x[:, 0]) // (2 * np.pi / 3) % 3 + 1) % 3
        y = y.astype(np.float32)
        m = ht.MultilayerPerceptronClassifier(
            layers=(2, 24, 3), max_iter=300, seed=1
        ).fit(ht.device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        assert m.num_classes == 3
        assert np.mean(np.asarray(m.predict_numpy(x)) == y) > 0.9

    def test_round_trip_and_validation(self, rng, mesh8, tmp_path):
        x = rng.normal(size=(256, 3)).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.float32)
        m = ht.MultilayerPerceptronClassifier(
            layers=(3, 8, 2), max_iter=50, seed=0
        ).fit(ht.device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        m.write().overwrite().save(str(tmp_path / "mlp"))
        back = ht.load_model(str(tmp_path / "mlp"))
        np.testing.assert_allclose(
            back.predict_numpy(x), m.predict_numpy(x)
        )
        with pytest.raises(ValueError, match="layers"):
            ht.MultilayerPerceptronClassifier(layers=(3,)).fit(
                ht.device_dataset(x, y, mesh=mesh8), mesh=mesh8
            )
        with pytest.raises(ValueError, match="features"):
            ht.MultilayerPerceptronClassifier(layers=(5, 4, 2)).fit(
                ht.device_dataset(x, y, mesh=mesh8), mesh=mesh8
            )
        with pytest.raises(ValueError, match="labels must be integers"):
            ht.MultilayerPerceptronClassifier(layers=(3, 4, 2)).fit(
                ht.device_dataset(x, y * 3, mesh=mesh8), mesh=mesh8
            )
        # negative and fractional labels raise too (they would silently
        # clamp/truncate under jit)
        with pytest.raises(ValueError, match="labels must be integers"):
            ht.MultilayerPerceptronClassifier(layers=(3, 4, 2)).fit(
                ht.device_dataset(x, y * 2 - 1, mesh=mesh8), mesh=mesh8
            )
        with pytest.raises(ValueError, match="labels must be integers"):
            ht.MultilayerPerceptronClassifier(layers=(3, 4, 2)).fit(
                ht.device_dataset(x, y + 0.5, mesh=mesh8), mesh=mesh8
            )
        with pytest.raises(ValueError, match="solver"):
            ht.MultilayerPerceptronClassifier(layers=(3, 2), solver="gd").fit(
                ht.device_dataset(x, y, mesh=mesh8), mesh=mesh8
            )


class TestFM:
    def test_interaction_signal_beats_linear(self, rng, mesh8):
        n, d = 4000, 4
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (
            2.0 * x[:, 0] * x[:, 1] + 0.5 * x[:, 2]
            + 0.05 * rng.normal(size=n)
        ).astype(np.float32)
        fm = ht.FMRegressor(factor_size=4, max_iter=800, step_size=0.1, seed=0).fit(
            ht.device_dataset(x, y, mesh=mesh8), mesh=mesh8
        )
        pred = np.asarray(fm.predict_numpy(x))
        r2 = 1 - np.sum((y - pred) ** 2) / np.sum((y - y.mean()) ** 2)
        assert r2 > 0.95
        lin = ht.LinearRegression().fit(ht.device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        lr2 = 1 - np.sum(
            (y - np.asarray(lin.predict_numpy(x))) ** 2
        ) / np.sum((y - y.mean()) ** 2)
        assert lr2 < 0.5    # the linear model structurally cannot fit x0*x1

    def test_classifier(self, rng, mesh8):
        n, d = 4000, 4
        x = rng.normal(size=(n, d)).astype(np.float32)
        yb = ((x[:, 0] * x[:, 1] + 0.3 * x[:, 2]) > 0).astype(np.float32)
        m = ht.FMClassifier(factor_size=4, max_iter=600, step_size=0.1, seed=0).fit(
            ht.device_dataset(x, yb, mesh=mesh8), mesh=mesh8
        )
        assert np.mean(np.asarray(m.predict_numpy(x)) == yb) > 0.9
        p = np.asarray(m.predict_proba(x[:32]))
        assert np.all((p >= 0) & (p <= 1))

    def test_round_trip_and_validation(self, rng, mesh8, tmp_path):
        x = rng.normal(size=(256, 3)).astype(np.float32)
        y = (x[:, 0] * x[:, 1]).astype(np.float32)
        m = ht.FMRegressor(factor_size=2, max_iter=50, seed=0).fit(
            ht.device_dataset(x, y, mesh=mesh8), mesh=mesh8
        )
        m.write().overwrite().save(str(tmp_path / "fm"))
        back = ht.load_model(str(tmp_path / "fm"))
        np.testing.assert_allclose(
            back.predict_numpy(x), m.predict_numpy(x), rtol=1e-6
        )
        assert back.factor_size == 2
        with pytest.raises(ValueError, match="binary"):
            ht.FMClassifier().fit(
                ht.device_dataset(x, y * 10, mesh=mesh8), mesh=mesh8
            )
        with pytest.raises(ValueError, match="factor_size"):
            ht.FMRegressor(factor_size=0).fit(
                ht.device_dataset(x, y, mesh=mesh8), mesh=mesh8
            )
        with pytest.raises(ValueError, match="classification-only"):
            m.predict_proba(x)


class TestAFT:
    def _survival_data(self, rng, n=6000):
        x = rng.normal(0, 0.5, size=(n, 2)).astype(np.float32)
        eta = x @ [0.8, -0.5] + 1.0
        sigma = 0.5
        eps = np.log(rng.exponential(size=n))    # Gumbel-min
        t = np.exp(eta + sigma * eps).astype(np.float32)
        c_time = rng.exponential(np.e ** 1.5, size=n).astype(np.float32)
        observed = (t <= c_time).astype(np.float32)
        return x, np.minimum(t, c_time), observed, sigma

    def test_recovers_weibull_parameters_under_censoring(self, rng, mesh8):
        x, y, observed, sigma = self._survival_data(rng)
        assert 0.3 < 1 - observed.mean() < 0.55   # real censoring happening
        m = ht.AFTSurvivalRegression(max_iter=100).fit(
            ht.device_dataset(x, y, mesh=mesh8), mesh=mesh8, censor=observed
        )
        np.testing.assert_allclose(m.coefficients, [0.8, -0.5], atol=0.07)
        np.testing.assert_allclose(m.intercept, 1.0, atol=0.07)
        np.testing.assert_allclose(m.scale, sigma, atol=0.06)
        # ignoring censoring (all observed) must bias the fit noticeably
        biased = ht.AFTSurvivalRegression(max_iter=100).fit(
            ht.device_dataset(x, y, mesh=mesh8), mesh=mesh8,
            censor=np.ones_like(observed),
        )
        assert abs(biased.intercept - 1.0) > abs(m.intercept - 1.0)

    def test_quantiles_and_prediction(self, rng, mesh8):
        x, y, observed, _ = self._survival_data(rng, n=2000)
        m = ht.AFTSurvivalRegression().fit(
            ht.device_dataset(x, y, mesh=mesh8), mesh=mesh8, censor=observed
        )
        q = np.asarray(m.predict_quantiles(x[:8]))
        assert q.shape == (8, 9)
        assert np.all(np.diff(q, axis=1) > 0)     # monotone in p
        # median quantile below mean for this sigma (right-skewed Weibull)
        pred = np.asarray(m.predict_numpy(x[:8]))
        assert np.all(q[:, 4] < pred)

    def test_table_censor_col_and_validation(self, rng, mesh8, tmp_path):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

        x, y, observed, _ = self._survival_data(rng, n=1024)
        tab = Table.from_dict(
            {
                "f0": x[:, 0], "f1": x[:, 1],
                "time": y.astype(np.float32), "censor": observed,
            }
        )
        asm = ht.VectorAssembler(["f0", "f1"]).transform(tab)
        m = ht.AFTSurvivalRegression(label_col="time").fit(asm, mesh=mesh8)
        assert np.isfinite(m.scale)
        m.write().overwrite().save(str(tmp_path / "aft"))
        back = ht.load_model(str(tmp_path / "aft"))
        np.testing.assert_allclose(
            back.predict_numpy(x[:16]), m.predict_numpy(x[:16]), rtol=1e-6
        )
        with pytest.raises(ValueError, match="censor"):
            ht.AFTSurvivalRegression().fit(
                ht.device_dataset(x, y, mesh=mesh8), mesh=mesh8,
                censor=observed * 3,
            )
        with pytest.raises(ValueError, match="positive"):
            ht.AFTSurvivalRegression().fit(
                ht.device_dataset(x, y - 100, mesh=mesh8), mesh=mesh8,
                censor=observed,
            )
        with pytest.raises(ValueError, match="table"):
            ht.AFTSurvivalRegression().fit(
                ht.device_dataset(x, y, mesh=mesh8), mesh=mesh8
            )


def test_new_families_compose_in_pipeline(rng, mesh8, tmp_path):
    """MLP and FM are full Pipeline citizens (chained stages +
    composite persistence), like every earlier estimator family."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

    n = 1500
    a = rng.normal(size=n).astype(np.float32)
    b = rng.normal(size=n).astype(np.float32)
    y = ((a * b) > 0).astype(np.float32)        # pure interaction rule
    t = Table.from_dict({"a": a, "b": b, "LOS_binary": y})

    pipe = ht.Pipeline(
        [
            ht.VectorAssembler(["a", "b"]),
            ht.StandardScaler(),
            ht.FMClassifier(factor_size=3, max_iter=500, step_size=0.1, seed=0),
        ]
    )
    pm = pipe.fit(t, label_col="LOS_binary", mesh=mesh8)
    pred, lab = pm.transform(t, label_col="LOS_binary", mesh=mesh8).to_numpy()
    assert np.mean(pred == lab) > 0.9           # linear stages can't do this
    pm.write().overwrite().save(str(tmp_path / "fm_pipe"))
    back = ht.load_model(str(tmp_path / "fm_pipe"))
    pred2, _ = back.transform(t, label_col="LOS_binary", mesh=mesh8).to_numpy()
    np.testing.assert_allclose(pred2, pred)

    mlp_pipe = ht.Pipeline(
        [
            ht.VectorAssembler(["a", "b"]),
            ht.MultilayerPerceptronClassifier(layers=(2, 12, 2), max_iter=150, seed=0),
        ]
    )
    mm = mlp_pipe.fit(t, label_col="LOS_binary", mesh=mesh8)
    mp, ml = mm.transform(t, label_col="LOS_binary", mesh=mesh8).to_numpy()
    assert np.mean(mp == ml) > 0.9
