"""RFormula + VectorSizeHint — the final pyspark.ml.feature stages.

Oracle: a known additive model over a categorical ward column; the
treatment-coded fit must recover the per-level effects exactly."""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

pytestmark = pytest.mark.fast


@pytest.fixture
def ward_table(rng):
    n = 600
    ward = rng.choice(["icu", "er", "gen"], size=n, p=[0.2, 0.3, 0.5])
    adm = rng.integers(0, 40, n).astype(np.float32)
    occ = rng.integers(20, 90, n).astype(np.float32)
    eff = {"icu": 3.0, "er": 1.0, "gen": 0.0}
    y = (
        0.1 * adm + np.vectorize(eff.get)(ward) + 2.0
        + 0.05 * rng.normal(size=n)
    ).astype(np.float32)
    return Table.from_dict(
        {"ward": ward.astype(object), "adm": adm, "occ": occ, "los": y}
    )


class TestRFormula:
    def test_treatment_coding_recovers_effects(self, ward_table, mesh8):
        m = ht.RFormula(formula="los ~ adm + ward").fit(ward_table)
        at = m.transform(ward_table)
        # Spark's composition drops the LAST level by descending
        # frequency: base = "icu" (rarest); dummies for gen and er
        assert at.feature_cols == ("adm", "ward_gen", "ward_er")
        lr = ht.LinearRegression(label_col="los").fit(at, mesh=mesh8)
        coef = np.asarray(lr.coefficients)
        np.testing.assert_allclose(coef[0], 0.1, atol=0.01)      # adm slope
        np.testing.assert_allclose(coef[1], -3.0, atol=0.05)     # gen vs icu
        np.testing.assert_allclose(coef[2], -2.0, atol=0.05)     # er vs icu
        np.testing.assert_allclose(float(lr.intercept), 5.0, atol=0.05)

    def test_dot_minus_and_interactions(self, ward_table):
        m = ht.RFormula(formula="los ~ . - occ").fit(ward_table)
        roots = {c.split("_")[0].split(":")[0] for c in m.transform(ward_table).feature_cols}
        assert "occ" not in roots and "adm" in roots and "ward" in roots
        m2 = ht.RFormula(formula="los ~ adm:occ").fit(ward_table)
        at = m2.transform(ward_table)
        assert at.feature_cols == ("adm:occ",)
        np.testing.assert_allclose(
            at.features[:, 0],
            np.asarray(ward_table.column("adm"))
            * np.asarray(ward_table.column("occ")),
            rtol=1e-6,
        )
        # categorical × numeric interaction expands per dummy
        m3 = ht.RFormula(formula="los ~ ward:adm").fit(ward_table)
        assert m3.transform(ward_table).feature_cols == (
            "ward_gen:adm", "ward_er:adm",
        )
        # '- a:b' removes exactly that interaction, keeping main effects
        m4 = ht.RFormula(formula="los ~ adm + occ + adm:occ - adm:occ").fit(
            ward_table
        )
        assert m4.transform(ward_table).feature_cols == ("adm", "occ")

    def test_categorical_label_and_unseen_levels(self, ward_table):
        y = np.asarray(ward_table.column("los"))
        t = ward_table.with_column(
            "risk", np.where(y > 4, "high", "low").astype(object)
        )
        m = ht.RFormula(formula="risk ~ adm + ward").fit(t)
        at = m.transform(t)
        assert set(np.unique(np.asarray(at.table.column("risk")))) <= {0.0, 1.0}
        # unseen category at transform time raises (like the binned trees)
        t_bad = t.with_column(
            "ward", np.array(["lunar"] * len(t), object)
        )
        with pytest.raises(ValueError, match="unseen level"):
            m.transform(t_bad)

    def test_round_trip_and_validation(self, ward_table, tmp_path):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import (
            load_model, save_model,
        )

        m = ht.RFormula(formula="los ~ adm + ward").fit(ward_table)
        save_model(str(tmp_path / "rf"), *m._artifacts())
        back = load_model(str(tmp_path / "rf"))
        np.testing.assert_allclose(
            back.transform(ward_table).features,
            m.transform(ward_table).features,
        )
        assert back.feature_names == m.transform(ward_table).feature_cols
        for bad, msg in [
            ("los adm", "~"),
            ("~ adm", "label"),
            ("los ~ ", "feature terms"),
            ("los ~ nope", "not in the table"),
        ]:
            with pytest.raises((ValueError, KeyError), match=msg):
                ht.RFormula(formula=bad).fit(ward_table)
        with pytest.raises(KeyError, match="label"):
            ht.RFormula(formula="nope ~ adm").fit(ward_table)
        with pytest.raises(TypeError, match="Table"):
            ht.RFormula(formula="y ~ x").fit(np.ones((3, 2)))


class TestVectorSizeHint:
    def test_pass_and_mismatch(self, ward_table):
        at = ht.RFormula(formula="los ~ adm + ward").fit_transform(ward_table)
        assert ht.VectorSizeHint(size=3).transform(at) is at
        with pytest.raises(ValueError, match="saw 3"):
            ht.VectorSizeHint(size=4).transform(at)
        with pytest.raises(ValueError, match="size"):
            ht.VectorSizeHint(size=0)
        with pytest.raises(ValueError, match="handle_invalid"):
            ht.VectorSizeHint(size=2, handle_invalid="skip")

    def test_in_pipeline(self, ward_table, mesh8):
        pipe = ht.Pipeline(
            [
                ht.VectorAssembler(["adm", "occ"]),
                ht.VectorSizeHint(size=2),
                ht.LinearRegression(label_col="los"),
            ]
        )
        pm = pipe.fit(ward_table, mesh=mesh8)
        assert np.isfinite(
            np.asarray(pm.transform(ward_table, mesh=mesh8).prediction)
        ).all()
