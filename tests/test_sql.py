"""Session.sql — the core/sql.py subset (SURVEY.md E1's Spark SQL row).

The reference runs one windowed SELECT (``mllearnforhospitalnetwork.py:
123-128``); Spark SQL makes projections and per-hospital GROUP BYs the
same one-liner, so the engine must not fall off a cliff beyond that shape.
"""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.sql import execute


pytestmark = pytest.mark.fast


@pytest.fixture
def session(hospital_table):
    s = ht.Session.builder.app_name("sql-test").get_or_create()
    s.register_table("events", hospital_table)
    yield s
    s.stop()


def test_reference_windowed_select(session, hospital_table):
    """The exact reference query shape — byte-for-byte parity target."""
    out = session.sql(
        "SELECT * FROM events WHERE event_time BETWEEN "
        "'2025-03-31 22:00:00' AND '2025-03-31 22:03:00'"
    )
    ref = hospital_table.between(
        "event_time", "2025-03-31 22:00:00", "2025-03-31 22:03:00"
    )
    assert len(out) == len(ref) > 0
    np.testing.assert_array_equal(
        out.column("length_of_stay"), ref.column("length_of_stay")
    )


def test_projection_and_comparisons(session, hospital_table):
    out = session.sql(
        "SELECT hospital_id, length_of_stay FROM events "
        "WHERE length_of_stay > 5.0 AND admission_count <= 30"
    )
    m = (hospital_table.column("length_of_stay") > 5.0) & (
        hospital_table.column("admission_count") <= 30
    )
    assert len(out) == int(m.sum())
    assert set(f.name for f in out.schema.fields) == {"hospital_id", "length_of_stay"}


def test_or_parens_and_equality(session, hospital_table):
    out = session.sql(
        "SELECT * FROM events WHERE hospital_id = 'H00' "
        "OR (hospital_id = 'H01' AND length_of_stay < 4)"
    )
    hid = hospital_table.column("hospital_id")
    los = hospital_table.column("length_of_stay")
    expect = (hid == "H00") | ((hid == "H01") & (los < 4))
    assert len(out) == int(expect.sum())


def test_group_by_aggregates(session, hospital_table):
    out = session.sql(
        "SELECT hospital_id, COUNT(*) AS n, AVG(length_of_stay) AS mean_los, "
        "MAX(emergency_visits) AS worst FROM events GROUP BY hospital_id "
        "ORDER BY hospital_id"
    )
    hid = hospital_table.column("hospital_id")
    los = hospital_table.column("length_of_stay")
    ev = hospital_table.column("emergency_visits")
    hospitals = np.unique(hid)
    np.testing.assert_array_equal(out.column("hospital_id"), hospitals)
    for i, h in enumerate(hospitals):
        sel = hid == h
        assert out.column("n")[i] == sel.sum()
        np.testing.assert_allclose(out.column("mean_los")[i], los[sel].mean())
        assert out.column("worst")[i] == ev[sel].max()


def test_whole_table_aggregate_and_limit(session, hospital_table):
    out = session.sql("SELECT COUNT(*) AS n, SUM(admission_count) AS s FROM events")
    assert len(out) == 1
    assert out.column("n")[0] == len(hospital_table)
    assert out.column("s")[0] == hospital_table.column("admission_count").sum()
    top = session.sql(
        "SELECT * FROM events ORDER BY length_of_stay DESC LIMIT 5"
    )
    assert len(top) == 5
    los = np.sort(hospital_table.column("length_of_stay"))[::-1][:5]
    np.testing.assert_allclose(top.column("length_of_stay"), los)


def test_errors_are_clear(session):
    with pytest.raises(ValueError, match="SQL"):
        session.sql("SELECT FROM events")
    with pytest.raises(ValueError, match="GROUP BY"):
        session.sql(
            "SELECT hospital_id, length_of_stay FROM events GROUP BY hospital_id"
        )
    with pytest.raises(ValueError, match="SUM"):
        session.sql("SELECT SUM(*) FROM events")
    with pytest.raises(KeyError, match="unknown table"):
        session.sql("SELECT * FROM nope")
    with pytest.raises(ValueError, match="trailing"):
        session.sql("SELECT * FROM events LIMIT 3 garbage")


def test_null_semantics_in_aggregates():
    t = ht.Table.from_dict({"g": np.array(["a", "a", "b", "b"], object),
                            "v": np.array([1.0, np.nan, np.nan, np.nan])})
    one = execute("SELECT AVG(v) AS m, COUNT(v) AS c FROM t", lambda n: t)
    # Spark null semantics: nulls skipped, COUNT(col) counts non-null
    assert one.column("m")[0] == 1.0 and one.column("c")[0] == 1
    g = execute(
        "SELECT g, SUM(v) AS s, COUNT(v) AS c FROM t GROUP BY g ORDER BY g",
        lambda n: t,
    )
    assert g.column("s")[0] == 1.0 and g.column("c")[1] == 0
    assert np.isnan(g.column("s")[1])  # all-null group aggregates to null


def test_order_by_unselected_column(hospital_table):
    out = execute(
        "SELECT hospital_id FROM t ORDER BY length_of_stay DESC LIMIT 3",
        lambda n: hospital_table,
    )
    top = np.argsort(hospital_table.column("length_of_stay"))[::-1][:3]
    np.testing.assert_array_equal(
        out.column("hospital_id"), hospital_table.column("hospital_id")[top]
    )


def test_mixed_bare_column_with_aggregate_raises(hospital_table):
    with pytest.raises(ValueError, match="GROUP BY"):
        execute(
            "SELECT hospital_id, COUNT(*) FROM t", lambda n: hospital_table
        )


def test_timestamp_group_min_max_and_whitespace(hospital_table):
    out = execute(
        "SELECT hospital_id, MIN(event_time) AS first, MAX(event_time) AS last "
        "FROM t GROUP BY hospital_id ORDER BY hospital_id  \n",  # trailing ws
        lambda n: hospital_table,
    )
    hid = hospital_table.column("hospital_id")
    ts = hospital_table.column("event_time")
    for i, h in enumerate(np.unique(hid)):
        assert out.column("first")[i] == ts[hid == h].min()
        assert out.column("last")[i] == ts[hid == h].max()
    with pytest.raises(ValueError, match="numeric"):
        execute("SELECT SUM(event_time) FROM t", lambda n: hospital_table)


def test_null_rows_fail_comparisons_and_group_once():
    t = ht.Table.from_dict({"v": np.array([1.0, np.nan, 3.0, np.nan])})
    # Spark: null fails every comparison, != included
    ne = execute("SELECT * FROM t WHERE v != 3", lambda n: t)
    np.testing.assert_array_equal(ne.column("v"), [1.0])
    # Spark: all nulls form ONE group
    g = execute(
        "SELECT v, COUNT(*) AS c FROM t GROUP BY v", lambda n: t
    )
    assert len(g) == 3 and sorted(g.column("c")) == [1, 1, 2]


def test_group_by_empty_result(hospital_table):
    out = execute(
        "SELECT hospital_id, COUNT(*) AS c FROM t "
        "WHERE length_of_stay > 1e9 GROUP BY hospital_id",
        lambda n: hospital_table,
    )
    assert len(out) == 0
    assert set(f.name for f in out.schema.fields) == {"hospital_id", "c"}


def test_order_by_select_alias(hospital_table):
    out = execute(
        "SELECT length_of_stay AS los FROM t ORDER BY los DESC LIMIT 4",
        lambda n: hospital_table,
    )
    ref = np.sort(hospital_table.column("length_of_stay"))[::-1][:4]
    np.testing.assert_allclose(out.column("los"), ref)


def test_execute_without_session(hospital_table):
    out = execute(
        "SELECT hospital_id FROM t WHERE seasonality_index >= 1.0",
        lambda name: hospital_table,
    )
    assert len(out) == int((hospital_table.column("seasonality_index") >= 1.0).sum())


# ---- round 4: JOIN / DISTINCT / HAVING (VERDICT r3 next #8) ----------


@pytest.fixture
def hospital_meta():
    """Per-hospital metadata table — the first real JOIN a user writes
    against this schema (reference ``mllearnforhospitalnetwork.py:65``
    gives every event a hospital_id)."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

    return Table.from_dict(
        {
            "hospital_id": np.array(["H00", "H01", "H02", "H99"], object),
            "name": np.array(
                ["General", "Mercy", "Childrens", "Closed"], object
            ),
            "beds": np.array([400, 150, 90, 10]),
        }
    )


def test_per_hospital_join_group_having(session, hospital_table, hospital_meta):
    """The VERDICT's target query: SELECT h.name, AVG(length_of_stay) ...
    JOIN ... GROUP BY ... HAVING."""
    session.register_table("hospitals", hospital_meta)
    out = session.sql(
        "SELECT h.name, AVG(length_of_stay) AS mean_los, COUNT(*) AS n "
        "FROM events e JOIN hospitals h ON e.hospital_id = h.hospital_id "
        "GROUP BY h.name HAVING COUNT(*) >= 1 ORDER BY mean_los DESC"
    )
    ids = hospital_table.column("hospital_id")
    los = hospital_table.column("length_of_stay")
    meta = {"H00": "General", "H01": "Mercy", "H02": "Childrens"}
    expect = {}
    for hid, nm in meta.items():
        m = ids == hid
        if m.any():
            expect[nm] = np.nanmean(los[m])
    assert len(expect) == 3 and set(out.column("name")) == set(expect)
    got = dict(zip(out.column("name"), out.column("mean_los")))
    for nm, v in expect.items():
        np.testing.assert_allclose(got[nm], v, rtol=1e-12)
    # ordered descending
    assert list(out.column("mean_los")) == sorted(out.column("mean_los"))[::-1]


def test_inner_join_drops_unmatched(session, hospital_table, hospital_meta):
    session.register_table("hospitals", hospital_meta)
    out = session.sql(
        "SELECT e.hospital_id, h.beds FROM events e "
        "JOIN hospitals h ON e.hospital_id = h.hospital_id"
    )
    matched = np.isin(
        hospital_table.column("hospital_id"), ["H00", "H01", "H02", "H99"]
    )
    assert len(out) == int(matched.sum()) > 0
    assert not np.isin(out.column("hospital_id"), ["H99"]).any()  # no events


def test_left_join_null_fills(session, hospital_table, hospital_meta):
    session.register_table("hospitals", hospital_meta)
    out = session.sql(
        "SELECT e.hospital_id, h.beds FROM events e "
        "LEFT JOIN hospitals h ON e.hospital_id = h.hospital_id"
    )
    assert len(out) == len(hospital_table)  # every event row survives
    unmatched = ~np.isin(out.column("hospital_id"), ["H00", "H01", "H02"])
    assert unmatched.any()  # H03/H04 events have no metadata row
    assert np.isnan(out.column("beds")[unmatched]).all()
    assert not np.isnan(out.column("beds")[~unmatched]).any()


def test_join_reversed_on_and_qualified_where(
    session, hospital_table, hospital_meta
):
    session.register_table("hospitals", hospital_meta)
    out = session.sql(
        "SELECT h.name, e.length_of_stay FROM events e "
        "JOIN hospitals h ON h.hospital_id = e.hospital_id "
        "WHERE h.beds >= 150 AND e.length_of_stay > 0"
    )
    assert set(out.column("name")) == {"General", "Mercy"}


def test_distinct(session):
    out = session.sql("SELECT DISTINCT hospital_id FROM events")
    ids = out.column("hospital_id")
    assert len(ids) == len(set(ids))
    assert set(ids) == set(
        session.sql("SELECT hospital_id FROM events").column("hospital_id")
    )


def test_having_on_unselected_aggregate(session):
    out = session.sql(
        "SELECT hospital_id FROM events GROUP BY hospital_id "
        "HAVING AVG(length_of_stay) > 0 AND COUNT(*) >= 2"
    )
    full = session.sql(
        "SELECT hospital_id, COUNT(*) AS c FROM events GROUP BY hospital_id"
    )
    keep = set(
        h for h, c in zip(full.column("hospital_id"), full.column("c")) if c >= 2
    )
    assert set(out.column("hospital_id")) == keep


def test_join_errors(session, hospital_meta):
    session.register_table("hospitals", hospital_meta)
    with pytest.raises(ValueError, match="ambiguous"):
        session.sql(
            "SELECT hospital_id FROM events e "
            "JOIN hospitals h ON e.hospital_id = h.hospital_id"
        )
    with pytest.raises(ValueError, match="duplicate"):
        session.sql(
            "SELECT * FROM events e JOIN hospitals e "
            "ON e.hospital_id = e.hospital_id"
        )
    with pytest.raises(ValueError, match="JOIN ON"):
        session.sql(
            "SELECT * FROM events e JOIN hospitals h ON e.nope = h.nope"
        )
    with pytest.raises(ValueError, match="HAVING"):
        session.sql("SELECT hospital_id FROM events HAVING COUNT(*) > 1")


def test_having_on_whole_table_aggregates(session):
    """No GROUP BY: the whole table is one group — HAVING filters the
    single output row (review finding: it was silently ignored)."""
    kept = session.sql("SELECT COUNT(*) AS n FROM events HAVING COUNT(*) > 0")
    assert len(kept) == 1
    dropped = session.sql(
        "SELECT COUNT(*) AS n FROM events HAVING COUNT(*) > 999999"
    )
    assert len(dropped) == 0
    # alias reference works too
    assert len(session.sql("SELECT COUNT(*) AS n FROM events HAVING n > 0")) == 1


def test_duplicate_output_columns_raise(session, hospital_meta):
    session.register_table("hospitals", hospital_meta)
    with pytest.raises(ValueError, match="duplicate output column"):
        session.sql(
            "SELECT e.hospital_id, h.hospital_id FROM events e "
            "JOIN hospitals h ON e.hospital_id = h.hospital_id"
        )
    # disambiguated with AS: both survive
    out = session.sql(
        "SELECT e.hospital_id AS eid, h.hospital_id AS hid FROM events e "
        "JOIN hospitals h ON e.hospital_id = h.hospital_id"
    )
    assert set(out.schema.names if hasattr(out.schema, "names") else
               [f.name for f in out.schema.fields]) == {"eid", "hid"}


def test_order_by_canonical_aggregate(session):
    out = session.sql(
        "SELECT hospital_id, COUNT(*) AS n FROM events "
        "GROUP BY hospital_id ORDER BY COUNT(*) DESC"
    )
    n = out.column("n")
    assert list(n) == sorted(n)[::-1]
    assert "__order_by__" not in out.columns
    # an aggregate never selected also orders (computed on demand)
    out2 = session.sql(
        "SELECT hospital_id FROM events GROUP BY hospital_id "
        "ORDER BY AVG(length_of_stay) DESC LIMIT 1"
    )
    ref = session.sql(
        "SELECT hospital_id, AVG(length_of_stay) AS a FROM events "
        "GROUP BY hospital_id ORDER BY a DESC LIMIT 1"
    )
    assert list(out2.column("hospital_id")) == list(ref.column("hospital_id"))


def test_order_by_qualified_group_key(session, hospital_meta):
    session.register_table("hospitals", hospital_meta)
    out = session.sql(
        "SELECT h.beds, COUNT(*) AS n FROM events e "
        "JOIN hospitals h ON e.hospital_id = h.hospital_id "
        "GROUP BY h.beds ORDER BY h.beds DESC"
    )
    b = out.column("beds")
    assert list(b) == sorted(b)[::-1]


def test_join_after_left_join_null_keys(session, hospital_table, hospital_meta):
    """Chained join whose key column contains LEFT-JOIN None fills: null
    keys never match and never crash np.unique (review finding)."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

    session.register_table("hospitals", hospital_meta)
    regions = Table.from_dict(
        {
            "name": np.array(["General", "Mercy"], object),
            "region": np.array(["north", "south"], object),
        }
    )
    session.register_table("regions", regions)
    out = session.sql(
        "SELECT e.hospital_id, r.region FROM events e "
        "LEFT JOIN hospitals h ON e.hospital_id = h.hospital_id "
        "JOIN regions r ON h.name = r.name"
    )
    assert set(out.column("region")) <= {"north", "south"}
    assert len(out) > 0


def test_join_incomparable_key_types(session, hospital_meta):
    session.register_table("hospitals", hospital_meta)
    with pytest.raises(ValueError, match="incomparable"):
        session.sql(
            "SELECT * FROM events e JOIN hospitals h ON e.hospital_id = h.beds"
        )


def test_left_join_none_fills_survive_downstream(
    session, hospital_table, hospital_meta
):
    """GROUP BY / DISTINCT / ORDER BY / WHERE over the None fills a LEFT
    JOIN writes into object columns (review findings: raw TypeErrors)."""
    session.register_table("hospitals", hospital_meta)
    base = (
        "FROM events e LEFT JOIN hospitals h "
        "ON e.hospital_id = h.hospital_id"
    )
    g = session.sql(f"SELECT h.name, COUNT(*) AS n {base} GROUP BY h.name")
    # one group is the null (unmatched) bucket
    names = list(g.column("name"))
    assert sum(1 for v in names if v is None) == 1
    assert sum(g.column("n")) == len(hospital_table)

    d = session.sql(f"SELECT DISTINCT h.name {base}")
    assert sum(1 for v in d.column("name") if v is None) == 1

    o = session.sql(f"SELECT h.name {base} ORDER BY h.name")
    vals = list(o.column("name"))
    k = sum(1 for v in vals if v is None)
    assert k > 0 and all(v is None for v in vals[:k])  # ASC: nulls first
    o2 = session.sql(f"SELECT h.name {base} ORDER BY h.name DESC")
    vals2 = list(o2.column("name"))
    assert all(v is None for v in vals2[-k:])          # DESC: nulls last

    w = session.sql(f"SELECT h.name {base} WHERE h.name >= 'A'")
    assert all(v is not None for v in w.column("name"))


def test_left_join_empty_right_table(session, hospital_table):
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

    empty = Table.from_dict(
        {
            "hospital_id": np.array([], object),
            "beds": np.array([], np.int64),
        }
    )
    session.register_table("nobody", empty)
    out = session.sql(
        "SELECT e.hospital_id, x.beds FROM events e "
        "LEFT JOIN nobody x ON e.hospital_id = x.hospital_id"
    )
    assert len(out) == len(hospital_table)
    assert np.isnan(out.column("beds")).all()


def test_order_by_aggregate_without_group_by(session):
    out = session.sql("SELECT COUNT(*) AS n FROM events ORDER BY COUNT(*)")
    assert len(out) == 1
    with pytest.raises(ValueError, match="ORDER BY"):
        session.sql("SELECT COUNT(*) AS n FROM events ORDER BY nope")


# ---- round 4b: arithmetic expressions + star-plus (SQLTransformer shapes)


def test_arithmetic_expressions(session, hospital_table):
    out = session.sql(
        "SELECT *, admission_count + emergency_visits AS load, "
        "length_of_stay * 2 AS dlos FROM events LIMIT 5"
    )
    assert "load" in out.columns and "hospital_id" in out.columns
    np.testing.assert_allclose(
        out.column("load"),
        (hospital_table.column("admission_count")
         + hospital_table.column("emergency_visits"))[:5],
    )
    # precedence, parens, unary minus, division
    out = session.sql(
        "SELECT admission_count + emergency_visits * 2 AS x, "
        "(admission_count + emergency_visits) * 2 AS y, "
        "-seasonality_index AS ns, "
        "length_of_stay / seasonality_index AS r FROM events LIMIT 3"
    )
    a = hospital_table.column("admission_count")[:3]
    e = hospital_table.column("emergency_visits")[:3]
    np.testing.assert_allclose(out.column("x"), a + 2 * e)
    np.testing.assert_allclose(out.column("y"), (a + e) * 2)
    np.testing.assert_allclose(
        out.column("ns"), -hospital_table.column("seasonality_index")[:3]
    )


def test_arithmetic_over_aggregates(session):
    grouped = session.sql(
        "SELECT hospital_id, SUM(length_of_stay) / COUNT(*) AS mean_los, "
        "MAX(length_of_stay) - MIN(length_of_stay) AS spread "
        "FROM events GROUP BY hospital_id ORDER BY hospital_id"
    )
    ref = session.sql(
        "SELECT hospital_id, AVG(length_of_stay) AS a FROM events "
        "GROUP BY hospital_id ORDER BY hospital_id"
    )
    np.testing.assert_allclose(
        grouped.column("mean_los"), ref.column("a"), rtol=1e-12
    )
    assert (grouped.column("spread") >= 0).all()
    whole = session.sql(
        "SELECT SUM(length_of_stay) / COUNT(*) AS m FROM events"
    )
    full = session.sql("SELECT AVG(length_of_stay) AS a FROM events")
    np.testing.assert_allclose(whole.column("m"), full.column("a"), rtol=1e-12)


def test_count_star_dtype_consistent_across_spellings(session):
    # count(*) must be integer however it is spelled — bare projection and
    # expression-atom paths used to disagree (int64 vs float64)
    out = session.sql("SELECT COUNT(*) AS a, COUNT(*) + 0 AS b FROM events")
    assert np.issubdtype(out.column("a").dtype, np.integer)
    assert np.issubdtype(out.column("b").dtype, np.integer)
    assert out.column("a")[0] == out.column("b")[0]


def test_division_by_zero_is_null(session):
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.sql import execute
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

    t = Table.from_dict({"a": np.array([1.0, 2.0]), "b": np.array([2.0, 0.0])})
    out = execute("SELECT a / b AS q FROM t", lambda n: t)
    assert out.column("q")[0] == 0.5 and np.isnan(out.column("q")[1])


def test_expression_errors(session):
    with pytest.raises(ValueError, match="GROUP BY"):
        session.sql("SELECT length_of_stay + COUNT(*) AS z FROM events")
    with pytest.raises(ValueError, match="mix"):
        session.sql(
            "SELECT *, COUNT(*) AS c FROM events GROUP BY hospital_id"
        )
    with pytest.raises(ValueError, match="expression"):
        session.sql(
            "SELECT length_of_stay + 1 AS z FROM events GROUP BY hospital_id"
        )
    # default rendered name for an un-aliased expression
    out = session.sql("SELECT admission_count + 1 FROM events LIMIT 1")
    assert list(out.columns) == ["(admission_count + 1)"]


def test_sql_transformer_spark_canonical_shape(session, hospital_table):
    """Spark's SQLTransformer doc example shape now runs verbatim."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

    t = Table.from_dict(
        {"id": np.array([0.0, 2.0]), "v1": np.array([1.0, 2.0]),
         "v2": np.array([3.0, 4.0])}
    )
    st = ht.SQLTransformer(
        statement="SELECT *, (v1 + v2) AS v3, (v1 * v2) AS v4 FROM __THIS__"
    )
    out = st.transform(t)
    assert list(out.columns) == ["id", "v1", "v2", "v3", "v4"]
    np.testing.assert_allclose(out.column("v3"), [4.0, 6.0])
    np.testing.assert_allclose(out.column("v4"), [3.0, 8.0])


def test_order_by_expression_alias_and_star_collision(session):
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.sql import execute
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

    t = Table.from_dict({"a": np.array([3.0, 1.0, 2.0])})
    out = execute("SELECT a + 1 AS x FROM t ORDER BY x DESC", lambda n: t)
    np.testing.assert_allclose(out.column("x"), [4.0, 3.0, 2.0])
    with pytest.raises(ValueError, match="duplicate output column"):
        execute("SELECT *, a + 1 AS a FROM t", lambda n: t)


def test_order_by_constant_expression_alias_keeps_all_rows(session):
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.sql import execute
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

    t = Table.from_dict({"a": np.array([3.0, 1.0, 2.0])})
    out = execute("SELECT a, 1 + 1 AS two FROM t ORDER BY two", lambda n: t)
    assert len(out) == 3
    np.testing.assert_allclose(out.column("two"), [2.0, 2.0, 2.0])


# ------------------------------------------------------------ CASE WHEN
class TestCaseWhen:
    def _t(self):
        return ht.Table.from_dict(
            {
                "los": np.array([2.0, 6.5, 4.0, 9.0, np.nan]),
                "hosp": np.array(["a", "b", "a", "c", "b"], dtype=object),
            }
        )

    def test_case_projection(self, session):
        session.register_table("adm", self._t())
        r = session.sql(
            "SELECT CASE WHEN los > 5.0 THEN 1 ELSE 0 END AS LOS_binary FROM adm"
        )
        # NULL > 5 is NULL -> falsy -> ELSE (Spark semantics)
        np.testing.assert_array_equal(r.column("LOS_binary"), [0, 1, 0, 1, 0])

    def test_case_string_implicit_else_null(self, session):
        session.register_table("adm", self._t())
        r = session.sql(
            "SELECT CASE WHEN los > 8 THEN 'high' WHEN los > 5 THEN 'mid' END "
            "AS tier FROM adm"
        )
        assert list(r.column("tier")) == [None, "mid", None, "high", None]

    def test_case_in_where_order(self, session):
        session.register_table("adm", self._t())
        r = session.sql(
            "SELECT los, CASE WHEN los > 5 THEN los ELSE 0 END AS capped "
            "FROM adm WHERE los > 1 ORDER BY capped DESC LIMIT 2"
        )
        np.testing.assert_array_equal(r.column("capped"), [9.0, 6.5])

    def test_agg_over_case_scalar(self, session):
        session.register_table("adm", self._t())
        r = session.sql(
            "SELECT avg(CASE WHEN los > 5 THEN 1 ELSE 0 END) AS frac, "
            "count(CASE WHEN los > 3 THEN 1 END) AS c FROM adm"
        )
        assert r.column("frac")[0] == pytest.approx(0.4)
        assert r.column("c")[0] == 3  # count skips the implicit-ELSE nulls

    def test_agg_over_case_grouped(self, session):
        session.register_table("adm", self._t())
        r = session.sql(
            "SELECT hosp, sum(CASE WHEN los > 5 THEN 1 ELSE 0 END) AS n_high "
            "FROM adm GROUP BY hosp ORDER BY hosp"
        )
        np.testing.assert_array_equal(r.column("n_high"), [0.0, 1.0, 1.0])

    def test_agg_over_arithmetic(self, session):
        session.register_table("adm", self._t())
        r = session.sql("SELECT avg(los * 2) AS a2 FROM adm")
        assert r.column("a2")[0] == pytest.approx(10.75)  # nulls skipped

    def test_case_requires_when_and_end(self, session):
        session.register_table("adm", self._t())
        with pytest.raises(ValueError, match="WHEN"):
            session.sql("SELECT CASE ELSE 1 END AS x FROM adm")
        with pytest.raises(ValueError, match="end"):
            session.sql("SELECT CASE WHEN los > 1 THEN 1 AS x FROM adm")

    def test_case_datetime_implicit_else_is_nat(self, session):
        t = ht.Table.from_dict(
            {
                "los": np.array([2.0, 9.0]),
                "ts": np.array(
                    ["2025-03-31T22:00:00", "2025-03-31T23:00:00"],
                    dtype="datetime64[s]",
                ),
            }
        )
        session.register_table("adm2", t)
        r = session.sql("SELECT CASE WHEN los > 5 THEN ts END AS t2 FROM adm2")
        out = r.column("t2")
        assert np.isnat(out[0]) and out[1] == np.datetime64("2025-03-31T23:00:00")

    def test_case_incompatible_branch_types_friendly_error(self, session):
        session.register_table("adm", self._t())
        with pytest.raises(ValueError, match="incompatible types"):
            session.sql("SELECT CASE WHEN los > 5 THEN 'hi' ELSE 0 END AS x FROM adm")


# ------------------------------------------- IS NULL / IN / NOT (3VL)
class TestNullPredicates:
    def _t(self):
        return ht.Table.from_dict(
            {
                "a": np.array([1.0, 8.0, np.nan, 3.0]),
                "b": np.array([np.nan, 1.0, 2.0, 9.0]),
                "h": np.array(["x", "y", "z", "y"], dtype=object),
            }
        )

    def test_is_null(self, session):
        session.register_table("t3", self._t())
        r = session.sql("SELECT h FROM t3 WHERE a IS NULL")
        assert list(r.column("h")) == ["z"]
        r2 = session.sql("SELECT h FROM t3 WHERE a IS NOT NULL AND b IS NOT NULL")
        assert list(r2.column("h")) == ["y", "y"]

    def test_in_and_not_in(self, session):
        session.register_table("t3", self._t())
        r = session.sql("SELECT a FROM t3 WHERE h IN ('x', 'z')")
        np.testing.assert_array_equal(np.isnan(r.column("a")), [False, True])
        # NOT IN on a null row: UNKNOWN -> filtered (Spark semantics)
        r2 = session.sql("SELECT h FROM t3 WHERE a NOT IN (1, 3)")
        assert list(r2.column("h")) == ["y"]

    def test_not_three_valued(self, session):
        session.register_table("t3", self._t())
        # row 'x': a=1 (a>5 FALSE), b null -> (a>5 AND b>5) = FALSE AND
        # UNKNOWN = FALSE -> NOT keeps it.  row 'z': a null, b=2 ->
        # UNKNOWN AND FALSE = FALSE -> NOT keeps it too.  row 'y'(8,1):
        # TRUE AND FALSE = FALSE -> kept; row 'y'(3,9): FALSE AND TRUE ->
        # kept.  Everything passes here; the discriminating case:
        r = session.sql("SELECT h FROM t3 WHERE NOT (a > 5 OR b > 5)")
        # 'x': FALSE OR UNKNOWN = UNKNOWN -> NOT = UNKNOWN -> filtered
        # 'y'(8,1): TRUE -> filtered; 'z': UNKNOWN OR FALSE -> filtered
        # 'y'(3,9): FALSE OR TRUE = TRUE -> filtered... keep none? no:
        assert list(r.column("h")) == []
        r2 = session.sql("SELECT h FROM t3 WHERE NOT (a > 5 AND b > 5)")
        assert list(r2.column("h")) == ["x", "y", "z", "y"]

    def test_not_requires_in(self, session):
        session.register_table("t3", self._t())
        with pytest.raises(ValueError, match="IN after NOT"):
            session.sql("SELECT h FROM t3 WHERE a NOT = 1")


# ------------------------------------------------------ scalar functions
class TestScalarFunctions:
    def _t(self):
        return ht.Table.from_dict(
            {
                "v": np.array([-2.5, 1.45, np.nan, 3.0]),
                "s": np.array(["Ab", None, "cD", "ee"], dtype=object),
                "fb": np.array([9.0, 9.0, 9.0, 9.0]),
            }
        )

    def test_abs_round_halfup(self, session):
        session.register_table("tf", self._t())
        r = session.sql("SELECT abs(v) AS a, round(v, 1) AS r FROM tf")
        np.testing.assert_allclose(r.column("a"), [2.5, 1.45, np.nan, 3.0])
        # Spark ROUND is HALF_UP: 1.45 -> 1.5 (numpy's half-even gives 1.4)
        np.testing.assert_allclose(r.column("r"), [-2.5, 1.5, np.nan, 3.0])

    def test_string_functions_null_propagation(self, session):
        session.register_table("tf", self._t())
        r = session.sql("SELECT upper(s) AS u, lower(s) AS lo, length(s) AS L FROM tf")
        assert list(r.column("u")) == ["AB", None, "CD", "EE"]
        assert list(r.column("lo")) == ["ab", None, "cd", "ee"]
        np.testing.assert_allclose(r.column("L"), [2, np.nan, 2, 2])

    def test_coalesce(self, session):
        session.register_table("tf", self._t())
        r = session.sql("SELECT coalesce(v, fb) AS c FROM tf")
        np.testing.assert_allclose(r.column("c"), [-2.5, 1.45, 9.0, 3.0])
        r2 = session.sql("SELECT coalesce(s, 'missing') AS cs FROM tf")
        assert list(r2.column("cs")) == ["Ab", "missing", "cD", "ee"]

    def test_fn_over_aggregate_and_in_case(self, session):
        session.register_table("tf", self._t())
        r = session.sql("SELECT round(avg(v), 2) AS m FROM tf")
        assert r.column("m")[0] == pytest.approx(0.65)
        r2 = session.sql(
            "SELECT CASE WHEN v > 0 THEN round(v) ELSE abs(v) END AS x FROM tf"
        )
        np.testing.assert_allclose(r2.column("x"), [2.5, 1.0, np.nan, 3.0])

    def test_fn_arity_and_unknown(self, session):
        session.register_table("tf", self._t())
        with pytest.raises(ValueError, match="ABS takes 1"):
            session.sql("SELECT abs(v, v) AS x FROM tf")
        # a column named like a function, WITHOUT parens, stays a column
        t2 = ht.Table.from_dict({"round": np.array([1.0, 2.0])})
        session.register_table("tr", t2)
        np.testing.assert_allclose(
            session.sql("SELECT round FROM tr").column("round"), [1.0, 2.0]
        )

    def test_fn_type_guards(self, session):
        session.register_table("tf", self._t())
        with pytest.raises(ValueError, match="COALESCE arguments mix"):
            session.sql("SELECT coalesce(v, 'x') AS c FROM tf")
        with pytest.raises(ValueError, match="LENGTH expects a string"):
            session.sql("SELECT length(v) AS L FROM tf")
        with pytest.raises(ValueError, match="ROUND scale must be a literal"):
            session.sql("SELECT round(v, v) AS r FROM tf")

    def test_round_decimal_parity(self, session):
        """Spark rounds via BigDecimal on the double's shortest repr:
        0.285 -> 0.29 even though the binary value is 0.28499999..."""
        t = ht.Table.from_dict({"x": np.array([0.285, 1e308, -0.285])})
        session.register_table("trd", t)
        r = session.sql("SELECT round(x, 2) AS r FROM trd")
        np.testing.assert_allclose(r.column("r"), [0.29, 1e308, -0.29])

    def test_fn_numeric_guards_and_predicate_hint(self, session):
        session.register_table("tf", self._t())
        with pytest.raises(ValueError, match="ABS expects a numeric"):
            session.sql("SELECT abs(s) AS a FROM tf")
        with pytest.raises(ValueError, match="ROUND expects a numeric"):
            session.sql("SELECT round(s) AS r FROM tf")
        with pytest.raises(ValueError, match="only supported in the select"):
            session.sql("SELECT v FROM tf WHERE length(s) > 1")
        with pytest.raises(ValueError, match="only supported in the select"):
            session.sql("SELECT v FROM tf ORDER BY abs(v)")


# -------------------------------------------------- GROUP BY expressions
class TestGroupByExpression:
    def _t(self):
        return ht.Table.from_dict(
            {
                "los": np.array([2.0, 6.5, 4.0, 9.0, 12.0, np.nan]),
                "w": np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0]),
            }
        )

    def test_group_by_case_bucketing(self, session):
        session.register_table("g1", self._t())
        r = session.sql(
            "SELECT CASE WHEN los > 8 THEN 'high' WHEN los > 5 THEN 'mid' "
            "ELSE 'low' END AS tier, count(*) AS n, avg(w) AS mw FROM g1 "
            "GROUP BY CASE WHEN los > 8 THEN 'high' WHEN los > 5 THEN 'mid' "
            "ELSE 'low' END ORDER BY tier"
        )
        assert list(r.column("tier")) == ["high", "low", "mid"]
        np.testing.assert_array_equal(r.column("n"), [2, 3, 1])
        np.testing.assert_allclose(r.column("mw"), [4.5, 10.0 / 3, 2.0])

    def test_group_by_function_key(self, session):
        session.register_table("g1", self._t())
        r = session.sql(
            "SELECT round(los) AS rl, count(*) AS n FROM g1 "
            "GROUP BY round(los) ORDER BY rl"
        )
        # 6.5 rounds HALF_UP to 7; the null lands in its own group first
        got = r.column("rl")
        assert np.isnan(got[0]) and list(got[1:]) == [2.0, 4.0, 7.0, 9.0, 12.0]

    def test_group_expr_mixed_with_name_key(self, session):
        t = ht.Table.from_dict(
            {
                "h": np.array(["a", "a", "b", "b"], dtype=object),
                "v": np.array([1.0, 7.0, 2.0, 8.0]),
            }
        )
        session.register_table("g2", t)
        r = session.sql(
            "SELECT h, CASE WHEN v > 5 THEN 1 ELSE 0 END AS big, count(*) AS n "
            "FROM g2 GROUP BY h, CASE WHEN v > 5 THEN 1 ELSE 0 END "
            "ORDER BY h"
        )
        assert len(r.column("n")) == 4 and set(r.column("n")) == {1}

    def test_group_expr_having(self, session):
        session.register_table("g1", self._t())
        r = session.sql(
            "SELECT CASE WHEN los > 8 THEN 1 ELSE 0 END AS big, count(*) AS n "
            "FROM g1 GROUP BY CASE WHEN los > 8 THEN 1 ELSE 0 END "
            "HAVING count(*) > 2"
        )
        # big=1 is [9, 12] (2 rows, filtered); big=0 keeps its 4 rows
        np.testing.assert_array_equal(r.column("big"), [0])
        np.testing.assert_array_equal(r.column("n"), [4])

    def test_group_by_agg_rejected(self, session):
        session.register_table("g1", self._t())
        with pytest.raises(ValueError, match="aggregates are not allowed"):
            session.sql("SELECT count(*) AS n FROM g1 GROUP BY avg(los) + 1")

    def test_nonkey_expression_still_rejected(self, session):
        session.register_table("g1", self._t())
        with pytest.raises(ValueError, match="must appear in GROUP BY"):
            session.sql(
                "SELECT los + 1 AS x, count(*) AS n FROM g1 "
                "GROUP BY CASE WHEN los > 5 THEN 1 ELSE 0 END"
            )

    def test_group_by_ordinal(self, session):
        """Spark groupByOrdinal: GROUP BY 1 = the first select item."""
        session.register_table("g1", self._t())
        r = session.sql(
            "SELECT CASE WHEN los > 8 THEN 1 ELSE 0 END AS big, count(*) AS n "
            "FROM g1 GROUP BY 1 ORDER BY big"
        )
        np.testing.assert_array_equal(r.column("big"), [0, 1])
        np.testing.assert_array_equal(r.column("n"), [4, 2])
        with pytest.raises(ValueError, match="ordinal 3"):
            session.sql("SELECT los, count(*) AS n FROM g1 GROUP BY 3")
        with pytest.raises(ValueError, match="refers to an aggregate"):
            session.sql("SELECT count(*) AS n FROM g1 GROUP BY 1")
        # a non-integer literal key is a CONSTANT, not an ordinal —
        # Spark groups every row under it (one group)
        r2 = session.sql("SELECT count(*) AS n FROM g1 GROUP BY 1.5")
        np.testing.assert_array_equal(r2.column("n"), [6])


# ------------------------------------------------------------ UNION [ALL]
class TestUnion:
    @pytest.fixture
    def two_tables(self, session):
        a = ht.Table.from_dict(
            {"h": np.array(["x", "y"], object), "v": np.array([1.0, 2.0])}
        )
        b = ht.Table.from_dict(
            {"hosp": np.array(["y", "z"], object), "val": np.array([2.0, 3.0])}
        )
        session.register_table("ua", a)
        session.register_table("ub", b)
        return session

    def test_union_all_positional_alignment(self, two_tables):
        r = two_tables.sql("SELECT h, v FROM ua UNION ALL SELECT hosp, val FROM ub")
        # names come from the FIRST branch; rows concatenate positionally
        assert list(r.column("h")) == ["x", "y", "y", "z"]
        np.testing.assert_allclose(r.column("v"), [1, 2, 2, 3])

    def test_union_dedups_and_orders_whole_result(self, two_tables):
        r = two_tables.sql(
            "SELECT h, v FROM ua UNION SELECT hosp, val FROM ub ORDER BY v DESC"
        )
        assert list(r.column("h")) == ["z", "y", "x"]  # (y,2) dedup'd

    def test_union_mixed_all_left_assoc(self, two_tables):
        # (ua UNION ua) dedups to 2 rows, then UNION ALL appends ub's 2
        r = two_tables.sql(
            "SELECT h, v FROM ua UNION SELECT h, v FROM ua "
            "UNION ALL SELECT hosp, val FROM ub"
        )
        assert len(r) == 4

    def test_union_guards(self, two_tables):
        with pytest.raises(ValueError, match="must match"):
            two_tables.sql("SELECT h FROM ua UNION SELECT hosp, val FROM ub")
        with pytest.raises(ValueError, match="mixes string and numeric"):
            two_tables.sql("SELECT h FROM ua UNION ALL SELECT val FROM ub")
        with pytest.raises(ValueError, match="set-operation branch"):
            two_tables.sql("SELECT h FROM ua LIMIT 1 UNION SELECT hosp FROM ub")

    def test_union_with_aggregates_and_limit(self, two_tables):
        r = two_tables.sql(
            "SELECT count(*) AS n FROM ua UNION ALL SELECT count(*) AS n "
            "FROM ub LIMIT 2"
        )
        np.testing.assert_array_equal(r.column("n"), [2, 2])

    def test_union_datetime_guard_and_join_order_resolution(self, two_tables):
        t = ht.Table.from_dict(
            {"ts": np.array(["2025-01-01T00:00:00"], dtype="datetime64[s]")}
        )
        two_tables.register_table("ut", t)
        with pytest.raises(ValueError, match="mixes numeric and timestamp"):
            two_tables.sql("SELECT v FROM ua UNION ALL SELECT ts FROM ut")
        # ORDER BY resolves unqualified names over qualified union output
        meta = ht.Table.from_dict(
            {"h": np.array(["x", "y", "z"], object), "beds": np.array([5.0, 7.0, 9.0])}
        )
        two_tables.register_table("um", meta)
        r = two_tables.sql(
            "SELECT ua.v, um.beds FROM ua JOIN um ON ua.h = um.h "
            "UNION ALL SELECT ua.v, um.beds FROM ua JOIN um ON ua.h = um.h "
            "ORDER BY beds DESC"
        )
        assert len(r) == 4 and r.column(list(r.columns)[1])[0] == 7.0


# ------------------------------------------------------- FROM subqueries
class TestDerivedTables:
    @pytest.fixture
    def adm(self, session):
        t = ht.Table.from_dict(
            {
                "h": np.array(["a", "a", "b", "b", "c"], object),
                "los": np.array([2.0, 6.0, 4.0, 9.0, 12.0]),
            }
        )
        session.register_table("adm", t)
        return session

    def test_from_subquery_with_filter(self, adm):
        r = adm.sql(
            "SELECT hosp, n FROM (SELECT h AS hosp, count(*) AS n FROM adm "
            "GROUP BY h) g WHERE n > 1 ORDER BY hosp"
        )
        assert list(r.column("hosp")) == ["a", "b"]
        np.testing.assert_array_equal(r.column("n"), [2, 2])

    def test_join_against_derived_aggregate(self, adm):
        """The canonical per-group-average enrichment join."""
        r = adm.sql(
            "SELECT a.h, a.los, m.mean_los FROM adm a "
            "JOIN (SELECT h, avg(los) AS mean_los FROM adm GROUP BY h) m "
            "ON a.h = m.h WHERE a.los > 5 ORDER BY a.los"
        )
        assert list(r.column("h")) == ["a", "b", "c"]
        np.testing.assert_allclose(r.column("mean_los"), [4.0, 6.5, 12.0])

    def test_topn_subquery_keeps_inner_order_limit(self, adm):
        r = adm.sql(
            "SELECT * FROM (SELECT los FROM adm ORDER BY los DESC LIMIT 2) t2 "
            "ORDER BY los"
        )
        np.testing.assert_allclose(r.column("los"), [9.0, 12.0])

    def test_union_inside_subquery(self, adm):
        r = adm.sql(
            "SELECT * FROM (SELECT h FROM adm UNION SELECT h FROM adm) u "
            "ORDER BY h"
        )
        assert list(r.column("h")) == ["a", "b", "c"]

    def test_subquery_requires_alias(self, adm):
        with pytest.raises(ValueError, match="needs an alias"):
            adm.sql("SELECT * FROM (SELECT los FROM adm)")

    def test_subquery_scoping_and_diagnostics(self, adm):
        meta = ht.Table.from_dict(
            {"h": np.array(["a", "b", "c"], object), "beds": np.array([5.0, 7.0, 9.0])}
        )
        adm.register_table("meta2", meta)
        # inner join qualifiers are stripped at the subquery boundary:
        # the outer query re-qualifies with ITS alias, and the inner
        # alias is invisible outside (Spark scoping)
        r = adm.sql(
            "SELECT g.beds FROM (SELECT adm.h AS hh, meta2.beds FROM adm "
            "JOIN meta2 ON adm.h = meta2.h) g WHERE g.beds > 5 "
            "ORDER BY g.beds DESC LIMIT 1"
        )
        np.testing.assert_allclose(r.column("beds"), [9.0])
        with pytest.raises(ValueError, match="unknown column"):
            adm.sql(
                "SELECT meta2.beds FROM (SELECT adm.h AS hh, meta2.beds "
                "FROM adm JOIN meta2 ON adm.h = meta2.h) g"
            )
        # explicit duplicate select items are caught by the subquery's own
        # alias check; a SELECT * join exposes the post-strip collision
        with pytest.raises(ValueError, match="disambiguate with AS"):
            adm.sql(
                "SELECT * FROM (SELECT adm.h, meta2.h FROM adm "
                "JOIN meta2 ON adm.h = meta2.h) g"
            )
        with pytest.raises(ValueError, match="alias one side"):
            adm.sql(
                "SELECT * FROM (SELECT * FROM adm "
                "JOIN meta2 ON adm.h = meta2.h) g"
            )

    def test_union_distinct_keyword_and_empty_order_validation(self, adm):
        r = adm.sql(
            "SELECT h FROM adm UNION DISTINCT SELECT h FROM adm ORDER BY h"
        )
        assert list(r.column("h")) == ["a", "b", "c"]
        with pytest.raises(ValueError, match="not in the union result"):
            adm.sql(
                "SELECT h FROM adm WHERE los > 99 UNION ALL "
                "SELECT h FROM adm WHERE los > 99 ORDER BY nope"
            )


# ------------------------------------------------- IN (SELECT …) subqueries
class TestInSubquery:
    @pytest.fixture
    def tbls(self, session):
        session.register_table(
            "adm3",
            ht.Table.from_dict(
                {
                    "h": np.array(["a", "a", "b", "c", "d"], object),
                    "los": np.array([2.0, 6.0, 4.0, 9.0, 1.0]),
                }
            ),
        )
        session.register_table(
            "flagged", ht.Table.from_dict({"h": np.array(["a", "c"], object)})
        )
        session.register_table(
            "wn", ht.Table.from_dict({"v": np.array([2.0, np.nan])})
        )
        return session

    def test_semi_and_anti_join(self, tbls):
        r = tbls.sql(
            "SELECT h FROM adm3 WHERE h IN (SELECT h FROM flagged) ORDER BY h"
        )
        assert list(r.column("h")) == ["a", "a", "c"]
        r2 = tbls.sql(
            "SELECT h FROM adm3 WHERE h NOT IN (SELECT h FROM flagged) "
            "ORDER BY h"
        )
        assert list(r2.column("h")) == ["b", "d"]

    def test_null_in_subquery_3vl(self, tbls):
        """Spark's NOT IN null trap: a null in the subquery set makes
        NOT IN never-true (UNKNOWN for non-matches)."""
        r = tbls.sql("SELECT los FROM adm3 WHERE los IN (SELECT v FROM wn)")
        np.testing.assert_allclose(r.column("los"), [2.0])
        r2 = tbls.sql(
            "SELECT los FROM adm3 WHERE los NOT IN (SELECT v FROM wn)"
        )
        assert len(r2) == 0

    def test_self_subquery_and_composition(self, tbls):
        r = tbls.sql(
            "SELECT h FROM adm3 WHERE los IN "
            "(SELECT los FROM adm3 WHERE los > 5) OR h = 'd' ORDER BY h"
        )
        assert list(r.column("h")) == ["a", "c", "d"]

    def test_multi_column_subquery_rejected(self, tbls):
        with pytest.raises(ValueError, match="exactly one column"):
            tbls.sql("SELECT h FROM adm3 WHERE h IN (SELECT h, los FROM adm3)")

    def test_empty_and_cross_type_subqueries(self, tbls):
        tbls.register_table(
            "empty", ht.Table.from_dict({"v": np.array([], dtype=np.float64)})
        )
        # Spark's semi/anti-join over an empty build side: IN = FALSE,
        # NOT IN = TRUE — null operands included
        tbls.register_table(
            "wnull", ht.Table.from_dict({"x": np.array([1.0, np.nan, 3.0])})
        )
        r = tbls.sql("SELECT x FROM wnull WHERE x NOT IN (SELECT v FROM empty)")
        assert len(r) == 3
        r2 = tbls.sql("SELECT x FROM wnull WHERE x IN (SELECT v FROM empty)")
        assert len(r2) == 0
        # numeric column vs string-typed subquery coerces like literal IN
        tbls.register_table(
            "codes", ht.Table.from_dict({"c": np.array(["1", "3"], object)})
        )
        r3 = tbls.sql(
            "SELECT x FROM wnull WHERE x IN (SELECT c FROM codes) ORDER BY x"
        )
        np.testing.assert_allclose(r3.column("x"), [1.0, 3.0])


# ---------------------------------------------------- INTERSECT / EXCEPT
class TestSetOps:
    @pytest.fixture
    def ab(self, session):
        session.register_table(
            "sa", ht.Table.from_dict({"h": np.array(["x", "y", "z", "y"], object)})
        )
        session.register_table(
            "sb", ht.Table.from_dict({"h2": np.array(["y", "z", "w"], object)})
        )
        return session

    def test_intersect_and_except_distinct(self, ab):
        r = ab.sql("SELECT h FROM sa INTERSECT SELECT h2 FROM sb ORDER BY h")
        assert list(r.column("h")) == ["y", "z"]  # distinct, both sides
        r2 = ab.sql("SELECT h FROM sa EXCEPT SELECT h2 FROM sb")
        assert list(r2.column("h")) == ["x"]
        r3 = ab.sql("SELECT h2 FROM sb EXCEPT DISTINCT SELECT h FROM sa")
        assert list(r3.column("h2")) == ["w"]

    def test_intersect_binds_tighter_than_union(self, ab):
        # a UNION (b INTERSECT b) — standard precedence
        r = ab.sql(
            "SELECT h FROM sa UNION SELECT h2 FROM sb "
            "INTERSECT SELECT h2 FROM sb ORDER BY h"
        )
        assert list(r.column("h")) == ["w", "x", "y", "z"]

    def test_trailing_order_limit_binds_chain(self, ab):
        r = ab.sql(
            "SELECT h FROM sa INTERSECT SELECT h2 FROM sb ORDER BY h DESC "
            "LIMIT 1"
        )
        assert list(r.column("h")) == ["z"]
        with pytest.raises(ValueError, match="set-operation branch"):
            ab.sql("SELECT h FROM sa LIMIT 2 EXCEPT SELECT h2 FROM sb")

    def test_nulls_compare_equal_in_set_ops(self, ab):
        ab.register_table(
            "n1", ht.Table.from_dict({"v": np.array([1.0, np.nan])})
        )
        ab.register_table(
            "n2", ht.Table.from_dict({"v": np.array([np.nan, 2.0])})
        )
        # set ops use grouping (null-safe) equality: NaN ∩ NaN = NaN row
        r = ab.sql("SELECT v FROM n1 INTERSECT SELECT v FROM n2")
        assert len(r) == 1 and np.isnan(r.column("v")[0])

    def test_timestamp_in_subquery(self, ab):
        ts = np.array(
            ["2025-03-31T22:00:00", "2025-03-31T23:00:00", "2025-04-01T00:00:00"],
            dtype="datetime64[ns]",
        )
        ab.register_table("tt", ht.Table.from_dict({"ts": ts}))
        ab.register_table("tf2", ht.Table.from_dict({"ts": ts[:2]}))
        r = ab.sql("SELECT ts FROM tt WHERE ts IN (SELECT ts FROM tf2)")
        assert len(r) == 2
        r2 = ab.sql("SELECT ts FROM tt WHERE ts NOT IN (SELECT ts FROM tf2)")
        assert len(r2) == 1 and r2.column("ts")[0] == ts[2]


# ------------------------------------------------------- window functions
class TestWindowFunctions:
    @pytest.fixture
    def wt(self, session):
        session.register_table(
            "wadm",
            ht.Table.from_dict(
                {
                    "h": np.array(["a", "a", "a", "b", "b"], object),
                    "los": np.array([2.0, 6.0, 6.0, 9.0, 1.0]),
                }
            ),
        )
        return session

    def test_partition_aggregate_broadcast(self, wt):
        r = wt.sql("SELECT h, avg(los) OVER (PARTITION BY h) AS m FROM wadm")
        np.testing.assert_allclose(
            r.column("m"), [14 / 3, 14 / 3, 14 / 3, 5.0, 5.0]
        )
        r2 = wt.sql("SELECT max(los) OVER (PARTITION BY h) AS mx FROM wadm")
        np.testing.assert_allclose(r2.column("mx"), [6, 6, 6, 9, 9])

    def test_ranking_functions(self, wt):
        r = wt.sql(
            "SELECT row_number() OVER (PARTITION BY h ORDER BY los) AS rn, "
            "rank() OVER (PARTITION BY h ORDER BY los) AS rk, "
            "dense_rank() OVER (PARTITION BY h ORDER BY los) AS dr FROM wadm"
        )
        np.testing.assert_array_equal(r.column("rn"), [1, 2, 3, 2, 1])
        np.testing.assert_array_equal(r.column("rk"), [1, 2, 2, 2, 1])
        np.testing.assert_array_equal(r.column("dr"), [1, 2, 2, 2, 1])

    def test_running_sum_range_frame_ties(self, wt):
        """Spark's default RANGE frame: tied order values share the
        cumulative at their block's last row."""
        r = wt.sql(
            "SELECT sum(los) OVER (PARTITION BY h ORDER BY los) AS run "
            "FROM wadm"
        )
        np.testing.assert_allclose(r.column("run"), [2, 14, 14, 10, 1])

    def test_global_window_desc(self, wt):
        r = wt.sql("SELECT count(*) OVER (ORDER BY los DESC) AS c FROM wadm")
        np.testing.assert_array_equal(r.column("c"), [4, 3, 3, 1, 5])

    def test_window_composes_with_where_order_and_subquery(self, wt):
        r = wt.sql(
            "SELECT h, rn FROM (SELECT h, los, row_number() OVER "
            "(PARTITION BY h ORDER BY los DESC) AS rn FROM wadm) x "
            "WHERE rn = 1 ORDER BY h"
        )
        # top-1 per hospital by LOS — the canonical windowed query
        assert list(r.column("h")) == ["a", "b"]

    def test_window_guards(self, wt):
        with pytest.raises(ValueError, match="needs an OVER"):
            wt.sql("SELECT row_number() AS r FROM wadm")
        with pytest.raises(ValueError, match="requires ORDER BY"):
            wt.sql("SELECT rank() OVER (PARTITION BY h) AS r FROM wadm")
        with pytest.raises(ValueError, match="cannot mix with GROUP BY"):
            wt.sql(
                "SELECT h, count(*) OVER (PARTITION BY h) AS c FROM wadm "
                "GROUP BY h"
            )
        with pytest.raises(ValueError, match="cannot mix with window"):
            wt.sql(
                "SELECT avg(los) AS a, count(*) OVER (PARTITION BY h) AS c "
                "FROM wadm"
            )
        with pytest.raises(ValueError, match="running MIN"):
            wt.sql("SELECT min(los) OVER (ORDER BY los) AS m FROM wadm")

    def test_star_plus_window_and_string_order(self, wt):
        r = wt.sql(
            "SELECT *, row_number() OVER (ORDER BY h) AS rn FROM wadm"
        )
        # string window ORDER BY ranks by VALUE order (a before b)
        assert set(r.columns) == {"h", "los", "rn"}
        got = dict(zip(r.column("rn"), r.column("h")))
        assert got[1.0] == "a" and got[5.0] == "b"

    def test_window_datetime_minmax_keeps_dtype(self, wt):
        ts = np.array(
            ["2025-01-02T00:00:00", "2025-01-01T00:00:00", "2025-01-03T00:00:00"],
            dtype="datetime64[ns]",
        )
        wt.register_table(
            "wts",
            ht.Table.from_dict(
                {"g": np.array(["u", "u", "v"], object), "ts": ts}
            ),
        )
        r = wt.sql("SELECT max(ts) OVER (PARTITION BY g) AS m FROM wts")
        assert r.column("m").dtype.kind == "M"
        assert r.column("m")[0] == ts[0]
        with pytest.raises(ValueError, match="running SUM needs a numeric"):
            wt.sql("SELECT sum(ts) OVER (ORDER BY ts) AS s FROM wts")

    def test_intersect_all_rejected(self, wt):
        with pytest.raises(ValueError, match="INTERSECT ALL"):
            wt.sql("SELECT h FROM wadm INTERSECT ALL SELECT h FROM wadm")
        with pytest.raises(ValueError, match="EXCEPT ALL"):
            wt.sql("SELECT h FROM wadm EXCEPT ALL SELECT h FROM wadm")

    def test_lag_lead(self, wt):
        r = wt.sql(
            "SELECT h, los, lag(los) OVER (PARTITION BY h ORDER BY los) AS p, "
            "lead(los) OVER (PARTITION BY h ORDER BY los) AS nx, "
            "lag(los, 2) OVER (PARTITION BY h ORDER BY los) AS p2 FROM wadm"
        )
        # rows: a:(2,6,6)  b:(9,1).  sorted a: 2,6,6;  b: 1,9
        by_row = {
            (h, l): (p, nx, p2)
            for h, l, p, nx, p2 in zip(
                r.column("h"), r.column("los"), r.column("p"),
                r.column("nx"), r.column("p2"),
            )
        }
        assert np.isnan(by_row[("a", 2.0)][0])      # no previous
        assert by_row[("b", 9.0)][0] == 1.0          # lag within b
        assert by_row[("b", 1.0)][1] == 9.0          # lead within b
        assert np.isnan(by_row[("b", 9.0)][1])       # no next
        assert by_row[("a", 2.0)][1] == 6.0
        # offset 2 crosses partition start -> NULL
        assert np.isnan(by_row[("a", 2.0)][2]) and np.isnan(by_row[("b", 9.0)][2])

    def test_lag_string_column(self, wt):
        r = wt.sql("SELECT h, lag(h) OVER (ORDER BY los) AS ph FROM wadm")
        # global order by los: 1(b), 2(a), 6(a), 6(a), 9(b)
        got = list(r.column("ph"))
        assert got.count(None) == 1  # only the first row lacks a lag
        with pytest.raises(ValueError, match="needs an OVER"):
            wt.sql("SELECT lag(h) AS x FROM wadm")

    def test_window_edge_guards(self, wt):
        with pytest.raises(ValueError, match="cannot nest inside"):
            wt.sql("SELECT row_number() + 1 AS x FROM wadm")
        with pytest.raises(ValueError, match="cannot mix with window"):
            wt.sql(
                "SELECT sum(los) + 1 AS s, count(*) OVER () AS c FROM wadm"
            )
        # distinct auto-aliases for different lag offsets
        r = wt.sql(
            "SELECT lag(los) OVER (ORDER BY los), "
            "lag(los, 2) OVER (ORDER BY los) FROM wadm"
        )
        assert len(r.columns) == 2

    def test_ntile_first_last_value(self, wt):
        wt.register_table(
            "wv",
            ht.Table.from_dict(
                {
                    "h": np.array(["a"] * 5 + ["b"] * 3, object),
                    "v": np.array([1.0, 2, 3, 4, 5, 10, 20, 30]),
                }
            ),
        )
        r = wt.sql(
            "SELECT ntile(2) OVER (PARTITION BY h ORDER BY v) AS nt, "
            "first_value(v) OVER (PARTITION BY h ORDER BY v) AS fv, "
            "last_value(v) OVER (PARTITION BY h ORDER BY v) AS lv FROM wv"
        )
        # SQL NTILE: first (n mod k) tiles get the extra row
        np.testing.assert_array_equal(r.column("nt"), [1, 1, 1, 2, 2, 1, 1, 2])
        np.testing.assert_allclose(
            r.column("fv"), [1, 1, 1, 1, 1, 10, 10, 10]
        )
        # default-frame LAST_VALUE = current row (no ties here) — the
        # Spark RANGE..CURRENT ROW gotcha, faithfully reproduced
        np.testing.assert_allclose(r.column("lv"), [1, 2, 3, 4, 5, 10, 20, 30])
        # ties: both 6.0 rows in wadm share their block-end value
        r2 = wt.sql(
            "SELECT los, last_value(los) OVER (ORDER BY los) AS lv FROM wadm"
        )
        by = dict(zip(r2.column("los"), r2.column("lv")))
        assert by[6.0] == 6.0 and by[1.0] == 1.0
        with pytest.raises(ValueError, match="NTILE needs a positive"):
            wt.sql("SELECT ntile(0) OVER (ORDER BY los) AS x FROM wadm")

    def test_edge_values_without_order_by(self, wt):
        r = wt.sql(
            "SELECT h, first_value(los) OVER (PARTITION BY h) AS f, "
            "last_value(los) OVER (PARTITION BY h) AS l FROM wadm"
        )
        # whole-partition frame in stable source order: a=(2,6,6), b=(9,1)
        by = {}
        for h, f, l in zip(r.column("h"), r.column("f"), r.column("l")):
            by[h] = (f, l)
        assert by["a"] == (2.0, 6.0) and by["b"] == (9.0, 1.0)


# ------------------------------------------------ percentile aggregates
class TestPercentiles:
    @pytest.fixture
    def pt(self, session):
        session.register_table(
            "pv",
            ht.Table.from_dict(
                {
                    "h": np.array(["a"] * 5 + ["b"] * 4, object),
                    "v": np.array([1.0, 2, 3, 4, 100, 10, 20, np.nan, 30]),
                }
            ),
        )
        return session

    def test_whole_table_median_and_percentile(self, pt):
        r = pt.sql(
            "SELECT median(v) AS m, percentile_approx(v, 0.9) AS p90 FROM pv"
        )
        assert r.column("m")[0] == pytest.approx(7.0)   # (4+10)/2, nan skipped
        assert r.column("p90")[0] == pytest.approx(51.0)

    def test_grouped_percentiles_skip_nulls(self, pt):
        r = pt.sql(
            "SELECT h, median(v) AS m, percentile_approx(v, 0.25, 100) AS q1 "
            "FROM pv GROUP BY h ORDER BY h"
        )
        np.testing.assert_allclose(r.column("m"), [3.0, 20.0])
        np.testing.assert_allclose(r.column("q1"), [2.0, 15.0])

    def test_percentile_over_expression_and_bounds(self, pt):
        r = pt.sql("SELECT median(v * 2) AS m2 FROM pv")
        assert r.column("m2")[0] == pytest.approx(14.0)
        with pytest.raises(ValueError, match="must be in \\[0, 1\\]"):
            pt.sql("SELECT percentile_approx(v, 1.5) AS x FROM pv")

    def test_percentile_guards_and_subquery_naming(self, pt):
        with pytest.raises(ValueError, match="expects a numeric"):
            pt.sql("SELECT median(h) AS m FROM pv")
        with pytest.raises(ValueError, match="only supported in the select"):
            pt.sql("SELECT h, median(v) AS m FROM pv GROUP BY h "
                   "HAVING median(v) > 5")
        # dotted default names survive the subquery boundary intact
        r = pt.sql("SELECT * FROM (SELECT median(v) FROM pv) s")
        assert list(r.columns) == ["percentile(v, 0.5)"]
        # and HAVING via the alias works
        r2 = pt.sql(
            "SELECT h, median(v) AS m FROM pv GROUP BY h HAVING m > 5 "
            "ORDER BY h"
        )
        assert list(r2.column("h")) == ["b"]


# ------------------------------------------------ RIGHT / FULL OUTER JOIN
class TestOuterJoins:
    @pytest.fixture
    def jt(self, session):
        session.register_table(
            "ja",
            ht.Table.from_dict(
                {"k": np.array(["x", "y", "z"], object),
                 "va": np.array([1.0, 2, 3])}
            ),
        )
        session.register_table(
            "jb",
            ht.Table.from_dict(
                {"k": np.array(["y", "z", "w"], object),
                 "vb": np.array([20.0, 30, 40])}
            ),
        )
        return session

    def test_right_join(self, jt):
        r = jt.sql(
            "SELECT a.k, va, vb FROM ja a RIGHT JOIN jb b ON a.k = b.k "
            "ORDER BY vb"
        )
        assert list(r.column("k")) == ["y", "z", None]
        np.testing.assert_allclose(r.column("va"), [2, 3, np.nan])
        np.testing.assert_allclose(r.column("vb"), [20, 30, 40])

    def test_full_outer_join(self, jt):
        r = jt.sql("SELECT va, vb FROM ja FULL OUTER JOIN jb ON ja.k = jb.k")
        assert len(r) == 4
        np.testing.assert_allclose(sorted(r.column("va")[~np.isnan(r.column("va"))]), [1, 2, 3])
        np.testing.assert_allclose(sorted(r.column("vb")[~np.isnan(r.column("vb"))]), [20, 30, 40])
        assert np.isnan(r.column("va")).sum() == 1
        assert np.isnan(r.column("vb")).sum() == 1

    def test_left_outer_synonym_and_null_keys(self, jt):
        r = jt.sql("SELECT va, vb FROM ja LEFT OUTER JOIN jb ON ja.k = jb.k")
        np.testing.assert_allclose(r.column("va"), [1, 2, 3])
        # null keys never match in outer joins either
        jt.register_table(
            "jn",
            ht.Table.from_dict(
                {"k": np.array([None, "y"], object), "vn": np.array([7.0, 8])}
            ),
        )
        r2 = jt.sql("SELECT vn, vb FROM jn FULL OUTER JOIN jb ON jn.k = jb.k")
        # null-key left row survives unmatched; y matches; z+w unmatched
        assert len(r2) == 4
        m = ~np.isnan(r2.column("vn")) & ~np.isnan(r2.column("vb"))
        assert m.sum() == 1  # only the y row pairs

    def test_right_full_stay_legal_identifiers(self, jt):
        # right/full/outer are NON-reserved (Spark parity)
        jt.register_table(
            "idt",
            ht.Table.from_dict(
                {"full": np.array([1.0, 2.0]), "outer": np.array([3.0, 4.0])}
            ),
        )
        r = jt.sql("SELECT full, outer FROM idt WHERE full > 1")
        np.testing.assert_allclose(r.column("full"), [2.0])
        # and FROM t RIGHT JOIN still parses as a join, not alias 'right'
        r2 = jt.sql("SELECT vb FROM ja RIGHT OUTER JOIN jb ON ja.k = jb.k")
        assert len(r2) == 3

    def test_cross_join(self, jt):
        r = jt.sql(
            "SELECT ja.k, jb.k AS k2 FROM ja CROSS JOIN jb ORDER BY ja.k"
        )
        assert len(r) == 9
        assert list(r.column("k"))[:3] == ["x", "x", "x"]
        # 'cross' stays a legal identifier
        jt.register_table(
            "ct", ht.Table.from_dict({"cross": np.array([1.0, 2.0])})
        )
        np.testing.assert_allclose(
            jt.sql("SELECT cross FROM ct").column("cross"), [1, 2]
        )

    def test_outer_join_after_derived_table(self, jt):
        # RIGHT after a FROM-subquery must be a join, not the alias
        # (an unaliased subquery then raises, pointing at the real fix)
        with pytest.raises(ValueError, match="needs an alias"):
            jt.sql("SELECT vb FROM (SELECT k FROM ja) RIGHT JOIN jb "
                   "ON k = jb.k")
        r = jt.sql(
            "SELECT vb FROM (SELECT k FROM ja) s RIGHT JOIN jb "
            "ON s.k = jb.k ORDER BY vb"
        )
        np.testing.assert_allclose(r.column("vb"), [20, 30, 40])


class TestDateTimeFunctions:
    """date_trunc / unix_timestamp / datediff — the timestamped-events
    scalars (reference window extraction, mllearnforhospitalnetwork.py:
    123-128)."""

    @pytest.fixture
    def tt(self):
        s = ht.Session.builder.app_name("sql-dt-test").get_or_create()
        times = np.array(
            ["2025-03-31T22:15:42", "2025-04-01T01:02:03",
             "2025-06-15T00:00:00", "NaT"],
            dtype="datetime64[ns]",
        )
        s.register_table(
            "ev",
            ht.Table.from_dict(
                {
                    "event_time": times,
                    "v": np.array([1.0, 2.0, 3.0, 4.0]),
                }
            ),
        )
        yield s
        s.stop()

    def test_date_trunc_units(self, tt):
        r = tt.sql(
            "SELECT date_trunc('year', event_time) AS y, "
            "date_trunc('quarter', event_time) AS q, "
            "date_trunc('month', event_time) AS m, "
            "date_trunc('week', event_time) AS w, "
            "date_trunc('day', event_time) AS d, "
            "date_trunc('hour', event_time) AS h, "
            "date_trunc('minute', event_time) AS mi FROM ev"
        )
        def col(name):
            return r.column(name).astype("datetime64[s]")
        np.testing.assert_array_equal(
            col("y")[:2], np.array(["2025-01-01T00:00:00"] * 2, "datetime64[s]")
        )
        np.testing.assert_array_equal(
            col("q")[:3],
            np.array(["2025-01-01", "2025-04-01", "2025-04-01"], "datetime64[s]"),
        )
        np.testing.assert_array_equal(
            col("m")[:2],
            np.array(["2025-03-01", "2025-04-01"], "datetime64[s]"),
        )
        # Spark weeks start Monday: 2025-03-31 IS a Monday; 2025-04-01
        # (Tue) truncates back to it; 2025-06-15 is a Sunday -> 06-09
        np.testing.assert_array_equal(
            col("w")[:3],
            np.array(["2025-03-31", "2025-03-31", "2025-06-09"], "datetime64[s]"),
        )
        np.testing.assert_array_equal(
            col("h")[0], np.datetime64("2025-03-31T22:00:00", "s")
        )
        np.testing.assert_array_equal(
            col("mi")[0], np.datetime64("2025-03-31T22:15:00", "s")
        )
        for name in ("y", "q", "m", "w", "d", "h", "mi"):
            assert np.isnat(r.column(name)[3]), name

    def test_date_trunc_bad_unit_and_nonliteral(self, tt):
        with pytest.raises(ValueError, match="DATE_TRUNC"):
            tt.sql("SELECT date_trunc('fortnight', event_time) AS x FROM ev")
        with pytest.raises(ValueError, match="DATE_TRUNC"):
            tt.sql("SELECT date_trunc(v, event_time) AS x FROM ev")

    def test_unix_timestamp(self, tt):
        r = tt.sql("SELECT unix_timestamp(event_time) AS ut FROM ev")
        ut = r.column("ut")
        expect = np.array(
            ["2025-03-31T22:15:42", "2025-04-01T01:02:03"], "datetime64[s]"
        ).astype(np.int64)
        np.testing.assert_allclose(ut[:2], expect)
        assert np.isnan(ut[3])
        # non-timestamp argument is a labeled analysis error
        with pytest.raises(ValueError, match="UNIX_TIMESTAMP"):
            tt.sql("SELECT unix_timestamp(v) AS x FROM ev")

    def test_datediff_col_vs_literal_and_null(self, tt):
        r = tt.sql(
            "SELECT datediff(event_time, '2025-03-30') AS dd, "
            "datediff('2025-04-10', event_time) AS rev FROM ev"
        )
        np.testing.assert_allclose(r.column("dd")[:3], [1.0, 2.0, 77.0])
        np.testing.assert_allclose(r.column("rev")[:3], [10.0, 9.0, -66.0])
        assert np.isnan(r.column("dd")[3])

    def test_datediff_in_arithmetic(self, tt):
        # scalar fns compose with arithmetic in the select list
        r = tt.sql(
            "SELECT v * datediff(event_time, '2025-03-30') AS scaled FROM ev"
        )
        np.testing.assert_allclose(r.column("scaled")[:2], [1.0, 4.0])
