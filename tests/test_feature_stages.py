"""New feature stages + ml.stat: MinMaxScaler, Bucketizer, OneHotEncoder,
Imputer, PCA (sklearn/scipy parity), Correlation, Summarizer — plus
artifact round-trips and Pipeline composition."""

import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io.model_io import (
    load_model,
    save_model,
)


# ------------------------------------------------------------ MinMax
@pytest.mark.fast
def test_minmax_matches_sklearn(rng, mesh8):
    sk = pytest.importorskip("sklearn.preprocessing")
    x = rng.normal(size=(500, 4)).astype(np.float32) * [1, 10, 0.1, 5]
    ours = ht.MinMaxScaler().fit(x).transform(x)
    ref = sk.MinMaxScaler().fit_transform(x)
    np.testing.assert_allclose(ours, ref, atol=1e-6)
    # custom range + device path
    ds = ht.device_dataset(x, mesh=mesh8)
    m = ht.MinMaxScaler(min_out=-1.0, max_out=1.0).fit(ds)
    out = m.transform(ds)
    ref2 = sk.MinMaxScaler(feature_range=(-1, 1)).fit_transform(x)
    got = np.asarray(out.x)[: len(x)]
    np.testing.assert_allclose(got, ref2, atol=1e-5)


def test_minmax_constant_column_midpoint(mesh8):
    x = np.c_[np.ones(64), np.arange(64.0)].astype(np.float32)
    out = ht.MinMaxScaler().fit(x).transform(x)
    np.testing.assert_allclose(out[:, 0], 0.5)  # Spark midpoint rule
    assert out[:, 1].min() == 0.0 and out[:, 1].max() == 1.0


# ------------------------------------------------------------ Bucketizer
def test_bucketizer(hospital_table):
    b = ht.Bucketizer(
        splits=[-np.inf, 2.0, 5.0, np.inf],
        input_col="length_of_stay",
        output_col="los_bucket",
    )
    out = b.transform(hospital_table)
    los = hospital_table.column("length_of_stay")
    expect = np.searchsorted([2.0, 5.0], los, side="right")
    np.testing.assert_array_equal(out.column("los_bucket"), expect)


@pytest.mark.fast
def test_bucketizer_validation_and_invalid_handling(hospital_table):
    with pytest.raises(ValueError, match="strictly increasing"):
        ht.Bucketizer([0.0, 0.0, 1.0], "a", "b")
    with pytest.raises(ValueError, match=">=3"):
        ht.Bucketizer([0.0, 1.0], "a", "b")
    bounded = ht.Bucketizer([0.0, 4.0, 6.0], "length_of_stay", "bk")
    with pytest.raises(ValueError, match="outside the split range"):
        bounded.transform(hospital_table)  # LOS exceeds 6 somewhere
    # Spark semantics: handleInvalid covers NaN ONLY; out-of-range raises
    # under EVERY mode (cover open ranges with ±inf splits instead)
    keep_oob = ht.Bucketizer([0.0, 4.0, 6.0], "length_of_stay", "bk", "keep")
    with pytest.raises(ValueError, match="outside the split range"):
        keep_oob.transform(hospital_table)
    v = np.array([0.5, np.nan, 1.5, np.nan])
    tab_nan = ht.Table.from_dict({"v": v}, ht.Schema([("v", "float")]))
    with pytest.raises(ValueError, match="NaN"):
        ht.Bucketizer([0.0, 1.0, 2.0], "v", "bk").transform(tab_nan)
    keep = ht.Bucketizer([0.0, 1.0, 2.0], "v", "bk", "keep").transform(tab_nan)
    np.testing.assert_array_equal(keep.column("bk"), [0, 2, 1, 2])  # extra bucket
    skip = ht.Bucketizer([0.0, 1.0, 2.0], "v", "bk", "skip").transform(tab_nan)
    assert len(skip) == 2 and skip.column("bk").max() <= 1
    inf_splits = ht.Bucketizer(
        [-np.inf, 4.0, np.inf], "length_of_stay", "bk"
    ).transform(hospital_table)
    assert inf_splits.column("bk").max() == 1  # open range, no error
    # top boundary inclusive
    b2 = ht.Bucketizer([0.0, 1.0, 2.0], "v", "bk")
    tab = ht.Table.from_dict({"v": np.array([0.0, 1.0, 2.0])},
                             ht.Schema([("v", "float")]))
    np.testing.assert_array_equal(b2.transform(tab).column("bk"), [0, 1, 1])


# ------------------------------------------------------------ OneHot
def test_one_hot_encoder(hospital_table):
    idx = ht.StringIndexer("hospital_id", "hid").fit(hospital_table)
    tab = idx.transform(hospital_table)
    enc = ht.OneHotEncoder(["hid"]).fit(tab)
    out = enc.transform(tab)
    k = len(idx.labels)
    names = enc.output_names(0)
    assert len(names) == k - 1  # drop_last
    codes = tab.column("hid")
    for i, nm in enumerate(names):
        np.testing.assert_array_equal(out.column(nm), (codes == i).astype(int))
    # keep-all variant + assembler composition
    enc2 = ht.OneHotEncoder(["hid"], drop_last=False).fit(tab)
    out2 = enc2.transform(tab)
    mat = ht.VectorAssembler(enc2.output_names(0)).transform_matrix(out2)
    np.testing.assert_allclose(mat.sum(axis=1), 1.0)


def test_one_hot_invalid_handling(hospital_table):
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.features import (
        OneHotEncoderModel,
    )

    idx = ht.StringIndexer("hospital_id", "hid").fit(hospital_table)
    tab = idx.transform(hospital_table)
    small = OneHotEncoderModel(("hid",), ("v",), (2,), True, "error")
    with pytest.raises(ValueError, match="outside"):
        small.transform(tab)

    rows_bad = tab.column("hid") >= 2
    # Spark keep semantics: the invalid bucket is an EXTRA last category.
    # dropLast=False → invalid rows get their own indicator column...
    keep_all = OneHotEncoderModel(("hid",), ("v",), (2,), False, "keep")
    out = keep_all.transform(tab)
    assert keep_all.output_names(0) == ["v_0", "v_1", "v_2"]
    np.testing.assert_array_equal(out.column("v_2"), rows_bad.astype(int))
    # ...and dropLast=True drops the invalid bucket, so every VALID
    # category keeps its indicator (code 1 stays distinguishable) while
    # invalid rows encode all-zeros
    keep_drop = OneHotEncoderModel(("hid",), ("v",), (2,), True, "keep")
    out2 = keep_drop.transform(tab)
    assert keep_drop.output_names(0) == ["v_0", "v_1"]
    codes = tab.column("hid")
    np.testing.assert_array_equal(out2.column("v_1"), (codes == 1).astype(int))
    assert (out2.column("v_0")[rows_bad] == 0).all()
    assert (out2.column("v_1")[rows_bad] == 0).all()

    with pytest.raises(ValueError, match="no 'skip'"):
        ht.OneHotEncoder(["hid"], handle_invalid="skip")


# ------------------------------------------------------------ Imputer
def test_imputer_strategies():
    v = np.array([1.0, 2.0, np.nan, 4.0, np.nan, 2.0])
    tab = ht.Table.from_dict({"v": v}, ht.Schema([("v", "float")]))
    mean = ht.Imputer(["v"]).fit(tab).transform(tab).column("v")
    np.testing.assert_allclose(mean[[2, 4]], np.nanmean(v))
    med = ht.Imputer(["v"], strategy="median").fit(tab).transform(tab).column("v")
    np.testing.assert_allclose(med[[2, 4]], 2.0)
    mode = ht.Imputer(["v"], strategy="mode").fit(tab).transform(tab).column("v")
    np.testing.assert_allclose(mode[[2, 4]], 2.0)
    # sentinel missing value + separate output col
    t2 = ht.Table.from_dict({"v": np.array([1.0, -999.0, 3.0])},
                            ht.Schema([("v", "float")]))
    m = ht.Imputer(["v"], ["v_f"], missing_value=-999.0).fit(t2)
    out = m.transform(t2)
    np.testing.assert_allclose(out.column("v_f"), [1.0, 2.0, 3.0])
    np.testing.assert_allclose(out.column("v"), [1.0, -999.0, 3.0])
    with pytest.raises(ValueError, match="strategy"):
        ht.Imputer(["v"], strategy="zero").fit(t2)


# ------------------------------------------------------------ PCA
def test_pca_matches_sklearn(rng, mesh8):
    skd = pytest.importorskip("sklearn.decomposition")
    x = (rng.normal(size=(600, 5)) @ rng.normal(size=(5, 5))).astype(np.float32)
    ours = ht.PCA(k=3).fit(x)
    ref = skd.PCA(n_components=3).fit(np.asarray(x, dtype=np.float64))
    # align sign per component before comparing
    for j in range(3):
        a = ours.components[:, j]
        b = ref.components_[j]
        if np.dot(a, b) < 0:
            b = -b
        np.testing.assert_allclose(a, b, atol=2e-4)
    np.testing.assert_allclose(
        ours.explained_variance, ref.explained_variance_, rtol=1e-3
    )
    # device path equals host path
    ds = ht.device_dataset(x, mesh=mesh8)
    m2 = ht.PCA(k=3).fit(ds)
    np.testing.assert_allclose(m2.components, ours.components, atol=1e-3)
    proj = m2.transform(ds)
    np.testing.assert_allclose(
        np.asarray(proj.x)[: len(x)],
        ours.transform(np.asarray(x, dtype=np.float64)),
        atol=2e-3,
    )
    with pytest.raises(ValueError, match="k must be"):
        ht.PCA(k=9).fit(x)


# ------------------------------------------------------------ stat
def test_correlation_pearson_spearman(rng, mesh8):
    stats = pytest.importorskip("scipy.stats")
    x = rng.normal(size=(400, 4))
    x[:, 1] = 0.7 * x[:, 0] + 0.3 * x[:, 1]
    r = ht.Correlation.corr(x.astype(np.float32), mesh=mesh8)
    np.testing.assert_allclose(r, np.corrcoef(x, rowvar=False), atol=1e-4)
    rs = ht.Correlation.corr(x, method="spearman")
    ref, _ = stats.spearmanr(x)
    np.testing.assert_allclose(rs, ref, atol=1e-10)
    with pytest.raises(ValueError, match="method"):
        ht.Correlation.corr(x, method="kendall")


def test_correlation_constant_column_nan(mesh8):
    x = np.c_[np.ones(64), np.arange(64.0)].astype(np.float32)
    r = ht.Correlation.corr(x, mesh=mesh8)
    assert np.isnan(r[0, 1]) and np.isnan(r[1, 0])
    assert r[0, 0] == 1.0 and r[1, 1] == 1.0


def test_summarizer(rng, mesh8):
    x = rng.normal(size=(300, 3)).astype(np.float32)
    x[5, 0] = 0.0
    w = rng.uniform(0.5, 2.0, size=300)
    s = ht.Summarizer.summary(ht.device_dataset(x, mesh=mesh8, weights=w), mesh=mesh8)
    wsum = w.sum()
    mean = (x * w[:, None]).sum(0) / wsum
    np.testing.assert_allclose(s.mean, mean, rtol=1e-4)
    biased = (w[:, None] * (x - mean) ** 2).sum(0) / wsum
    np.testing.assert_allclose(
        s.variance, biased * wsum / (wsum - 1), rtol=1e-3
    )
    np.testing.assert_allclose(s.min, x.min(0), rtol=1e-6)
    np.testing.assert_allclose(s.max, x.max(0), rtol=1e-6)
    np.testing.assert_allclose(s.norm_l1, (np.abs(x) * w[:, None]).sum(0), rtol=1e-4)
    np.testing.assert_allclose(
        s.norm_l2, np.sqrt((x * x * w[:, None]).sum(0)), rtol=1e-4
    )
    assert s.count == 300
    np.testing.assert_allclose(s.weight_sum, wsum, rtol=1e-5)


# ------------------------------------------------------------ row transforms
def test_normalizer_matches_sklearn(rng):
    sk = pytest.importorskip("sklearn.preprocessing")
    x = rng.normal(size=(200, 4)).astype(np.float32)
    for p, norm in ((2.0, "l2"), (1.0, "l1"), (np.inf, "max")):
        ours = ht.Normalizer(p=p).transform(x)
        ref = sk.normalize(x, norm=norm)
        np.testing.assert_allclose(ours, ref, atol=1e-6)
    # zero rows stay zero, no NaN
    z = np.zeros((3, 4), dtype=np.float32)
    assert not np.isnan(ht.Normalizer().transform(z)).any()
    with pytest.raises(ValueError, match="p must be"):
        ht.Normalizer(p=0.5)


def test_polynomial_expansion_matches_sklearn(rng):
    sk = pytest.importorskip("sklearn.preprocessing")
    x = rng.normal(size=(50, 3)).astype(np.float64)
    pe = ht.PolynomialExpansion(degree=3)
    ours = pe.transform(x)
    ref = sk.PolynomialFeatures(degree=3, include_bias=False).fit_transform(x)
    assert ours.shape[1] == pe.num_outputs(3) == ref.shape[1]
    np.testing.assert_allclose(ours, ref, rtol=1e-10)
    with pytest.raises(ValueError, match="degree"):
        ht.PolynomialExpansion(degree=9)


def test_index_to_string_roundtrip(hospital_table):
    idx = ht.StringIndexer("hospital_id", "hid").fit(hospital_table)
    tab = idx.transform(hospital_table)
    back = ht.IndexToString("hid", "hospital_back", idx.labels).transform(tab)
    assert (back.column("hospital_back") == hospital_table.column("hospital_id")).all()
    bad = ht.IndexToString("hid", "x", idx.labels[:2])
    with pytest.raises(ValueError, match="no label"):
        bad.transform(tab)


def test_chi_square_test(rng):
    sps = pytest.importorskip("scipy.stats")
    n = 2000
    y = rng.integers(0, 2, size=n)
    dependent = (y + rng.integers(0, 2, size=n) * (rng.random(n) < 0.2)).clip(0, 1)
    independent = rng.integers(0, 3, size=n)
    x = np.c_[dependent, independent].astype(np.float64)
    res = ht.ChiSquareTest.test(x, y)
    assert res.p_values[0] < 1e-10       # strongly dependent
    assert res.p_values[1] > 0.01        # independent
    assert res.degrees_of_freedom.tolist() == [1, 2]
    # cross-check statistic 0 against scipy's contingency chi2
    table = np.zeros((2, 2))
    np.add.at(table, (dependent.astype(int), y), 1.0)
    chi2_ref = sps.chi2_contingency(table, correction=False).statistic
    np.testing.assert_allclose(res.statistics[0], chi2_ref, rtol=1e-10)


# ----------------------------------------------- persistence + pipelines
def test_new_stage_artifacts_roundtrip(hospital_table, rng, tmp_path):
    x = rng.normal(size=(100, 4)).astype(np.float32)
    idx = ht.StringIndexer("hospital_id", "hid").fit(hospital_table)
    tab = idx.transform(hospital_table)
    stages = [
        ht.MinMaxScaler(min_out=-2.0).fit(x),
        ht.Bucketizer([0.0, 1.0, 2.0], "length_of_stay", "bk", "keep"),
        ht.OneHotEncoder(["hid"]).fit(tab),
        ht.Imputer(["length_of_stay"]).fit(hospital_table),
        ht.PCA(k=2).fit(x),
    ]
    for i, st in enumerate(stages):
        name, meta, arrays = st._artifacts()
        p = os.path.join(tmp_path, f"s{i}")
        save_model(p, name, meta, arrays)
        back = load_model(p)
        assert type(back) is type(st)
    pca_back = load_model(os.path.join(tmp_path, "s4"))
    np.testing.assert_allclose(pca_back.components, stages[4].components)


def test_new_stages_compose_in_pipeline(hospital_table, mesh8, tmp_path):
    """Imputer/Bucketizer/OneHot run as Table stages, MinMax/PCA as
    feature-matrix stages, all inside one fitted, persisted Pipeline."""
    pipe = ht.Pipeline(
        [
            ht.Imputer(["length_of_stay"]),
            ht.StringIndexer("hospital_id", "hid"),
            ht.OneHotEncoder(["hid"]),
            ht.VectorAssembler(ht.FEATURE_COLS),
            ht.MinMaxScaler(),
            ht.PCA(k=3),
            ht.LinearRegression(),
        ]
    )
    pm = pipe.fit(hospital_table, mesh=mesh8)
    pred = pm.transform(hospital_table, mesh=mesh8)
    rmse = ht.RegressionEvaluator("rmse").evaluate(pred)
    assert np.isfinite(rmse)
    p = os.path.join(tmp_path, "pm")
    pm.save(p)
    back = ht.load_model(p)
    a, _ = pm.transform(hospital_table, mesh=mesh8).to_numpy()
    b, _ = back.transform(hospital_table, mesh=mesh8).to_numpy()
    np.testing.assert_allclose(a, b, rtol=1e-6)


# ---- round 4: RobustScaler / MaxAbsScaler / vector ops / selector / SQL ----


class TestMaxAbsScaler:
    @pytest.mark.fast
    def test_matches_sklearn(self, rng, mesh8):
        from sklearn.preprocessing import MaxAbsScaler as SK

        x = (rng.normal(size=(500, 4)) * [1, 10, 0.1, 5]).astype(np.float32)
        ours = ht.MaxAbsScaler().fit(x)
        np.testing.assert_allclose(
            np.asarray(ours.transform(x)), SK().fit_transform(x), rtol=1e-5
        )

    def test_device_dataset_and_roundtrip(self, rng, mesh8, tmp_path):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import (
            load_model, save_model,
        )

        x = rng.normal(size=(256, 3)).astype(np.float32)
        ds = ht.device_dataset(x, mesh=mesh8)
        m = ht.MaxAbsScaler().fit(ds)
        out = m.transform(ds)
        assert float(np.abs(np.asarray(out.x)).max()) <= 1.0 + 1e-6
        save_model(str(tmp_path / "mas"), *m._artifacts())
        back = load_model(str(tmp_path / "mas"))
        np.testing.assert_allclose(back.max_abs, m.max_abs)

    def test_zero_column_stays_zero(self, mesh8):
        x = np.zeros((32, 2), np.float32)
        x[:, 1] = 3.0
        out = np.asarray(ht.MaxAbsScaler().fit(x).transform(x))
        assert np.all(out[:, 0] == 0) and np.all(out[:, 1] == 1.0)


class TestRobustScaler:
    def test_matches_sklearn(self, rng, mesh8):
        from sklearn.preprocessing import RobustScaler as SK

        x = rng.normal(size=(4000, 3)).astype(np.float64)
        x[:50] *= 50  # outliers — the point of the robust statistics
        ours = ht.RobustScaler(with_centering=True).fit(x)
        ref = SK(with_centering=True).fit(x)
        np.testing.assert_allclose(ours.median, ref.center_, rtol=5e-2, atol=5e-2)
        np.testing.assert_allclose(ours.iqr, ref.scale_, rtol=5e-2)

    def test_sharded_fit(self, rng, mesh8):
        x = rng.normal(loc=5.0, size=(2048, 2)).astype(np.float32)
        ds = ht.device_dataset(x, mesh=mesh8)
        m = ht.RobustScaler(with_centering=True).fit(ds)
        out = np.asarray(m.transform(x))
        assert abs(np.median(out[:, 0])) < 0.05   # centered
        q = np.quantile(out[:, 0], [0.25, 0.75])
        np.testing.assert_allclose(q[1] - q[0], 1.0, atol=0.1)  # unit IQR

    def test_validation(self):
        with pytest.raises(ValueError, match="lower"):
            ht.RobustScaler(lower=0.9, upper=0.1)
        with pytest.raises(ValueError, match="empty"):
            ht.RobustScaler().fit(np.empty((0, 2), np.float32))


class TestVectorOps:
    @pytest.mark.fast
    def test_slicer_product_interaction(self, rng, mesh8):
        x = rng.normal(size=(64, 4)).astype(np.float32)
        sl = ht.VectorSlicer(indices=(2, 0))
        np.testing.assert_array_equal(np.asarray(sl.transform(x)), x[:, [2, 0]])
        ep = ht.ElementwiseProduct(scaling_vec=(2.0, 0.0, 1.0, -1.0))
        np.testing.assert_allclose(
            np.asarray(ep.transform(x)), x * np.array([2.0, 0.0, 1.0, -1.0])
        )
        it = ht.Interaction(left=(0, 1), right=(2, 3))
        out = np.asarray(it.transform(x))
        assert out.shape == (64, 4)
        np.testing.assert_allclose(out[:, 0], x[:, 0] * x[:, 2], rtol=1e-6)
        np.testing.assert_allclose(out[:, 3], x[:, 1] * x[:, 3], rtol=1e-6)

    def test_validation_and_errors(self, rng):
        x = np.ones((8, 3), np.float32)
        with pytest.raises(ValueError, match="index"):
            ht.VectorSlicer(indices=())
        with pytest.raises(ValueError, match="duplicate"):
            ht.VectorSlicer(indices=(1, 1))
        with pytest.raises(ValueError, match="out of range"):
            ht.VectorSlicer(indices=(5,)).transform(x)
        with pytest.raises(ValueError, match="entries"):
            ht.ElementwiseProduct(scaling_vec=(1.0,)).transform(x)

    def test_device_dataset_pass_through(self, rng, mesh8):
        x = rng.normal(size=(128, 4)).astype(np.float32)
        ds = ht.device_dataset(x, mesh=mesh8)
        out = ht.VectorSlicer(indices=(1, 3)).transform(ds)
        np.testing.assert_allclose(np.asarray(out.x), x[:, [1, 3]], rtol=1e-6)


class TestVarianceThresholdSelector:
    def test_drops_low_variance(self, rng, mesh8):
        n = 1024
        x = np.stack(
            [
                rng.normal(0, 2.0, n),          # high variance: keep
                np.full(n, 7.0),                 # constant: drop
                rng.normal(0, 0.01, n),          # tiny variance: drop at 0.1
                rng.normal(0, 1.0, n),           # keep
            ],
            axis=1,
        ).astype(np.float32)
        m = ht.VarianceThresholdSelector(variance_threshold=0.1).fit(
            ht.device_dataset(x, mesh=mesh8)
        )
        assert m.selected == (0, 3)
        np.testing.assert_array_equal(
            np.asarray(m.transform(x)), x[:, [0, 3]]
        )
        # default 0 keeps everything non-constant
        m0 = ht.VarianceThresholdSelector().fit(x)
        assert m0.selected == (0, 2, 3)


class TestSQLTransformer:
    def test_statement_runs_against_this(self, mesh8):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

        t = Table.from_dict(
            {
                "hospital_id": np.array(["A", "B", "A"], object),
                "los": np.array([2.0, 8.0, 4.0]),
            }
        )
        st = ht.SQLTransformer(
            statement="SELECT hospital_id, AVG(los) AS a FROM __THIS__ "
            "GROUP BY hospital_id ORDER BY hospital_id"
        )
        out = st.transform(t)
        np.testing.assert_allclose(out.column("a"), [3.0, 8.0])

    def test_join_against_extra_table(self, mesh8):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

        t = Table.from_dict(
            {"hospital_id": np.array(["A", "B"], object), "los": np.array([2.0, 8.0])}
        )
        meta = Table.from_dict(
            {"hospital_id": np.array(["A", "B"], object),
             "name": np.array(["General", "Mercy"], object)}
        )
        st = ht.SQLTransformer(
            statement="SELECT m.name, e.los FROM __THIS__ e "
            "JOIN meta m ON e.hospital_id = m.hospital_id",
            tables={"meta": meta},
        )
        out = st.transform(t)
        assert list(out.column("name")) == ["General", "Mercy"]

    def test_validation(self, mesh8, tmp_path):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import (
            load_model, save_model,
        )

        with pytest.raises(ValueError, match="__THIS__"):
            ht.SQLTransformer(statement="SELECT * FROM events")
        st = ht.SQLTransformer(statement="SELECT * FROM __THIS__ LIMIT 1")
        with pytest.raises(TypeError, match="Table"):
            st.transform(np.ones((3, 2)))
        save_model(str(tmp_path / "sqlt"), *st._artifacts())
        assert load_model(str(tmp_path / "sqlt")).statement == st.statement


def test_round4_stages_compose_in_pipeline(rng, mesh8, tmp_path):
    """The new stages are first-class Pipeline citizens (fit/transform +
    persistence through the composite saver)."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

    n = 512
    t = Table.from_dict(
        {
            "a": rng.normal(0, 3, n).astype(np.float32),
            "b": rng.normal(5, 1, n).astype(np.float32),
            "c": np.full(n, 2.0, np.float32),        # constant → dropped
            "length_of_stay": rng.normal(4, 1, n).astype(np.float32),
        }
    )
    pipe = ht.Pipeline(
        [
            ht.VectorAssembler(["a", "b", "c"]),
            ht.VarianceThresholdSelector(variance_threshold=0.01),
            ht.RobustScaler(with_centering=True),
            ht.LinearRegression(),
        ]
    )
    pm = pipe.fit(t, mesh=mesh8)
    preds = pm.transform(t, mesh=mesh8)
    assert np.isfinite(np.asarray(preds.prediction)).all()
    pm.write().overwrite().save(str(tmp_path / "p4"))
    back = ht.load_model(str(tmp_path / "p4"))
    np.testing.assert_allclose(
        np.asarray(back.transform(t, mesh=mesh8).prediction),
        np.asarray(preds.prediction),
        rtol=1e-6,
    )


def test_round4_review_fixes(rng, mesh8):
    """Regression coverage for the review findings on the new stages."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

    n = 64
    t = Table.from_dict(
        {
            "a": rng.normal(size=n).astype(np.float32),
            "b": rng.normal(size=n).astype(np.float32),
            "c": rng.normal(size=n).astype(np.float32),
        }
    )
    asm = ht.VectorAssembler(["a", "b", "c"]).transform(t)
    # sliced AssembledTable keeps consistent feature_cols
    sl = ht.VectorSlicer(indices=(2, 0)).transform(asm)
    assert sl.feature_cols == ("c", "a")
    assert sl.features.shape[1] == 2
    it = ht.Interaction(left=(0,), right=(1, 2)).transform(asm)
    assert it.feature_cols == ("a*b", "a*c")
    assert it.features.shape[1] == 2
    # negative Interaction indices raise instead of wrapping
    with pytest.raises(ValueError, match="negative"):
        ht.Interaction(left=(-1,), right=(0,))
    # VarianceThresholdSelector transform accepts what fit accepts
    x = rng.normal(size=(128, 3)).astype(np.float32)
    ds = ht.device_dataset(x, mesh=mesh8)
    m = ht.VarianceThresholdSelector().fit(ds)
    out = m.transform(ds)
    np.testing.assert_allclose(
        np.asarray(out.x), np.asarray(ds.x)[:, list(m.selected)], rtol=1e-6
    )
    # MaxAbsScaler empty fit raises (not a sentinel statistic)
    with pytest.raises(ValueError, match="empty"):
        ht.MaxAbsScaler().fit(
            ht.device_dataset(np.ones((8, 2), np.float32),
                              weights=np.zeros(8, np.float32), mesh=mesh8)
        )
    # SQLTransformer with extra tables refuses persistence
    st = ht.SQLTransformer(
        statement="SELECT * FROM __THIS__ e JOIN m x ON e.a = x.a",
        tables={"m": t},
    )
    with pytest.raises(ValueError, match="persist"):
        st._artifacts()
