"""New feature stages + ml.stat: MinMaxScaler, Bucketizer, OneHotEncoder,
Imputer, PCA (sklearn/scipy parity), Correlation, Summarizer — plus
artifact round-trips and Pipeline composition."""

import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io.model_io import (
    load_model,
    save_model,
)


# ------------------------------------------------------------ MinMax
@pytest.mark.fast
def test_minmax_matches_sklearn(rng, mesh8):
    sk = pytest.importorskip("sklearn.preprocessing")
    x = rng.normal(size=(500, 4)).astype(np.float32) * [1, 10, 0.1, 5]
    ours = ht.MinMaxScaler().fit(x).transform(x)
    ref = sk.MinMaxScaler().fit_transform(x)
    np.testing.assert_allclose(ours, ref, atol=1e-6)
    # custom range + device path
    ds = ht.device_dataset(x, mesh=mesh8)
    m = ht.MinMaxScaler(min_out=-1.0, max_out=1.0).fit(ds)
    out = m.transform(ds)
    ref2 = sk.MinMaxScaler(feature_range=(-1, 1)).fit_transform(x)
    got = np.asarray(out.x)[: len(x)]
    np.testing.assert_allclose(got, ref2, atol=1e-5)


def test_minmax_constant_column_midpoint(mesh8):
    x = np.c_[np.ones(64), np.arange(64.0)].astype(np.float32)
    out = ht.MinMaxScaler().fit(x).transform(x)
    np.testing.assert_allclose(out[:, 0], 0.5)  # Spark midpoint rule
    assert out[:, 1].min() == 0.0 and out[:, 1].max() == 1.0


# ------------------------------------------------------------ Bucketizer
def test_bucketizer(hospital_table):
    b = ht.Bucketizer(
        splits=[-np.inf, 2.0, 5.0, np.inf],
        input_col="length_of_stay",
        output_col="los_bucket",
    )
    out = b.transform(hospital_table)
    los = hospital_table.column("length_of_stay")
    expect = np.searchsorted([2.0, 5.0], los, side="right")
    np.testing.assert_array_equal(out.column("los_bucket"), expect)


@pytest.mark.fast
def test_bucketizer_validation_and_invalid_handling(hospital_table):
    with pytest.raises(ValueError, match="strictly increasing"):
        ht.Bucketizer([0.0, 0.0, 1.0], "a", "b")
    with pytest.raises(ValueError, match=">=3"):
        ht.Bucketizer([0.0, 1.0], "a", "b")
    bounded = ht.Bucketizer([0.0, 4.0, 6.0], "length_of_stay", "bk")
    with pytest.raises(ValueError, match="outside the split range"):
        bounded.transform(hospital_table)  # LOS exceeds 6 somewhere
    # Spark semantics: handleInvalid covers NaN ONLY; out-of-range raises
    # under EVERY mode (cover open ranges with ±inf splits instead)
    keep_oob = ht.Bucketizer([0.0, 4.0, 6.0], "length_of_stay", "bk", "keep")
    with pytest.raises(ValueError, match="outside the split range"):
        keep_oob.transform(hospital_table)
    v = np.array([0.5, np.nan, 1.5, np.nan])
    tab_nan = ht.Table.from_dict({"v": v}, ht.Schema([("v", "float")]))
    with pytest.raises(ValueError, match="NaN"):
        ht.Bucketizer([0.0, 1.0, 2.0], "v", "bk").transform(tab_nan)
    keep = ht.Bucketizer([0.0, 1.0, 2.0], "v", "bk", "keep").transform(tab_nan)
    np.testing.assert_array_equal(keep.column("bk"), [0, 2, 1, 2])  # extra bucket
    skip = ht.Bucketizer([0.0, 1.0, 2.0], "v", "bk", "skip").transform(tab_nan)
    assert len(skip) == 2 and skip.column("bk").max() <= 1
    inf_splits = ht.Bucketizer(
        [-np.inf, 4.0, np.inf], "length_of_stay", "bk"
    ).transform(hospital_table)
    assert inf_splits.column("bk").max() == 1  # open range, no error
    # top boundary inclusive
    b2 = ht.Bucketizer([0.0, 1.0, 2.0], "v", "bk")
    tab = ht.Table.from_dict({"v": np.array([0.0, 1.0, 2.0])},
                             ht.Schema([("v", "float")]))
    np.testing.assert_array_equal(b2.transform(tab).column("bk"), [0, 1, 1])


# ------------------------------------------------------------ OneHot
def test_one_hot_encoder(hospital_table):
    idx = ht.StringIndexer("hospital_id", "hid").fit(hospital_table)
    tab = idx.transform(hospital_table)
    enc = ht.OneHotEncoder(["hid"]).fit(tab)
    out = enc.transform(tab)
    k = len(idx.labels)
    names = enc.output_names(0)
    assert len(names) == k - 1  # drop_last
    codes = tab.column("hid")
    for i, nm in enumerate(names):
        np.testing.assert_array_equal(out.column(nm), (codes == i).astype(int))
    # keep-all variant + assembler composition
    enc2 = ht.OneHotEncoder(["hid"], drop_last=False).fit(tab)
    out2 = enc2.transform(tab)
    mat = ht.VectorAssembler(enc2.output_names(0)).transform_matrix(out2)
    np.testing.assert_allclose(mat.sum(axis=1), 1.0)


def test_one_hot_invalid_handling(hospital_table):
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.features import (
        OneHotEncoderModel,
    )

    idx = ht.StringIndexer("hospital_id", "hid").fit(hospital_table)
    tab = idx.transform(hospital_table)
    small = OneHotEncoderModel(("hid",), ("v",), (2,), True, "error")
    with pytest.raises(ValueError, match="outside"):
        small.transform(tab)

    rows_bad = tab.column("hid") >= 2
    # Spark keep semantics: the invalid bucket is an EXTRA last category.
    # dropLast=False → invalid rows get their own indicator column...
    keep_all = OneHotEncoderModel(("hid",), ("v",), (2,), False, "keep")
    out = keep_all.transform(tab)
    assert keep_all.output_names(0) == ["v_0", "v_1", "v_2"]
    np.testing.assert_array_equal(out.column("v_2"), rows_bad.astype(int))
    # ...and dropLast=True drops the invalid bucket, so every VALID
    # category keeps its indicator (code 1 stays distinguishable) while
    # invalid rows encode all-zeros
    keep_drop = OneHotEncoderModel(("hid",), ("v",), (2,), True, "keep")
    out2 = keep_drop.transform(tab)
    assert keep_drop.output_names(0) == ["v_0", "v_1"]
    codes = tab.column("hid")
    np.testing.assert_array_equal(out2.column("v_1"), (codes == 1).astype(int))
    assert (out2.column("v_0")[rows_bad] == 0).all()
    assert (out2.column("v_1")[rows_bad] == 0).all()

    with pytest.raises(ValueError, match="no 'skip'"):
        ht.OneHotEncoder(["hid"], handle_invalid="skip")


# ------------------------------------------------------------ Imputer
def test_imputer_strategies():
    v = np.array([1.0, 2.0, np.nan, 4.0, np.nan, 2.0])
    tab = ht.Table.from_dict({"v": v}, ht.Schema([("v", "float")]))
    mean = ht.Imputer(["v"]).fit(tab).transform(tab).column("v")
    np.testing.assert_allclose(mean[[2, 4]], np.nanmean(v))
    med = ht.Imputer(["v"], strategy="median").fit(tab).transform(tab).column("v")
    np.testing.assert_allclose(med[[2, 4]], 2.0)
    mode = ht.Imputer(["v"], strategy="mode").fit(tab).transform(tab).column("v")
    np.testing.assert_allclose(mode[[2, 4]], 2.0)
    # sentinel missing value + separate output col
    t2 = ht.Table.from_dict({"v": np.array([1.0, -999.0, 3.0])},
                            ht.Schema([("v", "float")]))
    m = ht.Imputer(["v"], ["v_f"], missing_value=-999.0).fit(t2)
    out = m.transform(t2)
    np.testing.assert_allclose(out.column("v_f"), [1.0, 2.0, 3.0])
    np.testing.assert_allclose(out.column("v"), [1.0, -999.0, 3.0])
    with pytest.raises(ValueError, match="strategy"):
        ht.Imputer(["v"], strategy="zero").fit(t2)


# ------------------------------------------------------------ PCA
def test_pca_matches_sklearn(rng, mesh8):
    skd = pytest.importorskip("sklearn.decomposition")
    x = (rng.normal(size=(600, 5)) @ rng.normal(size=(5, 5))).astype(np.float32)
    ours = ht.PCA(k=3).fit(x)
    ref = skd.PCA(n_components=3).fit(np.asarray(x, dtype=np.float64))
    # align sign per component before comparing
    for j in range(3):
        a = ours.components[:, j]
        b = ref.components_[j]
        if np.dot(a, b) < 0:
            b = -b
        np.testing.assert_allclose(a, b, atol=2e-4)
    np.testing.assert_allclose(
        ours.explained_variance, ref.explained_variance_, rtol=1e-3
    )
    # device path equals host path
    ds = ht.device_dataset(x, mesh=mesh8)
    m2 = ht.PCA(k=3).fit(ds)
    np.testing.assert_allclose(m2.components, ours.components, atol=1e-3)
    proj = m2.transform(ds)
    np.testing.assert_allclose(
        np.asarray(proj.x)[: len(x)],
        ours.transform(np.asarray(x, dtype=np.float64)),
        atol=2e-3,
    )
    with pytest.raises(ValueError, match="k must be"):
        ht.PCA(k=9).fit(x)


# ------------------------------------------------------------ stat
def test_correlation_pearson_spearman(rng, mesh8):
    stats = pytest.importorskip("scipy.stats")
    x = rng.normal(size=(400, 4))
    x[:, 1] = 0.7 * x[:, 0] + 0.3 * x[:, 1]
    r = ht.Correlation.corr(x.astype(np.float32), mesh=mesh8)
    np.testing.assert_allclose(r, np.corrcoef(x, rowvar=False), atol=1e-4)
    rs = ht.Correlation.corr(x, method="spearman")
    ref, _ = stats.spearmanr(x)
    np.testing.assert_allclose(rs, ref, atol=1e-10)
    with pytest.raises(ValueError, match="method"):
        ht.Correlation.corr(x, method="kendall")


def test_correlation_constant_column_nan(mesh8):
    x = np.c_[np.ones(64), np.arange(64.0)].astype(np.float32)
    r = ht.Correlation.corr(x, mesh=mesh8)
    assert np.isnan(r[0, 1]) and np.isnan(r[1, 0])
    assert r[0, 0] == 1.0 and r[1, 1] == 1.0


def test_summarizer(rng, mesh8):
    x = rng.normal(size=(300, 3)).astype(np.float32)
    x[5, 0] = 0.0
    w = rng.uniform(0.5, 2.0, size=300)
    s = ht.Summarizer.summary(ht.device_dataset(x, mesh=mesh8, weights=w), mesh=mesh8)
    wsum = w.sum()
    mean = (x * w[:, None]).sum(0) / wsum
    np.testing.assert_allclose(s.mean, mean, rtol=1e-4)
    biased = (w[:, None] * (x - mean) ** 2).sum(0) / wsum
    np.testing.assert_allclose(
        s.variance, biased * wsum / (wsum - 1), rtol=1e-3
    )
    np.testing.assert_allclose(s.min, x.min(0), rtol=1e-6)
    np.testing.assert_allclose(s.max, x.max(0), rtol=1e-6)
    np.testing.assert_allclose(s.norm_l1, (np.abs(x) * w[:, None]).sum(0), rtol=1e-4)
    np.testing.assert_allclose(
        s.norm_l2, np.sqrt((x * x * w[:, None]).sum(0)), rtol=1e-4
    )
    assert s.count == 300
    np.testing.assert_allclose(s.weight_sum, wsum, rtol=1e-5)


# ------------------------------------------------------------ row transforms
def test_normalizer_matches_sklearn(rng):
    sk = pytest.importorskip("sklearn.preprocessing")
    x = rng.normal(size=(200, 4)).astype(np.float32)
    for p, norm in ((2.0, "l2"), (1.0, "l1"), (np.inf, "max")):
        ours = ht.Normalizer(p=p).transform(x)
        ref = sk.normalize(x, norm=norm)
        np.testing.assert_allclose(ours, ref, atol=1e-6)
    # zero rows stay zero, no NaN
    z = np.zeros((3, 4), dtype=np.float32)
    assert not np.isnan(ht.Normalizer().transform(z)).any()
    with pytest.raises(ValueError, match="p must be"):
        ht.Normalizer(p=0.5)


def test_polynomial_expansion_matches_sklearn(rng):
    sk = pytest.importorskip("sklearn.preprocessing")
    x = rng.normal(size=(50, 3)).astype(np.float64)
    pe = ht.PolynomialExpansion(degree=3)
    ours = pe.transform(x)
    ref = sk.PolynomialFeatures(degree=3, include_bias=False).fit_transform(x)
    assert ours.shape[1] == pe.num_outputs(3) == ref.shape[1]
    np.testing.assert_allclose(ours, ref, rtol=1e-10)
    with pytest.raises(ValueError, match="degree"):
        ht.PolynomialExpansion(degree=9)


def test_index_to_string_roundtrip(hospital_table):
    idx = ht.StringIndexer("hospital_id", "hid").fit(hospital_table)
    tab = idx.transform(hospital_table)
    back = ht.IndexToString("hid", "hospital_back", idx.labels).transform(tab)
    assert (back.column("hospital_back") == hospital_table.column("hospital_id")).all()
    bad = ht.IndexToString("hid", "x", idx.labels[:2])
    with pytest.raises(ValueError, match="no label"):
        bad.transform(tab)


def test_chi_square_test(rng):
    sps = pytest.importorskip("scipy.stats")
    n = 2000
    y = rng.integers(0, 2, size=n)
    dependent = (y + rng.integers(0, 2, size=n) * (rng.random(n) < 0.2)).clip(0, 1)
    independent = rng.integers(0, 3, size=n)
    x = np.c_[dependent, independent].astype(np.float64)
    res = ht.ChiSquareTest.test(x, y)
    assert res.p_values[0] < 1e-10       # strongly dependent
    assert res.p_values[1] > 0.01        # independent
    assert res.degrees_of_freedom.tolist() == [1, 2]
    # cross-check statistic 0 against scipy's contingency chi2
    table = np.zeros((2, 2))
    np.add.at(table, (dependent.astype(int), y), 1.0)
    chi2_ref = sps.chi2_contingency(table, correction=False).statistic
    np.testing.assert_allclose(res.statistics[0], chi2_ref, rtol=1e-10)


# ----------------------------------------------- persistence + pipelines
def test_new_stage_artifacts_roundtrip(hospital_table, rng, tmp_path):
    x = rng.normal(size=(100, 4)).astype(np.float32)
    idx = ht.StringIndexer("hospital_id", "hid").fit(hospital_table)
    tab = idx.transform(hospital_table)
    stages = [
        ht.MinMaxScaler(min_out=-2.0).fit(x),
        ht.Bucketizer([0.0, 1.0, 2.0], "length_of_stay", "bk", "keep"),
        ht.OneHotEncoder(["hid"]).fit(tab),
        ht.Imputer(["length_of_stay"]).fit(hospital_table),
        ht.PCA(k=2).fit(x),
    ]
    for i, st in enumerate(stages):
        name, meta, arrays = st._artifacts()
        p = os.path.join(tmp_path, f"s{i}")
        save_model(p, name, meta, arrays)
        back = load_model(p)
        assert type(back) is type(st)
    pca_back = load_model(os.path.join(tmp_path, "s4"))
    np.testing.assert_allclose(pca_back.components, stages[4].components)


def test_new_stages_compose_in_pipeline(hospital_table, mesh8, tmp_path):
    """Imputer/Bucketizer/OneHot run as Table stages, MinMax/PCA as
    feature-matrix stages, all inside one fitted, persisted Pipeline."""
    pipe = ht.Pipeline(
        [
            ht.Imputer(["length_of_stay"]),
            ht.StringIndexer("hospital_id", "hid"),
            ht.OneHotEncoder(["hid"]),
            ht.VectorAssembler(ht.FEATURE_COLS),
            ht.MinMaxScaler(),
            ht.PCA(k=3),
            ht.LinearRegression(),
        ]
    )
    pm = pipe.fit(hospital_table, mesh=mesh8)
    pred = pm.transform(hospital_table, mesh=mesh8)
    rmse = ht.RegressionEvaluator("rmse").evaluate(pred)
    assert np.isfinite(rmse)
    p = os.path.join(tmp_path, "pm")
    pm.save(p)
    back = ht.load_model(p)
    a, _ = pm.transform(hospital_table, mesh=mesh8).to_numpy()
    b, _ = back.transform(hospital_table, mesh=mesh8).to_numpy()
    np.testing.assert_allclose(a, b, rtol=1e-6)
