"""Multi-controller runtime (SURVEY.md §2D distributed comm backend): a
REAL two-process CPU cluster — each process runs the same SPMD program,
``parallel.distributed.initialize`` wires them through the coordinator, and
a sharded LinearRegression fit reduces across process boundaries (the DCN
path of a pod slice, emulated with the CPU collectives transport).

This is the test Spark gets by spinning up local-cluster mode; here it
proves the framework's control plane works beyond one process, not just on
the in-process virtual mesh the rest of the suite uses.
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

_WORKER = textwrap.dedent(
    """
    import importlib.util
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)   # 2 local devices / process

    # The runtime must be wired BEFORE anything touches the XLA backend —
    # importing the package materializes jnp constants, so load the
    # bootstrap module standalone (it only imports os/dataclasses/jax).
    spec = importlib.util.spec_from_file_location(
        "distributed_standalone",
        os.path.join(
            @@REPO@@,
            "clustermachinelearningforhospitalnetworks_apache_spark_tpu",
            "parallel",
            "distributed.py",
        ),
    )
    distributed = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = distributed   # dataclass needs the module registered
    spec.loader.exec_module(distributed)

    ctx = distributed.initialize(
        coordinator_address=@@COORD@@,
        num_processes=2,
        process_id=int(os.environ["PROC_ID"]),
    )
    sys.path.insert(0, @@REPO@@)
    assert ctx.num_processes == 2, ctx
    assert ctx.global_devices == 4, ctx

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.mesh import (
        DATA_AXIS, build_mesh,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.config import MeshConfig

    mesh = build_mesh(MeshConfig(data=4, model=1))

    # every controller materializes the same global rows, each holds its
    # local shards (multi-controller SPMD: jax.make_array_from_callback)
    rng = np.random.default_rng(0)
    n, d = 64, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    beta = np.array([1.0, -2.0, 0.5], np.float32)
    y = (x @ beta + 0.25).astype(np.float32)

    sh = NamedSharding(mesh, P(DATA_AXIS, None))
    xg = jax.make_array_from_callback((n, d), sh, lambda idx: x[idx])
    sh1 = NamedSharding(mesh, P(DATA_AXIS))
    yg = jax.make_array_from_callback((n,), sh1, lambda idx: y[idx])
    wg = jax.make_array_from_callback(
        (n,), sh1, lambda idx: np.ones((n,), np.float32)[idx]
    )

    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.linear_regression import (
        _wls_fit,
    )
    coef, intercept = _wls_fit(xg, yg, wg, jnp.float32(0.0), True, True)
    coef = np.asarray(jax.device_get(coef))
    np.testing.assert_allclose(coef, beta, atol=1e-3)
    np.testing.assert_allclose(float(intercept), 0.25, atol=1e-3)
    print(f"proc {ctx.process_id}: OK coef={coef.round(3).tolist()}")
    """
)


def test_two_process_cluster_fit(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(
        _WORKER.replace("@@REPO@@", repr(repo)).replace(
            "@@COORD@@", repr(f"127.0.0.1:{port}")
        )
    )

    # strip the image's sitecustomize (PYTHONPATH) — it initializes the XLA
    # backend at interpreter start, which must not happen before
    # jax.distributed.initialize in the workers
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "PYTHONPATH")
    }
    procs = []
    for pid in (0, 1):
        e = dict(env, PROC_ID=str(pid), JAX_PLATFORMS="cpu")
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=e,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed worker timed out")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid}: OK" in out, out
