"""Multi-controller runtime (SURVEY.md §2D distributed comm backend): a
REAL two-process CPU cluster — each process runs the same SPMD program,
``parallel.distributed.initialize`` wires them through the coordinator, and
the fits reduce across process boundaries (the DCN path of a pod slice,
emulated with the CPU collectives transport).

Covered cross-process (round 3 broadened this beyond the WLS fit):
- sharded LinearRegression WLS (psum'd Gram) on a 1-D data mesh;
- a KMeans Lloyd loop on a 2-D **data×model** mesh — the model-axis
  ``all_gather`` argmin + data-axis ``psum`` mix that breaks on real pods;
- a level-order histogram tree fit (replicated winner tensors fetched by
  every controller).
Results are asserted against the same fits run in-process by the parent.

This is the test Spark gets by spinning up local-cluster mode; here it
proves the framework's control plane works beyond one process, not just on
the in-process virtual mesh the rest of the suite uses.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest


def _problem_data():
    """Deterministic shared problem set (parent and both workers)."""
    rng = np.random.default_rng(0)
    n, d = 96, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    beta = np.array([1.0, -2.0, 0.5], np.float32)
    y = (x @ beta + 0.25).astype(np.float32)
    # well-separated blobs for the KMeans phase
    blob_centers = np.array(
        [[0, 0, 0], [10, 0, 0], [0, 10, 0], [0, 0, 10]], np.float32
    )
    assign = rng.integers(0, 4, size=n)
    xk = (blob_centers[assign] + rng.normal(0, 0.5, size=(n, d))).astype(np.float32)
    yk = (xk[:, 0] > 5).astype(np.float32) * 3.0 + xk[:, 1] * 0.1
    init = (blob_centers + rng.normal(0, 0.3, size=(4, d))).astype(np.float32)
    return x, y, beta, xk, yk.astype(np.float32), init


_WORKER = textwrap.dedent(
    """
    import importlib.util
    import json
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 2)   # 2 local devices / process
    except AttributeError:  # jax 0.4.x: flag route, backend not yet up
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=2"
            ).strip()

    # The runtime must be wired BEFORE anything touches the XLA backend —
    # importing the package materializes jnp constants, so load the
    # bootstrap module standalone (it only imports os/dataclasses/jax).
    spec = importlib.util.spec_from_file_location(
        "distributed_standalone",
        os.path.join(
            @@REPO@@,
            "clustermachinelearningforhospitalnetworks_apache_spark_tpu",
            "parallel",
            "distributed.py",
        ),
    )
    distributed = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = distributed   # dataclass needs the module registered
    spec.loader.exec_module(distributed)

    ctx = distributed.initialize(
        coordinator_address=@@COORD@@,
        num_processes=2,
        process_id=int(os.environ["PROC_ID"]),
    )
    sys.path.insert(0, @@REPO@@)
    sys.path.insert(0, os.path.join(@@REPO@@, "tests"))
    assert ctx.num_processes == 2, ctx
    assert ctx.global_devices == 4, ctx
    # the runtime is wired: anything failing past this marker is a
    # COLLECTIVES capability gap, not a bootstrap regression — the
    # parent only honors the CPU-backend skip when it sees this
    print("BOOTSTRAP_OK", flush=True)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.mesh import (
        DATA_AXIS, MODEL_AXIS, build_mesh,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.config import MeshConfig
    from test_distributed import _problem_data

    x, y, beta, xk, yk, init = _problem_data()
    n, d = x.shape

    def put(mesh, arr, spec):
        sh = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(arr.shape, sh, lambda idx: arr[idx])

    # ---- phase 1: WLS fit over the hybrid DCN mesh, partitioner-routed
    # The package's own distributed module reads the live runtime
    # (initialize() is a no-op re-read here) and hands back the
    # topology-aware DCN x ICI mesh; the batch layout comes from the one
    # declarative partitioner, not a hand-built PartitionSpec.
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel import (
        distributed as pkg_distributed,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.partitioner import (
        family as partitioner_family,
    )
    pctx = pkg_distributed.context()
    assert pctx.num_processes == 2, pctx
    mesh = pkg_distributed.cluster_mesh()
    assert mesh is not None and mesh.devices.size == 4, mesh
    rows_pt = partitioner_family("rows")

    def put_rows(path, arr):
        sh = rows_pt.sharding(path, mesh=mesh, ndim=arr.ndim)
        return jax.make_array_from_callback(arr.shape, sh, lambda idx: arr[idx])

    xg = put_rows("batch/x", x)
    yg = put_rows("batch/y", y)
    wg = put_rows("batch/w", np.ones((n,), np.float32))

    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.linear_regression import (
        _wls_fit,
    )
    coef, intercept = _wls_fit(xg, yg, wg, jnp.float32(0.0), True, True)
    coef = np.asarray(jax.device_get(coef))
    np.testing.assert_allclose(coef, beta, atol=1e-3)
    np.testing.assert_allclose(float(intercept), 0.25, atol=1e-3)

    # ---- phase 2: KMeans Lloyd on a 2-D data×model mesh ---------------
    # model-axis all_gather argmin + data-axis psum, across processes
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.kmeans import (
        _make_train_step,
    )
    mesh2 = build_mesh(MeshConfig(data=2, model=2))
    xkg = put(mesh2, xk, P(DATA_AXIS, None))
    wkg = put(mesh2, np.ones((n,), np.float32), P(DATA_AXIS))
    cen = put(mesh2, init, P(MODEL_AXIS, None))
    cv = put(mesh2, np.ones((4,), np.float32), P(MODEL_AXIS))
    step = _make_train_step(mesh2, n // 2, 4, d, 32768)
    for _ in range(5):
        cen, counts, cost, move = step(xkg, wkg, cen, cv)
    rep = jax.jit(lambda c: c, out_shardings=NamedSharding(mesh2, P()))
    centers = np.asarray(jax.device_get(rep(cen)))
    result = {
        "centers": centers.tolist(),
        "cost": float(cost),
        "counts": np.asarray(jax.device_get(rep(counts))).tolist(),
    }

    # ---- phase 3: histogram tree fit across processes -----------------
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.tree.engine import (
        grow_forest,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.tree.binning import (
        quantile_thresholds,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.sharding import (
        DeviceDataset,
    )
    thr = quantile_thresholds(xk.astype(np.float64), 16)   # host, shared
    ykg = put(mesh2, yk, P(DATA_AXIS))
    ds = DeviceDataset(x=xkg, y=ykg, w=wkg)
    grown = grow_forest(
        ds, task="regression", num_trees=1, max_depth=3, max_bins=16,
        seed=0, mesh=mesh2, bin_thresholds=thr,
    )
    result["split_feat"] = grown.split_feat.tolist()
    result["threshold"] = grown.threshold.tolist()
    result["value"] = np.asarray(grown.value[..., 0]).tolist()

    # ---- phase 4: GMM EM loop across processes (moment psums) ---------
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.gmm import (
        _init_params, _make_em_loop,
    )
    shift = xk.mean(axis=0).astype(np.float32)
    m0, c0, w0 = _init_params(
        (xk - shift).astype(np.float64), 3, d, 0, 1e-6
    )
    loop = _make_em_loop(mesh, n // 4, 3, d, 65536, 5)
    gm_means, gm_covs, gm_weights, gm_ll, _ = loop(
        put(mesh, xk, P(DATA_AXIS, None)),
        put(mesh, np.ones((n,), np.float32), P(DATA_AXIS)),
        put(mesh, shift, P()),
        put(mesh, m0, P()), put(mesh, c0, P()), put(mesh, w0, P()),
        jnp.float32(1e-6), jnp.float32(-jnp.inf),
    )
    result["gmm_means"] = np.asarray(jax.device_get(gm_means)).tolist()
    result["gmm_weights"] = np.asarray(jax.device_get(gm_weights)).tolist()
    result["gmm_ll"] = float(gm_ll)

    # ---- phase 5: multinomial logistic Hessian reductions -------------
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.logistic_regression import (
        _multinomial_fit,
    )
    y3 = np.clip(
        (xk[:, 0] > 5).astype(np.int32) + 2 * (xk[:, 1] > 5).astype(np.int32),
        0, 2,
    ).astype(np.float32)
    mcoef, mint, _ = _multinomial_fit(
        put(mesh, xk, P(DATA_AXIS, None)),
        put(mesh, y3, P(DATA_AXIS)),
        put(mesh, np.ones((n,), np.float32), P(DATA_AXIS)),
        jnp.float32(0.01), jnp.float32(1e-6), 3, True, True, 30, 4096,
    )
    result["mlr_coef"] = np.asarray(jax.device_get(mcoef)).tolist()
    result["mlr_intercept"] = np.asarray(jax.device_get(mint)).tolist()

    print("RESULT " + json.dumps(result), flush=True)
    print(f"proc {ctx.process_id}: OK coef={coef.round(3).tolist()}")
    """
)


def _in_process_reference():
    """The same KMeans/tree fits on the parent's in-process virtual mesh."""
    import jax
    import jax.numpy as jnp  # noqa: F401
    from jax.sharding import NamedSharding, PartitionSpec as P

    import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.config import (
        MeshConfig,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.kmeans import (
        _make_train_step,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.tree.binning import (
        quantile_thresholds,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.tree.engine import (
        grow_forest,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.mesh import (
        DATA_AXIS,
        MODEL_AXIS,
        build_mesh,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.sharding import (
        DeviceDataset,
    )

    _, _, _, xk, yk, init = _problem_data()
    n, d = xk.shape
    mesh = build_mesh(MeshConfig(data=2, model=2))

    def put(arr, spec):
        return jax.device_put(arr, NamedSharding(mesh, spec))

    xkg = put(xk, P(DATA_AXIS, None))
    wkg = put(np.ones((n,), np.float32), P(DATA_AXIS))
    cen = put(init, P(MODEL_AXIS, None))
    cv = put(np.ones((4,), np.float32), P(MODEL_AXIS))
    step = _make_train_step(mesh, n // 2, 4, d, 32768)
    for _ in range(5):
        cen, counts, cost, move = step(xkg, wkg, cen, cv)
    thr = quantile_thresholds(xk.astype(np.float64), 16)
    grown = grow_forest(
        DeviceDataset(x=xkg, y=put(yk, P(DATA_AXIS)), w=wkg),
        task="regression", num_trees=1, max_depth=3, max_bins=16,
        seed=0, mesh=mesh, bin_thresholds=thr,
    )
    # GMM EM + multinomial logistic on the 1-D data mesh (same shapes as
    # the workers' phases 4-5)
    import jax.numpy as jnp
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.gmm import (
        _init_params,
        _make_em_loop,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.logistic_regression import (
        _multinomial_fit,
    )

    mesh1 = build_mesh(MeshConfig(data=4, model=1))

    def put1(arr, spec):
        return jax.device_put(arr, NamedSharding(mesh1, spec))

    shift = xk.mean(axis=0).astype(np.float32)
    m0, c0, w0 = _init_params((xk - shift).astype(np.float64), 3, d, 0, 1e-6)
    loop = _make_em_loop(mesh1, n // 4, 3, d, 65536, 5)
    gm_means, _, gm_weights, gm_ll, _ = loop(
        put1(xk, P(DATA_AXIS, None)),
        put1(np.ones((n,), np.float32), P(DATA_AXIS)),
        put1(shift, P()),
        put1(m0, P()), put1(c0, P()), put1(w0, P()),
        jnp.float32(1e-6), jnp.float32(-jnp.inf),
    )
    y3 = np.clip(
        (xk[:, 0] > 5).astype(np.int32) + 2 * (xk[:, 1] > 5).astype(np.int32),
        0, 2,
    ).astype(np.float32)
    mcoef, mint, _ = _multinomial_fit(
        put1(xk, P(DATA_AXIS, None)),
        put1(y3, P(DATA_AXIS)),
        put1(np.ones((n,), np.float32), P(DATA_AXIS)),
        jnp.float32(0.01), jnp.float32(1e-6), 3, True, True, 30, 4096,
    )
    return {
        "centers": np.asarray(jax.device_get(cen)),
        "cost": float(cost),
        "counts": np.asarray(jax.device_get(counts)),
        "split_feat": grown.split_feat,
        "threshold": grown.threshold,
        "value": np.asarray(grown.value[..., 0]),
        "gmm_means": np.asarray(jax.device_get(gm_means)),
        "gmm_weights": np.asarray(jax.device_get(gm_weights)),
        "gmm_ll": float(gm_ll),
        "mlr_coef": np.asarray(jax.device_get(mcoef)),
        "mlr_intercept": np.asarray(jax.device_get(mint)),
    }


def test_two_process_cluster_fit(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(
        _WORKER.replace("@@REPO@@", repr(repo)).replace(
            "@@COORD@@", repr(f"127.0.0.1:{port}")
        )
    )

    # strip the image's sitecustomize (PYTHONPATH) — it initializes the XLA
    # backend at interpreter start, which must not happen before
    # jax.distributed.initialize in the workers
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "PYTHONPATH")
    }
    procs = []
    for pid in (0, 1):
        e = dict(env, PROC_ID=str(pid), JAX_PLATFORMS="cpu")
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=e,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed worker timed out")
        outs.append(out)
    if any(
        "Multiprocess computations aren't implemented on the CPU backend"
        in out
        for out in outs
    ):
        # jax 0.4.x jaxlib: the CPU runtime has no cross-process
        # collectives at all (gloo-backed CPU collectives land in later
        # jaxlibs) — the capability under test cannot exist here.  The
        # skip is honored ONLY when every worker proved its bootstrap
        # (coordinator handshake, process/device counts) first: a broken
        # jax.distributed.initialize must fail loudly, not hide behind
        # the collectives skip.
        assert all("BOOTSTRAP_OK" in out for out in outs), (
            "distributed bootstrap failed BEFORE the collectives probe "
            "— this is a regression, not a backend capability gap:\n"
            + "\n".join(outs)
        )
        pytest.skip("this jaxlib's CPU backend lacks multiprocess collectives")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid}: OK" in out, out

    # cross-process results must match the parent's in-process fits
    results = []
    for out in outs:
        line = next(l for l in out.splitlines() if l.startswith("RESULT "))
        results.append(json.loads(line[len("RESULT "):]))
    # both controllers computed identical replicated results
    np.testing.assert_array_equal(
        np.asarray(results[0]["centers"]), np.asarray(results[1]["centers"])
    )
    ref = _in_process_reference()
    got = results[0]
    np.testing.assert_allclose(
        np.asarray(got["centers"]), ref["centers"], atol=1e-4
    )
    np.testing.assert_allclose(got["cost"], ref["cost"], rtol=1e-4)
    np.testing.assert_array_equal(
        np.asarray(got["counts"]), ref["counts"]
    )
    np.testing.assert_array_equal(
        np.asarray(got["split_feat"]), ref["split_feat"]
    )
    np.testing.assert_allclose(
        np.asarray(got["threshold"]), ref["threshold"], atol=1e-6
    )
    np.testing.assert_allclose(np.asarray(got["value"]), ref["value"], atol=1e-4)
    # GMM moment psums and multinomial Hessian reductions crossed the
    # process boundary and landed on the in-process trajectories
    np.testing.assert_allclose(
        np.asarray(got["gmm_means"]), ref["gmm_means"], atol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(got["gmm_weights"]), ref["gmm_weights"], atol=1e-4
    )
    np.testing.assert_allclose(got["gmm_ll"], ref["gmm_ll"], rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(got["mlr_coef"]), ref["mlr_coef"], atol=2e-3
    )
    # intercepts are the least-pinned direction of a softmax fit; the
    # cross-process partitioning reorders f32 accumulation slightly
    np.testing.assert_allclose(
        np.asarray(got["mlr_intercept"]), ref["mlr_intercept"], atol=5e-3
    )
