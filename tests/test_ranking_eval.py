"""RankingEvaluator + MultilabelClassificationEvaluator (the last two
pyspark.ml.evaluation evaluators; the ragged per-row sets are padded to
fixed-width -1-sentinel matrices so every metric is one vectorized
membership reduction — the same padding-not-branching rule the
estimators use for rows)."""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht

pytestmark = pytest.mark.fast

# the example from Spark's RankingMetrics docs
PRED = [
    [1, 6, 2, 7, 8, 3, 9, 10, 4, 5],
    [4, 1, 5, 6, 2, 7, 3, 8, 9, 10],
    [1, 2, 3, 4, 5],
]
TRUTH = [
    [1, 2, 3, 4, 5],
    [1, 2, 3],
    [],
]


class TestRankingEvaluator:
    def test_mean_average_precision(self):
        # hand-computed AP per Spark's formula:
        # row0 hits at ranks 1,3,6,9,10 → (1/1+2/3+3/6+4/9+5/10)/5
        ap0 = (1 + 2 / 3 + 3 / 6 + 4 / 9 + 5 / 10) / 5
        # row1 hits at ranks 2,5,7 → (1/2+2/5+3/7)/3
        ap1 = (1 / 2 + 2 / 5 + 3 / 7) / 3
        expect = (ap0 + ap1 + 0.0) / 3
        got = ht.RankingEvaluator("meanAveragePrecision").evaluate(PRED, TRUTH)
        np.testing.assert_allclose(got, expect, rtol=1e-9)

    def test_precision_and_recall_at_k(self):
        # k=3: row0 has hits {1,2} in top 3 → 2/3; row1 {1} → 1/3; row2 0
        p3 = ht.RankingEvaluator("precisionAtK", k=3).evaluate(PRED, TRUTH)
        np.testing.assert_allclose(p3, (2 / 3 + 1 / 3 + 0) / 3, rtol=1e-9)
        r3 = ht.RankingEvaluator("recallAtK", k=3).evaluate(PRED, TRUTH)
        np.testing.assert_allclose(r3, (2 / 5 + 1 / 3 + 0) / 3, rtol=1e-9)

    def test_ndcg_perfect_ranking_is_one(self):
        pred = [[3, 1, 2], [7, 8]]
        truth = [[1, 2, 3], [7, 8]]
        got = ht.RankingEvaluator("ndcgAtK", k=3).evaluate(pred, truth)
        np.testing.assert_allclose(got, 1.0, rtol=1e-9)

    def test_ndcg_order_sensitivity(self):
        best = ht.RankingEvaluator("ndcgAtK", k=2).evaluate([[1, 9]], [[1]])
        worse = ht.RankingEvaluator("ndcgAtK", k=2).evaluate([[9, 1]], [[1]])
        assert best == 1.0 and 0 < worse < 1.0
        np.testing.assert_allclose(worse, (1 / np.log2(3)) / 1.0, rtol=1e-9)

    def test_map_at_k(self):
        # k=2: row0 hits rank 1 → (1/1)/min(5,2)=0.5; row1 hits rank 2 →
        # (1/2)/min(3,2)=0.25; row2 empty → 0
        got = ht.RankingEvaluator("meanAveragePrecisionAtK", k=2).evaluate(
            PRED, TRUTH
        )
        np.testing.assert_allclose(got, (0.5 + 0.25 + 0) / 3, rtol=1e-9)

    def test_validation(self):
        ev = ht.RankingEvaluator("nope")
        with pytest.raises(ValueError, match="metric_name"):
            ev.evaluate([[1]], [[1]])
        with pytest.raises(ValueError, match="rows"):
            ht.RankingEvaluator().evaluate([[1]], [[1], [2]])
        with pytest.raises(ValueError, match="empty"):
            ht.RankingEvaluator().evaluate([], [])
        with pytest.raises(ValueError, match="k"):
            ht.RankingEvaluator(k=0).evaluate([[1]], [[1]])


class TestMultilabelEvaluator:
    # Spark's MultilabelMetrics doc example
    P = [[0.0, 1.0], [0.0, 2.0], [], [2.0], [2.0, 0.0], [0.0, 1.0, 2.0], [1.0]]
    T = [[0.0, 1.0], [0.0, 2.0], [0.0], [2.0], [2.0, 0.0], [0.0, 1.0], [1.0, 2.0]]

    def _ev(self, name):
        return ht.MultilabelClassificationEvaluator(name).evaluate(self.P, self.T)

    def test_spark_doc_example_values(self):
        # values from the Spark MultilabelMetrics documentation example
        np.testing.assert_allclose(self._ev("subsetAccuracy"), 4 / 7, rtol=1e-9)
        # per-row |pred|+|truth|−2·tp: 0,0,1,0,0,1,1 → Σ=3 over n·labels=21
        np.testing.assert_allclose(self._ev("hammingLoss"), 3 / 21, rtol=1e-9)
        np.testing.assert_allclose(
            self._ev("accuracy"), (1 + 1 + 0 + 1 + 1 + 2 / 3 + 1 / 2) / 7, rtol=1e-9
        )
        np.testing.assert_allclose(
            self._ev("precision"), (1 + 1 + 0 + 1 + 1 + 2 / 3 + 1) / 7, rtol=1e-9
        )
        np.testing.assert_allclose(
            self._ev("recall"), (1 + 1 + 0 + 1 + 1 + 1 + 1 / 2) / 7, rtol=1e-9
        )
        # micro metrics are asserted exactly in test_micro_metrics_pool_counts

    def test_micro_metrics_pool_counts(self):
        tp = 2 + 2 + 0 + 1 + 2 + 2 + 1      # per-row intersections
        p = sum(len(r) for r in self.P)
        t = sum(len(r) for r in self.T)
        np.testing.assert_allclose(self._ev("microPrecision"), tp / p, rtol=1e-9)
        np.testing.assert_allclose(self._ev("microRecall"), tp / t, rtol=1e-9)
        np.testing.assert_allclose(
            self._ev("microF1Measure"), 2 * tp / (p + t), rtol=1e-9
        )

    def test_f1_and_larger_better(self):
        f1 = self._ev("f1Measure")
        assert 0 < f1 <= 1
        assert not ht.MultilabelClassificationEvaluator("hammingLoss").is_larger_better
        assert ht.MultilabelClassificationEvaluator("f1Measure").is_larger_better

    def test_validation(self):
        with pytest.raises(ValueError, match="metric_name"):
            ht.MultilabelClassificationEvaluator("nope").evaluate([[1]], [[1]])
        with pytest.raises(ValueError, match="empty"):
            ht.MultilabelClassificationEvaluator().evaluate([], [])

    def test_duplicate_ids_are_set_semantics(self):
        # Spark's MultilabelMetrics operates on sets — duplicated ids in a
        # row must not inflate tp/|pred|/|truth|
        dup = ht.MultilabelClassificationEvaluator("microPrecision").evaluate(
            [[1.0, 1.0, 2.0]], [[1.0, 1.0]]
        )
        clean = ht.MultilabelClassificationEvaluator("microPrecision").evaluate(
            [[1.0, 2.0]], [[1.0]]
        )
        np.testing.assert_allclose(dup, clean, rtol=1e-12)

    def test_accuracy_empty_vs_empty_is_nan(self):
        # Spark: intersect/union on an empty/empty row is 0/0 → NaN, which
        # propagates through the mean
        out = ht.MultilabelClassificationEvaluator("accuracy").evaluate(
            [[], [1.0]], [[], [1.0]]
        )
        assert np.isnan(out)


def test_atk_short_prediction_lists_use_k_denominators():
    """Review regression: a row predicting fewer than k items must not
    score a perfect AtK metric (Spark pads the denominator to k /
    min(|truth|, k))."""
    ndcg = ht.RankingEvaluator("ndcgAtK", k=10).evaluate([[1]], [[1, 2, 3]])
    disc = 1.0 / np.log2(np.arange(10) + 2.0)
    expect = disc[0] / disc[:3].sum()      # idcg over min(3, 10) slots
    np.testing.assert_allclose(ndcg, expect, rtol=1e-9)
    m = ht.RankingEvaluator("meanAveragePrecisionAtK", k=10).evaluate(
        [[1]], [[1, 2, 3]]
    )
    np.testing.assert_allclose(m, 1.0 / 3.0, rtol=1e-9)


def test_hamming_loss_num_labels_is_truth_only():
    """Spark's numLabels counts distinct ground-truth labels only."""
    got = ht.MultilabelClassificationEvaluator("hammingLoss").evaluate(
        [[0.0, 1.0]], [[0.0]]
    )
    np.testing.assert_allclose(got, 1.0, rtol=1e-9)
