"""Export-surface audit (VERDICT r4 #6).

Round 4 shipped ``ChiSqSelector`` implemented but unreachable — the kind
of gap a human notices only by accident.  This test makes the audit
automatic: every public name each submodule declares must be re-exported
at the package top level (or be on the explicit, documented internals
list), every top-level ``__all__`` name must resolve, and the
pyspark-shaped core surface must import by its Spark name.
"""

import importlib

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht

_BASE = "clustermachinelearningforhospitalnetworks_apache_spark_tpu"

#: names a submodule exports for INTERNAL composition, not for users —
#: each entry is a deliberate decision, not an oversight
_INTERNAL = {
    "models": {"Estimator", "Model", "as_device_dataset"},
    "evaluation": {"inertia"},          # silhouette helper
    "parallel": {
        # mesh/sharding plumbing used by estimator implementations
        "DATA_AXIS", "MODEL_AXIS", "distributed", "global_sum", "pad_rows",
        "place_hospitals", "replicate", "row_sharding", "set_default_mesh",
        "shard_rows", "single_device_mesh", "tree_aggregate", "unpad",
    },
}


def test_top_level_all_resolves():
    bad = [n for n in ht.__all__ if getattr(ht, n, None) is None]
    assert not bad, f"__all__ names that do not resolve: {bad}"


@pytest.mark.parametrize(
    "sub", ["features", "models", "evaluation", "tuning", "stat", "parallel"]
)
def test_submodule_surface_is_reexported(sub):
    mod = importlib.import_module(f"{_BASE}.{sub}")
    top = set(ht.__all__)
    internal = _INTERNAL.get(sub, set())
    missing = sorted(
        n for n in getattr(mod, "__all__", []) if n not in top and n not in internal
    )
    assert not missing, (
        f"{sub} exports {missing} but the package top level does not; "
        "export them or add them to _INTERNAL with a reason"
    )


def test_pyspark_shaped_names_import():
    """The Spark names a reference user would reach for, spot-checked
    across every pyspark.ml namespace the README claims."""
    for name in [
        # ml.feature
        "VectorAssembler", "StandardScaler", "StringIndexer", "OneHotEncoder",
        "MinMaxScaler", "Bucketizer", "QuantileDiscretizer", "Imputer", "PCA",
        "Word2Vec", "CountVectorizer", "HashingTF", "IDF", "NGram",
        "Tokenizer", "RegexTokenizer", "StopWordsRemover", "FeatureHasher",
        "RFormula", "VectorSizeHint", "VectorIndexer", "VectorSlicer",
        "ChiSqSelector", "UnivariateFeatureSelector",
        "VarianceThresholdSelector", "BucketedRandomProjectionLSH",
        "MinHashLSH", "SQLTransformer", "Binarizer", "Normalizer",
        "PolynomialExpansion", "ElementwiseProduct", "Interaction", "DCT",
        "IndexToString", "RobustScaler", "MaxAbsScaler",
        # ml.regression / classification
        "LinearRegression", "GeneralizedLinearRegression",
        "DecisionTreeRegressor", "RandomForestRegressor", "GBTRegressor",
        "AFTSurvivalRegression", "IsotonicRegression", "FMRegressor",
        "LogisticRegression", "DecisionTreeClassifier",
        "RandomForestClassifier", "GBTClassifier", "LinearSVC", "NaiveBayes",
        "MultilayerPerceptronClassifier", "FMClassifier", "OneVsRest",
        # ml.clustering
        "KMeans", "BisectingKMeans", "GaussianMixture", "LDA",
        "PowerIterationClustering",
        # ml.recommendation / fpm
        "ALS", "FPGrowth", "PrefixSpan",
        # ml.evaluation
        "RegressionEvaluator", "BinaryClassificationEvaluator",
        "MulticlassClassificationEvaluator", "ClusteringEvaluator",
        "RankingEvaluator", "MultilabelClassificationEvaluator",
        # ml.tuning / pipeline
        "CrossValidator", "TrainValidationSplit", "ParamGridBuilder",
        "Pipeline", "PipelineModel",
        # ml.stat
        "Correlation", "ChiSquareTest", "Summarizer",
        # streaming (mllib parity)
        "StreamingKMeans", "StreamingLinearRegression",
        "StreamingLogisticRegression",
    ]:
        assert getattr(ht, name, None) is not None, f"ht.{name} missing"


def test_model_classes_reachable_for_load():
    """Model classes are part of Spark's public API (KMeansModel.load);
    here they arrive via ht.load_model, but the names must still import
    for isinstance checks and typing."""
    for name in [
        "KMeansModel", "LinearRegressionModel", "LogisticRegressionModel",
        "GaussianMixtureModel", "BisectingKMeansModel", "NaiveBayesModel",
        "DecisionTreeModel", "RandomForestModel", "GBTModel", "ALSModel",
        "GeneralizedLinearRegressionModel", "LinearSVCModel",
        "IsotonicRegressionModel", "OneVsRestModel", "StreamingKMeansModel",
        "PCAModel", "StandardScalerModel", "StringIndexerModel",
        "BucketedRandomProjectionLSHModel", "MinHashLSHModel",
    ]:
        assert getattr(ht, name, None) is not None, f"ht.{name} missing"


def test_exported_estimator_fit_smoke():
    """The newly exported names are live classes, not dangling imports —
    one end-to-end touch through an exported model class."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 3)).astype(np.float32)
    m = ht.KMeans(k=2, seed=0, max_iter=2).fit(x)
    assert isinstance(m, ht.KMeansModel)
    at = ht.VectorAssembler(["a"]).transform(
        ht.Table.from_dict({"a": np.arange(8.0)})
    )
    assert isinstance(at, ht.AssembledTable)
