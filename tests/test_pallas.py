"""Pallas fused-kernel parity: the kernels must agree with the XLA
reference path (ops/distance.py) bit-for-bit on assignment indices and to
float tolerance on the accumulated statistics.  On the CPU test mesh the
kernels run in interpreter mode — same kernel code, same block walk."""

import jax.numpy as jnp
import numpy as np
import pytest

from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.kmeans import KMeans
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.ops.distance import (
    assign_clusters,
    pairwise_sqdist,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.ops.pallas_kernels import (
    fused_assign,
    fused_lloyd_stats,
)


def _data(n=1000, d=5, k=16, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 3, size=(k, d)).astype(np.float32)
    x = (centers[rng.integers(0, k, n)] + rng.normal(size=(n, d))).astype(np.float32)
    return x, centers


@pytest.mark.fast
def test_fused_assign_matches_xla():
    x, centers = _data()
    a_ref, d2_ref = assign_clusters(jnp.asarray(x), jnp.asarray(centers))
    a, d2 = fused_assign(jnp.asarray(x), jnp.asarray(centers), block_rows=128)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(a_ref))
    np.testing.assert_allclose(np.asarray(d2), np.asarray(d2_ref), rtol=1e-5, atol=1e-4)


def test_fused_assign_respects_c_valid():
    x, centers = _data(k=8)
    c_valid = jnp.asarray([1, 1, 1, 0, 0, 0, 0, 0], jnp.float32)
    a, _ = fused_assign(jnp.asarray(x), jnp.asarray(centers), c_valid, block_rows=256)
    assert int(np.max(np.asarray(a))) <= 2


@pytest.mark.parametrize("n,block", [(1000, 128), (513, 256), (64, 64)])
def test_fused_lloyd_stats_matches_dense(n, block):
    x, centers = _data(n=n)
    k = centers.shape[0]
    rng = np.random.default_rng(1)
    w = (rng.random(n) > 0.1).astype(np.float32)  # some zero-weight pad rows
    c_valid = np.ones(k, np.float32)
    c_valid[-2:] = 0.0

    sums, counts, cost = fused_lloyd_stats(
        jnp.asarray(x), jnp.asarray(w), jnp.asarray(centers), jnp.asarray(c_valid),
        block_rows=block,
    )

    d2 = np.array(pairwise_sqdist(jnp.asarray(x), jnp.asarray(centers)))
    d2[:, c_valid == 0] = np.inf
    a = np.argmin(d2, axis=1)
    exp_sums = np.zeros_like(centers)
    exp_counts = np.zeros(k, np.float32)
    for j in range(k):
        m = (a == j) & (w > 0)
        exp_sums[j] = (x[m] * w[m, None]).sum(axis=0)
        exp_counts[j] = w[m].sum()
    exp_cost = float((np.min(d2, axis=1) * w).sum())

    np.testing.assert_allclose(np.asarray(sums), exp_sums, rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(counts), exp_counts, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(float(cost), exp_cost, rtol=1e-4)


def test_kmeans_pallas_path_matches_xla_path(rng, mesh8):
    """End-to-end: the fused-kernel fit must land on the same centers as
    the XLA scan fit (identical init, identical update rule)."""
    centers = rng.normal(scale=3.0, size=(8, 5))
    x = (centers[rng.integers(0, 8, 800)] + rng.normal(scale=0.2, size=(800, 5))).astype(
        np.float32
    )
    km = dict(k=8, max_iter=15, seed=3, chunk_rows=256)
    m_xla = KMeans(use_pallas=False, **km).fit(x, mesh=mesh8)
    m_pal = KMeans(use_pallas=True, **km).fit(x, mesh=mesh8)
    np.testing.assert_allclose(
        np.sort(m_pal.cluster_centers, axis=0),
        np.sort(m_xla.cluster_centers, axis=0),
        rtol=1e-4, atol=1e-4,
    )
    np.testing.assert_allclose(m_pal.training_cost, m_xla.training_cost, rtol=1e-4)
    # opt-in fused predict agrees with the XLA predict
    np.testing.assert_array_equal(
        np.asarray(m_pal.predict(jnp.asarray(x), use_pallas=True)),
        np.asarray(m_pal.predict(jnp.asarray(x))),
    )


def test_use_pallas_rejected_on_model_sharded_mesh(rng, mesh42):
    x = rng.normal(size=(200, 4))
    with pytest.raises(ValueError, match="model axis"):
        KMeans(k=4, use_pallas=True).fit(x, mesh=mesh42)


def test_fused_level_hist_matches_xla_scan(rng, mesh8):
    """The fused bin-and-accumulate kernel (interpret mode on CPU) produces
    the exact histograms of the XLA one-hot-contraction scan, through the
    full forest fit."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.tree.engine import (
        grow_forest,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.sharding import (
        device_dataset,
    )

    n, d = 3000, 5
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x[:, 2] * 2.0 + 0.3 * rng.normal(size=n)).astype(np.float32)
    ds = device_dataset(x, y, mesh=mesh8)
    kw = dict(
        task="regression", num_trees=3, max_depth=4, max_bins=16,
        bootstrap=True, seed=0, mesh=mesh8,
    )
    a = grow_forest(ds, **kw)
    b = grow_forest(ds, use_pallas=True, **kw)
    np.testing.assert_array_equal(a.split_feat, b.split_feat)
    np.testing.assert_array_equal(a.split_bin, b.split_bin)
    np.testing.assert_allclose(a.value, b.value, atol=1e-5)
    np.testing.assert_allclose(a.importances, b.importances, atol=1e-6)


def test_fused_level_hist_classification_parity(rng, mesh8):
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.tree.engine import (
        grow_forest,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.sharding import (
        device_dataset,
    )

    n, d = 2000, 4
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x[:, 0] + x[:, 3] > 0).astype(np.float32)
    ds = device_dataset(x, y, mesh=mesh8)
    kw = dict(
        task="classification", num_classes=2, num_trees=2, max_depth=3,
        max_bins=8, seed=1, mesh=mesh8,
    )
    a = grow_forest(ds, **kw)
    b = grow_forest(ds, use_pallas=True, **kw)
    np.testing.assert_array_equal(a.split_feat, b.split_feat)
    np.testing.assert_allclose(a.value, b.value, atol=1e-5)
