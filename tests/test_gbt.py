"""GBTRegressor / GBTClassifier: boosting beats a single tree, tracks
sklearn's GradientBoosting on the same hyperparameters, persists, and
composes with Pipelines and weights."""

import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht


def _nonlinear(rng, n=3000, d=5):
    x = rng.uniform(-2, 2, size=(n, d)).astype(np.float32)
    y = (
        np.sin(2 * x[:, 0]) * 2
        + x[:, 1] ** 2
        - 1.5 * x[:, 2]
        + 0.1 * rng.normal(size=n)
    ).astype(np.float32)
    return x, y


def test_gbt_regressor_beats_single_tree(rng, mesh8):
    x, y = _nonlinear(rng)
    tree = ht.DecisionTreeRegressor(max_depth=4).fit((x, y), mesh=mesh8)
    gbt = ht.GBTRegressor(max_iter=30, max_depth=4, step_size=0.2).fit(
        (x, y), mesh=mesh8
    )
    probe_x, probe_y = _nonlinear(rng, n=1000)
    rmse = ht.RegressionEvaluator("rmse")
    r_tree = rmse.evaluate(tree.transform((probe_x, probe_y), mesh=mesh8))
    r_gbt = rmse.evaluate(gbt.transform((probe_x, probe_y), mesh=mesh8))
    assert r_gbt < 0.7 * r_tree
    assert gbt.num_trees == 30
    assert gbt.feature_importances.shape == (5,)
    # the three real features dominate the importances
    assert gbt.feature_importances[[0, 1, 2]].sum() > 0.9


def _assembled_with_indicator(rng, n=2400, noise=1.0):
    """AssembledTable with a 30%-held-out validation indicator column."""
    x = rng.uniform(-2, 2, size=(n, 3))
    y = np.sin(2 * x[:, 0]) * 2 + x[:, 1] + noise * rng.normal(size=n)
    is_val = (np.arange(n) % 10 < 3).astype(np.int64)
    tab = ht.Table.from_dict(
        {"f0": x[:, 0], "f1": x[:, 1], "f2": x[:, 2], "label": y, "is_val": is_val}
    )
    return ht.VectorAssembler(["f0", "f1", "f2"]).transform(tab), x, y, is_val


def test_gbt_validation_early_stop(rng, mesh8):
    """Spark's validationIndicatorCol/validationTol: noisy data → the
    held-out loss plateaus and boosting stops before max_iter."""
    # small noisy sample + deep trees + aggressive step = real overfitting:
    # held-out loss bottoms out and climbs, so the stopped prefix wins
    at, x, y, is_val = _assembled_with_indicator(rng, n=800, noise=1.5)
    kw = dict(
        max_iter=80, max_depth=6, step_size=0.5, label_col="label", seed=0
    )
    full = ht.GBTRegressor(**kw).fit(at, mesh=mesh8)
    stopped = ht.GBTRegressor(
        **kw, validation_indicator_col="is_val", validation_tol=1e-3
    ).fit(at, mesh=mesh8)
    assert stopped.num_trees < full.num_trees == 80
    # on FRESH data (neither model saw it) the stopped prefix generalizes
    # at least as well as the overfit 80-round model
    px = rng.uniform(-2, 2, size=(2000, 3))
    py = np.sin(2 * px[:, 0]) * 2 + px[:, 1]  # noiseless truth
    err = lambda m: float(
        np.mean((np.asarray(m.predict_numpy(px)) - py) ** 2)
    )
    assert err(stopped) <= err(full) * 1.05


def test_gbt_validation_non_default_mesh(rng, mesh42):
    """The indicator mask must land on the CALLER's mesh, not the process
    default (mixing meshes raises an incompatible-devices error)."""
    at, x, y, is_val = _assembled_with_indicator(rng, n=600)
    m = ht.GBTRegressor(
        max_iter=6, max_depth=3, label_col="label", seed=0,
        validation_indicator_col="is_val",
    ).fit(at, mesh=mesh42)
    assert np.all(np.isfinite(np.asarray(m.predict_numpy(x))))


def test_gbt_validation_classifier_and_errors(rng, mesh8):
    at, x, y, is_val = _assembled_with_indicator(rng)
    tab = at.table.with_column("y01", (y > 0).astype(np.int64))
    at2 = ht.VectorAssembler(["f0", "f1", "f2"]).transform(tab)
    m = ht.GBTClassifier(
        max_iter=40, max_depth=3, step_size=0.3, label_col="y01", seed=0,
        validation_indicator_col="is_val",
    ).fit(at2, mesh=mesh8)
    pred = np.asarray(m.predict_numpy(x))
    assert (pred == (y > 0)).mean() > 0.8
    # non-table input cannot resolve the column
    with pytest.raises(ValueError, match="table input"):
        ht.GBTRegressor(validation_indicator_col="is_val").fit(
            (x.astype(np.float32), y.astype(np.float32)), mesh=mesh8
        )
    # an indicator that selects nothing is an error, not a silent no-op
    tab0 = at.table.with_column("none_val", np.zeros(len(at.table), np.int64))
    at0 = ht.VectorAssembler(["f0", "f1", "f2"]).transform(tab0)
    with pytest.raises(ValueError, match="no validation rows"):
        ht.GBTRegressor(
            label_col="label", validation_indicator_col="none_val"
        ).fit(at0, mesh=mesh8)


def test_gbt_regressor_tracks_sklearn(rng, mesh8):
    ske = pytest.importorskip("sklearn.ensemble")
    x, y = _nonlinear(rng)
    ours = ht.GBTRegressor(max_iter=40, max_depth=3, step_size=0.1).fit(
        (x, y), mesh=mesh8
    )
    ref = ske.GradientBoostingRegressor(
        n_estimators=40, max_depth=3, learning_rate=0.1
    ).fit(x, y)
    px, py = _nonlinear(rng, n=1000)
    r_ours = float(np.sqrt(np.mean((ours.predict_numpy(px) - py) ** 2)))
    r_ref = float(np.sqrt(np.mean((ref.predict(px) - py) ** 2)))
    # histogram binning vs exact splits: allow 25% slack, not parity
    assert r_ours < 1.25 * r_ref


def test_gbt_classifier(rng, mesh8):
    x, y = _nonlinear(rng)
    yb = (y > np.median(y)).astype(np.float32)
    gbt = ht.GBTClassifier(max_iter=25, max_depth=3, label_col=None or "y").fit(
        (x, yb), mesh=mesh8
    )
    acc = ht.MulticlassClassificationEvaluator("accuracy").evaluate(
        gbt.transform((x, yb), mesh=mesh8)
    )
    assert acc > 0.9
    # probabilities are calibrated-ish: mean ≈ base rate
    p = np.asarray(gbt.predict_proba(ht.device_dataset(x, mesh=mesh8).x))[: len(x)]
    assert abs(p.mean() - yb.mean()) < 0.05
    # margin sign == prediction
    raw = np.asarray(gbt.predict_raw(ht.device_dataset(x, mesh=mesh8).x))[: len(x)]
    np.testing.assert_array_equal(gbt.predict_numpy(x), (raw > 0).astype(np.float32))
    with pytest.raises(ValueError, match="binary"):
        ht.GBTClassifier(max_iter=2).fit((x, y), mesh=mesh8)  # continuous labels


@pytest.mark.fast
def test_gbt_persistence_and_pipeline(hospital_table, mesh8, tmp_path):
    pipe = ht.Pipeline(
        [ht.VectorAssembler(ht.FEATURE_COLS),
         ht.GBTRegressor(max_iter=30, max_depth=3, step_size=0.3)]
    )
    train, test = ht.train_test_split(hospital_table, 0.7, 42)
    pm = pipe.fit(train, mesh=mesh8)
    rmse = ht.RegressionEvaluator("rmse").evaluate(pm.transform(test, mesh=mesh8))
    assert rmse < 0.8
    p = os.path.join(tmp_path, "gbt_pipe")
    pm.save(p)
    back = ht.load_model(p)
    a, _ = pm.transform(test, mesh=mesh8).to_numpy()
    b, _ = back.transform(test, mesh=mesh8).to_numpy()
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_gbt_weighted_zero_rows_inert(rng, mesh8):
    x, y = _nonlinear(rng, n=1200)
    keep = 800
    w = np.r_[np.ones(keep), np.zeros(len(x) - keep)]
    m_w = ht.GBTRegressor(max_iter=8, max_depth=3, seed=1).fit((x, y, w), mesh=mesh8)
    m_t = ht.GBTRegressor(max_iter=8, max_depth=3, seed=1).fit(
        (x[:keep], y[:keep]), mesh=mesh8
    )
    px, _ = _nonlinear(rng, n=300)
    np.testing.assert_allclose(
        m_w.predict_numpy(px), m_t.predict_numpy(px), rtol=1e-5, atol=1e-5
    )
