"""GBTRegressor / GBTClassifier: boosting beats a single tree, tracks
sklearn's GradientBoosting on the same hyperparameters, persists, and
composes with Pipelines and weights."""

import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht


def _nonlinear(rng, n=3000, d=5):
    x = rng.uniform(-2, 2, size=(n, d)).astype(np.float32)
    y = (
        np.sin(2 * x[:, 0]) * 2
        + x[:, 1] ** 2
        - 1.5 * x[:, 2]
        + 0.1 * rng.normal(size=n)
    ).astype(np.float32)
    return x, y


def test_gbt_regressor_beats_single_tree(rng, mesh8):
    x, y = _nonlinear(rng)
    tree = ht.DecisionTreeRegressor(max_depth=4).fit((x, y), mesh=mesh8)
    gbt = ht.GBTRegressor(max_iter=30, max_depth=4, step_size=0.2).fit(
        (x, y), mesh=mesh8
    )
    probe_x, probe_y = _nonlinear(rng, n=1000)
    rmse = ht.RegressionEvaluator("rmse")
    r_tree = rmse.evaluate(tree.transform((probe_x, probe_y), mesh=mesh8))
    r_gbt = rmse.evaluate(gbt.transform((probe_x, probe_y), mesh=mesh8))
    assert r_gbt < 0.7 * r_tree
    assert gbt.num_trees == 30
    assert gbt.feature_importances.shape == (5,)
    # the three real features dominate the importances
    assert gbt.feature_importances[[0, 1, 2]].sum() > 0.9


def test_gbt_regressor_tracks_sklearn(rng, mesh8):
    ske = pytest.importorskip("sklearn.ensemble")
    x, y = _nonlinear(rng)
    ours = ht.GBTRegressor(max_iter=40, max_depth=3, step_size=0.1).fit(
        (x, y), mesh=mesh8
    )
    ref = ske.GradientBoostingRegressor(
        n_estimators=40, max_depth=3, learning_rate=0.1
    ).fit(x, y)
    px, py = _nonlinear(rng, n=1000)
    r_ours = float(np.sqrt(np.mean((ours.predict_numpy(px) - py) ** 2)))
    r_ref = float(np.sqrt(np.mean((ref.predict(px) - py) ** 2)))
    # histogram binning vs exact splits: allow 25% slack, not parity
    assert r_ours < 1.25 * r_ref


def test_gbt_classifier(rng, mesh8):
    x, y = _nonlinear(rng)
    yb = (y > np.median(y)).astype(np.float32)
    gbt = ht.GBTClassifier(max_iter=25, max_depth=3, label_col=None or "y").fit(
        (x, yb), mesh=mesh8
    )
    acc = ht.MulticlassClassificationEvaluator("accuracy").evaluate(
        gbt.transform((x, yb), mesh=mesh8)
    )
    assert acc > 0.9
    # probabilities are calibrated-ish: mean ≈ base rate
    p = np.asarray(gbt.predict_proba(ht.device_dataset(x, mesh=mesh8).x))[: len(x)]
    assert abs(p.mean() - yb.mean()) < 0.05
    # margin sign == prediction
    raw = np.asarray(gbt.predict_raw(ht.device_dataset(x, mesh=mesh8).x))[: len(x)]
    np.testing.assert_array_equal(gbt.predict_numpy(x), (raw > 0).astype(np.float32))
    with pytest.raises(ValueError, match="binary"):
        ht.GBTClassifier(max_iter=2).fit((x, y), mesh=mesh8)  # continuous labels


def test_gbt_persistence_and_pipeline(hospital_table, mesh8, tmp_path):
    pipe = ht.Pipeline(
        [ht.VectorAssembler(ht.FEATURE_COLS),
         ht.GBTRegressor(max_iter=30, max_depth=3, step_size=0.3)]
    )
    train, test = ht.train_test_split(hospital_table, 0.7, 42)
    pm = pipe.fit(train, mesh=mesh8)
    rmse = ht.RegressionEvaluator("rmse").evaluate(pm.transform(test, mesh=mesh8))
    assert rmse < 0.8
    p = os.path.join(tmp_path, "gbt_pipe")
    pm.save(p)
    back = ht.load_model(p)
    a, _ = pm.transform(test, mesh=mesh8).to_numpy()
    b, _ = back.transform(test, mesh=mesh8).to_numpy()
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_gbt_weighted_zero_rows_inert(rng, mesh8):
    x, y = _nonlinear(rng, n=1200)
    keep = 800
    w = np.r_[np.ones(keep), np.zeros(len(x) - keep)]
    m_w = ht.GBTRegressor(max_iter=8, max_depth=3, seed=1).fit((x, y, w), mesh=mesh8)
    m_t = ht.GBTRegressor(max_iter=8, max_depth=3, seed=1).fit(
        (x[:keep], y[:keep]), mesh=mesh8
    )
    px, _ = _nonlinear(rng, n=300)
    np.testing.assert_allclose(
        m_w.predict_numpy(px), m_t.predict_numpy(px), rtol=1e-5, atol=1e-5
    )
