"""Per-hospital federation (SURVEY.md §2C federation row; reference
``hospital_id`` at mllearnforhospitalnetwork.py:65): explicit hospital →
shard placement, shard locality, and fit-equality with the unpartitioned
layout."""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel import (
    DATA_AXIS,
    device_dataset,
    federated_dataset,
    place_hospitals,
)


def _hospital_data(rng, n=1200, n_hosp=11):
    ids = np.array([f"H{rng.integers(0, n_hosp):02d}" for _ in range(n)], dtype=object)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = (x @ np.array([1.0, -0.5, 2.0, 0.0]) + 0.1 * rng.normal(size=n)).astype(
        np.float32
    )
    return x, y, ids


def test_placement_deterministic_and_balanced(rng):
    _, _, ids = _hospital_data(rng)
    p1 = place_hospitals(ids, 8)
    p2 = place_hospitals(ids, 8)
    assert p1 == p2
    counts = np.unique(ids, return_counts=True)
    load = np.zeros(8)
    for h, c in zip(*counts):
        load[p1[h]] += c
    # LPT bound: max load ≤ mean + largest hospital
    assert load.max() <= load.mean() + counts[1].max()


@pytest.mark.fast
def test_hospital_rows_land_on_one_shard(rng, mesh8):
    x, y, ids = _hospital_data(rng)
    fd = federated_dataset(x, ids, y, mesh=mesh8)
    n_shards = mesh8.shape[DATA_AXIS]
    shard_len = fd.n_padded // n_shards
    # every original row's slot maps to the shard its hospital was placed on
    for slot, row in enumerate(fd.row_order):
        if row >= 0:
            assert slot // shard_len == fd.hospital_to_shard[ids[row]]
    # all rows present exactly once
    present = sorted(r for r in fd.row_order if r >= 0)
    assert present == list(range(len(x)))


def test_federated_fit_equals_unpartitioned(rng, mesh8):
    """The federated layout trains the same model as the ingest-order
    layout (reductions are permutation-invariant)."""
    x, y, ids = _hospital_data(rng)
    fd = federated_dataset(x, ids, y, mesh=mesh8)
    plain = device_dataset(x, y, mesh=mesh8)

    m_fed = ht.LinearRegression().fit(fd, mesh=mesh8)
    m_plain = ht.LinearRegression().fit(plain, mesh=mesh8)
    np.testing.assert_allclose(
        np.asarray(m_fed.coefficients), np.asarray(m_plain.coefficients), atol=1e-4
    )
    np.testing.assert_allclose(
        float(m_fed.intercept), float(m_plain.intercept), atol=1e-4
    )

    r_fed = ht.RegressionEvaluator("rmse").evaluate(m_fed.transform(fd, mesh=mesh8))
    r_plain = ht.RegressionEvaluator("rmse").evaluate(
        m_plain.transform(plain, mesh=mesh8)
    )
    assert abs(r_fed - r_plain) < 1e-5


def test_federated_from_assembled_table(rng, hospital_table, mesh8):
    asm = ht.VectorAssembler(ht.FEATURE_COLS).transform(hospital_table)
    fd = federated_dataset(asm, mesh=mesh8)
    assert fd.n_rows == hospital_table.num_rows
    assert set(fd.hospital_to_shard) == set(hospital_table["hospital_id"])
    # label rode along from the source table
    m = ht.LinearRegression().fit(fd, mesh=mesh8)
    assert np.isfinite(np.asarray(m.coefficients)).all()


def test_bisecting_on_federated_layout(rng, mesh8):
    """BASELINE config 4 shape: hierarchical clustering over the federated
    layout matches the plain layout's tree (same seed, same data)."""
    centers = np.array([[0.0, 0.0], [9.0, 9.0], [0.0, 9.0], [9.0, 0.0]])
    a = rng.integers(0, 4, 900)
    x = (centers[a] + rng.normal(scale=0.4, size=(900, 2))).astype(np.float32)
    ids = np.array([f"H{v}" for v in rng.integers(0, 5, 900)], dtype=object)
    fd = federated_dataset(x, ids, mesh=mesh8)
    bk = ht.BisectingKMeans(k=4, seed=0).fit(fd, mesh=mesh8)
    assert bk.cluster_centers.shape == (4, 2)
    pred = np.asarray(bk.predict_numpy(x))
    # recovered the 4 true blobs
    assert len(np.unique(pred)) == 4


def test_silhouette_on_federated_layout(rng, mesh8):
    """Host-order assignments are scattered through row_order, so the
    federated evaluator result equals the plain-layout result."""
    centers = np.array([[0.0, 0.0], [9.0, 9.0], [0.0, 9.0]])
    a = rng.integers(0, 3, 700)
    x = (centers[a] + rng.normal(scale=0.5, size=(700, 2))).astype(np.float32)
    ids = np.array([f"H{v}" for v in rng.integers(0, 6, 700)], dtype=object)

    fd = federated_dataset(x, ids, mesh=mesh8)
    km = ht.KMeans(k=3, seed=0).fit(fd, mesh=mesh8)
    pred_host = np.asarray(km.predict_numpy(x))       # original row order

    s_fed = ht.ClusteringEvaluator().evaluate(fd, pred_host, k=3)
    s_plain = ht.ClusteringEvaluator().evaluate(x, pred_host, k=3)
    assert abs(s_fed - s_plain) < 1e-5
