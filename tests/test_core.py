"""Core table / schema / split tests (SURVEY.md §4 unit-test tier)."""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.split import split_indices
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.config import PipelineConfig


@pytest.mark.fast
def test_schema_roundtrip():
    s = ht.hospital_event_schema()
    assert len(s) == 7
    assert s.names[0] == "hospital_id"
    assert s.field("length_of_stay").is_numeric
    assert not s.field("hospital_id").is_numeric
    assert s.numeric_names() == [
        "admission_count",
        "current_occupancy",
        "emergency_visits",
        "seasonality_index",
        "length_of_stay",
    ]


@pytest.mark.fast
def test_table_basics(hospital_table):
    t = hospital_table
    assert t.num_rows == 400
    sel = t.select(["hospital_id", "length_of_stay"])
    assert sel.schema.names == ["hospital_id", "length_of_stay"]
    m = t.numeric_matrix(list(ht.FEATURE_COLS))
    assert m.shape == (400, 4)


def test_with_column_and_binarize(hospital_table):
    t = ht.Binarizer("length_of_stay", "LOS_binary", 5.0).transform(hospital_table)
    v = t.column("LOS_binary")
    los = t.column("length_of_stay")
    np.testing.assert_array_equal(v, (los > 5.0).astype(np.int64))


def test_na_drop():
    t = ht.Table.from_dict({"a": [1.0, np.nan, 3.0], "b": [1.0, 2.0, 3.0]})
    assert t.na_drop().num_rows == 2


def test_between_window(hospital_table):
    # parity: SELECT * WHERE event_time BETWEEN start AND end (:123-128)
    w = hospital_table.between(
        "event_time", "2025-03-31T22:00:00", "2025-03-31T22:01:39"
    )
    assert w.num_rows == 100


def test_split_deterministic(hospital_table):
    tr1, te1 = ht.train_test_split(hospital_table, 0.7, seed=42)
    tr2, te2 = ht.train_test_split(hospital_table, 0.7, seed=42)
    assert tr1.num_rows == tr2.num_rows
    np.testing.assert_array_equal(tr1.column("length_of_stay"), tr2.column("length_of_stay"))
    assert tr1.num_rows + te1.num_rows == 400
    assert abs(tr1.num_rows - 280) <= 1
    idx = split_indices(100, [0.5, 0.5], seed=1)
    assert len(np.intersect1d(idx[0], idx[1])) == 0


def test_table_arrow_roundtrip(hospital_table):
    pa_tbl = hospital_table.to_arrow()
    back = ht.Table.from_arrow(pa_tbl, hospital_table.schema)
    np.testing.assert_allclose(
        back.column("seasonality_index"), hospital_table.column("seasonality_index")
    )


def test_config_parity_keys(tmp_path):
    cfg = PipelineConfig()
    assert cfg.los_threshold == 5.0          # :49
    assert cfg.train_fraction == 0.7         # :139
    assert cfg.split_seed == 42
    assert cfg.watermark_minutes == 10.0     # :81
    p = tmp_path / "cfg.json"
    cfg.save_json(str(p))
    cfg2 = PipelineConfig.from_json(str(p))
    assert cfg2 == cfg
    # reference camelCase spelling accepted
    cfg3 = PipelineConfig.from_dict({"losThreshold": 6.5, "appName": "x"})
    assert cfg3.los_threshold == 6.5 and cfg3.app_name == "x"


def test_device_dataset_padding(mesh8):
    x = np.arange(30, dtype=np.float64).reshape(10, 3)
    y = np.arange(10, dtype=np.float64)
    ds = ht.device_dataset(x, y, mesh=mesh8)
    assert ds.n_padded == 16  # padded to multiple of 8
    assert float(ds.count()) == 10.0


def test_wrong_feature_width_raises_friendly(rng, mesh8):
    """Predicting with a mismatched feature matrix raises a ValueError
    naming the model and widths, not a raw XLA dot-dimension error."""
    import pytest

    x = rng.normal(size=(200, 4)).astype(np.float32)
    y = (x @ np.ones(4)).astype(np.float32)
    bad = x[:, :3]
    models = [
        ht.LinearRegression().fit((x, y), mesh=mesh8),
        ht.LogisticRegression(max_iter=3).fit((x, (y > 0).astype(np.float32)), mesh=mesh8),
        ht.KMeans(k=3, seed=0, max_iter=3).fit(x, mesh=mesh8),
        ht.GaussianMixture(k=2, seed=0, max_iter=3).fit(x, mesh=mesh8),
        ht.DecisionTreeRegressor(max_depth=2, seed=0).fit((x, y), mesh=mesh8),
    ]
    for m in models:
        with pytest.raises(ValueError, match="features"):
            m.predict_numpy(bad)


# ------------------------------------------------------- device_fence
def test_device_fence_slots_and_warning(mesh8):
    """The fence must reach device arrays held by __slots__ objects (a
    silent no-op fence reproduces the round-5 mistimed-bench failure),
    and warn when it finds nothing to fence."""
    import warnings

    import jax.numpy as jnp

    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils import (
        device_fence,
    )

    class Slotted:
        __slots__ = ("arr",)

        def __init__(self, arr):
            self.arr = arr

    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any RuntimeWarning -> failure
        device_fence(Slotted(jnp.arange(8)))           # slots traversed
        device_fence(np.zeros(4))                      # host array: quiet

    with pytest.warns(RuntimeWarning, match="nothing was fenced"):
        device_fence(object())


# ---------------------------------------------------------- libsvm io
def test_libsvm_roundtrip_and_validation(tmp_path, mesh8):
    import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht

    rng = np.random.default_rng(0)
    x = rng.normal(size=(40, 6)).astype(np.float32)
    x[rng.random(x.shape) < 0.5] = 0.0  # sparsity for the omit-zeros path
    y = rng.integers(0, 2, 40).astype(np.float32)
    p = str(tmp_path / "data.libsvm")
    ht.write_libsvm(p, x, y)
    x2, y2 = ht.read_libsvm(p, n_features=6)
    np.testing.assert_allclose(x2, x, atol=1e-6)
    np.testing.assert_array_equal(y2, y)
    # the tuple feeds straight into a fit
    m = ht.LogisticRegression(max_iter=5).fit((x2, y2), mesh=mesh8)
    assert np.isfinite(np.asarray(m.coefficients)).all()

    # width comes from the max index when unspecified (trailing zero
    # features are unrecoverable without n_features — document by test)
    x3, _ = ht.read_libsvm(p)
    assert x3.shape[1] <= 6

    bad = tmp_path / "bad.libsvm"
    bad.write_text("1.0 3:1.0 2:2.0\n")
    with pytest.raises(ValueError, match="ascending"):
        ht.read_libsvm(str(bad))
    bad.write_text("1.0 0:1.0\n")
    with pytest.raises(ValueError, match="below the 1-based"):
        ht.read_libsvm(str(bad))
    ok0 = tmp_path / "zero.libsvm"
    ok0.write_text("2.0 0:5.0 3:1.0  # comment\n\n1.0 1:2.0\n")
    xz, yz = ht.read_libsvm(str(ok0), zero_based=True)
    assert xz.shape == (2, 4) and xz[0, 0] == 5.0 and yz.tolist() == [2.0, 1.0]
    with pytest.raises(ValueError, match="exceeds n_features"):
        ht.read_libsvm(str(ok0), n_features=2, zero_based=True)


# -------------------------------------------------- show() / describe()
def test_table_describe_spark_semantics():
    import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht

    t = ht.Table.from_dict(
        {
            "h": np.array(["a", "b", "c"], object),
            "v": np.array([1.5, np.nan, 3.0]),
            "w": np.array([2.0, 4.0, 6.0]),
        }
    )
    d = t.describe()
    assert list(d.column("summary")) == ["count", "mean", "stddev", "min", "max"]
    np.testing.assert_allclose(
        d.column("v"), [2, 2.25, np.std([1.5, 3.0], ddof=1), 1.5, 3.0]
    )
    np.testing.assert_allclose(d.column("w")[0:2], [3, 4.0])
    # named subset + non-numeric rejection
    d2 = t.describe("w")
    assert set(d2.columns) == {"summary", "w"}
    with pytest.raises(TypeError, match="not numeric"):
        t.describe("h")


def test_table_show_smoke(capsys):
    import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht

    t = ht.Table.from_dict({"x": np.arange(30).astype(np.float64)})
    t.show(3)
    out = capsys.readouterr().out
    assert "only showing top 3 rows" in out and "| x" in out


def test_describe_show_edge_cases(capsys):
    import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht

    with pytest.raises(ValueError, match="reserves the output column"):
        ht.Table.from_dict({"summary": np.array([1.0, 2.0])}).describe()
    t = ht.Table.from_dict(
        {
            "s": np.array(["abcdefghij"], object),
            "ts": np.array(["NaT"], dtype="datetime64[ns]"),
        }
    )
    t.show(truncate=2)
    out = capsys.readouterr().out
    assert "ab " in out and "abcdefghi" not in out  # hard cut, no ellipsis
    assert "NULL" in out and "NaT" not in out       # NaT renders as NULL


def test_table_sample_drop_rename():
    import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht

    t = ht.Table.from_dict(
        {"a": np.arange(1000).astype(np.float64), "b": np.ones(1000)}
    )
    s = t.sample(0.3, seed=1)
    assert 200 < len(s) < 400                    # Bernoulli around 300
    np.testing.assert_array_equal(
        s.column("a"), t.sample(0.3, seed=1).column("a")  # seeded = stable
    )
    with pytest.raises(ValueError, match="fraction"):
        t.sample(1.5)
    d = t.drop("b", "nonexistent")
    assert list(d.columns) == ["a"]
    r = t.with_column_renamed("a", "alpha")
    assert list(r.columns) == ["alpha", "b"]
    assert r.schema.field("alpha").dtype == t.schema.field("a").dtype
    assert t.with_column_renamed("zzz", "x") is t  # absent = no-op


def test_rename_collision_raises():
    import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht

    t = ht.Table.from_dict({"a": np.ones(3), "b": np.zeros(3)})
    with pytest.raises(ValueError, match="already exists"):
        t.with_column_renamed("a", "b")
