"""LDA (online variational Bayes) + PowerIterationClustering — the last
two pyspark.ml.clustering members.

Oracles: documents generated from known disjoint-support topics (the
learned topic-word distributions must re-concentrate on the true
supports) and a two-block affinity graph PIC must separate."""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht


def _topic_docs(rng, v=30, k=3, n=300, doc_len=60):
    topics = np.zeros((k, v))
    span = v // k
    for j in range(k):
        topics[j, j * span : (j + 1) * span] = 1.0 / span
    docs = np.zeros((n, v), np.float32)
    zs = rng.integers(0, k, n)
    for i in range(n):
        words = rng.choice(v, size=doc_len, p=topics[zs[i]])
        np.add.at(docs[i], words, 1.0)
    return docs, zs, span


class TestLDA:
    def test_recovers_disjoint_topics(self, rng, mesh8):
        docs, zs, span = _topic_docs(rng)
        m = ht.LDA(k=3, max_iter=30, seed=0).fit(docs, mesh=mesh8)
        learned = m.topics_matrix().T          # (k, v)
        # every learned topic concentrates on ONE true support block
        mass = np.zeros((3, 3))
        for a in range(3):
            for b in range(3):
                mass[a, b] = learned[a, b * span : (b + 1) * span].sum()
        assert (mass.max(axis=1) > 0.85).all()
        # and the three topics pick three DIFFERENT supports
        assert len(set(mass.argmax(axis=1))) == 3

    def test_transform_and_perplexity(self, rng, mesh8):
        docs, zs, span = _topic_docs(rng)
        m = ht.LDA(k=3, max_iter=30, seed=0).fit(docs, mesh=mesh8)
        mix = m.transform(docs)
        assert mix.shape == (len(docs), 3)
        np.testing.assert_allclose(mix.sum(axis=1), 1.0, atol=1e-5)
        # dominant topic clusters agree with the generating labels
        dom = mix.argmax(axis=1)
        # map learned topic → true topic by majority vote, require >90%
        agree = 0
        for t in range(3):
            sel = dom == t
            if sel.any():
                agree += (zs[sel] == np.bincount(zs[sel]).argmax()).sum()
        assert agree / len(zs) > 0.9
        # trained model beats an untrained one on held-out perplexity
        untrained = ht.LDA(k=3, max_iter=0, seed=0).fit(docs, mesh=mesh8)
        assert m.log_perplexity(docs) < untrained.log_perplexity(docs) - 0.1

    def test_describe_topics_and_round_trip(self, rng, mesh8, tmp_path):
        docs, _, span = _topic_docs(rng, n=120)
        m = ht.LDA(k=3, max_iter=15, seed=0).fit(docs, mesh=mesh8)
        desc = m.describe_topics(max_terms=5)
        assert len(desc) == 3
        for idx, wts in desc:
            assert len(idx) == 5 and np.all(np.diff(wts) <= 1e-12)
        m.write().overwrite().save(str(tmp_path / "lda"))
        back = ht.load_model(str(tmp_path / "lda"))
        np.testing.assert_allclose(back.lam, m.lam)
        np.testing.assert_allclose(back.transform(docs[:8]), m.transform(docs[:8]))

    def test_validation(self, rng, mesh8):
        docs, _, _ = _topic_docs(rng, n=32)
        with pytest.raises(ValueError, match="optimizer"):
            ht.LDA(optimizer="em").fit(docs, mesh=mesh8)
        with pytest.raises(ValueError, match="k must"):
            ht.LDA(k=1).fit(docs, mesh=mesh8)
        with pytest.raises(ValueError, match="non-negative"):
            ht.LDA(k=2).fit(docs - 5.0, mesh=mesh8)


class TestPIC:
    def _two_blocks(self, rng, nn=60, p_in=0.6, p_out=0.02):
        src, dst = [], []
        for i in range(nn):
            for j in range(i + 1, nn):
                same = (i < nn // 2) == (j < nn // 2)
                if rng.uniform() < (p_in if same else p_out):
                    src.append(i)
                    dst.append(j)
        return np.asarray(src), np.asarray(dst)

    def test_separates_blocks(self, rng, mesh8):
        src, dst = self._two_blocks(rng)
        a = ht.PowerIterationClustering(k=2, max_iter=15, seed=1).assign_clusters(
            src, dst, mesh=mesh8
        )
        g1, g2 = a[:30], a[30:]
        m1, m2 = np.bincount(g1).argmax(), np.bincount(g2).argmax()
        assert m1 != m2
        purity = (np.mean(g1 == m1) + np.mean(g2 == m2)) / 2
        assert purity > 0.9

    def test_degree_init_and_weights(self, rng, mesh8):
        src, dst = self._two_blocks(rng)
        w = np.ones(len(src), np.float32)
        a = ht.PowerIterationClustering(
            k=2, max_iter=15, seed=0, init_mode="degree"
        ).assign_clusters(src, dst, w, mesh=mesh8)
        assert set(np.unique(a)) == {0, 1}

    def test_self_loops_fold_once(self):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.pic import (
            _build_affinity,
        )

        src = np.array([0, 0, 1])
        dst = np.array([1, 0, 1])          # two self-loops, one cross edge
        w = np.array([2.0, 3.0, 5.0], np.float32)
        a = _build_affinity(src, dst, w, 2)
        # symmetrization must not double the diagonal
        np.testing.assert_allclose(a, [[3.0, 2.0], [2.0, 5.0]])

    def test_validation(self, rng, mesh8):
        with pytest.raises(ValueError, match="empty"):
            ht.PowerIterationClustering().assign_clusters(
                np.array([], np.int64), np.array([], np.int64), mesh=mesh8
            )
        with pytest.raises(ValueError, match="no edges"):
            # node 2 exists (max id) but has no edges... use id gap:
            ht.PowerIterationClustering().assign_clusters(
                np.array([0]), np.array([2]), mesh=mesh8
            )
        with pytest.raises(ValueError, match="non-negative"):
            ht.PowerIterationClustering().assign_clusters(
                np.array([0]), np.array([1]), np.array([-1.0]), mesh=mesh8
            )
        with pytest.raises(ValueError, match="init_mode"):
            ht.PowerIterationClustering(init_mode="ones").assign_clusters(
                np.array([0]), np.array([1]), mesh=mesh8
            )


def test_lda_outofcore_minibatch_recovers_topics(rng, mesh8):
    """Docs >> HBM: the streamed minibatch form (Hoffman's native
    algorithm) must recover the same disjoint topic structure."""
    docs, zs, span = _topic_docs(rng)
    m = ht.LDA(k=3, max_iter=60, seed=0).fit(
        ht.HostDataset(x=docs.astype(np.float32), max_device_rows=64),
        mesh=mesh8,
    )
    learned = m.topics_matrix().T
    mass = np.zeros((3, 3))
    for a in range(3):
        for b in range(3):
            mass[a, b] = learned[a, b * span : (b + 1) * span].sum()
    assert (mass.max(axis=1) > 0.8).all()
    assert len(set(mass.argmax(axis=1))) == 3
    # perplexity evaluates on held-in docs
    assert np.isfinite(m.log_perplexity(docs))


def test_lda_outofcore_validation(mesh8):
    with pytest.raises(ValueError, match="non-negative"):
        ht.LDA(k=2).fit(
            ht.HostDataset(x=-np.ones((8, 4), np.float32)), mesh=mesh8
        )
