"""Pipelined streaming ingest (streaming/pipeline.py): parity with the
serial driver, durability under injected crashes, donation/zero-recompile
contracts, and the round-end bench_meta plumbing.

The parity gate is the PR's hard promise: overlapping parse/firewall/
transfer with the device update must not change a single observable —
batches, sink rows, quarantine evidence, WAL contents, or model state.
"""

import gc
import importlib.util
import json
import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import write_csv
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models import (
    StreamingKMeans,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.quality import (
    DataFirewall,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming import (
    FileStreamSource,
    ModelUpdateConsumer,
    PipelinedStreamExecution,
    StreamCheckpoint,
    StreamExecution,
    UnboundedTable,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming.wal import (
    read_lines,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils import faults

pytestmark = pytest.mark.perf

FEATURES = list(ht.FEATURE_COLS)


def _event_csv(path, start_minute, n, rng, dirty_lines=()):
    base = np.datetime64("2025-03-31T22:00:00") + np.timedelta64(
        int(start_minute), "m"
    )
    t = ht.Table.from_dict(
        {
            "hospital_id": np.array(["H01"] * n, dtype=object),
            "event_time": base + np.arange(n).astype("timedelta64[s]"),
            "admission_count": rng.integers(0, 50, n),
            "current_occupancy": rng.integers(20, 200, n),
            "emergency_visits": rng.integers(0, 30, n),
            "seasonality_index": rng.uniform(0.5, 1.5, n),
            "length_of_stay": rng.uniform(1.0, 9.0, n),
        },
        ht.hospital_event_schema(),
    )
    write_csv(t, path)
    if dirty_lines:
        with open(path) as f:
            lines = f.read().rstrip("\n").split("\n")
        for idx, garbage in dirty_lines:
            lines[idx] = garbage
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")


def _drop_fleet(incoming, n_files=5, rows=200, dirty=False):
    rng = np.random.default_rng(7)
    for i in range(n_files):
        dirty_lines = []
        if dirty and i % 2 == 1:
            # line 3 gets a garbage numeric, line 5 a ragged row
            dirty_lines = [
                (3, "H01,2025-03-31 22:00:00,banana,100,5,1.0,4.0"),
                (5, "H01,2025-03-31 22:00:01,7"),
            ]
        _event_csv(
            str(incoming / f"{i:02d}.csv"), i, rows, rng, dirty_lines=dirty_lines
        )


def _build(
    tmp_path, pipelined, tag, foreach=None, firewall=False, watermark=None, **kw
):
    src = FileStreamSource(
        str(tmp_path / "incoming"), ht.hospital_event_schema(),
        max_files_per_batch=1,
    )
    sink = UnboundedTable(str(tmp_path / f"table_{tag}"), ht.hospital_event_schema())
    ckpt = StreamCheckpoint(str(tmp_path / f"ckpt_{tag}"))
    fw = DataFirewall(ht.hospital_event_schema()) if firewall else None
    cls = PipelinedStreamExecution if pipelined else StreamExecution
    return cls(
        source=src, sink=sink, checkpoint=ckpt, foreach_batch=foreach,
        firewall=fw, watermark=watermark, **kw,
    )


def _features_of(sink):
    t = sink.read()
    return np.asarray(t.numeric_matrix(FEATURES), np.float64)


def _wal_summary(ckpt):
    """(batch_id → files) from offsets + the committed id set — the
    driver-visible WAL contract, ignoring piggybacked attempt flags."""
    offsets = {
        int(e["batch_id"]): list(e["files"])
        for e in read_lines(os.path.join(ckpt.path, "offsets.log"))
    }
    commits = {
        int(e["batch_id"])
        for e in read_lines(os.path.join(ckpt.path, "commits.log"))
    }
    return offsets, commits


# ================================================================ parity
def test_pipelined_matches_serial_end_to_end(tmp_path):
    """Same files → same batches, same sink rows, same WAL, and
    BIT-IDENTICAL streaming-kmeans state (same update sequence, same
    shapes, same executable)."""
    (tmp_path / "incoming").mkdir()
    _drop_fleet(tmp_path / "incoming", n_files=5, rows=200)

    sk_s = StreamingKMeans(k=3, seed=0)
    ser = _build(
        tmp_path, False, "s",
        foreach=lambda t, b: sk_s.update(
            t.numeric_matrix(FEATURES).astype(np.float32)
        ),
    )
    infos_s = ser.run(max_batches=5, timeout_s=30)

    sk_p = StreamingKMeans(k=3, seed=0)
    pipe = _build(tmp_path, True, "p")
    pipe.stage = lambda t: t.numeric_matrix(FEATURES).astype(np.float32)
    pipe.foreach_batch = lambda x, b: sk_p.update(x)
    with pipe:
        infos_p = pipe.run(max_batches=5, timeout_s=30)

    assert [(i.batch_id, i.num_input_rows, i.num_appended_rows, i.files)
            for i in infos_s] == \
           [(i.batch_id, i.num_input_rows, i.num_appended_rows, i.files)
            for i in infos_p]
    np.testing.assert_array_equal(_features_of(ser.sink), _features_of(pipe.sink))
    assert _wal_summary(ser.checkpoint) == _wal_summary(pipe.checkpoint)
    np.testing.assert_array_equal(
        sk_s.latest_model.cluster_centers, sk_p.latest_model.cluster_centers
    )
    np.testing.assert_array_equal(
        sk_s.latest_model.cluster_weights, sk_p.latest_model.cluster_weights
    )
    # both drained: one more poll answers "no data" in both drivers
    assert ser.run_once() is None and pipe.run_once() is None


@pytest.mark.quality
def test_pipelined_matches_serial_quarantine(tmp_path):
    """Dirty fleet: the pipelined firewall quarantines EXACTLY the serial
    rows — same files, same line numbers, same reasons, same counters."""
    (tmp_path / "incoming").mkdir()
    _drop_fleet(tmp_path / "incoming", n_files=5, rows=50, dirty=True)

    ser = _build(tmp_path, False, "s", firewall=True)
    infos_s = ser.run(max_batches=5, timeout_s=30)
    pipe = _build(tmp_path, True, "p", firewall=True)
    with pipe:
        infos_p = pipe.run(max_batches=5, timeout_s=30)

    def strip(recs):
        return [
            {k: v for k, v in r.items() if k != "quarantined_at"}
            for r in recs
        ]

    assert strip(ser.checkpoint.quarantined_rows()) == strip(
        pipe.checkpoint.quarantined_rows()
    )
    assert ser.checkpoint.quarantined_row_count() == \
        pipe.checkpoint.quarantined_row_count() > 0
    assert ser.checkpoint.row_reason_histogram() == \
        pipe.checkpoint.row_reason_histogram()
    assert ser.metrics.counters.get("stream.rows_rejected") == \
        pipe.metrics.counters.get("stream.rows_rejected")
    assert [i.num_rejected_rows for i in infos_s] == \
        [i.num_rejected_rows for i in infos_p]
    np.testing.assert_array_equal(_features_of(ser.sink), _features_of(pipe.sink))


def test_staged_payload_respects_watermark_filtering(tmp_path):
    """Late rows the watermark drops must never train the model: the
    worker stages the PRE-filter table, so the driver re-stages from the
    filtered table whenever filtering removed rows — centers stay
    bit-identical to the serial driver."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming import (
        WatermarkTracker,
    )

    (tmp_path / "incoming").mkdir()
    rng = np.random.default_rng(11)
    # file 0 advances the watermark to minute 50; file 1's rows sit at
    # minute 0 — ALL late, all dropped.  Names force processing order.
    _event_csv(str(tmp_path / "incoming" / "00.csv"), 60, 40, rng)
    _event_csv(str(tmp_path / "incoming" / "01.csv"), 0, 10, rng)

    def run(pipelined, tag):
        sk = StreamingKMeans(k=2, seed=0, decay_factor=0.9)
        if pipelined:
            ex = _build(
                tmp_path, True, tag,
                watermark=WatermarkTracker("event_time", 10.0),
            )
            ex.stage = lambda t: t.numeric_matrix(FEATURES).astype(np.float32)
            ex.foreach_batch = lambda x, b: sk.update(x) if len(x) else None
            with ex:
                infos = ex.run(max_batches=2, timeout_s=30)
        else:
            ex = _build(
                tmp_path, False, tag,
                foreach=lambda t, b: sk.update(
                    t.numeric_matrix(FEATURES).astype(np.float32)
                ) if t.num_rows else None,
                watermark=WatermarkTracker("event_time", 10.0),
            )
            infos = ex.run(max_batches=2, timeout_s=30)
        return sk, infos

    sk_s, infos_s = run(False, "ws")
    sk_p, infos_p = run(True, "wp")
    assert [i.num_late_rows for i in infos_s] == [0, 10]
    assert [i.num_late_rows for i in infos_p] == [0, 10]
    # the model only ever saw file 0's rows in both drivers
    np.testing.assert_array_equal(
        sk_s.latest_model.cluster_centers, sk_p.latest_model.cluster_centers
    )
    assert sk_s._steps == sk_p._steps == 1


def test_backlog_drains_through_update_many(tmp_path):
    """A pre-dropped backlog coalesces into update_many drains (not N
    per-batch dispatches) and lands on the same centers as the serial
    per-batch reference."""
    (tmp_path / "incoming").mkdir()
    _drop_fleet(tmp_path / "incoming", n_files=6, rows=150)

    sk_s = StreamingKMeans(k=3, seed=0)
    ser = _build(
        tmp_path, False, "s",
        foreach=lambda t, b: sk_s.update(
            t.numeric_matrix(FEATURES).astype(np.float32)
        ),
    )
    ser.run(max_batches=6, timeout_s=30)

    sk_p = StreamingKMeans(k=3, seed=0)
    pipe = _build(tmp_path, True, "p", pipeline_depth=4)
    cons = ModelUpdateConsumer(sk_p, pipeline=pipe)
    pipe.stage = lambda t: t.numeric_matrix(FEATURES).astype(np.float32)
    pipe.foreach_batch = cons
    with pipe:
        pipe.run(max_batches=6, timeout_s=30)
        cons.flush()
    assert cons.batches_drained > 0  # the backlog actually coalesced
    np.testing.assert_allclose(
        sk_s.latest_model.cluster_centers,
        sk_p.latest_model.cluster_centers,
        rtol=1e-5, atol=1e-6,
    )


# ============================================================== durability
PIPELINE_KILL_SITES = [
    "stream.after_offsets",
    "stream.after_read",
    "stream.after_foreach",
    "stream.after_sink",
    "stream.after_commit",
    "source.read_file",   # dies on the WORKER thread, mid-parse
]


@pytest.mark.chaos
@pytest.mark.parametrize("site", PIPELINE_KILL_SITES)
def test_pipeline_killed_mid_batch_resumes_exactly_once(tmp_path, site):
    """Kill the pipelined driver at every lifecycle boundary — including
    a crash on the prefetch worker — then restart (pipelined again) and
    drain: every row exactly once, no quarantines, ids contiguous."""
    (tmp_path / "incoming").mkdir()
    _drop_fleet(tmp_path / "incoming", n_files=3, rows=100)

    pipe = _build(tmp_path, True, "c")
    with pipe:
        plan = faults.FaultPlan().crash(site)
        if site == "source.read_file":
            # the worker prefetches ahead, so a parse-time kill must be
            # armed BEFORE the first batch ever gets read; the worker may
            # hit it on several prefetches before the delivery surfaces
            with faults.active(plan):
                with pytest.raises(faults.InjectedCrash):
                    pipe.run_once()
            assert plan.fired(site) >= 1
        else:
            assert pipe.run_once().num_appended_rows == 100  # batch 0 clean
            with faults.active(plan):
                with pytest.raises(faults.InjectedCrash):
                    pipe.run_once()
            assert plan.fired(site) == 1

    # "restart": a fresh pipelined driver over the same dirs, drained to
    # quiescence (run_once() → None is authoritative: it forces a poll)
    pipe2 = _build(tmp_path, True, "c")
    with pipe2:
        infos = []
        while (info := pipe2.run_once()) is not None:
            infos.append(info)
        assert pipe2.sink.read().num_rows == 300
        assert pipe2.checkpoint.quarantine_count() == 0
        assert pipe2.sink.max_batch_id() == 2
    assert all(i.status == "ok" for i in infos)


@pytest.mark.chaos
def test_pipeline_replay_does_not_double_count_quarantine(tmp_path):
    """Kill after the sink on a DIRTY batch; the replay must not
    double-count quarantined rows (metric gated per batch id) nor
    duplicate sink rows."""
    (tmp_path / "incoming").mkdir()
    _drop_fleet(tmp_path / "incoming", n_files=2, rows=50, dirty=True)

    pipe = _build(tmp_path, True, "q", firewall=True)
    with pipe:
        pipe.run_once()
        plan = faults.FaultPlan().crash("stream.after_sink")
        with faults.active(plan):
            with pytest.raises(faults.InjectedCrash):
                pipe.run_once()

    pipe2 = _build(tmp_path, True, "q", firewall=True)
    with pipe2:
        while pipe2.run_once() is not None:
            pass
        # batch 1 is the dirty file: 2 bad rows, once
        assert pipe2.checkpoint.quarantined_row_count() == 2
        assert pipe2.metrics.counters.get("stream.rows_rejected") == 2
        assert pipe2.sink.read().num_rows == 50 + 48


@pytest.mark.chaos
def test_pipeline_in_session_replay_rereads_serially(tmp_path):
    """A transient foreach failure replays the batch IN-SESSION while the
    worker is alive: the replay re-reads serially (paused worker, no
    firewall interleaving) and the stream completes with exact totals."""
    (tmp_path / "incoming").mkdir()
    _drop_fleet(tmp_path / "incoming", n_files=3, rows=80)

    boom = {"armed": True}

    def flaky_foreach(batch, batch_id):
        if batch_id == 1 and boom.pop("armed", False):
            raise RuntimeError("transient consumer failure")

    pipe = _build(tmp_path, True, "ir", foreach=flaky_foreach, firewall=True)
    pipe.replay_backoff = pipe.replay_backoff.__class__(
        max_attempts=3, base_delay_s=0.001, max_delay_s=0.01
    )
    with pipe:
        infos = []
        while (info := pipe.run_once()) is not None:
            infos.append(info)
    assert [i.status for i in infos] == ["ok"] * 3
    assert pipe.sink.read().num_rows == 240
    assert pipe.metrics.counters.get("stream.batch_failures") == 1
    # the replay's serial re-read went through the same firewall without
    # corrupting its counters: every input row accounted exactly once
    # per ATTEMPT (batch 1 read twice: once prefetched, once replayed)
    assert pipe.firewall.rows_in == 240 + 80


@pytest.mark.chaos
@pytest.mark.parametrize("pipelined", [False, True], ids=["serial", "pipelined"])
def test_in_session_crash_loop_quarantines_at_budget(tmp_path, pipelined):
    """A driver looped in-session over an escaping crash re-polls the
    same files under the same batch id; once the durable attempt budget
    is spent the batch must QUARANTINE, not retry forever (the fresh
    path's budget guard — not just the restart/pending path's)."""
    (tmp_path / "incoming").mkdir()
    _drop_fleet(tmp_path / "incoming", n_files=1, rows=40)
    exec_ = _build(tmp_path, pipelined, "bl", max_batch_replays=2)
    # every attempt dies (crash() fires once; this emulates a crash on
    # EACH incarnation, the budget guard's target scenario)
    plan = faults.FaultPlan().fail(
        "stream.after_read", times=None,
        error=lambda: faults.InjectedCrash("kill every attempt"),
    )
    try:
        with faults.active(plan):
            for _ in range(2):
                with pytest.raises(faults.InjectedCrash):
                    exec_.run_once()
            info = exec_.run_once()  # budget (2) spent → quarantined
        assert info.status == "quarantined"
        assert exec_.checkpoint.quarantine_count() == 1
        assert exec_.sink.read().num_rows == 0
        # WAL, quarantine evidence, and recovery agree on the files the
        # quarantined batch consumed (offsets intent written pre-quarantine)
        offsets, commits = _wal_summary(exec_.checkpoint)
        assert offsets[info.batch_id] == info.files
        assert info.batch_id in commits
        assert exec_.run_once() is None  # stream moved on
    finally:
        if pipelined:
            exec_.close()


def test_begin_batch_is_one_append_and_counts_attempt(tmp_path):
    """The fused intent write: ONE offsets append carries the first
    attempt; recovery re-counts it across restarts."""
    ckpt = StreamCheckpoint(str(tmp_path / "ck"))
    n = ckpt.begin_batch(4, ["f1.csv", "f2.csv"], {"wm": 1})
    assert n == 1 and ckpt.attempts(4) == 1
    entries = read_lines(os.path.join(ckpt.path, "offsets.log"))
    assert len(entries) == 1 and entries[0]["attempt"] is True
    assert not os.path.exists(os.path.join(ckpt.path, "attempts.log"))
    # replay attempts append to attempts.log, counts accumulate
    assert ckpt.record_attempt(4) == 2
    # a restarted checkpoint recovers both sources of attempts
    ckpt2 = StreamCheckpoint(str(tmp_path / "ck"))
    assert ckpt2.attempts(4) == 2
    rec = ckpt2.recover()
    assert rec["pending"]["batch_id"] == 4
    assert rec["pending"]["files"] == ["f1.csv", "f2.csv"]


def test_max_files_per_batch_caps_poll(tmp_path):
    (tmp_path / "incoming").mkdir()
    _drop_fleet(tmp_path / "incoming", n_files=4, rows=20)
    src = FileStreamSource(
        str(tmp_path / "incoming"), ht.hospital_event_schema(),
        max_files_per_batch=3,
    )
    first = src.poll()
    assert len(first) == 3
    src.commit_files(first)
    assert len(src.poll()) == 1


@pytest.mark.chaos
def test_worker_discovery_failure_surfaces_instead_of_hanging(tmp_path):
    """A file-listing failure on the worker thread (file deleted between
    list and stat, transient mount error) must surface from run_once like
    a serial poll() failure — not leave the driver spinning on a dead
    worker."""
    (tmp_path / "incoming").mkdir()
    _drop_fleet(tmp_path / "incoming", n_files=1, rows=20)
    pipe = _build(tmp_path, True, "d")

    def boom():
        raise OSError("mount fell over")

    pipe.source.list_files = boom
    with pipe:
        with pytest.raises(OSError, match="mount fell over"):
            pipe.run_once()


def test_pipeline_recovers_after_transient_discovery_error(tmp_path):
    """After a surfaced worker error the NEXT run_once spawns a fresh
    worker and ingests normally — a one-off listing blip must not leave
    the driver permanently answering 'no new data'."""
    (tmp_path / "incoming").mkdir()
    _drop_fleet(tmp_path / "incoming", n_files=1, rows=30)
    pipe = _build(tmp_path, True, "r")
    real_list = pipe.source.list_files
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("transient blip")
        return real_list()

    pipe.source.list_files = flaky
    with pipe:
        with pytest.raises(OSError, match="transient blip"):
            pipe.run_once()
        info = pipe.run_once()  # fresh worker, same driver object
        assert info is not None and info.num_appended_rows == 30


def test_consumer_counts_tuple_batch_rows_correctly():
    """A staged (x, w) TUPLE with zero rows must read as empty (len() of
    the tuple would say 2) — and a non-empty tuple as its row count."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.sharding import (
        batch_rows,
    )

    assert batch_rows((np.zeros((0, 3), np.float32), np.zeros(0))) == 0
    assert batch_rows((np.zeros((7, 3), np.float32), np.zeros(7))) == 7
    sk = StreamingKMeans(k=2, seed=0)
    cons = ModelUpdateConsumer(sk)
    cons((np.zeros((0, 2), np.float32), np.zeros(0, np.float32)), 0)
    assert sk._steps == 0  # pre-init empty tuple: skipped, no ++ crash


def test_consumer_decays_empty_batches_after_init():
    """Parity detail: an EMPTY committed batch still applies the decay
    step to an initialized model (a serial unconditional foreach would);
    before any rows arrive, empties are skipped (nothing to init from)."""
    rng = np.random.default_rng(0)
    sk = StreamingKMeans(k=2, seed=0, decay_factor=0.5)
    cons = ModelUpdateConsumer(sk)
    cons(np.zeros((0, 2), np.float32), 0)   # pre-init empty: skipped
    assert sk._steps == 0
    cons(rng.normal(size=(64, 2)).astype(np.float32), 1)
    w1 = float(np.sum(sk.latest_model.cluster_weights))
    cons(np.zeros((0, 2), np.float32), 2)   # post-init empty: decays
    assert sk._steps == 2
    w2 = float(np.sum(sk.latest_model.cluster_weights))
    assert w2 == pytest.approx(0.5 * w1, rel=1e-6)


# ======================================================= donation contract
def test_streaming_updates_zero_recompile_and_no_buffer_growth():
    """Steady-state micro-batch updates: the jitted step is compiled once
    (zero recompiles across batches) and donated state means the live
    device-buffer census does not grow with the batch count."""
    import jax

    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.streaming_kmeans import (
        _make_update_step,
    )

    rng = np.random.default_rng(0)
    sk = StreamingKMeans(k=4, seed=0)
    batches = [rng.normal(size=(256, 3)).astype(np.float32) for _ in range(14)]
    sk.update(batches[0])
    sk.update(batches[1])
    mode, param = sk._alpha()
    step = _make_update_step(4, mode, param, 0)
    warm_cache = step._cache_size()
    gc.collect()
    live0 = len(jax.live_arrays())
    for b in batches[2:]:
        sk.update(b)
    gc.collect()
    assert step._cache_size() == warm_cache  # zero steady-state recompiles
    live1 = len(jax.live_arrays())
    assert live1 <= live0, (
        f"device buffers grew with batches: {live0} -> {live1}"
    )


def test_update_step_actually_donates_state():
    """The previous state buffer is CONSUMED by the update (input-output
    aliasing), not copied — the old reference is deleted."""
    sk = StreamingKMeans(k=2, seed=0)
    rng = np.random.default_rng(1)
    sk.update(rng.normal(size=(64, 2)).astype(np.float32))
    old_centers = sk._centers
    old_hi = sk._weights
    sk.update(rng.normal(size=(64, 2)).astype(np.float32))
    assert old_centers.is_deleted() and old_hi.is_deleted()
    # and the new state is intact
    assert sk.latest_model.cluster_centers.shape == (2, 2)


def test_streaming_micro_batches_run_single_device(mesh8):
    """Adaptive placement: a micro-batch far below the shard threshold
    runs on ONE device of the 8-mesh (per-chip throughput accounting in
    the bench depends on this)."""
    sk = StreamingKMeans(k=2, seed=0)
    sk.update(np.zeros((100, 2), np.float32), mesh=mesh8)
    assert len(sk._centers.sharding.device_set) == 1
    # explicit estimator override restores full-mesh sharding
    sk2 = StreamingKMeans(k=2, seed=0, shard_min_rows_per_device=1)
    sk2.update(np.zeros((100, 2), np.float32), mesh=mesh8)
    assert len(sk2._centers.sharding.device_set) == 8


# ========================================================== bench plumbing
def _load_bench():
    path = os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py")
    spec = importlib.util.spec_from_file_location("bench_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_meta_line_always_fits_and_parses():
    """The round-end line can never again overflow the driver's 2-KB tail
    capture (BENCH_r05's ``parsed: null``): adversarial inputs must stay
    ≤ 2000 bytes of VALID json, headline preserved when it fits."""
    bench = _load_bench()
    rows = [
        {"metric": "m" * 5000, "value": 1.0, "unit": "u" * 900,
         "vs_baseline": 2.0},
    ] + [{"metric": f"c{i}", "error": "e" * 2000} for i in range(60)]
    line = bench._final_meta_line(
        platform="p" * 900, reason="r" * 9000, all_rows=rows,
        cache_dir="/nonexistent", sidecar_note="s" * 9000,
        probe_attempts=123, elapsed_s=1.5,
    )
    assert len(line) <= bench._META_LINE_BUDGET
    meta = json.loads(line)
    assert meta["metric"] == "bench_meta"
    assert meta["configs_ok"] == 1 and meta["configs_err"] == 60

    # the normal case keeps the full headline
    ok = bench._final_meta_line(
        platform="tpu", reason="ok", cache_dir="", sidecar_note="tools/x.jsonl",
        all_rows=[{"metric": "kmeans", "value": 5.0, "unit": "rps",
                   "vs_baseline": 3.2}],
        probe_attempts=1, elapsed_s=10.0,
    )
    meta = json.loads(ok)
    assert meta["headline"]["vs_baseline"] == 3.2
    assert len(ok) <= bench._META_LINE_BUDGET


def test_bench_streaming_pipeline_config_registered():
    bench = _load_bench()
    assert "streaming_pipeline" in bench.CONFIGS
    assert "streaming_pipeline" in bench._TPU_PRIORITY
