"""Fixture: a bare suppression does NOT silence, and is itself a
finding (suppression-missing-reason)."""

import numpy as np


def subsample():
    # cmlhn: disable=unseeded-random
    rng = np.random.default_rng()
    return rng
