"""Fixture: interprocedural blocking-under-lock clean twin — stage
under the lock, run the fsync-reaching helper after release."""

import os
import threading


class Journal:
    def __init__(self, f):
        self._lock = threading.Lock()
        self._f = f
        self._pending = {}

    def append(self, entry):
        with self._lock:
            self._pending[entry["id"]] = entry
            staged = dict(self._pending)
        self._flush(staged)

    def _flush(self, staged):
        self._sync()

    def _sync(self):
        os.fsync(self._f.fileno())
