"""Fixture: journal-mutation-unfaulted clean twin — the mutation's own
function fires a covered site, or a CALLER on the path does (the
microbatch driver ladder shape: the ancestors walk must find it)."""

import os


def fault_point(site, **info):
    """Stands in for utils.faults.fault_point — the pass matches the
    call NAME and resolves the site argument, it never imports."""


def commit_step(ckpt_dir, payload):
    fault_point("fit_ckpt.save.commit", path=ckpt_dir)
    tmp = os.path.join(ckpt_dir, "step-000001.tmp")
    with open(tmp, "w") as f:
        f.write(payload)
    os.replace(tmp, os.path.join(ckpt_dir, "step-000001"))


def _write_state(state_path, payload):
    # no site HERE — the caller brackets it, which the ancestors walk
    # must accept
    tmp = state_path + ".tmp"
    with open(tmp, "w") as f:
        f.write(payload)
    os.replace(tmp, state_path)


def save(ckpt_dir, payload):
    fault_point("fit_ckpt.save.arrays", path=ckpt_dir)
    _write_state(os.path.join(ckpt_dir, "step-000001"), payload)
