"""Fixture: determinism clean — explicit seeded generators only."""

import random

import numpy as np


def subsample(x, seed):
    rng = np.random.default_rng(seed)
    jitter = random.Random(seed + 1)
    return rng.integers(0, 10, 4), jitter.random()
