"""Fixture: seal-without-dirsync regression (ISSUE 18) — a segment
publish that renames the staged bytes into place but never fsyncs the
segments directory.  The commit-log seal entry IS fsync'd, so power
loss here could keep a seal entry whose segment file the directory
forgot — exactly the rename-without-dirsync shape, staged at the
sanctioned ``core/segments.py`` path by the test."""

import os


def _publish(tmp, final_path):
    os.replace(tmp, final_path)  # BAD: no dirsync here or in any caller


def stage_segment(seg_dir, payload):
    final = os.path.join(seg_dir, "seg-0000000000-0000000003.parquet")
    tmp = final + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    _publish(tmp, final)
