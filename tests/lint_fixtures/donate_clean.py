"""Fixture: donated-arg-reused clean — the rebind idiom."""

import jax

step = jax.jit(lambda s, x: s + x, donate_argnums=(0,))


def run(state, xs):
    for x in xs:
        state = step(state, x)  # result rebinds the donated name
    return state
