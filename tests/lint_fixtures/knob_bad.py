"""Fixture: every untracked-knob binding shape the rule must catch."""

# shape 1a: module constant under a registered py_name
max_wait_s = 0.004

# shape 3: alias — a module constant laundered into a knob-named default
_QUEUE_BOUND = 8192


class Server:
    def __init__(self, max_queue_rows: int = 4096):   # shape 2: default
        # shape 1b: attribute assignment of a raw literal
        self.pipeline_depth = 3
        self.rows = max_queue_rows


def build(max_rows=_QUEUE_BOUND):                     # flags _QUEUE_BOUND
    # negative/unary literals count too
    min_compiled_rows = +2048
    return min_compiled_rows
