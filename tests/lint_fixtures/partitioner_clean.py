"""Fixture: partitioner clean — layouts resolved through the rule table.

``PartitionSpec`` may still be *named* (isinstance checks, annotations);
only construction mints a layout.
"""

import jax
from jax.sharding import PartitionSpec

from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.partitioner import (
    family,
)


def shard_batch(mesh, x):
    pt = family("kmeans")
    return jax.device_put(x, pt.sharding("batch/x", mesh=mesh, ndim=x.ndim))


def is_spec(obj) -> bool:
    return isinstance(obj, PartitionSpec)         # OK: not a construction


def annotated(spec: PartitionSpec) -> PartitionSpec:  # OK: annotations
    return spec
