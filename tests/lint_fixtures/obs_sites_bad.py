"""Fixture: fault sites the regex scanner silently skipped (ISSUE 13
bugfix regression) — an f-string-built site with no coverage entry, and
a genuinely dynamic site."""

_KIND = "uncovered"


def fault_point(site, **ctx):
    pass


def work():
    fault_point(f"custom.{_KIND}.site")  # resolves; NOT in SITE_COVERAGE


def hook(site):
    fault_point(site)  # genuinely dynamic: its own violation


def helper():
    name = "wal.append"
    fault_point(name)  # resolves: local single assignment


def other():
    fault_point(name)  # `name` is helper's LOCAL — must flag dynamic,
    # not silently resolve through a leaked module-const table
