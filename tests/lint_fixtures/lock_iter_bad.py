"""Fixture: lock-iter-snapshot — iterating a mutated dict attr of a
lock-owning class without the lock or a snapshot (the PR 10
``ReplicaSet.health()`` RuntimeError class).  Never imported."""

import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._models = {}

    def add(self, name, model):
        with self._lock:
            self._models[name] = model

    def health(self):
        # BAD: a concurrent add() raises RuntimeError mid-iteration
        return {name: m for name, m in self._models.items()}
