"""Fixture: jit-in-function clean — the three sanctioned shapes:
module-level jit, lru_cache'd factory, instance-stored wrapper."""

from functools import lru_cache

import jax

top_level = jax.jit(lambda x: x + 1)


@lru_cache(maxsize=8)
def make_fn(k):
    return jax.jit(lambda x: x * k)


class Scorer:
    def __init__(self, model):
        self._fn = jax.jit(model.predict_fn())  # instance IS the cache
