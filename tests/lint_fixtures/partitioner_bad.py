"""Fixture: handrolled-sharding — layout construction outside parallel/."""

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P


def shard_batch(mesh, x):
    spec = P("data", None)                        # BAD: aliased constructor
    return jax.device_put(x, NamedSharding(mesh, spec))   # BAD


def build_mesh(devices):
    return Mesh(devices, axis_names=("data",))    # BAD: hand-built mesh


def via_module(x, mesh):
    import jax.sharding as sharding

    s = sharding.PartitionSpec("model")           # BAD: module-attr path
    return jax.device_put(x, sharding.NamedSharding(mesh, s))  # BAD
