"""Fixture: donated-arg-reused ACROSS a call boundary — the helper
forwards its parameter into a donate_argnums position, so the caller's
buffer is invalidated through the call; only the deep summary engine
sees it (the single-file rule provably misses this)."""

import jax

_step = jax.jit(lambda s, x: s + x, donate_argnums=(0,))


def apply_step(state, x):
    return _step(state, x)


def run(state, x):
    new_state = apply_step(state, x)
    total = state.sum()  # BAD (deep): state was donated inside apply_step
    return new_state, total
