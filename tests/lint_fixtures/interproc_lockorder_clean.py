"""Fixture: interprocedural lock-order clean twin — both chains
acquire in the same global order (A before B), cycle-free."""

import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()
STATE = {}


def forward():
    with LOCK_A:
        _fwd_helper()


def _fwd_helper():
    _fwd_inner()


def _fwd_inner():
    with LOCK_B:
        STATE["f"] = 1


def backward():
    with LOCK_A:
        _bwd_helper()


def _bwd_helper():
    with LOCK_B:  # same A->B order: no cycle
        STATE["b"] = 1
