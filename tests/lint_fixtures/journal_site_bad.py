"""Fixture: journal-mutation-unfaulted — a durable mutation in a
sanctioned module with NO named fault site firing in the function, its
callees, or any caller chain: durable state the chaos matrix can never
kill at.  Staged at a sanctioned module path by the test."""

import os


def commit_step(ckpt_dir, payload):
    tmp = os.path.join(ckpt_dir, "step-000001.tmp")
    with open(tmp, "w") as f:  # BAD: unkillable durable mutation
        f.write(payload)
    os.replace(tmp, os.path.join(ckpt_dir, "step-000001"))  # BAD: same
