"""Fixture: unseeded-random + wallclock-in-kernel in model code."""

import random
import time
from numpy.random import default_rng

import numpy as np


def subsample(x):
    t0 = time.time()                       # BAD: wallclock in a kernel
    idx = np.random.randint(0, 10, 4)      # BAD: numpy global RNG
    pick = random.choice([1, 2, 3])        # BAD: process-global RNG
    rng = np.random.default_rng()          # BAD: entropy-seeded
    bare = default_rng()                         # BAD: direct import
    return t0, idx, pick, rng, bare
