"""Fixture: crash-swallowed — handlers that eat InjectedCrash (a
BaseException ON PURPOSE) without re-raising or delivering it."""


def poll(source):
    try:
        return source.read()
    except:  # noqa: E722 — BAD: bare except eats the chaos kill
        return None


def retry(fn):
    try:
        return fn()
    except BaseException:  # BAD: swallows InjectedCrash, tests nothing
        return None
