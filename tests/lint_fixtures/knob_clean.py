"""Clean twin: knob-owned names resolved through the registry, and the
shapes the rule must NOT flag."""

from clustermachinelearningforhospitalnetworks_apache_spark_tpu.tune import (
    knob,
)

# unregistered names keep their literals — only the registry set is law
retry_budget = 7


class Server:
    def __init__(self, max_queue_rows: int | None = None):
        # None-sentinel default resolved through the registry: clean
        if max_queue_rows is None:
            max_queue_rows = int(knob("serve.queue.max_rows"))
        self.rows = max_queue_rows
        # non-literal values under a knob name are fine (the resolution
        # path itself assigns these names)
        self.max_wait_s = knob("serve.microbatch.max_wait_ms") / 1e3
        # bools are ints to the AST but never a tuned quantity
        self.fused_rounds = True


def sweep():
    # call KEYWORDS are exempt: explicitly pinning an operating point
    # (benches sweeping a domain, soak configs) is the sanctioned way
    # to pass a non-default value
    return Server(max_queue_rows=1024)
