"""Fixture: soak chaos-dispatch sites with ONE deleted —
``soak.schedule.tick`` has no reachable ``fault_point`` call, so the
chaos schedule can no longer inject at the dispatcher (rule 7,
``required-site-missing``: absence of a load-bearing site is a finding,
the inverse direction of rule 1)."""


def fault_point(site, **ctx):
    pass


def phase_boundary(phase):
    fault_point("soak.phase.transition", phase=phase)


def commit_report(path):
    fault_point("soak.report.commit", path=path)
