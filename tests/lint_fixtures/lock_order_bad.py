"""Fixture: lock-order-cycle — two functions take the same two locks in
opposite orders (the breaker/registry ABBA deadlock class)."""

import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()


def forward():
    with LOCK_A:
        with LOCK_B:
            pass


def backward():
    with LOCK_B:
        with LOCK_A:  # BAD: A->B in forward(), B->A here
            pass
