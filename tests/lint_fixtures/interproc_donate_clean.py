"""Fixture: interprocedural donation clean twin — the donation idiom:
rebind the caller's name to the result, never touch the old buffer."""

import jax

_step = jax.jit(lambda s, x: s + x, donate_argnums=(0,))


def apply_step(state, x):
    return _step(state, x)


def run(state, x):
    state = apply_step(state, x)  # rebound: the old buffer is dead
    total = state.sum()
    return state, total
