"""Fixture: raw-metric-label — tenant/replica label values not minted
through the bounded helpers (unbounded Prometheus series)."""


def fragment(registry, tenant_id, index):
    registry.counter(f'farm.requests{{cohort="{tenant_id}"}}')     # BAD
    registry.gauge(f'fleet.state{{replica="{index}"}}', 1.0)       # BAD
    registry.counter(f'farm.requests{{tenant="{str(tenant_id)}"}}')  # BAD


def concat_fragment(registry, tenant_id, index):
    registry.counter('farm.requests{cohort="' + tenant_id + '"}')      # BAD
    registry.gauge('fleet.state{replica="{}"}'.format(index), 1.0)     # BAD


def mints_elsewhere(registry, index, replica_label):
    lbl = replica_label(index)
    return lbl


def raw_param(registry, lbl):
    # BAD: `lbl` here is a caller-supplied raw value — the minted alias
    # of the SAME NAME in mints_elsewhere() must not legitimize it
    registry.gauge(f'fleet.state{{replica="{lbl}"}}', 1.0)
