"""Fixture: donated-arg-reused — reading a buffer after donating it
(use-after-free on device; silently "works" on CPU)."""

import jax

step = jax.jit(lambda s, x: s + x, donate_argnums=(0,))


def run(state, x):
    new_state = step(state, x)
    total = state.sum()  # BAD: state's buffer was donated
    return new_state, total
