"""Fixture: lock-order-cycle clean — one global acquisition order."""

import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()


def forward():
    with LOCK_A:
        with LOCK_B:
            pass


def also_forward():
    with LOCK_A:
        with LOCK_B:
            pass


GLOBAL_LOCK = threading.Lock()


class A:
    def __init__(self):
        self._lock = threading.Lock()

    def work(self):
        with self._lock:
            with GLOBAL_LOCK:
                pass


class B:
    def __init__(self):
        self._lock = threading.Lock()  # a DIFFERENT lock than A._lock

    def work(self):
        with GLOBAL_LOCK:
            with self._lock:
                pass
