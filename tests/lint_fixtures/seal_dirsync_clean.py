"""Fixture: seal-without-dirsync clean twin — same staged publish, but
the caller fsyncs the segments directory after the rename lands (the
ladder :mod:`core.segments` actually implements)."""

import os


def fsync_dir(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _publish(tmp, final_path):
    os.replace(tmp, final_path)


def stage_segment(seg_dir, payload):
    final = os.path.join(seg_dir, "seg-0000000000-0000000003.parquet")
    tmp = final + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    _publish(tmp, final)
    fsync_dir(seg_dir)
