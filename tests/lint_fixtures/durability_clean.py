"""Fixture: durability clean twin — non-durable scratch writes and
read-mode opens on durable paths are all legal outside the sanctioned
modules."""

import os


def save_report(report_dir, payload):
    # a scratch report is not durable state — no protocol applies
    with open(os.path.join(report_dir, "summary.json"), "w") as f:
        f.write(payload)


def read_state(ckpt_dir):
    # read-mode open of a durable path is always fine
    with open(os.path.join(ckpt_dir, "step-000001.json")) as f:
        return f.read()


def _dump(path, payload):
    with open(path, "w") as f:
        f.write(payload)


def save_summary(report_dir, payload):
    # helper parameter stays untainted: no durable caller
    _dump(os.path.join(report_dir, "summary.json"), payload)
