"""Fixture: lock-iter-snapshot clean — snapshot copy and under-lock
iteration are both fine; a dict that is only rebound is fine too."""

import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._models = {}
        self._frozen = {}

    def add(self, name, model):
        with self._lock:
            self._models[name] = model

    def rebind(self, new):
        self._frozen = dict(new)  # rebound, never mutated in place

    def health(self):
        return {name: m for name, m in list(self._models.items())}

    def health_locked(self):
        with self._lock:
            return {name: m for name, m in self._models.items()}

    def frozen_view(self):
        return [k for k in self._frozen]  # rebind-only: no race
