"""Fixture: a violation silenced by a suppression WITH a reason."""

import numpy as np


def subsample():
    # cmlhn: disable=unseeded-random — fixture: deliberate jitter, documented
    rng = np.random.default_rng()
    return rng
