"""Fixture: rename-without-dirsync — the commit rename happens in a
HELPER and no fsync_dir is reachable after it in the helper or along
any caller chain.  The taint (tmp/final are durable only via the
caller's argument) and the caller-chain reachability are both
cross-function: the one-hop engine provably cannot see this.  Staged at
a sanctioned module path by the test."""

import os


def _install(tmp, final_path):
    os.replace(tmp, final_path)  # BAD: no dirsync here or in any caller


def save_step(ckpt_dir, payload):
    tmp = os.path.join(ckpt_dir, "step-000001.tmp")
    with open(tmp, "w") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    _install(tmp, os.path.join(ckpt_dir, "step-000001"))
