"""Fixture: blocking-under-lock — fsync while a lock is held (the PR 8
flight-recorder dump-under-lock ABBA class)."""

import os
import threading


class Journal:
    def __init__(self, f):
        self._lock = threading.Lock()
        self._f = f
        self._pending = {}

    def append(self, entry):
        with self._lock:
            self._pending[entry["id"]] = entry
            os.fsync(self._f.fileno())  # BAD: every waiter stalls on IO
