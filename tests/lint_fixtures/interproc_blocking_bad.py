"""Fixture: blocking-under-lock THROUGH a helper call — the fsync is
two frames down, so only the interprocedural engine (deep=True) can see
it; the PR 11 lexical/one-hop engine provably misses this file."""

import os
import threading


class Journal:
    def __init__(self, f):
        self._lock = threading.Lock()
        self._f = f
        self._pending = {}

    def append(self, entry):
        with self._lock:
            self._pending[entry["id"]] = entry
            self._flush()  # BAD (deep): _flush -> _sync -> os.fsync

    def _flush(self):
        self._sync()

    def _sync(self):
        os.fsync(self._f.fileno())
