"""Fixture: span emissions — an unregistered literal, a genuinely
dynamic name, and a constant-prefix glob that is not a registered
sink."""


def span(name, attrs=None):
    pass


def work():
    with span("not.registered"):  # span-unregistered
        pass


def emit(name):
    span(name)  # dynamic-span-name


def prefix_emit(kind):
    span("custom." + kind)  # dynamic-span-name: custom.* not a sink
