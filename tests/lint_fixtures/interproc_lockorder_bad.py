"""Fixture: lock-order-cycle through the CALL GRAPH — each chain's
second acquisition is two call hops from the first, so the lexical
nesting walk and the one-hop method rule both provably miss it; only
the deep same-module callee walk sees the A->B / B->A cycle."""

import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()
STATE = {}


def forward():
    with LOCK_A:
        _fwd_helper()


def _fwd_helper():
    _fwd_inner()


def _fwd_inner():
    with LOCK_B:
        STATE["f"] = 1


def backward():
    with LOCK_B:
        _bwd_helper()


def _bwd_helper():
    with LOCK_A:  # BAD: B->A while forward's chain is A->B
        STATE["b"] = 1
