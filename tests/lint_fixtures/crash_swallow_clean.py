"""Fixture: crash-swallowed clean twin — Exception handlers can't eat a
BaseException kill; BaseException handlers re-raise or hand the object
onward (the pipelined prefetcher's capture-and-deliver shape)."""


def poll(source):
    try:
        return source.read()
    except Exception:  # cannot eat a BaseException chaos kill
        return None


def deliver(fn):
    try:
        return None, fn()
    except BaseException as e:
        return e, None  # capture-and-deliver: the object travels onward


def reraise(fn):
    try:
        return fn()
    except BaseException:
        raise
