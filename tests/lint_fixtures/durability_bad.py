"""Fixture: raw durable IO outside the sanctioned durability modules —
raw-durable-write (direct and through a helper PARAMETER, the
cross-call taint the one-hop engine provably cannot see),
raw-durable-rename, and wal-append-bypass."""

import os


def save_state(ckpt_dir, payload):
    path = os.path.join(ckpt_dir, "step-000001.json")
    with open(path, "w") as f:  # BAD: raw-durable-write
        f.write(payload)


def _dump(path, payload):
    # BAD: raw-durable-write — `path` is durable only via the CALLER's
    # argument (parameter taint across the call boundary)
    with open(path, "w") as f:
        f.write(payload)


def save_evidence(quarantine_dir, payload):
    _dump(os.path.join(quarantine_dir, "evidence.json"), payload)


def promote(ckpt_dir):
    staged = os.path.join(ckpt_dir, "step-000002.tmp")
    # BAD: raw-durable-rename — an unsanctioned commit point
    os.replace(staged, os.path.join(ckpt_dir, "step-000002"))


def log_offsets(checkpoint_path, line):
    # BAD: wal-append-bypass — appends route through wal.append_lines
    with open(checkpoint_path + "/offsets.log", "a") as f:
        f.write(line)
