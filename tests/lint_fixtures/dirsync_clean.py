"""Fixture: rename-without-dirsync clean twin — the rename still lives
in a helper, but the CALLER fsyncs the directory after the helper
returns (the legal save()/finalize() split: reachability along the
caller chain satisfies the rule)."""

import os


def fsync_dir(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _install(tmp, final_path):
    os.replace(tmp, final_path)


def save_step(ckpt_dir, payload):
    tmp = os.path.join(ckpt_dir, "step-000001.tmp")
    with open(tmp, "w") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    _install(tmp, os.path.join(ckpt_dir, "step-000001"))
    fsync_dir(ckpt_dir)
