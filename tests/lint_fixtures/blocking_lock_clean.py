"""Fixture: blocking-under-lock clean — stage under the lock, perform
the blocking write after release (the PR 8 fix shape)."""

import os
import threading


class Journal:
    def __init__(self, f):
        self._lock = threading.Lock()
        self._f = f
        self._pending = {}

    def append(self, entry):
        with self._lock:
            self._pending[entry["id"]] = entry
            staged = dict(self._pending)
        self._write(staged)

    def _write(self, staged):
        os.fsync(self._f.fileno())  # outside any lock
