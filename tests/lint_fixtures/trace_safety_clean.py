"""Fixture: trace-safety clean — pad-and-weight instead of masks, shape
reads are static, host coercions live outside the traced region."""

import jax
import jax.numpy as jnp


@jax.jit
def kernel(x):
    n = float(x.shape[0])  # static: shape read, not a traced value
    w = (x > 0).astype(jnp.float32)
    return jnp.sum(x * w) / n


def fit(x):
    out = kernel(x)
    return float(out)  # host coercion OUTSIDE the jitted region
