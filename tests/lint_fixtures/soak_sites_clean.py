"""Fixture: all three soak chaos-dispatch sites reachable — rule 7
(``required-site-missing``) stays quiet."""


def fault_point(site, **ctx):
    pass


def dispatch_tick(event):
    fault_point("soak.schedule.tick", event=event)


def phase_boundary(phase):
    fault_point("soak.phase.transition", phase=phase)


def commit_report(path):
    fault_point("soak.report.commit", path=path)
