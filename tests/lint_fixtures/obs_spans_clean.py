"""Fixture: span emissions clean — registered literal and the
registered ``stage.*`` glob sink (the StageClock shape)."""


def span(name, attrs=None):
    pass


def record_span(name, dur_s, attrs=None):
    pass


def work():
    with span("serve.request"):
        pass


def stage_sink(name, dt):
    record_span("stage." + name, dt)  # registered glob sink: ok
