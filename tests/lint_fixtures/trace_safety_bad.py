"""Fixture: host-sync-in-jit + bool-mask-in-jit inside a traced body."""

import jax
import jax.numpy as jnp


@jax.jit
def kernel(x):
    total = float(jnp.sum(x))  # BAD: concretizes a traced value
    pos = x[x > 0]             # BAD: data-dependent shape
    return jnp.sum(pos) + total


def loop(xs):
    def body(carry, row):
        return carry + row.item(), None  # BAD: .item() in a scanned body

    return jax.lax.scan(body, 0.0, xs)


def while_body(x0):
    def cond(c):
        return c[0] < 10

    def body(c):
        return c[0] + c[1].item(), c[1]  # BAD: .item() in a while body

    return jax.lax.while_loop(cond, body, x0)


def fori(xs):
    def body(i, acc):
        return acc + float(xs[i])  # BAD: float() in a fori body

    return jax.lax.fori_loop(0, 10, body, 0.0)
