"""Fixture: obs sites clean — literal, once-assigned alias, and
parameter-default forwarding (the streaming/wal.py shape) all resolve
to covered sites."""

site_name = "wal.append"


def fault_point(site, **ctx):
    pass


def direct():
    fault_point("serve.predict")


def aliased():
    fault_point(site_name)  # single-assignment alias: resolves


def forwarding(site="stream.after_commit"):
    fault_point(site)  # parameter default: resolves
