"""Fixture: jit-in-function — a fresh jax.jit wrapper per call (the
PR 5 ``_make_boost_scan`` retrace-per-fit class)."""

import jax


def score(model, x):
    fn = jax.jit(model.predict_fn())  # BAD: retraces every call
    return fn(x)
