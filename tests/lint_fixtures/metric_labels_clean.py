"""Fixture: raw-metric-label clean — minted values, incl. the
once-assigned alias the regex rules could not follow (ISSUE 13
bugfix regression)."""


def fragment(registry, tenant_id, index, cohort_label, replica_label):
    registry.counter(f'farm.requests{{cohort="{cohort_label(tenant_id)}"}}')
    lbl = replica_label(index)
    registry.gauge(f'fleet.state{{replica="{lbl}"}}', 1.0)  # alias: ok
    registry.gauge(f'serve.breaker{{model="{tenant_id}"}}', 1.0)  # unguarded key


def concat_fragment(registry, tenant_id, cohort_label):
    registry.counter(
        'farm.requests{cohort="' + cohort_label(tenant_id) + '"}'
    )
    registry.gauge('fleet.state{model="{}"}'.format(tenant_id), 1.0)
