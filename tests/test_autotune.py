"""ISSUE 20: the measurement-driven autotuner (``tune/``).

Four contracts under test:

* **registry round-trip** — knobs declare once (idempotent re-add,
  conflicting redeclaration refused, domain/mode validated), and every
  migrated call site's resolved default is BIT-IDENTICAL to the literal
  it replaced while no selector is installed (the migration must be
  behavior-neutral until trials exist).
* **selector discipline** — thin coverage falls back to the declared
  default (``default:no-trials``), measured coverage picks the best
  value and names the winning trial (``tuned:<id>``), and NO selection
  ever happens inside a fenced A/B (``frozen:fenced-ab`` — probed with
  trials present, both with and without a pre-fence selection to pin).
* **store durability** (chaos) — a killed trial commit leaves the
  previous document intact and the replayed add merges by content hash
  to a byte-identical store: exactly-once.
* **live retuning** (chaos) — a committed retune survives restart via
  the journal; a kill at ``tune.select.apply`` leaves the PREVIOUS
  value serving (intent without commit is ignored on resume).

Plus the PR's bugfix-sweep regression: the five previously-diverged
``max_queue_rows``/``max_rows`` copies all resolve through ONE registry
entry now.
"""

from __future__ import annotations

import os

import pytest

from clustermachinelearningforhospitalnetworks_apache_spark_tpu import tune
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming.wal import (
    read_lines,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.tune import (
    Knob, KnobRegistry, LiveRetuner, Selector, TrialStore, make_trial,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils import (
    faults,
)

pytestmark = pytest.mark.fast

WAIT_KNOB = "serve.microbatch.max_wait_ms"


def _store_with_wait_trials(tmp_path, name="trials.json"):
    st = TrialStore(str(tmp_path / name))
    st.add([
        make_trial(knob=WAIT_KNOB, value=v, score=s, shape_rows=64,
                   metric="span:serve.request")
        for v, s in [(0.0, 9000.0), (2.0, 400.0), (8.0, 90.0)]
    ])
    return st


# ================================================================ registry
def test_registry_round_trip_and_validation():
    reg = KnobRegistry()
    k = Knob(name="x.y", default=4, domain=(2, 4, 8), metric="span:x",
             mode="max", py_names=("y",))
    reg.add(k)
    reg.add(k)                                # idempotent re-declare
    assert reg.get("x.y").default == 4
    assert "x.y" in reg and reg.names() == ["x.y"]
    assert reg.py_name_map() == {"y": "x.y"}
    with pytest.raises(ValueError, match="different declaration"):
        reg.add(Knob(name="x.y", default=2, domain=(2, 4, 8)))
    with pytest.raises(ValueError, match="not in domain"):
        Knob(name="bad", default=3, domain=(2, 4))
    with pytest.raises(ValueError, match="mode"):
        Knob(name="bad", default=2, domain=(2,), mode="sideways")
    with pytest.raises(KeyError, match="unregistered"):
        reg.get("nope")


def test_migrated_defaults_parity():
    """With no selector installed, every migrated call site resolves to
    the EXACT literal it replaced — bit-tight, not approximately."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.sql_compile import (
        bucket_for_rows,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table_lifecycle import (
        RetentionPolicy,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.farm.farm import (
        _next_pow2,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve.batcher import (
        DEFAULT_MAX_WAIT_S,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve.fleet.admission import (
        default_slo_classes,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve.queue import (
        RequestQueue,
    )

    assert tune.installed() is None
    assert DEFAULT_MAX_WAIT_S == 0.002          # was the literal 0.002
    assert tune.knob(WAIT_KNOB) / 1e3 == 0.002
    assert RequestQueue().max_rows == 4096      # was five 4096 copies
    classes = default_slo_classes()
    assert classes["batch"].shed_load == 0.45
    assert classes["best_effort"].shed_load == 0.25
    assert classes["interactive"].shed_load == 1.0   # invariant, not a knob
    pol = RetentionPolicy()
    assert (pol.min_seal_batches, pol.max_segment_batches) == (4, 64)
    assert bucket_for_rows(1) == 256            # was _MIN_BUCKET = 256
    assert bucket_for_rows(300) == 512
    assert _next_pow2(3) == 8                   # was floor=8
    assert tune.knob("stream.pipeline.depth") == 2
    assert tune.knob("stream.worker.poll_interval_ms") / 1e3 == 0.05
    assert tune.knob("stream.source.max_files_per_batch") == 0
    assert tune.knob("sql.stage.min_compiled_rows") == 4096


def test_queue_bound_unified_regression(monkeypatch):
    """Bugfix-sweep regression: the proc-fleet facade's queue bound used
    to be a fifth hand-copied ``4096`` that could diverge from the other
    four — every path must now agree with the ONE registry entry."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve.fleet.proc import (
        ProcServerClient,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve.queue import (
        RequestQueue,
    )

    # only the bound derivation matters here, not a live worker process
    monkeypatch.setattr(ProcServerClient, "_spawn", lambda self: None)
    client = ProcServerClient(0, {})
    assert client.max_queue_rows == int(tune.knob("serve.queue.max_rows"))
    assert client.max_queue_rows == RequestQueue().max_rows == 4096
    assert ProcServerClient(0, {"max_queue_rows": 512}).max_queue_rows == 512


# ================================================================ selector
def test_selector_thin_coverage_falls_back_to_default(tmp_path):
    st = TrialStore(str(tmp_path / "t.json"))
    # ONE distinct value measured: no gradient — default must win
    st.add([make_trial(knob=WAIT_KNOB, value=0.0, score=9000.0)])
    sel = Selector(st)
    k = tune.REGISTRY.get(WAIT_KNOB)
    assert sel.resolve(k, 64) == k.default
    ex = sel.explain(WAIT_KNOB)
    assert ex["reason"] == tune.REASON_DEFAULT_NO_TRIALS
    assert ex["value"] == k.default


def test_selector_picks_best_and_names_the_trial(tmp_path):
    st = _store_with_wait_trials(tmp_path)
    sel = Selector(st)
    with tune.active(sel):
        assert tune.knob(WAIT_KNOB, 64) == 0.0
    ex = sel.explain(WAIT_KNOB)
    assert ex["reason"].startswith(tune.REASON_TUNED_PREFIX)
    tid = ex["reason"][len(tune.REASON_TUNED_PREFIX):]
    assert tid in {t["trial_id"] for t in st.trials(knob=WAIT_KNOB)}
    assert ex["trials_considered"] == 3


def test_selector_interpolates_between_shape_buckets(tmp_path):
    st = TrialStore(str(tmp_path / "t.json"))
    # value 1.0 wins at small shapes, value 4.0 at large; at bucket 256
    # the log2-interpolated scores must cross over to 4.0
    st.add([
        make_trial(knob=WAIT_KNOB, value=1.0, score=1000.0, shape_rows=16),
        make_trial(knob=WAIT_KNOB, value=1.0, score=100.0, shape_rows=1024),
        make_trial(knob=WAIT_KNOB, value=4.0, score=200.0, shape_rows=16),
        make_trial(knob=WAIT_KNOB, value=4.0, score=900.0, shape_rows=1024),
    ])
    sel = Selector(st)
    k = tune.REGISTRY.get(WAIT_KNOB)
    assert sel.resolve(k, 16) == 1.0
    assert sel.resolve(k, 1024) == 4.0
    assert sel.resolve(k, 512) == 4.0      # nearer the large regime
    assert sel.resolve(k, 32) == 1.0       # nearer the small regime
    # min-mode knob: lower score wins
    st.add([
        make_trial(knob="sql.rowbucket.min", value=64, score=5.0),
        make_trial(knob="sql.rowbucket.min", value=256, score=9.0),
    ])
    assert sel.resolve(tune.REGISTRY.get("sql.rowbucket.min"), 1) == 64


def test_no_selection_inside_fenced_ab(tmp_path):
    """The acceptance probe: trials exist that WOULD move the knob, but
    inside the fence nothing is selected — the value already in effect
    (last pre-fence selection, else the default) is returned with the
    frozen reason, and nesting keeps the fence closed."""
    st = _store_with_wait_trials(tmp_path)
    sel = Selector(st)
    k = tune.REGISTRY.get(WAIT_KNOB)
    with tune.active(sel):
        # no pre-fence selection yet: frozen resolves pin the DEFAULT
        with tune.ab_fence():
            assert tune.fence_active()
            assert tune.knob(WAIT_KNOB, 64) == k.default
            assert sel.explain(WAIT_KNOB)["reason"] == \
                tune.REASON_FROZEN_FENCED
            with tune.ab_fence():               # nested: still fenced
                assert tune.knob(WAIT_KNOB, 64) == k.default
        assert not tune.fence_active()
        # selection outside the fence moves it...
        assert tune.knob(WAIT_KNOB, 64) == 0.0
        # ...and a later fence pins THAT value, still without selecting
        with tune.ab_fence():
            assert tune.knob(WAIT_KNOB, 64) == 0.0
            assert sel.explain(WAIT_KNOB)["reason"] == \
                tune.REASON_FROZEN_FENCED


# ================================================================== store
def test_store_round_trip_and_content_hash_dedup(tmp_path):
    st = _store_with_wait_trials(tmp_path)
    assert len(st) == 3
    # same observation again: content hash dedups, document unchanged
    before = open(st.path, "rb").read()
    assert st.add([make_trial(knob=WAIT_KNOB, value=0.0, score=9000.0,
                              shape_rows=64,
                              metric="span:serve.request")]) == 0
    assert open(st.path, "rb").read() == before
    assert len(TrialStore(st.path)) == 3


@pytest.mark.chaos
def test_killed_store_commit_replays_exactly_once(tmp_path):
    """Kill the durable commit, replay the add: the resumed store must
    be BYTE-identical to one that never crashed."""
    base = [make_trial(knob=WAIT_KNOB, value=2.0, score=400.0)]
    extra = [make_trial(knob=WAIT_KNOB, value=0.0, score=9000.0)]

    ref = TrialStore(str(tmp_path / "ref.json"))
    ref.add(base)
    ref.add(extra)

    st = TrialStore(str(tmp_path / "t.json"))
    st.add(base)
    plan = faults.FaultPlan().crash("tune.store.commit")
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash):
            st.add(extra)
    assert plan.fired("tune.store.commit") == 1
    # the kill landed before the tmp write: previous document intact
    resumed = TrialStore(str(tmp_path / "t.json"))
    assert len(resumed) == 1
    resumed.add(extra)                          # the replay
    assert open(resumed.path, "rb").read() == open(ref.path, "rb").read()


# ============================================================ live retune
class _Holder:
    def __init__(self, value):
        self.value = value

    def apply(self, v):
        self.value = v


def _retuner(tmp_path, st, holder):
    sel = Selector(st)
    return LiveRetuner(
        WAIT_KNOB, journal_path=str(tmp_path / "retune.journal"),
        apply_fn=holder.apply, selector=sel, convert=lambda ms: ms / 1e3,
    )


def test_live_retune_applies_journals_and_resumes(tmp_path):
    st = _store_with_wait_trials(tmp_path)
    holder = _Holder(0.002)
    rt = _retuner(tmp_path, st, holder)
    out = rt.retune(shape_rows=64)
    assert out["applied"] and out["old"] == 2.0 and out["new"] == 0.0
    assert out["reason"].startswith(tune.REASON_TUNED_PREFIX)
    assert holder.value == 0.0                  # converted ms → s
    kinds = [e["kind"] for e in read_lines(rt.journal_path)]
    assert kinds == ["intent", "commit"]
    # a fresh process resumes the COMMITTED value through the journal
    holder2 = _Holder(0.002)
    rt2 = _retuner(tmp_path, st, holder2)
    assert rt2.resume() == 0.0
    assert holder2.value == 0.0 and rt2.current == 0.0
    # steady state: re-selecting the same value applies nothing new
    out2 = rt2.retune(shape_rows=64)
    assert not out2["applied"]
    assert [e["kind"] for e in read_lines(rt.journal_path)] == kinds


def test_live_observe_feeds_the_store(tmp_path):
    st = TrialStore(str(tmp_path / "t.json"))
    holder = _Holder(0.002)
    rt = _retuner(tmp_path, st, holder)
    t = rt.observe(1234.5, shape_rows=128, meta={"phase": "midday"})
    assert t["source"] == "live" and t["value"] == 2.0
    assert TrialStore(st.path).trials(knob=WAIT_KNOB)[0]["meta"] == \
        {"phase": "midday"}


@pytest.mark.chaos
def test_killed_live_retune_leaves_previous_value_serving(tmp_path):
    """Kill between intent and apply: the old value keeps serving, the
    journal shows an uncommitted intent, and resume() ignores it."""
    st = _store_with_wait_trials(tmp_path)
    holder = _Holder(0.002)
    rt = _retuner(tmp_path, st, holder)
    plan = faults.FaultPlan().crash("tune.select.apply")
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash):
            rt.retune(shape_rows=64)
    assert plan.fired("tune.select.apply") == 1
    assert holder.value == 0.002                # previous value serving
    assert [e["kind"] for e in read_lines(rt.journal_path)] == ["intent"]
    # restart: the uncommitted intent must NOT be replayed
    holder2 = _Holder(0.002)
    rt2 = _retuner(tmp_path, st, holder2)
    assert rt2.resume() is None
    assert holder2.value == 0.002 and rt2.current == 2.0
    # the retry (no fault) completes the move
    out = rt2.retune(shape_rows=64)
    assert out["applied"] and holder2.value == 0.0
    assert [e["kind"] for e in read_lines(rt.journal_path)] == \
        ["intent", "intent", "commit"]


def test_live_retune_is_frozen_inside_fence(tmp_path):
    st = _store_with_wait_trials(tmp_path)
    holder = _Holder(0.002)
    rt = _retuner(tmp_path, st, holder)
    with tune.ab_fence():
        out = rt.retune(shape_rows=64)
    assert not out["applied"]
    assert out["reason"] == tune.REASON_FROZEN_FENCED
    assert holder.value == 0.002
    assert not os.path.exists(rt.journal_path)  # nothing even journaled
