"""Pipeline / PipelineModel — the pyspark.ml.Pipeline composition contract:
stage chaining through Table → AssembledTable → DeviceDataset, estimator
stages replaced by their fitted models, full-chain persistence."""

import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht


def _split(table):
    return ht.train_test_split(table, 0.7, 42)


@pytest.mark.fast
def test_supervised_pipeline_matches_manual_chain(hospital_table, mesh8):
    train, test = _split(hospital_table)
    pipe = ht.Pipeline(
        [
            ht.VectorAssembler(ht.FEATURE_COLS),
            ht.StandardScaler(),
            ht.LinearRegression(),
        ]
    )
    pm = pipe.fit(train, mesh=mesh8)
    assert isinstance(pm, ht.PipelineModel)
    assert len(pm.stages) == 3

    # manual chain, same stages by hand
    asm = ht.VectorAssembler(ht.FEATURE_COLS)
    a_train = asm.transform(train)
    scaler = ht.StandardScaler().fit(a_train)
    lr = ht.LinearRegression().fit(scaler.transform(a_train), mesh=mesh8)

    np.testing.assert_allclose(
        np.asarray(pm.stages[2].coefficients),
        np.asarray(lr.coefficients),
        rtol=1e-6,
    )

    # end-to-end transform on the raw test Table → PredictionResult
    pred = pm.transform(test, mesh=mesh8)
    rmse = ht.RegressionEvaluator("rmse").evaluate(pred)
    manual = ht.RegressionEvaluator("rmse").evaluate(
        lr.transform(scaler.transform(asm.transform(test)), mesh=mesh8)
    )
    np.testing.assert_allclose(rmse, manual, rtol=1e-6)
    assert rmse < 0.2  # noise sigma 0.1 — the chain actually learned


def test_classification_pipeline_with_binarizer(hospital_table, mesh8):
    train, test = _split(hospital_table)
    pipe = ht.Pipeline(
        [
            ht.Binarizer("length_of_stay", "LOS_binary", 5.0),
            ht.VectorAssembler(ht.FEATURE_COLS),
            ht.DecisionTreeClassifier(max_depth=4, label_col="LOS_binary"),
        ]
    )
    pm = pipe.fit(train, label_col="LOS_binary", mesh=mesh8)
    pred = pm.transform(test, label_col="LOS_binary", mesh=mesh8)
    acc = ht.MulticlassClassificationEvaluator("accuracy").evaluate(pred)
    assert acc > 0.85


def test_clustering_pipeline_appends_prediction_column(hospital_table, mesh8):
    pipe = ht.Pipeline(
        [
            ht.VectorAssembler(ht.FEATURE_COLS),
            ht.StandardScaler(),
            ht.KMeans(k=4, seed=0),
        ]
    )
    pm = pipe.fit(hospital_table, mesh=mesh8)
    out = pm.transform(hospital_table, mesh=mesh8)
    # ClusteringModel.transform(AssembledTable) → source Table + prediction
    assert isinstance(out, ht.Table)
    assert "prediction" in out.schema
    p = out.column("prediction")
    assert p.shape == (len(hospital_table),)
    assert set(np.unique(p)) <= set(range(4))


def test_string_indexer_stage(hospital_table, mesh8):
    pipe = ht.Pipeline(
        [
            ht.StringIndexer("hospital_id", "hospital_idx"),
            ht.VectorAssembler(ht.FEATURE_COLS + ("hospital_idx",)),
            ht.LinearRegression(),
        ]
    )
    pm = pipe.fit(hospital_table, mesh=mesh8)
    # the indexer stage was fitted into its model
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.features.indexer import (
        StringIndexerModel,
    )

    assert isinstance(pm.stages[0], StringIndexerModel)
    assert len(pm.stages[2].coefficients) == 5


def test_pipeline_save_load_roundtrip(hospital_table, mesh8, tmp_path):
    train, test = _split(hospital_table)
    pm = ht.Pipeline(
        [
            ht.VectorAssembler(ht.FEATURE_COLS),
            ht.StandardScaler(),
            ht.LinearRegression(),
        ]
    ).fit(train, mesh=mesh8)
    path = os.path.join(tmp_path, "pm")
    pm.write().overwrite().save(path)

    for loader in (ht.load_pipeline_model, ht.load_model):
        back = loader(path)
        assert isinstance(back, ht.PipelineModel)
        assert [type(s).__name__ for s in back.stages] == [
            type(s).__name__ for s in pm.stages
        ]
        p0, l0 = pm.transform(test, mesh=mesh8).to_numpy()
        p1, l1 = back.transform(test, mesh=mesh8).to_numpy()
        np.testing.assert_allclose(p0, p1, rtol=1e-6)
        np.testing.assert_allclose(l0, l1)

    with pytest.raises(FileExistsError):
        pm.save(path, overwrite=False)


def test_feature_stage_artifacts_roundtrip(hospital_table, tmp_path):
    """Every feature stage persists standalone through the model registry
    (Spark's MLWritable on feature transformers)."""
    asm = ht.VectorAssembler(ht.FEATURE_COLS)
    a = asm.transform(hospital_table)
    stages = [
        asm,
        ht.Binarizer("length_of_stay", "LOS_binary", 5.0),
        ht.StringIndexer("hospital_id", "idx").fit(hospital_table),
        ht.StandardScaler().fit(a),
    ]
    for i, st in enumerate(stages):
        name, meta, arrays = st._artifacts()
        p = os.path.join(tmp_path, f"s{i}")
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io.model_io import (
            load_model as lm,
            save_model as sm,
        )

        sm(p, name, meta, arrays)
        back = lm(p)
        assert type(back) is type(st)
    # the scaler round-trips its arrays exactly
    back = lm(os.path.join(tmp_path, "s3"))
    np.testing.assert_allclose(back.mean, stages[3].mean)
    np.testing.assert_allclose(back.std, stages[3].std)


def test_device_dataset_scaler_chain(hospital_table, mesh8):
    """The scaler stage consumes a DeviceDataset mid-chain (features scaled
    in place on the mesh, labels/weights carried through)."""
    a = ht.VectorAssembler(ht.FEATURE_COLS).transform(hospital_table)
    ds = a.to_device(mesh=mesh8)
    pm = ht.Pipeline([ht.StandardScaler(), ht.KMeans(k=3, seed=0)]).fit(
        ds, mesh=mesh8
    )
    # parity with the AssembledTable route
    pm2 = ht.Pipeline(
        [ht.VectorAssembler(ht.FEATURE_COLS), ht.StandardScaler(), ht.KMeans(k=3, seed=0)]
    ).fit(hospital_table, mesh=mesh8)
    np.testing.assert_allclose(
        np.sort(np.asarray(pm.stages[1].cluster_centers), axis=0),
        np.sort(np.asarray(pm2.stages[2].cluster_centers), axis=0),
        atol=1e-5,
    )


def test_nested_pipeline_roundtrip(hospital_table, mesh8, tmp_path):
    """A PipelineModel can itself be a stage of a saved pipeline (Spark
    nests pipelines; persistence recurses into the composite layout)."""
    train, test = _split(hospital_table)
    feats = ht.Pipeline(
        [ht.VectorAssembler(ht.FEATURE_COLS), ht.StandardScaler()]
    ).fit(train)
    outer = ht.Pipeline([feats, ht.LinearRegression()]).fit(train, mesh=mesh8)
    path = os.path.join(tmp_path, "nested")
    outer.save(path)
    back = ht.load_model(path)
    assert isinstance(back.stages[0], ht.PipelineModel)
    p0, _ = outer.transform(test, mesh=mesh8).to_numpy()
    p1, _ = back.transform(test, mesh=mesh8).to_numpy()
    np.testing.assert_allclose(p0, p1, rtol=1e-6)


def test_unpersistable_stage_raises(hospital_table, mesh8, tmp_path):
    class Opaque:
        def transform(self, data):
            return data

    pm = ht.Pipeline([Opaque(), ht.VectorAssembler(ht.FEATURE_COLS),
                      ht.LinearRegression()]).fit(hospital_table, mesh=mesh8)
    with pytest.raises(TypeError, match="not persistable"):
        pm.save(os.path.join(tmp_path, "x"))


def test_failed_save_preserves_existing_artifact(hospital_table, mesh8, tmp_path):
    """Save validates all stages before touching the target path: a failed
    overwrite never deletes the previously saved good artifact."""
    class Opaque:
        def transform(self, data):
            return data

    path = os.path.join(tmp_path, "pm")
    good = ht.Pipeline(
        [ht.VectorAssembler(ht.FEATURE_COLS), ht.LinearRegression()]
    ).fit(hospital_table, mesh=mesh8)
    good.save(path)
    bad = ht.Pipeline([Opaque(), ht.VectorAssembler(ht.FEATURE_COLS),
                       ht.LinearRegression()]).fit(hospital_table, mesh=mesh8)
    with pytest.raises(TypeError, match="not persistable"):
        bad.save(path, overwrite=True)
    # the old artifact still loads
    back = ht.load_pipeline_model(path)
    assert len(back.stages) == 2

    # validation recurses into nested pipelines: an unpersistable stage
    # buried one level down must also fail BEFORE the old artifact is
    # touched
    inner = ht.Pipeline([Opaque(), ht.VectorAssembler(ht.FEATURE_COLS)]).fit(
        hospital_table
    )
    nested_bad = ht.Pipeline([inner, ht.LinearRegression()]).fit(
        hospital_table, mesh=mesh8
    )
    with pytest.raises(TypeError, match="not persistable"):
        nested_bad.save(path, overwrite=True)
    back = ht.load_pipeline_model(path)
    assert len(back.stages) == 2


def test_stage_without_fit_or_transform_raises(hospital_table):
    with pytest.raises(TypeError, match="neither"):
        ht.Pipeline([object()]).fit(hospital_table)
