"""Survivable history (ISSUE 18): seal/retire/scrub lifecycle over the
unbounded table, zone-map scan pruning, disk-exhaustion faults, and the
disk-budget degradation ladder.

The kill-and-resume tests at the ``table.seal.*`` / ``table.retire.*`` /
``table.scrub.*`` boundaries live with the rest of the kill matrix in
``tests/test_chaos.py``; this file covers the steady-state contracts —
snapshot identity across sealing, CRC bitrot detection/quarantine/
rebuild, pruning parity, ENOSPC degradation at three sites, and
``disk:budget`` backpressure/quarantine while reads keep serving.
"""

import errno
import json
import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core import (
    sql as core_sql,
    sql_fuzz,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.segments import (
    SegmentCorruptError,
    segment_may_match,
    zone_maps,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.sql_views import (
    ViewRegistry,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table_lifecycle import (
    RetentionPolicy,
    TableLifecycle,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import (
    FitCheckpointer,
    write_csv,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.obs.registry import (
    global_registry,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming import (
    FileStreamSource,
    StreamCheckpoint,
    StreamExecution,
    UnboundedTable,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming.microbatch import (
    BATCH_QUARANTINED,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming.unbounded_table import (
    DiskBudgetExceeded,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils import faults
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils.retry import (
    RetryPolicy,
)

pytestmark = pytest.mark.fast

POLICY = RetentionPolicy(min_seal_batches=2, hot_batches=2,
                         max_segment_batches=3)
KEEP_PARTS = RetentionPolicy(min_seal_batches=2, hot_batches=2,
                             max_segment_batches=3, retire_parts=False)


def _batch(bid, n=6):
    """Batch ``bid``'s rows: i1 lives in [bid*10, bid*10+n) so zone maps
    are disjoint per batch and pruning is decidable per segment."""
    t1 = (
        np.datetime64("2025-03-31T22:00:00") + np.timedelta64(bid, "h")
        + np.arange(n).astype("timedelta64[s]")
    ).astype("datetime64[ns]")
    return ht.Table.from_dict({
        "f1": np.arange(n, dtype=np.float64) + bid,
        "i1": np.arange(n) + bid * 10,
        "t1": t1,
    })


def _mk_table(tmp_path, n_batches=8, **kw):
    tbl = UnboundedTable(
        str(tmp_path / "tbl"), _batch(0, 1).schema, name="events", **kw
    )
    for bid in range(n_batches):
        tbl.append_batch(_batch(bid), bid)
    return tbl


def _bit_identical(a, b):
    assert list(a.columns) == list(b.columns)
    assert len(a) == len(b)
    for c in a.columns:
        assert a.column(c).dtype == b.column(c).dtype, c
        if a.column(c).dtype == object:  # strings: pointers aren't bytes
            assert a.column(c).tolist() == b.column(c).tolist(), c
        else:
            assert a.column(c).tobytes() == b.column(c).tobytes(), c


def _flip(path, at=None):
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2 if at is None else at] ^= 0x01
    with open(path, "wb") as f:
        f.write(bytes(blob))


def _counter(name):
    return global_registry().counters.get(name, 0.0)


# ============================================================ seal/retire
def test_seal_retire_preserves_snapshot_rows_and_order(tmp_path):
    tbl = _mk_table(tmp_path)
    ref = tbl.read()
    sealed0 = _counter("table.segments_sealed")
    retired0 = _counter("table.parts_retired")
    out = TableLifecycle(tbl, POLICY).tick()
    assert out["sealed"] == 2 and out["retired"] == 6
    assert _counter("table.segments_sealed") - sealed0 == 2
    assert _counter("table.parts_retired") - retired0 == 6
    reopened = UnboundedTable(tbl.path, tbl.schema)
    _bit_identical(reopened.read(), ref)
    # the hot tail stays as parts; the sealed cold prefix lost its parts
    left = sorted(f for f in os.listdir(tbl.path) if f.startswith("part-"))
    assert left == ["part-0000000006.parquet", "part-0000000007.parquet"]
    assert reopened.num_rows() == len(ref)


def test_seal_is_idempotent_and_respects_min_batches(tmp_path):
    tbl = _mk_table(tmp_path)
    lc = TableLifecycle(tbl, POLICY)
    assert lc.seal() == 2
    assert lc.seal() == 0  # nothing cold left uncovered
    tall = TableLifecycle(
        tbl, RetentionPolicy(min_seal_batches=64, hot_batches=2)
    )
    assert tall.seal() == 0  # below the minimum worth sealing


def test_seal_watermark_keeps_hot_event_times_unsealed(tmp_path):
    tbl = _mk_table(tmp_path)
    pol = RetentionPolicy(min_seal_batches=1, hot_batches=0,
                          max_segment_batches=2, watermark_column="t1")
    # watermark between batch 3 and 4: only event-time-cold batches seal
    wm = np.datetime64("2025-03-31T22:00:00") + np.timedelta64(4, "h")
    TableLifecycle(tbl, pol).seal(watermark=wm)
    covered = set()
    for s in tbl._committed_state()[1]:
        covered.update(int(b["batch_id"]) for b in s["batches"])
    assert covered == {0, 1, 2, 3}


def test_empty_and_replayed_batches_survive_sealing(tmp_path):
    tbl = _mk_table(tmp_path, n_batches=6)
    tbl.append_batch(_batch(6, n=0), 6)          # empty committed batch
    TableLifecycle(tbl, RetentionPolicy(min_seal_batches=2, hot_batches=0,
                                        max_segment_batches=8)).tick()
    ref_rows = tbl.num_rows()
    # replay a SEALED batch with different rows: the later commit entry
    # supersedes the sealed copy, and retention must not delete it
    new = _batch(2).with_column("f1", np.full(6, 999.0))
    tbl.append_batch(new, 2)
    reopened = UnboundedTable(tbl.path, tbl.schema)
    snap = reopened.read()
    assert len(snap) == ref_rows
    assert int((snap["f1"] == 999.0).sum()) == 6
    TableLifecycle(reopened, POLICY).retire()
    assert os.path.exists(os.path.join(tbl.path, "part-0000000002.parquet"))
    _bit_identical(UnboundedTable(tbl.path, tbl.schema).read(), snap)


# ================================================================= scrub
def test_scrub_detects_bitflip_quarantines_and_rebuilds(tmp_path):
    tbl = _mk_table(tmp_path)
    ref = tbl.read()
    TableLifecycle(tbl, KEEP_PARTS).seal()
    seg = sorted(f for f in os.listdir(tbl.segments_dir)
                 if f.endswith(".parquet"))[0]
    _flip(os.path.join(tbl.segments_dir, seg))
    repairs0 = _counter("table.scrub_repairs")
    out = TableLifecycle(tbl, KEEP_PARTS).scrub()
    assert out == {"checked": 2, "repaired": 1, "quarantined": 0}
    assert _counter("table.scrub_repairs") - repairs0 == 1
    # rotten bytes moved aside as evidence, rebuilt segment serves
    assert any(f.endswith(".quarantine")
               for f in os.listdir(tbl.segments_dir))
    reopened = UnboundedTable(tbl.path, tbl.schema)
    _bit_identical(reopened.read(), ref)
    # and the rebuilt segment now passes a clean scrub
    assert TableLifecycle(reopened, KEEP_PARTS).scrub()["repaired"] == 0


def test_scrub_with_retired_parts_quarantines_loudly(tmp_path):
    tbl = _mk_table(tmp_path)
    TableLifecycle(tbl, POLICY).tick()  # parts retired: no rebuild source
    seg = sorted(f for f in os.listdir(tbl.segments_dir)
                 if f.endswith(".parquet"))[0]
    _flip(os.path.join(tbl.segments_dir, seg))
    with pytest.raises(SegmentCorruptError, match="no surviving parts"):
        TableLifecycle(tbl).scrub()
    # the loss is recorded in the log and reads stay loud, never silent
    entries = UnboundedTable(tbl.path, tbl.schema)._log_entries()
    assert any(
        e.get("scrub", {}).get("action") == "quarantine" for e in entries
    )
    with pytest.raises(SegmentCorruptError):
        UnboundedTable(tbl.path, tbl.schema).read()


def test_rotten_segment_read_falls_back_to_surviving_parts(tmp_path):
    tbl = _mk_table(tmp_path)
    ref = tbl.read()
    TableLifecycle(tbl, KEEP_PARTS).seal()
    for seg in os.listdir(tbl.segments_dir):
        if seg.endswith(".parquet"):
            _flip(os.path.join(tbl.segments_dir, seg))
    reopened = UnboundedTable(tbl.path, tbl.schema)
    _bit_identical(reopened.read(), ref)  # all rot, all parts survive


# =============================================================== pruning
def test_zone_map_evaluator_is_conservative():
    zones = zone_maps(_batch(3))  # i1 in [30, 36), f1 in [3, 9)
    assert not segment_may_match(zones, ("cmp", "i1", ">=", 100))
    assert segment_may_match(zones, ("cmp", "i1", ">=", 31))
    assert not segment_may_match(zones, ("cmp", "i1", "=", 7))
    assert segment_may_match(zones, ("not", ("cmp", "i1", "=", 31)))
    assert not segment_may_match(zones, ("between", "i1", 40, 50))
    assert not segment_may_match(zones, ("in", "i1", (7, 99)))
    assert segment_may_match(zones, ("in", "i1", (7, 32)))
    assert segment_may_match(zones, ("isnull", "f1"))  # never pruned
    assert segment_may_match(zones, ("unknown-shape", "x"))  # conservative
    # and/or compose; NOT pushes through De Morgan
    assert not segment_may_match(
        zones, ("and", ("cmp", "i1", ">", 100), ("cmp", "f1", ">", 0)))
    assert segment_may_match(
        zones, ("or", ("cmp", "i1", ">", 100), ("cmp", "f1", ">", 0)))
    # a column with nulls never prunes negative-polarity predicates
    nz = zone_maps(ht.Table.from_dict({"f1": np.array([1.0, np.nan])}))
    assert segment_may_match(nz, ("cmp", "f1", "!=", 1.0))
    assert segment_may_match(nz, ("notin", "f1", (1.0,)))


def test_pruned_scan_matches_interpreter_and_reports_stats(tmp_path):
    tbl = _mk_table(tmp_path, n_batches=10)
    TableLifecycle(tbl, POLICY).tick()
    resolve = lambda _n: tbl.read()
    q = "SELECT i1, f1 FROM events WHERE i1 >= 65"  # only the hot tail
    full = core_sql.execute(q, resolve, mode="interpret")
    auto = core_sql.execute(q, resolve, mode="auto")
    assert core_sql.last_dispatch().route == "compiled"
    _bit_identical(auto, full)
    info = core_sql.explain(q, resolve)
    assert info["route"] == "compiled"
    prune = info["prune"]
    # 8 cold batches chunk into segments [0-2][3-5][6-7]; i1 >= 65 lands
    # in batch 6's zone, so exactly the first two segments prune away
    assert prune["segments"] == 3 and prune["segments_pruned"] == 2
    assert prune["rows_pruned"] == 36
    # a filter zone maps cannot decide prunes nothing and still matches
    q2 = "SELECT i1 FROM events WHERE f1 != 3.0"
    _bit_identical(
        core_sql.execute(q2, resolve, mode="auto"),
        core_sql.execute(q2, resolve, mode="interpret"),
    )
    # pinned reads prune against the pinned assembly only
    q3 = "SELECT i1 FROM events WHERE i1 < 25"
    pinned = core_sql.execute(
        q3, lambda _n: tbl.read(upto_batch_id=4), mode="auto"
    )
    _bit_identical(
        pinned,
        core_sql.execute(q3, lambda _n: tbl.read(upto_batch_id=4),
                         mode="interpret"),
    )


def test_prune_key_absent_for_plain_tables_and_filterless_queries(tmp_path):
    tbl = _mk_table(tmp_path)
    TableLifecycle(tbl, POLICY).tick()
    plain = _batch(0)
    assert "prune" not in core_sql.explain(
        "SELECT i1 FROM events WHERE i1 > 3", lambda _n: plain
    )
    assert "prune" not in core_sql.explain(
        "SELECT i1 FROM events", lambda _n: tbl.read()
    )


def test_all_segments_pruned_yields_empty_result(tmp_path):
    tbl = _mk_table(tmp_path, n_batches=6)
    TableLifecycle(
        tbl, RetentionPolicy(min_seal_batches=2, hot_batches=0,
                             max_segment_batches=8)
    ).tick()
    resolve = lambda _n: tbl.read()
    q = "SELECT i1, f1 FROM events WHERE i1 > 1000"
    out = core_sql.execute(q, resolve, mode="auto")
    assert len(out) == 0
    _bit_identical(out, core_sql.execute(q, resolve, mode="interpret"))


# ====================================================== ENOSPC degradation
@pytest.mark.chaos
def test_enospc_at_seal_commit_degrades_and_resumes(tmp_path):
    tbl = _mk_table(tmp_path)
    ref = tbl.read()
    plan = faults.FaultPlan().disk_full("table.seal.commit")
    with faults.active(plan):
        with pytest.raises(OSError) as ei:
            TableLifecycle(tbl, POLICY).tick()
    assert ei.value.errno == errno.ENOSPC
    assert plan.fired("table.seal.commit") == 1
    reopened = UnboundedTable(tbl.path, tbl.schema)
    _bit_identical(reopened.read(), ref)      # committed state intact
    TableLifecycle(reopened, POLICY).tick()   # retry once space exists
    _bit_identical(UnboundedTable(tbl.path, tbl.schema).read(), ref)


@pytest.mark.chaos
def test_enospc_at_fit_ckpt_save_keeps_previous_step(tmp_path):
    ck = FitCheckpointer(str(tmp_path / "ck"), {"algo": "demo"})
    ck.save(1, {"w": np.arange(4.0)})
    plan = faults.FaultPlan().disk_full("fit_ckpt.save.arrays")
    with faults.active(plan):
        with pytest.raises(OSError) as ei:
            ck.save(2, {"w": np.arange(4.0) * 2})
    assert ei.value.errno == errno.ENOSPC
    step, arrays, _extra = FitCheckpointer(
        str(tmp_path / "ck"), {"algo": "demo"}
    ).resume()
    assert step == 1
    np.testing.assert_array_equal(arrays["w"], np.arange(4.0))


@pytest.mark.chaos
def test_enospc_at_stream_sink_retries_without_unhandled(tmp_path):
    incoming = tmp_path / "incoming"
    incoming.mkdir()
    base = np.datetime64("2025-03-31T22:00:00")
    t = ht.Table.from_dict(
        {
            "hospital_id": np.array(["H01"] * 12, dtype=object),
            "event_time": base + np.arange(12).astype("timedelta64[s]"),
            "admission_count": np.arange(12),
            "current_occupancy": np.full(12, 100),
            "emergency_visits": np.full(12, 5),
            "seasonality_index": np.full(12, 1.0),
            "length_of_stay": np.full(12, 4.0),
        },
        ht.hospital_event_schema(),
    )
    write_csv(t, str(incoming / "a.csv"))
    fast = RetryPolicy(max_attempts=3, base_delay_s=0.001, max_delay_s=0.01)
    exec_ = StreamExecution(
        source=FileStreamSource(str(incoming), ht.hospital_event_schema(),
                                retry=fast),
        sink=UnboundedTable(str(tmp_path / "table"),
                            ht.hospital_event_schema()),
        checkpoint=StreamCheckpoint(str(tmp_path / "ckpt")),
        max_batch_replays=3,
        replay_backoff=fast,
    )
    plan = faults.FaultPlan().disk_full("stream.after_sink")
    with faults.active(plan):
        info = exec_.run_once()  # ENOSPC on attempt 1, replay succeeds
    assert plan.fired("stream.after_sink") == 1
    assert info.num_appended_rows == 12
    assert exec_.checkpoint.quarantine_count() == 0
    assert exec_.sink.read().num_rows == 12


# ===================================================== disk-budget ladder
@pytest.mark.chaos
def test_disk_budget_backpressures_quarantines_and_keeps_serving(tmp_path):
    incoming = tmp_path / "incoming"
    incoming.mkdir()
    base = np.datetime64("2025-03-31T22:00:00")

    def _csv(name, n):
        t = ht.Table.from_dict(
            {
                "hospital_id": np.array(["H01"] * n, dtype=object),
                "event_time": base + np.arange(n).astype("timedelta64[s]"),
                "admission_count": np.arange(n),
                "current_occupancy": np.full(n, 100),
                "emergency_visits": np.full(n, 5),
                "seasonality_index": np.full(n, 1.0),
                "length_of_stay": np.full(n, 4.0),
            },
            ht.hospital_event_schema(),
        )
        write_csv(t, str(incoming / name))

    fast = RetryPolicy(max_attempts=3, base_delay_s=0.001, max_delay_s=0.01)

    def _exec(budget):
        return StreamExecution(
            source=FileStreamSource(str(incoming),
                                    ht.hospital_event_schema(), retry=fast),
            sink=UnboundedTable(str(tmp_path / "table"),
                                ht.hospital_event_schema(),
                                disk_budget_bytes=budget),
            checkpoint=StreamCheckpoint(str(tmp_path / "ckpt")),
            max_batch_replays=2,
            replay_backoff=fast,
        )

    exec_ = _exec(budget=1 << 20)
    _csv("a.csv", 10)
    assert exec_.run_once().num_appended_rows == 10
    committed = exec_.sink.read()

    # shrink the budget below current usage: the next batch must NOT
    # land; it backpressures (retries), then quarantines disk:budget
    exec2 = _exec(budget=64)
    _csv("b.csv", 10)
    info = exec2.run_once()
    assert info.status == BATCH_QUARANTINED
    qdir = tmp_path / "ckpt" / "quarantine"
    recs = [
        json.load(open(qdir / f))
        for f in os.listdir(qdir) if f.startswith("batch-")
    ]
    assert any(r["reason"] == "disk:budget" for r in recs)
    assert any("disk:budget" in r["error"] for r in recs)
    # committed state keeps answering — bit-identical to pre-breach
    _bit_identical(exec2.sink.read(), committed)
    counters = exec2.metrics.snapshot()["counters"]
    assert counters.get("stream.backpressure", 0) >= 1
    # and the typed error is what the sink actually raised
    with pytest.raises(DiskBudgetExceeded, match="disk:budget"):
        exec2.sink.append_batch(_batch(0), 99)


# ================================================= views over sealed history
def test_views_survive_part_retirement_without_rebuild(tmp_path):
    tbl = _mk_table(tmp_path, n_batches=0)
    reg = ViewRegistry()
    q = "SELECT i1, count(*) AS c, sum(f1) AS s FROM events GROUP BY i1"
    view = reg.register("agg", q, tbl)
    for bid in range(8):
        tbl.append_batch(_batch(bid), bid)
        reg.maintain(tbl, bid)
    full = core_sql.execute(q, lambda _n: tbl.read(), mode="interpret")

    rebuilds0 = _counter("sql.view.rebuilds")
    retract0 = _counter("sql.view.retractions")
    TableLifecycle(tbl, POLICY).tick()
    reg.maintain(tbl)  # refresh against the sealed/retired log
    assert _counter("sql.view.rebuilds") == rebuilds0
    assert _counter("sql.view.retractions") == retract0
    got = view.read()
    assert sql_fuzz.compare_tables(full, got) is None

    # a view registered AFTER retirement folds sealed slices (the parts
    # are gone) and still answers full history
    late = reg.register("agg_late", q, tbl)
    assert sql_fuzz.compare_tables(full, late.read()) is None
