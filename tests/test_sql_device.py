"""Device-resident SQL + fused feature plans (ISSUE 7, the Flare move).

Covers the split engine (parse → plan → execution): compiled-vs-
interpreter parity (targeted + fuzz), the paper's window-extract query
compiling with zero fallback nodes, the plan-executable cache's
zero-recompile contract (counter + jit-cache cross-check, the serve
discipline), the device-column no-re-transfer contract, and the fused
SQL → assemble → fit chain holding host syncs at a small constant.
"""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core import sql
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core import (
    sql_fuzz,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.sql import (
    SqlCompileUnsupported,
    execute,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.sql_compile import (
    bucket_for_rows,
    clear_executable_cache,
    compile_rowlevel,
    executable_cache_info,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import (
    Table,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils.profiling import (
    StageClock,
    host_sync_census,
)

pytestmark = pytest.mark.fast

WINDOW_QUERY = (
    "SELECT * FROM events WHERE event_time BETWEEN "
    "'2025-03-31 22:00:00' AND '2025-03-31 22:03:00'"
)


@pytest.fixture
def session(hospital_table):
    s = ht.Session.builder.app_name("sql-device-test").get_or_create()
    s.register_table("events", hospital_table)
    yield s
    s.stop()


def _parity(query, table, limit_slack=False):
    resolve = lambda _n: table  # noqa: E731
    ti = execute(query, resolve, mode="interpret")
    tc = execute(query, resolve, mode="compile")
    mismatch = sql_fuzz.compare_tables(ti, tc)
    assert mismatch is None, f"{query}: {mismatch}"
    return ti


# ------------------------------------------------------------- routing
def test_window_extract_compiles_no_fallback(session):
    """Satellite 2: the paper's exact window-extract shape
    (mllearnforhospitalnetwork.py:123-128) must compile end to end —
    zero fallback nodes."""
    info = session.sql_explain(WINDOW_QUERY)
    assert info["route"] == "compiled"
    assert info["fallback"] == []
    assert {n["op"] for n in info["nodes"]} == {"scan", "filter", "project"}
    out = session.sql(WINDOW_QUERY)
    assert sql.last_dispatch().route == "compiled"
    assert len(out) > 0


def test_window_extract_parity(session, hospital_table):
    out = _parity(WINDOW_QUERY, hospital_table)
    ref = hospital_table.between(
        "event_time", "2025-03-31 22:00:00", "2025-03-31 22:03:00"
    )
    assert len(out) == len(ref) > 0


def test_fallback_reasons_are_per_node(session):
    info = session.sql_explain(
        "SELECT hospital_id, length_of_stay FROM events "
        "WHERE hospital_id = 'H00' ORDER BY length_of_stay"
    )
    assert info["route"] == "interpreter"
    ops = dict(info["fallback"])
    assert "filter" in ops and "string" in ops["filter"]
    assert "sort" in ops
    # and the dispatcher actually recorded the interpreter route
    session.sql(
        "SELECT hospital_id FROM events WHERE hospital_id = 'H00'"
    )
    rec = sql.last_dispatch()
    assert rec.route == "interpreter"
    assert rec.reasons


def test_mode_compile_raises_on_unsupported(session, hospital_table):
    with pytest.raises(SqlCompileUnsupported, match="interpreter"):
        execute(
            "SELECT * FROM events ORDER BY length_of_stay",
            lambda _n: hospital_table,
            mode="compile",
        )


def test_dispatch_off_switch(session, monkeypatch):
    monkeypatch.setenv("CMLHN_SQL_COMPILE", "0")
    session.sql(WINDOW_QUERY)
    assert sql.last_dispatch().route == "interpreter"
    # the kill switch covers the fused path and explain too (review
    # finding: compile_rowlevel used to bypass it)
    ds = session.sql_to_device(WINDOW_QUERY, label_col="length_of_stay")
    assert sql.last_dispatch().route == "interpreter"
    assert float(np.asarray(ds.count())) > 0
    assert session.sql_explain(WINDOW_QUERY)["route"] == "interpreter"


# ------------------------------------------------------- fuzz parity
def test_fuzz_parity_green():
    """Satellite 1: N random queries over random tables, compiled ==
    interpreter; mismatches would arrive pre-shrunk to a minimal repro.
    24 queries in tier-1 (each distinct plan is a cold-cache XLA
    compile — budget); the slow-marked deep run covers 250."""
    failures = sql_fuzz.run_fuzz(n_queries=24, seed=0)
    assert failures == [], "\n".join(
        f"{q}  ->  {why}" for q, why in failures
    )


def test_string_group_key_parity():
    """String GROUP BY keys ride the compiled path as host-encoded
    sorted-rank dictionary codes (ISSUE 17): codes are order-isomorphic
    to the values — a row's code never depends on which other rows are
    present — so pre-filter encoding matches the interpreter's
    post-filter group order, nulls (None) fold to one trailing group,
    and multi-key mixes with numeric/timestamp columns lexsort
    identically on both paths."""
    rng = np.random.default_rng(5)
    n = 96
    s1 = np.array(
        [f"H{int(i):02d}" for i in rng.integers(0, 5, n)], dtype=object
    )
    s1[rng.random(n) < 0.15] = None
    table = Table.from_dict(
        {
            "s1": s1,
            "i1": rng.integers(-2, 4, n),
            "f1": rng.normal(size=n) * 10,
        }
    )
    for q in (
        "SELECT s1, count(*) AS c, sum(f1) AS s FROM events GROUP BY s1",
        "SELECT s1, avg(f1) AS a FROM events WHERE i1 >= 1 GROUP BY s1",
        "SELECT i1, s1, min(f1) AS lo FROM events GROUP BY i1, s1",
    ):
        _parity(q, table)  # mode="compile" raises if it fell back


@pytest.mark.slow
def test_fuzz_parity_deep():
    failures = sql_fuzz.run_fuzz(n_queries=250, seed=7)
    assert failures == [], "\n".join(
        f"{q}  ->  {why}" for q, why in failures
    )


def test_fuzz_shrinker_minimizes(monkeypatch):
    """The shrinker strips items/predicates that don't matter to a
    failure (here: an injected one keyed on f1 being selected)."""
    rng = np.random.default_rng(3)
    table = sql_fuzz.random_table(rng, 50)
    spec = sql_fuzz.QuerySpec(
        "rowlevel",
        ("f1", "f2", "i1"),
        ("bool", "AND", ("leaf", "i2 > 10"), ("leaf", "f2 < 1.0")),
        limit=5,
    )
    fake = lambda s, t: "boom" if "f1" in s.items else None  # noqa: E731
    monkeypatch.setattr(sql_fuzz, "check_spec", fake)
    small = sql_fuzz.shrink(spec, table)
    assert small.items == ("f1",)
    assert small.where is None and small.limit is None


# ------------------------------------------- executable cache discipline
def test_zero_recompiles_within_bucket(session, hospital_table):
    """Satellite 4: rerunning a plan at varying row counts inside one
    power-of-two bucket reuses the executable — build counter AND
    jit-cache size cross-check, serve's zero-recompile discipline."""
    clear_executable_cache()
    t = hospital_table
    for n in (100, 150, 37, 256):
        sub = t.limit(n)
        out = execute(WINDOW_QUERY, lambda _x: sub, mode="compile")
        assert bucket_for_rows(n) == 256
    info = executable_cache_info()
    assert info["kernels"] == 1
    assert info["builds"] == 1
    # one executable per kernel: n is a traced operand, not a static arg
    assert info["jit_entries"] == 1


def test_new_bucket_compiles_once(session, hospital_table):
    clear_executable_cache()
    execute(WINDOW_QUERY, lambda _x: hospital_table.limit(100), mode="compile")
    b1 = executable_cache_info()["builds"]
    execute(WINDOW_QUERY, lambda _x: hospital_table, mode="compile")  # 400 rows
    info = executable_cache_info()
    assert info["builds"] == b1 + 1  # bucket 512 is a new executable ...
    execute(WINDOW_QUERY, lambda _x: hospital_table, mode="compile")
    assert executable_cache_info()["builds"] == b1 + 1  # ... exactly once


def test_device_cache_no_retransfer(session, hospital_table):
    """Repeated queries over one Table snapshot re-transfer nothing: the
    second run does zero device_put and one batched device_get (the
    result materialization)."""
    q = (
        "SELECT admission_count + emergency_visits AS load FROM events "
        "WHERE length_of_stay > 3.0"
    )
    resolve = lambda _n: hospital_table  # noqa: E731
    execute(q, resolve, mode="compile")  # warm: cache fill + compile
    with host_sync_census(count_puts=True) as c:
        execute(q, resolve, mode="compile")
    assert c["device_put"] == 0
    assert c["device_get"] == 1
    cache = hospital_table.device_cache_info()
    assert cache["entries"], "device-column cache unexpectedly empty"


def test_unbounded_table_read_memoized(tmp_path):
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming.unbounded_table import (
        UnboundedTable,
    )

    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.schema import (
        FLOAT,
    )

    schema = ht.Schema([("v", FLOAT)])
    ut = UnboundedTable(str(tmp_path / "ut"), schema)
    ut.append_batch(Table.from_dict({"v": np.arange(4.0)}, schema), 0)
    t1 = ut.read()
    assert ut.read() is t1  # same snapshot → device cache survives
    ut.append_batch(Table.from_dict({"v": np.arange(2.0)}, schema), 1)
    t2 = ut.read()
    assert t2 is not t1 and len(t2) == 6


def test_unbounded_table_read_stat_fast_path(tmp_path, monkeypatch):
    """With the commit log unchanged, repeated reads skip the O(batches)
    log parse + part-stat sweep entirely (the memo KEY itself is cached
    against the log's stat); a new commit — or a same-count replay,
    which also appends a commit line — re-derives it and drops the
    stale snapshot."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming.unbounded_table import (
        UnboundedTable,
    )

    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.schema import (
        FLOAT,
    )

    schema = ht.Schema([("v", FLOAT)])
    ut = UnboundedTable(str(tmp_path / "ut"), schema)
    ut.append_batch(Table.from_dict({"v": np.arange(4.0)}, schema), 0)
    t1 = ut.read()
    calls = {"n": 0}
    orig = ut.committed_batches

    def counting():
        calls["n"] += 1
        return orig()

    monkeypatch.setattr(ut, "committed_batches", counting)
    for _ in range(5):
        assert ut.read() is t1
    assert calls["n"] == 0  # stat-only: no log parse, no part stats
    ut.append_batch(Table.from_dict({"v": np.arange(3.0, 6.0)}, schema), 0)
    t2 = ut.read()  # same-count replay appended a commit line
    assert calls["n"] == 1
    assert t2 is not t1
    assert float(t2.column("v")[0]) == 3.0  # the replayed bytes, not stale


# ------------------------------------------------------ fused assembly
def test_fused_assemble_matches_host_path(session, hospital_table):
    clock = StageClock()
    ds = session.sql_to_device(
        WINDOW_QUERY, label_col="length_of_stay", clock=clock
    )
    assert sql.last_dispatch().route == "compiled"
    host = session.sql(WINDOW_QUERY).na_drop()
    assert float(np.asarray(ds.count())) == len(host)
    # stage evidence threaded through the chain
    assert {"transfer", "sql", "assemble"} <= set(clock.seconds)

    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models import (
        LinearRegression,
    )

    m_dev = LinearRegression().fit(ds)
    m_host = LinearRegression().fit(
        ht.VectorAssembler(ht.FEATURE_COLS).transform(host),
        label_col="length_of_stay",
    )
    np.testing.assert_allclose(
        np.asarray(m_dev.coefficients),
        np.asarray(m_host.coefficients),
        rtol=5e-4, atol=5e-5,
    )


def test_fused_na_drop_zeroes_invalid_rows(session):
    n = 64
    rng = np.random.default_rng(0)
    f = rng.normal(size=n)
    f[::7] = np.nan
    t = Table.from_dict({"a": f, "b": rng.normal(size=n), "y": rng.normal(size=n)})
    s = ht.Session.builder.get_or_create()
    s.register_table("tt", t)
    try:
        ds = s.sql_to_device(
            "SELECT * FROM tt", feature_cols=("a", "b"), label_col="y"
        )
        x, w = np.asarray(ds.x), np.asarray(ds.w)
        expected_valid = int(np.sum(~np.isnan(f)))
        assert int(w.sum()) == expected_valid
        assert np.all(np.isfinite(x))  # NaN rows zero-filled, never NaN
        assert np.all(x[w == 0] == 0)
    finally:
        s.stop()


def test_compact_gather_parity(session, hospital_table):
    """The opt-in on-device compaction (decision record in
    VectorAssembler.transform_device) keeps rows, order, and weights."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.features.assembler import (
        VectorAssembler,
    )

    view = compile_rowlevel(WINDOW_QUERY, session.table)
    asm = VectorAssembler(ht.FEATURE_COLS)
    ds_pad = asm.transform_device(view, label_col="length_of_stay")
    ds_cmp = asm.transform_device(
        view, label_col="length_of_stay", compact=True
    )
    xp, wp = np.asarray(ds_pad.x), np.asarray(ds_pad.w)
    xc, wc, yc = np.asarray(ds_cmp.x), np.asarray(ds_cmp.w), np.asarray(ds_cmp.y)
    nv = int(wp.sum())
    assert int(wc.sum()) == nv
    assert ds_cmp.n_padded <= ds_pad.n_padded
    # valid rows, in source order, bit-identical; tail fully zeroed
    np.testing.assert_array_equal(xc[:nv], xp[wp > 0])
    assert np.all(xc[nv:] == 0) and np.all(wc[nv:] == 0) and np.all(yc[nv:] == 0)


def test_fused_falls_back_outside_subset(session):
    # a string GROUP BY cannot fuse — the host fallback must still
    # produce a working dataset
    ds = session.sql_to_device(
        "SELECT * FROM events WHERE hospital_id = 'H00'",
        label_col="length_of_stay",
    )
    assert sql.last_dispatch().route == "interpreter"
    assert float(np.asarray(ds.count())) > 0


def test_sql_transformer_compiled_route(session, hospital_table):
    tr = ht.SQLTransformer(
        "SELECT *, (admission_count + emergency_visits) AS load "
        "FROM __THIS__ WHERE length_of_stay > 2.0"
    )
    info = tr.explain(hospital_table)
    assert info["route"] == "compiled"
    out = tr.transform(hospital_table)
    assert sql.last_dispatch().route == "compiled"
    ref = hospital_table.mask(hospital_table.column("length_of_stay") > 2.0)
    np.testing.assert_array_equal(
        out.column("load"),
        ref.column("admission_count") + ref.column("emergency_visits"),
    )


def test_streaming_sql_feature_stage(hospital_table):
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming.pipeline import (
        make_sql_feature_stage,
    )

    stage = make_sql_feature_stage(
        "SELECT * FROM __THIS__ WHERE length_of_stay > 2.0",
        ht.FEATURE_COLS,
        label_col="length_of_stay",
    )
    x, y = stage(hospital_table)
    ref = hospital_table.mask(hospital_table.column("length_of_stay") > 2.0)
    assert x.dtype == np.float32 and y.dtype == np.float32
    assert x.shape == (len(ref), len(ht.FEATURE_COLS))
    np.testing.assert_allclose(
        y, ref.column("length_of_stay").astype(np.float32)
    )


# ------------------------------------------------------------ edge cases
def test_empty_table_and_empty_result(session):
    t = Table.from_dict(
        {"a": np.empty(0, np.float64), "b": np.empty(0, np.int64)}
    )
    for q in (
        "SELECT a, b FROM t0 WHERE a > 1.0",
        "SELECT b, count(*) AS n, avg(a) AS m FROM t0 GROUP BY b",
        "SELECT count(*) AS n, sum(a) AS s FROM t0",
    ):
        _parity(q, t)
    # non-empty table, filter matches nothing
    t2 = Table.from_dict({"a": np.arange(5.0), "b": np.arange(5)})
    _parity("SELECT a, a * 2 AS d FROM t1 WHERE a > 99", t2)
    _parity("SELECT b, min(a) AS lo FROM t1 WHERE a > 99 GROUP BY b", t2)


def test_three_valued_logic_and_null_aggregates(session):
    t = Table.from_dict(
        {
            "a": np.array([1.0, np.nan, 3.0, np.nan, 5.0]),
            "b": np.array([1, 1, 2, 2, 2]),
        }
    )
    _parity("SELECT a FROM t WHERE NOT (a > 2 AND a < 99)", t)
    _parity("SELECT a FROM t WHERE a NOT IN (1.0, 3.0)", t)
    _parity("SELECT a FROM t WHERE a IS NULL OR a >= 5", t)
    _parity(
        "SELECT b, count(a) AS c, sum(a) AS s, avg(a) AS m FROM t GROUP BY b",
        t,
    )
    # all-null group: sum/avg/min/max null, count 0
    t2 = Table.from_dict(
        {"a": np.array([np.nan, np.nan, 7.0]), "b": np.array([1, 1, 2])}
    )
    out = _parity(
        "SELECT b, count(a) AS c, max(a) AS hi FROM t2 GROUP BY b", t2
    )
    assert out.column("c").tolist() == [0, 1]
    assert np.isnan(out.column("hi")[0]) and out.column("hi")[1] == 7.0


def test_timestamp_group_keys_and_window_partition(session):
    rng = np.random.default_rng(5)
    n = 200
    ts = (
        np.datetime64("2025-03-31T22:00:00")
        + rng.integers(0, 5, n).astype("timedelta64[m]")
    ).astype("datetime64[ns]")
    ts[::11] = np.datetime64("NaT")
    t = Table.from_dict(
        {"t1": ts, "v": rng.normal(size=n), "g": rng.integers(0, 3, n)}
    )
    _parity("SELECT t1, count(*) AS n, avg(v) AS m FROM t GROUP BY t1", t)
    _parity(
        "SELECT v, sum(v) OVER (PARTITION BY g) AS s, "
        "count(v) OVER (PARTITION BY g) AS c FROM t WHERE v > -1.0",
        t,
    )


def test_compiled_limit_matches_interpreter(session, hospital_table):
    _parity(
        "SELECT event_time, length_of_stay FROM events "
        "WHERE length_of_stay > 2.0 LIMIT 9",
        hospital_table,
    )


# --------------------------------------------------- host-sync contract
@pytest.mark.perf
def test_fused_chain_host_syncs_constant(session, hospital_table):
    """Satellite 3: on the compiled fused path, SQL → assemble → fit
    performs a small CONSTANT number of host syncs — independent of row
    count — and zero device_puts once the column cache is warm."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models import (
        LinearRegression,
    )

    est = LinearRegression()
    # warm: compile + device-column cache
    est.fit(session.sql_to_device(WINDOW_QUERY, label_col="length_of_stay"))
    counts = []
    for _ in range(3):
        with host_sync_census(count_puts=True) as c:
            ds = session.sql_to_device(
                WINDOW_QUERY, label_col="length_of_stay"
            )
            est.fit(ds)
        counts.append((c["device_get"], c["device_put"]))
    for gets, puts in counts:
        assert gets <= 2, counts   # fit-internal fetches only, O(1)
        # warm cache: no column re-transfer; the ≤3 allows the x/y/w
        # device-to-device mesh reshard on multi-device meshes
        assert puts <= 3, counts
    assert len({c for c in counts}) == 1, f"sync count not constant: {counts}"
