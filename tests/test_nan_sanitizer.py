"""``jax_debug_nans`` sanitizer sweep — SURVEY.md §5's race/sanitizer row.

One representative tiny fit per estimator family runs with
``jax_debug_nans=True``: any NaN escaping a jitted computation raises
``FloatingPointError`` at dispatch instead of silently poisoning a model.
Set ``NAN_SWEEP=0`` to skip (e.g. when bisecting unrelated failures).
"""

import os

import jax
import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht

pytestmark = pytest.mark.skipif(
    os.environ.get("NAN_SWEEP", "1") == "0", reason="NAN_SWEEP=0"
)


@pytest.fixture
def debug_nans():
    jax.config.update("jax_debug_nans", True)
    yield
    jax.config.update("jax_debug_nans", False)


@pytest.fixture
def tiny(rng):
    n, d = 256, 4
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x @ rng.normal(size=d) + rng.normal(0, 0.1, size=n)).astype(np.float32)
    return x, y


def test_regressors_nan_clean(debug_nans, tiny, mesh8):
    x, y = tiny
    for est in (
        ht.LinearRegression(),
        ht.LinearRegression(reg_param=0.1, elastic_net_param=0.5),
        ht.DecisionTreeRegressor(max_depth=3),
        ht.RandomForestRegressor(num_trees=3, max_depth=3),
        ht.GBTRegressor(max_iter=3, max_depth=2),
    ):
        m = est.fit((x, y), mesh=mesh8)
        assert np.all(np.isfinite(np.asarray(m.predict_numpy(x))))


def test_classifiers_nan_clean(debug_nans, tiny, mesh8):
    x, y = tiny
    yb = (y > np.median(y)).astype(np.float32)
    for est in (
        ht.LogisticRegression(max_iter=10),
        ht.DecisionTreeClassifier(max_depth=3),
        ht.RandomForestClassifier(num_trees=3, max_depth=3),
        ht.GBTClassifier(max_iter=3, max_depth=2),
        ht.NaiveBayes(model_type="gaussian"),
    ):
        m = est.fit((x, yb), mesh=mesh8)
        assert np.all(np.isfinite(np.asarray(m.predict_numpy(x))))


def test_clustering_nan_clean(debug_nans, tiny, mesh8):
    x, _ = tiny
    for est in (
        ht.KMeans(k=3, max_iter=5),
        ht.GaussianMixture(k=2, max_iter=5),
        ht.BisectingKMeans(k=3),
    ):
        m = est.fit(x, mesh=mesh8)
        assert np.all(
            np.isfinite(np.asarray(m.predict(ht.device_dataset(x, mesh=mesh8).x)))
        )


def test_streaming_and_evaluators_nan_clean(debug_nans, tiny, mesh8):
    x, y = tiny
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.streaming_kmeans import (
        StreamingKMeans,
    )

    sk = StreamingKMeans(k=2, seed=0)
    sk.update(x[:128], mesh=mesh8)
    sk.update(x[128:], mesh=mesh8)
    m = ht.LinearRegression().fit((x, y), mesh=mesh8)
    rmse = ht.RegressionEvaluator("rmse").evaluate(
        m.transform((x, y), mesh=mesh8)
    )
    assert np.isfinite(rmse)
