"""Interprocedural lint (ISSUE 15) — call-graph resolution, durable
taint, the three new pass families, and the provably-misses contract.

Layers:

* **call-graph unit suite** — the documented resolution rules
  (module-level alias, ``self._helper``, one-assignment attribute,
  annotation types, parameter-default indirection, cross-module
  imports, recursion terminates);
* **durable-taint units** — parameter and return-value propagation;
* **per-rule fixtures** — bad+clean pairs for every new rule
  (durability family, crash_protocol family, the interprocedural
  concurrency/donation upgrades);
* **provably-misses** — every interprocedural fixture is run through
  the PR 11 one-hop engine (``deep=False`` / pre-ISSUE-15 pass set) and
  must produce ZERO findings there: the new engine's value is exactly
  the delta;
* **regression per fixed true positive** — the old buggy shape of each
  in-tree fix (unbounded_table part write, quarantine evidence,
  sql_views snapshot, the _apply inline-write-under-lock) staged at its
  sanctioned path must fire, and the one-hop engine must miss it;
* **CLI** — the ``--format=github`` annotation schema pin.
"""

from __future__ import annotations

import os
import re
import shutil
import subprocess
import sys

import pytest

pytestmark = pytest.mark.lint

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "tools")
FIXTURES = os.path.join(ROOT, "tests", "lint_fixtures")
PKG = "clustermachinelearningforhospitalnetworks_apache_spark_tpu"

if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

from lint import run  # noqa: E402
from lint.callgraph import ProjectGraph  # noqa: E402
from lint.dataflow import DurableTaint  # noqa: E402
from lint.engine import Project, load_file  # noqa: E402
from lint.passes.concurrency import ConcurrencyPass  # noqa: E402
from lint.passes.crash_protocol import CrashProtocolPass  # noqa: E402
from lint.passes.durability import DurabilityPass  # noqa: E402
from lint.passes.jit_hygiene import JitHygienePass  # noqa: E402


# ------------------------------------------------------------- helpers
def build_project(tmp_path, sources: dict[str, str]):
    """Write ``rel -> source`` under a temp root, parse, build the graph."""
    root = tmp_path / "repo"
    paths = []
    for rel, src in sources.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
        paths.append(str(p))
    contexts = [load_file(p, str(root)) for p in paths]
    project = Project(root=str(root), contexts=contexts)
    project.graph = ProjectGraph(project)
    return project


def stage_and_run(
    tmp_path, fixture: str, dest_rel: str, passes, complete: bool = True,
    with_trace: bool = False,
):
    """Stage a fixture AT an explicit repo-relative path (the durability
    rules are sanctioned-module-scoped, so the staged NAME matters) and
    run the given pass instances over it."""
    root = tmp_path / "repo"
    target = root / dest_rel
    target.parent.mkdir(parents=True, exist_ok=True)
    shutil.copy(os.path.join(FIXTURES, fixture), target)
    paths = [str(target)]
    if with_trace:
        obs = root / PKG / "obs"
        obs.mkdir(parents=True, exist_ok=True)
        shutil.copy(
            os.path.join(ROOT, PKG, "obs", "trace.py"), obs / "trace.py"
        )
        paths.append(str(obs / "trace.py"))
    return run(paths=paths, passes=passes, root=str(root), complete=complete)


def rules_of(report) -> set[str]:
    return {f.rule for f in report.active}


def fmt(report) -> str:
    return "\n".join(
        f"  {f.path}:{f.line} {f.rule} {f.message[:80]}"
        for f in report.active
    )


# ------------------------------------------------- call-graph resolution
def test_resolves_module_alias(tmp_path):
    g = build_project(tmp_path, {"m.py": (
        "def helper():\n    pass\n\ng = helper\n\n"
        "def f():\n    g()\n"
    )}).graph
    (cs,) = g.callees(("m.py", "f"))
    assert cs.target == ("m.py", "helper")


def test_resolves_self_method(tmp_path):
    g = build_project(tmp_path, {"m.py": (
        "class C:\n"
        "    def m(self):\n        self._helper()\n"
        "    def _helper(self):\n        pass\n"
    )}).graph
    (cs,) = g.callees(("m.py", "C.m"))
    assert cs.target == ("m.py", "C._helper")


def test_resolves_one_assignment_attribute(tmp_path):
    g = build_project(tmp_path, {"m.py": (
        "class Writer:\n    def write(self):\n        pass\n\n"
        "class A:\n"
        "    def __init__(self):\n        self.w = Writer()\n"
        "    def go(self):\n        self.w.write()\n"
    )}).graph
    targets = {cs.target for cs in g.callees(("m.py", "A.go"))}
    assert ("m.py", "Writer.write") in targets


def test_resolves_annotated_attribute(tmp_path):
    g = build_project(tmp_path, {"m.py": (
        "class Writer:\n    def write(self):\n        pass\n\n"
        "class A:\n"
        "    w: Writer\n"
        "    def go(self):\n        self.w.write()\n"
    )}).graph
    targets = {cs.target for cs in g.callees(("m.py", "A.go"))}
    assert ("m.py", "Writer.write") in targets


def test_resolves_parameter_default(tmp_path):
    g = build_project(tmp_path, {"m.py": (
        "def helper():\n    pass\n\n"
        "def run(hook=helper):\n    hook()\n"
    )}).graph
    (cs,) = g.callees(("m.py", "run"))
    assert cs.target == ("m.py", "helper")


def test_resolves_local_single_assignment(tmp_path):
    g = build_project(tmp_path, {"m.py": (
        "def helper():\n    pass\n\n"
        "def f():\n    h = helper\n    h()\n"
    )}).graph
    (cs,) = g.callees(("m.py", "f"))
    assert cs.target == ("m.py", "helper")


def test_rebound_local_is_ambiguous(tmp_path):
    g = build_project(tmp_path, {"m.py": (
        "def helper():\n    pass\n\ndef other():\n    pass\n\n"
        "def f(flag):\n"
        "    h = helper\n"
        "    if flag:\n        h = other\n"
        "    h()\n"
    )}).graph
    (cs,) = g.callees(("m.py", "f"))
    assert cs.target is None, "a rebound alias must not resolve"


def test_resolves_cross_module_import(tmp_path):
    g = build_project(tmp_path, {
        "pkg/a.py": "def helper():\n    pass\n",
        "pkg/b.py": (
            "from .a import helper\n\n"
            "def f():\n    helper()\n"
        ),
    }).graph
    (cs,) = g.callees(("pkg/b.py", "f"))
    assert cs.target == ("pkg/a.py", "helper")


def test_recursion_does_not_loop(tmp_path):
    g = build_project(tmp_path, {"m.py": (
        "def f():\n    g()\n\n"
        "def g():\n    f()\n"
    )}).graph
    reach = g.reachable(("m.py", "f"))
    assert ("m.py", "g") in reach and ("m.py", "f") in reach


def test_dynamic_callable_parameter_unresolved(tmp_path):
    g = build_project(tmp_path, {"m.py": (
        "def run(hook):\n    hook()\n"
    )}).graph
    (cs,) = g.callees(("m.py", "run"))
    assert cs.target is None, "a no-default parameter is genuinely dynamic"


# --------------------------------------------------------- durable taint
def test_taint_flows_into_callee_parameter(tmp_path):
    project = build_project(tmp_path, {"m.py": (
        "import os\n\n"
        "def _dump(path):\n    return path\n\n"
        "def save(ckpt_dir):\n"
        "    _dump(os.path.join(ckpt_dir, 'step-1'))\n"
    )})
    taint = DurableTaint(project.graph)
    assert "path" in taint.params.get(("m.py", "_dump"), set())


def test_taint_flows_out_of_return_value(tmp_path):
    project = build_project(tmp_path, {"m.py": (
        "def part_path(i):\n    return 'part-' + str(i)\n\n"
        "def g():\n    p = part_path(0)\n    return p\n"
    )})
    taint = DurableTaint(project.graph)
    assert ("m.py", "part_path") in taint.returns
    assert "p" in taint.locals.get(("m.py", "g"), set())


def test_plain_scratch_path_stays_untainted(tmp_path):
    project = build_project(tmp_path, {"m.py": (
        "import os\n\n"
        "def save(report_dir):\n"
        "    p = os.path.join(report_dir, 'summary.json')\n"
        "    return p\n"
    )})
    taint = DurableTaint(project.graph)
    assert "p" not in taint.locals.get(("m.py", "save"), set())


# --------------------------------------------------- new-rule fixtures
NEW_RULE_CASES = [
    # (fixture, dest rel path, pass factory, expected rules, with_trace)
    ("durability_bad.py", f"{PKG}/models/durability_bad.py",
     lambda: [DurabilityPass()],
     {"raw-durable-write", "raw-durable-rename", "wal-append-bypass"},
     False),
    ("dirsync_bad.py", f"{PKG}/streaming/checkpoint.py",
     lambda: [DurabilityPass()], {"rename-without-dirsync"}, False),
    ("seal_dirsync_bad.py", f"{PKG}/core/segments.py",
     lambda: [DurabilityPass()], {"rename-without-dirsync"}, False),
    ("crash_swallow_bad.py", f"{PKG}/models/crash_swallow_bad.py",
     lambda: [CrashProtocolPass()], {"crash-swallowed"}, False),
    ("journal_site_bad.py", f"{PKG}/io/fit_checkpoint.py",
     lambda: [CrashProtocolPass()], {"journal-mutation-unfaulted"}, True),
    ("interproc_blocking_bad.py", f"{PKG}/models/ipb.py",
     lambda: [ConcurrencyPass()], {"blocking-under-lock"}, False),
    ("interproc_lockorder_bad.py", f"{PKG}/models/ipl.py",
     lambda: [ConcurrencyPass()], {"lock-order-cycle"}, False),
    ("interproc_donate_bad.py", f"{PKG}/models/ipd.py",
     lambda: [JitHygienePass()], {"donated-arg-reused"}, False),
]


@pytest.mark.parametrize(
    "fixture,dest,factory,expected,with_trace", NEW_RULE_CASES,
    ids=[c[0].removesuffix("_bad.py") for c in NEW_RULE_CASES],
)
def test_new_rule_fires_on_violation(
    tmp_path, fixture, dest, factory, expected, with_trace
):
    report = stage_and_run(
        tmp_path, fixture, dest, factory(), with_trace=with_trace
    )
    got = rules_of(report)
    assert expected <= got, (
        f"{fixture}: expected {sorted(expected)}, got {sorted(got)}:\n"
        + fmt(report)
    )


@pytest.mark.parametrize(
    "fixture,dest,factory,expected,with_trace", NEW_RULE_CASES,
    ids=[c[0].removesuffix("_bad.py") for c in NEW_RULE_CASES],
)
def test_new_rule_clean_twin_stays_clean(
    tmp_path, fixture, dest, factory, expected, with_trace
):
    clean = fixture.replace("_bad.py", "_clean.py")
    dest = dest.replace("_bad.py", "_clean.py")
    report = stage_and_run(
        tmp_path, clean, dest, factory(), with_trace=with_trace
    )
    assert not report.active, f"{clean} should be clean:\n" + fmt(report)


def test_durability_rules_complete_scan_only(tmp_path):
    """--changed-only contract: the program-completeness durability rule
    (rename-without-dirsync needs CALLERS) auto-disables on partial
    scans, same as obs_coverage."""
    report = stage_and_run(
        tmp_path, "dirsync_bad.py", f"{PKG}/streaming/checkpoint.py",
        [DurabilityPass()], complete=False,
    )
    assert "rename-without-dirsync" not in rules_of(report)
    report = stage_and_run(
        tmp_path, "journal_site_bad.py", f"{PKG}/io/fit_checkpoint.py",
        [CrashProtocolPass()], complete=False, with_trace=True,
    )
    assert "journal-mutation-unfaulted" not in rules_of(report)


# --------------------------------------------------- provably-misses
OLD_ENGINE_CASES = [
    ("interproc_blocking_bad.py", f"{PKG}/models/ipb.py",
     lambda: [ConcurrencyPass(deep=False)]),
    ("interproc_lockorder_bad.py", f"{PKG}/models/ipl.py",
     lambda: [ConcurrencyPass(deep=False)]),
    ("interproc_donate_bad.py", f"{PKG}/models/ipd.py",
     lambda: [JitHygienePass(deep=False)]),
]


@pytest.mark.parametrize(
    "fixture,dest,factory", OLD_ENGINE_CASES,
    ids=[c[0].removesuffix("_bad.py") for c in OLD_ENGINE_CASES],
)
def test_one_hop_engine_provably_misses(tmp_path, fixture, dest, factory):
    """The PR 11 engine (deep=False) finds NOTHING in the
    interprocedural fixtures — the deep engine's findings are exactly
    the cross-function delta the review rounds kept catching by hand."""
    report = stage_and_run(tmp_path, fixture, dest, factory())
    assert not report.active, (
        f"one-hop engine unexpectedly sees {fixture}:\n" + fmt(report)
    )


def test_deep_donation_module_qualified_call_binding(tmp_path):
    """Review-round regression: a module-qualified ``helpers.f(a, b)``
    call is an Attribute but consumes NO self slot — the donated-
    argument mapping was off by one (flagged the undonated arg, missed
    the donated one).  The binding offset must apply only when the
    callee's first parameter IS self/cls."""
    project = build_project(tmp_path, {
        "pkg/helpers.py": (
            "import jax\n\n"
            "_step = jax.jit(lambda s, x: s + x, donate_argnums=(0,))\n\n\n"
            "def run_step(params, batch):\n"
            "    return _step(batch, params)\n"
        ),
        "pkg/caller.py": (
            "from . import helpers\n\n\n"
            "def train(p, b):\n"
            "    out = helpers.run_step(p, b)\n"
            "    return out, b.sum(), p.sum()\n"
        ),
    })
    jp = JitHygienePass()
    caller = project.context("pkg/caller.py")
    findings = list(jp._check_donated_reuse_deep(caller, project))
    assert findings, "the forwarded donation must be seen cross-module"
    assert all("'b'" in f.message for f in findings), [
        f.message[:60] for f in findings
    ]
    assert not any("'p'" in f.message for f in findings), (
        "the undonated argument must NOT be flagged (off-by-one binding)"
    )


def test_deep_lockorder_cross_module_order_independent(tmp_path):
    """Review-round regression: the per-function lock table was filled
    lazily per file, so an edge into a module scanned LATER was dropped
    and the reported cycle set depended on file iteration order.  A
    cross-module ABBA (caller file sorts first) must still cycle."""
    sources = {
        f"{PKG}/models/aa.py": (
            "import threading\n\n"
            "from . import zz\n\n"
            "LOCK_A = threading.Lock()\n\n\n"
            "def fwd():\n"
            "    with LOCK_A:\n"
            "        zz.take_b()\n\n\n"
            "def take_a():\n"
            "    with LOCK_A:\n"
            "        pass\n"
        ),
        f"{PKG}/models/zz.py": (
            "import threading\n\n"
            "from . import aa\n\n"
            "LOCK_B = threading.Lock()\n\n\n"
            "def take_b():\n"
            "    with LOCK_B:\n"
            "        pass\n\n\n"
            "def bwd():\n"
            "    with LOCK_B:\n"
            "        aa.take_a()\n"
        ),
    }
    root = tmp_path / "repo"
    paths = []
    for rel, src in sources.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
        paths.append(str(p))
    report = run(
        paths=paths, passes=[ConcurrencyPass()], root=str(root),
        complete=True,
    )
    assert "lock-order-cycle" in rules_of(report), fmt(report)


# ------------------------------------- regressions: fixed true positives
#: the OLD (pre-ISSUE-15) buggy shape of each in-tree fix, staged at its
#: real sanctioned path; the durability pass must fire and the one-hop
#: PR 11 pass set must stay silent (it had no durability rules at all,
#: and the taint is cross-function besides)
_OLD_PART_WRITE = '''\
import os


def _append_commit(log_path, line):
    return (log_path, line)


class UnboundedTable:
    def __init__(self, path):
        self.path = path

    def _write_parquet(self, table, path):
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(table)
        os.replace(tmp, path)  # OLD BUG: no bytes fsync, no dirsync
'''

_OLD_QUARANTINE = '''\
import os


class StreamCheckpoint:
    def __init__(self, path):
        self.path = path

    def quarantine(self, batch_id, payload):
        qdir = os.path.join(self.path, "quarantine")
        os.makedirs(qdir, exist_ok=True)
        p = os.path.join(qdir, f"batch-{batch_id}.json")
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)  # OLD BUG: evidence rename, no dirsync
        return p
'''

_OLD_VIEW_SNAPSHOT = '''\
import os


def _write_json_atomic(path, payload):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # OLD BUG: snapshot rename, no dirsync


class MaterializedView:
    def __init__(self, state_dir):
        self.state_dir = state_dir
        self._state_path = os.path.join(state_dir, "_views", "state.json")

    def persist(self, payload):
        _write_json_atomic(self._state_path, payload)
'''

_OLD_APPLY_INLINE_WRITE = '''\
import os
import threading


def _write_parquet_atomic(path, table):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(table)
    os.replace(tmp, path)


class MaterializedView:
    def __init__(self, state_dir):
        self.state_dir = state_dir
        self._lock = threading.Lock()
        self._batches = {}

    def refresh(self, entries):
        with self._lock:
            for bid, entry in entries.items():
                self._apply(bid, entry)  # OLD BUG: inline write branch
                                         # put os.replace under _lock

    def _apply(self, bid, entry):
        fpath = os.path.join(self.state_dir, f"delta-{bid}.parquet")
        _write_parquet_atomic(fpath, entry)
        self._batches[bid] = fpath
'''

_REGRESSIONS = [
    ("unbounded_table_part_write", _OLD_PART_WRITE,
     f"{PKG}/streaming/unbounded_table.py",
     lambda: [DurabilityPass()], {"rename-without-dirsync"}),
    ("quarantine_evidence", _OLD_QUARANTINE,
     f"{PKG}/streaming/checkpoint.py",
     lambda: [DurabilityPass()], {"rename-without-dirsync"}),
    ("view_snapshot", _OLD_VIEW_SNAPSHOT,
     f"{PKG}/core/sql_views.py",
     lambda: [DurabilityPass()], {"rename-without-dirsync"}),
    ("apply_inline_write_under_lock", _OLD_APPLY_INLINE_WRITE,
     f"{PKG}/core/sql_views.py",
     lambda: [ConcurrencyPass()], {"blocking-under-lock"}),
]


@pytest.mark.parametrize(
    "name,source,dest,factory,expected", _REGRESSIONS,
    ids=[r[0] for r in _REGRESSIONS],
)
def test_fixed_true_positive_regression(
    tmp_path, name, source, dest, factory, expected
):
    root = tmp_path / "repo"
    target = root / dest
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(source)
    report = run(
        paths=[str(target)], passes=factory(), root=str(root), complete=True
    )
    got = rules_of(report)
    assert expected <= got, (
        f"{name}: the old buggy shape must fire {sorted(expected)}; "
        f"got {sorted(got)}:\n" + fmt(report)
    )


@pytest.mark.parametrize(
    "name,source,dest,factory,expected", _REGRESSIONS,
    ids=[r[0] for r in _REGRESSIONS],
)
def test_one_hop_engine_missed_the_true_positive(
    tmp_path, name, source, dest, factory, expected
):
    """Why these shipped: the PR 11 engine — its full pass set, lexical
    one-hop mode, no durability family — reports nothing on the exact
    code that carried the bug."""
    from lint.passes.determinism import DeterminismPass
    from lint.passes.metric_labels import MetricLabelsPass

    old_engine = [
        ConcurrencyPass(deep=False), JitHygienePass(deep=False),
        DeterminismPass(), MetricLabelsPass(),
    ]
    root = tmp_path / "repo"
    target = root / dest
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(source)
    report = run(
        paths=[str(target)], passes=old_engine, root=str(root), complete=True
    )
    assert not report.active, (
        f"{name}: the PR 11 engine was supposed to miss this:\n"
        + fmt(report)
    )


def test_live_repo_fixed_sites_clean():
    """The in-tree fixes hold: the durability + crash_protocol + deep
    concurrency families over the REAL sanctioned modules report
    nothing (suppressions carry the deliberate non-fixes)."""
    paths = [
        os.path.join(ROOT, PKG, rel) for rel in (
            "streaming/unbounded_table.py", "streaming/checkpoint.py",
            "streaming/wal.py", "core/sql_views.py",
            "io/fit_checkpoint.py", "io/model_io.py",
        )
    ]
    report = run(
        paths=paths,
        passes=[DurabilityPass(), ConcurrencyPass(), JitHygienePass()],
        complete=False,
    )
    assert not report.active, fmt(report)


# ---------------------------------------------------------------- CLI
_GITHUB_LINE = re.compile(
    r"^::error file=[^,]+,line=\d+,col=\d+,title=lint/[a-z0-9\-]+::.+$"
)


def test_github_format_schema_pinned(tmp_path):
    """--format=github emits one ::error workflow command per active
    finding, matching the Actions annotation grammar exactly."""
    root = tmp_path / "repo"
    dest = root / PKG / "models"
    dest.mkdir(parents=True)
    shutil.copy(
        os.path.join(FIXTURES, "determinism_bad.py"),
        dest / "determinism_bad.py",
    )
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "lint.py"),
         "--format=github", "--passes", "determinism", "--root", str(root),
         str(dest / "determinism_bad.py")],
        capture_output=True, text=True,
    )
    assert r.returncode == 1, r.stdout + r.stderr
    lines = [l for l in r.stdout.splitlines() if l.startswith("::error")]
    assert lines, "no annotations emitted"
    for line in lines:
        assert _GITHUB_LINE.match(line), f"malformed annotation: {line}"
    assert any("unseeded-random" in l for l in lines)
    # the summary line still closes the output (humans read CI logs too)
    assert r.stdout.splitlines()[-1].startswith("lint:")


def test_github_format_clean_exit(tmp_path):
    root = tmp_path / "repo"
    dest = root / PKG / "models"
    dest.mkdir(parents=True)
    shutil.copy(
        os.path.join(FIXTURES, "determinism_clean.py"),
        dest / "determinism_clean.py",
    )
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "lint.py"),
         "--format=github", "--passes", "determinism", "--root", str(root),
         str(dest / "determinism_clean.py")],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "::error" not in r.stdout
