"""StreamingLinearRegression / StreamingLogisticRegression — incremental
supervised learners over micro-batches (the working realization of the
reference's dead incremental-training hook, C6/D2, whose comment names
LogisticRegression as the intended per-batch model)."""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht


def _reg_data(rng, n=8000, d=4):
    x = rng.normal(size=(n, d)).astype(np.float32)
    beta = np.array([1.0, -2.0, 0.5, 0.3], np.float32)[:d]
    y = (x @ beta + 0.7 + 0.1 * rng.normal(size=n)).astype(np.float32)
    return x, y, beta


class TestStreamingLinear:
    def test_decay_one_equals_batch_wls(self, rng, mesh8):
        x, y, _ = _reg_data(rng)
        sl = ht.StreamingLinearRegression()
        for s in range(0, len(x), 1000):
            sl.update((x[s : s + 1000], y[s : s + 1000]), mesh=mesh8)
        assert sl.n_batches == 8
        m = sl.latest_model
        batch = ht.LinearRegression().fit((x, y), mesh=mesh8)
        np.testing.assert_allclose(
            np.asarray(m.coefficients), np.asarray(batch.coefficients),
            rtol=1e-4, atol=1e-4,
        )
        np.testing.assert_allclose(
            float(m.intercept), float(batch.intercept), rtol=1e-3
        )

    def test_forgetting_tracks_drift(self, rng, mesh8):
        x, y, beta = _reg_data(rng)
        y2 = (x @ (-beta) + 0.7).astype(np.float32)   # regime flip
        tracker = ht.StreamingLinearRegression(decay_factor=0.3)
        averager = ht.StreamingLinearRegression(decay_factor=1.0)
        for yy in (y, y2):
            for s in range(0, len(x), 1000):
                tracker.update((x[s : s + 1000], yy[s : s + 1000]), mesh=mesh8)
                averager.update((x[s : s + 1000], yy[s : s + 1000]), mesh=mesh8)
        tc = np.asarray(tracker.latest_model.coefficients)
        ac = np.asarray(averager.latest_model.coefficients)
        assert np.abs(tc + beta).max() < 0.05      # locked onto the new regime
        assert np.abs(ac + beta).max() > 0.5       # still dragged by history

    def test_validation(self, rng, mesh8):
        with pytest.raises(ValueError, match="decay_factor"):
            ht.StreamingLinearRegression(decay_factor=1.5)
        with pytest.raises(RuntimeError, match="update"):
            ht.StreamingLinearRegression().latest_model


class TestStreamingLogistic:
    def test_converges_to_batch_newton(self, rng, mesh8):
        x, _, beta = _reg_data(rng)
        p = 1 / (1 + np.exp(-(x @ beta + 0.3)))
        yb = (rng.uniform(size=len(x)) < p).astype(np.float32)
        sl = ht.StreamingLogisticRegression(newton_steps_per_batch=2)
        for s in range(0, len(x), 1000):
            sl.update((x[s : s + 1000], yb[s : s + 1000]), mesh=mesh8)
        sm = sl.latest_model
        bm = ht.LogisticRegression(max_iter=50).fit((x, yb), mesh=mesh8)
        np.testing.assert_allclose(
            np.asarray(sm.coefficients), np.asarray(bm.coefficients), atol=0.05
        )
        acc_s = np.mean(np.asarray(sm.predict_numpy(x)) == yb)
        acc_b = np.mean(np.asarray(bm.predict_numpy(x)) == yb)
        assert acc_s > acc_b - 0.01

    def test_validation(self, mesh8):
        with pytest.raises(ValueError, match="decay_factor"):
            ht.StreamingLogisticRegression(decay_factor=-0.1)
        with pytest.raises(ValueError, match="newton_steps"):
            ht.StreamingLogisticRegression(newton_steps_per_batch=0)
        with pytest.raises(RuntimeError, match="update"):
            ht.StreamingLogisticRegression().latest_model


def test_foreach_batch_incremental_supervised(tmp_path, mesh8):
    """The reference's C6 intent end-to-end: stream micro-batches through
    the file-source driver, train LogisticRegression incrementally in the
    foreachBatch hook."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io.csv import write_csv
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming import (
        FileStreamSource,
        StreamCheckpoint,
        StreamExecution,
        UnboundedTable,
        WatermarkTracker,
    )

    rng = np.random.default_rng(0)

    def event_csv(path, start_minute, n):
        base = np.datetime64("2025-03-31T22:00:00") + np.timedelta64(
            int(start_minute), "m"
        )
        adm = rng.integers(0, 50, n)
        t = ht.Table.from_dict(
            {
                "hospital_id": np.array(["H01"] * n, dtype=object),
                "event_time": base + np.arange(n).astype("timedelta64[s]"),
                "admission_count": adm,
                "current_occupancy": rng.integers(20, 200, n),
                "emergency_visits": rng.integers(0, 30, n),
                "seasonality_index": rng.uniform(0.5, 1.5, n),
                # LOS driven by admissions → the stream learner must find it
                "length_of_stay": 2.0 + 0.2 * adm + rng.normal(0, 0.1, n),
            },
            ht.hospital_event_schema(),
        )
        write_csv(t, path)

    incoming = tmp_path / "incoming"
    incoming.mkdir()
    learner = ht.StreamingLogisticRegression(newton_steps_per_batch=3)

    def hook(batch, batch_id):
        if batch.num_rows:
            xb = batch.numeric_matrix(list(ht.FEATURE_COLS)).astype(np.float32)
            yb = (
                np.asarray(batch.column("length_of_stay")) > 5.0
            ).astype(np.float32)
            learner.update((xb, yb), mesh=mesh8)

    exec_ = StreamExecution(
        source=FileStreamSource(str(incoming), ht.hospital_event_schema()),
        sink=UnboundedTable(str(tmp_path / "table"), ht.hospital_event_schema()),
        checkpoint=StreamCheckpoint(str(tmp_path / "ckpt")),
        watermark=WatermarkTracker("event_time", 10.0),
        foreach_batch=hook,
    )
    for i in range(4):
        event_csv(str(incoming / f"{i}.csv"), i, 400)
        exec_.run_once()
    assert learner.n_batches >= 1
    m = learner.latest_model
    # the learned boundary tracks the LOS>5 rule (admissions-driven)
    xt = np.asarray(
        exec_.sink.read().numeric_matrix(list(ht.FEATURE_COLS)), np.float32
    )
    yt = (np.asarray(exec_.sink.read().column("length_of_stay")) > 5.0).astype(
        np.float32
    )
    acc = np.mean(np.asarray(m.predict_numpy(xt)) == yt)
    assert acc > 0.95
