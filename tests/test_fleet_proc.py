"""serve/fleet/proc — the multi-process fleet (ISSUE 19b).

Contracts:

1. framing — torn header/payload, bad magic, oversize length, and
   undecodable pickle each raise :class:`FrameError`; clean EOF at a
   frame boundary is ``None``; an oversize SEND is refused before any
   bytes hit the wire;
2. transport ladder — an RPC timeout or wire death feeds the parent-
   side breaker; transport death completes EVERY in-flight request
   ``unavailable`` (answered, never stranded) and flips the client so
   ``submit`` raises ``KeyError`` — the fleet's reroute signal;
3. the fleet over real processes — predict parity with the in-process
   model, atomic fleet-wide swap, SIGKILL mid-load with unanswered=0
   and a CRC-intact postmortem, revive through the same build seam;
4. the ``fleet.proc.rpc`` chaos site — a corrupt frame on the wire is
   transport death, answered by the same ladder.

Framing/transport tests run on plain socketpairs (no worker process);
the process-backed tests share ONE module-scoped 2-replica fleet to
keep the spawn bill bounded.
"""

import itertools
import os
import signal
import socket
import struct
import threading
import time

import numpy as np
import pytest

from clustermachinelearningforhospitalnetworks_apache_spark_tpu.obs.flight_recorder import (
    read_dump,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve.breaker import (
    CircuitBreaker,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve.fleet import (
    proc as FP,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils import (
    faults,
)

pytestmark = [pytest.mark.fleet]

D = 4


# --------------------------------------------------------------- framing


class TestFraming:
    def test_round_trip(self):
        a, b = socket.socketpair()
        with a, b:
            FP.send_frame(a, {"op": "ping", "x": np.arange(3)})
            msg = FP.recv_frame(b)
        assert msg["op"] == "ping"
        np.testing.assert_array_equal(msg["x"], np.arange(3))

    def test_clean_eof_is_none(self):
        a, b = socket.socketpair()
        with b:
            a.close()
            assert FP.recv_frame(b) is None

    def test_torn_header(self):
        a, b = socket.socketpair()
        with b:
            a.sendall(b"CM")      # 2 of 8 header bytes, then death
            a.close()
            with pytest.raises(FP.FrameError, match="mid-frame"):
                FP.recv_frame(b)

    def test_torn_payload(self):
        a, b = socket.socketpair()
        with b:
            a.sendall(struct.pack(">4sI", b"CMP1", 100) + b"x" * 10)
            a.close()
            with pytest.raises(FP.FrameError, match="mid-frame"):
                FP.recv_frame(b)

    def test_bad_magic(self):
        a, b = socket.socketpair()
        with a, b:
            a.sendall(struct.pack(">4sI", b"XXXX", 4) + b"abcd")
            with pytest.raises(FP.FrameError, match="magic"):
                FP.recv_frame(b)

    def test_oversize_frame_refused_without_buffering(self):
        a, b = socket.socketpair()
        with a, b:
            # a corrupted length field must not make the receiver try to
            # buffer gigabytes — it fails on the header alone
            a.sendall(struct.pack(">4sI", b"CMP1", FP.MAX_FRAME_BYTES + 1))
            with pytest.raises(FP.FrameError, match="oversize"):
                FP.recv_frame(b)

    def test_undecodable_payload(self):
        a, b = socket.socketpair()
        with a, b:
            a.sendall(struct.pack(">4sI", b"CMP1", 4) + b"\xff\xfe\xfd\xfc")
            with pytest.raises(FP.FrameError, match="undecodable"):
                FP.recv_frame(b)

    def test_oversize_send_refused_before_write(self):
        a, b = socket.socketpair()
        with a, b:
            with pytest.raises(FP.FrameError, match="exceeds"):
                FP.send_frame(a, {"blob": b"x" * 64}, max_bytes=32)
            # nothing hit the wire
            b.setblocking(False)
            with pytest.raises(BlockingIOError):
                b.recv(1)


# --------------------------------------------------------------- transport


class _FakeProc:
    """Stands in for the Popen handle on a loopback client."""

    pid = -1

    def __init__(self):
        self._rc = None

    def poll(self):
        return self._rc


def _loopback_client(rpc_timeout_s=0.2):
    """A ProcServerClient wired to a test-controlled peer socket instead
    of a spawned worker — the transport ladder in isolation."""
    parent, peer = socket.socketpair()
    c = FP.ProcServerClient.__new__(FP.ProcServerClient)
    c.replica_id = 0
    c._server_kw = {}
    c.max_queue_rows = 64
    c.breaker = CircuitBreaker(failure_threshold=2, recovery_timeout_s=60.0)
    c._worker_threads = 1
    c._spawn_timeout_s = 1.0
    c._rpc_timeout_s = rpc_timeout_s
    c._max_frame = FP.MAX_FRAME_BYTES
    c._env_extra = {}
    c.registry = FP._ClientRegistry()
    c._send_lock = threading.Lock()
    c._state_lock = threading.Lock()
    c._pending = {}
    c._ids = itertools.count(1)
    c._inflight_rows = 0
    c._dead = threading.Event()
    c._closing = False
    c._sock = parent
    c._proc = _FakeProc()
    c.pid = -1
    c.counters = {
        "serve.requests": 0.0, "fleet.proc.rpc_sent": 0.0,
        "fleet.proc.short_circuited": 0.0,
        "fleet.proc.transport_down": 0.0, "fleet.proc.killed": 0.0,
    }
    c.last_postmortem = None
    threading.Thread(target=c._recv_loop, daemon=True).start()
    return c, peer


class TestTransportLadder:
    def test_rpc_timeout_counts_against_breaker(self):
        c, peer = _loopback_client(rpc_timeout_s=0.05)
        with peer:
            with pytest.raises(FP.RPCError, match="timed out"):
                c._call("ping")
            assert c.breaker._consecutive_failures == 1
            # peer actually received the request frame
            assert FP.recv_frame(peer)["op"] == "ping"

    def test_transport_death_answers_all_inflight(self):
        c, peer = _loopback_client()
        c.registry._entries["m"] = FP._RegistryEntry(object())
        reqs = [c.submit("m", np.zeros((2, D), np.float32)) for _ in range(5)]
        assert c.inflight_rows() == 10
        peer.close()              # worker death
        results = [r.wait(5.0) for r in reqs]
        assert all(r.status == "unavailable" for r in results)
        assert c.inflight_rows() == 0
        assert not c.alive()
        # and the fleet's reroute signal fires on the next dispatch
        with pytest.raises(KeyError):
            c.submit("m", np.zeros((1, D), np.float32))

    def test_torn_frame_from_peer_is_transport_death(self):
        c, peer = _loopback_client()
        c.registry._entries["m"] = FP._RegistryEntry(object())
        req = c.submit("m", np.zeros((1, D), np.float32))
        with peer:
            peer.sendall(b"garbage!")   # bad magic → FrameError → down
            assert req.wait(5.0).status == "unavailable"

    def test_unknown_model_is_keyerror_before_any_rpc(self):
        c, peer = _loopback_client()
        with peer:
            with pytest.raises(KeyError):
                c.submit("nope", np.zeros((1, D), np.float32))

    def test_open_breaker_short_circuits_submit(self):
        c, peer = _loopback_client()
        c.registry._entries["m"] = FP._RegistryEntry(object())
        with peer:
            c.breaker.record_failure()
            c.breaker.record_failure()  # threshold=2 → OPEN
            with pytest.raises(KeyError, match="breaker"):
                c.submit("m", np.zeros((1, D), np.float32))
            assert c.counters["fleet.proc.short_circuited"] == 1


# --------------------------------------------------------------- processes


@pytest.fixture(scope="module")
def proc_fleet():
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.kmeans import (
        KMeans,
    )

    rng = np.random.default_rng(0)
    x = rng.normal(size=(192, D)).astype(np.float32)
    model = KMeans(k=3, max_iter=5, seed=0).fit(x)
    fs = FP.ProcReplicaSet(n_replicas=2, max_wait_s=0.005)
    fs.add_model("km", model, n_features=D)
    fs.start()
    yield fs, model, x
    fs.stop()


class TestProcFleet:
    def test_predict_parity_with_in_process_model(self, proc_fleet):
        fs, _, x = proc_fleet
        # compare against the CURRENTLY served model (order-independent
        # with the swap test on the shared fleet)
        current = fs.registry.get("km").model
        r = fs.predict("km", x[:16], tenant_id="h1")
        assert r.status == "ok"
        np.testing.assert_array_equal(
            np.asarray(r.value), np.asarray(current.predict(x[:16]))
        )

    def test_each_replica_is_a_distinct_os_process(self, proc_fleet):
        fs, _, _ = proc_fleet
        pids = {r.server.pid for r in fs.replicas}
        assert len(pids) == 2
        assert os.getpid() not in pids
        for pid in pids:
            os.kill(pid, 0)   # alive

    def test_atomic_swap_across_processes(self, proc_fleet):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.kmeans import (
            KMeans,
        )

        fs, _, x = proc_fleet
        m2 = KMeans(k=3, max_iter=9, seed=5).fit(x)
        fs.swap_model("km", m2, n_features=D)
        r = fs.predict("km", x[:16], tenant_id="h1")
        assert r.status == "ok"
        np.testing.assert_array_equal(
            np.asarray(r.value), np.asarray(m2.predict(x[:16]))
        )

    def test_lifecycle_attachment_is_loudly_unsupported(self, proc_fleet):
        fs, _, _ = proc_fleet
        with pytest.raises(NotImplementedError):
            fs.attach_lifecycle(object())

    @pytest.mark.chaos
    def test_sigkill_mid_load_unanswered_zero_then_revive(self, proc_fleet):
        """The tentpole chaos row: SIGKILL a replica PROCESS mid-load —
        every in-flight request is answered (ok or unavailable, zero
        stranded), the router reroutes, the postmortem round-trips CRC-
        intact, and revive rebuilds through the spawn seam."""
        fs, _, x = proc_fleet
        reqs = [
            fs.submit("km", x[i % 64: i % 64 + 4], tenant_id=f"t{i}")
            for i in range(24)
        ]
        fs.kill_replica(0)
        results = [r.wait(15.0) for r in reqs]
        statuses = {r.status for r in results}
        assert statuses <= {"ok", "unavailable", "rejected"}, statuses
        assert sum(r.status == "ok" for r in results) > 0
        # unanswered == 0: wait() never hit its client timeout
        assert all(r.detail != "client wait timed out" for r in results)
        # postmortem round-trips CRC-intact
        dump = fs.replicas[0].server.last_postmortem
        assert dump is not None
        post = read_dump(dump)
        assert post["site"] == "fleet.proc.kill"
        assert post["trigger"]["replica"] == 0
        # router reroutes to the survivor
        r = fs.predict("km", x[:4], tenant_id="h1")
        assert r.status == "ok"
        # revive rebuilds a REAL process through the same seam
        fs.revive_replica(0)
        assert fs.replicas[0].healthy()
        assert fs.replicas[0].server.pid not in (None, os.getpid())
        assert fs.predict("km", x[:4], tenant_id="h1").status == "ok"
        assert fs.health()["status"] == "ok"

    @pytest.mark.chaos
    def test_external_sigkill_reaped_and_rerouted(self, proc_fleet):
        """A kill the fleet API never saw (OOM killer shape): routing
        excludes the dead process immediately, reap() flips it DEAD so
        revive accepts it."""
        fs, _, x = proc_fleet
        victim = fs.replicas[1]
        os.kill(victim.server.pid, signal.SIGKILL)
        deadline = time.monotonic() + 10.0
        while victim.server.alive() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not victim.healthy()
        assert fs.predict("km", x[:4], tenant_id="h1").status == "ok"
        assert fs.reap() == [1]
        fs.revive_replica(1)
        assert fs.predict("km", x[:4], tenant_id="h1").status == "ok"

    @pytest.mark.chaos
    def test_rpc_corruption_site_is_transport_death(self, proc_fleet):
        """fleet.proc.rpc: a corrupt frame on the wire has no resync
        point — the worker dies loudly, the parent answers in-flight
        work, and revive recovers the replica."""
        fs, _, x = proc_fleet
        target = fs.router.route(tenant_id="h1", model="km").index
        plan = faults.FaultPlan().corrupt(
            "fleet.proc.rpc", at_byte=1, times=1,
            when=lambda ctx: ctx.get("replica") == target,
        )
        with faults.active(plan):
            req = fs.submit("km", x[:4], tenant_id="h1")
            res = req.wait(10.0)
        # the corrupted dispatch itself is answered, one way or the other
        assert res.status in ("ok", "unavailable")
        assert plan.fired("fleet.proc.rpc") == 1
        victim = fs.replicas[target]
        deadline = time.monotonic() + 10.0
        while victim.server.alive() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not victim.healthy()
        assert fs.reap() == [target]
        fs.revive_replica(target)
        assert fs.predict("km", x[:4], tenant_id="h1").status == "ok"


@pytest.mark.chaos
def test_spawn_fault_rides_retry_ladder():
    """fleet.proc.spawn: a failed worker spawn rides the SAME retry
    ladder the rest of the stack uses — one injected OSError costs one
    backoff retry, not a dead replica."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.kmeans import (
        KMeans,
    )

    rng = np.random.default_rng(1)
    x = rng.normal(size=(96, D)).astype(np.float32)
    model = KMeans(k=2, max_iter=3, seed=0).fit(x)
    plan = faults.FaultPlan().fail(
        "fleet.proc.spawn", times=1,
        error=lambda: OSError("injected spawn failure"),
    )
    with faults.active(plan):
        fs = FP.ProcReplicaSet(n_replicas=1, max_wait_s=0.005)
    assert plan.fired("fleet.proc.spawn") == 1
    try:
        fs.add_model("km", model, n_features=D)
        with fs:
            assert fs.predict("km", x[:4], tenant_id="h1").status == "ok"
    except BaseException:
        fs.stop()
        raise
