"""Explicit collective surface: tree_aggregate (Spark's treeAggregate
analogue) and the hybrid DCN+ICI mesh builder — both consumed by real
paths (RegressionEvaluator's sharded reduction; multi-host mesh layout)."""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.collectives import (
    tree_aggregate,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    build_hybrid_mesh,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.sharding import (
    device_dataset,
)


pytestmark = pytest.mark.fast


def test_tree_aggregate_matches_host_sum(rng, mesh8):
    import jax.numpy as jnp

    x = rng.normal(size=(1000,)).astype(np.float32)
    ds = device_dataset(x[:, None], mesh=mesh8)

    stats = tree_aggregate(
        lambda t: {"s": jnp.sum(t[0][:, 0] * t[1]), "n": jnp.sum(t[1])},
        (ds.x, ds.w),
        mesh=mesh8,
    )
    np.testing.assert_allclose(float(stats["s"]), x.sum(), rtol=1e-5)
    assert float(stats["n"]) == 1000.0


def test_regression_evaluator_uses_tree_aggregate_path(rng, mesh8):
    """Sharded PredictionResult → explicit treeAggregate reduction; value
    matches the host computation exactly."""
    x = rng.normal(size=(512, 3)).astype(np.float32)
    y = (x @ np.array([1.0, -2.0, 0.5]) + 0.1 * rng.normal(size=512)).astype(
        np.float32
    )
    model = ht.LinearRegression().fit((x, y), mesh=mesh8)
    preds = model.transform((x, y), mesh=mesh8)
    assert getattr(preds.prediction.sharding, "mesh", None) is not None
    rmse_mesh = ht.RegressionEvaluator("rmse").evaluate(preds)
    p_host, l_host = preds.to_numpy()
    rmse_host = float(np.sqrt(np.mean((p_host - l_host) ** 2)))
    assert abs(rmse_mesh - rmse_host) < 1e-5


def test_hybrid_mesh_single_process_fallback(rng):
    """8 CPU devices, 2 emulated hosts: same axis names, host-major order,
    and a KMeans fit that matches the flat-mesh fit."""
    mesh = build_hybrid_mesh(dcn_hosts=2, model=2)
    assert mesh.shape[DATA_AXIS] == 4 and mesh.shape[MODEL_AXIS] == 2

    centers = np.array([[0.0, 0.0], [10.0, 10.0], [0.0, 10.0], [10.0, 0.0]])
    a = rng.integers(0, 4, 800)
    x = (centers[a] + rng.normal(scale=0.4, size=(800, 2))).astype(np.float32)

    flat = ht.build_mesh()
    km_flat = ht.KMeans(k=4, seed=0).fit(x, mesh=flat)
    km_hyb = ht.KMeans(k=4, seed=0).fit(x, mesh=mesh)
    np.testing.assert_allclose(
        np.sort(km_hyb.cluster_centers, axis=0),
        np.sort(km_flat.cluster_centers, axis=0),
        atol=1e-4,
    )
