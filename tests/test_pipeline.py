"""End-to-end pipeline parity test: CSV drops → stream → window → 5 models →
metrics → plots → saved artifacts → report (the whole reference script)."""

import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.config import (
    MeshConfig,
    PipelineConfig,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import write_csv
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.pipeline import run_pipeline
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.session import (
    Session,
    parse_duration_minutes,
)


def _make_input(dirpath, n=600, seed=5):
    rng = np.random.default_rng(seed)
    os.makedirs(dirpath, exist_ok=True)
    base = np.datetime64("2025-03-31T22:00:00")
    for part in range(3):
        m = n // 3
        adm = rng.integers(0, 50, m)
        occ = rng.integers(20, 400, m)
        emer = rng.integers(0, 30, m)
        sea = rng.uniform(0.5, 1.5, m)
        los = 3.0 + 0.01 * occ + 0.08 * emer + rng.normal(0, 0.15, m)
        t = ht.Table.from_dict(
            {
                "hospital_id": np.array([f"H{i%4:02d}" for i in range(m)], dtype=object),
                "event_time": base
                + (part * m + np.arange(m)).astype("timedelta64[s]"),
                "admission_count": adm,
                "current_occupancy": occ,
                "emergency_visits": emer,
                "seasonality_index": sea,
                "length_of_stay": los,
            },
            ht.hospital_event_schema(),
        )
        write_csv(t, os.path.join(dirpath, f"drop_{part}.csv"))


@pytest.fixture
def pipeline_cfg(tmp_path):
    _make_input(str(tmp_path / "incoming"))
    return PipelineConfig(
        input_path=str(tmp_path / "incoming"),
        checkpoint_location=str(tmp_path / "ckpt"),
        model_save_path=str(tmp_path / "models"),
        plot_dir=str(tmp_path / "plots"),
        training_window_start="2025-03-31 22:00:00",
        training_window_end="2025-03-31 23:00:00",
        mesh=MeshConfig(data=8, model=1),
    )


def test_full_pipeline(pipeline_cfg):
    result = run_pipeline(pipeline_cfg)
    # all five reference models present (:146-158, :183-190)
    assert set(result.regression_rmse) == {
        "LinearRegression",
        "DecisionTreeRegressor",
        "RandomForestRegressor",
    }
    assert set(result.classification_accuracy) == {
        "DecisionTreeClassifier",
        "RandomForestClassifier",
    }
    # signal is learnable: linear data → LR near noise floor 0.15
    assert result.regression_rmse["LinearRegression"] < 0.3
    for acc in result.classification_accuracy.values():
        assert acc > 0.9
    # importances for the four tree models (:228-235 + classifiers)
    assert len(result.feature_importances) == 4
    # artifacts on disk with the reference layout (:241-243 + D7 superset)
    for name, path in result.model_paths.items():
        assert os.path.isdir(path), name
        loaded = ht.load_model(path)
        assert loaded is not None
    assert os.path.basename(result.model_paths["LinearRegression"]) == "lr"
    # plots written as files (D6)
    assert os.path.exists(result.plot_paths["predicted_vs_actual"])
    assert os.path.exists(result.plot_paths["residuals"])
    # report text carries the metrics (:245-255)
    assert "OPERATIONAL INSIGHTS" in result.report
    assert "RMSE" in result.report and "accuracy" in result.report


def test_pipeline_resume_is_idempotent(pipeline_cfg):
    """Re-running over the same checkpoint must not duplicate table rows.

    Tiny trees: this asserts STREAM-RESUME semantics, not model quality —
    test_full_pipeline covers the reference's default hyper-parameters,
    and small trees skip ~2 min of per-level compile on the 1-core CI."""
    cfg = pipeline_cfg.replace(tree_max_depth=2, rf_num_trees=2)
    r1 = run_pipeline(cfg, make_plots=False, save_models=False)
    r2 = run_pipeline(cfg, make_plots=False, save_models=False)
    assert r1.training_rows == r2.training_rows


def test_session_sql_and_builder(tmp_path):
    spark = (
        Session.builder.app_name("t").mesh(MeshConfig(data=8, model=1)).get_or_create()
    )
    t = ht.Table.from_dict(
        {
            "event_time": np.datetime64("2025-01-01T00:00:00")
            + np.arange(10).astype("timedelta64[m]"),
            "v": np.arange(10).astype(float),
        }
    )
    spark.register_table("events", t)
    out = spark.sql(
        "SELECT * FROM events WHERE event_time BETWEEN "
        "'2025-01-01 00:02:00' AND '2025-01-01 00:05:00'"
    )
    assert out.num_rows == 4
    # aggregates are real SQL now (core/sql.py), not an error
    assert spark.sql("SELECT count(*) AS n FROM events").column("n")[0] == 10
    with pytest.raises(ValueError, match="SQL"):
        spark.sql("SELECT * FROM events JOIN other")  # unsupported form
    with pytest.raises(KeyError):
        spark.table("nope")
    spark.stop()


def test_parse_duration():
    assert parse_duration_minutes("10 minutes") == 10.0
    assert parse_duration_minutes("1 hour") == 60.0
    assert parse_duration_minutes("30 seconds") == 0.5
    with pytest.raises(ValueError):
        parse_duration_minutes("fortnight")


def test_fluent_streaming_api(tmp_path):
    """The reference's exact chain shape (:75-82, :111-115) works."""
    _make_input(str(tmp_path / "in"), n=90)
    spark = Session(
        PipelineConfig(
            checkpoint_location=str(tmp_path / "ck"),
            mesh=MeshConfig(data=8, model=1),
        )
    )
    seen = []
    q = (
        spark.read_stream.schema(ht.hospital_event_schema())
        .csv(str(tmp_path / "in"))
        .with_watermark("event_time", "10 minutes")
        .write_stream.foreach_batch(lambda df, bid: seen.append((bid, df.num_rows)))
        .output_mode("append")
        .option("checkpointLocation", str(tmp_path / "ck"))
        .table("hospital_unbounded_table")
    )
    infos = q.process_available()
    assert sum(i.num_appended_rows for i in infos) == 90
    assert sum(n for _, n in seen) == 90
    assert spark.table("hospital_unbounded_table").num_rows == 90
    assert q.last_progress is not None


def test_session_get_or_create_reuses_active(tmp_path):
    s1 = Session.builder.app_name("one").mesh(MeshConfig(data=8, model=1)).get_or_create()
    s2 = Session.builder.app_name("two").get_or_create()
    assert s2 is s1  # Spark semantics: active session reused
    s1.stop()
    s3 = Session.builder.app_name("three").mesh(MeshConfig(data=8, model=1)).get_or_create()
    assert s3 is not s1
    s3.stop()


def test_run_pipeline_uses_session_config(pipeline_cfg):
    """run_pipeline(session=...) without config must honor the session's
    config (regression: it silently used defaults).  Tiny trees — config
    plumbing is the subject, not model quality."""
    spark = Session(pipeline_cfg.replace(tree_max_depth=2, rf_num_trees=2))
    result = run_pipeline(session=spark, make_plots=False, save_models=False)
    assert result.training_rows > 0
    spark.stop()


def test_headerless_stream_option(tmp_path):
    """option('header','false') must reach the CSV reader (regression)."""
    import os
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import write_csv

    os.makedirs(tmp_path / "in", exist_ok=True)
    t = ht.Table.from_dict(
        {
            "hospital_id": np.array(["H0", "H1"], dtype=object),
            "event_time": np.datetime64("2025-03-31T22:00:00")
            + np.arange(2).astype("timedelta64[s]"),
            "admission_count": [1, 2],
            "current_occupancy": [10, 20],
            "emergency_visits": [0, 1],
            "seasonality_index": [1.0, 1.1],
            "length_of_stay": [3.0, 4.0],
        },
        ht.hospital_event_schema(),
    )
    write_csv(t, str(tmp_path / "in" / "x.csv"), header=False)
    spark = Session(PipelineConfig(mesh=MeshConfig(data=8, model=1)))
    q = (
        spark.read_stream.schema(ht.hospital_event_schema())
        .option("header", "false")
        .csv(str(tmp_path / "in"))
        .write_stream.option("checkpointLocation", str(tmp_path / "ck"))
        .start()  # Spark-style no-arg start (regression: used to TypeError)
    )
    infos = q.process_available()
    assert sum(i.num_appended_rows for i in infos) == 2
    spark.stop()
