"""VectorIndexer + UnivariateFeatureSelector/ChiSqSelector.

The StringIndexer → VectorIndexer → categorical-tree loop is the
reference's intended categorical flow (``mllearnforhospitalnetwork.py:29``,
SURVEY.md D5); the selectors reuse the chi2/ANOVA/F-value device tests.
"""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht

pytestmark = pytest.mark.fast


def _mixed_table(rng, n=800):
    ward = rng.integers(0, 4, size=n).astype(np.float64) * 2  # values {0,2,4,6}
    sev = rng.normal(size=n)
    los = np.array([0.0, 8.0, 1.0, 9.0])[(ward / 2).astype(int)] + sev
    t = ht.Table.from_dict({"ward_raw": ward, "severity": sev, "los": los})
    return ht.VectorAssembler(["ward_raw", "severity"]).transform(t)


class TestVectorIndexer:
    def test_detects_and_reencodes_categorical(self, rng, mesh8):
        at = _mixed_table(rng)
        m = ht.VectorIndexer(max_categories=10).fit(at)
        # ward_raw has 4 distinct values → categorical; severity continuous
        assert set(m.category_maps) == {0}
        assert m.categorical_features == {0: 4}
        out = m.transform(at)
        # values {0,2,4,6} → indices {0,1,2,3}, ascending-value order
        assert set(np.unique(out.features[:, 0])) == {0.0, 1.0, 2.0, 3.0}
        np.testing.assert_array_equal(
            out.features[:, 1], at.features[:, 1]  # continuous untouched
        )

    def test_feeds_categorical_trees(self, rng, mesh8):
        at = _mixed_table(rng)
        m = ht.VectorIndexer(max_categories=10).fit(at)
        out = m.transform(at)
        tree = ht.DecisionTreeRegressor(
            max_depth=2, label_col="los",
            categorical_features=m.categorical_features,
        ).fit(out, mesh=mesh8)
        pred = tree.transform(out, label_col="los", mesh=mesh8)
        assert ht.RegressionEvaluator("rmse").evaluate(pred) < 1.5

    def test_handle_invalid_modes(self, rng):
        at = _mixed_table(rng)
        m = ht.VectorIndexer(max_categories=10).fit(at)
        probe = np.array([[3.0, 0.0]])  # 3 is not in {0,2,4,6}
        with pytest.raises(ValueError, match="unseen"):
            m.transform(probe)
        m_keep = ht.VectorIndexer(max_categories=10, handle_invalid="keep").fit(at)
        assert m_keep.transform(probe)[0, 0] == 4.0  # reserved extra index
        assert m_keep.categorical_features == {0: 5}
        m_skip = ht.VectorIndexer(max_categories=10, handle_invalid="skip").fit(at)
        assert m_skip.transform(probe).shape[0] == 0

    def test_round_trip(self, rng, tmp_path):
        at = _mixed_table(rng)
        m = ht.VectorIndexer(max_categories=10).fit(at)
        m.save(str(tmp_path / "vi"))
        back = ht.load_model(str(tmp_path / "vi"))
        np.testing.assert_array_equal(
            back.transform(at.features), m.transform(at.features)
        )
        assert back.categorical_features == m.categorical_features


class TestUnivariateFeatureSelector:
    def test_anova_selection(self, rng, mesh8):
        n, d = 1000, 6
        y = rng.integers(0, 3, size=n).astype(np.float64)
        x = rng.normal(size=(n, d))
        x[:, 1] += y           # informative
        x[:, 4] += 2 * y       # most informative
        t = ht.Table.from_dict(
            {**{f"f{j}": x[:, j] for j in range(d)}, "cls": y}
        )
        at = ht.VectorAssembler([f"f{j}" for j in range(d)]).transform(t)
        sel = ht.UnivariateFeatureSelector(
            feature_type="continuous", label_type="categorical",
            selection_mode="numTopFeatures", selection_threshold=2,
            label_col="cls",
        ).fit(at, mesh=mesh8)
        assert set(sel.selected) == {1, 4}
        out = sel.transform(at)
        assert out.features.shape == (n, 2)
        assert out.feature_cols == ("f1", "f4")

    def test_fvalue_and_fpr_modes(self, rng, mesh8):
        n, d = 1200, 5
        x = rng.normal(size=(n, d))
        y = 3.0 * x[:, 2] + rng.normal(size=n)
        t = ht.Table.from_dict(
            {**{f"f{j}": x[:, j] for j in range(d)}, "target": y}
        )
        at = ht.VectorAssembler([f"f{j}" for j in range(d)]).transform(t)
        sel = ht.UnivariateFeatureSelector(
            feature_type="continuous", label_type="continuous",
            selection_mode="fpr", selection_threshold=1e-6,
            label_col="target",
        ).fit(at, mesh=mesh8)
        assert tuple(sel.selected) == (2,)

    def test_chi2_selector(self, rng, mesh8):
        n = 900
        y = rng.integers(0, 2, size=n).astype(np.float64)
        f0 = y.copy()                                   # perfectly dependent
        f1 = rng.integers(0, 3, size=n).astype(np.float64)  # independent
        t = ht.Table.from_dict({"f0": f0, "f1": f1, "lbl": y})
        at = ht.VectorAssembler(["f0", "f1"]).transform(t)
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.features import (
            ChiSqSelector,
        )

        sel = ChiSqSelector(num_top_features=1, label_col="lbl").fit(at, mesh=mesh8)
        assert tuple(sel.selected) == (0,)

    def test_invalid_combination_and_round_trip(self, rng, mesh8, tmp_path):
        at = _mixed_table(rng)
        with pytest.raises(ValueError, match="no Spark test"):
            ht.UnivariateFeatureSelector(
                feature_type="categorical", label_type="continuous",
                label_col="los",
            ).fit(at, mesh=mesh8)
        sel = ht.UnivariateFeatureSelector(
            feature_type="continuous", label_type="continuous",
            selection_mode="numTopFeatures", selection_threshold=1,
            label_col="los",
        ).fit(at, mesh=mesh8)
        sel.save(str(tmp_path / "sel"))
        assert ht.load_model(str(tmp_path / "sel")).selected == sel.selected
