"""GaussianMixture, BisectingKMeans, StreamingKMeans tests."""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models import (
    BisectingKMeans,
    GaussianMixture,
    StreamingKMeans,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import load_model


def _blobs(rng, n=600, k=3, d=4, spread=0.2, scale=4.0):
    centers = rng.normal(scale=scale, size=(k, d))
    labels = rng.integers(0, k, n)
    x = centers[labels] + rng.normal(scale=spread, size=(n, d))
    return x.astype(np.float64), labels, centers


# ---------------------------------------------------------------- GMM
@pytest.mark.fast
def test_gmm_recovers_components(rng, mesh8):
    x, labels, true_centers = _blobs(rng)
    model = GaussianMixture(k=3, seed=0).fit(x, mesh=mesh8)
    assert model.weights.shape == (3,)
    np.testing.assert_allclose(model.weights.sum(), 1.0, atol=1e-5)
    dist = np.linalg.norm(true_centers[:, None] - model.means[None], axis=2)
    assert dist.min(axis=1).max() < 0.3
    # responsibilities are near-deterministic on well-separated blobs
    proba = np.asarray(model.predict_proba(ht.device_dataset(x, mesh=mesh8).x))
    valid = proba[: len(x)]
    assert (valid.max(axis=1) > 0.95).mean() > 0.95


def test_gmm_loglik_improves(rng, mesh8):
    x, _, _ = _blobs(rng, n=400)
    m1 = GaussianMixture(k=3, seed=0, max_iter=1).fit(x, mesh=mesh8)
    m20 = GaussianMixture(k=3, seed=0, max_iter=40).fit(x, mesh=mesh8)
    assert m20.log_likelihood >= m1.log_likelihood - 1e-6


def test_gmm_sklearn_parity(rng, mesh8):
    from sklearn.mixture import GaussianMixture as SK

    x, _, _ = _blobs(rng, n=500, k=3)
    ours = GaussianMixture(k=3, seed=0, max_iter=100).fit(x, mesh=mesh8)
    sk = SK(n_components=3, random_state=0, n_init=3).fit(x)
    # mean per-sample log-likelihood should be close
    assert abs(ours.avg_log_likelihood - sk.score(x)) < 0.25


@pytest.mark.fast
def test_gmm_factor_logpdf_matches_solve_form(rng):
    """The matmul E-step (x @ stacked-L⁻ᵀ) must reproduce the triangular-
    solve log-densities exactly (modulo f32 matmul rounding)."""
    import jax
    import jax.numpy as jnp

    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.gmm import (
        _batched_log_pdf,
        _chol_log_pdf,
        _pdf_factors,
    )

    k, d, n = 4, 6, 300
    a = rng.standard_normal((k, d, d)).astype(np.float32)
    covs = jnp.asarray(a @ np.transpose(a, (0, 2, 1)) + 2 * np.eye(d, dtype=np.float32))
    chols = jnp.linalg.cholesky(covs)
    means = jnp.asarray(rng.standard_normal((k, d)).astype(np.float32))
    x = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32) * 2)
    ref = jax.vmap(lambda m, L: _chol_log_pdf(x, m, L))(means, chols).T
    got = _batched_log_pdf(x, *_pdf_factors(means, chols), "highest")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-4)


def test_gmm_bf16_precision_parity(rng, mesh8):
    """matmul_precision="bf16" (one-pass MXU mode) must land in the same
    optimum on separated blobs — same gate shape as the KMeans bench A/B."""
    x, _, _ = _blobs(rng, n=500)
    exact = GaussianMixture(k=3, seed=0, max_iter=40).fit(x, mesh=mesh8)
    fast = GaussianMixture(
        k=3, seed=0, max_iter=40, matmul_precision="bf16"
    ).fit(x, mesh=mesh8)
    assert abs(fast.avg_log_likelihood - exact.avg_log_likelihood) < 0.05
    dist = np.linalg.norm(exact.means[:, None] - fast.means[None], axis=2)
    assert dist.min(axis=1).max() < 0.1


def test_gmm_bad_precision_raises(rng, mesh8):
    x, _, _ = _blobs(rng, n=50)
    with pytest.raises(ValueError, match="matmul_precision"):
        GaussianMixture(k=2, matmul_precision="fp8").fit(x, mesh=mesh8)


def test_gmm_save_load(rng, mesh8, tmp_path):
    x, _, _ = _blobs(rng, n=200)
    model = GaussianMixture(k=2, seed=0).fit(x, mesh=mesh8)
    model.save(str(tmp_path / "gmm"))
    loaded = load_model(str(tmp_path / "gmm"))
    np.testing.assert_allclose(loaded.means, model.means)
    np.testing.assert_allclose(loaded.covariances, model.covariances)


def test_gmm_large_offset_covariances(rng, mesh8):
    """Unstandardized data whose mean dwarfs its spread: the chunked E/M
    scan recenters rows so the f32 covariance refit Σr·xxᵀ/nk − μμᵀ keeps
    its signal (regression guard for the moment-formula cancellation)."""
    x, labels, _ = _blobs(rng, n=900, k=2, spread=0.5, scale=3.0)
    m0 = GaussianMixture(k=2, seed=0).fit(x, mesh=mesh8)
    m1 = GaussianMixture(k=2, seed=0).fit(x + 1.0e4, mesh=mesh8)
    assert np.all(np.isfinite(m1.covariances))
    # same fit up to the translation: match components by weight ordering
    o0, o1 = np.argsort(m0.weights), np.argsort(m1.weights)
    np.testing.assert_allclose(
        m1.means[o1] - 1.0e4, m0.means[o0], rtol=0, atol=0.05
    )
    np.testing.assert_allclose(
        m1.covariances[o1], m0.covariances[o0], rtol=0.2, atol=0.05
    )


# ---------------------------------------------------- BisectingKMeans
def test_bisecting_recovers_blobs(rng, mesh8):
    x, labels, true_centers = _blobs(rng, k=4)
    model = BisectingKMeans(k=4, seed=0).fit(x, mesh=mesh8)
    assert model.cluster_centers.shape[0] == 4
    dist = np.linalg.norm(true_centers[:, None] - model.cluster_centers[None], axis=2)
    assert dist.min(axis=1).max() < 0.3
    assert model.cluster_sizes.sum() == len(x)


def test_bisecting_hierarchy_cost_decreases(rng, mesh8):
    x, _, _ = _blobs(rng, k=4)
    m2 = BisectingKMeans(k=2, seed=0).fit(x, mesh=mesh8)
    m4 = BisectingKMeans(k=4, seed=0).fit(x, mesh=mesh8)
    assert m4.training_cost < m2.training_cost


def test_bisecting_sequential_beats_level_on_budget_trap(rng, mesh8):
    """k below the level fan-out: strict level-order (Spark semantics) can
    waste budget halving a pure cluster; sequential largest-SSE (sklearn
    biggest_inertia) must recover all 4 true centers tightly."""
    x, _, true_centers = _blobs(rng, n=2000, k=4, spread=0.3, scale=5.0)
    seq = BisectingKMeans(k=4, seed=0, strategy="sequential").fit(x, mesh=mesh8)
    assert seq.cluster_centers.shape[0] == 4
    dist = np.linalg.norm(true_centers[:, None] - seq.cluster_centers[None], axis=2)
    assert dist.min(axis=1).max() < 0.3
    lvl = BisectingKMeans(k=4, seed=0, strategy="level").fit(x, mesh=mesh8)
    assert seq.training_cost <= lvl.training_cost + 1e-3


def test_bisecting_strategy_validation(rng, mesh8):
    x, _, _ = _blobs(rng, n=100)
    with pytest.raises(ValueError, match="strategy"):
        BisectingKMeans(k=2, strategy="zigzag").fit(x, mesh=mesh8)


def test_bisecting_duplicate_points_terminate(rng, mesh8):
    """k larger than the number of distinct points: splits of duplicate-only
    clusters fail gracefully and the fit terminates with 2 clusters."""
    x = np.repeat(np.array([[0.0, 0.0], [5.0, 5.0]]), 50, axis=0)
    for strategy in ("level", "sequential"):
        m = BisectingKMeans(k=4, seed=0, strategy=strategy).fit(x, mesh=mesh8)
        assert m.cluster_centers.shape[0] == 2
        assert m.cluster_sizes.sum() == len(x)


def test_bisecting_large_offset_data(rng, mesh8):
    """Unstandardized data whose mean dwarfs its spread: the root-SSE /
    distance math must not cancel in f32 (regression: a moment-formula root
    SSE collapsed the seeding radius and returned 1 cluster)."""
    x, _, true_centers = _blobs(rng, n=1000, k=2, spread=0.2, scale=2.0)
    x = x + 1.0e4
    model = BisectingKMeans(k=2, seed=0).fit(x, mesh=mesh8)
    assert model.cluster_centers.shape[0] == 2
    dist = np.linalg.norm(
        (true_centers + 1.0e4)[:, None] - model.cluster_centers[None], axis=2
    )
    assert dist.min(axis=1).max() < 1.0


def test_bisecting_min_divisible(rng, mesh8):
    x, _, _ = _blobs(rng, n=100, k=2)
    # min size larger than any cluster → no split beyond the root
    model = BisectingKMeans(k=4, seed=0, min_divisible_cluster_size=1000).fit(x, mesh=mesh8)
    assert model.cluster_centers.shape[0] == 1


# ---------------------------------------------------- StreamingKMeans
def test_streaming_update_many_matches_sequential(rng, mesh8):
    """The one-dispatch backlog drain (lax.scan over stacked batches) is
    bit-identical to per-batch update() calls for equal-length batches
    (same shapes → same XLA reduction tiling), and numerically identical
    (f32 reduction-order ulps only) for ragged ones."""
    x, _, _ = _blobs(rng, n=2400, k=3)
    batches = [x[i : i + 300] for i in range(0, 2400, 300)]

    seq = StreamingKMeans(k=3, decay_factor=0.9, seed=7)
    for b in batches:
        seq.update(b, mesh=mesh8)
    many = StreamingKMeans(k=3, decay_factor=0.9, seed=7)
    many.update_many(batches, mesh=mesh8)

    ms, mm = seq.latest_model, many.latest_model
    np.testing.assert_array_equal(ms.cluster_centers, mm.cluster_centers)
    np.testing.assert_array_equal(ms.cluster_weights, mm.cluster_weights)
    assert ms.n_iter == mm.n_iter == len(batches)

    # ragged batches: pad-with-inert-rows changes reduction tiling, so
    # equality is numerical (ulp-level), not bitwise; half-life "points"
    # mode also exercises per-batch-mass-dependent alpha
    sizes = [300, 250, 300, 250, 300, 250]
    offs = np.cumsum([0] + sizes)
    ragged = [x[offs[i] : offs[i + 1]] for i in range(len(sizes))]
    seq2 = StreamingKMeans(k=3, half_life=500.0, time_unit="points", seed=3)
    for b in ragged:
        seq2.update(b, mesh=mesh8)
    many2 = StreamingKMeans(k=3, half_life=500.0, time_unit="points", seed=3)
    many2.update_many(ragged[:3], mesh=mesh8).update_many(ragged[3:], mesh=mesh8)
    np.testing.assert_allclose(
        seq2.latest_model.cluster_centers,
        many2.latest_model.cluster_centers,
        rtol=1e-5,
    )

    # empty backlog is a no-op
    st = many.latest_model.cluster_centers.copy()
    many.update_many([], mesh=mesh8)
    np.testing.assert_array_equal(many.latest_model.cluster_centers, st)

    # update_many accepts the same batch forms update() does: (x, y)
    # tuples and DeviceDatasets drain to the same state as bare arrays
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.sharding import (
        device_dataset,
    )

    forms = StreamingKMeans(k=3, decay_factor=0.9, seed=7)
    forms.update_many(
        [batches[0], (batches[1], np.zeros(len(batches[1]))),
         device_dataset(batches[2], mesh=mesh8)] + batches[3:],
        mesh=mesh8,
    )
    np.testing.assert_array_equal(
        forms.latest_model.cluster_centers, mm.cluster_centers
    )


def test_streaming_kmeans_converges_on_stream(rng, mesh8):
    x, labels, true_centers = _blobs(rng, n=2000, k=3)
    sk = StreamingKMeans(k=3, decay_factor=1.0, seed=0)
    for i in range(0, 2000, 250):
        sk.update(x[i : i + 250], mesh=mesh8)
    model = sk.latest_model
    dist = np.linalg.norm(true_centers[:, None] - model.cluster_centers[None], axis=2)
    assert dist.min(axis=1).max() < 0.3
    assert model.n_iter == 8


def test_streaming_kmeans_weights_survive_f32_saturation(mesh8):
    """Kahan-compensated weights: with decay 1.0, per-batch counts keep
    accumulating even after a cluster passes 2^24 points (where a plain
    f32 accumulator would stop growing)."""
    s = StreamingKMeans(k=1, decay_factor=1.0, seed=0)
    s.set_initial_centers(np.zeros((1, 2)), np.array([2.0**24]))
    for _ in range(4):
        s.update(np.zeros((1000, 2)), mesh=mesh8)
    w = float(s.latest_model.cluster_weights[0])
    assert w == pytest.approx(2.0**24 + 4000, rel=1e-9)


def test_streaming_kmeans_bad_time_unit_raises(mesh8):
    s = StreamingKMeans(k=2, half_life=5.0, time_unit="batch")  # typo'd unit
    with pytest.raises(ValueError, match="time_unit"):
        s.update(np.zeros((10, 2)), mesh=mesh8)


def test_streaming_kmeans_decay_forgets(rng, mesh8):
    d = 3
    old = rng.normal(size=(300, d)) + np.array([10.0, 0, 0])
    new = rng.normal(size=(300, d)) + np.array([-10.0, 0, 0])
    # full memory: centers stay influenced by old data
    s_full = StreamingKMeans(k=1, decay_factor=1.0, seed=0)
    s_full.update(old, mesh=mesh8)
    s_full.update(new, mesh=mesh8)
    # zero memory: centers jump to the new batch
    s_zero = StreamingKMeans(k=1, decay_factor=0.0, seed=0)
    s_zero.update(old, mesh=mesh8)
    s_zero.update(new, mesh=mesh8)
    assert abs(s_full.latest_model.cluster_centers[0, 0] - 0.0) < 1.0
    assert abs(s_zero.latest_model.cluster_centers[0, 0] + 10.0) < 1.0


def test_streaming_kmeans_half_life(rng, mesh8):
    s = StreamingKMeans(k=1, half_life=1.0, time_unit="batches", seed=0)
    s.update(np.zeros((100, 2)) + 4.0, mesh=mesh8)
    s.update(np.zeros((100, 2)) - 4.0, mesh=mesh8)
    # half-life 1 batch → old weight halved: center = (4*0.5*100 + -4*100)/(150)
    np.testing.assert_allclose(
        s.latest_model.cluster_centers[0, 0], (4 * 50 - 4 * 100) / 150, atol=1e-4
    )


def test_streaming_kmeans_save_load(rng, mesh8, tmp_path):
    x, _, _ = _blobs(rng, n=300, k=2)
    s = StreamingKMeans(k=2, seed=0)
    s.update(x, mesh=mesh8)
    s.latest_model.save(str(tmp_path / "skm"))
    loaded = load_model(str(tmp_path / "skm"))
    np.testing.assert_allclose(loaded.cluster_centers, s.latest_model.cluster_centers)
    assert loaded.cluster_weights is not None


def test_bisecting_cosine_fit_predict_consistent(rng, mesh8):
    """Cosine geometry honored during training: predictions on the training
    data match the training partition sizes (regression: fit used euclidean
    while predict normalized)."""
    a = rng.normal(size=(100, 3)) * 0.05 + np.array([1.0, 0, 0])
    b = rng.normal(size=(100, 3)) * 0.05 + np.array([0, 1.0, 0])
    x = np.concatenate([a * 1.0, b * 5.0])
    model = BisectingKMeans(k=2, seed=0, distance_measure="cosine").fit(x, mesh=mesh8)
    pred = model.predict_numpy(x)
    sizes = np.sort(np.bincount(pred, minlength=2))
    np.testing.assert_array_equal(sizes, np.sort(model.cluster_sizes.astype(int)))
    assert set(np.bincount(pred, minlength=2)) == {100}


def test_gmm_close_blobs_regression(rng, mesh8):
    """5 blobs with one close pair (regression: global-covariance init made
    EM merge the close pair)."""
    rng2 = np.random.default_rng(42)
    tc = rng2.normal(scale=4.0, size=(5, 4))
    labels = rng2.integers(0, 5, 2000)
    x = tc[labels] + rng2.normal(scale=0.25, size=(2000, 4))
    gm = GaussianMixture(k=5, seed=0).fit(x, mesh=mesh8)
    err = np.linalg.norm(tc[:, None] - gm.means[None], axis=2).min(axis=1).max()
    assert err < 0.2


def test_streaming_kmeans_empty_batch_keeps_centers(rng, mesh8):
    """Empty micro-batch with zero decay must not collapse centers to zero
    (regression: 0-mass merge divided by epsilon)."""
    x = rng.normal(size=(100, 2)) + 5.0
    s = StreamingKMeans(k=2, decay_factor=0.0, seed=0)
    s.update(x, mesh=mesh8)
    before = s.latest_model.cluster_centers.copy()
    s.update(np.zeros((0, 2)), mesh=mesh8)
    np.testing.assert_allclose(s.latest_model.cluster_centers, before)


def test_gmm_predict_assigned_matches_proba(rng, mesh8):
    """Chunked fused argmax+posterior == argmax over the full (n, k)
    responsibility matrix, including on sharded inputs."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.sharding import (
        device_dataset,
        unpad,
    )

    centers = np.array([[0.0, 0.0], [6.0, 0.0], [0.0, 6.0]])
    a = rng.integers(0, 3, 901)
    x = (centers[a] + rng.normal(scale=0.5, size=(901, 2))).astype(np.float32)
    gm = ht.GaussianMixture(k=3, seed=0, max_iter=40).fit(x, mesh=mesh8)

    import jax.numpy as jnp

    p = np.asarray(gm.predict_proba(jnp.asarray(x)))
    pred_c, prob_c = gm.predict_assigned(jnp.asarray(x), chunk=128)
    np.testing.assert_array_equal(np.asarray(pred_c), p.argmax(1))
    np.testing.assert_allclose(
        np.asarray(prob_c), p[np.arange(len(x)), p.argmax(1)], atol=1e-5
    )

    ds = device_dataset(x, mesh=mesh8)
    pred_s, prob_s = gm.predict_assigned(ds.x, chunk=128)
    np.testing.assert_array_equal(unpad(pred_s, len(x)), p.argmax(1))
    np.testing.assert_allclose(
        unpad(prob_s, len(x)), p[np.arange(len(x)), p.argmax(1)], atol=1e-5
    )
