"""GeneralizedLinearRegression (sharded IRLS) and OneVsRest.

GLM parity targets: gaussian ≡ the WLS LinearRegression; binomial ≡ this
framework's own Newton logistic fit; poisson/gamma vs sklearn's
PoissonRegressor/GammaRegressor (log link, unpenalized).
"""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht


class TestGLM:
    @pytest.mark.fast
    def test_gaussian_equals_wls(self, rng, mesh8):
        n, d = 2000, 4
        x = rng.normal(size=(n, d)).astype(np.float32)
        beta = rng.normal(size=d)
        y = (x @ beta + 1.0 + 0.1 * rng.normal(size=n)).astype(np.float32)
        glm = ht.GeneralizedLinearRegression(family="gaussian").fit(
            (x, y), mesh=mesh8
        )
        wls = ht.LinearRegression().fit((x, y), mesh=mesh8)
        np.testing.assert_allclose(
            glm.coefficients, np.asarray(wls.coefficients), rtol=1e-4, atol=1e-4
        )
        np.testing.assert_allclose(
            glm.intercept, float(wls.intercept), rtol=1e-4, atol=1e-4
        )

    def test_binomial_equals_logistic(self, rng, mesh8):
        n, d = 3000, 3
        x = rng.normal(size=(n, d)).astype(np.float32)
        p = 1 / (1 + np.exp(-(x @ [1.0, -2.0, 0.5] + 0.3)))
        y = (rng.uniform(size=n) < p).astype(np.float32)
        glm = ht.GeneralizedLinearRegression(family="binomial").fit(
            (x, y), mesh=mesh8
        )
        logit = ht.LogisticRegression(max_iter=50).fit((x, y), mesh=mesh8)
        np.testing.assert_allclose(
            glm.coefficients, np.asarray(logit.coefficients), rtol=2e-3, atol=2e-3
        )
        # mean prediction is a probability
        mu = np.asarray(glm.predict_numpy(x))
        assert np.all((mu >= 0) & (mu <= 1))

    def test_poisson_matches_sklearn(self, rng, mesh8):
        sklm = pytest.importorskip("sklearn.linear_model")
        n, d = 4000, 3
        x = rng.normal(0, 0.5, size=(n, d)).astype(np.float32)
        rate = np.exp(x @ [0.8, -0.5, 0.3] + 0.7)
        y = rng.poisson(rate).astype(np.float32)
        glm = ht.GeneralizedLinearRegression(family="poisson").fit(
            (x, y), mesh=mesh8
        )
        ref = sklm.PoissonRegressor(alpha=0.0, max_iter=300).fit(x, y)
        np.testing.assert_allclose(glm.coefficients, ref.coef_, rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(glm.intercept, ref.intercept_, rtol=2e-3, atol=2e-3)

    def test_gamma_log_link_matches_sklearn(self, rng, mesh8):
        sklm = pytest.importorskip("sklearn.linear_model")
        n, d = 4000, 2
        x = rng.normal(0, 0.4, size=(n, d)).astype(np.float32)
        mu = np.exp(x @ [0.6, -0.4] + 1.0)
        y = rng.gamma(shape=4.0, scale=mu / 4.0).astype(np.float32)
        glm = ht.GeneralizedLinearRegression(family="gamma", link="log").fit(
            (x, y), mesh=mesh8
        )
        ref = sklm.GammaRegressor(alpha=0.0, max_iter=300).fit(x, y)
        np.testing.assert_allclose(glm.coefficients, ref.coef_, rtol=5e-3, atol=5e-3)
        np.testing.assert_allclose(glm.intercept, ref.intercept_, rtol=5e-3)

    def test_deviance_and_link_prediction(self, rng, mesh8):
        x = rng.normal(size=(500, 2)).astype(np.float32)
        y = rng.poisson(np.exp(0.5 * x[:, 0])).astype(np.float32)
        m = ht.GeneralizedLinearRegression(family="poisson").fit((x, y), mesh=mesh8)
        assert np.isfinite(m.deviance) and m.deviance >= 0
        eta = np.asarray(m.predict_link(ht.device_dataset(x, mesh=mesh8).x))
        mu = np.asarray(m.predict(ht.device_dataset(x, mesh=mesh8).x))
        np.testing.assert_allclose(np.exp(eta), mu, rtol=1e-5)

    def test_round_trip_and_validation(self, rng, mesh8, tmp_path):
        x = np.abs(rng.normal(size=(256, 2))).astype(np.float32) + 0.1
        y = (x[:, 0] * 2 + 0.5).astype(np.float32)
        m = ht.GeneralizedLinearRegression(family="gamma").fit((x, y), mesh=mesh8)
        m.write().overwrite().save(str(tmp_path / "glm"))
        back = ht.load_model(str(tmp_path / "glm"))
        np.testing.assert_allclose(back.predict_numpy(x), m.predict_numpy(x))
        assert back.family == "gamma" and back.link == "inverse"
        with pytest.raises(ValueError, match="family"):
            ht.GeneralizedLinearRegression(family="negbinomial").fit(
                (x, y), mesh=mesh8
            )
        with pytest.raises(ValueError, match="link"):
            ht.GeneralizedLinearRegression(family="binomial", link="log").fit(
                (x, (y > 1).astype(np.float32)), mesh=mesh8
            )
        with pytest.raises(ValueError, match="0/1"):
            ht.GeneralizedLinearRegression(family="binomial").fit((x, y), mesh=mesh8)
        with pytest.raises(ValueError, match="positive"):
            ht.GeneralizedLinearRegression(family="gamma").fit(
                (x, y - 10.0), mesh=mesh8
            )
        # gaussian + log link: log(y<=0) would silently NaN the fit
        with pytest.raises(ValueError, match="positive"):
            ht.GeneralizedLinearRegression(family="gaussian", link="log").fit(
                (x, y - 10.0), mesh=mesh8
            )


class TestGLMTweedie:
    """family="tweedie" (Spark's variancePower/linkPower surface)."""

    def test_matches_sklearn(self, rng, mesh8):
        sklm = pytest.importorskip("sklearn.linear_model")
        n, d = 5000, 3
        x = rng.normal(0, 0.4, size=(n, d)).astype(np.float32)
        mu = np.exp(x @ [0.7, -0.4, 0.2] + 0.8)
        # compound-poisson-ish draw: gamma noise with occasional zeros
        y = (rng.gamma(shape=2.0, scale=mu / 2.0)
             * (rng.uniform(size=n) > 0.1)).astype(np.float32)
        ours = ht.GeneralizedLinearRegression(
            family="tweedie", variance_power=1.5, link_power=0.0, max_iter=50
        ).fit((x, y), mesh=mesh8)
        ref = sklm.TweedieRegressor(
            power=1.5, alpha=0.0, link="log", max_iter=500, tol=1e-8
        ).fit(x, y)
        np.testing.assert_allclose(ours.coefficients, ref.coef_, rtol=5e-3, atol=5e-3)
        np.testing.assert_allclose(ours.intercept, ref.intercept_, rtol=5e-3)
        assert ours.link == "power" and ours.link_power == 0.0

    def test_default_link_power(self, rng, mesh8):
        """link_power defaults to 1 − variancePower (Spark's rule)."""
        x = np.abs(rng.normal(size=(2000, 2))).astype(np.float32) + 0.5
        y = (x @ np.array([1.0, 0.5], np.float32) + 1.0).astype(np.float32)
        m = ht.GeneralizedLinearRegression(
            family="tweedie", variance_power=1.5, max_iter=50
        ).fit((x, y), mesh=mesh8)
        assert m.link_power == -0.5
        assert np.all(np.isfinite(np.asarray(m.coefficients)))
        # μ prediction is positive
        assert np.all(np.asarray(m.predict_numpy(x)) > 0)

    def test_special_powers_collapse_to_named_families(self, rng, mesh8):
        """variance_power 0/1/2 reproduce gaussian/poisson/gamma."""
        n, d = 3000, 2
        x = rng.normal(0, 0.4, size=(n, d)).astype(np.float32)
        rate = np.exp(x @ [0.8, -0.5] + 0.6)
        y = rng.poisson(rate).astype(np.float32)
        tw = ht.GeneralizedLinearRegression(
            family="tweedie", variance_power=1.0, link_power=0.0, max_iter=50
        ).fit((x, y), mesh=mesh8)
        po = ht.GeneralizedLinearRegression(family="poisson", max_iter=50).fit(
            (x, y), mesh=mesh8
        )
        np.testing.assert_allclose(
            tw.coefficients, po.coefficients, rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(tw.deviance, po.deviance, rtol=1e-4)

    def test_summary_and_persistence(self, rng, mesh8, tmp_path):
        x = rng.normal(0, 0.4, size=(3000, 2)).astype(np.float32)
        mu = np.exp(x @ [0.6, -0.3] + 0.5)
        y = (rng.gamma(shape=3.0, scale=mu / 3.0)
             * (rng.uniform(size=3000) > 0.05)).astype(np.float32)
        m = ht.GeneralizedLinearRegression(
            family="tweedie", variance_power=1.3, link_power=0.0, max_iter=50
        ).fit((x, y), mesh=mesh8)
        s = m.summary
        assert s.null_deviance > s.deviance > 0
        assert np.isfinite(s.dispersion) and s.dispersion > 0
        assert len(s.coefficient_standard_errors) == 3
        assert (s.p_values[:2] < 1e-4).all()
        with pytest.raises(RuntimeError, match="tweedie"):
            s.aic
        m.write().overwrite().save(str(tmp_path / "tw"))
        back = ht.load_model(str(tmp_path / "tw"))
        assert back.variance_power == 1.3 and back.link_power == 0.0
        np.testing.assert_allclose(back.predict_numpy(x), m.predict_numpy(x))

    def test_validation(self, rng, mesh8):
        x = np.abs(rng.normal(size=(128, 2))).astype(np.float32)
        y = np.abs(rng.normal(size=128)).astype(np.float32) + 0.1
        with pytest.raises(ValueError, match="variance_power"):
            ht.GeneralizedLinearRegression(
                family="tweedie", variance_power=0.5
            ).fit((x, y), mesh=mesh8)
        with pytest.raises(ValueError, match="positive"):
            ht.GeneralizedLinearRegression(
                family="tweedie", variance_power=2.5
            ).fit((x, y - 10.0), mesh=mesh8)
        with pytest.raises(ValueError, match="non-negative"):
            ht.GeneralizedLinearRegression(
                family="tweedie", variance_power=1.5
            ).fit((x, y - 10.0), mesh=mesh8)
        with pytest.raises(ValueError, match="link"):
            ht.GeneralizedLinearRegression(family="tweedie", link="log").fit(
                (x, y), mesh=mesh8
            )


class TestGLMSummary:
    """GeneralizedLinearRegressionTrainingSummary parity.

    statsmodels is not in the image, so the oracle is an independent
    NumPy reference computed in-test from the model's own fitted μ:
    deviance / null deviance / Pearson χ² / IRLS-weighted Gram standard
    errors use the textbook formulas (McCullagh & Nelder) directly on
    host float64 — a different code path from the device reductions
    under test."""

    @staticmethod
    def _np_reference(x, y, coef, intercept, family, link):
        eta = x.astype(np.float64) @ np.asarray(coef, np.float64) + float(intercept)
        inv = {"log": np.exp, "identity": lambda e: e,
               "logit": lambda e: 1 / (1 + np.exp(-e)),
               "inverse": lambda e: 1 / e}[link]
        mu = inv(eta)
        ybar = y.mean()
        if family == "poisson":
            ysafe = np.maximum(y, 1e-300)
            dev = 2 * np.sum(np.where(y > 0, y * np.log(ysafe / mu), 0) - (y - mu))
            dev0 = 2 * np.sum(
                np.where(y > 0, y * np.log(ysafe / ybar), 0) - (y - ybar)
            )
            V = mu
            gp = 1 / mu
        elif family == "gamma":
            dev = 2 * np.sum(-np.log(y / mu) + (y - mu) / mu)
            dev0 = 2 * np.sum(-np.log(y / ybar) + (y - ybar) / ybar)
            V = mu**2
            gp = {"log": 1 / mu, "inverse": -1 / mu**2}[link]
        else:  # gaussian identity
            dev = np.sum((y - mu) ** 2)
            dev0 = np.sum((y - ybar) ** 2)
            V = np.ones_like(mu)
            gp = np.ones_like(mu)
        pearson = np.sum((y - mu) ** 2 / V)
        xa = np.c_[x.astype(np.float64), np.ones(len(y))]
        om = 1.0 / (gp * gp * V)
        gram = (xa * om[:, None]).T @ xa
        cov = np.linalg.inv(gram)
        return dict(dev=dev, dev0=dev0, pearson=pearson,
                    se=np.sqrt(np.diag(cov)), mu=mu)

    def test_poisson_summary_vs_numpy(self, rng, mesh8):
        n, d = 4000, 3
        x = rng.normal(0, 0.5, size=(n, d)).astype(np.float32)
        y = rng.poisson(np.exp(x @ [0.8, -0.5, 0.3] + 0.7)).astype(np.float32)
        m = ht.GeneralizedLinearRegression(family="poisson").fit((x, y), mesh=mesh8)
        s = m.summary
        ref = self._np_reference(x, y, m.coefficients, m.intercept, "poisson", "log")
        np.testing.assert_allclose(s.deviance, ref["dev"], rtol=1e-4)
        np.testing.assert_allclose(s.null_deviance, ref["dev0"], rtol=1e-4)
        np.testing.assert_allclose(s.pearson_chi_squared, ref["pearson"], rtol=1e-4)
        assert s.dispersion == 1.0
        np.testing.assert_allclose(
            s.coefficient_standard_errors, ref["se"], rtol=2e-3
        )
        # AIC = −2ℓ + 2·rank with ℓ the exact poisson loglik
        from scipy.special import gammaln

        ll = np.sum(y * np.log(ref["mu"]) - ref["mu"] - gammaln(y + 1.0))
        np.testing.assert_allclose(s.aic, -2 * ll + 2 * s.rank, rtol=1e-5)
        # strong true effects → tiny p-values; t = beta/se
        assert (s.p_values[:3] < 1e-6).all()
        np.testing.assert_allclose(
            s.t_values,
            np.r_[np.asarray(m.coefficients, np.float64), m.intercept] / ref["se"],
            rtol=2e-3,
        )
        assert s.num_instances == n
        assert s.degrees_of_freedom == n - 4
        assert s.residual_degree_of_freedom_null == n - 1

    def test_gamma_summary_vs_numpy(self, rng, mesh8):
        n, d = 4000, 2
        x = rng.normal(0, 0.4, size=(n, d)).astype(np.float32)
        mu = np.exp(x @ [0.6, -0.4] + 1.0)
        y = rng.gamma(shape=4.0, scale=mu / 4.0).astype(np.float32)
        m = ht.GeneralizedLinearRegression(family="gamma", link="log").fit(
            (x, y), mesh=mesh8
        )
        s = m.summary
        ref = self._np_reference(x, y, m.coefficients, m.intercept, "gamma", "log")
        np.testing.assert_allclose(s.deviance, ref["dev"], rtol=1e-3)
        np.testing.assert_allclose(s.null_deviance, ref["dev0"], rtol=1e-3)
        # moment dispersion ≈ 1/shape = 0.25 for gamma(shape=4) noise
        disp = ref["pearson"] / (n - 3)
        np.testing.assert_allclose(s.dispersion, disp, rtol=1e-3)
        np.testing.assert_allclose(
            s.coefficient_standard_errors, ref["se"] * np.sqrt(disp), rtol=2e-3
        )
        assert 0.2 < s.dispersion < 0.32
        # gamma AIC: −2·Σ log f(y; a=1/φ, scale=μφ) + 2(rank+1)
        from scipy import stats as sps

        a = 1.0 / s.dispersion
        ll = np.sum(sps.gamma.logpdf(y, a, scale=ref["mu"] * s.dispersion))
        np.testing.assert_allclose(s.aic, -2 * ll + 2 * (s.rank + 1), rtol=1e-4)

    @pytest.mark.fast
    def test_gaussian_summary_matches_lr(self, rng, mesh8):
        n, d = 2000, 4
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (x @ rng.normal(size=d) + 1.0 + 0.3 * rng.normal(size=n)).astype(
            np.float32
        )
        glm = ht.GeneralizedLinearRegression(family="gaussian").fit(
            (x, y), mesh=mesh8
        )
        lr = ht.LinearRegression().fit((x, y), mesh=mesh8)
        s = glm.summary
        # same unregularized gaussian model → same inference statistics
        np.testing.assert_allclose(
            s.coefficient_standard_errors,
            lr.summary.coefficient_standard_errors,
            rtol=2e-3,
        )
        np.testing.assert_allclose(s.p_values, lr.summary.p_values, atol=1e-6)
        # dispersion = RSS/(n−p) = classic σ̂²
        np.testing.assert_allclose(
            s.dispersion, s.deviance / (n - 5), rtol=1e-6
        )
        # null deviance = TSS
        np.testing.assert_allclose(
            s.null_deviance, np.sum((y - y.mean()) ** 2), rtol=1e-4
        )
        # residual types
        r = s.residuals("response")
        np.testing.assert_allclose(
            r, y - np.asarray(glm.predict_numpy(x)), atol=1e-5
        )
        np.testing.assert_allclose(s.residuals("deviance"), r, atol=1e-5)
        np.testing.assert_allclose(s.residuals("pearson"), r, atol=1e-5)
        np.testing.assert_allclose(s.residuals("working"), r, atol=1e-5)
        with pytest.raises(ValueError, match="residuals_type"):
            s.residuals("anscombe")

    @pytest.mark.fast
    def test_summary_lifecycle(self, rng, mesh8, tmp_path):
        x = np.abs(rng.normal(size=(256, 2))).astype(np.float32) + 0.1
        y = (x[:, 0] * 2 + 0.5).astype(np.float32)
        m = ht.GeneralizedLinearRegression(family="gamma").fit((x, y), mesh=mesh8)
        assert m.has_summary
        m.write().overwrite().save(str(tmp_path / "g"))
        back = ht.load_model(str(tmp_path / "g"))
        assert not back.has_summary
        with pytest.raises(RuntimeError, match="no training summary"):
            back.summary
        # regularized fit refuses inference stats but serves deviance
        mr = ht.GeneralizedLinearRegression(family="gamma", reg_param=0.1).fit(
            (x, y), mesh=mesh8
        )
        assert np.isfinite(mr.summary.deviance)
        with pytest.raises(RuntimeError, match="unregularized"):
            mr.summary.coefficient_standard_errors


class TestOneVsRest:
    def test_multiclass_with_logistic(self, rng, mesh8):
        n = 1500
        centers = np.array([[0, 0], [4, 0], [0, 4]], np.float32)
        y = rng.integers(0, 3, size=n)
        x = (centers[y] + rng.normal(0, 0.8, size=(n, 2))).astype(np.float32)
        ovr = ht.OneVsRest(classifier=ht.LogisticRegression(max_iter=20)).fit(
            (x, y.astype(np.float32)), mesh=mesh8
        )
        assert ovr.num_classes == 3
        pred = np.asarray(ovr.predict_numpy(x))
        assert (pred == y).mean() > 0.95
        # agrees with the native multinomial softmax fit on easy data
        mlr = ht.LogisticRegression(family="multinomial", max_iter=20).fit(
            (x, y.astype(np.float32)), mesh=mesh8
        )
        agree = (pred == np.asarray(mlr.predict_numpy(x))).mean()
        assert agree > 0.97

    def test_with_tree_classifier_and_round_trip(self, rng, mesh8, tmp_path):
        n = 900
        y = rng.integers(0, 3, size=n)
        x = (y[:, None] * 2.0 + rng.normal(0, 0.4, size=(n, 2))).astype(np.float32)
        ovr = ht.OneVsRest(
            classifier=ht.DecisionTreeClassifier(max_depth=3)
        ).fit((x, y.astype(np.float32)), mesh=mesh8)
        pred = np.asarray(ovr.predict_numpy(x))
        assert (pred == y).mean() > 0.95
        ovr.write().overwrite().save(str(tmp_path / "ovr"))
        back = ht.load_model(str(tmp_path / "ovr"))
        np.testing.assert_array_equal(back.predict_numpy(x), pred)
        assert back.num_classes == 3

    def test_validation(self, rng, mesh8):
        x = rng.normal(size=(64, 2)).astype(np.float32)
        with pytest.raises(ValueError, match="classifier"):
            ht.OneVsRest().fit((x, np.zeros(64, np.float32)), mesh=mesh8)
        with pytest.raises(ValueError, match="2 classes"):
            ht.OneVsRest(classifier=ht.LogisticRegression()).fit(
                (x, np.zeros(64, np.float32)), mesh=mesh8
            )
        with pytest.raises(ValueError, match="weight_col"):
            ht.OneVsRest(
                classifier=ht.LogisticRegression(weight_col="w")
            ).fit((x, np.array([0.0, 1.0] * 32, np.float32)), mesh=mesh8)


class TestIsotonicRegression:
    @pytest.mark.fast
    def test_matches_sklearn(self, rng, mesh8):
        ski = pytest.importorskip("sklearn.isotonic")
        n = 2000
        x = rng.uniform(0, 10, size=n).astype(np.float32)
        y = (np.sqrt(x) + 0.3 * rng.normal(size=n)).astype(np.float32)
        m = ht.IsotonicRegression().fit((x[:, None], y), mesh=mesh8)
        ref = ski.IsotonicRegression(out_of_bounds="clip").fit(x, y)
        probe = rng.uniform(-1, 11, size=500).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(m.predict_numpy(probe[:, None])),
            ref.predict(probe),
            atol=1e-4,
        )

    def test_decreasing_weighted_round_trip(self, rng, mesh8, tmp_path):
        ski = pytest.importorskip("sklearn.isotonic")
        n = 1200
        x = rng.uniform(0, 5, size=n)
        y = 5.0 - x + 0.2 * rng.normal(size=n)
        w = rng.integers(1, 4, size=n).astype(np.float64)
        m = ht.IsotonicRegression(isotonic=False).fit(
            (x[:, None].astype(np.float32), y.astype(np.float32), w), mesh=mesh8
        )
        ref = ski.IsotonicRegression(increasing=False, out_of_bounds="clip").fit(
            x, y, sample_weight=w
        )
        probe = rng.uniform(0, 5, size=300)
        np.testing.assert_allclose(
            np.asarray(m.predict_numpy(probe[:, None].astype(np.float32))),
            ref.predict(probe),
            atol=1e-4,
        )
        m.write().overwrite().save(str(tmp_path / "iso"))
        back = ht.load_model(str(tmp_path / "iso"))
        np.testing.assert_array_equal(
            back.predict_numpy(probe[:, None].astype(np.float32)),
            m.predict_numpy(probe[:, None].astype(np.float32)),
        )

    def test_feature_index_and_validation(self, rng, mesh8):
        n = 400
        x = rng.normal(size=(n, 3)).astype(np.float32)
        y = (2 * x[:, 2] + 0.1 * rng.normal(size=n)).astype(np.float32)
        m = ht.IsotonicRegression(feature_index=2).fit((x, y), mesh=mesh8)
        pred = np.asarray(m.predict_numpy(x))
        assert np.corrcoef(pred, y)[0, 1] > 0.95
        with pytest.raises(ValueError, match="feature_index"):
            ht.IsotonicRegression(feature_index=7).fit((x, y), mesh=mesh8)


class TestLinearSVC:
    @pytest.mark.fast
    def test_matches_sklearn_squared_hinge(self, rng, mesh8):
        sksvm = pytest.importorskip("sklearn.svm")
        n, d = 2000, 3
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = ((x @ [1.5, -1.0, 0.5] + 0.2) > 0).astype(np.float32)
        lam = 0.01
        ours = ht.LinearSVC(reg_param=lam, standardize=False).fit((x, y), mesh=mesh8)
        # sklearn: min ½wᵀw + C Σ max(0,1−m)²  ⇔  ours (λ/2‖β‖² + MEAN
        # loss): divide sklearn's objective by Cn → λ = 1/(Cn), i.e.
        # C = 1/(λn)
        ref = sksvm.LinearSVC(
            C=1.0 / (lam * n), loss="squared_hinge", max_iter=20000, tol=1e-8
        ).fit(x, y)
        np.testing.assert_allclose(
            np.asarray(ours.coefficients), ref.coef_[0], rtol=5e-2, atol=5e-3
        )
        pred = np.asarray(ours.predict_numpy(x))
        agree = (pred == ref.predict(x)).mean()
        assert agree > 0.995

    def test_separable_weighted_and_round_trip(self, rng, mesh8, tmp_path):
        n = 800
        x = np.concatenate(
            [rng.normal(-2, 0.5, size=(n // 2, 2)), rng.normal(2, 0.5, size=(n // 2, 2))]
        ).astype(np.float32)
        y = np.concatenate([np.zeros(n // 2), np.ones(n // 2)]).astype(np.float32)
        w = rng.integers(1, 4, size=n).astype(np.float64)
        m = ht.LinearSVC(reg_param=0.01).fit((x, y, w), mesh=mesh8)
        assert (np.asarray(m.predict_numpy(x)) == y).mean() == 1.0
        rep = np.repeat(np.arange(n), w.astype(int))
        md = ht.LinearSVC(reg_param=0.01).fit((x[rep], y[rep]), mesh=mesh8)
        np.testing.assert_allclose(
            np.asarray(m.coefficients), np.asarray(md.coefficients), atol=1e-4
        )
        m.write().overwrite().save(str(tmp_path / "svc"))
        back = ht.load_model(str(tmp_path / "svc"))
        np.testing.assert_array_equal(back.predict_numpy(x), m.predict_numpy(x))

    def test_validation_and_ovr_compose(self, rng, mesh8):
        x = rng.normal(size=(300, 2)).astype(np.float32)
        with pytest.raises(ValueError, match="binary"):
            ht.LinearSVC().fit((x, rng.integers(0, 3, 300).astype(np.float32)), mesh=mesh8)
        # SVC as the OneVsRest inner classifier (margin-based confidence)
        y3 = rng.integers(0, 3, size=300)
        x3 = (np.array([[0, 0], [6, 0], [0, 6]])[y3] + rng.normal(0, 0.7, (300, 2))).astype(np.float32)
        ovr = ht.OneVsRest(classifier=ht.LinearSVC(reg_param=0.01)).fit(
            (x3, y3.astype(np.float32)), mesh=mesh8
        )
        assert (np.asarray(ovr.predict_numpy(x3)) == y3).mean() > 0.95


class TestGLMOffset:
    """offset_col (Spark's offsetCol): η = Xβ + b + offset."""

    def test_poisson_log_exposure(self, rng, mesh8):
        """Counts ~ Poisson(exposure · e^{xβ+b}): fitting with
        offset = log(exposure) must recover the RATE coefficients (and a
        no-offset fit must not)."""
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

        n, d = 6000, 2
        x = rng.normal(0, 0.5, size=(n, d)).astype(np.float32)
        exposure = rng.uniform(0.2, 5.0, size=n).astype(np.float32)
        rate = np.exp(x @ [0.8, -0.5] + 0.3)
        y = rng.poisson(exposure * rate).astype(np.float32)

        tab = Table.from_dict(
            {
                "f0": x[:, 0], "f1": x[:, 1],
                "label": y,
                "log_exposure": np.log(exposure).astype(np.float32),
            }
        )
        asm = ht.VectorAssembler(["f0", "f1"]).transform(tab)
        m = ht.GeneralizedLinearRegression(
            family="poisson", label_col="label", offset_col="log_exposure",
            max_iter=50,
        ).fit(asm, mesh=mesh8)
        np.testing.assert_allclose(
            m.coefficients, [0.8, -0.5], atol=0.05
        )
        np.testing.assert_allclose(m.intercept, 0.3, atol=0.05)

        # summary statistics are offset-aware
        s = m.summary
        assert s.null_deviance > s.deviance > 0
        assert (s.p_values[:2] < 1e-6).all()

        # serving with the offset reproduces the fitted mean
        mu = np.asarray(m.predict(x, offset=np.log(exposure)))
        np.testing.assert_allclose(
            mu, exposure * np.exp(x @ np.asarray(m.coefficients) + m.intercept),
            rtol=1e-4,
        )

        # the no-offset fit is confounded by exposure — worse deviance
        m0 = ht.GeneralizedLinearRegression(
            family="poisson", label_col="label", max_iter=50
        ).fit(asm, mesh=mesh8)
        assert m0.deviance > m.deviance

    def test_offset_needs_table(self, rng, mesh8):
        x = rng.normal(size=(64, 2)).astype(np.float32)
        y = np.abs(rng.normal(size=64)).astype(np.float32)
        with pytest.raises(ValueError, match="offset_col"):
            ht.GeneralizedLinearRegression(offset_col="o").fit((x, y), mesh=mesh8)


def test_tweedie_power0_is_gaussian_on_negative_data(rng, mesh8):
    """variance_power=0 must be EXACT gaussian semantics — negative labels
    and means are legal (review finding: μ was clamped to 1e-8)."""
    n, d = 2000, 2
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x @ [1.5, -2.0] - 5.0 + 0.1 * rng.normal(size=n)).astype(np.float32)
    tw = ht.GeneralizedLinearRegression(
        family="tweedie", variance_power=0.0, link_power=1.0, max_iter=50
    ).fit((x, y), mesh=mesh8)
    ga = ht.GeneralizedLinearRegression(family="gaussian").fit((x, y), mesh=mesh8)
    np.testing.assert_allclose(tw.coefficients, ga.coefficients, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(tw.intercept, ga.intercept, rtol=1e-4)
    # negative means survive (no 1e-8 clamp); tail rows may cross zero
    assert np.mean(np.asarray(tw.predict_numpy(x)) < 0) > 0.95


def test_tweedie_power_link_domain_violation_is_nan(rng, mesh8):
    """η ≤ 0 is outside the μ^linkPower domain for fractional powers; the
    inverse link must surface NaN (visible divergence) rather than clamp
    to an extreme μ (advisor finding: 1e-12 clamp hid garbage fits)."""
    import jax.numpy as jnp

    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.glm import _link_fns

    _, inv, _ = _link_fns("power", link_power=0.5)
    out = np.asarray(inv(jnp.asarray([-1.0, 0.0, 4.0], jnp.float32)))
    assert np.isnan(out[0])
    # η = 0 is the domain BOUNDARY, not a violation: μ = 0^2 = 0 (Spark's
    # math.pow semantics)
    np.testing.assert_allclose(out[1], 0.0)
    np.testing.assert_allclose(out[2], 16.0, rtol=1e-6)


def test_offset_null_deviance_is_offset_aware(rng, mesh8):
    """null_deviance for an offset fit must come from the offset-aware
    intercept-only model (review finding: it used the plain weighted
    mean).  Oracle: 1-D scipy minimization of the intercept-only poisson
    deviance with offset."""
    from scipy.optimize import minimize_scalar

    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

    n = 4000
    x = rng.normal(0, 0.5, size=(n, 2)).astype(np.float32)
    exposure = rng.uniform(0.2, 5.0, size=n).astype(np.float32)
    y = rng.poisson(exposure * np.exp(x @ [0.8, -0.5] + 0.3)).astype(np.float32)
    tab = Table.from_dict(
        {
            "f0": x[:, 0], "f1": x[:, 1], "label": y,
            "log_exposure": np.log(exposure).astype(np.float32),
        }
    )
    m = ht.GeneralizedLinearRegression(
        family="poisson", label_col="label", offset_col="log_exposure",
        max_iter=50,
    ).fit(ht.VectorAssembler(["f0", "f1"]).transform(tab), mesh=mesh8)

    def null_dev(b0):
        mu = np.exp(b0) * exposure
        t = np.where(y > 0, y * np.log(np.maximum(y, 1e-300) / mu), 0.0)
        return 2.0 * np.sum(t - (y - mu))

    best = minimize_scalar(null_dev, bounds=(-5, 5), method="bounded")
    np.testing.assert_allclose(m.summary.null_deviance, best.fun, rtol=1e-4)
    assert m.summary.null_deviance > m.summary.deviance
