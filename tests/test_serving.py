"""serve/ — registry, shape buckets, micro-batching, degradation.

Covers the subsystem's three contracts:

1. any family ``io/model_io`` can round-trip serves through the registry
   with save→load→predict parity (the MLlib ``transform()`` gap the
   serving layer closes);
2. bucket padding never changes a real row's prediction, and steady-state
   serving after warmup triggers ZERO recompiles (cross-checked against
   the jit cache itself where available);
3. overload degrades gracefully — saturated queues shed at admission,
   expired deadlines answer degraded, nothing hangs, the queue stays
   bounded.
"""

import threading
import time

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu import serve


pytestmark = pytest.mark.fast


@pytest.fixture
def xy(rng):
    n, d = 96, 4
    x = rng.normal(size=(n, d)).astype(np.float32)
    y_reg = (x @ np.array([1.0, -2.0, 0.5, 0.0]) + 0.3).astype(np.float32)
    y_cls = (y_reg > 0).astype(np.float32)
    return x, y_reg, y_cls


#: (family name, estimator factory, which label column it trains on)
FAMILIES = [
    ("linear_regression", lambda: ht.LinearRegression(max_iter=20), "reg"),
    ("logistic_regression", lambda: ht.LogisticRegression(max_iter=20), "cls"),
    ("linear_svc", lambda: ht.LinearSVC(max_iter=20), "cls"),
    ("naive_bayes", lambda: ht.NaiveBayes(model_type="gaussian"), "cls"),
    ("decision_tree", lambda: ht.DecisionTreeRegressor(max_depth=3), "reg"),
    ("random_forest", lambda: ht.RandomForestRegressor(num_trees=3, max_depth=3), "reg"),
    ("gbt", lambda: ht.GBTRegressor(max_iter=3, max_depth=2), "reg"),
    ("kmeans", lambda: ht.KMeans(k=3, max_iter=5, seed=0), None),
    ("gmm", lambda: ht.GaussianMixture(k=2, max_iter=5, seed=0), None),
]


def _fit(factory, label, x, y_reg, y_cls):
    est = factory()
    if label is None:
        return est.fit(x)
    return est.fit((x, y_reg if label == "reg" else y_cls))


@pytest.mark.parametrize("name,factory,label", FAMILIES, ids=[f[0] for f in FAMILIES])
def test_save_load_serve_roundtrip(tmp_path, xy, name, factory, label):
    """save → load_model → registry → bucketed predict parity, for every
    family the registry must serve."""
    x, y_reg, y_cls = xy
    model = _fit(factory, label, x, y_reg, y_cls)
    path = str(tmp_path / name)
    model.save(path)

    reg = serve.ModelRegistry()
    sm = reg.load(name, path, buckets=(1, 4, 16, 128))
    assert sm.n_features == x.shape[1]  # num_features inferred post-load
    expect = model.predict_numpy(x)
    got = sm.predict(x)
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)


def test_bucket_ladder_selection():
    assert serve.bucket_for(1, (1, 2, 4)) == 1
    assert serve.bucket_for(3, (1, 2, 4)) == 4
    assert serve.bucket_for(4, (1, 2, 4)) == 4
    with pytest.raises(ValueError, match="largest bucket"):
        serve.bucket_for(5, (1, 2, 4))
    with pytest.raises(ValueError):
        serve.bucket_for(0, (1, 2, 4))


def test_bucket_padding_parity(xy):
    """Padded bucketed predict == unpadded predict for every request size
    that lands mid-bucket (the pad rows must be inert)."""
    x, y_reg, _ = xy
    model = ht.LinearRegression(max_iter=20).fit((x, y_reg))
    sm = serve.ServingModel(model, buckets=(1, 2, 4, 8, 16, 32)).warmup()
    for n in (1, 2, 3, 5, 8, 13, 31):
        np.testing.assert_allclose(
            sm.predict(x[:n]),
            model.predict_numpy(x[:n]),
            rtol=1e-5, atol=1e-6,
            err_msg=f"padding leaked at n={n}",
        )
    # oversized request streams through the top bucket, same answers
    np.testing.assert_allclose(
        sm.predict(x[:70]), model.predict_numpy(x[:70]), rtol=1e-5, atol=1e-6
    )


def test_zero_recompiles_after_warmup(xy):
    x, y_reg, _ = xy
    model = ht.LinearRegression(max_iter=20).fit((x, y_reg))
    sm = serve.ServingModel(model, buckets=(1, 2, 4, 8, 16)).warmup()
    warm_cache = sm.jit_cache_size()
    assert sm.metrics.recompile_count == 0
    for n in (1, 3, 7, 13, 16, 2, 9, 1, 5):  # ≥3 distinct sizes, shuffled
        sm.predict(x[:n])
    assert sm.metrics.recompile_count == 0
    if warm_cache is not None:  # cross-check against the jit cache itself
        assert sm.jit_cache_size() == warm_cache
    snap = sm.metrics.snapshot()
    assert snap["warmup_compiles"] == 5
    assert 0 < snap["batch_fill_ratio"] <= 1.0


def test_recompile_counter_detects_cold_shape(xy):
    """A bucket NOT in the warmed ladder must be visible in the counter —
    the alarm the zero-recompile assertion relies on."""
    x, y_reg, _ = xy
    model = ht.LinearRegression(max_iter=20).fit((x, y_reg))
    sm = serve.ServingModel(model, buckets=(1, 2, 4, 8))
    sm.warmup(buckets=(1, 2, 4))  # deliberately partial
    sm.predict(x[:7])             # lands in the cold 8-bucket
    assert sm.metrics.recompile_count == 1


def test_microbatcher_coalesces_and_answers_all(xy):
    x, y_reg, _ = xy
    model = ht.LinearRegression(max_iter=20).fit((x, y_reg))
    sm = serve.ServingModel(model, buckets=(1, 2, 4, 8, 16, 32)).warmup()
    expect = model.predict_numpy(x)
    with serve.MicroBatcher(sm, max_queue_rows=256) as mb:
        reqs = [mb.submit(x[i]) for i in range(48)]
        res = [r.wait(10.0) for r in reqs]
    assert all(r.ok for r in res)
    got = np.concatenate([r.value for r in res])
    np.testing.assert_allclose(got, expect[:48], rtol=1e-5, atol=1e-6)
    # coalescing actually happened (strictly fewer batches than requests)
    snap = sm.metrics.snapshot()
    assert snap["batches"] < snap["requests"]


def test_saturated_queue_sheds_not_hangs(xy):
    """Acceptance gate: queue artificially saturated → overflow requests
    get an immediate degraded answer, queue depth stays bounded, nothing
    hangs."""
    x, y_reg, _ = xy
    model = ht.LinearRegression(max_iter=20).fit((x, y_reg))
    prior = float(np.mean(y_reg))
    sm = serve.ServingModel(model, buckets=(1, 2, 4)).warmup()
    mb = serve.MicroBatcher(
        sm, max_queue_rows=8,
        fallback=lambda rows: np.full(rows.shape[0], prior, np.float32),
    )
    # worker NOT started: the queue saturates by construction
    t0 = time.monotonic()
    reqs = [mb.submit(x[i]) for i in range(50)]
    admission_s = time.monotonic() - t0
    assert admission_s < 2.0  # no blocking admission
    shed = [r for r in reqs if r._result is not None]
    assert len(shed) == 42  # everything beyond the 8-row bound
    for r in shed:
        out = r.wait(0.1)
        assert out.status == serve.STATUS_REJECTED
        assert out.degraded and out.value is not None
        np.testing.assert_allclose(out.value, [prior])
    assert mb.queue.depth_rows == 8  # bounded, not growing
    # the queued 8 are served once the worker starts — no lost requests
    mb.start()
    served = [r.wait(10.0) for r in reqs[:8]]
    assert all(r.ok for r in served)
    mb.stop()


def test_deadline_exceeded_degrades(xy):
    x, y_reg, _ = xy
    model = ht.LinearRegression(max_iter=20).fit((x, y_reg))
    prior = float(np.mean(y_reg))
    sm = serve.ServingModel(model, buckets=(1, 2, 4)).warmup()
    mb = serve.MicroBatcher(
        sm, max_queue_rows=64,
        fallback=lambda rows: np.full(rows.shape[0], prior, np.float32),
    )
    # enqueue with a deadline that expires before the worker exists
    req = mb.submit(x[0], deadline_s=0.01)
    time.sleep(0.05)
    mb.start()
    out = req.wait(10.0)
    assert out.status == serve.STATUS_DEADLINE_EXCEEDED
    assert out.degraded
    np.testing.assert_allclose(out.value, [prior])
    # a patient request right behind it is served normally
    ok = mb.predict(x[1])
    assert ok.ok
    mb.stop()
    # stop() answers stragglers instead of stranding them
    late = mb.submit(x[2])
    assert late.wait(1.0).status in (serve.STATUS_REJECTED, serve.STATUS_SHUTDOWN)


def test_stop_answers_queued_requests(xy):
    x, y_reg, _ = xy
    model = ht.LinearRegression(max_iter=20).fit((x, y_reg))
    sm = serve.ServingModel(model, buckets=(1, 2)).warmup()
    mb = serve.MicroBatcher(sm, max_queue_rows=64)  # never started
    reqs = [mb.submit(x[i]) for i in range(5)]
    mb.stop()
    for r in reqs:
        assert r.wait(1.0).status == serve.STATUS_SHUTDOWN


def test_inference_server_multi_model_and_stats(xy):
    x, y_reg, y_cls = xy
    reg_m = ht.LinearRegression(max_iter=20).fit((x, y_reg))
    cls_m = ht.LogisticRegression(max_iter=20).fit((x, y_cls))
    srv = serve.InferenceServer(max_queue_rows=256)
    srv.add_model("los", reg_m, buckets=(1, 2, 4, 8))
    srv.add_model("risk", cls_m, buckets=(1, 2, 4, 8))
    with srv:
        a = srv.predict("los", x[:3])
        b = srv.predict("risk", x[:3])
        assert a.ok and b.ok
        np.testing.assert_allclose(a.value, reg_m.predict_numpy(x[:3]), rtol=1e-5)
        np.testing.assert_allclose(b.value, cls_m.predict_numpy(x[:3]), rtol=1e-5)
        with pytest.raises(KeyError):
            srv.predict("nope", x[:1])
        stats = srv.stats()
    assert stats["recompiles"] == 0
    assert set(stats["models"]) == {"los", "risk"}
    assert stats["latency_p50_ms"] > 0


def test_concurrent_clients_all_answered(xy):
    """Many threads × mixed batch sizes: every request answered OK, zero
    recompiles, predictions correct."""
    x, y_reg, _ = xy
    model = ht.LinearRegression(max_iter=20).fit((x, y_reg))
    expect = model.predict_numpy(x)
    sm = serve.ServingModel(model, buckets=(1, 2, 4, 8, 16, 32)).warmup()
    errs: list = []
    with serve.MicroBatcher(sm, max_queue_rows=1024) as mb:
        def client(size: int) -> None:
            for i in range(20):
                s = (i * size) % (len(x) - size)
                r = mb.predict(x[s : s + size], wait_timeout_s=30.0)
                if not r.ok:
                    errs.append(r.status)
                elif not np.allclose(r.value, expect[s : s + size], rtol=1e-4, atol=1e-5):
                    errs.append(f"wrong value at {s}+{size}")
        threads = [
            threading.Thread(target=client, args=(sz,)) for sz in (1, 3, 7, 16)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
    assert not errs
    assert sm.metrics.recompile_count == 0


def test_bulk_score_matches_predict(xy, mesh8):
    x, y_reg, _ = xy
    model = ht.LinearRegression(max_iter=20).fit((x, y_reg))
    expect = model.predict_numpy(x)
    np.testing.assert_allclose(
        serve.bulk_score(model, x, mesh=mesh8), expect, rtol=1e-5, atol=1e-6
    )
    # chunked path (chunk smaller than the job) through one fixed shape
    np.testing.assert_allclose(
        serve.bulk_score(model, x, mesh=mesh8, chunk_rows=32),
        expect, rtol=1e-5, atol=1e-6,
    )
    scorer = serve.ShardedScorer(model, mesh=mesh8, chunk_rows=32).warmup()
    np.testing.assert_allclose(scorer.score(x), expect, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(scorer.score(x[:5]), expect[:5], rtol=1e-5, atol=1e-6)
