"""NaiveBayes (multinomial + gaussian) vs sklearn; QuantileDiscretizer."""

import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht


pytestmark = pytest.mark.fast


def test_multinomial_nb_matches_sklearn(rng, mesh8):
    sknb = pytest.importorskip("sklearn.naive_bayes")
    # count-like features from two different multinomial profiles
    n, d = 1500, 6
    y = rng.integers(0, 3, size=n)
    profiles = rng.dirichlet(np.ones(d), size=3)
    x = np.stack([rng.multinomial(40, profiles[c]) for c in y]).astype(np.float32)

    ours = ht.NaiveBayes(smoothing=1.0).fit((x, y.astype(np.float32)), mesh=mesh8)
    # Spark's MLlib smooths the priors with the Laplace λ too (pi =
    # log(n_c+λ) − log(n+kλ)), unlike sklearn's log(n_c/n); hand sklearn
    # that prior so every piece matches exactly
    counts = np.bincount(y, minlength=3).astype(np.float64)
    spark_prior = (counts + 1.0) / (counts.sum() + 3.0)
    ref = sknb.MultinomialNB(alpha=1.0, class_prior=spark_prior).fit(x, y)
    np.testing.assert_allclose(ours.pi, ref.class_log_prior_, atol=1e-6)
    np.testing.assert_allclose(ours.theta, ref.feature_log_prob_, atol=1e-5)
    np.testing.assert_array_equal(ours.predict_numpy(x), ref.predict(x))


def test_gaussian_nb_matches_sklearn(rng, mesh8):
    sknb = pytest.importorskip("sklearn.naive_bayes")
    n, d = 1200, 4
    y = rng.integers(0, 2, size=n)
    centers = np.array([[0, 0, 0, 0], [2, -1, 1, 3]], dtype=np.float64)
    x = (centers[y] + rng.normal(0, 1.0, size=(n, d))).astype(np.float32)

    ours = ht.NaiveBayes(model_type="gaussian", var_smoothing=1e-9).fit(
        (x, y.astype(np.float32)), mesh=mesh8
    )
    ref = sknb.GaussianNB(var_smoothing=1e-9).fit(x, y)
    np.testing.assert_allclose(ours.theta, ref.theta_, atol=1e-4)
    np.testing.assert_allclose(ours.sigma, ref.var_, rtol=1e-3)
    agree = (ours.predict_numpy(x) == ref.predict(x)).mean()
    assert agree > 0.999
    # probabilities match too
    np.testing.assert_allclose(
        np.asarray(ours.predict_proba(ht.device_dataset(x, mesh=mesh8).x))[: n],
        ref.predict_proba(x),
        atol=1e-4,
    )


def test_bernoulli_nb_matches_sklearn(rng, mesh8):
    sknb = pytest.importorskip("sklearn.naive_bayes")
    n, d = 1200, 8
    y = rng.integers(0, 3, size=n)
    p = rng.uniform(0.1, 0.9, size=(3, d))
    x = (rng.uniform(size=(n, d)) < p[y]).astype(np.float32)

    ours = ht.NaiveBayes(model_type="bernoulli", smoothing=1.0).fit(
        (x, y.astype(np.float32)), mesh=mesh8
    )
    counts = np.bincount(y, minlength=3).astype(np.float64)
    spark_prior = (counts + 1.0) / (counts.sum() + 3.0)
    ref = sknb.BernoulliNB(alpha=1.0, class_prior=spark_prior).fit(x, y)
    np.testing.assert_allclose(ours.theta, ref.feature_log_prob_, atol=1e-5)
    np.testing.assert_array_equal(ours.predict_numpy(x), ref.predict(x))


def test_gaussian_nb_unsmoothed_priors_imbalanced(rng, mesh8):
    """Spark's gaussian path does NOT Laplace-smooth priors (λ is
    discrete-only); imbalanced classes expose any smoothing drift."""
    sknb = pytest.importorskip("sklearn.naive_bayes")
    y = np.concatenate([np.zeros(950), np.ones(50)]).astype(int)
    x = (np.array([[0.0], [2.0]])[y] + rng.normal(0, 1, size=(1000, 1))).astype(
        np.float32
    )
    ours = ht.NaiveBayes(model_type="gaussian").fit(
        (x, y.astype(np.float32)), mesh=mesh8
    )
    ref = sknb.GaussianNB().fit(x, y)
    np.testing.assert_allclose(ours.pi, np.log(ref.class_prior_), atol=1e-6)
    np.testing.assert_array_equal(ours.predict_numpy(x), ref.predict(x))


def test_bernoulli_nb_binarizes_at_predict(rng, mesh8):
    """Non-binary inputs at PREDICT time are binarized (x≠0 → 1, sklearn
    BernoulliNB semantics) rather than scored as raw counts."""
    xb = (rng.uniform(size=(400, 5)) < 0.5).astype(np.float32)
    y = (xb[:, 0] > 0).astype(np.float32)
    m = ht.NaiveBayes(model_type="bernoulli").fit((xb, y), mesh=mesh8)
    counts = xb * rng.integers(1, 40, size=xb.shape).astype(np.float32)
    np.testing.assert_array_equal(m.predict_numpy(counts), m.predict_numpy(xb))
    # sklearn's binarize=0.0 is x > 0: negatives map to ABSENT, not present
    neg = xb - 2.0 * (1.0 - xb)  # 1 stays 1, 0 becomes -2
    np.testing.assert_array_equal(m.predict_numpy(neg), m.predict_numpy(xb))


def test_bernoulli_nb_rejects_non_binary(rng, mesh8):
    x = rng.uniform(size=(64, 3)).astype(np.float32)
    y = rng.integers(0, 2, size=64).astype(np.float32)
    with pytest.raises(ValueError, match="0/1"):
        ht.NaiveBayes(model_type="bernoulli").fit((x, y), mesh=mesh8)


def test_complement_nb_matches_sklearn(rng, mesh8):
    sknb = pytest.importorskip("sklearn.naive_bayes")
    n, d = 1500, 6
    # imbalanced classes — the regime CNB exists for
    y = rng.choice(3, size=n, p=[0.7, 0.2, 0.1])
    profiles = rng.dirichlet(np.ones(d), size=3)
    x = np.stack([rng.multinomial(30, profiles[c]) for c in y]).astype(np.float32)

    ours = ht.NaiveBayes(model_type="complement", smoothing=1.0).fit(
        (x, y.astype(np.float32)), mesh=mesh8
    )
    ref = sknb.ComplementNB(alpha=1.0, norm=False).fit(x, y)
    np.testing.assert_allclose(ours.theta, ref.feature_log_prob_, atol=1e-5)
    np.testing.assert_array_equal(ours.predict_numpy(x), ref.predict(x))


def test_bernoulli_complement_round_trip(rng, mesh8, tmp_path):
    y = rng.integers(0, 2, size=200)
    xb = (rng.uniform(size=(200, 4)) < 0.5).astype(np.float32)
    for mt, x in (("bernoulli", xb), ("complement", xb * 3)):
        m = ht.NaiveBayes(model_type=mt).fit((x, y.astype(np.float32)), mesh=mesh8)
        m.write().overwrite().save(str(tmp_path / mt))
        back = ht.load_model(str(tmp_path / mt))
        np.testing.assert_array_equal(back.predict_numpy(x), m.predict_numpy(x))


def test_nb_weighted_equals_duplication(rng, mesh8):
    n, d = 600, 5
    y = rng.integers(0, 2, size=n).astype(np.float32)
    x = np.abs(rng.normal(size=(n, d))).astype(np.float32)
    w = rng.integers(1, 4, size=n).astype(np.float64)
    rep = np.repeat(np.arange(n), w.astype(int))
    m_w = ht.NaiveBayes().fit((x, y, w), mesh=mesh8)
    m_d = ht.NaiveBayes().fit((x[rep], y[rep]), mesh=mesh8)
    np.testing.assert_allclose(m_w.theta, m_d.theta, atol=1e-5)
    np.testing.assert_allclose(m_w.pi, m_d.pi, atol=1e-6)


def test_nb_validation_and_persistence(rng, mesh8, tmp_path):
    x = rng.normal(size=(100, 3)).astype(np.float32)  # has negatives
    y = rng.integers(0, 2, size=100).astype(np.float32)
    with pytest.raises(ValueError, match="non-negative"):
        ht.NaiveBayes().fit((x, y), mesh=mesh8)
    with pytest.raises(ValueError, match="model_type"):
        ht.NaiveBayes(model_type="poisson").fit((np.abs(x), y), mesh=mesh8)
    m = ht.NaiveBayes(model_type="gaussian").fit((x, y), mesh=mesh8)
    p = os.path.join(tmp_path, "nb")
    m.write().overwrite().save(p)
    back = ht.load_model(p)
    np.testing.assert_array_equal(back.predict_numpy(x), m.predict_numpy(x))


def test_nb_in_pipeline_with_evaluator(hospital_table, mesh8):
    pipe = ht.Pipeline(
        [
            ht.Binarizer("length_of_stay", "LOS_binary", 5.0),
            ht.VectorAssembler(ht.FEATURE_COLS),
            ht.NaiveBayes(model_type="gaussian", label_col="LOS_binary"),
        ]
    )
    train, test = ht.train_test_split(hospital_table, 0.7, 42)
    pm = pipe.fit(train, label_col="LOS_binary", mesh=mesh8)
    acc = ht.MulticlassClassificationEvaluator("accuracy").evaluate(
        pm.transform(test, label_col="LOS_binary", mesh=mesh8)
    )
    assert acc > 0.8


def test_gaussian_nb_large_mean_stability(rng, mesh8):
    """Globally-centered stats survive features whose mean dwarfs the
    within-class std (e.g. a year column) — the naive E[x²]−mean² form
    in f32 would produce garbage variances here."""
    sknb = pytest.importorskip("sklearn.naive_bayes")
    n = 2000
    y = rng.integers(0, 2, size=n)
    year = (2023.0 + y + rng.normal(0, 0.5, size=n)).astype(np.float32)
    other = (y * 2 + rng.normal(0, 1.0, size=n)).astype(np.float32)
    x = np.c_[year, other].astype(np.float32)
    ours = ht.NaiveBayes(model_type="gaussian").fit((x, y.astype(np.float32)), mesh=mesh8)
    ref = sknb.GaussianNB().fit(np.asarray(x, np.float64), y)
    np.testing.assert_allclose(ours.sigma, ref.var_, rtol=5e-3)
    agree = (ours.predict_numpy(x) == ref.predict(x)).mean()
    assert agree > 0.999


def test_gaussian_nb_nan_in_zero_weight_rows_inert(rng, mesh8):
    """w=0 rows are contractually inert — a NaN there must not poison the
    gaussian moments or trip the NaN guard; a NaN in a VALID row raises."""
    x = rng.normal(size=(200, 3)).astype(np.float32)
    y = rng.integers(0, 2, size=200).astype(np.float32)
    xz = x.copy()
    xz[-20:] = np.nan
    w = np.r_[np.ones(180), np.zeros(20)]
    m = ht.NaiveBayes(model_type="gaussian").fit((xz, y, w), mesh=mesh8)
    ref = ht.NaiveBayes(model_type="gaussian").fit((x[:180], y[:180]), mesh=mesh8)
    np.testing.assert_allclose(m.theta, ref.theta, atol=1e-5)
    bad_w = np.ones(200)
    with pytest.raises(ValueError, match="NaN"):
        ht.NaiveBayes(model_type="gaussian").fit((xz, y, bad_w), mesh=mesh8)


def test_chi_square_rejects_continuous_features(rng):
    x = rng.normal(size=(20000, 1))
    y = rng.integers(0, 2, size=20000)
    with pytest.raises(ValueError, match="distinct values"):
        ht.ChiSquareTest.test(x, y)


def test_quantile_discretizer_boundary_at_max():
    """A quantile boundary equal to the column max is a VALID split
    (closed top bucket) — Spark produces two buckets here."""
    tab = ht.Table.from_dict(
        {"v": np.array([1.0, 2.0, 2.0, 2.0])}, ht.Schema([("v", "float")])
    )
    bk = ht.QuantileDiscretizer(2, "v", "q").fit(tab)
    out = bk.transform(tab)
    np.testing.assert_array_equal(out.column("q"), [0, 1, 1, 1])


def test_quantile_discretizer(hospital_table):
    qd = ht.QuantileDiscretizer(4, "length_of_stay", "los_q")
    bk = qd.fit(hospital_table)
    out = bk.transform(hospital_table)
    counts = np.bincount(out.column("los_q"), minlength=4)
    # quartiles: roughly equal occupancy
    assert counts.min() > 0.15 * len(hospital_table)
    assert bk.num_buckets == 4
    # constant column cannot be discretized
    tab = ht.Table.from_dict({"c": np.ones(50)}, ht.Schema([("c", "float")]))
    with pytest.raises(ValueError, match="too few distinct"):
        ht.QuantileDiscretizer(3, "c", "cq").fit(tab)
    with pytest.raises(ValueError, match="num_buckets"):
        ht.QuantileDiscretizer(1, "c", "cq")
