"""pyspark.ml.tuning parity: ParamGridBuilder grids, CrossValidator k-fold
selection, TrainValidationSplit, param application to estimators and
Pipeline stages, selection-model persistence."""

import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.tuning.tuning import (
    apply_params,
)


@pytest.mark.fast
def test_param_grid_builder_cartesian():
    grid = (
        ht.ParamGridBuilder()
        .add_grid("reg_param", [0.0, 0.1, 1.0])
        .add_grid("elastic_net_param", [0.0, 1.0])
        .base_on({"max_iter": 500})
        .build()
    )
    assert len(grid) == 6
    assert all(g["max_iter"] == 500 for g in grid)
    assert {(g["reg_param"], g["elastic_net_param"]) for g in grid} == {
        (r, a) for r in (0.0, 0.1, 1.0) for a in (0.0, 1.0)
    }
    with pytest.raises(ValueError, match="empty"):
        ht.ParamGridBuilder().add_grid("x", [])


def test_apply_params_estimator_and_pipeline():
    est = ht.LinearRegression()
    out = apply_params(est, {"reg_param": 0.5})
    assert out.reg_param == 0.5 and est.reg_param != 0.5  # copy, not mutation

    pipe = ht.Pipeline(
        [ht.VectorAssembler(ht.FEATURE_COLS), ht.StandardScaler(), ht.LinearRegression()]
    )
    # bare key lands on the last stage having the field
    p2 = apply_params(pipe, {"reg_param": 0.3})
    assert p2.stages[2].reg_param == 0.3
    # dotted key targets an explicit stage
    p3 = apply_params(pipe, {"1.with_mean": False})
    assert p3.stages[1].with_mean is False

    with pytest.raises(ValueError, match="no param"):
        apply_params(est, {"nope": 1})
    with pytest.raises(ValueError, match="no pipeline stage"):
        apply_params(pipe, {"nope": 1})
    with pytest.raises(ValueError, match="out of range"):
        apply_params(pipe, {"9.reg_param": 1.0})


def _ridge_data(rng, n=3000, d=8):
    """Few informative dims + noise: heavy regularization should LOSE on
    validation rmse, so the grid has a clear right answer (lam=0)."""
    x = rng.normal(size=(n, d)).astype(np.float32)
    beta = np.array([2.0, -1.0, 1.5, 0.0, 0.0, 0.5, -2.5, 1.0])
    y = (x @ beta + 0.2 * rng.normal(size=n)).astype(np.float32)
    return x, y


def test_cross_validator_selects_lowest_rmse(rng, mesh8):
    x, y = _ridge_data(rng)
    grid = ht.ParamGridBuilder().add_grid("reg_param", [0.0, 1000.0]).build()
    cv = ht.CrossValidator(
        estimator=ht.LinearRegression(),
        param_maps=grid,
        evaluator=ht.RegressionEvaluator("rmse"),
        num_folds=3,
        seed=7,
    )
    cvm = cv.fit((x, y), mesh=mesh8)
    assert cvm.best_index == 0  # rmse is smaller-better; lam=0 wins
    assert cvm.avg_metrics[0] < cvm.avg_metrics[1]
    assert cvm.avg_metrics.shape == (2,)
    assert cvm.fold_metrics.shape == (2, 3)
    # best model was refit on the FULL data
    pred = cvm.transform((x, y), mesh=mesh8)
    rmse = ht.RegressionEvaluator("rmse").evaluate(pred)
    assert rmse < 0.3


def test_cross_validator_larger_better_metric(rng, mesh8, hospital_table):
    """Accuracy (larger-better) flips the argbest direction."""
    pipe = ht.Pipeline(
        [
            ht.Binarizer("length_of_stay", "LOS_binary", 5.0),
            ht.VectorAssembler(ht.FEATURE_COLS),
            ht.DecisionTreeClassifier(label_col="LOS_binary"),
        ]
    )
    grid = ht.ParamGridBuilder().add_grid("max_depth", [1, 5]).build()
    cv = ht.CrossValidator(
        estimator=pipe,
        param_maps=grid,
        evaluator=ht.MulticlassClassificationEvaluator("accuracy"),
        num_folds=2,
        seed=3,
    )
    cvm = cv.fit(hospital_table, label_col="LOS_binary", mesh=mesh8)
    # depth 5 separates the LOS signal better than a stump
    assert cvm.best_index == 1
    assert cvm.avg_metrics[1] >= cvm.avg_metrics[0]


def test_cross_validator_on_assembled_table(hospital_table, mesh8):
    asm = ht.VectorAssembler(ht.FEATURE_COLS).transform(hospital_table)
    grid = ht.ParamGridBuilder().add_grid("reg_param", [0.0, 100.0]).build()
    cvm = ht.CrossValidator(
        estimator=ht.LinearRegression(),
        param_maps=grid,
        evaluator=ht.RegressionEvaluator("rmse"),
        num_folds=2,
        seed=0,
    ).fit(asm, mesh=mesh8)
    assert cvm.best_index == 0


@pytest.mark.fast
def test_train_validation_split(rng, mesh8):
    x, y = _ridge_data(rng)
    grid = ht.ParamGridBuilder().add_grid("reg_param", [0.0, 1000.0]).build()
    tvs = ht.TrainValidationSplit(
        estimator=ht.LinearRegression(),
        param_maps=grid,
        evaluator=ht.RegressionEvaluator("rmse"),
        train_ratio=0.75,
        seed=5,
    )
    m = tvs.fit((x, y), mesh=mesh8)
    assert m.best_index == 0
    assert m.validation_metrics.shape == (2,)
    with pytest.raises(ValueError, match="train_ratio"):
        ht.TrainValidationSplit(
            ht.LinearRegression(), grid, ht.RegressionEvaluator(), train_ratio=1.5
        ).fit((x, y))


def test_selection_model_persistence(rng, mesh8, tmp_path):
    x, y = _ridge_data(rng)
    grid = ht.ParamGridBuilder().add_grid("reg_param", [0.0, 10.0]).build()
    cvm = ht.CrossValidator(
        ht.LinearRegression(), grid, ht.RegressionEvaluator("rmse"),
        num_folds=2, seed=1,
    ).fit((x, y), mesh=mesh8)
    p = os.path.join(tmp_path, "cvm")
    cvm.write().overwrite().save(p)
    back = ht.load_model(p)  # composite dispatch through the registry
    assert isinstance(back, ht.CrossValidatorModel)
    np.testing.assert_allclose(back.avg_metrics, cvm.avg_metrics)
    assert back.best_index == cvm.best_index
    assert back.param_maps == cvm.param_maps
    a, _ = cvm.transform((x, y), mesh=mesh8).to_numpy()
    b, _ = back.transform((x, y), mesh=mesh8).to_numpy()
    np.testing.assert_allclose(a, b, rtol=1e-6)

    tvm = ht.TrainValidationSplit(
        ht.LinearRegression(), grid, ht.RegressionEvaluator("rmse"), seed=2
    ).fit((x, y), mesh=mesh8)
    p2 = os.path.join(tmp_path, "tvm")
    tvm.save(p2)
    back2 = ht.load_model(p2)
    assert isinstance(back2, ht.TrainValidationSplitModel)
    np.testing.assert_allclose(back2.validation_metrics, tvm.validation_metrics)


def test_cross_validator_clustering_silhouette(rng, mesh8):
    """Clustering estimators tune through ClusteringEvaluator's
    (features, assignments) signature: the silhouette-best k wins."""
    centers = np.array([[0, 0], [8, 8], [-8, 8]], dtype=np.float32)
    x = np.concatenate(
        [c + rng.normal(0, 0.4, size=(300, 2)).astype(np.float32) for c in centers]
    )
    grid = ht.ParamGridBuilder().add_grid("k", [2, 3]).build()
    cvm = ht.CrossValidator(
        estimator=ht.KMeans(seed=0),
        param_maps=grid,
        evaluator=ht.ClusteringEvaluator(),
        num_folds=2,
        seed=9,
    ).fit(x, mesh=mesh8)
    assert cvm.best_index == 1  # true k=3 has the higher silhouette
    assert cvm.avg_metrics[1] > cvm.avg_metrics[0]


def test_cv_model_as_pipeline_stage_persists(rng, mesh8, tmp_path):
    """Spark's CV-inside-Pipeline pattern: the fitted selection model is a
    pipeline stage and the whole thing persists through the composite
    registry."""
    x, y = _ridge_data(rng, n=500)
    grid = ht.ParamGridBuilder().add_grid("reg_param", [0.0, 10.0]).build()
    cv = ht.CrossValidator(
        ht.LinearRegression(), grid, ht.RegressionEvaluator("rmse"),
        num_folds=2, seed=1,
    )
    pm = ht.Pipeline([cv]).fit((x, y), mesh=mesh8)
    assert isinstance(pm.stages[0], ht.CrossValidatorModel)
    p = os.path.join(tmp_path, "pm_cv")
    pm.save(p)
    back = ht.load_model(p)
    assert isinstance(back.stages[0], ht.CrossValidatorModel)
    a, _ = pm.transform((x, y), mesh=mesh8).to_numpy()
    b, _ = back.transform((x, y), mesh=mesh8).to_numpy()
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_selection_save_preserves_existing_artifact(rng, mesh8, tmp_path):
    """A failed selection-model save must not destroy the old artifact."""
    import dataclasses

    x, y = _ridge_data(rng, n=400)
    grid = ht.ParamGridBuilder().add_grid("reg_param", [0.0]).build()
    cvm = ht.CrossValidator(
        ht.LinearRegression(), grid, ht.RegressionEvaluator("rmse"),
        num_folds=2,
    ).fit((x, y), mesh=mesh8)
    p = os.path.join(tmp_path, "cvm")
    cvm.save(p)

    class Opaque:
        def transform(self, data):
            return data

    bad = dataclasses.replace(cvm, best_model=Opaque())
    with pytest.raises(TypeError, match="not persistable"):
        bad.save(p, overwrite=True)
    assert isinstance(ht.load_model(p), ht.CrossValidatorModel)


def test_cv_sub_models_persist(rng, mesh8, tmp_path):
    """collect_sub_models=True survives a save/load round-trip."""
    x, y = _ridge_data(rng, n=400)
    grid = ht.ParamGridBuilder().add_grid("reg_param", [0.0, 5.0]).build()
    cvm = ht.CrossValidator(
        ht.LinearRegression(), grid, ht.RegressionEvaluator("rmse"),
        num_folds=2, collect_sub_models=True,
    ).fit((x, y), mesh=mesh8)
    assert len(cvm.sub_models) == 2 and len(cvm.sub_models[0]) == 2
    p = os.path.join(tmp_path, "cvm_sub")
    cvm.save(p)
    back = ht.load_model(p)
    assert len(back.sub_models) == 2 and len(back.sub_models[0]) == 2
    np.testing.assert_allclose(
        np.asarray(back.sub_models[1][0].coefficients),
        np.asarray(cvm.sub_models[1][0].coefficients),
    )


def test_nested_validation_error_names_path(hospital_table, mesh8, tmp_path):
    """Deep nesting keeps the full path in the not-persistable error."""
    class Opaque:
        def transform(self, data):
            return data

    inner = ht.Pipeline([Opaque(), ht.VectorAssembler(ht.FEATURE_COLS)]).fit(
        hospital_table
    )
    outer = ht.Pipeline([inner, ht.LinearRegression()]).fit(
        hospital_table, mesh=mesh8
    )
    with pytest.raises(TypeError, match=r"stage 0 → stage 0 \(Opaque\)"):
        outer.save(os.path.join(tmp_path, "x"))


def test_cv_validation_errors(rng):
    x, y = _ridge_data(rng, n=100)
    with pytest.raises(ValueError, match="num_folds"):
        ht.CrossValidator(
            ht.LinearRegression(), [{}], ht.RegressionEvaluator(), num_folds=1
        ).fit((x, y))
    with pytest.raises(ValueError, match="param_maps"):
        ht.CrossValidator(
            ht.LinearRegression(), [], ht.RegressionEvaluator()
        ).fit((x, y))


def test_evaluator_is_larger_better_flags():
    assert not ht.RegressionEvaluator("rmse").is_larger_better
    assert ht.RegressionEvaluator("r2").is_larger_better
    assert ht.MulticlassClassificationEvaluator("accuracy").is_larger_better
    assert ht.BinaryClassificationEvaluator().is_larger_better
    assert ht.ClusteringEvaluator().is_larger_better
