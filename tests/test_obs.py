"""Observability fabric (ISSUE 10, ``obs/``): registry + exporters,
span tracing with cross-subsystem ID propagation, and the crash flight
recorder.

The load-bearing promises tested here:

* **schema stability** — the Prometheus text and JSON snapshot forms
  are a scrape contract: the pinned-schema tests freeze (name, type,
  label-keys) triples and the ``health()`` key set, so a downstream
  scraper can rely on them;
* **trace propagation** — one trace id survives the real unit-of-work
  chain: streaming batch → SQL dispatch → stage clocks (including on
  ``PipelinedStreamExecution``'s prefetch THREAD) → serve request →
  lifecycle journal transition;
* **uninstalled cost** — with no tracer, ``span()`` is a shared
  singleton and the hot path allocates nothing (the obs_overhead bench
  gate's unit-level twin);
* **postmortems** — flight dumps round-trip CRC-intact, carry the
  killing site, and are written by every trigger (InjectedCrash,
  breaker trip, rollback);
* **drift tripwire** — ``tools/check_obs.py`` passes against the
  current source.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import write_csv
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models import (
    LinearRegression,
    StreamingKMeans,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.kmeans import (
    KMeans,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.obs import (
    FixedHistogram,
    MetricsRegistry,
    export as obs_export,
    flight_recorder as obs_flight,
    global_registry,
    trace as obs_trace,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.quality.sketches import (
    DataProfile,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve import (
    CircuitBreaker,
    InferenceServer,
    ServingMetrics,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming import (
    FileStreamSource,
    PipelinedStreamExecution,
    StreamCheckpoint,
    StreamExecution,
    UnboundedTable,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming.pipeline import (
    make_sql_feature_stage,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils import faults
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils.profiling import (
    StageClock,
)

FEATURES = list(ht.FEATURE_COLS)
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def flight(tmp_path):
    """A fresh flight recorder dumping into tmp; the previous (global)
    one is restored afterwards."""
    rec = obs_flight.FlightRecorder(dump_dir=str(tmp_path / "flight"))
    old = obs_flight.recorder()
    obs_flight.install(rec)
    yield rec
    obs_flight.install(old)


@pytest.fixture
def tracer():
    with obs_trace.active(obs_trace.Tracer()) as t:
        yield t
    assert not obs_trace.enabled()


def _event_csv(path, n, rng, start_minute=0):
    base = np.datetime64("2025-03-31T22:00:00") + np.timedelta64(
        int(start_minute), "m"
    )
    t = ht.Table.from_dict(
        {
            "hospital_id": np.array(["H01"] * n, dtype=object),
            "event_time": base + np.arange(n).astype("timedelta64[s]"),
            "admission_count": rng.integers(0, 50, n),
            "current_occupancy": rng.integers(20, 200, n),
            "emergency_visits": rng.integers(0, 30, n),
            "seasonality_index": rng.uniform(0.5, 1.5, n),
            "length_of_stay": rng.uniform(1.0, 9.0, n),
        },
        ht.hospital_event_schema(),
    )
    write_csv(t, path)


def _stream(tmp_path, pipelined=True, **kw):
    cls = PipelinedStreamExecution if pipelined else StreamExecution
    return cls(
        source=FileStreamSource(
            str(tmp_path / "incoming"), ht.hospital_event_schema(),
            max_files_per_batch=1,
        ),
        sink=UnboundedTable(str(tmp_path / "table"), ht.hospital_event_schema()),
        checkpoint=StreamCheckpoint(str(tmp_path / "ckpt")),
        add_ingest_time=False,
        **kw,
    )


# ===================================================================== registry
class TestRegistry:
    def test_counters_gauges_compat_surface(self):
        r = MetricsRegistry()
        r.inc("a.b")
        r.inc("a.b", 2.0)
        r.set("g", 0.5)
        assert r.counters["a.b"] == 3.0
        assert r.gauges["g"] == 0.5
        snap = r.snapshot()
        assert set(snap) == {"counters", "gauges", "histograms", "stages"}

    def test_histogram_mean_exact_and_quantile_monotone(self):
        h = FixedHistogram([0.0, 1.0, 2.0, 4.0])
        vals = [0.1, 0.5, 1.5, 3.0, 3.5, 9.0]
        h.observe(vals)
        assert h.count == len(vals)
        assert h.mean == pytest.approx(np.mean(vals))
        qs = [h.quantile(q) for q in (0.1, 0.5, 0.9, 0.99)]
        assert qs == sorted(qs)
        assert qs[0] >= 0.0

    def test_histogram_merge_is_exact_bin_addition(self):
        a, b = FixedHistogram([0, 1, 2]), FixedHistogram([0, 1, 2])
        a.observe([0.5, 1.5, 5.0])
        b.observe([-1.0, 0.2])
        both = FixedHistogram([0, 1, 2])
        both.observe([0.5, 1.5, 5.0, -1.0, 0.2])
        a.merge(b)
        assert np.array_equal(a.counts, both.counts)
        assert a.count == both.count and a.sum == pytest.approx(both.sum)
        with pytest.raises(ValueError):
            a.merge(FixedHistogram([0, 1, 3]))

    def test_collector_sums_counters_and_prunes_dead_owners(self):
        r = MetricsRegistry()

        class Src:
            def __init__(self, n):
                self.n = n

        a, b = Src(2.0), Src(3.0)
        r.register_collector("a", a, lambda s: {"counters": {"x": s.n}})
        r.register_collector("b", b, lambda s: {"counters": {"x": s.n}})
        assert r.collect()["counters"]["x"] == 5.0
        del b
        import gc

        gc.collect()
        assert r.collect()["counters"]["x"] == 2.0
        assert r.collector_keys() == ["a"]

    def test_broken_collector_flagged_not_fatal(self):
        r = MetricsRegistry()

        class Bad:
            pass

        bad = Bad()
        r.register_collector(
            "bad", bad, lambda s: (_ for _ in ()).throw(RuntimeError("x"))
        )
        out = r.collect()
        assert out["gauges"]["obs.collector_broken.bad"] == 1.0


# ================================================================ serve metrics
class TestServingMetrics:
    def test_latency_percentiles_from_histogram(self):
        sm = ServingMetrics()
        assert sm.percentile(50) is None
        for ms in (1, 2, 3, 50):
            sm.record_request(ms / 1e3)
        p50, p99 = sm.percentile(50), sm.percentile(99)
        assert 0 < p50 < p99
        snap = sm.snapshot()
        assert snap["latency_p50_ms"] > 0
        assert snap["requests"] == 4 and snap["statuses"] == {"ok": 4}

    def test_fill_ratio_is_exact_mean(self):
        sm = ServingMetrics()
        sm.record_batch(2, 4)
        sm.record_batch(4, 4)
        assert sm.batch_fill_ratio() == pytest.approx(0.75)

    def test_distributions_merge_across_sinks(self):
        a, b = ServingMetrics(), ServingMetrics()
        a.record_request(0.001)
        b.record_request(0.1)
        ha = a.registry.histograms["serve.latency_seconds"]
        hb = b.registry.histograms["serve.latency_seconds"]
        ha.merge(hb)
        assert ha.count == 2


# ================================================================ export schema
class TestExportSchema:
    def _representative(self) -> MetricsRegistry:
        r = MetricsRegistry()
        sm = ServingMetrics(registry=r)
        sm.record_request(0.002)
        sm.record_batch(3, 4)
        sm.record_breaker_transition("closed", "open")
        r.inc("stream.batches")
        r.inc("stream.rows_rejected", 2)
        r.set("stream.drift_psi", 0.11)
        r.inc("sql.dispatch.compiled")
        r.set('serve.breaker_state{model="los"}', 2.0)
        return r

    def test_pinned_scrape_schema(self):
        """THE scrape contract: names, types, and label keys — frozen.
        A change here is a breaking change for downstream scrapers and
        must be deliberate."""
        assert obs_export.schema(self._representative()) == [
            ("cmlhn_serve_batch_fill", "histogram", ()),
            ("cmlhn_serve_batches_total", "counter", ()),
            ("cmlhn_serve_breaker_state", "gauge", ("model",)),
            ("cmlhn_serve_breaker_to_open_total", "counter", ()),
            ("cmlhn_serve_breaker_transitions_total", "counter", ()),
            ("cmlhn_serve_latency_seconds", "histogram", ()),
            ("cmlhn_serve_padded_rows_total", "counter", ()),
            ("cmlhn_serve_requests_total", "counter", ()),
            ("cmlhn_serve_rows_total", "counter", ()),
            ("cmlhn_serve_status_ok_total", "counter", ()),
            ("cmlhn_sql_dispatch_compiled_total", "counter", ()),
            ("cmlhn_stream_batches_total", "counter", ()),
            ("cmlhn_stream_drift_psi", "gauge", ()),
            ("cmlhn_stream_rows_rejected_total", "counter", ()),
        ]

    def test_prometheus_text_invariants(self):
        text = obs_export.prometheus_text(self._representative())
        lines = text.strip().split("\n")
        # one TYPE line per family, before its samples
        assert "# TYPE cmlhn_serve_requests_total counter" in lines
        assert "# TYPE cmlhn_stream_drift_psi gauge" in lines
        assert "# TYPE cmlhn_serve_latency_seconds histogram" in lines
        assert 'cmlhn_serve_breaker_state{model="los"} 2' in lines
        # histogram: +Inf bucket equals _count (cumulative, complete)
        inf = next(
            ln for ln in lines
            if ln.startswith('cmlhn_serve_latency_seconds_bucket{le="+Inf"}')
        )
        count = next(
            ln for ln in lines
            if ln.startswith("cmlhn_serve_latency_seconds_count")
        )
        assert inf.split()[-1] == count.split()[-1] == "1"

    def test_json_snapshot_shape_and_roundtrip(self):
        snap = obs_export.json_snapshot(self._representative())
        assert set(snap) == {"time", "counters", "gauges", "histograms"}
        again = json.loads(json.dumps(snap))
        assert again["counters"]["stream.batches"] == 1
        h = again["histograms"]["serve.latency_seconds"]
        assert len(h["counts"]) == len(h["edges"]) + 1

    def test_snapshot_log_append_and_read(self, tmp_path):
        path = str(tmp_path / "snaps.jsonl")
        obs_export.write_snapshot(path, self._representative())
        obs_export.write_snapshot(path, self._representative())
        with open(path, "a") as f:
            f.write('{"torn')  # torn tail: reader must skip it
        snaps = obs_export.read_snapshots(path)
        assert len(snaps) == 2

    def test_health_key_set_pinned(self):
        x = np.random.default_rng(0).normal(size=(64, 4)).astype(np.float32)
        y = x.sum(axis=1)
        srv = InferenceServer()
        srv.add_model("los", LinearRegression().fit((x, y)), buckets=(1, 4))
        with srv:
            srv.predict("los", x[:2])
            assert set(srv.health()) == {
                "status", "started", "lifecycle", "models_serving",
                "breakers", "drift", "quarantined_batches",
                "quarantined_rows", "drift_events", "retry_totals",
                "fallback_answers", "inputs_imputed", "inputs_rejected",
                "drift_trips",
            }
            text = srv.metrics_text()
        assert "# TYPE cmlhn_serve_requests_total counter" in text
        assert 'cmlhn_serve_breaker_state{model="los"} 0' in text

    def test_server_registers_on_global_registry(self):
        x = np.random.default_rng(0).normal(size=(64, 4)).astype(np.float32)
        srv = InferenceServer()
        srv.add_model(
            "glos", LinearRegression().fit((x, x.sum(axis=1))), buckets=(1, 4)
        )
        with srv:
            srv.predict("glos", x[:2])
            counters = global_registry().collect()["counters"]
            assert counters.get("serve.requests", 0) >= 1


# ===================================================================== tracing
class TestTrace:
    def test_noop_span_is_shared_singleton(self):
        assert not obs_trace.enabled()
        a = obs_trace.span("serve.request")
        b = obs_trace.span("stream.batch")
        assert a is b
        with a as sp:
            sp.note("k", "v")  # must be a no-op, not an error
        assert a.trace_id is None

    def test_noop_span_allocation_free(self):
        """The exporters-off hot path is pinned allocation-free: after
        warmup, a no-op span cycle leaves the allocator block count
        unchanged (the unit twin of the obs_overhead bench gate)."""
        assert not obs_trace.enabled()
        for _ in range(5000):
            with obs_trace.span("serve.request"):
                pass
        base = sys.getallocatedblocks()
        for _ in range(50_000):
            with obs_trace.span("serve.request"):
                pass
        assert sys.getallocatedblocks() - base <= 16

    def test_nesting_and_ids(self, tracer):
        with obs_trace.span("obs.demo") as root:
            assert obs_trace.current_trace_id() == root.trace_id
            with obs_trace.span("sql.query") as child:
                pass
        assert obs_trace.current_trace_id() is None
        spans = {s["name"]: s for s in tracer.spans}
        assert spans["sql.query"]["trace_id"] == root.trace_id
        assert spans["sql.query"]["parent_id"] == root.span_id
        assert spans["obs.demo"]["parent_id"] is None

    def test_span_records_exception_and_reraises(self, tracer):
        with pytest.raises(ValueError):
            with obs_trace.span("obs.demo"):
                raise ValueError("boom")
        [sp] = tracer.spans
        assert "ValueError" in sp["attrs"]["error"]

    def test_span_log_roundtrip_and_torn_tail(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        with obs_trace.active(obs_trace.Tracer(path, flush_every=2)):
            for _ in range(5):
                with obs_trace.span("obs.demo"):
                    pass
        assert len(obs_trace.read_spans(path)) == 5
        with open(path, "a") as f:
            f.write('{"torn"')
        assert len(obs_trace.read_spans(path)) == 5  # torn line skipped

    def test_stage_clock_is_a_span_sink(self, tracer):
        clock = StageClock()
        with obs_trace.span("obs.demo") as root:
            with clock.stage("update"):
                pass
        names = {s["name"]: s for s in tracer.spans}
        assert "stage.update" in names
        assert names["stage.update"]["trace_id"] == root.trace_id
        # and silent without a tracer (the uninstalled discipline)
        obs_trace.clear()
        with clock.stage("update"):
            pass
        assert clock.counts["update"] == 2

    def test_sql_span_carries_route_and_fingerprint(self, tracer):
        t = ht.Table.from_dict(
            {"v": np.arange(8_192, dtype=np.float64)}, None
        )
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.sql import (
            execute,
        )

        execute("SELECT v + 1 AS w FROM t WHERE v > 3", lambda name: t)
        [sp] = [s for s in tracer.spans if s["name"] == "sql.query"]
        assert sp["attrs"]["route"] in ("compiled", "interpreter")
        if sp["attrs"]["route"] == "compiled":
            assert sp["attrs"]["fingerprint"]

    def test_trace_threads_batch_sql_fit_serve_lifecycle(
        self, tmp_path, tracer
    ):
        """THE propagation contract: one ambient trace id survives the
        whole chain — pipelined ingest (prefetch WORKER thread included),
        the SQL feature stage, the stage-clocked model update, a serve
        request, and a lifecycle journal transition."""
        rng = np.random.default_rng(0)
        os.makedirs(tmp_path / "incoming")
        for i in range(2):
            _event_csv(
                str(tmp_path / "incoming" / f"f{i}.csv"), 60, rng,
                start_minute=i,
            )
        sk = StreamingKMeans(k=2, seed=0)
        exec_ = _stream(tmp_path, pipelined=True)
        exec_.stage = make_sql_feature_stage(
            "SELECT * FROM __THIS__", FEATURES
        )
        exec_.foreach_batch = lambda x, bid: sk.update(x)

        x = rng.normal(size=(64, 4)).astype(np.float32)
        srv = InferenceServer()
        srv.add_model(
            "los", LinearRegression().fit((x, x.sum(axis=1))), buckets=(1, 4)
        )

        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.lifecycle import (
            KMeansRetrainer,
            LifecycleController,
        )

        with obs_trace.span("obs.demo") as root:
            with exec_:
                infos = exec_.run(max_batches=2, timeout_s=60.0)
            with srv:
                r = srv.predict("los", x[:2])
            ctrl = LifecycleController(
                str(tmp_path / "lifecycle"), srv, "cohorts",
                KMeansRetrainer(tuple(FEATURES), k=2, max_iter=2),
                buckets=(1, 4),
            )
            km = KMeans(k=2, seed=0, max_iter=2).fit(x)
            ctrl.bootstrap(
                km, DataProfile.from_matrix(x.astype(np.float64), FEATURES)
            )
        assert len(infos) == 2 and r.ok
        tid = root.trace_id
        mine = [s for s in tracer.spans if s["trace_id"] == tid]
        names = {s["name"] for s in mine}
        assert {
            "stream.batch", "sql.query", "stage.ingest", "stage.update",
            "serve.request", "lifecycle.transition",
        } <= names, f"chain broken; got {sorted(names)}"
        # the prefetch worker's spans joined the SAME trace
        worker = [s for s in mine if s["thread"] == "stream-prefetch"]
        assert worker, "no spans from the prefetch thread"
        assert {"stage.ingest"} <= {s["name"] for s in worker}
        # and the batch attempt exposed its trace id for correlation
        assert exec_.last_trace_id == tid
        # timeline reconstruction is ordered and complete
        tl = obs_trace.timeline(tracer.spans, tid)
        assert [s["t0"] for s in tl] == sorted(s["t0"] for s in tl)
        assert obs_trace.format_timeline(tl).count("\n") == len(tl) - 1

    def test_serial_driver_roots_its_own_trace(self, tmp_path, tracer):
        rng = np.random.default_rng(1)
        os.makedirs(tmp_path / "incoming")
        _event_csv(str(tmp_path / "incoming" / "f.csv"), 40, rng)
        exec_ = _stream(tmp_path, pipelined=False)
        exec_.run(max_batches=1, timeout_s=30.0)
        [batch] = [s for s in tracer.spans if s["name"] == "stream.batch"]
        assert batch["parent_id"] is None  # no ambient trace: a new root
        assert batch["attrs"]["rows"] == 40
        assert exec_.last_trace_id == batch["trace_id"]


# =============================================================== flight recorder
class TestFlightRecorder:
    def test_dump_roundtrip_and_crc_detects_tamper(self, flight):
        obs_flight.note("fault", "x.site", action="crash")
        path = obs_flight.notify("test_trigger", "x.site", detail=1)
        payload = obs_flight.read_dump(path)
        assert payload["site"] == "x.site"
        assert payload["trigger"] == {"detail": 1}
        assert any(e["name"] == "x.site" for e in payload["events"])
        assert "counters" in payload["metrics"]
        # flip one byte inside the payload region → loud corruption
        raw = open(path).read()
        broken = raw.replace('"site":"x.site"', '"site":"y.site"')
        with open(path, "w") as f:
            f.write(broken)
        with pytest.raises(ValueError, match="crc32c mismatch"):
            obs_flight.read_dump(path)

    def test_injected_crash_dumps_with_site(self, flight):
        plan = faults.FaultPlan().crash("obs.test.kill")
        with faults.active(plan):
            with pytest.raises(faults.InjectedCrash):
                faults.fault_point("obs.test.kill")
        assert flight.dumps == 1
        payload = obs_flight.read_dump(flight.last_dump_path)
        assert payload["site"] == "obs.test.kill"
        assert payload["reason"] == "injected_crash"
        # the rule FIRE preceding the crash is in the ring too
        kinds = {(e["kind"], e["name"]) for e in payload["events"]}
        assert ("fault", "obs.test.kill") in kinds

    def test_breaker_open_dumps(self, flight):
        b = CircuitBreaker(failure_threshold=2)
        b.record_failure()
        assert flight.dumps == 0
        b.record_failure()  # threshold: closed → open
        assert flight.dumps == 1
        assert obs_flight.read_dump(flight.last_dump_path)["site"] == (
            "serve.breaker"
        )
        b.trip("drift")  # already open: clock restart, no second dump
        assert flight.dumps == 1

    def test_breaker_dump_runs_outside_its_lock(self, flight):
        """Regression: the open-transition dump snapshots breakers via
        the registry collectors — with the dump inside the breaker's own
        lock this deadlocked (same-lock re-entry / ABBA across two
        breakers).  A collector that snapshots the opening breaker must
        complete."""
        b = CircuitBreaker(failure_threshold=1)
        global_registry().register_collector(
            "bkr-regression", b,
            lambda br: {
                "gauges": {"t": float(br.snapshot()["opened_count"])}
            },
        )
        try:
            b.record_failure()  # closed → open → dump → collect → snapshot
            assert flight.dumps == 1
        finally:
            global_registry().unregister_collector("bkr-regression")

    def test_dump_dir_is_bounded(self, tmp_path):
        rec = obs_flight.FlightRecorder(
            dump_dir=str(tmp_path / "fl"), max_dumps=3
        )
        for i in range(6):
            assert rec.dump("r", site=f"s{i}") is not None
        files = [f for f in os.listdir(rec.dump_dir) if f.endswith(".json")]
        assert len(files) == 3
        assert all("s5" in f or "s4" in f or "s3" in f for f in files)

    def test_ring_is_bounded(self, flight):
        for i in range(flight.capacity + 50):
            obs_flight.note("fault", f"s{i}")
        assert len(flight.events) == flight.capacity

    def test_dump_failure_is_counted_not_raised(self, tmp_path):
        rec = obs_flight.FlightRecorder(
            dump_dir=str(tmp_path / "flight-as-file")
        )
        open(rec.dump_dir, "w").close()  # makedirs will fail on a file
        assert rec.dump("reason", site="s") is None
        assert rec.dump_failures == 1


class TestFlightRecorderUnderLoad:
    """ISSUE 17 satellite: a soak-length run must not let observability
    itself become the resource leak — the ring and the metric series
    set stay bounded across ≥10k spans, and postmortem dumps fired
    concurrently (two replicas dying at once) never collide."""

    def _series_count(self) -> int:
        snap = obs_export.json_snapshot()
        return sum(
            len(snap.get(kind, {}))
            for kind in ("counters", "gauges", "histograms")
        )

    def test_ring_and_metric_cardinality_bounded_over_10k_spans(
        self, flight, tracer
    ):
        names = (
            "stream.batch", "sql.view.maintain",
            "lifecycle.retrain", "serve.request",
        )
        mid_series = mid_ring = None
        for i in range(10_000):
            with obs_trace.span(names[i % len(names)], {"i": i}):
                pass
            if i == 4_999:  # past any warmup: cardinality must be flat
                mid_series = self._series_count()
                mid_ring = len(flight.events)
        assert tracer.emitted == 10_000
        assert len(flight.events) <= flight.capacity
        assert mid_ring <= flight.capacity
        # a per-span (id-keyed) metric would grow the series set by
        # thousands between the half-way mark and the end
        assert self._series_count() == mid_series
        # and a dump fired AFTER the flood still round-trips CRC-intact
        path = obs_flight.notify("test_trigger", "load.after_flood")
        assert obs_flight.read_dump(path)["site"] == "load.after_flood"

    def test_concurrent_crash_dumps_never_collide(self, flight):
        import threading

        paths: list = []
        lock = threading.Lock()

        def die_repeatedly(t):
            for j in range(10):
                p = obs_flight.notify(
                    "injected_crash", f"load.site.t{t}", burst=j
                )
                with lock:
                    paths.append(p)

        threads = [
            threading.Thread(target=die_repeatedly, args=(t,))
            for t in range(8)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert len(paths) == 80 and None not in paths
        assert len(set(paths)) == 80  # no two dumps shared a file
        for p in paths:
            payload = obs_flight.read_dump(p)  # every one CRC-intact
            assert payload["reason"] == "injected_crash"
            assert payload["site"].startswith("load.site.t")


# ================================================================== static check
def test_check_obs_static_coverage():
    """Instrumentation cannot silently drift: every fault site and
    journal state maps to a registered span (tools/check_obs.py)."""
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "check_obs.py")],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, f"\n{r.stdout}\n{r.stderr}"
