"""parallel/partitioner — the ONE declarative sharding rule table (ISSUE 19a).

Contracts:

1. rule resolution — ordered first-match-wins over ``fnmatch`` path
   patterns, unmatched leaves fall to the family default (replicated),
   aliases map logical axes (tenant/replica) to mesh axes or None;
2. ``spec(path, ndim)`` pads with None up to the leaf's rank and
   REFUSES a rule longer than the rank (a rule written for a matrix
   must not silently mis-shard a vector);
3. caching — spec resolution is cached per (path, ndim), NamedSharding
   resolution per (family, path, ndim, mesh), and ``register_family``
   invalidates exactly its own family's cached resolutions;
4. ``partition_devices`` — the replica-axis split the fleet placement
   delegates to (contiguous even split, round-robin oversubscription);
5. migration gate — the family tables reproduce the exact specs the
   scattered call sites used to hand-build, and the sharded estimators
   stay bit-identical to single-device fits THROUGH the partitioner
   layer (kmeans is the canary family; every other family's parity is
   pinned by its own suite, which now routes through this module).
"""

import numpy as np
import pytest

from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel import (
    partitioner as PT,
)

pytestmark = [pytest.mark.fast]


def _pt(rules, default=(), aliases=None, family="test"):
    return PT.Partitioner(
        family, [PT.Rule(p, a) for p, a in rules],
        default=default, aliases=aliases,
    )


# --------------------------------------------------------------- resolution


class TestRuleResolution:
    def test_first_match_wins_in_declaration_order(self):
        pt = _pt([
            ("batch/x", (PT.MODEL,)),   # specific, listed first
            ("batch/*", (PT.DATA,)),
        ])
        assert pt.axes_for("batch/x") == (PT.MODEL,)
        assert pt.axes_for("batch/w") == (PT.DATA,)

    def test_later_broad_rule_shadowed_not_merged(self):
        pt = _pt([
            ("batch/*", (PT.DATA,)),
            ("batch/x", (PT.MODEL,)),   # unreachable: glob above wins
        ])
        assert pt.axes_for("batch/x") == (PT.DATA,)

    def test_unmatched_leaf_falls_to_family_default(self):
        pt = _pt([("batch/*", (PT.DATA,))])
        # default () = fully replicated
        assert pt.axes_for("state/centers") == ()

    def test_unmatched_leaf_custom_default(self):
        pt = _pt([("batch/*", (PT.DATA,))], default=(PT.MODEL,))
        assert pt.axes_for("anything/else") == (PT.MODEL,)

    def test_alias_resolution_tenant_defaults_to_none(self):
        pt = _pt([("stack/*", (PT.TENANT,))])
        sp = pt.spec("stack/x", ndim=2)
        # default alias: the tenant axis is a replication decision until
        # a pod maps it onto a real mesh axis
        assert tuple(sp) == (None, None)

    def test_alias_override_maps_tenant_onto_mesh_axis(self):
        pt = _pt(
            [("stack/*", (PT.TENANT,))],
            aliases={PT.TENANT: "data"},
        )
        assert tuple(pt.spec("stack/x", ndim=2)) == ("data", None)

    def test_invalid_axis_name_rejected_at_rule_construction(self):
        with pytest.raises(ValueError):
            PT.Rule("batch/*", ("bogus_axis",))

    def test_match_is_fnmatch_not_prefix(self):
        pt = _pt([("state/c*", (PT.MODEL,))])
        assert pt.axes_for("state/centers") == (PT.MODEL,)
        assert pt.axes_for("state/weights") == ()


class TestSpecPadding:
    def test_spec_pads_rank_with_replicated_dims(self):
        pt = _pt([("batch/*", (PT.DATA,))])
        assert tuple(pt.spec("batch/x", ndim=3)) == ("data", None, None)
        assert tuple(pt.spec("batch/w", ndim=1)) == ("data",)

    def test_rule_longer_than_rank_is_an_error(self):
        pt = _pt([("cols/*", (None, PT.DATA))])
        with pytest.raises(ValueError):
            pt.spec("cols/binned", ndim=1)

    def test_scalar_spec_is_empty(self):
        pt = _pt([])
        assert tuple(pt.spec("scalar/cost")) == ()


# --------------------------------------------------------------- caching


class TestCaching:
    def test_spec_cache_keyed_by_path_and_ndim(self):
        pt = _pt([("batch/*", (PT.DATA,))])
        a = pt.spec("batch/x", ndim=2)
        b = pt.spec("batch/x", ndim=2)
        c = pt.spec("batch/x", ndim=3)
        assert a is b          # cache hit: identical object
        assert tuple(c) != tuple(a)

    def test_sharding_cache_keyed_by_mesh(self):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.mesh import (
            default_mesh,
            single_device_mesh,
        )

        pt = PT.family("rows")
        m1, m2 = default_mesh(), single_device_mesh()
        s1 = pt.sharding("batch/x", mesh=m1, ndim=2)
        s1b = pt.sharding("batch/x", mesh=m1, ndim=2)
        s2 = pt.sharding("batch/x", mesh=m2, ndim=2)
        assert s1 is s1b       # same (family, path, ndim, mesh) → cached
        assert s1 is not s2
        assert s1.mesh is m1 and s2.mesh is m2

    def test_register_family_clears_only_its_own_resolutions(self):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.mesh import (
            default_mesh,
        )

        PT.register_family("tmp_fam_a", [("batch/*", (PT.DATA,))])
        PT.register_family("tmp_fam_b", [("batch/*", (PT.DATA,))])
        mesh = default_mesh()
        sa = PT.family("tmp_fam_a").sharding("batch/x", mesh=mesh, ndim=2)
        sb = PT.family("tmp_fam_b").sharding("batch/x", mesh=mesh, ndim=2)
        n_before = PT.resolution_cache_size()
        # re-registering A must drop A's cached resolutions, not B's
        PT.register_family("tmp_fam_a", [("batch/*", (PT.DATA,))])
        assert PT.resolution_cache_size() < n_before
        sb2 = PT.family("tmp_fam_b").sharding("batch/x", mesh=mesh, ndim=2)
        assert sb2 is sb
        sa2 = PT.family("tmp_fam_a").sharding("batch/x", mesh=mesh, ndim=2)
        assert sa2 is not sa

    def test_unknown_family_is_loud(self):
        with pytest.raises(KeyError):
            PT.family("no_such_family")


# --------------------------------------------------------------- devices


class TestPartitionDevices:
    def test_contiguous_even_split(self):
        out = PT.partition_devices(list("abcdefgh"), 4)
        assert list(out) == [("a", "b"), ("c", "d"), ("e", "f"), ("g", "h")]

    def test_remainder_spreads_over_first_slices(self):
        out = PT.partition_devices(list("abcde"), 2)
        assert [len(s) for s in out] == [3, 2]
        assert out[0] == ("a", "b", "c")

    def test_oversubscription_round_robins_single_device_slices(self):
        out = PT.partition_devices(list("ab"), 5)
        assert list(out) == [("a",), ("b",), ("a",), ("b",), ("a",)]

    def test_zero_replicas_rejected(self):
        with pytest.raises(ValueError):
            PT.partition_devices(list("ab"), 0)

    def test_no_devices_rejected(self):
        with pytest.raises(ValueError):
            PT.partition_devices([], 2)


# --------------------------------------------------------------- rounding


def test_round_rows_is_multiple_of_data_shards():
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.mesh import (
        default_mesh,
    )

    pt = PT.family("rows")
    mesh = default_mesh()
    m = pt.data_shards(mesh)
    assert m >= 1
    for n in (1, m, m + 1, 1000):
        r = pt.round_rows(n, mesh)
        assert r % m == 0 and r >= n


# --------------------------------------------------------------- migration


class TestMigrationGate:
    """The family tables reproduce the exact literal specs the migrated
    call sites used to hand-build (the bit-parity precondition)."""

    def test_kmeans_table_matches_former_literals(self):
        from jax.sharding import PartitionSpec as P

        pt = PT.family("kmeans")
        assert pt.spec("batch/x", ndim=2) == P("data", None)
        assert pt.spec("batch/w", ndim=1) == P("data")
        assert pt.spec("state/centers", ndim=2) == P("model", None)
        assert pt.spec("state/c_valid", ndim=1) == P("model")
        assert pt.spec("stats/sums", ndim=2) == P("model", None)
        assert pt.spec("stats/counts", ndim=1) == P("model")
        assert pt.spec("scalar/cost") == P()

    def test_gmm_trees_farm_sql_tables(self):
        from jax.sharding import PartitionSpec as P

        gmm = PT.family("gmm")
        assert gmm.spec("batch/x", ndim=2) == P("data", None)
        assert gmm.spec("const/params") == P()
        assert gmm.spec("rows/assign", ndim=1) == P("data")
        trees = PT.family("trees")
        assert trees.spec("cols/binned", ndim=2) == P(None, "data")
        farm = PT.family("farm")
        # tenant axis replicated by default (single-pod placement)
        assert farm.spec("stack/x", ndim=3) == P(None, None, None)
        sql = PT.family("sql")
        assert sql.spec("column", ndim=1) == P(None)

    def test_kmeans_sharded_vs_single_device_bit_parity(self):
        """The migration gate proper: a sharded fit THROUGH the
        partitioner layer is bit-identical to the single-device fit."""
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.kmeans import (
            KMeans,
        )
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.mesh import (
            default_mesh,
            single_device_mesh,
        )

        rng = np.random.default_rng(7)
        x = rng.normal(size=(256, 5)).astype(np.float32)
        single = KMeans(k=4, max_iter=8, seed=3).fit(
            x, mesh=single_device_mesh()
        )
        sharded = KMeans(k=4, max_iter=8, seed=3).fit(
            x, mesh=default_mesh()
        )
        # 1-ulp f32 tolerance: the 8-shard psum reduces in a different
        # order than the single-device sum (repo-wide parity discipline;
        # see tests/test_option_parity.py)
        np.testing.assert_allclose(
            np.asarray(single.cluster_centers),
            np.asarray(sharded.cluster_centers), atol=1e-6,
        )
        np.testing.assert_allclose(
            single.training_cost, sharded.training_cost, rtol=1e-6,
        )
