"""KMeans: convergence on blobs vs sklearn, sharded-equals-single, cosine,
2-D (data×model) mesh, save/load (SURVEY.md §4 unit + distributed tiers)."""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.kmeans import KMeans
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import load_model


def _blobs(rng, n=600, k=4, d=5, spread=0.15):
    centers = rng.normal(scale=3.0, size=(k, d))
    labels = rng.integers(0, k, n)
    x = centers[labels] + rng.normal(scale=spread, size=(n, d))
    return x.astype(np.float64), labels, centers


@pytest.mark.fast
def test_kmeans_recovers_blobs(rng, mesh8):
    x, labels, true_centers = _blobs(rng)
    model = KMeans(k=4, seed=0).fit(x, mesh=mesh8)
    assert model.n_iter >= 1
    # every true center is within spread of a learned center
    dist = np.linalg.norm(true_centers[:, None, :] - model.cluster_centers[None], axis=2)
    assert dist.min(axis=1).max() < 0.2
    # assignments respect the blob structure: same-blob rows share a cluster
    pred = model.predict_numpy(x)
    for b in range(4):
        vals, counts = np.unique(pred[labels == b], return_counts=True)
        assert counts.max() / counts.sum() > 0.99


def test_kmeans_matches_sklearn_inertia(rng, mesh8):
    from sklearn.cluster import KMeans as SK

    x, _, _ = _blobs(rng, n=500, k=3)
    ours = KMeans(k=3, seed=1, max_iter=50).fit(x, mesh=mesh8)
    sk = SK(n_clusters=3, n_init=10, random_state=0).fit(x)
    assert ours.training_cost <= sk.inertia_ * 1.05


def test_kmeans_sharded_equals_single(rng, mesh8, mesh1):
    x, _, _ = _blobs(rng, n=333)  # force padding
    m8 = KMeans(k=4, seed=3).fit(x, mesh=mesh8)
    m1 = KMeans(k=4, seed=3).fit(x, mesh=mesh1)
    # same init (host-side, mesh-independent) → identical trajectories
    c8 = m8.cluster_centers[np.lexsort(m8.cluster_centers.T)]
    c1 = m1.cluster_centers[np.lexsort(m1.cluster_centers.T)]
    np.testing.assert_allclose(c8, c1, rtol=1e-4, atol=1e-4)


def test_kmeans_model_axis_sharding(rng, mesh42):
    """data=4 × model=2 mesh: centroid axis sharded; k=6 pads to 6 (div by 2)."""
    x, _, _ = _blobs(rng, n=400, k=6, d=4)
    model = KMeans(k=6, seed=0).fit(x, mesh=mesh42)
    assert model.cluster_centers.shape == (6, 4)
    sil = ht.ClusteringEvaluator().evaluate(x, model.predict_numpy(x), k=6)
    assert sil > 0.6


def test_kmeans_model_axis_k_padding(rng, mesh42):
    """k=5 not divisible by model=2 → internal padding must stay inert."""
    x, _, _ = _blobs(rng, n=300, k=5, d=3)
    model = KMeans(k=5, seed=0).fit(x, mesh=mesh42)
    assert model.cluster_centers.shape == (5, 3)
    assert np.isfinite(model.cluster_centers).all()
    assert model.cluster_sizes.sum() == 300


def test_kmeans_cosine(rng, mesh8):
    # two direction-clusters at different magnitudes
    a = rng.normal(size=(100, 3)) * 0.05 + np.array([1.0, 0, 0])
    b = rng.normal(size=(100, 3)) * 0.05 + np.array([0, 1.0, 0])
    x = np.concatenate([a * 1.0, b * 5.0])
    model = KMeans(k=2, seed=0, distance_measure="cosine").fit(x, mesh=None)
    pred = model.predict_numpy(x)
    assert len(set(pred[:100])) == 1 and len(set(pred[100:])) == 1
    assert pred[0] != pred[150]


def test_kmeans_silhouette_parity_sklearn(rng, mesh8):
    from sklearn.metrics import silhouette_score

    x, _, _ = _blobs(rng, n=300, k=3)
    model = KMeans(k=3, seed=0).fit(x, mesh=mesh8)
    pred = model.predict_numpy(x)
    ours = ht.ClusteringEvaluator().evaluate(x, pred, k=3)
    ref = silhouette_score(x, pred, metric="sqeuclidean")
    np.testing.assert_allclose(ours, ref, atol=1e-3)


def test_kmeans_save_load(rng, mesh8, tmp_path):
    x, _, _ = _blobs(rng, n=200, k=3)
    model = KMeans(k=3, seed=0).fit(x, mesh=mesh8)
    model.write().overwrite().save(str(tmp_path / "km"))
    loaded = load_model(str(tmp_path / "km"))
    np.testing.assert_allclose(loaded.cluster_centers, model.cluster_centers)
    np.testing.assert_array_equal(loaded.predict_numpy(x), model.predict_numpy(x))
    assert loaded.n_iter == model.n_iter


def test_kmeans_compute_cost(rng, mesh8):
    x, _, _ = _blobs(rng, n=200, k=3)
    model = KMeans(k=3, seed=0, max_iter=50).fit(x, mesh=mesh8)
    cost = model.compute_cost(x, mesh=mesh8)
    np.testing.assert_allclose(cost, model.training_cost, rtol=0.05)


def test_kmeans_init_duplicate_heavy(rng, mesh8):
    """Duplicate-heavy data: fewer distinct points than ++ candidate trials
    (regression: rng.choice(replace=False) needs enough nonzero-p entries)."""
    x = np.concatenate([np.zeros((50, 3)), np.ones((1, 3))])
    model = KMeans(k=3, seed=0).fit(x, mesh=mesh8)
    assert np.isfinite(model.cluster_centers).all()


def test_kmeans_cosine_centroids_unit_norm(rng, mesh8):
    """Cosine mode keeps centroids on the unit sphere after every update."""
    x = rng.normal(size=(200, 4)) + np.array([3.0, 0, 0, 0])
    model = KMeans(k=3, seed=0, distance_measure="cosine").fit(x, mesh=mesh8)
    norms = np.linalg.norm(model.cluster_centers, axis=1)
    np.testing.assert_allclose(norms, 1.0, atol=1e-5)


def test_kmeans_training_cost_is_final(rng, mesh8):
    """training_cost describes the returned centers, not the pre-update ones
    (regression: cost was one Lloyd step stale)."""
    x, _, _ = _blobs(rng, n=300, k=3)
    m = KMeans(k=3, seed=0, max_iter=1).fit(x, mesh=mesh8)
    np.testing.assert_allclose(m.training_cost, m.compute_cost(x, mesh=mesh8), rtol=1e-4)


def test_silhouette_mesh_resident_device_inputs(rng, mesh8):
    """The evaluator consumes the sharded DeviceDataset + device-resident
    assignments (no host gather) and agrees with the host-array path and
    sklearn."""
    from sklearn.metrics import silhouette_score

    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.sharding import (
        device_dataset,
    )

    centers = np.array([[0.0, 0.0, 0.0], [6.0, 6.0, 0.0], [0.0, 6.0, 6.0]])
    a = rng.integers(0, 3, 700)
    x = (centers[a] + rng.normal(scale=0.6, size=(700, 3))).astype(np.float32)

    ds = device_dataset(x, mesh=mesh8)
    model = ht.KMeans(k=3, seed=0).fit(ds, mesh=mesh8)
    assign_dev = model.predict(ds.x)          # sharded, padded like ds
    ev = ht.ClusteringEvaluator()
    on_mesh = ev.evaluate(ds, assign_dev, k=3)
    on_host = ev.evaluate(x, np.asarray(model.predict_numpy(x)), k=3)
    ref = silhouette_score(
        x, np.asarray(model.predict_numpy(x)), metric="sqeuclidean"
    )
    assert abs(on_mesh - on_host) < 1e-5
    assert abs(on_mesh - ref) < 1e-4


@pytest.mark.fast
def test_kmeans_bf16_precision_parity(rng, mesh8):
    """matmul_precision="bf16" (native single-pass MXU mode, f32
    accumulation) recovers the same clustering as exact f32 on separated
    blobs — the parity gate behind the bench's bf16 headline A/B."""
    x, labels, _ = _blobs(rng, n=800, k=4, d=6)
    exact = KMeans(k=4, seed=0).fit(x, mesh=mesh8)
    fast = KMeans(k=4, seed=0, matmul_precision="bf16").fit(x, mesh=mesh8)
    # same partition (centers may be ulp-perturbed; match by assignment)
    a, b = exact.predict_numpy(x), fast.predict_numpy(x)
    remap = {}
    for ca, cb in zip(a, b):
        remap.setdefault(ca, cb)
    assert np.mean([remap[ca] == cb for ca, cb in zip(a, b)]) > 0.995
    np.testing.assert_allclose(
        fast.training_cost, exact.training_cost, rtol=1e-2
    )
    with pytest.raises(ValueError, match="matmul_precision"):
        KMeans(k=4, matmul_precision="fp8").fit(x, mesh=mesh8)


@pytest.mark.fast
def test_kmeans_fused_stats_parity(rng, mesh8):
    """fused_stats=True (bf16-rate accumulation: x²-free argmin basis +
    one bf16 one-hot matmul for sums AND counts) recovers the same
    clustering as the plain bf16 mode — the gate behind the bench's
    second A/B rung."""
    x, labels, _ = _blobs(rng, n=800, k=4, d=6)
    exact = KMeans(k=4, seed=0).fit(x, mesh=mesh8)
    fused = KMeans(
        k=4, seed=0, matmul_precision="bf16", fused_stats=True
    ).fit(x, mesh=mesh8)
    a, b = exact.predict_numpy(x), fused.predict_numpy(x)
    remap = {}
    for ca, cb in zip(a, b):
        remap.setdefault(ca, cb)
    assert np.mean([remap[ca] == cb for ca, cb in zip(a, b)]) > 0.995
    np.testing.assert_allclose(
        fused.training_cost, exact.training_cost, rtol=1e-2
    )
    # sizes survive the bf16 ones-column counts (integer-exact ≤ 2^24)
    assert int(sum(fused.cluster_sizes)) == len(x)
    with pytest.raises(ValueError, match="fused_stats"):
        KMeans(k=4, fused_stats=True).fit(x, mesh=mesh8)


def test_kmeans_fused_stats_2d_mesh(rng, mesh42):
    """fused_stats on the (data=4, model=2) mesh: the x²-free argmin
    basis must resolve the cross-shard owner identically to the full-d²
    comparison (x² is row-constant, hence shard-invariant)."""
    x, labels, _ = _blobs(rng, n=640, k=4, d=6)
    base = KMeans(k=4, seed=0, matmul_precision="bf16").fit(x, mesh=mesh42)
    fused = KMeans(
        k=4, seed=0, matmul_precision="bf16", fused_stats=True
    ).fit(x, mesh=mesh42)
    dist = np.linalg.norm(
        base.cluster_centers[:, None] - fused.cluster_centers[None], axis=2
    )
    assert dist.min(axis=1).max() < 0.05


def test_kmeans_fused_stats_weighted(rng, mesh8):
    """Fractional sample weights ride the bf16 ones-column: counts carry
    ~1e-3 relative rounding but the partition still matches exact f32."""
    x, labels, _ = _blobs(rng, n=600, k=3, d=5)
    w = rng.uniform(0.5, 2.0, len(x)).astype(np.float32)
    exact = KMeans(k=3, seed=0).fit((x, None, w), mesh=mesh8)
    fused = KMeans(
        k=3, seed=0, matmul_precision="bf16", fused_stats=True
    ).fit((x, None, w), mesh=mesh8)
    dist = np.linalg.norm(
        exact.cluster_centers[:, None] - fused.cluster_centers[None], axis=2
    )
    assert dist.min(axis=1).max() < 0.05
    np.testing.assert_allclose(
        sorted(fused.cluster_sizes), sorted(exact.cluster_sizes), rtol=5e-3
    )
