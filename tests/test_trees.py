"""Decision tree / random forest tests: exact-split recovery, sklearn
parity, sharded-equals-single, classification pipeline parity."""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
    RandomForestClassifier,
    RandomForestRegressor,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import load_model


def test_tree_recovers_axis_aligned_split(rng, mesh8):
    """A clean split on a low-cardinality feature must be found exactly.

    (Low cardinality is required: quantile binning — like Spark's maxBins —
    places thresholds on quantile edges, so a boundary inside a dense
    continuous region is only recovered to bin granularity; with ≤ max_bins
    distinct values every value is its own bin edge.)"""
    x = rng.uniform(0, 1, size=(500, 3))
    x[:, 1] = rng.choice([0.2, 0.4, 0.7, 0.9], size=500)
    y = np.where(x[:, 1] > 0.6, 5.0, 1.0)
    model = DecisionTreeRegressor(max_depth=2, seed=0).fit((x, y), mesh=mesh8)
    pred = model.predict_numpy(x)
    np.testing.assert_allclose(pred, y, atol=1e-4)
    # importance concentrated on feature 1
    assert model.feature_importances[1] > 0.99


@pytest.mark.fast
def test_tree_regression_sklearn_parity(rng, mesh8):
    from sklearn.tree import DecisionTreeRegressor as SK

    x = rng.uniform(-2, 2, size=(800, 4))
    y = np.sin(x[:, 0]) + 0.5 * (x[:, 2] > 0) + 0.1 * rng.normal(size=800)
    ours = DecisionTreeRegressor(max_depth=5, max_bins=64, seed=0).fit((x, y), mesh=mesh8)
    sk = SK(max_depth=5, random_state=0).fit(x, y)
    our_mse = np.mean((ours.predict_numpy(x) - y) ** 2)
    sk_mse = np.mean((sk.predict(x) - y) ** 2)
    # binned splits vs exact splits: allow a modest gap
    assert our_mse <= sk_mse * 1.3 + 1e-3


def test_tree_classifier_binary(rng, mesh8):
    x = rng.uniform(0, 1, size=(600, 4))
    x[:, 0] = rng.choice([0.1, 0.3, 0.6, 0.8], size=600)
    x[:, 3] = rng.choice([0.2, 0.4, 0.7, 0.9], size=600)
    # AND target (greedy-splittable; XOR has zero marginal root gain and
    # defeats any greedy tree, Spark's included)
    y = ((x[:, 0] > 0.5) & (x[:, 3] > 0.5)).astype(np.int64)
    model = DecisionTreeClassifier(max_depth=3, seed=0).fit((x, y), mesh=mesh8)
    acc = (model.predict_numpy(x) == y).mean()
    assert acc > 0.97
    proba = np.asarray(model.predict_proba(ht.device_dataset(x, mesh=mesh8).x))[: len(x)]
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)


def test_tree_sharded_equals_single(rng, mesh8, mesh1):
    x = rng.uniform(0, 1, size=(257, 4))
    y = 2.0 * x[:, 0] + (x[:, 1] > 0.3) * 3.0
    m8 = DecisionTreeRegressor(max_depth=4, seed=0).fit((x, y), mesh=mesh8)
    m1 = DecisionTreeRegressor(max_depth=4, seed=0).fit((x, y), mesh=mesh1)
    np.testing.assert_array_equal(m8.split_feat, m1.split_feat)
    np.testing.assert_allclose(m8.threshold, m1.threshold, atol=1e-6)
    np.testing.assert_allclose(
        m8.predict_numpy(x), m1.predict_numpy(x), atol=1e-5
    )


def test_forest_beats_single_tree(rng, mesh8):
    x = rng.uniform(-2, 2, size=(800, 4))
    y = np.sin(2 * x[:, 0]) * np.cos(x[:, 1]) + 0.05 * rng.normal(size=800)
    xt = rng.uniform(-2, 2, size=(400, 4))
    yt = np.sin(2 * xt[:, 0]) * np.cos(xt[:, 1])
    tree = DecisionTreeRegressor(max_depth=6, max_bins=64, seed=0).fit((x, y), mesh=mesh8)
    # subset="all" isolates the bagging effect (the default "onethird" on a
    # 4-feature problem forces 1-feature nodes, which hurts when one feature
    # dominates — faithful to Spark's default, but not what we assert here)
    forest = RandomForestRegressor(
        num_trees=20, max_depth=6, max_bins=64, seed=0, feature_subset_strategy="all"
    ).fit((x, y), mesh=mesh8)
    t_mse = np.mean((tree.predict_numpy(xt) - yt) ** 2)
    f_mse = np.mean((forest.predict_numpy(xt) - yt) ** 2)
    assert f_mse < t_mse * 1.1  # ensemble at least comparable, usually better
    assert forest.num_trees == 20


def test_forest_classifier_accuracy(rng, mesh8):
    x = rng.uniform(0, 1, size=(800, 4))
    y = ((x[:, 0] + x[:, 1] > 1.0)).astype(np.int64)
    model = RandomForestClassifier(num_trees=10, max_depth=5, seed=0).fit(
        (x, y), mesh=mesh8
    )
    acc = (model.predict_numpy(x) == y).mean()
    assert acc > 0.95
    imp = model.feature_importances
    assert imp[0] + imp[1] > 0.9
    np.testing.assert_allclose(imp.sum(), 1.0, atol=1e-6)


def test_tree_save_load(rng, mesh8, tmp_path):
    x = rng.uniform(0, 1, size=(300, 4))
    y = np.where(x[:, 2] > 0.4, 2.0, -1.0)
    model = DecisionTreeRegressor(max_depth=3, seed=0).fit((x, y), mesh=mesh8)
    model.write().overwrite().save(str(tmp_path / "dt"))
    loaded = load_model(str(tmp_path / "dt"))
    np.testing.assert_allclose(loaded.predict_numpy(x), model.predict_numpy(x))
    forest = RandomForestClassifier(num_trees=5, seed=0).fit(
        (x, (y > 0).astype(np.int64)), mesh=mesh8
    )
    forest.save(str(tmp_path / "rf"))
    lf = load_model(str(tmp_path / "rf"))
    np.testing.assert_array_equal(lf.predict_numpy(x), forest.predict_numpy(x))


def test_tree_constant_labels(rng, mesh8):
    """Pure node: no split, predicts the constant."""
    x = rng.uniform(0, 1, size=(100, 3))
    y = np.full(100, 7.0)
    model = DecisionTreeRegressor(max_depth=3, seed=0).fit((x, y), mesh=mesh8)
    np.testing.assert_allclose(model.predict_numpy(x), 7.0, atol=1e-5)
    assert (model.split_feat[0] == -1).all()


def test_tree_min_instances(rng, mesh8):
    x = rng.uniform(0, 1, size=(100, 2))
    y = x[:, 0]
    strict = DecisionTreeRegressor(max_depth=6, min_instances_per_node=40, seed=0).fit(
        (x, y), mesh=mesh8
    )
    loose = DecisionTreeRegressor(max_depth=6, min_instances_per_node=1, seed=0).fit(
        (x, y), mesh=mesh8
    )
    assert (strict.split_feat >= 0).sum() < (loose.split_feat >= 0).sum()
