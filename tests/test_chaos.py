"""Chaos suite: kill-and-resume at every durability boundary.

Each test injects a deterministic fault (``utils/faults.py``) — a crash at
a named WAL/commit boundary, a torn write at an exact byte offset, flipped
payload bytes, a transient IO error, a failing model executable — then
asserts the recovery contract: exactly-once rows after restart, bit-
identical resumed fits, typed ``CorruptArtifactError`` instead of deep
shape errors, poison-batch quarantine instead of a wedged stream, and
circuit-breaker degradation instead of unhandled serving exceptions.

Every fault is also asserted to have FIRED — a chaos test whose fault
never triggered proves nothing.
"""

import json
import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import (
    CorruptArtifactError,
    FitCheckpointer,
    load_model,
    write_csv,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.kmeans import (
    KMeans,
    KMeansModel,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming import (
    FileStreamSource,
    StreamCheckpoint,
    StreamExecution,
    UnboundedTable,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming.microbatch import (
    BATCH_QUARANTINED,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table_lifecycle import (
    RetentionPolicy,
    TableLifecycle,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils import faults
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils.retry import (
    RetryPolicy,
)

pytestmark = pytest.mark.chaos

#: near-instant backoffs so the suite exercises the ladder, not the clock
FAST_RETRY = RetryPolicy(max_attempts=4, base_delay_s=0.001, max_delay_s=0.01)
FAST_REPLAY = RetryPolicy(max_attempts=3, base_delay_s=0.001, max_delay_s=0.01)


# ------------------------------------------------------------------ helpers
def _event_csv(path, start_minute, n, hospital="H01"):
    base = np.datetime64("2025-03-31T22:00:00") + np.timedelta64(start_minute, "m")
    t = ht.Table.from_dict(
        {
            "hospital_id": np.array([hospital] * n, dtype=object),
            "event_time": base + np.arange(n).astype("timedelta64[s]"),
            "admission_count": np.arange(n),
            "current_occupancy": np.full(n, 100),
            "emergency_visits": np.full(n, 5),
            "seasonality_index": np.full(n, 1.0),
            "length_of_stay": np.full(n, 4.0),
        },
        ht.hospital_event_schema(),
    )
    write_csv(t, path)
    return t


def _mk_stream(tmp_path, foreach=None, max_batch_replays=3):
    """A fresh StreamExecution over tmp_path's dirs — calling it again
    after a crash IS the process restart."""
    incoming = tmp_path / "incoming"
    incoming.mkdir(exist_ok=True)
    src = FileStreamSource(
        str(incoming), ht.hospital_event_schema(), retry=FAST_RETRY
    )
    return incoming, StreamExecution(
        source=src,
        sink=UnboundedTable(str(tmp_path / "table"), ht.hospital_event_schema()),
        checkpoint=StreamCheckpoint(str(tmp_path / "ckpt")),
        foreach_batch=foreach,
        max_batch_replays=max_batch_replays,
        replay_backoff=FAST_REPLAY,
    )


def _drain(tmp_path, **kw):
    """Restart + drain everything; → (exec, infos)."""
    _, exec_ = _mk_stream(tmp_path, **kw)
    infos = []
    while True:
        info = exec_.run_once()
        if info is None:
            return exec_, infos
        infos.append(info)


# ================================================================ stream kills
STREAM_SITES = [
    "stream.after_offsets",
    "stream.after_read",
    "stream.after_foreach",
    "stream.after_sink",
    "stream.after_commit",
]


@pytest.mark.parametrize("site", STREAM_SITES)
def test_stream_killed_at_boundary_resumes_exactly_once(tmp_path, site):
    """Kill the driver at each lifecycle boundary mid-batch; a restarted
    stream must deliver every row exactly once — replaying the in-flight
    batch when it died before commit, skipping it when it died after."""
    incoming, exec_ = _mk_stream(tmp_path)
    _event_csv(str(incoming / "a.csv"), 0, 30)
    assert exec_.run_once().num_appended_rows == 30

    _event_csv(str(incoming / "b.csv"), 1, 20)
    plan = faults.FaultPlan().crash(site)
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash):
            exec_.run_once()
    assert plan.fired(site) == 1

    exec2, infos = _drain(tmp_path)
    snap = exec2.sink.read()
    assert snap.num_rows == 50  # no loss, no duplicates
    assert exec2.checkpoint.quarantine_count() == 0
    # batch ids are contiguous and the stream is fully caught up
    assert exec2.sink.max_batch_id() == 1
    assert exec2.run_once() is None


# ================================================================ torn WAL
@pytest.mark.parametrize("log_name", ["offsets.log", "commits.log"])
@pytest.mark.parametrize("cut", [0, 1, 15, -1], ids=["b0", "b1", "mid", "last-1"])
def test_stream_survives_torn_wal_write(tmp_path, log_name, cut):
    """Tear the WAL append at exact byte offsets (0, 1, mid-entry, all but
    the final newline) in each log; recovery must neither lose nor
    duplicate rows, and the log must stay parseable."""
    incoming, exec_ = _mk_stream(tmp_path)
    _event_csv(str(incoming / "a.csv"), 0, 30)
    exec_.run_once()

    _event_csv(str(incoming / "b.csv"), 1, 20)
    plan = faults.FaultPlan().tear(
        "wal.append", at_byte=cut,
        when=lambda ctx: ctx.get("path", "").endswith(log_name),
    )
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash):
            exec_.run_once()
    assert plan.fired("wal.append") == 1

    exec2, _ = _drain(tmp_path)
    assert exec2.sink.read().num_rows == 50
    assert exec2.run_once() is None
    # a third drop keeps flowing over the repaired tail
    _event_csv(str(incoming / "c.csv"), 2, 10)
    exec3, infos = _drain(tmp_path)
    assert exec3.sink.read().num_rows == 60
    assert infos[-1].num_appended_rows == 10


# ================================================================ fit kills
FIT_SITES = [
    "fit_ckpt.save.arrays",   # before any bytes of the new step land
    "fit_ckpt.save.commit",   # step staged + installed, COMMIT missing
    "fit_ckpt.post_commit",   # committed, cleanup never ran
]


@pytest.fixture(scope="module")
def fit_data():
    # structureless: Lloyd cannot hit exact convergence (move == 0) before
    # the injected kill, so every parametrized crash site actually fires
    rng = np.random.default_rng(7)
    return rng.normal(size=(512, 4)).astype(np.float32)


@pytest.mark.parametrize("site", FIT_SITES)
def test_fit_killed_mid_checkpoint_resumes_bit_identical(
    tmp_path, mesh8, fit_data, site
):
    """Kill a checkpointed KMeans fit inside the save protocol (before,
    at, and after the commit point); rerunning the same config must land
    on EXACTLY the uninterrupted fit's centers."""
    def est(ckpt_dir):
        return KMeans(
            k=4, seed=0, max_iter=6, tol=0.0,
            checkpoint_dir=str(ckpt_dir), checkpoint_every=1,
        )

    ref = est(tmp_path / "ref").fit(fit_data, mesh=mesh8)

    plan = faults.FaultPlan().crash(site, after=2)  # die on the 3rd save
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash):
            est(tmp_path / "crashed").fit(fit_data, mesh=mesh8)
    assert plan.fired(site) == 1

    resumed = est(tmp_path / "crashed").fit(fit_data, mesh=mesh8)
    np.testing.assert_array_equal(resumed.cluster_centers, ref.cluster_centers)
    np.testing.assert_array_equal(resumed.cluster_sizes, ref.cluster_sizes)


def test_double_kill_crash_during_crash_recovery(tmp_path, mesh8, fit_data):
    """ISSUE 17 satellite: the double-kill — a second ``InjectedCrash``
    fired at ``fit_ckpt.resume`` WHILE the ladder is recovering from the
    first kill.  The twice-restarted fit must still land bit-identical
    to the uninterrupted run."""
    def est(ckpt_dir):
        return KMeans(
            k=4, seed=0, max_iter=6, tol=0.0,
            checkpoint_dir=str(ckpt_dir), checkpoint_every=1,
        )

    ref = est(tmp_path / "ref").fit(fit_data, mesh=mesh8)

    plan = faults.FaultPlan()
    # after=1: commit #0 must land first — resume() bails out before its
    # own fault site when no commit record exists, so a crash on the very
    # first commit could never be followed by a crash inside recovery
    plan.crash("fit_ckpt.save.commit", after=1)
    plan.crash("fit_ckpt.resume")
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash) as e1:
            est(tmp_path / "crashed").fit(fit_data, mesh=mesh8)
        assert e1.value.site == "fit_ckpt.save.commit"
        # the second incarnation dies INSIDE recovery, at the resume site
        with pytest.raises(faults.InjectedCrash) as e2:
            est(tmp_path / "crashed").fit(fit_data, mesh=mesh8)
        assert e2.value.site == "fit_ckpt.resume"
        assert plan.fired("fit_ckpt.save.commit") == 1
        assert plan.fired("fit_ckpt.resume") == 1
        # the third incarnation recovers the recovery and completes
        resumed = est(tmp_path / "crashed").fit(fit_data, mesh=mesh8)
    np.testing.assert_array_equal(resumed.cluster_centers, ref.cluster_centers)
    np.testing.assert_array_equal(resumed.cluster_sizes, ref.cluster_sizes)


# ================================================================ save kills
SAVE_SITES = ["model_io.save.arrays", "model_io.save.meta", "model_io.save.swap"]


def _toy_model(scale: float) -> KMeansModel:
    return KMeansModel(
        cluster_centers=np.full((2, 3), scale, np.float32),
        distance_measure="euclidean",
        training_cost=1.0,
        n_iter=1,
        cluster_sizes=np.array([1.0, 1.0], np.float32),
    )


@pytest.mark.parametrize("site", SAVE_SITES)
def test_model_save_killed_preserves_previous_artifact(tmp_path, site):
    """A save that dies at any staging/swap point must leave the previous
    committed artifact loadable and intact."""
    path = str(tmp_path / "model")
    _toy_model(1.0).save(path)

    plan = faults.FaultPlan().crash(site)
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash):
            _toy_model(2.0).save(path, overwrite=True)
    assert plan.fired(site) == 1

    m = load_model(path)  # repairs a displaced artifact if needed
    np.testing.assert_array_equal(
        m.cluster_centers, np.full((2, 3), 1.0, np.float32)
    )
    # and the NEXT save over the crash debris works
    _toy_model(3.0).save(path, overwrite=True)
    np.testing.assert_array_equal(
        load_model(path).cluster_centers, np.full((2, 3), 3.0, np.float32)
    )


def test_composite_prepare_finalize_protocol_survives_crash(tmp_path):
    """Composite savers (pipeline/CV/OvR) write in place between
    prepare_artifact_dir and finalize_artifact_dir; a crash in between
    must leave the PREVIOUS committed artifact recoverable."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io.model_io import (
        INCOMPLETE_SENTINEL,
        finalize_artifact_dir,
        prepare_artifact_dir,
        repair_artifact_dir,
    )

    path = str(tmp_path / "composite")
    # v1 committed through the full protocol
    prepare_artifact_dir(path, overwrite=True)
    with open(os.path.join(path, "payload"), "w") as f:
        f.write("v1")
    finalize_artifact_dir(path)
    assert not os.path.exists(os.path.join(path, INCOMPLETE_SENTINEL))

    # v2 save crashes mid-write: sentinel still present, v1 displaced
    prepare_artifact_dir(path, overwrite=True)
    with open(os.path.join(path, "payload"), "w") as f:
        f.write("v2-torn")
    # "restart": repair discards the torn save and restores v1
    repair_artifact_dir(path)
    with open(os.path.join(path, "payload")) as f:
        assert f.read() == "v1"
    # overwrite=False still refuses over the restored artifact
    with pytest.raises(FileExistsError):
        prepare_artifact_dir(path, overwrite=False)


def test_stream_rejects_nonpositive_replay_budget(tmp_path):
    with pytest.raises(ValueError, match="max_batch_replays"):
        _mk_stream(tmp_path, max_batch_replays=0)


# ================================================================ corruption
def test_model_load_detects_bitflip(tmp_path):
    path = str(tmp_path / "model")
    _toy_model(1.0).save(path)
    f = os.path.join(path, "arrays.npz")
    data = bytearray(open(f, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(f, "wb").write(bytes(data))
    with pytest.raises(CorruptArtifactError, match="crc32c mismatch"):
        load_model(path)


def test_model_load_detects_truncation(tmp_path):
    path = str(tmp_path / "model")
    _toy_model(1.0).save(path)
    f = os.path.join(path, "arrays.npz")
    data = open(f, "rb").read()
    open(f, "wb").write(data[: len(data) // 2])
    with pytest.raises(CorruptArtifactError, match="size mismatch"):
        load_model(path)


def test_model_save_corrupted_in_flight_detected(tmp_path):
    """Bytes corrupted between checksum and platter (the write path lies):
    the manifest carries the intended CRC, so load catches it."""
    path = str(tmp_path / "model")
    plan = faults.FaultPlan().corrupt("model_io.save.arrays", at_byte=64)
    with faults.active(plan):
        _toy_model(1.0).save(path)
    assert plan.fired("model_io.save.arrays") == 1
    with pytest.raises(CorruptArtifactError):
        load_model(path)


def test_fit_checkpoint_corrupt_step_falls_back_to_previous(tmp_path):
    """Bit rot in the committed step → resume() silently falls back to the
    previous retained commit; rot in ALL steps → typed error."""
    ck = FitCheckpointer(str(tmp_path / "ck"), {"sig": 1}, keep=2)
    ck.save(1, {"a": np.arange(4.0)})
    ck.save(2, {"a": np.arange(4.0) * 2})

    f2 = str(tmp_path / "ck" / "step-2" / "arrays.npz")
    data = bytearray(open(f2, "rb").read())
    data[len(data) // 2] ^= 0x01
    open(f2, "wb").write(bytes(data))

    step, arrays, _ = FitCheckpointer(str(tmp_path / "ck"), {"sig": 1}).resume()
    assert step == 1
    np.testing.assert_array_equal(arrays["a"], np.arange(4.0))

    f1 = str(tmp_path / "ck" / "step-1" / "arrays.npz")
    open(f1, "wb").write(b"not an npz at all")
    with pytest.raises(CorruptArtifactError):
        FitCheckpointer(str(tmp_path / "ck"), {"sig": 1}).resume()


def test_fit_checkpoint_signature_still_guards_before_fallback(tmp_path):
    ck = FitCheckpointer(str(tmp_path / "ck"), {"sig": 1})
    ck.save(1, {"a": np.zeros(2)})
    with pytest.raises(ValueError, match="signature mismatch"):
        FitCheckpointer(str(tmp_path / "ck"), {"sig": 2}).resume()


# ================================================================ quarantine
def test_poison_batch_quarantined_and_stream_progresses(tmp_path):
    """A batch whose foreach_batch always raises must be quarantined after
    max_batch_replays tries — with the stream continuing past it — not
    replayed forever."""
    def poison(table, batch_id):
        if len(table) and int(np.asarray(table.column("admission_count"))[0]) == 999:
            raise ValueError("poison row")

    incoming, exec_ = _mk_stream(tmp_path, foreach=poison, max_batch_replays=2)
    n = 3
    base = np.datetime64("2025-03-31T22:00:00")
    bad = ht.Table.from_dict(
        {
            "hospital_id": np.array(["H01"] * n, dtype=object),
            "event_time": base + np.arange(n).astype("timedelta64[s]"),
            "admission_count": np.full(n, 999),  # the poison marker
            "current_occupancy": np.full(n, 100),
            "emergency_visits": np.full(n, 5),
            "seasonality_index": np.full(n, 1.0),
            "length_of_stay": np.full(n, 4.0),
        },
        ht.hospital_event_schema(),
    )
    write_csv(bad, str(incoming / "bad.csv"))

    info = exec_.run_once()
    assert info.status == BATCH_QUARANTINED
    assert exec_.metrics.counters.get("stream.quarantined") == 1
    assert exec_.metrics.counters.get("stream.batch_failures") == 2
    q = exec_.checkpoint.quarantined()
    assert len(q) == 1 and q[0]["attempts"] == 2 and "poison" in q[0]["error"]

    # the stream moves on: the next (clean) drop processes normally
    _event_csv(str(incoming / "good.csv"), 1, 10)
    info2 = exec_.run_once()
    assert info2.status == "ok" and info2.num_appended_rows == 10
    assert exec_.sink.read().num_rows == 10  # poison rows never landed

    # and a RESTART does not resurrect the quarantined batch
    exec2, infos = _drain(tmp_path, foreach=poison, max_batch_replays=2)
    assert infos == [] and exec2.sink.read().num_rows == 10


def test_crash_poison_batch_quarantined_across_restarts(tmp_path):
    """A batch that KILLS the process on every replay: the durable attempt
    count recognizes it on the Nth restart and quarantines it up front."""
    def die(table, batch_id):
        if len(table):
            raise faults.InjectedCrash("batch kills the process")

    incoming, _ = _mk_stream(tmp_path)
    _event_csv(str(incoming / "a.csv"), 0, 5)

    for _ in range(2):  # two incarnations crash mid-batch
        _, exec_ = _mk_stream(tmp_path, foreach=die, max_batch_replays=2)
        with pytest.raises(faults.InjectedCrash):
            exec_.run_once()

    # third incarnation: attempt budget spent → quarantined, no third try
    _, exec3 = _mk_stream(tmp_path, foreach=die, max_batch_replays=2)
    info = exec3.run_once()
    assert info.status == BATCH_QUARANTINED
    assert exec3.checkpoint.quarantine_count() == 1
    assert exec3.run_once() is None  # fully caught up, nothing pending


# ================================================================ source retry
def test_source_read_retries_transient_fault(tmp_path):
    incoming, exec_ = _mk_stream(tmp_path)
    _event_csv(str(incoming / "a.csv"), 0, 12)
    plan = faults.FaultPlan().fail("source.read_file", times=2)
    with faults.active(plan):
        info = exec_.run_once()
    assert info.num_appended_rows == 12   # healed within the batch
    assert plan.fired("source.read_file") == 2
    assert exec_.source.retries == 2
    assert exec_.metrics.counters.get("stream.retries") == 2


def test_source_read_exhaustion_escalates_to_quarantine(tmp_path):
    """Retries exhausted on every replay → the batch ladder gives up and
    quarantines; the file is NOT reprocessed after the fault clears."""
    incoming, exec_ = _mk_stream(tmp_path, max_batch_replays=2)
    _event_csv(str(incoming / "a.csv"), 0, 12)
    plan = faults.FaultPlan().fail("source.read_file", times=None)
    with faults.active(plan):
        info = exec_.run_once()
    assert info.status == BATCH_QUARANTINED
    # 2 replays × 4 read attempts each
    assert plan.fired("source.read_file") == 8
    assert exec_.run_once() is None


# ================================================================ breaker
def test_circuit_breaker_state_machine():
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve import (
        CircuitBreaker,
        STATE_CLOSED,
        STATE_HALF_OPEN,
        STATE_OPEN,
    )

    now = [0.0]
    cb = CircuitBreaker(
        failure_threshold=2, recovery_timeout_s=10.0, clock=lambda: now[0]
    )
    assert cb.state == STATE_CLOSED and cb.allow()
    cb.record_failure()
    assert cb.state == STATE_CLOSED  # one failure is not an outage
    cb.record_failure()
    assert cb.snapshot()["state"] == STATE_OPEN
    assert not cb.allow() and cb.short_circuited == 1

    now[0] = 10.0  # recovery window elapsed → one probe admitted
    assert cb.state == STATE_HALF_OPEN
    assert cb.allow()
    assert not cb.allow()  # only one probe in flight
    cb.record_failure()    # probe fails → straight back to open
    assert cb.snapshot()["state"] == STATE_OPEN and not cb.allow()

    now[0] = 20.0
    assert cb.allow()
    cb.record_success()    # probe succeeds → closed, counters reset
    assert cb.state == STATE_CLOSED
    assert cb.snapshot()["consecutive_failures"] == 0
    assert cb.opened_count == 2


@pytest.mark.slow
def test_serving_degrades_via_breaker_and_recovers(mesh8):
    """Primary-model faults behind the breaker: every request is answered
    (fallback, degraded), zero unhandled exceptions, breaker opens, and
    service self-heals once the fault clears."""
    import time as _time

    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models import (
        LinearRegression,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve import (
        InferenceServer,
        STATUS_UNAVAILABLE,
    )

    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 4)).astype(np.float32)
    y = (x @ np.array([1.0, -1.0, 0.5, 2.0], np.float32)).astype(np.float32)
    model = LinearRegression().fit((x, y))
    prior = float(np.mean(y))

    srv = InferenceServer(
        breaker_failure_threshold=2, breaker_recovery_s=0.05,
    )
    srv.add_model(
        "los", model, buckets=(1, 4, 8),
        fallback=lambda rows: np.full(rows.shape[0], prior, np.float32),
    )
    plan = faults.FaultPlan().fail("serve.predict", times=6)
    with srv:
        results = []
        with faults.active(plan):
            for i in range(10):
                r = srv.predict("los", x[i], wait_timeout_s=10.0)
                results.append(r)
        # every faulted request was ANSWERED by the fallback — degraded,
        # not dropped, and nothing raised
        degraded = [r for r in results if r.status == STATUS_UNAVAILABLE]
        assert len(degraded) >= 2
        assert all(r.degraded and r.value is not None for r in degraded)
        assert all(float(v) == prior for r in degraded for v in r.value)

        health = srv.health()
        assert health["breakers"]["los"]["opened_count"] >= 1
        assert health["fallback_answers"] >= len(degraded)
        assert health["retry_totals"]["primary_failures"] >= 2

        # fault cleared: the breaker's half-open probe heals the service
        deadline = _time.monotonic() + 5.0
        while _time.monotonic() < deadline:
            _time.sleep(0.06)
            if srv.predict("los", x[0], wait_timeout_s=10.0).ok:
                break
        else:
            pytest.fail("service never recovered after faults cleared")
        assert srv.health()["status"] == "ok"


# ================================================================ soak
@pytest.mark.slow
def test_chaos_soak_every_boundary_twice(tmp_path):
    """Serial kill-and-resume across every stream boundary, twice over,
    on one long-lived checkpoint directory — accumulated recovery must
    stay exactly-once end to end."""
    incoming = tmp_path / "incoming"
    incoming.mkdir()
    total = 0
    for round_ in range(2):
        for i, site in enumerate(STREAM_SITES):
            n = 5 + i + round_ * len(STREAM_SITES)
            _event_csv(
                str(incoming / f"drop-{round_}-{i}.csv"), total, n
            )
            total += n
            _, exec_ = _mk_stream(tmp_path)
            plan = faults.FaultPlan().crash(site)
            with faults.active(plan):
                with pytest.raises(faults.InjectedCrash):
                    exec_.run_once()
            # heal before the next kill: the replay budget belongs to
            # each batch, and every boundary crash must recover cleanly
            exec_, _ = _drain(tmp_path)
            assert exec_.sink.read().num_rows == total
    assert exec_.checkpoint.quarantine_count() == 0
    assert exec_.sink.max_batch_id() + 1 == 2 * len(STREAM_SITES)


# ================================================================ primitives
def test_fault_plan_counts_and_after():
    plan = faults.FaultPlan().fail("x.y", times=2, after=1)
    with faults.active(plan):
        faults.fault_point("x.y")          # after=1 skips the first
        for _ in range(2):
            with pytest.raises(faults.FaultError):
                faults.fault_point("x.y")
        faults.fault_point("x.y")          # times=2 exhausted
    assert plan.fired("x.y") == 2 and plan.calls["x.y"] == 4
    faults.fault_point("x.y")              # no plan installed → no-op


def test_crc32c_known_vector():
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import crc32c

    # RFC 3720 §B.4 test vector: 32 zero bytes
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c(b"") == 0


def test_quarantine_record_is_json_and_atomic(tmp_path):
    ck = StreamCheckpoint(str(tmp_path / "ck"))
    p = ck.quarantine(
        7, ["f1.csv"], attempts=3, error="ValueError('x')",
        sink_rows_visible=True,
    )
    with open(p) as f:
        rec = json.load(f)
    assert rec["batch_id"] == 7 and rec["attempts"] == 3
    assert rec["sink_rows_visible"] is True
    assert ck.quarantine_count() == 1


# ====================================================== table lifecycle kills
def _event_batch(bid, n=6, hospital="H01"):
    base = np.datetime64("2025-03-31T22:00:00") + np.timedelta64(bid, "m")
    return ht.Table.from_dict(
        {
            "hospital_id": np.array([hospital] * n, dtype=object),
            "event_time": (base + np.arange(n).astype("timedelta64[s]")
                           ).astype("datetime64[ns]"),
            "admission_count": np.arange(n) + bid * 100,
            "current_occupancy": np.full(n, 100),
            "emergency_visits": np.full(n, 5),
            "seasonality_index": np.full(n, 1.0),
            "length_of_stay": np.full(n, 4.0),
        },
        ht.hospital_event_schema(),
    )


def _mk_history(tmp_path, n_batches=8):
    tbl = UnboundedTable(str(tmp_path / "tbl"), ht.hospital_event_schema())
    for bid in range(n_batches):
        tbl.append_batch(_event_batch(bid), bid)
    return tbl


def _assert_tables_bit_identical(a, b):
    assert list(a.columns) == list(b.columns)
    assert len(a) == len(b)
    for c in a.columns:
        va, vb = a.column(c), b.column(c)
        assert va.dtype == vb.dtype, c
        if va.dtype == object:
            assert list(va) == list(vb), c
        else:
            assert va.tobytes() == vb.tobytes(), c


LIFECYCLE_POLICY = RetentionPolicy(min_seal_batches=2, hot_batches=2,
                                   max_segment_batches=3)
TABLE_SITES = ["table.seal.stage", "table.seal.commit", "table.retire.commit"]


@pytest.mark.parametrize("site", TABLE_SITES)
def test_table_lifecycle_killed_resumes_bit_identical(tmp_path, site):
    """Kill the lifecycle at each seal/retire boundary; a reopened table
    must read exactly the pre-lifecycle snapshot both immediately after
    the kill and after a resumed tick completes the pass."""
    tbl = _mk_history(tmp_path)
    ref = tbl.read()
    plan = faults.FaultPlan().crash(site)
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash):
            TableLifecycle(tbl, LIFECYCLE_POLICY).tick()
    assert plan.fired(site) == 1

    reopened = UnboundedTable(tbl.path, ht.hospital_event_schema())
    _assert_tables_bit_identical(reopened.read(), ref)  # mid-crash state
    TableLifecycle(reopened, LIFECYCLE_POLICY).tick()   # resume finishes
    final = UnboundedTable(tbl.path, ht.hospital_event_schema())
    _assert_tables_bit_identical(final.read(), ref)

    # retired parts are never referenced by the commit-log read plan,
    # and every file the plan DOES reference exists on disk
    retired = {
        f for e in final._log_entries() if "retire" in e
        for f in e["retire"]["files"]
    }
    items, _ = final._assembly()
    for it in items:
        if it[0] == "part":
            assert it[2]["file"] not in retired
            assert os.path.exists(os.path.join(final.path, it[2]["file"]))
    for f in retired:
        assert not os.path.exists(os.path.join(final.path, f))


def test_table_scrub_killed_mid_repair_resumes(tmp_path):
    """Kill scrub at table.scrub.repair (after rot is detected, before
    the quarantine/rebuild lands); a resumed scrub must finish the
    repair and the table reads bit-identical to the pre-rot snapshot."""
    tbl = _mk_history(tmp_path)
    ref = tbl.read()
    keep = RetentionPolicy(min_seal_batches=2, hot_batches=2,
                           max_segment_batches=3, retire_parts=False)
    TableLifecycle(tbl, keep).seal()
    seg = sorted(
        f for f in os.listdir(tbl.segments_dir) if f.endswith(".parquet")
    )[0]
    p = os.path.join(tbl.segments_dir, seg)
    blob = bytearray(open(p, "rb").read())
    blob[len(blob) // 2] ^= 0x01
    with open(p, "wb") as f:
        f.write(bytes(blob))

    plan = faults.FaultPlan().crash("table.scrub.repair")
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash):
            TableLifecycle(tbl, keep).scrub()
    assert plan.fired("table.scrub.repair") == 1

    reopened = UnboundedTable(tbl.path, ht.hospital_event_schema())
    _assert_tables_bit_identical(reopened.read(), ref)  # parts still serve
    out = TableLifecycle(reopened, keep).scrub()
    assert out["repaired"] == 1
    final = UnboundedTable(tbl.path, ht.hospital_event_schema())
    _assert_tables_bit_identical(final.read(), ref)
    # the rotten bytes were quarantined aside, not deleted evidence
    assert any(
        f.endswith(".quarantine") for f in os.listdir(final.segments_dir)
    )
