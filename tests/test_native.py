"""Native C++ scan/watch shim (native/csv_scan.cpp via io/native.py).

The shim replaces the host half of the reference's ingest stack — Spark
Tungsten's generated CSV scan + the streaming file source's directory
listing (mllearnforhospitalnetwork.py:74-82; SURVEY.md E1/E2).  Tests
assert byte-for-byte agreement with the pure-Python engines so the fast
path can never silently change semantics.
"""

import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.schema import (
    FLOAT,
    INT,
    STRING,
    TIMESTAMP,
    Schema,
    hospital_event_schema,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import native
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io.csv import (
    read_csv,
    write_csv,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming.source import (
    FileStreamSource,
)

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native shim not built (no toolchain)"
)


CSV = """hospital_id,event_time,admission_count,current_occupancy,emergency_visits,seasonality_index,length_of_stay
H00,2025-03-31 22:00:00,5,120,3,1.05,4.5
H01,2025-03-31 22:00:01,7,200,1,0.95,6.25
H02,2025-03-31 22:00:02.500,2,80,0,1.20,3.0
"""


pytestmark = pytest.mark.fast


@pytest.fixture
def csv_file(tmp_path):
    p = tmp_path / "events.csv"
    p.write_text(CSV)
    return str(p)


def test_count_rows(csv_file):
    assert native.native_count_rows(csv_file, header=True) == 3
    assert native.native_count_rows(csv_file, header=False) == 4


def test_parse_numeric_projection(csv_file):
    out = native.native_parse_numeric(csv_file, [2, 3, 6], ncols=7)
    np.testing.assert_allclose(
        out, [[5, 120, 4.5], [7, 200, 6.25], [2, 80, 3.0]]
    )


def test_full_table_matches_numpy_engine(csv_file):
    schema = hospital_event_schema()
    t_native = read_csv(csv_file, schema, engine="native")
    t_numpy = read_csv(csv_file, schema, engine="numpy")
    assert list(t_native.columns["hospital_id"]) == list(t_numpy.columns["hospital_id"])
    np.testing.assert_array_equal(
        t_native.columns["event_time"], t_numpy.columns["event_time"]
    )
    for c in ("admission_count", "current_occupancy", "emergency_visits",
              "seasonality_index", "length_of_stay"):
        np.testing.assert_allclose(t_native.columns[c], t_numpy.columns[c])


def test_fractional_timestamp(csv_file):
    schema = hospital_event_schema()
    t = read_csv(csv_file, schema, engine="native")
    assert t.columns["event_time"][2] == np.datetime64("2025-03-31T22:00:02.500")


def test_quoted_fields_and_escapes(tmp_path):
    p = tmp_path / "q.csv"
    p.write_text(
        'name,value\n"Smith, John",1.5\n"say ""hi""",2.5\n'
    )
    schema = Schema([("name", STRING), ("value", FLOAT)])
    t = read_csv(str(p), schema, engine="native")
    assert list(t.columns["name"]) == ['Smith, John', 'say "hi"']
    np.testing.assert_allclose(t.columns["value"], [1.5, 2.5])


def test_invalid_and_empty_numerics_are_nan_then_droppable(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("a,b\n1,2\n,3\nx,4\n")
    schema = Schema([("a", FLOAT), ("b", FLOAT)])
    t = read_csv(str(p), schema, engine="native")
    assert np.isnan(t.columns["a"][1]) and np.isnan(t.columns["a"][2])
    dropped = t.na_drop()
    assert len(dropped) == 1


def test_empty_and_bad_timestamp_is_nat(tmp_path):
    p = tmp_path / "ts.csv"
    p.write_text("t,v\n2025-01-02 03:04:05,1\n,2\nnot-a-time,3\n")
    schema = Schema([("t", TIMESTAMP), ("v", INT)])
    t = read_csv(str(p), schema, engine="native")
    col = t.columns["t"]
    assert col[0] == np.datetime64("2025-01-02T03:04:05")
    assert np.isnat(col[1]) and np.isnat(col[2])


def test_minute_resolution_and_date_only_timestamps(tmp_path):
    p = tmp_path / "res.csv"
    p.write_text("t,v\n2025-03-31 22:00,1\n2025-03-31,2\n2025-03-31T22:05,3\n")
    schema = Schema([("t", TIMESTAMP), ("v", INT)])
    t_native = read_csv(str(p), schema, engine="native")
    t_numpy = read_csv(str(p), schema, engine="numpy")
    np.testing.assert_array_equal(t_native.columns["t"], t_numpy.columns["t"])


def test_dir_list_odd_filenames(tmp_path):
    (tmp_path / "a\tb.csv").write_text("x\n1\n")
    (tmp_path / "plain.csv").write_text("x\n1\n")
    entries = native.native_dir_list(str(tmp_path), ".csv")
    names = sorted(name for _, _, name in entries)
    assert names == ["a\tb.csv", "plain.csv"]


def test_roundtrip_through_write_csv(tmp_path, hospital_table):
    p = tmp_path / "round.csv"
    write_csv(hospital_table, str(p))
    back = read_csv(str(p), hospital_table.schema, engine="native")
    assert len(back) == len(hospital_table)
    np.testing.assert_allclose(
        back.columns["length_of_stay"], hospital_table.columns["length_of_stay"]
    )
    np.testing.assert_array_equal(
        back.columns["event_time"], hospital_table.columns["event_time"]
    )


def test_native_dir_list_matches_scandir(tmp_path):
    for i in range(3):
        (tmp_path / f"f{i}.csv").write_text("a\n1\n")
    (tmp_path / "skip.txt").write_text("x")
    os.mkdir(tmp_path / "sub.csv")  # directories must be excluded
    entries = native.native_dir_list(str(tmp_path), ".csv")
    names = sorted(name for _, _, name in entries)
    assert names == ["f0.csv", "f1.csv", "f2.csv"]
    for mtime_ns, size, name in entries:
        st = os.stat(tmp_path / name)
        assert size == st.st_size
        assert mtime_ns == st.st_mtime_ns


def test_stream_source_uses_native_listing(tmp_path):
    src = FileStreamSource(str(tmp_path), hospital_event_schema())
    assert src.poll() == []
    (tmp_path / "a.csv").write_text(CSV)
    batch = src.poll()
    assert [os.path.basename(f) for f in batch] == ["a.csv"]
    tbl = src.read_files(batch)
    assert len(tbl) == 3
