"""LinearRegression: closed-form parity vs numpy lstsq; sharded == single-device."""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models import LinearRegression
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import load_model


def _xy(rng, n=200, d=4):
    x = rng.normal(size=(n, d))
    w_true = np.array([1.0, -2.0, 0.5, 3.0])
    y = x @ w_true + 0.7 + rng.normal(scale=0.01, size=n)
    return x, y, w_true


@pytest.mark.fast
def test_lr_matches_lstsq(rng, mesh8):
    x, y, w_true = _xy(rng)
    model = LinearRegression().fit((x, y), mesh=mesh8)
    xa = np.concatenate([x, np.ones((len(x), 1))], axis=1)
    ref, *_ = np.linalg.lstsq(xa, y, rcond=None)
    np.testing.assert_allclose(np.asarray(model.coefficients), ref[:4], atol=1e-3)
    np.testing.assert_allclose(float(model.intercept), ref[4], atol=1e-3)


def test_lr_sharded_equals_single(rng, mesh8, mesh1):
    x, y, _ = _xy(rng, n=203)  # odd n forces padding
    m8 = LinearRegression().fit((x, y), mesh=mesh8)
    m1 = LinearRegression().fit((x, y), mesh=mesh1)
    np.testing.assert_allclose(
        np.asarray(m8.coefficients), np.asarray(m1.coefficients), rtol=1e-4, atol=1e-5
    )


def test_lr_transform_and_rmse(rng, mesh8):
    x, y, _ = _xy(rng)
    model = LinearRegression().fit((x, y), mesh=mesh8)
    result = model.transform((x, y), mesh=mesh8)
    rmse = ht.RegressionEvaluator("rmse").evaluate(result)
    assert rmse < 0.05
    r2 = ht.RegressionEvaluator("r2").evaluate(result)
    assert r2 > 0.99


def test_lr_ridge_shrinks(rng, mesh8):
    x, y, _ = _xy(rng)
    m0 = LinearRegression(reg_param=0.0).fit((x, y), mesh=mesh8)
    m1 = LinearRegression(reg_param=10.0).fit((x, y), mesh=mesh8)
    assert np.linalg.norm(np.asarray(m1.coefficients)) < np.linalg.norm(
        np.asarray(m0.coefficients)
    )


def test_lr_save_load_overwrite(rng, mesh8, tmp_path):
    x, y, _ = _xy(rng)
    model = LinearRegression().fit((x, y), mesh=mesh8)
    path = str(tmp_path / "lr")
    # spark-style chain: model.write().overwrite().save(path)  (:241-243)
    model.write().overwrite().save(path)
    model.write().overwrite().save(path)  # overwrite works
    loaded = load_model(path)
    np.testing.assert_allclose(
        np.asarray(loaded.coefficients), np.asarray(model.coefficients)
    )
    pred_a = model.predict_numpy(x[:5])
    pred_b = loaded.predict_numpy(x[:5])
    np.testing.assert_allclose(pred_a, pred_b, rtol=1e-6)


def test_lr_on_hospital_table(hospital_table, mesh8):
    assembler = ht.VectorAssembler(ht.FEATURE_COLS)
    train, test = ht.train_test_split(hospital_table, 0.7, seed=42)
    model = LinearRegression().fit(assembler.transform(train), label_col="length_of_stay", mesh=mesh8)
    res = model.transform(assembler.transform(test), label_col="length_of_stay", mesh=mesh8)
    rmse = ht.RegressionEvaluator("rmse").evaluate(res)
    assert rmse < 0.2  # noise sigma is 0.1
