"""Framework invariant linter (ISSUE 13) — engine + per-rule fixtures.

Three layers:

* **per-rule fixtures** — for every rule, one minimal violating snippet
  and one clean snippet (``tests/lint_fixtures/``), run through the real
  engine against a temp root shaped like the package (scoped passes see
  package-relative paths);
* **machinery** — suppression-requires-reason, baseline round-trip, the
  pinned ``--json`` schema, ``--changed-only`` smoke;
* **the tier-1 gate** — ``python tools/lint.py --json`` over the live
  repo must exit 0 (every invariant the linter encodes holds on the
  shipped source), in < 10 s, without importing jax or numpy (pure AST
  — the check_obs discipline).
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys

import pytest

pytestmark = pytest.mark.lint

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "tools")
FIXTURES = os.path.join(ROOT, "tests", "lint_fixtures")
PKG = "clustermachinelearningforhospitalnetworks_apache_spark_tpu"

if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

from lint import load_baseline, passes_by_name, run, write_baseline  # noqa: E402


def run_fixture(
    tmp_path, fixture: str, passes: list[str],
    dest: str = f"{PKG}/models", complete: bool = False,
    with_trace: bool = False, with_knobs: bool = False, baseline=None,
):
    """Run ``passes`` over one fixture, staged into a temp tree shaped
    like the repo so path-scoped passes apply."""
    root = tmp_path / "repo"
    target_dir = root / dest
    target_dir.mkdir(parents=True, exist_ok=True)
    target = target_dir / fixture
    shutil.copy(os.path.join(FIXTURES, fixture), target)
    paths = [str(target)]
    if with_trace:
        obs = root / PKG / "obs"
        obs.mkdir(parents=True, exist_ok=True)
        shutil.copy(
            os.path.join(ROOT, PKG, "obs", "trace.py"), obs / "trace.py"
        )
        paths.append(str(obs / "trace.py"))
    if with_knobs:
        tune = root / PKG / "tune"
        tune.mkdir(parents=True, exist_ok=True)
        shutil.copy(
            os.path.join(ROOT, PKG, "tune", "knobs.py"), tune / "knobs.py"
        )
        paths.append(str(tune / "knobs.py"))
    return run(
        paths=paths, passes=passes_by_name(passes), root=str(root),
        complete=complete, baseline=baseline,
    )


def active_rules(report) -> set[str]:
    return {f.rule for f in report.active}


# ================================================================ fixtures
#: (fixture, passes, rules that MUST fire, kwargs) — the paired *_clean
#: fixture must produce zero active findings under the same passes
RULE_CASES = [
    ("lock_iter_bad.py", ["concurrency"], {"lock-iter-snapshot"}, {}),
    ("blocking_lock_bad.py", ["concurrency"], {"blocking-under-lock"}, {}),
    ("lock_order_bad.py", ["concurrency"], {"lock-order-cycle"},
     {"complete": True}),
    ("jit_nested_bad.py", ["jit_hygiene"], {"jit-in-function"}, {}),
    ("donate_bad.py", ["jit_hygiene"], {"donated-arg-reused"}, {}),
    ("trace_safety_bad.py", ["trace_safety"],
     {"host-sync-in-jit", "bool-mask-in-jit"}, {}),
    ("determinism_bad.py", ["determinism"],
     {"unseeded-random", "wallclock-in-kernel"}, {}),
    ("metric_labels_bad.py", ["metric_labels"], {"raw-metric-label"}, {}),
    ("obs_sites_bad.py", ["obs_coverage"],
     {"fault-site-uncovered", "dynamic-fault-site"}, {"with_trace": True}),
    ("obs_spans_bad.py", ["obs_coverage"],
     {"span-unregistered", "dynamic-span-name"}, {"with_trace": True}),
    ("partitioner_bad.py", ["partitioner"], {"handrolled-sharding"}, {}),
    ("knob_bad.py", ["knobs"], {"untracked-knob"}, {"with_knobs": True}),
]


@pytest.mark.parametrize(
    "fixture,passes,expected,kwargs", RULE_CASES,
    ids=[c[0].removesuffix("_bad.py") for c in RULE_CASES],
)
def test_rule_fires_on_violation(tmp_path, fixture, passes, expected, kwargs):
    report = run_fixture(tmp_path, fixture, passes, **kwargs)
    got = active_rules(report)
    assert expected <= got, (
        f"{fixture}: expected {sorted(expected)}, engine found "
        f"{sorted(got)}:\n"
        + "\n".join(f"  {f.path}:{f.line} {f.rule} {f.message}"
                    for f in report.active)
    )


@pytest.mark.parametrize(
    "fixture,passes,expected,kwargs", RULE_CASES,
    ids=[c[0].removesuffix("_bad.py") for c in RULE_CASES],
)
def test_clean_twin_stays_clean(tmp_path, fixture, passes, expected, kwargs):
    clean = fixture.replace("_bad.py", "_clean.py")
    report = run_fixture(tmp_path, clean, passes, **kwargs)
    assert not report.active, (
        f"{clean} should be clean; engine found:\n"
        + "\n".join(f"  {f.path}:{f.line} {f.rule} {f.message}"
                    for f in report.active)
    )


def test_metric_label_counts(tmp_path):
    """All six raw label shapes in the fixture are caught — raw
    f-string name, raw value, str() of runtime data, string CONCAT,
    .format() (shapes the regex rules caught but a naive f-string-only
    AST port would miss), and a raw PARAMETER whose name is minted in a
    different function (the alias resolution must be scope-bounded) —
    review-round regressions all."""
    report = run_fixture(
        tmp_path, "metric_labels_bad.py", ["metric_labels"]
    )
    assert len([f for f in report.active if f.rule == "raw-metric-label"]) == 6


def test_partitioner_alias_resolution_counts(tmp_path):
    """All five construction shapes in the fixture are caught — the
    ``as P`` alias, the direct-name import, the hand-built Mesh, and
    both ``sharding.``-module-attribute paths — while isinstance and
    annotation *uses* of PartitionSpec in the clean twin stay exempt
    (only a call mints a layout)."""
    report = run_fixture(tmp_path, "partitioner_bad.py", ["partitioner"])
    hits = [f for f in report.active if f.rule == "handrolled-sharding"]
    assert len(hits) == 5, [(f.line, f.message) for f in hits]


def test_untracked_knob_binding_shapes(tmp_path):
    """All five binding shapes in the fixture are caught — the module
    constant, the attribute assignment, the signature default, the
    alias-laundered default (flagged at the constant, like
    ``handrolled-sharding`` resolves import aliases), and the unary-
    prefixed literal — while call keywords, None-sentinel defaults,
    knob()-derived values and bools in the clean twin stay exempt."""
    report = run_fixture(
        tmp_path, "knob_bad.py", ["knobs"], with_knobs=True
    )
    hits = [f for f in report.active if f.rule == "untracked-knob"]
    assert len(hits) == 5, [(f.line, f.message) for f in hits]


def test_obs_alias_and_forwarding_resolve(tmp_path):
    """ISSUE 13 bugfix regression: the regex scanner silently skipped
    sites passed through aliases and parameter defaults; the AST port
    resolves them (clean) and the f-string site in the bad twin is
    actually CHECKED (fault-site-uncovered, not skipped)."""
    report = run_fixture(
        tmp_path, "obs_sites_clean.py", ["obs_coverage"], with_trace=True
    )
    assert not report.active
    report = run_fixture(
        tmp_path, "obs_sites_bad.py", ["obs_coverage"], with_trace=True
    )
    uncovered = [f for f in report.active if f.rule == "fault-site-uncovered"]
    assert any("custom.uncovered.site" in f.message for f in uncovered), (
        "the f-string-built site must be resolved and checked, "
        "not silently skipped"
    )
    dynamic = [f for f in report.active if f.rule == "dynamic-fault-site"]
    assert len(dynamic) == 2, (
        "expected BOTH dynamic sites flagged: the parameter-forwarded one "
        "AND the one referencing another function's local (a scope-leaked "
        "constant table would silently resolve the latter — review-round "
        f"regression); got {[(f.line, f.message[:40]) for f in dynamic]}"
    )


def test_required_soak_sites_must_stay_reachable(tmp_path):
    """ISSUE 17 satellite: rule 7 (``required-site-missing``) — the soak
    harness's chaos-dispatch fault sites are load-bearing for the chaos
    matrix, so a site going UNREACHABLE (deleted hook call) is itself a
    finding, not just a site existing without coverage.  Completeness
    rules need ``complete=True``; other obs_coverage rules fire over the
    minimal tree too, so assert on the one rule under test."""
    report = run_fixture(
        tmp_path, "soak_sites_bad.py", ["obs_coverage"],
        dest=f"{PKG}/soak", with_trace=True, complete=True,
    )
    missing = [
        f for f in report.active if f.rule == "required-site-missing"
    ]
    assert any("soak.schedule.tick" in f.message for f in missing), (
        "deleting the dispatcher's fault_point must fire "
        f"required-site-missing; got {[f.message[:60] for f in missing]}"
    )
    # the two sites still present must NOT be flagged
    assert not any("soak.phase.transition" in f.message for f in missing)
    assert not any("soak.report.commit" in f.message for f in missing)

    report = run_fixture(
        tmp_path, "soak_sites_clean.py", ["obs_coverage"],
        dest=f"{PKG}/soak", with_trace=True, complete=True,
    )
    assert not [
        f for f in report.active if f.rule == "required-site-missing"
    ], "all three soak sites reachable: rule 7 must stay quiet"


# ============================================================= suppressions
def test_suppression_with_reason_silences(tmp_path):
    report = run_fixture(tmp_path, "suppress_ok.py", ["determinism"])
    assert not report.active
    assert report.suppressed == 1


def test_suppression_without_reason_is_a_finding(tmp_path):
    report = run_fixture(tmp_path, "suppress_noreason.py", ["determinism"])
    rules = active_rules(report)
    # the bare disable does NOT silence, and is itself flagged
    assert "suppression-missing-reason" in rules
    assert "unseeded-random" in rules


# ================================================================ baseline
def test_baseline_round_trip(tmp_path):
    report = run_fixture(tmp_path, "lock_iter_bad.py", ["concurrency"])
    assert report.active
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), report)
    report2 = run_fixture(
        tmp_path, "lock_iter_bad.py", ["concurrency"],
        baseline=load_baseline(str(bl)),
    )
    assert report2.findings and not report2.active, (
        "baselined findings must still be reported but not gate the build"
    )
    # fingerprints key the stripped source line, not the line number
    data = json.loads(open(bl).read())
    assert data["version"] == 2 and data["fingerprints"]


def test_shipped_baseline_is_empty():
    """ISSUE 13: every pre-existing true positive was fixed in this PR —
    the committed baseline must not become a dumping ground."""
    data = json.loads(open(os.path.join(TOOLS, "lint_baseline.json")).read())
    assert data["fingerprints"] == []


# ============================================================== JSON schema
_REPORT_KEYS = {
    "version", "passes", "rules", "files_scanned", "runtime_s",
    "counts", "findings",
}
_COUNT_KEYS = {"total", "baselined", "suppressed", "active"}
_FINDING_KEYS = {
    "rule", "path", "line", "col", "message", "symbol", "fingerprint",
    "baselined",
}


def test_json_schema_pinned(tmp_path):
    """The --json contract consumed by CI tooling is frozen."""
    root = tmp_path / "repo"
    (root / PKG / "models").mkdir(parents=True)
    shutil.copy(
        os.path.join(FIXTURES, "determinism_bad.py"),
        root / PKG / "models" / "determinism_bad.py",
    )
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "lint.py"), "--json",
         "--passes", "determinism", "--root", str(root),
         str(root / PKG / "models" / "determinism_bad.py")],
        capture_output=True, text=True,
    )
    assert r.returncode == 1, r.stdout + r.stderr
    data = json.loads(r.stdout)
    assert set(data) == _REPORT_KEYS
    assert set(data["counts"]) == _COUNT_KEYS
    assert data["version"] == 2, (
        "ISSUE 15 bumped the engine version: the interprocedural layer "
        "changes what a scan means, so schema consumers must see it"
    )
    assert data["findings"], "fixture must produce findings"
    for f in data["findings"]:
        assert set(f) == _FINDING_KEYS
        assert f["fingerprint"].startswith(f["rule"] + ":")


# ============================================================== CLI modes
def test_changed_only_smoke():
    """--changed-only runs off git diff and emits the FULL pinned JSON
    schema even when the change set is empty (pre-commit mode;
    program-completeness rules are skipped on partial scans).  A
    hand-rolled short dict on the empty branch broke schema consumers —
    review-round regression, so the schema is asserted on whichever
    branch this working tree hits."""
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "lint.py"),
         "--changed-only", "--base", "HEAD", "--json"],
        capture_output=True, text=True, cwd=ROOT,
    )
    assert r.returncode in (0, 1), r.stdout + r.stderr
    data = json.loads(r.stdout)
    assert set(data) == _REPORT_KEYS
    assert set(data["counts"]) == _COUNT_KEYS


def test_unknown_pass_is_usage_error():
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "lint.py"),
         "--passes", "no_such_pass"],
        capture_output=True, text=True,
    )
    assert r.returncode == 2


# ============================================================ the tier-1 gate
def test_repo_is_lint_clean_fast_and_jaxfree():
    """THE meta-test: the engine runs clean over the live package with
    ≥ 8 passes in ≤ 15 s (re-pinned for ISSUE 15 — the call-graph build,
    the durable-taint fixpoint, and the durability/crash_protocol
    families ride the same single parse per file) — and the subprocess
    proves the run never imports jax or numpy (``-S`` keeps the image's
    sitecustomize from pre-importing jax on its own)."""
    code = (
        "import sys, json\n"
        f"sys.path.insert(0, {TOOLS!r})\n"
        "from lint import run, load_baseline\n"
        f"bl = load_baseline({os.path.join(TOOLS, 'lint_baseline.json')!r})\n"
        "r = run(baseline=bl)\n"
        "assert 'jax' not in sys.modules, 'engine imported jax'\n"
        "assert 'numpy' not in sys.modules, 'engine imported numpy'\n"
        "print(json.dumps({\n"
        "    'active': [[f.rule, f.path, f.line] for f in r.active],\n"
        "    'runtime_s': r.runtime_s,\n"
        "    'passes': r.passes,\n"
        "    'files': r.files_scanned,\n"
        "}))\n"
    )
    r = subprocess.run(
        [sys.executable, "-S", "-c", code], capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads(r.stdout)
    assert data["active"] == [], (
        "the live package must be lint-clean:\n"
        + "\n".join(f"  {p}:{ln} {rule}" for rule, p, ln in data["active"])
    )
    assert len(data["passes"]) >= 8
    assert data["files"] > 100, "full scan set went missing"
    assert data["runtime_s"] <= 15.0, (
        f"engine took {data['runtime_s']:.1f}s — the ≤15s pre-commit "
        "budget is part of the contract (ISSUE 15 re-pin)"
    )


def test_check_obs_shim_still_works():
    """The historical entry point keeps its contract (run_chaos.sh and
    tests/test_obs.py shell out to it)."""
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "check_obs.py")],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "check_obs: OK" in r.stdout
