"""Compressed-production-day soak (ISSUE 17).

One seeded smoke run (``SMOKE_CONFIG``, ~10 schedule-seconds at 2x)
drives every subsystem together, and the suite asserts its one output
artifact — the CRC-wrapped, machine-checked SoakReport — is clean:
zero unhandled exceptions, unanswered=0 per phase, goodput within each
phase's SLO floor, every injected kill recovered with a CRC-intact
site-tagged postmortem, at least one double-kill (crash during crash
recovery) with the twice-restarted fit bit-identical, bounded
memory/disk/metric-cardinality growth, and one trace id followed from
a raw CSV row to the promoted model.

Also here: the chaos schedule's replayability contract (same seed →
same kills in the same order; the structural invariants every schedule
keeps), the report's flight-recorder-grade CRC discipline (round-trip,
tamper detection), ``check_report``'s teeth (one doctored payload per
invariant, each caught), and the stall watchdog's verdict ladder
(progress → clean, busy-no-progress → StallError + dump, idle ≠ stall).
"""

import copy
import json
import time

import pytest

from clustermachinelearningforhospitalnetworks_apache_spark_tpu.obs import (
    flight_recorder as flight,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve.fleet.watchdog import (
    StallError,
    StallWatchdog,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.soak import (
    KIND_DOUBLE_KILL,
    KIND_KILL,
    KIND_REVIVE,
    SMOKE_CONFIG,
    SoakConfig,
    build_chaos_schedule,
    check_report,
    read_report,
    run_soak,
    write_report,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.soak.report import (
    REQUIRED_TRACE_SPANS,
    SCHEMA_VERSION,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.soak.schedule import (
    CRASH_SITES,
    full_config,
)

pytestmark = pytest.mark.soak


# --------------------------------------------------------------------------
# the smoke run — ONE run per module, every report assertion reads it
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def smoke(tmp_path_factory):
    wd = tmp_path_factory.mktemp("soak_smoke")
    payload, path = run_soak(SMOKE_CONFIG, str(wd))
    return payload, path


def test_smoke_report_machine_checks_clean(smoke):
    payload, _ = smoke
    assert check_report(payload) == []


def test_smoke_every_chaos_kind_ran_and_recovered(smoke):
    payload, _ = smoke
    kills = payload["kills"]
    kinds = {k["kind"] for k in kills}
    assert {KIND_KILL, KIND_REVIVE, KIND_DOUBLE_KILL} <= kinds
    assert all(k["recovered"] for k in kills)
    # every non-revive event left at least one CRC-intact postmortem
    # whose embedded site matches the report's tag
    for k in kills:
        if k["kind"] == KIND_REVIVE:
            continue
        assert k["postmortems"], k["label"]
        for pm in k["postmortems"]:
            dump = flight.read_dump(pm["path"])
            assert dump["site"] == pm["site"]


def test_smoke_double_kill_is_a_crash_inside_recovery(smoke):
    payload, _ = smoke
    dk = [k for k in payload["kills"] if k["kind"] == KIND_DOUBLE_KILL]
    assert len(dk) >= 1
    for k in dk:
        sites = [pm["site"] for pm in k["postmortems"]]
        # first crash in the checkpoint commit, second inside resume
        assert "fit_ckpt.save.commit" in sites
        assert "fit_ckpt.resume" in sites
        assert k["bit_identical"] is True


def test_smoke_phase_slos_and_trace_chain(smoke):
    payload, _ = smoke
    names = [p["name"] for p in payload["phases"]]
    assert names == [p.name for p in SMOKE_CONFIG.phases]
    for p in payload["phases"]:
        assert p["unanswered"] == 0
        assert p["goodput_frac"] >= p["min_goodput_frac"]
    assert payload["unanswered_total"] == 0
    tr = payload["trace"]
    assert tr["trace_id"]
    assert set(REQUIRED_TRACE_SPANS) <= set(tr["span_names"])
    assert tr["csv_file"].endswith(".csv")
    assert tr["promoted_model"]


def test_smoke_report_crc_round_trip(smoke):
    payload, path = smoke
    assert read_report(path) == json.loads(json.dumps(payload, default=str))


def test_report_tamper_detected(smoke, tmp_path):
    payload, _ = smoke
    path = str(tmp_path / "r.json")
    write_report(payload, path)
    with open(path) as f:
        record = json.load(f)
    record["payload"]["unanswered_total"] = 0  # same value, but ...
    record["payload"]["wall_s"] = -1           # ... this one lies
    with open(path, "w") as f:
        json.dump(record, f)
    with pytest.raises(ValueError, match="crc32c mismatch"):
        read_report(path)
    with open(path, "w") as f:
        json.dump({"not": "a report"}, f)
    with pytest.raises(ValueError, match="not a SoakReport"):
        read_report(path)


# --------------------------------------------------------------------------
# chaos schedule: replayability + structural invariants
# --------------------------------------------------------------------------


def test_schedule_same_seed_same_kills():
    a = build_chaos_schedule(SMOKE_CONFIG)
    b = build_chaos_schedule(SMOKE_CONFIG)
    assert [e.to_dict() for e in a] == [e.to_dict() for e in b]
    # and via the JSON round-trip the report relies on
    cfg2 = SoakConfig.from_dict(SMOKE_CONFIG.to_dict())
    assert [e.to_dict() for e in build_chaos_schedule(cfg2)] == [
        e.to_dict() for e in a
    ]


def test_schedule_seed_changes_the_day():
    a = [e.to_dict() for e in build_chaos_schedule(SMOKE_CONFIG)]
    b = [
        e.to_dict()
        for e in build_chaos_schedule(
            SoakConfig.from_dict({**SMOKE_CONFIG.to_dict(), "seed": 7})
        )
    ]
    assert a != b


@pytest.mark.parametrize("seed", [0, 1, 1107, 4242])
def test_schedule_structural_invariants(seed):
    cfg = SoakConfig.from_dict({**SMOKE_CONFIG.to_dict(), "seed": seed})
    events = build_chaos_schedule(cfg)
    assert [e.t for e in events] == sorted(e.t for e in events)
    kills = [e for e in events if e.kind == KIND_KILL]
    revives = [e for e in events if e.kind == KIND_REVIVE]
    assert len(kills) == cfg.replica_kills
    # replica 0 is never killed and every kill has a later revival
    for k in kills:
        assert k.target != "0"
        mates = [r for r in revives if r.target == k.target and r.t > k.t]
        assert mates, f"kill of replica {k.target} never revived"
    crashes = [e for e in events if e.kind == "crash"]
    assert len(crashes) == cfg.crashes
    assert all(c.target in CRASH_SITES for c in crashes)
    # the seeded site permutation: n crashes hit n distinct sites
    assert len({c.target for c in crashes}) == min(
        cfg.crashes, len(CRASH_SITES)
    )
    assert sum(e.kind == KIND_DOUBLE_KILL for e in events) == cfg.double_kills


# --------------------------------------------------------------------------
# check_report has teeth: one doctored payload per invariant
# --------------------------------------------------------------------------


def _doctored(payload, mutate):
    p = copy.deepcopy(payload)
    mutate(p)
    return check_report(p, verify_postmortems=False)


def test_check_report_catches_each_invariant(smoke):
    payload, _ = smoke
    assert check_report(payload, verify_postmortems=False) == []

    def unhandled(p):
        p["unhandled"] = ["phase night: RuntimeError('boom')"]

    def unanswered(p):
        p["phases"][0]["unanswered"] = 3

    def goodput(p):
        p["phases"][1]["goodput_frac"] = 0.0

    def kill_unrecovered(p):
        p["kills"][0]["recovered"] = False

    def no_double_kill(p):
        p["kills"] = [
            k for k in p["kills"] if k["kind"] != KIND_DOUBLE_KILL
        ]

    def second_kill_missing(p):
        for k in p["kills"]:
            if k["kind"] == KIND_DOUBLE_KILL:
                k["postmortems"] = k["postmortems"][:1]

    def not_bit_identical(p):
        for k in p["kills"]:
            if k["kind"] == KIND_DOUBLE_KILL:
                k["bit_identical"] = False

    def unbounded(p):
        p["resources"] = {"bounded": False, "violations": ["rss grew 9x"]}

    def no_lifecycle(p):
        p["lifecycle"]["ticks"] = []

    def lost_segment(p):
        p["lifecycle"]["ticks"][0]["scrub"] = {
            "checked": 1, "repaired": 0, "quarantined": 1,
        }

    def table_over_budget(p):
        p["resources"]["samples"][1]["table_kb"] = (
            p["config"]["table_budget_mb"] * 1024.0 + 1.0
        )

    def table_unobserved(p):
        p["resources"]["samples"][0].pop("table_kb", None)

    def broken_trace(p):
        p["trace"]["span_names"] = ["stream.batch"]

    def not_replayable(p):
        p["chaos_schedule"] = p["chaos_schedule"][:-1]

    def wrong_version(p):
        p["version"] = SCHEMA_VERSION + 1

    def no_retune(p):
        p["retune"] = None

    def retune_not_applied(p):
        p["retune"]["applied"] = False

    def retune_commitless_journal(p):
        p["retune"]["journal_kinds"] = ["intent"]

    def retune_goodput_regressed(p):
        # below min(before) - tolerance but still over the phase floor,
        # so only the retune-boundary invariant can catch it
        p["phases"][-1]["goodput_frac"] = 0.6

    cases = [
        (unhandled, "unhandled exception"),
        (unanswered, "unanswered=3"),
        (goodput, "below the"),
        (kill_unrecovered, "not recovered"),
        (no_double_kill, "no double-kill"),
        (second_kill_missing, "fewer than 2 postmortems"),
        (not_bit_identical, "NOT bit-identical"),
        (unbounded, "rss grew 9x"),
        (no_lifecycle, "seal/retire/scrub never ran"),
        (lost_segment, "quarantined without rebuild"),
        (table_over_budget, "over the"),
        (table_unobserved, "table_kb not recorded"),
        (broken_trace, "span chain incomplete"),
        (not_replayable, "not replayable"),
        (wrong_version, "schema version"),
        (no_retune, "live-retune leg never ran"),
        (retune_not_applied, "never moved"),
        (retune_commitless_journal, "commit last"),
        (retune_goodput_regressed, "regressed across the retune"),
    ]
    for mutate, needle in cases:
        violations = _doctored(payload, mutate)
        assert any(needle in v for v in violations), (
            f"{mutate.__name__}: {needle!r} not in {violations}"
        )


def test_check_report_site_tag_must_match_dump(smoke):
    payload, _ = smoke
    p = copy.deepcopy(payload)
    victim = next(k for k in p["kills"] if k["postmortems"])
    victim["postmortems"][0]["site"] = "somewhere.else"
    violations = check_report(p)  # verify_postmortems=True re-reads disk
    assert any("dump tagged" in v for v in violations)


# --------------------------------------------------------------------------
# stall watchdog (serve/fleet/watchdog.py): the soak's hang-to-failure
# converter, unit-tested at a tight window
# --------------------------------------------------------------------------


@pytest.fixture
def quiet_recorder(tmp_path):
    prev = flight.recorder()
    rec = flight.install(
        flight.FlightRecorder(dump_dir=str(tmp_path / "flight"))
    )
    yield rec
    flight.install(prev)


def _settle(wd, timeout_s=3.0):
    t0 = time.monotonic()
    while wd.stalled() is None and time.monotonic() - t0 < timeout_s:
        time.sleep(0.02)
    return wd.stalled()


def test_watchdog_declares_busy_no_progress(quiet_recorder):
    wd = StallWatchdog(window_s=0.2, poll_s=0.02)
    wd.register("wedged", lambda: 0.0)  # no busy_fn: always busy
    with wd:
        err = _settle(wd)
    assert isinstance(err, StallError)
    assert err.stage == "wedged"
    with pytest.raises(StallError):
        wd.check()
    dump = flight.read_dump(err.dump_path)
    assert dump["site"] == "watchdog.stall"
    assert dump["trigger"]["stage"] == "wedged"


def test_watchdog_progress_and_idle_are_not_stalls(quiet_recorder):
    ticks = [0]

    def progress():
        ticks[0] += 1
        return float(ticks[0])

    wd = StallWatchdog(window_s=0.15, poll_s=0.02)
    wd.register("alive", progress)
    wd.register("idle", lambda: 0.0, busy_fn=lambda: False)
    with wd:
        time.sleep(0.5)
        assert wd.stalled() is None
        wd.check()  # no raise


def test_watchdog_on_stall_callback_and_raising_reader(quiet_recorder):
    seen = []
    wd = StallWatchdog(
        window_s=0.15, poll_s=0.02, on_stall=seen.append
    )

    def dying():
        raise RuntimeError("source crashed")  # reads as no-change

    wd.register("dying", dying)
    with wd:
        err = _settle(wd)
    assert err is not None and err.stage == "dying"
    assert seen and seen[0] is err


# --------------------------------------------------------------------------
# the slow shape: the full multi-phase day, excluded from tier-1
# --------------------------------------------------------------------------


@pytest.mark.slow
def test_full_day_soak_clean(tmp_path):
    payload, _ = run_soak(full_config(), str(tmp_path))
    assert check_report(payload) == []
    assert len(payload["phases"]) == 4
