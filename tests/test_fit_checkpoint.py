"""Mid-training checkpoint/resume (SURVEY.md §5): a fit preempted between
commits resumes from the last committed iteration and converges to exactly
the result of an uninterrupted run — the fault-injection strategy the
reference lacks entirely (its only recovery is the *stream* WAL)."""

import numpy as np
import pytest

from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io.fit_checkpoint import (
    FitCheckpointer,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.gmm import (
    GaussianMixture,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.kmeans import KMeans


class Preempt(RuntimeError):
    pass


def _blobs(rng, n=800, k=4, d=5, spread=0.3):
    centers = rng.normal(scale=4.0, size=(k, d))
    x = centers[rng.integers(0, k, n)] + rng.normal(scale=spread, size=(n, d))
    return x.astype(np.float32)


# --- FitCheckpointer unit tier -----------------------------------------


@pytest.mark.fast
def test_roundtrip_and_prune(tmp_path):
    ck = FitCheckpointer(str(tmp_path / "ck"), {"a": 1}, keep=2)
    assert ck.resume() is None
    for step in (2, 4, 6):
        ck.save(step, {"x": np.full((3,), step)}, extra={"ll": step * 1.5})
    step, arrays, extra = ck.resume()
    assert step == 6 and extra == {"ll": 9.0}
    np.testing.assert_array_equal(arrays["x"], np.full((3,), 6))
    assert sorted(ck._step_dirs()) == [4, 6]  # pruned to keep=2


def test_signature_mismatch_raises(tmp_path):
    path = str(tmp_path / "ck")
    FitCheckpointer(path, {"k": 4}).save(1, {"x": np.zeros(2)})
    with pytest.raises(ValueError, match="signature mismatch"):
        FitCheckpointer(path, {"k": 5}).resume()


def test_torn_save_invisible(tmp_path):
    """A crash mid-save (tmp dir present, no COMMIT update) must leave the
    previous commit as the resume point."""
    path = str(tmp_path / "ck")
    ck = FitCheckpointer(path, {"k": 4})
    ck.save(3, {"x": np.ones(2)})
    # simulate a torn later save: stage the tmp dir but die before rename
    import os

    os.makedirs(os.path.join(path, ".tmp-step-6"))
    step, arrays, _ = FitCheckpointer(path, {"k": 4}).resume()
    assert step == 3
    np.testing.assert_array_equal(arrays["x"], np.ones(2))


def test_resave_crash_window_recovers(tmp_path):
    """Re-saving the committed step displaces the old dir instead of
    deleting it, so a crash after the displace but before the new dir
    lands still leaves a resumable copy (restored on next construction)."""
    import os
    import shutil

    path = str(tmp_path / "ck")
    ck = FitCheckpointer(path, {"k": 4})
    ck.save(3, {"x": np.ones(2)})
    # simulate the crash window inside a re-save of step 3: the committed
    # dir has been displaced aside, the replacement never landed
    os.replace(os.path.join(path, "step-3"), os.path.join(path, ".old-step-3"))
    step, arrays, _ = FitCheckpointer(path, {"k": 4}).resume()
    assert step == 3
    np.testing.assert_array_equal(arrays["x"], np.ones(2))


def test_orphan_step_dirs_not_counted_committed(tmp_path):
    """A step dir newer than COMMIT (crash between rename and COMMIT) must
    not count toward ``keep`` or evict genuinely committed steps."""
    import os

    path = str(tmp_path / "ck")
    ck = FitCheckpointer(path, {"k": 4}, keep=2)
    ck.save(1, {"x": np.full((2,), 1.0)})
    ck.save(2, {"x": np.full((2,), 2.0)})
    # orphan from a crashed future save: dir exists, COMMIT still at 2
    os.makedirs(os.path.join(path, "step-9"))
    ck.save(3, {"x": np.full((2,), 3.0)})
    # keep=2 retains {2, 3}; the orphan is gone and step-2 survived
    assert sorted(ck._step_dirs()) == [2, 3]
    step, arrays, _ = ck.resume()
    assert step == 3


# --- estimator fault-injection tier ------------------------------------


def test_kmeans_preempt_resume_exact(tmp_path, rng, mesh8):
    x = _blobs(rng, spread=1.5)  # overlapping blobs: Lloyd needs many iters
    base = dict(k=4, seed=0, max_iter=25, tol=0.0)  # tol=0: run to fixpoint
    uninterrupted = KMeans(**base).fit(x, mesh=mesh8)

    ckdir = str(tmp_path / "km")
    est = KMeans(checkpoint_dir=ckdir, checkpoint_every=1, **base)

    def bomb(it, cost, move):
        if it == 2:
            raise Preempt()

    with pytest.raises(Preempt):
        est.fit(x, mesh=mesh8, on_iteration=bomb)

    seen = []
    resumed = est.fit(x, mesh=mesh8, on_iteration=lambda it, c, m: seen.append(it))
    assert seen[0] == 3  # resumed from the commit at it=2, not from scratch
    np.testing.assert_allclose(
        resumed.cluster_centers, uninterrupted.cluster_centers, rtol=0, atol=1e-6
    )
    np.testing.assert_allclose(
        resumed.training_cost, uninterrupted.training_cost, rtol=1e-6
    )
    assert resumed.n_iter == uninterrupted.n_iter


def test_gmm_preempt_resume_exact(tmp_path, rng, mesh8):
    x = _blobs(rng, n=600, k=3, d=3)
    base = dict(k=3, seed=1, max_iter=12, tol=0.0)
    uninterrupted = GaussianMixture(**base).fit(x, mesh=mesh8)

    ckdir = str(tmp_path / "gmm")
    est = GaussianMixture(checkpoint_dir=ckdir, checkpoint_every=3, **base)

    def bomb(it, ll):
        if it == 5:
            raise Preempt()

    with pytest.raises(Preempt):
        est.fit(x, mesh=mesh8, on_iteration=bomb)

    seen = []
    resumed = est.fit(x, mesh=mesh8, on_iteration=lambda it, ll: seen.append(it))
    assert seen[0] == 4  # commit at it=3
    np.testing.assert_allclose(resumed.means, uninterrupted.means, atol=1e-5)
    np.testing.assert_allclose(resumed.weights, uninterrupted.weights, atol=1e-6)
    np.testing.assert_allclose(
        resumed.covariances, uninterrupted.covariances, atol=1e-5
    )


def test_different_data_same_shape_refuses_resume(tmp_path, rng, mesh8):
    """The signature's data fingerprint catches 'same shape, different
    rows' — resuming Monday's trajectory on Tuesday's batch must raise."""
    x1 = _blobs(rng)
    x2 = _blobs(rng)  # fresh draw, identical shape
    ckdir = str(tmp_path / "km3")
    est = KMeans(k=4, seed=0, max_iter=5, checkpoint_dir=ckdir, checkpoint_every=1)
    est.fit(x1, mesh=mesh8)
    with pytest.raises(ValueError, match="signature mismatch"):
        est.fit(x2, mesh=mesh8)


def test_kmeans_checkpoint_noop_when_converged(tmp_path, rng, mesh8):
    """Resuming a checkpoint of an already-converged fit returns the same
    model without re-running the trajectory."""
    x = _blobs(rng)
    ckdir = str(tmp_path / "km2")
    est = KMeans(k=4, seed=0, max_iter=30, checkpoint_dir=ckdir, checkpoint_every=1)
    first = est.fit(x, mesh=mesh8)
    again = est.fit(x, mesh=mesh8)
    np.testing.assert_allclose(
        again.cluster_centers, first.cluster_centers, atol=1e-6
    )
