"""Mid-training checkpoint/resume (SURVEY.md §5): a fit preempted between
commits resumes from the last committed iteration and converges to exactly
the result of an uninterrupted run — the fault-injection strategy the
reference lacks entirely (its only recovery is the *stream* WAL)."""

import numpy as np
import pytest

from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io.fit_checkpoint import (
    FitCheckpointer,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.gmm import (
    GaussianMixture,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.kmeans import KMeans


class Preempt(RuntimeError):
    pass


def _blobs(rng, n=800, k=4, d=5, spread=0.3):
    centers = rng.normal(scale=4.0, size=(k, d))
    x = centers[rng.integers(0, k, n)] + rng.normal(scale=spread, size=(n, d))
    return x.astype(np.float32)


# --- FitCheckpointer unit tier -----------------------------------------


@pytest.mark.fast
def test_roundtrip_and_prune(tmp_path):
    ck = FitCheckpointer(str(tmp_path / "ck"), {"a": 1}, keep=2)
    assert ck.resume() is None
    for step in (2, 4, 6):
        ck.save(step, {"x": np.full((3,), step)}, extra={"ll": step * 1.5})
    step, arrays, extra = ck.resume()
    assert step == 6 and extra == {"ll": 9.0}
    np.testing.assert_array_equal(arrays["x"], np.full((3,), 6))
    assert sorted(ck._step_dirs()) == [4, 6]  # pruned to keep=2


def test_signature_mismatch_raises(tmp_path):
    path = str(tmp_path / "ck")
    FitCheckpointer(path, {"k": 4}).save(1, {"x": np.zeros(2)})
    with pytest.raises(ValueError, match="signature mismatch"):
        FitCheckpointer(path, {"k": 5}).resume()


def test_torn_save_invisible(tmp_path):
    """A crash mid-save (tmp dir present, no COMMIT update) must leave the
    previous commit as the resume point."""
    path = str(tmp_path / "ck")
    ck = FitCheckpointer(path, {"k": 4})
    ck.save(3, {"x": np.ones(2)})
    # simulate a torn later save: stage the tmp dir but die before rename
    import os

    os.makedirs(os.path.join(path, ".tmp-step-6"))
    step, arrays, _ = FitCheckpointer(path, {"k": 4}).resume()
    assert step == 3
    np.testing.assert_array_equal(arrays["x"], np.ones(2))


def test_resave_crash_window_recovers(tmp_path):
    """Re-saving the committed step displaces the old dir instead of
    deleting it, so a crash after the displace but before the new dir
    lands still leaves a resumable copy (restored on next construction)."""
    import os
    import shutil

    path = str(tmp_path / "ck")
    ck = FitCheckpointer(path, {"k": 4})
    ck.save(3, {"x": np.ones(2)})
    # simulate the crash window inside a re-save of step 3: the committed
    # dir has been displaced aside, the replacement never landed
    os.replace(os.path.join(path, "step-3"), os.path.join(path, ".old-step-3"))
    step, arrays, _ = FitCheckpointer(path, {"k": 4}).resume()
    assert step == 3
    np.testing.assert_array_equal(arrays["x"], np.ones(2))


def test_orphan_step_dirs_not_counted_committed(tmp_path):
    """A step dir newer than COMMIT (crash between rename and COMMIT) must
    not count toward ``keep`` or evict genuinely committed steps."""
    import os

    path = str(tmp_path / "ck")
    ck = FitCheckpointer(path, {"k": 4}, keep=2)
    ck.save(1, {"x": np.full((2,), 1.0)})
    ck.save(2, {"x": np.full((2,), 2.0)})
    # orphan from a crashed future save: dir exists, COMMIT still at 2
    os.makedirs(os.path.join(path, "step-9"))
    ck.save(3, {"x": np.full((2,), 3.0)})
    # keep=2 retains {2, 3}; the orphan is gone and step-2 survived
    assert sorted(ck._step_dirs()) == [2, 3]
    step, arrays, _ = ck.resume()
    assert step == 3


# --- estimator fault-injection tier ------------------------------------


def test_kmeans_preempt_resume_exact(tmp_path, rng, mesh8):
    x = _blobs(rng, spread=1.5)  # overlapping blobs: Lloyd needs many iters
    base = dict(k=4, seed=0, max_iter=25, tol=0.0)  # tol=0: run to fixpoint
    uninterrupted = KMeans(**base).fit(x, mesh=mesh8)

    ckdir = str(tmp_path / "km")
    est = KMeans(checkpoint_dir=ckdir, checkpoint_every=1, **base)

    def bomb(it, cost, move):
        if it == 2:
            raise Preempt()

    with pytest.raises(Preempt):
        est.fit(x, mesh=mesh8, on_iteration=bomb)

    seen = []
    resumed = est.fit(x, mesh=mesh8, on_iteration=lambda it, c, m: seen.append(it))
    assert seen[0] == 3  # resumed from the commit at it=2, not from scratch
    np.testing.assert_allclose(
        resumed.cluster_centers, uninterrupted.cluster_centers, rtol=0, atol=1e-6
    )
    np.testing.assert_allclose(
        resumed.training_cost, uninterrupted.training_cost, rtol=1e-6
    )
    assert resumed.n_iter == uninterrupted.n_iter


def test_gmm_preempt_resume_exact(tmp_path, rng, mesh8):
    x = _blobs(rng, n=600, k=3, d=3)
    base = dict(k=3, seed=1, max_iter=12, tol=0.0)
    uninterrupted = GaussianMixture(**base).fit(x, mesh=mesh8)

    ckdir = str(tmp_path / "gmm")
    est = GaussianMixture(checkpoint_dir=ckdir, checkpoint_every=3, **base)

    def bomb(it, ll):
        if it == 5:
            raise Preempt()

    with pytest.raises(Preempt):
        est.fit(x, mesh=mesh8, on_iteration=bomb)

    seen = []
    resumed = est.fit(x, mesh=mesh8, on_iteration=lambda it, ll: seen.append(it))
    assert seen[0] == 4  # commit at it=3
    np.testing.assert_allclose(resumed.means, uninterrupted.means, atol=1e-5)
    np.testing.assert_allclose(resumed.weights, uninterrupted.weights, atol=1e-6)
    np.testing.assert_allclose(
        resumed.covariances, uninterrupted.covariances, atol=1e-5
    )


def test_different_data_same_shape_refuses_resume(tmp_path, rng, mesh8):
    """The signature's data fingerprint catches 'same shape, different
    rows' — resuming Monday's trajectory on Tuesday's batch must raise."""
    x1 = _blobs(rng)
    x2 = _blobs(rng)  # fresh draw, identical shape
    ckdir = str(tmp_path / "km3")
    est = KMeans(k=4, seed=0, max_iter=5, checkpoint_dir=ckdir, checkpoint_every=1)
    est.fit(x1, mesh=mesh8)
    with pytest.raises(ValueError, match="signature mismatch"):
        est.fit(x2, mesh=mesh8)


def test_kmeans_checkpoint_noop_when_converged(tmp_path, rng, mesh8):
    """Resuming a checkpoint of an already-converged fit returns the same
    model without re-running the trajectory."""
    x = _blobs(rng)
    ckdir = str(tmp_path / "km2")
    est = KMeans(k=4, seed=0, max_iter=30, checkpoint_dir=ckdir, checkpoint_every=1)
    first = est.fit(x, mesh=mesh8)
    again = est.fit(x, mesh=mesh8)
    np.testing.assert_allclose(
        again.cluster_centers, first.cluster_centers, atol=1e-6
    )


# ---- round-5: checkpoint x out-of-core for trees and GBT (VERDICT r4 #5)

def _tree_data(rng, n=2000, d=5):
    x = np.round(rng.normal(size=(n, d)) * 4).astype(np.float32)  # integer-
    # valued features: f32-exact sums -> bit-identical splits across paths
    y = (x @ rng.normal(size=(d,)) + rng.normal(0, 0.3, size=n)).astype(np.float32)
    return x, y


def test_outofcore_forest_preempt_resume_exact(tmp_path, rng, mesh8):
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.tree.engine import (
        grow_forest_outofcore,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.outofcore import (
        HostDataset,
    )

    x, y = _tree_data(rng)
    hd = HostDataset(x=x, y=y, max_device_rows=256)
    kw = dict(task="regression", num_trees=3, max_depth=4, bootstrap=True,
              subsampling_rate=0.8, seed=0, mesh=mesh8)
    uninterrupted = grow_forest_outofcore(hd, **kw)

    ckdir = str(tmp_path / "forest")

    def bomb(depth):
        if depth == 2:
            raise Preempt()

    with pytest.raises(Preempt):
        grow_forest_outofcore(
            hd, checkpoint_dir=ckdir, checkpoint_every=1, on_level=bomb, **kw
        )

    seen = []
    resumed = grow_forest_outofcore(
        hd, checkpoint_dir=ckdir, checkpoint_every=1,
        on_level=lambda dep: seen.append(dep), **kw
    )
    assert seen[0] == 3  # resumed after the level-2 commit, not from scratch
    np.testing.assert_array_equal(resumed.split_feat, uninterrupted.split_feat)
    np.testing.assert_array_equal(resumed.split_bin, uninterrupted.split_bin)
    np.testing.assert_allclose(resumed.value, uninterrupted.value, atol=1e-6)
    np.testing.assert_allclose(
        resumed.importances, uninterrupted.importances, atol=1e-6
    )


def test_outofcore_tree_estimator_checkpoint_roundtrip(tmp_path, rng, mesh8):
    """The estimator surface: a DecisionTreeRegressor out-of-core fit with
    checkpoint_dir commits per level; a second fit call resumes from the
    final commit and returns the identical model."""
    import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht

    x, y = _tree_data(rng, n=1500, d=4)
    hd = ht.HostDataset(x=x, y=y, max_device_rows=256)
    ckdir = str(tmp_path / "dt")
    est = ht.DecisionTreeRegressor(
        max_depth=3, seed=0, checkpoint_dir=ckdir, checkpoint_every=1
    )
    first = est.fit(hd, mesh=mesh8)
    again = est.fit(hd, mesh=mesh8)   # resumes at the completed state
    np.testing.assert_array_equal(first.split_feat, again.split_feat)
    np.testing.assert_allclose(first.value, again.value, atol=1e-6)
    # resident fits ignore checkpoint_dir (documented) and still work
    resident = est.fit((x, y), mesh=mesh8)
    np.testing.assert_array_equal(first.split_feat, resident.split_feat)


def test_outofcore_gbt_preempt_resume_exact(tmp_path, rng, mesh8, monkeypatch):
    import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.tree import engine

    x, y = _tree_data(rng, n=1200, d=4)
    hd = ht.HostDataset(x=x, y=y, max_device_rows=256)
    base = dict(max_iter=5, max_depth=2, seed=0)
    uninterrupted = ht.GBTRegressor(**base).fit(hd, mesh=mesh8)

    ckdir = str(tmp_path / "gbt")
    est = ht.GBTRegressor(checkpoint_dir=ckdir, checkpoint_every=1, **base)

    real = engine.grow_forest_outofcore
    calls = {"n": 0}

    def bombing(*a, **k):
        calls["n"] += 1
        if calls["n"] == 3:      # die growing round-2's tree (rounds 0,1 done)
            raise Preempt()
        return real(*a, **k)

    monkeypatch.setattr(engine, "grow_forest_outofcore", bombing)
    with pytest.raises(Preempt):
        est.fit(hd, mesh=mesh8)
    monkeypatch.setattr(engine, "grow_forest_outofcore", real)

    resumed = est.fit(hd, mesh=mesh8)
    np.testing.assert_array_equal(
        resumed.split_feat, uninterrupted.split_feat
    )
    np.testing.assert_allclose(resumed.value, uninterrupted.value, atol=1e-6)
    np.testing.assert_allclose(resumed.init, uninterrupted.init, rtol=1e-7)
    pred_r = np.asarray(resumed.predict_numpy(x[:64]))
    pred_u = np.asarray(uninterrupted.predict_numpy(x[:64]))
    np.testing.assert_allclose(pred_r, pred_u, atol=1e-5)


def test_outofcore_forest_resume_with_categoricals(tmp_path, rng, mesh8):
    """Review regression: the signature's categorical map must survive the
    JSON round trip — tuples vs lists made every categorical resume raise
    a spurious signature mismatch."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.tree.engine import (
        grow_forest_outofcore,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.outofcore import (
        HostDataset,
    )

    n = 800
    xc = rng.integers(0, 3, size=n).astype(np.float32)
    xn = np.round(rng.normal(size=n) * 4).astype(np.float32)
    y = (np.where(xc == 1, 3.0, 0.0) + 0.5 * xn).astype(np.float32)
    hd = HostDataset(
        x=np.column_stack([xc, xn]).astype(np.float32), y=y, max_device_rows=128
    )
    kw = dict(task="regression", num_trees=1, max_depth=3, seed=0, mesh=mesh8,
              categorical_features={0: 3})
    uninterrupted = grow_forest_outofcore(hd, **kw)
    ckdir = str(tmp_path / "catforest")

    def bomb(depth):
        if depth == 1:
            raise Preempt()

    with pytest.raises(Preempt):
        grow_forest_outofcore(
            hd, checkpoint_dir=ckdir, checkpoint_every=1, on_level=bomb, **kw
        )
    resumed = grow_forest_outofcore(
        hd, checkpoint_dir=ckdir, checkpoint_every=1, **kw
    )
    np.testing.assert_array_equal(resumed.split_feat, uninterrupted.split_feat)
    np.testing.assert_array_equal(
        resumed.split_catmask, uninterrupted.split_catmask
    )
