"""ALS collaborative filtering (pyspark.ml.recommendation parity).

Oracle: an independent per-row NumPy ALS (explicit solves with
np.linalg.solve in a Python loop) — a different code path from the
batched padded einsum/Cholesky device implementation under test."""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht


def _synth(rng, n_u=60, n_i=40, f=3, frac=0.35, noise=0.05):
    U = rng.normal(0, 1, size=(n_u, f))
    V = rng.normal(0, 1, size=(n_i, f))
    mask = rng.uniform(size=(n_u, n_i)) < frac
    uu, ii = np.nonzero(mask)
    rr = ((U @ V.T)[uu, ii] + noise * rng.normal(size=len(uu))).astype(np.float32)
    return U, V, mask, uu, ii, rr


def _numpy_als(uu, ii, rr, n_u, n_i, rank, iters, reg, uf0, vf0):
    """Reference ALS-WR with per-row loops (λ·n_u scaling)."""
    uf, vf = uf0.copy(), vf0.copy()
    for _ in range(iters):
        for u in range(n_u):
            sel = uu == u
            if not sel.any():
                uf[u] = 0
                continue
            y = vf[ii[sel]]
            a = y.T @ y + reg * sel.sum() * np.eye(rank)
            uf[u] = np.linalg.solve(a, y.T @ rr[sel])
        for i in range(n_i):
            sel = ii == i
            if not sel.any():
                vf[i] = 0
                continue
            y = uf[uu[sel]]
            a = y.T @ y + reg * sel.sum() * np.eye(rank)
            vf[i] = np.linalg.solve(a, y.T @ rr[sel])
    return uf, vf


class TestALSExplicit:
    def test_recovers_low_rank_signal(self, rng):
        U, V, mask, uu, ii, rr = _synth(rng)
        m = ht.ALS(rank=3, max_iter=12, reg_param=0.05, seed=0).fit((uu, ii, rr))
        rmse = np.sqrt(np.mean((m.predict(uu, ii) - rr) ** 2))
        assert rmse < 0.15
        # held-out pairs generalize (low-rank structure was learned, not
        # memorized)
        hu, hi = np.nonzero(~mask)
        hr = (U @ V.T)[hu, hi]
        ho = np.sqrt(np.mean((m.predict(hu, hi) - hr) ** 2))
        assert ho < 0.5 * hr.std()

    def test_matches_numpy_reference(self, rng):
        """Same init, same iteration count → same factors (the batched
        padded solves are algebraically the per-row normal equations)."""
        _, _, _, uu, ii, rr = _synth(rng, n_u=25, n_i=18, f=2)
        n_u, n_i, rank = 25, 18, 2
        seed_rng = np.random.default_rng(7)
        scale = 1.0 / np.sqrt(rank)
        uf0 = seed_rng.normal(0, scale, size=(n_u, rank)).astype(np.float32)
        vf0 = seed_rng.normal(0, scale, size=(n_i, rank)).astype(np.float32)

        ref_uf, ref_vf = _numpy_als(
            uu, ii, rr.astype(np.float64), n_u, n_i, rank, 3, 0.1,
            uf0.astype(np.float64), vf0.astype(np.float64),
        )

        # drive the framework's half-step solvers directly from the same
        # init (the estimator draws its own init internally)
        import jax.numpy as jnp

        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.als import (
            _group_ratings, _solve_explicit,
        )

        u_idx, u_val, u_msk, u_cnt = _group_ratings(uu, ii, rr, n_u)
        i_idx, i_val, i_msk, i_cnt = _group_ratings(ii, uu, rr, n_i)
        uf, vf = jnp.asarray(uf0), jnp.asarray(vf0)
        for _ in range(3):
            uf = _solve_explicit(
                vf, jnp.asarray(u_idx), jnp.asarray(u_val), jnp.asarray(u_msk),
                jnp.asarray(u_cnt), jnp.float32(0.1), rank,
            )
            vf = _solve_explicit(
                uf, jnp.asarray(i_idx), jnp.asarray(i_val), jnp.asarray(i_msk),
                jnp.asarray(i_cnt), jnp.float32(0.1), rank,
            )
        np.testing.assert_allclose(np.asarray(uf), ref_uf, rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(np.asarray(vf), ref_vf, rtol=2e-3, atol=2e-3)

    def test_regularization_shrinks_factors(self, rng):
        _, _, _, uu, ii, rr = _synth(rng)
        lo = ht.ALS(rank=3, max_iter=5, reg_param=0.01, seed=0).fit((uu, ii, rr))
        hi = ht.ALS(rank=3, max_iter=5, reg_param=10.0, seed=0).fit((uu, ii, rr))
        assert (
            np.linalg.norm(hi.user_factors) < np.linalg.norm(lo.user_factors)
        )

    def test_input_forms(self, rng):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

        _, _, _, uu, ii, rr = _synth(rng, n_u=12, n_i=9)
        m1 = ht.ALS(rank=2, max_iter=3, seed=0).fit((uu, ii, rr))
        m2 = ht.ALS(rank=2, max_iter=3, seed=0).fit(
            np.stack([uu, ii, rr], axis=1)
        )
        tab = Table.from_dict(
            {"user": uu.astype(np.int64), "item": ii.astype(np.int64),
             "rating": rr}
        )
        m3 = ht.ALS(rank=2, max_iter=3, seed=0).fit(tab)
        np.testing.assert_allclose(m1.user_factors, m2.user_factors, rtol=1e-5)
        np.testing.assert_allclose(m1.user_factors, m3.user_factors, rtol=1e-5)


class TestALSImplicit:
    def test_preferred_items_rank_higher(self, rng):
        U, V, _, _, _, _ = _synth(rng)
        pref = U @ V.T > 1.0
        uu, ii = np.nonzero(pref)
        m = ht.ALS(
            rank=3, max_iter=10, implicit_prefs=True, alpha=10.0, seed=0
        ).fit((uu, ii, np.ones(len(uu), np.float32)))
        s = m.user_factors @ m.item_factors.T
        assert s[pref].mean() > s[~pref].mean() + 0.2

    def test_negative_ratings_rejected(self, rng):
        with pytest.raises(ValueError, match="non-negative"):
            ht.ALS(implicit_prefs=True).fit(
                (np.array([0]), np.array([0]), np.array([-1.0], np.float32))
            )


class TestALSModel:
    def test_recommend_and_cold_start(self, rng):
        _, _, _, uu, ii, rr = _synth(rng, n_u=20, n_i=15)
        m = ht.ALS(rank=3, max_iter=5, seed=0).fit((uu, ii, rr))
        ids, scores = m.recommend_for_all_users(4)
        assert ids.shape == (20, 4)
        assert np.all(np.diff(scores, axis=1) <= 1e-5)   # descending
        # top-1 equals the argmax of the full score matrix
        full = m.user_factors @ m.item_factors.T
        np.testing.assert_array_equal(ids[:, 0], full.argmax(axis=1))
        iids, _ = m.recommend_for_all_items(3)
        assert iids.shape == (15, 3)
        # cold start
        p = m.predict([0, 99], [0, 0])
        assert np.isfinite(p[0]) and np.isnan(p[1])
        md = ht.ALS(rank=3, max_iter=2, cold_start_strategy="drop", seed=0).fit(
            (uu, ii, rr)
        )
        assert len(md.predict([0, 99], [0, 0])) == 1

    def test_round_trip(self, rng, tmp_path):
        _, _, _, uu, ii, rr = _synth(rng, n_u=10, n_i=8)
        m = ht.ALS(rank=2, max_iter=3, seed=0).fit((uu, ii, rr))
        m.write().overwrite().save(str(tmp_path / "als"))
        back = ht.load_model(str(tmp_path / "als"))
        np.testing.assert_allclose(back.user_factors, m.user_factors)
        np.testing.assert_allclose(
            back.predict(uu[:5], ii[:5]), m.predict(uu[:5], ii[:5])
        )

    def test_validation(self, rng):
        with pytest.raises(NotImplementedError, match="nonnegative"):
            ht.ALS(nonnegative=True).fit(
                (np.array([0]), np.array([0]), np.array([1.0], np.float32))
            )
        with pytest.raises(ValueError, match="cold_start"):
            ht.ALS(cold_start_strategy="keep").fit(
                (np.array([0]), np.array([0]), np.array([1.0], np.float32))
            )
        with pytest.raises(ValueError, match="empty"):
            ht.ALS().fit((np.array([], np.int64),) * 2 + (np.array([], np.float32),))
        with pytest.raises(ValueError, match="non-negative integers"):
            ht.ALS().fit(
                (np.array([-1]), np.array([0]), np.array([1.0], np.float32))
            )
        with pytest.raises(ValueError, match="columns"):
            from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

            ht.ALS().fit(Table.from_dict({"x": np.array([1.0])}))
