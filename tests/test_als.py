"""ALS collaborative filtering (pyspark.ml.recommendation parity).

Oracle: an independent per-row NumPy ALS (explicit solves with
np.linalg.solve in a Python loop) — a different code path from the
batched padded einsum/Cholesky device implementation under test."""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht


def _synth(rng, n_u=60, n_i=40, f=3, frac=0.35, noise=0.05):
    U = rng.normal(0, 1, size=(n_u, f))
    V = rng.normal(0, 1, size=(n_i, f))
    mask = rng.uniform(size=(n_u, n_i)) < frac
    uu, ii = np.nonzero(mask)
    rr = ((U @ V.T)[uu, ii] + noise * rng.normal(size=len(uu))).astype(np.float32)
    return U, V, mask, uu, ii, rr


def _numpy_als(uu, ii, rr, n_u, n_i, rank, iters, reg, uf0, vf0):
    """Reference ALS-WR with per-row loops (λ·n_u scaling)."""
    uf, vf = uf0.copy(), vf0.copy()
    for _ in range(iters):
        for u in range(n_u):
            sel = uu == u
            if not sel.any():
                uf[u] = 0
                continue
            y = vf[ii[sel]]
            a = y.T @ y + reg * sel.sum() * np.eye(rank)
            uf[u] = np.linalg.solve(a, y.T @ rr[sel])
        for i in range(n_i):
            sel = ii == i
            if not sel.any():
                vf[i] = 0
                continue
            y = uf[uu[sel]]
            a = y.T @ y + reg * sel.sum() * np.eye(rank)
            vf[i] = np.linalg.solve(a, y.T @ rr[sel])
    return uf, vf


class TestALSExplicit:
    def test_recovers_low_rank_signal(self, rng):
        U, V, mask, uu, ii, rr = _synth(rng)
        m = ht.ALS(rank=3, max_iter=12, reg_param=0.05, seed=0).fit((uu, ii, rr))
        rmse = np.sqrt(np.mean((m.predict(uu, ii) - rr) ** 2))
        assert rmse < 0.15
        # held-out pairs generalize (low-rank structure was learned, not
        # memorized)
        hu, hi = np.nonzero(~mask)
        hr = (U @ V.T)[hu, hi]
        ho = np.sqrt(np.mean((m.predict(hu, hi) - hr) ** 2))
        assert ho < 0.5 * hr.std()

    def test_matches_numpy_reference(self, rng):
        """Same init, same iteration count → same factors (the batched
        padded solves are algebraically the per-row normal equations)."""
        _, _, _, uu, ii, rr = _synth(rng, n_u=25, n_i=18, f=2)
        n_u, n_i, rank = 25, 18, 2
        seed_rng = np.random.default_rng(7)
        scale = 1.0 / np.sqrt(rank)
        uf0 = seed_rng.normal(0, scale, size=(n_u, rank)).astype(np.float32)
        vf0 = seed_rng.normal(0, scale, size=(n_i, rank)).astype(np.float32)

        ref_uf, ref_vf = _numpy_als(
            uu, ii, rr.astype(np.float64), n_u, n_i, rank, 3, 0.1,
            uf0.astype(np.float64), vf0.astype(np.float64),
        )

        # drive the framework's half-step solvers directly from the same
        # init (the estimator draws its own init internally)
        import jax.numpy as jnp

        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.als import (
            _group_ratings, _solve_explicit,
        )

        u_idx, u_val, u_msk, u_cnt = _group_ratings(uu, ii, rr, n_u)
        i_idx, i_val, i_msk, i_cnt = _group_ratings(ii, uu, rr, n_i)
        uf, vf = jnp.asarray(uf0), jnp.asarray(vf0)
        for _ in range(3):
            uf = _solve_explicit(
                vf, jnp.asarray(u_idx), jnp.asarray(u_val), jnp.asarray(u_msk),
                jnp.asarray(u_cnt), jnp.float32(0.1), rank,
            )
            vf = _solve_explicit(
                uf, jnp.asarray(i_idx), jnp.asarray(i_val), jnp.asarray(i_msk),
                jnp.asarray(i_cnt), jnp.float32(0.1), rank,
            )
        np.testing.assert_allclose(np.asarray(uf), ref_uf, rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(np.asarray(vf), ref_vf, rtol=2e-3, atol=2e-3)

    def test_regularization_shrinks_factors(self, rng):
        _, _, _, uu, ii, rr = _synth(rng)
        lo = ht.ALS(rank=3, max_iter=5, reg_param=0.01, seed=0).fit((uu, ii, rr))
        hi = ht.ALS(rank=3, max_iter=5, reg_param=10.0, seed=0).fit((uu, ii, rr))
        assert (
            np.linalg.norm(hi.user_factors) < np.linalg.norm(lo.user_factors)
        )

    def test_input_forms(self, rng):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

        _, _, _, uu, ii, rr = _synth(rng, n_u=12, n_i=9)
        m1 = ht.ALS(rank=2, max_iter=3, seed=0).fit((uu, ii, rr))
        m2 = ht.ALS(rank=2, max_iter=3, seed=0).fit(
            np.stack([uu, ii, rr], axis=1)
        )
        tab = Table.from_dict(
            {"user": uu.astype(np.int64), "item": ii.astype(np.int64),
             "rating": rr}
        )
        m3 = ht.ALS(rank=2, max_iter=3, seed=0).fit(tab)
        np.testing.assert_allclose(m1.user_factors, m2.user_factors, rtol=1e-5)
        np.testing.assert_allclose(m1.user_factors, m3.user_factors, rtol=1e-5)


class TestALSImplicit:
    def test_preferred_items_rank_higher(self, rng):
        U, V, _, _, _, _ = _synth(rng)
        pref = U @ V.T > 1.0
        uu, ii = np.nonzero(pref)
        m = ht.ALS(
            rank=3, max_iter=10, implicit_prefs=True, alpha=10.0, seed=0
        ).fit((uu, ii, np.ones(len(uu), np.float32)))
        s = m.user_factors @ m.item_factors.T
        assert s[pref].mean() > s[~pref].mean() + 0.2

    def test_negative_ratings_rejected(self, rng):
        with pytest.raises(ValueError, match="non-negative"):
            ht.ALS(implicit_prefs=True).fit(
                (np.array([0]), np.array([0]), np.array([-1.0], np.float32))
            )


class TestALSModel:
    def test_recommend_and_cold_start(self, rng):
        _, _, _, uu, ii, rr = _synth(rng, n_u=20, n_i=15)
        m = ht.ALS(rank=3, max_iter=5, seed=0).fit((uu, ii, rr))
        ids, scores = m.recommend_for_all_users(4)
        assert ids.shape == (20, 4)
        assert np.all(np.diff(scores, axis=1) <= 1e-5)   # descending
        # top-1 equals the argmax of the full score matrix
        full = m.user_factors @ m.item_factors.T
        np.testing.assert_array_equal(ids[:, 0], full.argmax(axis=1))
        iids, _ = m.recommend_for_all_items(3)
        assert iids.shape == (15, 3)
        # cold start
        p = m.predict([0, 99], [0, 0])
        assert np.isfinite(p[0]) and np.isnan(p[1])
        md = ht.ALS(rank=3, max_iter=2, cold_start_strategy="drop", seed=0).fit(
            (uu, ii, rr)
        )
        assert len(md.predict([0, 99], [0, 0])) == 1

    def test_recommend_subsets(self, rng):
        _, _, _, uu, ii, rr = _synth(rng, n_u=20, n_i=15)
        m = ht.ALS(rank=3, max_iter=5, seed=0).fit((uu, ii, rr))
        subset = [3, 7, 11]
        ids, scores = m.recommend_for_user_subset(subset, 4)
        assert ids.shape == (3, 4)
        all_ids, all_scores = m.recommend_for_all_users(4)
        np.testing.assert_array_equal(ids, all_ids[subset])
        np.testing.assert_allclose(scores, all_scores[subset], rtol=1e-6)
        iids, _ = m.recommend_for_item_subset([0, 14], 5)
        all_iids, _ = m.recommend_for_all_items(5)
        np.testing.assert_array_equal(iids, all_iids[[0, 14]])
        with pytest.raises(ValueError, match="unknown user id"):
            m.recommend_for_user_subset([0, 20], 3)
        with pytest.raises(ValueError, match="unknown item id"):
            m.recommend_for_item_subset([-1], 3)

    def test_round_trip(self, rng, tmp_path):
        _, _, _, uu, ii, rr = _synth(rng, n_u=10, n_i=8)
        m = ht.ALS(rank=2, max_iter=3, seed=0).fit((uu, ii, rr))
        m.write().overwrite().save(str(tmp_path / "als"))
        back = ht.load_model(str(tmp_path / "als"))
        np.testing.assert_allclose(back.user_factors, m.user_factors)
        np.testing.assert_allclose(
            back.predict(uu[:5], ii[:5]), m.predict(uu[:5], ii[:5])
        )

    def test_validation(self, rng):
        with pytest.raises(ValueError, match="cold_start"):
            ht.ALS(cold_start_strategy="keep").fit(
                (np.array([0]), np.array([0]), np.array([1.0], np.float32))
            )
        with pytest.raises(ValueError, match="empty"):
            ht.ALS().fit((np.array([], np.int64),) * 2 + (np.array([], np.float32),))
        with pytest.raises(ValueError, match="non-negative integers"):
            ht.ALS().fit(
                (np.array([-1]), np.array([0]), np.array([1.0], np.float32))
            )
        with pytest.raises(ValueError, match="columns"):
            from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

            ht.ALS().fit(Table.from_dict({"x": np.array([1.0])}))


class TestALSBucketedDistributed:
    """Round-5 upgrades (VERDICT r4 #3): count-capped padding + mesh."""

    def test_bucketed_grouping_reconstructs_triplets(self, rng):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.als import (
            _group_ratings_bucketed,
        )

        _, _, _, uu, ii, rr = _synth(rng, n_u=40, n_i=25)
        seen = {}
        covered = np.zeros(40, bool)
        for rows, idx, val, msk, cnt in _group_ratings_bucketed(uu, ii, rr, 40):
            assert not covered[rows].any()       # each row in ONE bucket
            covered[rows] = True
            for j, u in enumerate(rows):
                on = msk[j] > 0
                assert on.sum() == cnt[j] == (uu == u).sum()
                seen[int(u)] = set(zip(idx[j, on].tolist(), val[j, on].tolist()))
        assert covered[np.unique(uu)].all()
        for u in np.unique(uu):
            sel = uu == u
            assert seen[int(u)] == set(
                zip(ii[sel].tolist(), rr[sel].astype(np.float32).tolist())
            )

    def test_skewed_counts_have_bounded_padding(self):
        """One power-law row must not inflate every row's padded width:
        total padded cells stay <= 4x nnz (the documented bucket bound),
        where the single global (n, C) layout would be ~1000x nnz."""
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.als import (
            _group_ratings_bucketed,
        )

        gen = np.random.default_rng(0)
        # 1000 users with 1-4 ratings; user 0 with 5000
        light_u = np.repeat(np.arange(1, 1001), gen.integers(1, 5, size=1000))
        heavy_u = np.zeros(5000, np.int64)
        uu = np.concatenate([heavy_u, light_u])
        ii = gen.integers(0, 6000, size=len(uu))
        rr = gen.uniform(1, 5, size=len(uu)).astype(np.float32)
        nnz = len(uu)
        buckets = _group_ratings_bucketed(uu, ii, rr, 1001)
        cells = sum(idx.size for _, idx, _, _, _ in buckets)
        assert cells <= 4 * nnz
        # the old layout for comparison: 1001 rows x 5000 cap
        assert cells < 0.01 * (1001 * 5000)

    def test_mesh_fit_equals_single_device(self, rng, mesh8):
        _, _, _, uu, ii, rr = _synth(rng, n_u=50, n_i=30)
        solo = ht.ALS(rank=3, max_iter=4, seed=1).fit((uu, ii, rr))
        dist = ht.ALS(rank=3, max_iter=4, seed=1).fit((uu, ii, rr), mesh=mesh8)
        np.testing.assert_allclose(
            dist.user_factors, solo.user_factors, rtol=2e-4, atol=2e-5
        )
        np.testing.assert_allclose(
            dist.item_factors, solo.item_factors, rtol=2e-4, atol=2e-5
        )

    def test_mesh_fit_implicit_equals_single_device(self, rng, mesh8):
        _, _, _, uu, ii, rr = _synth(rng, n_u=40, n_i=25)
        rr = np.abs(rr).astype(np.float32)
        solo = ht.ALS(rank=3, max_iter=4, seed=2, implicit_prefs=True).fit(
            (uu, ii, rr)
        )
        dist = ht.ALS(rank=3, max_iter=4, seed=2, implicit_prefs=True).fit(
            (uu, ii, rr), mesh=mesh8
        )
        np.testing.assert_allclose(
            dist.user_factors, solo.user_factors, rtol=2e-4, atol=2e-5
        )
        np.testing.assert_allclose(
            dist.item_factors, solo.item_factors, rtol=2e-4, atol=2e-5
        )

    def test_skewed_fit_end_to_end(self, rng, mesh8):
        """The skewed shape actually FITS (and on the mesh) - the bound
        is not just a bookkeeping claim."""
        gen = np.random.default_rng(3)
        f = 2
        U = gen.normal(size=(201, f))
        V = gen.normal(size=(120, f))
        heavy_i = gen.integers(0, 120, size=110)
        light_u = np.repeat(np.arange(1, 201), 3)
        uu = np.concatenate([np.zeros(110, np.int64), light_u])
        ii = np.concatenate([heavy_i, gen.integers(0, 120, size=600)])
        rr = ((U @ V.T)[uu, ii] + 0.05 * gen.normal(size=len(uu))).astype(
            np.float32
        )
        m = ht.ALS(rank=f, max_iter=8, reg_param=0.05, seed=0).fit(
            (uu, ii, rr), mesh=mesh8
        )
        rmse = np.sqrt(np.mean((m.predict(uu, ii) - rr) ** 2))
        assert rmse < 0.5


class TestALSNonnegative:
    """nonnegative=True — Spark's NNLS solver, as batched projected CD."""

    def test_half_step_matches_scipy_nnls(self, rng):
        from scipy import optimize

        import jax.numpy as jnp

        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.als import (
            _group_ratings, _solve_explicit,
        )

        _, _, _, uu, ii, rr = _synth(rng, n_u=20, n_i=12, f=3)
        rr = np.abs(rr).astype(np.float32)
        n_u, rank = 20, 3
        y = np.abs(rng.normal(size=(12, rank))).astype(np.float32)
        u_idx, u_val, u_msk, u_cnt = _group_ratings(uu, ii, rr, n_u)
        out = np.asarray(_solve_explicit(
            jnp.asarray(y), jnp.asarray(u_idx), jnp.asarray(u_val),
            jnp.asarray(u_msk), jnp.asarray(u_cnt), jnp.float32(0.1), rank,
            True,
        ))
        assert (out >= 0).all()
        # per-row oracle: min ||Ax-b|| s.t. x>=0 via scipy on the SAME
        # normal equations (Cholesky square root of A)
        for u in range(n_u):
            sel = uu == u
            if not sel.any():
                continue
            yy = y[ii[sel]].astype(np.float64)
            a = yy.T @ yy + 0.1 * sel.sum() * np.eye(rank)
            b = yy.T @ rr[sel].astype(np.float64)
            L = np.linalg.cholesky(a)
            ref, _ = optimize.nnls(L.T, np.linalg.solve(L, b))
            np.testing.assert_allclose(out[u], ref, atol=5e-3)

    def test_end_to_end_nonnegative_fit(self, rng, mesh8):
        U = np.abs(rng.normal(size=(40, 3)))
        V = np.abs(rng.normal(size=(25, 3)))
        mask = rng.uniform(size=(40, 25)) < 0.5
        uu, ii = np.nonzero(mask)
        rr = ((U @ V.T)[uu, ii] + 0.02 * rng.normal(size=len(uu))).astype(
            np.float32
        )
        m = ht.ALS(rank=3, max_iter=12, reg_param=0.02, nonnegative=True,
                   seed=0).fit((uu, ii, rr))
        assert (m.user_factors >= 0).all() and (m.item_factors >= 0).all()
        rmse = np.sqrt(np.mean((m.predict(uu, ii) - rr) ** 2))
        assert rmse < 0.25 * rr.std()
        # mesh == solo for the NNLS path too
        md = ht.ALS(rank=3, max_iter=12, reg_param=0.02, nonnegative=True,
                    seed=0).fit((uu, ii, rr), mesh=mesh8)
        np.testing.assert_allclose(
            md.user_factors, m.user_factors, rtol=2e-3, atol=2e-4
        )

    def test_implicit_nonnegative(self, rng):
        _, _, _, uu, ii, rr = _synth(rng, n_u=25, n_i=15)
        rr = np.abs(rr).astype(np.float32)
        m = ht.ALS(rank=2, max_iter=6, implicit_prefs=True, nonnegative=True,
                   seed=0).fit((uu, ii, rr))
        assert (m.user_factors >= 0).all() and (m.item_factors >= 0).all()
