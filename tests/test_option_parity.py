"""Spark-MLlib estimator *option* parity (VERDICT round-1 gaps):

- LinearRegression ``elasticNetParam`` (L1/elastic-net via FISTA on the
  sharded Gram) vs sklearn's coordinate-descent ElasticNet/Lasso,
- LogisticRegression ``family="multinomial"`` (softmax Newton) vs sklearn,
- DataFrame-style ``transform`` on clustering models (prediction /
  probability columns on the Table pipeline, reference pattern
  ``mllearnforhospitalnetwork.py:148,157``).
"""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht


def _reg_data(rng, n=2000, d=6):
    x = rng.normal(size=(n, d))
    beta = np.array([3.0, -2.0, 0.0, 0.0, 1.5, 0.0])
    y = x @ beta + 0.3 * rng.normal(size=n) + 1.0
    return x.astype(np.float32), y.astype(np.float32), beta


# --- elastic net -------------------------------------------------------


@pytest.mark.fast
def test_lasso_matches_sklearn(rng, mesh8):
    sk = pytest.importorskip("sklearn.linear_model")
    x, y, _ = _reg_data(rng)
    lam = 0.1
    ours = ht.LinearRegression(
        reg_param=lam, elastic_net_param=1.0, standardize=False, tol=1e-8,
        max_iter=2000,
    ).fit((x, y), mesh=mesh8)
    ref = sk.Lasso(alpha=lam, tol=1e-10, max_iter=50000).fit(x, y)
    np.testing.assert_allclose(
        np.asarray(ours.coefficients), ref.coef_, atol=2e-3
    )
    np.testing.assert_allclose(
        float(ours.intercept), ref.intercept_, atol=2e-3
    )
    # the true-zero coefficients are driven to exactly zero
    assert np.all(np.asarray(ours.coefficients)[[2, 3, 5]] == 0.0)


def test_elastic_net_matches_sklearn(rng, mesh8):
    sk = pytest.importorskip("sklearn.linear_model")
    x, y, _ = _reg_data(rng)
    lam, alpha = 0.2, 0.5
    ours = ht.LinearRegression(
        reg_param=lam, elastic_net_param=alpha, standardize=False, tol=1e-8,
        max_iter=2000,
    ).fit((x, y), mesh=mesh8)
    ref = sk.ElasticNet(alpha=lam, l1_ratio=alpha, tol=1e-10, max_iter=50000).fit(x, y)
    np.testing.assert_allclose(np.asarray(ours.coefficients), ref.coef_, atol=2e-3)
    np.testing.assert_allclose(float(ours.intercept), ref.intercept_, atol=2e-3)


def test_elastic_net_zero_alpha_is_ridge(rng, mesh8):
    """elasticNetParam=0 keeps the closed-form ridge path byte-compatible."""
    x, y, _ = _reg_data(rng)
    a = ht.LinearRegression(reg_param=0.3).fit((x, y), mesh=mesh8)
    b = ht.LinearRegression(reg_param=0.3, elastic_net_param=0.0).fit((x, y), mesh=mesh8)
    np.testing.assert_array_equal(
        np.asarray(a.coefficients), np.asarray(b.coefficients)
    )


def test_elastic_net_standardized_penalty(rng, mesh8):
    """standardize=True penalizes scaled coefficients (Spark semantics):
    a feature on a 100x scale keeps a 100x-smaller coefficient, which pure
    raw-scale L1 would kill entirely."""
    n = 4000
    x = rng.normal(size=(n, 2)).astype(np.float32)
    x[:, 1] /= 100.0                      # same signal, tiny scale
    y = (x[:, 0] + 100.0 * x[:, 1] + 0.1 * rng.normal(size=n)).astype(np.float32)
    m = ht.LinearRegression(
        reg_param=0.05, elastic_net_param=1.0, standardize=True, max_iter=3000
    ).fit((x, y), mesh=mesh8)
    c = np.asarray(m.coefficients)
    assert c[1] > 10.0 * c[0] > 0.0       # both survive, scale-adjusted


# --- multinomial logistic regression -----------------------------------


def _cls_data(rng, n=3000, d=4, k=3):
    centers = rng.normal(scale=2.0, size=(k, d))
    y = rng.integers(0, k, n)
    x = centers[y] + rng.normal(size=(n, d))
    return x.astype(np.float32), y.astype(np.float32)


def test_multinomial_matches_sklearn(rng, mesh8):
    sk = pytest.importorskip("sklearn.linear_model")
    x, y, = _cls_data(rng)
    ours = ht.LogisticRegression(family="multinomial", tol=1e-8).fit((x, y), mesh=mesh8)
    assert isinstance(ours, ht.MultinomialLogisticRegressionModel)
    ref = sk.LogisticRegression(penalty=None, tol=1e-10, max_iter=2000).fit(x, y.astype(int))
    p_ours = np.asarray(ours.predict_proba(x))
    p_ref = ref.predict_proba(x)
    np.testing.assert_allclose(p_ours, p_ref, atol=2e-3)
    assert (np.asarray(ours.predict(x)) == ref.predict(x)).mean() > 0.999


def test_family_auto_dispatch(rng, mesh8):
    x, y = _cls_data(rng, k=3)
    m3 = ht.LogisticRegression(family="auto").fit((x, y), mesh=mesh8)
    assert isinstance(m3, ht.MultinomialLogisticRegressionModel)
    assert m3.num_classes == 3
    xb, yb = _cls_data(rng, k=2)
    m2 = ht.LogisticRegression(family="auto").fit((xb, yb), mesh=mesh8)
    assert not isinstance(m2, ht.MultinomialLogisticRegressionModel)
    with pytest.raises(ValueError, match="family"):
        ht.LogisticRegression(family="ovr").fit((x, y), mesh=mesh8)
    # Spark parity: binomial on >2 classes raises instead of fitting garbage
    with pytest.raises(ValueError, match="binomial"):
        ht.LogisticRegression(family="binomial").fit((x, y), mesh=mesh8)


def test_multinomial_regularized_and_weighted(rng, mesh8):
    """L2'd multinomial still separates; sharded fit == single-device fit."""
    x, y = _cls_data(rng)
    a = ht.LogisticRegression(family="multinomial", reg_param=0.01).fit(
        (x, y), mesh=mesh8
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel import (
        single_device_mesh,
    )

    b = ht.LogisticRegression(family="multinomial", reg_param=0.01).fit(
        (x, y), mesh=single_device_mesh()
    )
    np.testing.assert_allclose(
        np.asarray(a.coefficient_matrix), np.asarray(b.coefficient_matrix), atol=1e-4
    )
    assert (np.asarray(a.predict(x)) == y).mean() > 0.9


def test_multinomial_save_load(tmp_path, rng, mesh8):
    x, y = _cls_data(rng)
    m = ht.LogisticRegression(family="multinomial").fit((x, y), mesh=mesh8)
    m.write().overwrite().save(str(tmp_path / "mlr"))
    m2 = ht.load_model(str(tmp_path / "mlr"))
    np.testing.assert_allclose(
        np.asarray(m.predict_proba(x[:64])), np.asarray(m2.predict_proba(x[:64])),
        atol=1e-6,
    )


# --- clustering Table transform ----------------------------------------


def _clustered_table(rng, n=600):
    centers = np.array([[0.0, 0.0], [8.0, 8.0], [0.0, 8.0]])
    a = rng.integers(0, 3, n)
    x = centers[a] + rng.normal(scale=0.5, size=(n, 2))
    tab = ht.Table.from_dict(
        {"f0": x[:, 0], "f1": x[:, 1]},
        ht.Schema([("f0", "float"), ("f1", "float")]),
    )
    return tab, x.astype(np.float32)


def test_kmeans_table_transform(rng, mesh8):
    tab, x = _clustered_table(rng)
    asm = ht.VectorAssembler(["f0", "f1"]).transform(tab)
    km = ht.KMeans(k=3, seed=0).fit(asm.features, mesh=mesh8)
    out = km.transform(asm)
    assert isinstance(out, ht.Table)
    assert "prediction" in out.schema
    assert out.num_rows == tab.num_rows
    np.testing.assert_array_equal(
        out["prediction"], np.asarray(km.predict_numpy(x)).astype(np.int32)
    )
    # non-table input keeps the sharded PredictionResult contract
    res = km.transform((x, np.zeros(len(x), np.float32)), mesh=mesh8)
    assert hasattr(res, "prediction") and hasattr(res, "weight")


def test_gmm_table_transform_probability(rng, mesh8):
    tab, x = _clustered_table(rng)
    asm = ht.VectorAssembler(["f0", "f1"]).transform(tab)
    gm = ht.GaussianMixture(k=3, seed=0, max_iter=50).fit(asm.features, mesh=mesh8)
    out = gm.transform(asm)
    assert "prediction" in out.schema and "probability" in out.schema
    p = out["probability"]
    assert np.all((p >= 0.0) & (p <= 1.0 + 1e-6))
    # well-separated blobs: assigned-component posterior is near 1
    assert np.median(p) > 0.99


def test_bisecting_streaming_table_transform(rng, mesh8):
    tab, x = _clustered_table(rng)
    asm = ht.VectorAssembler(["f0", "f1"]).transform(tab)
    bk = ht.BisectingKMeans(k=3, seed=0).fit(asm.features, mesh=mesh8)
    out = bk.transform(asm)
    assert "prediction" in out.schema and out.num_rows == tab.num_rows
    sk = ht.StreamingKMeans(k=3, seed=0, half_life=5.0)
    sk.update(x, mesh=mesh8)
    out2 = sk.latest_model.transform(asm)
    assert "prediction" in out2.schema
