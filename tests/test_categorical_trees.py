"""Categorical (unordered-set) tree splits — MLlib categoricalFeaturesInfo.

The reference imports StringIndexer (``mllearnforhospitalnetwork.py:29``,
SURVEY.md D5 reads it as intended categorical handling); MLlib trees split
indexed categoricals as unordered sets.  Engine contract under test
(``_make_level_step``): per node, a categorical feature's bins are sorted
by label mean and the best prefix of that order is the best category
SUBSET — exact for regression and binary classification (Breiman), so a
depth-1 split must match exhaustive subset enumeration.
"""

import itertools

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.sharding import (
    device_dataset,
)


def _cat_regression_data(rng, n=2000, arity=8):
    """Non-monotonic category→mean mapping: a threshold split on the raw
    category id cannot isolate the high group, an unordered set can."""
    means = np.array([0.0, 10.0, 0.5, 9.5, -0.5, 10.5, 0.0, 9.0])[:arity]
    c = rng.integers(0, arity, size=n)
    y = means[c] + rng.normal(0, 0.1, size=n)
    return c.astype(np.float32)[:, None], y.astype(np.float32), means


def _best_subset_sse(c, y, arity):
    """Exhaustive best binary partition of categories (2^(a-1) subsets)."""
    best = np.inf
    total_sse_fn = lambda v: ((v - v.mean()) ** 2).sum() if v.size else 0.0
    for r in range(1, arity):
        for left in itertools.combinations(range(arity), r):
            m = np.isin(c, left)
            sse = total_sse_fn(y[m]) + total_sse_fn(y[~m])
            best = min(best, sse)
    return best


@pytest.mark.fast
class TestCategoricalRegression:
    def test_depth1_matches_exhaustive_subset_search(self, mesh8, rng):
        arity = 6
        c = rng.integers(0, arity, size=512)
        y = rng.normal(size=512) + np.array([0, 3, -2, 5, 1, -4])[c]
        x = c.astype(np.float32)[:, None]
        ds = device_dataset(x, y.astype(np.float32), mesh=mesh8)
        m = ht.DecisionTreeRegressor(
            max_depth=1, categorical_features={0: arity}
        ).fit(ds, mesh=mesh8)
        pred = np.asarray(m.predict_numpy(x))
        engine_sse = ((pred - y) ** 2).sum()
        exhaustive_sse = _best_subset_sse(c, y, arity)
        # Breiman: sort-by-mean prefix scan is exact for regression
        np.testing.assert_allclose(engine_sse, exhaustive_sse, rtol=1e-3)

    def test_beats_continuous_treatment(self, mesh8, rng):
        x, y, _ = _cat_regression_data(rng)
        ds = device_dataset(x, y, mesh=mesh8)
        cat = ht.DecisionTreeRegressor(
            max_depth=1, categorical_features={0: 8}
        ).fit(ds, mesh=mesh8)
        cont = ht.DecisionTreeRegressor(max_depth=1).fit(ds, mesh=mesh8)
        rmse = lambda m: float(
            np.sqrt(np.mean((np.asarray(m.predict_numpy(x)) - y) ** 2))
        )
        # interleaved high/low means: the set split isolates the high group
        # at depth 1, a single threshold cannot
        assert rmse(cat) < 0.5 * rmse(cont)

    def test_mixed_continuous_and_categorical(self, mesh8, rng):
        n = 1500
        c = rng.integers(0, 5, size=n)
        z = rng.normal(size=n)
        y = (np.array([0, 8, 1, 9, 0.5])[c] + 2.0 * z).astype(np.float32)
        x = np.stack([c.astype(np.float32), z.astype(np.float32)], axis=1)
        m = ht.DecisionTreeRegressor(
            max_depth=4, categorical_features={0: 5}
        ).fit(device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        pred = np.asarray(m.predict_numpy(x))
        assert np.sqrt(np.mean((pred - y) ** 2)) < 1.0
        # both features matter
        assert np.all(m.feature_importances > 0.05)

    def test_unseen_category_goes_right(self, mesh8, rng):
        x, y, _ = _cat_regression_data(rng)
        m = ht.DecisionTreeRegressor(
            max_depth=2, categorical_features={0: 8}
        ).fit(device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        # categories never seen at fit time (and out-of-range ids) predict
        # via the right-descent path — finite, no crash (Spark's rule)
        probe = np.array([[8.0], [31.0], [100.0], [-3.0]], np.float32)
        out = np.asarray(m.predict_numpy(probe))
        assert np.all(np.isfinite(out))


class TestCategoricalClassification:
    def test_depth1_binary_exact(self, mesh8, rng):
        arity = 6
        c = rng.integers(0, arity, size=800)
        # class 1 on an id-interleaved category subset
        y = np.isin(c, [0, 3, 5]).astype(np.float32)
        x = c.astype(np.float32)[:, None]
        m = ht.DecisionTreeClassifier(
            max_depth=1, categorical_features={0: arity}
        ).fit(device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        pred = np.asarray(m.predict_numpy(x))
        assert (pred == y).mean() == 1.0  # separable by one set split

    def test_random_forest_categorical(self, mesh8, rng):
        n = 1200
        c = rng.integers(0, 7, size=n)
        z = rng.normal(size=n)
        y = (np.isin(c, [1, 4, 6]) ^ (z > 1.2)).astype(np.float32)
        x = np.stack([c.astype(np.float32), z.astype(np.float32)], axis=1)
        m = ht.RandomForestClassifier(
            num_trees=10, max_depth=4, categorical_features={0: 7}, seed=0
        ).fit(device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        pred = np.asarray(m.predict_numpy(x))
        assert (pred == y).mean() > 0.93


class TestCategoricalGBT:
    def test_gbt_categorical_regression(self, mesh8, rng):
        x, y, _ = _cat_regression_data(rng, n=1500)
        ds = device_dataset(x, y, mesh=mesh8)
        # step_size sized so shrinkage converges within the round budget
        # ((1-0.7^30)≈1; Spark's default 0.1 would need ~70 rounds)
        cat = ht.GBTRegressor(
            max_iter=30, max_depth=2, step_size=0.3,
            categorical_features={0: 8}, seed=0,
        ).fit(ds, mesh=mesh8)
        pred = np.asarray(cat.predict_numpy(x))
        assert np.sqrt(np.mean((pred - y) ** 2)) < 0.5


class TestCategoricalPersistence:
    def test_round_trip(self, mesh8, rng, tmp_path):
        x, y, _ = _cat_regression_data(rng, n=600)
        m = ht.RandomForestRegressor(
            num_trees=5, max_depth=3, categorical_features={0: 8}, seed=0
        ).fit(device_dataset(x, y, mesh=mesh8), mesh=mesh8)
        m.write().overwrite().save(str(tmp_path / "rf_cat"))
        m2 = ht.load_model(str(tmp_path / "rf_cat"))
        probe = rng.integers(0, 8, size=64).astype(np.float32)[:, None]
        np.testing.assert_array_equal(
            np.asarray(m.predict_numpy(probe)), np.asarray(m2.predict_numpy(probe))
        )
        assert m2.split_catmask is not None

    def test_continuous_models_unchanged(self, mesh8, rng, tmp_path):
        """No categorical spec → artifacts stay in the old shape."""
        x = rng.normal(size=(256, 3)).astype(np.float32)
        y = x[:, 0].astype(np.float32)
        m = ht.DecisionTreeRegressor(max_depth=2).fit(
            device_dataset(x, y, mesh=mesh8), mesh=mesh8
        )
        assert m.split_catmask is None
        m.write().overwrite().save(str(tmp_path / "dt"))
        assert ht.load_model(str(tmp_path / "dt")).split_catmask is None


class TestCategoricalValidation:
    def test_out_of_arity_values_raise_at_fit(self, mesh8, rng):
        """A valid row with category id ≥ arity is a spec error (wrong
        arity / not StringIndexer output) — raise like Spark, never train
        on a category the predict path would route differently."""
        x = rng.integers(0, 8, size=(128, 1)).astype(np.float32)
        y = rng.normal(size=128).astype(np.float32)
        with pytest.raises(ValueError, match="outside \\[0, 4\\)"):
            ht.DecisionTreeRegressor(categorical_features={0: 4}).fit(
                device_dataset(x, y, mesh=mesh8), mesh=mesh8
            )
        with pytest.raises(ValueError, match="outside"):
            ht.GBTRegressor(max_iter=2, categorical_features={0: 4}).fit(
                device_dataset(x, y, mesh=mesh8), mesh=mesh8
            )

    def test_arity_bounds(self, mesh8, rng):
        x = rng.integers(0, 3, size=(64, 1)).astype(np.float32)
        y = rng.normal(size=64).astype(np.float32)
        ds = device_dataset(x, y, mesh=mesh8)
        with pytest.raises(ValueError, match="arity"):
            ht.DecisionTreeRegressor(categorical_features={0: 40}).fit(ds, mesh=mesh8)
        with pytest.raises(ValueError, match="arity"):
            ht.DecisionTreeRegressor(categorical_features={0: 1}).fit(ds, mesh=mesh8)
        with pytest.raises(ValueError, match="out of range"):
            ht.DecisionTreeRegressor(categorical_features={5: 3}).fit(ds, mesh=mesh8)
        with pytest.raises(ValueError, match="arity"):
            ht.DecisionTreeRegressor(
                max_bins=8, categorical_features={0: 16}
            ).fit(ds, mesh=mesh8)
