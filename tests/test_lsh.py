"""LSH families (BucketedRandomProjectionLSH / MinHashLSH) vs brute force.

Verification model: candidate generation is approximate by design, so the
contract tested is (a) every RETURNED pair/neighbor is exactly right
(exact re-ranking: true distance, correct ordering, threshold respected),
(b) with enough hash tables the families find what they should (recall on
planted structure), (c) hash identity: same-bucket probability behaves
like the family's collision probability (clustered data collides, far
data doesn't), (d) persistence round-trips.
"""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _clustered(rng, n_per=40, centers=((0.0, 0.0, 0.0), (8.0, 8.0, 8.0))):
    xs = [rng.normal(c, 0.4, size=(n_per, len(c))) for c in centers]
    return np.concatenate(xs).astype(np.float64)


class TestBucketedRandomProjectionLSH:
    def test_transform_shape_and_determinism(self, rng):
        x = _clustered(rng)
        m = ht.BucketedRandomProjectionLSH(
            bucket_length=2.0, num_hash_tables=3, seed=5
        ).fit(x)
        h = m.transform(x)
        assert h.shape == (len(x), 3) and h.dtype == np.int64
        np.testing.assert_array_equal(h, m.transform(x))
        # same seed → same family
        h2 = ht.BucketedRandomProjectionLSH(
            bucket_length=2.0, num_hash_tables=3, seed=5
        ).fit(x).transform(x)
        np.testing.assert_array_equal(h, h2)

    def test_near_points_collide_far_points_dont(self, rng):
        x = _clustered(rng)
        m = ht.BucketedRandomProjectionLSH(
            bucket_length=4.0, num_hash_tables=6, seed=0
        ).fit(x)
        h = m.transform(x)
        # collision probability is monotone in distance (the family's
        # defining property): averaged over pairs, same-cluster rows
        # share far more buckets than cross-cluster rows (≈ 13.8 apart
        # vs bucket 4).  Averaged, because any single pair can straddle
        # a bucket boundary in any table.
        same = np.mean([(h[i] == h[j]).mean() for i in range(10) for j in range(10, 20)])
        cross = np.mean([(h[i] == h[-1 - j]).mean() for i in range(10) for j in range(10)])
        assert same > cross + 0.2

    def test_approx_nearest_neighbors_match_brute_force(self, rng):
        x = _clustered(rng, n_per=60)
        key = np.array([0.2, -0.1, 0.1])
        m = ht.BucketedRandomProjectionLSH(
            bucket_length=3.0, num_hash_tables=8, seed=2
        ).fit(x)
        idx, dist = m.approx_nearest_neighbors(x, key, 5)
        true = np.sqrt(((x - key) ** 2).sum(axis=1))
        # returned distances are EXACT and ascending
        np.testing.assert_allclose(dist, true[idx], rtol=1e-12)
        assert (np.diff(dist) >= 0).all()
        # with 8 tables on this scale, the top-5 is the true top-5
        np.testing.assert_array_equal(np.sort(idx), np.sort(np.argsort(true)[:5]))

    def test_approx_similarity_join_vs_brute_force(self, rng):
        a = _clustered(rng, n_per=30)
        b = a + rng.normal(0, 0.05, size=a.shape)   # jittered copy
        m = ht.BucketedRandomProjectionLSH(
            bucket_length=3.0, num_hash_tables=8, seed=3
        ).fit(a)
        ia, ib, d = m.approx_similarity_join(a, b, threshold=0.5)
        # every returned pair is exactly verified
        true = np.sqrt(((a[ia] - b[ib]) ** 2).sum(axis=1))
        np.testing.assert_allclose(d, true, rtol=1e-12)
        assert (d <= 0.5).all()
        # the diagonal (each row vs its jittered copy) must be found
        diag = set(zip(ia.tolist(), ib.tolist()))
        found = sum((i, i) in diag for i in range(len(a)))
        assert found >= 0.95 * len(a)
        # no pair across the two distant clusters sneaks in
        assert not ((ia < 30) & (ib >= 30)).any()

    def test_validation(self, rng):
        x = _clustered(rng)
        with pytest.raises(ValueError, match="bucket_length"):
            ht.BucketedRandomProjectionLSH().fit(x)
        with pytest.raises(ValueError, match="num_hash_tables"):
            ht.BucketedRandomProjectionLSH(
                bucket_length=1.0, num_hash_tables=0
            ).fit(x)
        m = ht.BucketedRandomProjectionLSH(bucket_length=1.0).fit(x)
        with pytest.raises(ValueError, match="features"):
            m.approx_nearest_neighbors(x, np.zeros(7), 3)
        with pytest.raises(ValueError, match="k"):
            m.approx_nearest_neighbors(x, np.zeros(3), 0)
        with pytest.raises(ValueError, match="threshold"):
            m.approx_similarity_join(x, x, -1.0)

    def test_persistence_round_trip(self, rng, tmp_path):
        x = _clustered(rng)
        m = ht.BucketedRandomProjectionLSH(
            bucket_length=2.0, num_hash_tables=4, seed=9
        ).fit(x)
        p = str(tmp_path / "brp")
        m.save(p)
        m2 = ht.load_model(p)
        np.testing.assert_array_equal(m.transform(x), m2.transform(x))


def _binary(rng, n=60, d=40, density=0.25):
    return (rng.uniform(size=(n, d)) < density).astype(np.float64)


class TestMinHashLSH:
    def test_hash_values_match_spark_family(self, rng):
        # h = min over non-zero j of ((1+j)·a + b) mod 2038074743 —
        # recompute by hand against the model's coefficients
        x = _binary(rng, n=10)
        m = ht.MinHashLSH(num_hash_tables=3, seed=1).fit(x)
        h = m.transform(x)
        prime = 2038074743
        for i in range(len(x)):
            nz = np.flatnonzero(x[i])
            for t in range(3):
                vals = ((1 + nz) * int(m.coef_a[t]) + int(m.coef_b[t])) % prime
                assert h[i, t] == vals.min()

    def test_identical_sets_always_collide(self, rng):
        x = _binary(rng)
        x[1] = x[0]
        m = ht.MinHashLSH(num_hash_tables=5, seed=0).fit(x)
        h = m.transform(x)
        np.testing.assert_array_equal(h[0], h[1])

    def test_approx_nearest_neighbors_jaccard(self, rng):
        x = _binary(rng, n=80, d=50)
        key = x[7].copy()
        m = ht.MinHashLSH(num_hash_tables=10, seed=4).fit(x)
        idx, dist = m.approx_nearest_neighbors(x, key, 3)
        assert idx[0] == 7 and dist[0] == 0.0
        # distances are the exact Jaccard distances
        a = x[idx] > 0
        b = key[None, :] > 0
        true = 1.0 - (a & b).sum(axis=1) / (a | b).sum(axis=1)
        np.testing.assert_allclose(dist, true, rtol=1e-12)

    def test_approx_similarity_join_threshold(self, rng):
        a = _binary(rng, n=50, d=60)
        # b: copies of a with a few bits flipped → low Jaccard distance
        b = a.copy()
        flips = rng.integers(0, 60, size=50)
        b[np.arange(50), flips] = 1 - b[np.arange(50), flips]
        m = ht.MinHashLSH(num_hash_tables=12, seed=6).fit(a)
        ia, ib, d = m.approx_similarity_join(a, b, threshold=0.3)
        assert (d <= 0.3).all()
        diag = set(zip(ia.tolist(), ib.tolist()))
        found = sum((i, i) in diag for i in range(50))
        assert found >= 45     # near-duplicates must be found
        ja = a[ia] > 0
        jb = b[ib] > 0
        true = 1.0 - (ja & jb).sum(axis=1) / (ja | jb).sum(axis=1)
        np.testing.assert_allclose(d, true, rtol=1e-12)

    def test_validation(self, rng):
        x = _binary(rng)
        with pytest.raises(ValueError, match="num_hash_tables"):
            ht.MinHashLSH(num_hash_tables=0).fit(x)
        m = ht.MinHashLSH(num_hash_tables=2, seed=0).fit(x)
        with pytest.raises(ValueError, match="non-negative"):
            m.transform(-x)
        empty = x.copy()
        empty[3] = 0.0
        with pytest.raises(ValueError, match="non-zero"):
            m.transform(empty)

    def test_persistence_round_trip(self, rng, tmp_path):
        x = _binary(rng)
        m = ht.MinHashLSH(num_hash_tables=4, seed=2).fit(x)
        p = str(tmp_path / "minhash")
        m.save(p)
        m2 = ht.load_model(p)
        np.testing.assert_array_equal(m.transform(x), m2.transform(x))


def test_assembled_table_inputs():
    """LSH transform on an AssembledTable APPENDS hash columns and keeps
    the feature matrix intact (Spark adds outputCol, leaves inputCol) —
    an LSH stage mid-Pipeline must not replace features with bucket
    ids."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

    gen = np.random.default_rng(0)
    t = Table.from_dict(
        {"a": gen.normal(size=30), "b": gen.normal(size=30), "c": gen.normal(size=30)}
    )
    at = ht.VectorAssembler(["a", "b", "c"]).transform(t)
    m = ht.BucketedRandomProjectionLSH(bucket_length=1.0, num_hash_tables=2).fit(at)
    out = m.transform(at)
    np.testing.assert_array_equal(
        np.asarray(out.features), np.asarray(at.features)
    )
    np.testing.assert_array_equal(
        np.column_stack([out.table.column("hashes_0"), out.table.column("hashes_1")]),
        m.hash_matrix(at),
    )
    assert m.hash_matrix(at).shape == (30, 2)
    idx, dist = m.approx_nearest_neighbors(at, np.zeros(3), 4)
    assert len(idx) <= 4


def test_brp_large_magnitude_buckets_stay_exact():
    """Review regression: f32 hashing quantized bucket ids for features
    of magnitude ~1e8 (ULP ≈ 8 > bucket_length) — hashing must stay in
    double like Spark's."""
    gen = np.random.default_rng(1)
    base = 1.0e8
    x = base + gen.uniform(0, 100, size=(50, 4))
    m = ht.BucketedRandomProjectionLSH(bucket_length=1.0, num_hash_tables=4).fit(x)
    h = m.hash_matrix(x)
    expect = np.floor(x @ m.projections.T / 1.0).astype(np.int64)
    np.testing.assert_array_equal(h, expect)
    # distinct buckets survive: rows spread ~100/|v| apart in projection
    assert len(np.unique(h[:, 0])) > 10
