"""Cross-silo federated fit (ISSUE 16).

The load-bearing claims, each pinned bitwise:

* federated == pooled for linear/RLS, k-means, and GMM when silo
  boundaries sit on the estimators' scan-chunk boundaries (the merge is
  the same zero-init ascending fold the chunk scans run);
* the result never depends on arrival order, only on silo ids;
* a silo that drops and recovers *within* a round (retry ladder) costs
  nothing — the fit stays bit-identical;
* a coordinator killed at any ``fed.round.*`` site resumes from the
  journal without re-asking silos for work they already did.
"""

import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.federated import (
    FED_BROADCAST_SITE,
    FED_COLLECT_SITE,
    FED_FIT_SITE,
    FED_MERGE_SITE,
    FederatedConfig,
    FederatedCoordinator,
    FederatedQuorumError,
    NoiseConfig,
    Partials,
    Silo,
    apply_clipped_noise,
    merge_partials,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models import (
    GaussianMixture,
    KMeans,
    LinearRegression,
    StreamingLinearRegression,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.base import (
    Estimator,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils import faults
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils.retry import (
    RetryPolicy,
)

pytestmark = pytest.mark.federated

N_SILOS, ROWS, D = 4, 512, 4


# ------------------------------------------------------------------ data
def _int_xy(n_rows: int, d: int = D, seed: int = 0):
    """Integer-valued f32 rows: every partial sum is exact in f32, so the
    linear parity claims hold on ANY mesh/chunk layout."""
    rng = np.random.default_rng(seed)
    x = rng.integers(-8, 8, size=(n_rows, d)).astype(np.float32)
    y = (x @ np.arange(1, d + 1).astype(np.float32) + 1.0).astype(np.float32)
    return x, y


def _blobs(n_rows: int, d: int = D, seed: int = 1):
    rng = np.random.default_rng(seed)
    x = np.concatenate(
        [rng.normal(c, 1.0, size=(n_rows // 3 + 1, d)) for c in (0.0, 6.0, -6.0)]
    )[:n_rows].astype(np.float32)
    rng.shuffle(x)
    return x


def _silos(x, y=None, mesh=None, n=N_SILOS, rows=ROWS):
    out = []
    for i in range(n):
        sl = slice(i * rows, (i + 1) * rows)
        data = x[sl] if y is None else (x[sl], y[sl])
        out.append(Silo(f"s{i}", data, mesh=mesh))
    return out


def _fast_cfg(**kw):
    kw.setdefault(
        "retry", RetryPolicy(max_attempts=3, base_delay_s=0.0, max_delay_s=0.0)
    )
    kw.setdefault("breaker_recovery_s", 0.0)
    return FederatedConfig(**kw)


def _km(**kw):
    x = _blobs(N_SILOS * ROWS)
    kw.setdefault("k", 3)
    kw.setdefault("max_iter", 15)
    kw.setdefault("warm_start_centers", x[: kw["k"]].copy())
    kw.setdefault("chunk_rows", ROWS)
    return KMeans(**kw), x


def _gm(**kw):
    x = _blobs(N_SILOS * ROWS, seed=2)
    k = kw.setdefault("k", 3)
    kw.setdefault("max_iter", 8)
    kw.setdefault("tol", 1e-3)
    kw.setdefault("chunk_rows", ROWS)
    kw.setdefault(
        "warm_start_params",
        (
            np.full((k,), 1.0 / k, np.float32),
            x[:k].astype(np.float32),
            np.stack([np.eye(D, dtype=np.float32) * 4.0] * k),
        ),
    )
    return GaussianMixture(**kw), x


def _assert_kmeans_equal(a, b):
    assert np.array_equal(np.asarray(a.cluster_centers), np.asarray(b.cluster_centers))
    assert float(a.training_cost) == float(b.training_cost)
    assert a.n_iter == b.n_iter
    assert np.array_equal(np.asarray(a.cluster_sizes), np.asarray(b.cluster_sizes))


def _assert_gmm_equal(a, b):
    # federated GMM runs unshifted; −0.0 vs +0.0 may differ from the
    # pooled path's shift arithmetic — array_equal treats them as equal
    assert np.array_equal(np.asarray(a.weights), np.asarray(b.weights))
    assert np.array_equal(np.asarray(a.means), np.asarray(b.means))
    assert np.array_equal(np.asarray(a.covariances), np.asarray(b.covariances))
    assert float(a.log_likelihood) == float(b.log_likelihood)
    assert a.n_iter == b.n_iter


# ------------------------------------------------- per-family bit parity
def test_linear_federated_matches_pooled_bitwise(mesh1):
    x, y = _int_xy(N_SILOS * ROWS)
    est = LinearRegression(reg_param=0.1)
    pooled = est.fit((x, y), mesh=mesh1)
    silos = _silos(x, y, mesh1)
    res = FederatedCoordinator(est, silos, _fast_cfg()).fit()
    assert np.array_equal(
        np.asarray(pooled.coefficients), np.asarray(res.model.coefficients)
    )
    assert float(pooled.intercept) == float(res.model.intercept)
    (r,) = res.rounds
    assert r.contributed == ("s0", "s1", "s2", "s3") and r.done
    assert all(len(s.received_models) == 1 for s in silos)


def test_linear_federated_matches_pooled_mesh8(mesh8):
    # integer-exact sums: parity survives the 8-way data sharding too
    x, y = _int_xy(N_SILOS * ROWS, seed=3)
    est = LinearRegression(reg_param=0.05, standardize=False)
    pooled = est.fit((x, y), mesh=mesh8)
    res = FederatedCoordinator(est, _silos(x, y, mesh8), _fast_cfg()).fit()
    assert np.array_equal(
        np.asarray(pooled.coefficients), np.asarray(res.model.coefficients)
    )
    assert float(pooled.intercept) == float(res.model.intercept)


def test_kmeans_federated_matches_pooled_bitwise(mesh1):
    km, x = _km()
    pooled = km.fit(x, mesh=mesh1)
    res = FederatedCoordinator(km, _silos(x, mesh=mesh1), _fast_cfg()).fit()
    _assert_kmeans_equal(pooled, res.model)
    # iterative family: every round broadcast the updated state
    assert res.rounds[-1].done
    assert res.state.version == pooled.n_iter


def test_gmm_federated_matches_pooled_bitwise(mesh1):
    gm, x = _gm()
    pooled = gm.fit(x, mesh=mesh1)
    res = FederatedCoordinator(gm, _silos(x, mesh=mesh1), _fast_cfg()).fit()
    _assert_gmm_equal(pooled, res.model)


def test_federated_result_independent_of_silo_registration_order(mesh1):
    km, x = _km()
    silos_fwd = _silos(x, mesh=mesh1)
    silos_rev = list(reversed(_silos(x, mesh=mesh1)))
    a = FederatedCoordinator(km, silos_fwd, _fast_cfg()).fit()
    b = FederatedCoordinator(km, silos_rev, _fast_cfg()).fit()
    _assert_kmeans_equal(a.model, b.model)


# ------------------------------------------------ dropout / straggler
def test_transient_silo_failure_recovers_bit_tight(mesh1):
    """Two collect faults on one silo are absorbed by the in-round retry
    ladder — the dropped-and-recovered fit is IDENTICAL to the clean one
    (ISSUE 16 acceptance)."""
    km, x = _km()
    pooled = km.fit(x, mesh=mesh1)
    silos = _silos(x, mesh=mesh1)
    plan = faults.FaultPlan().fail(
        FED_COLLECT_SITE, times=2, when=lambda ctx: ctx.get("silo") == "s2"
    )
    with faults.active(plan):
        res = FederatedCoordinator(km, silos, _fast_cfg()).fit()
    assert plan.fired(FED_COLLECT_SITE) == 2
    _assert_kmeans_equal(pooled, res.model)
    # the failed attempts never reached the silo's compute
    s2 = next(s for s in silos if s.silo_id == "s2")
    s0 = next(s for s in silos if s.silo_id == "s0")
    assert s2.compute_calls == s0.compute_calls


def test_linear_late_partial_folds_exactly(mesh1):
    """A silo that misses round 0 entirely (retries exhausted) lands in a
    later attempt round; the zero-init ascending merge folds its late
    partial into the SAME bits as an on-time run."""
    x, y = _int_xy(N_SILOS * ROWS, seed=4)
    est = LinearRegression(reg_param=0.1)
    pooled = est.fit((x, y), mesh=mesh1)
    silos = _silos(x, y, mesh1)
    plan = faults.FaultPlan().fail(
        FED_COLLECT_SITE, times=3, when=lambda ctx: ctx.get("silo") == "s1"
    )
    with faults.active(plan):
        res = FederatedCoordinator(est, silos, _fast_cfg()).fit()
    assert plan.fired(FED_COLLECT_SITE) == 3
    assert len(res.rounds) == 2
    assert res.rounds[0].dropped == ("s1",) and not res.rounds[0].done
    assert res.rounds[1].contributed == ("s0", "s1", "s2", "s3")
    assert np.array_equal(
        np.asarray(pooled.coefficients), np.asarray(res.model.coefficients)
    )


def test_hard_dropout_completes_round_with_quorum(mesh1):
    km, x = _km(max_iter=5)
    silos = _silos(x, mesh=mesh1)
    plan = faults.FaultPlan().fail(
        FED_COLLECT_SITE, times=None, when=lambda ctx: ctx.get("silo") == "s3"
    )
    with faults.active(plan):
        res = FederatedCoordinator(km, silos, _fast_cfg(quorum=0.5)).fit()
    assert all("s3" not in r.contributed for r in res.rounds)
    assert res.model.n_iter >= 1
    # the broadcast still reaches the dropped silo so it can rejoin
    s3 = next(s for s in silos if s.silo_id == "s3")
    assert len(s3.received_versions) == len(res.rounds)


def test_quorum_failure_raises(mesh1):
    km, x = _km(max_iter=3)
    silos = _silos(x, mesh=mesh1)
    plan = faults.FaultPlan().fail(
        FED_COLLECT_SITE, times=None,
        when=lambda ctx: ctx.get("silo") in ("s1", "s2", "s3"),
    )
    with faults.active(plan):
        with pytest.raises(FederatedQuorumError):
            FederatedCoordinator(km, silos, _fast_cfg(quorum=0.75)).fit()


# ------------------------------------------------------- merge contract
def test_merge_is_arrival_order_independent():
    rng = np.random.default_rng(7)
    parts = [
        Partials(
            family="linear",
            stats={"g": rng.normal(size=(3, 3)).astype(np.float32)},
            n_rows=10.0, silo_id=f"s{i}",
        )
        for i in range(5)
    ]
    ref = merge_partials(parts)
    shuffled = [parts[i] for i in (3, 0, 4, 2, 1)]
    out = merge_partials(shuffled)
    assert np.array_equal(ref.stats["g"], out.stats["g"])
    assert ref.sources == out.sources == ("s0", "s1", "s2", "s3", "s4")


def test_merge_rejects_mixed_versions_and_families():
    a = Partials(family="linear", stats={"g": np.ones(2, np.float32)},
                 silo_id="a", state_version=0)
    b = Partials(family="linear", stats={"g": np.ones(2, np.float32)},
                 silo_id="b", state_version=1)
    with pytest.raises(ValueError, match="state version"):
        merge_partials([a, b])
    c = Partials(family="kmeans", stats={"g": np.ones(2, np.float32)},
                 silo_id="c", state_version=0)
    with pytest.raises(ValueError, match="family"):
        merge_partials([a, c])


def test_partials_journal_payload_roundtrip_is_exact():
    rng = np.random.default_rng(11)
    p = Partials(
        family="gmm",
        stats={
            "nk": rng.normal(size=(3,)).astype(np.float32),
            "outer": rng.normal(size=(3, 4, 4)).astype(np.float32),
        },
        n_rows=123.0, silo_id="s1", round_id=4, state_version=4,
    )
    q = Partials.from_payload(p.to_payload())
    for k in p.stats:
        assert np.array_equal(p.stats[k], q.stats[k])
        assert p.stats[k].dtype == q.stats[k].dtype
    assert (q.silo_id, q.round_id, q.state_version) == ("s1", 4, 4)


def test_weighting_scales_contribution_and_row_mass():
    a = Partials(family="linear", stats={"g": np.full(2, 2.0, np.float32)},
                 n_rows=10.0, silo_id="a")
    b = Partials(family="linear", stats={"g": np.full(2, 4.0, np.float32)},
                 n_rows=10.0, silo_id="b")
    merged = merge_partials([a, b], weights={"a": 3.0, "b": 1.0})
    assert np.array_equal(merged.stats["g"], np.full(2, 10.0, np.float32))
    assert merged.n_rows == 40.0
    # the unweighted fold skips the multiply entirely (bit-parity path)
    plain = merge_partials([a, b])
    assert np.array_equal(plain.stats["g"], np.full(2, 6.0, np.float32))


# ------------------------------------------------------------- noise knob
def test_clipped_noise_is_deterministic_and_flagged():
    p = Partials(
        family="linear",
        stats={"g": np.full((4,), 100.0, np.float32)},
        n_rows=5.0, silo_id="s0", round_id=2,
    )
    cfg = NoiseConfig(clip_norm=1.0, noise_multiplier=0.5, seed=9)
    a, b = apply_clipped_noise(p, cfg), apply_clipped_noise(p, cfg)
    assert a.noised and np.array_equal(a.stats["g"], b.stats["g"])
    # clipping bound: the noised stats' norm ≤ clip + noise scale margin
    assert not np.array_equal(a.stats["g"], p.stats["g"])
    # no-op config ships the partial untouched (bit-parity preserved)
    clean = apply_clipped_noise(p, NoiseConfig(clip_norm=1e9, noise_multiplier=0.0))
    assert clean is p and not clean.noised


def test_noise_knob_end_to_end_close_but_marked(mesh1):
    x, y = _int_xy(N_SILOS * ROWS, seed=5)
    est = LinearRegression(reg_param=0.1)
    pooled = est.fit((x, y), mesh=mesh1)
    noise = NoiseConfig(clip_norm=1e9, noise_multiplier=1e-9, seed=3)
    res = FederatedCoordinator(
        est, _silos(x, y, mesh1), _fast_cfg(noise=noise)
    ).fit()
    np.testing.assert_allclose(
        np.asarray(pooled.coefficients), np.asarray(res.model.coefficients),
        rtol=1e-3, atol=1e-3,
    )
    # deterministic: a rerun produces the identical noised model
    res2 = FederatedCoordinator(
        est, _silos(x, y, mesh1), _fast_cfg(noise=noise)
    ).fit()
    assert np.array_equal(
        np.asarray(res.model.coefficients), np.asarray(res2.model.coefficients)
    )


# -------------------------------------------------------- federated init
def test_kmeans_federated_init_without_warm_start(mesh1):
    x = _blobs(N_SILOS * ROWS, seed=6)
    km = KMeans(k=3, max_iter=10, chunk_rows=ROWS, init_sample_size=ROWS)
    silos = _silos(x, mesh=mesh1)
    res = FederatedCoordinator(km, silos, _fast_cfg()).fit()
    assert res.model.cluster_centers.shape == (3, D)
    assert float(res.model.training_cost) > 0.0
    # candidate init counts as one extra collect per silo
    assert silos[0].compute_calls == res.state.version + 2


def test_gmm_federated_init_without_warm_start(mesh1):
    x = _blobs(N_SILOS * ROWS, seed=8)
    gm = GaussianMixture(k=2, max_iter=4, tol=1e-3, chunk_rows=ROWS,
                         init_sample_size=ROWS)
    res = FederatedCoordinator(gm, _silos(x, mesh=mesh1), _fast_cfg()).fit()
    assert res.model.means.shape == (2, D)
    assert np.isfinite(res.model.log_likelihood)
    assert abs(float(np.sum(res.model.weights)) - 1.0) < 1e-5


# -------------------------------------------------------- estimator API
def test_partials_protocol_surface():
    assert LinearRegression().supports_partials()
    # the elastic-net path centers on the pooled mean — not decomposable
    assert not LinearRegression(
        reg_param=0.1, elastic_net_param=0.5
    ).supports_partials()
    assert KMeans().supports_partials() and KMeans().partials_final_collect()
    assert GaussianMixture().supports_partials()
    assert not GaussianMixture().partials_final_collect()

    class Plain(Estimator):
        def fit(self, data, label_col=None, mesh=None):  # pragma: no cover
            return None

    p = Plain()
    assert not p.supports_partials()
    with pytest.raises(NotImplementedError):
        p.partial_fit_stats(None)
    with pytest.raises(NotImplementedError):
        p.fit_from_partials(None)


def test_streaming_linear_absorbs_federated_round(mesh1):
    """RLS coverage: the streaming estimator folds a merged federated
    round as one micro-batch, bit-matching its own update on the pooled
    rows (decay 1.0, integer-exact sums)."""
    x, y = _int_xy(2 * ROWS, seed=9)
    est = LinearRegression(reg_param=0.0)
    silos = _silos(x, y, mesh1, n=2, rows=ROWS)
    parts = [
        s.compute_partials(est, state=None, round_id=0) for s in silos
    ]
    merged = merge_partials(parts)

    fed = StreamingLinearRegression()
    fed.absorb_partials(merged)
    direct = StreamingLinearRegression()
    direct.update((x, y), mesh=mesh1)
    a, b = fed.latest_model, direct.latest_model
    assert np.array_equal(np.asarray(a.coefficients), np.asarray(b.coefficients))
    assert float(a.intercept) == float(b.intercept)
    with pytest.raises(ValueError, match="linear"):
        fed.absorb_partials(
            Partials(family="kmeans", stats={}, silo_id="x")
        )


# ------------------------------------------------------------- profiles
def test_merged_profile_matches_pooled_moments(mesh1):
    x = _blobs(N_SILOS * ROWS, seed=10)
    coord = FederatedCoordinator(
        LinearRegression(), _silos(x, np.zeros(len(x), np.float32), mesh1),
        _fast_cfg(),
    )
    prof = coord.merged_profile(names=[f"f{j}" for j in range(D)])
    for j in range(D):
        sk = prof.sketches[f"f{j}"]
        assert sk.count == float(len(x))
        np.testing.assert_allclose(
            sk.mean, float(x[:, j].astype(np.float64).mean()), rtol=1e-7
        )
        assert sk.min == float(x[:, j].min()) and sk.max == float(x[:, j].max())


# ------------------------------------------------------- silo ingestion
def test_silo_from_csv_runs_local_stack(tmp_path, mesh1):
    rows = 64
    rng = np.random.default_rng(12)
    f0 = rng.integers(0, 10, size=rows)
    f1 = rng.integers(0, 10, size=rows)
    los = f0 * 2 + f1 + 1
    csv = tmp_path / "hospital_a.csv"
    lines = ["f0,f1,length_of_stay"] + [
        f"{a},{b},{c}" for a, b, c in zip(f0, f1, los)
    ]
    csv.write_text("\n".join(lines) + "\n")
    schema = ht.Schema(
        [("f0", "float"), ("f1", "float"), ("length_of_stay", "float")]
    )
    silo = Silo.from_csv(
        "hosp_a", str(csv), schema, feature_cols=["f0", "f1"],
        label_col="length_of_stay", mesh=mesh1,
        table_dir=str(tmp_path / "tbl"),
    )
    assert silo.n_rows == rows
    p = silo.compute_partials(LinearRegression(), state=None, round_id=0)
    assert p.silo_id == "hosp_a" and p.n_rows == float(rows)
    model = LinearRegression().fit_from_partials(merge_partials([p]))
    pred = np.asarray(model.predict(silo.feature_matrix().astype(np.float32)))
    np.testing.assert_allclose(pred, los.astype(np.float32), atol=1e-2)


# --------------------------------------------------------- round journal
FED_SITES = [FED_COLLECT_SITE, FED_MERGE_SITE, FED_FIT_SITE, FED_BROADCAST_SITE]


@pytest.mark.chaos
@pytest.mark.parametrize("site", FED_SITES)
def test_coordinator_killed_mid_round_resumes_bit_equal(tmp_path, mesh1, site):
    """Kill the coordinator at each round phase; a fresh coordinator over
    the same journal finishes the fit bit-identical to an unkilled run —
    and no silo recomputes a partial the journal already holds."""
    km, x = _km(max_iter=6)
    baseline_silos = _silos(x, mesh=mesh1)
    baseline = FederatedCoordinator(km, baseline_silos, _fast_cfg()).fit()
    per_silo_calls = baseline_silos[0].compute_calls

    silos = _silos(x, mesh=mesh1)
    jdir = str(tmp_path / "journal")
    cfg = _fast_cfg(journal_dir=jdir)
    plan = faults.FaultPlan().crash(site)
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash):
            FederatedCoordinator(km, silos, cfg).fit()
    assert plan.fired(site) == 1

    res = FederatedCoordinator(km, silos, cfg).fit()
    _assert_kmeans_equal(baseline.model, res.model)
    # journaled partials are folded, not recomputed: total work per silo
    # matches the unkilled run exactly
    for s in silos:
        assert s.compute_calls == per_silo_calls, s.silo_id


@pytest.mark.chaos
def test_coordinator_killed_after_terminal_commit_rebroadcasts_only(
    tmp_path, mesh1
):
    x, y = _int_xy(N_SILOS * ROWS, seed=13)
    est = LinearRegression(reg_param=0.1)
    silos = _silos(x, y, mesh1)
    jdir = str(tmp_path / "j2")
    cfg = _fast_cfg(journal_dir=jdir)
    plan = faults.FaultPlan().crash(FED_BROADCAST_SITE)
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash):
            FederatedCoordinator(est, silos, cfg).fit()
    calls = [s.compute_calls for s in silos]
    res = FederatedCoordinator(est, silos, cfg).fit()
    assert res.resumed_from_round is not None
    # terminal commit was journaled before the crash — the resume only
    # rebuilds + re-broadcasts, zero new silo work
    assert [s.compute_calls for s in silos] == calls
    assert all(len(s.received_models) == 1 for s in silos)
    pooled = est.fit((x, y), mesh=mesh1)
    assert np.array_equal(
        np.asarray(pooled.coefficients), np.asarray(res.model.coefficients)
    )


def test_journal_signature_mismatch_refuses_resume(tmp_path, mesh1):
    x, y = _int_xy(N_SILOS * ROWS, seed=14)
    est = LinearRegression()
    jdir = str(tmp_path / "j3")
    FederatedCoordinator(est, _silos(x, y, mesh1), _fast_cfg(journal_dir=jdir)).fit()
    other = _silos(x, y, mesh1, n=2)
    with pytest.raises(ValueError, match="signature mismatch"):
        FederatedCoordinator(est, other, _fast_cfg(journal_dir=jdir)).fit()


# ------------------------------------------------------------------ soak
@pytest.mark.slow
def test_multi_round_soak_with_transient_dropouts(mesh1):
    """Longer horizon: two silos flap across a deeper k-means run; every
    failure is absorbed in-round, so the fit stays bit-identical to the
    clean run."""
    n, rows = 8, 512
    x = _blobs(n * rows, seed=15)
    km = KMeans(
        k=4, max_iter=40, tol=1e-6, warm_start_centers=x[:4].copy(),
        chunk_rows=rows,
    )
    pooled = km.fit(x, mesh=mesh1)
    clean = FederatedCoordinator(
        km, _silos(x, mesh=mesh1, n=n, rows=rows), _fast_cfg()
    ).fit()
    _assert_kmeans_equal(pooled, clean.model)

    silos = _silos(x, mesh=mesh1, n=n, rows=rows)
    plan = (
        faults.FaultPlan()
        .fail(FED_COLLECT_SITE, times=2,
              when=lambda ctx: ctx.get("silo") == "s2")
        .fail(FED_COLLECT_SITE, times=2, after=4,
              when=lambda ctx: ctx.get("silo") == "s5")
    )
    with faults.active(plan):
        flappy = FederatedCoordinator(km, silos, _fast_cfg()).fit()
    assert plan.fired(FED_COLLECT_SITE) == 4
    _assert_kmeans_equal(pooled, flappy.model)
