"""LogisticRegression (the reference's intended per-batch classifier,
SURVEY.md C6/D2): Newton/IRLS convergence vs sklearn, sharded == single
device, LOS-binarization pipeline, save/load."""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import load_model
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.logistic_regression import (
    LogisticRegression,
)


def _logit_data(rng, n=2000, d=4):
    x = rng.normal(size=(n, d))
    true_w = np.array([1.5, -2.0, 0.7, 0.0][:d])
    logits = x @ true_w + 0.3
    p = 1.0 / (1.0 + np.exp(-logits))
    y = (rng.random(n) < p).astype(np.float64)
    return x, y, true_w


@pytest.mark.fast
def test_matches_sklearn_unregularized(rng, mesh8):
    from sklearn.linear_model import LogisticRegression as SK

    x, y, _ = _logit_data(rng)
    ours = LogisticRegression(reg_param=0.0).fit((x, y), mesh=mesh8)
    sk = SK(C=np.inf, tol=1e-8, max_iter=200).fit(x, y)
    np.testing.assert_allclose(
        np.asarray(ours.coefficients), sk.coef_[0], rtol=2e-3, atol=2e-3
    )
    np.testing.assert_allclose(
        float(ours.intercept), sk.intercept_[0], rtol=2e-3, atol=2e-3
    )
    assert ours.n_iter < 30  # quadratic convergence


def test_l2_regularized_matches_sklearn(rng, mesh8):
    from sklearn.linear_model import LogisticRegression as SK
    from sklearn.preprocessing import StandardScaler

    x, y, _ = _logit_data(rng, n=3000)
    lam = 0.1
    ours = LogisticRegression(reg_param=lam, standardize=True).fit((x, y), mesh=mesh8)
    # Spark semantics: L2 on standardized coefficients, intercept free.
    # sklearn equivalent: scale features, C = 1/(lam*n), then unscale.
    scaler = StandardScaler().fit(x)
    sk = SK(C=1.0 / (lam * len(x)), tol=1e-8, max_iter=500).fit(
        scaler.transform(x), y
    )
    np.testing.assert_allclose(
        np.asarray(ours.coefficients) * scaler.scale_, sk.coef_[0], rtol=5e-2, atol=5e-3
    )


def test_sharded_equals_single_device(rng, mesh8, mesh1):
    x, y, _ = _logit_data(rng, n=1000)
    m8 = LogisticRegression().fit((x, y), mesh=mesh8)
    m1 = LogisticRegression().fit((x, y), mesh=mesh1)
    np.testing.assert_allclose(
        np.asarray(m8.coefficients), np.asarray(m1.coefficients), atol=1e-5
    )


def test_los_binarization_pipeline(hospital_table, mesh8):
    """Reference :176-190 parity — binarize LOS at 5.0, train, accuracy."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.features.binarizer import (
        Binarizer,
    )

    t = Binarizer("length_of_stay", "LOS_binary", 5.0).transform(hospital_table)
    train, test = ht.train_test_split(t, 0.7, 42)
    asm = ht.VectorAssembler(ht.FEATURE_COLS)
    model = LogisticRegression().fit(
        asm.transform(train), label_col="LOS_binary", mesh=mesh8
    )
    pred = model.transform(asm.transform(test), label_col="LOS_binary", mesh=mesh8)
    acc = ht.MulticlassClassificationEvaluator("accuracy").evaluate(pred)
    assert acc > 0.7
    # predictions are hard 0/1 classes
    p, _ = pred.to_numpy()
    assert set(np.unique(p)).issubset({0.0, 1.0})


def test_save_load_roundtrip(tmp_path, rng, mesh8):
    x, y, _ = _logit_data(rng, n=500)
    model = LogisticRegression(threshold=0.4).fit((x, y), mesh=mesh8)
    path = str(tmp_path / "logit")
    model.write().overwrite().save(path)
    re = load_model(path)
    assert re.threshold == 0.4
    np.testing.assert_array_equal(re.predict_numpy(x), model.predict_numpy(x))


def test_per_batch_training_hook(tmp_path, rng, mesh8):
    """The reference's intended ``train_model_on_batch`` (C6/D2: a
    LogisticRegression fit + model save per micro-batch inside
    ``foreachBatch``) — realized on the working streaming loop."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.features.binarizer import (
        Binarizer,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import write_csv
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming import (
        FileStreamSource,
        StreamCheckpoint,
        StreamExecution,
        UnboundedTable,
        WatermarkTracker,
    )

    incoming = tmp_path / "incoming"
    incoming.mkdir()
    saved = []

    def train_model_on_batch(batch_table, batch_id):
        t = Binarizer("length_of_stay", "LOS_binary", 5.0).transform(batch_table)
        feats = ht.VectorAssembler(ht.FEATURE_COLS).transform(t)
        model = LogisticRegression(max_iter=25).fit(
            feats, label_col="LOS_binary", mesh=mesh8
        )
        path = str(tmp_path / f"model_batch_{batch_id}")
        model.write().overwrite().save(path)  # :103 per-batch save parity
        saved.append(path)

    exec_ = StreamExecution(
        source=FileStreamSource(str(incoming), ht.hospital_event_schema()),
        sink=UnboundedTable(str(tmp_path / "table"), ht.hospital_event_schema()),
        checkpoint=StreamCheckpoint(str(tmp_path / "ckpt")),
        watermark=WatermarkTracker("event_time", 10.0),
        foreach_batch=train_model_on_batch,
    )

    for b in range(2):
        n = 300
        base = np.datetime64("2025-03-31T22:00:00") + np.timedelta64(b, "m")
        adm = rng.integers(0, 50, n)
        t = ht.Table.from_dict(
            {
                "hospital_id": np.array(["H01"] * n, dtype=object),
                "event_time": base + np.arange(n).astype("timedelta64[s]"),
                "admission_count": adm,
                "current_occupancy": rng.integers(20, 400, n),
                "emergency_visits": rng.integers(0, 30, n),
                "seasonality_index": rng.uniform(0.5, 1.5, n),
                "length_of_stay": 3.0 + 0.1 * adm + rng.normal(0, 0.5, n),
            },
            ht.hospital_event_schema(),
        )
        write_csv(t, str(incoming / f"batch{b}.csv"))
        assert exec_.run_once() is not None

    assert len(saved) == 2
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.logistic_regression import (
        LogisticRegressionModel,
    )

    for path in saved:
        assert isinstance(load_model(path), LogisticRegressionModel)


def test_perfect_separation_does_not_blow_up(mesh8):
    """IRLS floor keeps the Hessian invertible on separable data."""
    x = np.concatenate([np.full((50, 2), -2.0), np.full((50, 2), 2.0)])
    y = np.concatenate([np.zeros(50), np.ones(50)])
    model = LogisticRegression(max_iter=50).fit((x, y), mesh=mesh8)
    assert np.isfinite(np.asarray(model.coefficients)).all()
    assert (model.predict_numpy(x) == y).all()
