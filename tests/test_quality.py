"""Data-quality firewall (PR 3): row validation, salvage parse, schema
drift, quantile sketches / PSI, row quarantine, data-fault chaos, and
drift-aware serving degradation.

The chaos-marked classes run under ``tools/run_chaos.sh`` alongside the
process-fault matrix; the soak test at the bottom is the PR's acceptance
scenario (5% corrupt rows + one schema-drifted hospital, end to end).
"""

import json
import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu import quality
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import (
    attach_data_profile,
    load_data_profile,
    read_csv,
    read_csv_salvage,
    save_model,
    write_csv,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.quality.reconcile import (
    DRIFT_COLUMN_ADDED,
    DRIFT_COLUMN_MISSING,
    DRIFT_COLUMN_RENAMED,
    DRIFT_COLUMN_REORDERED,
    reconcile_columns,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming import (
    FileStreamSource,
    StreamCheckpoint,
    StreamExecution,
    UnboundedTable,
    WatermarkTracker,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils import faults

pytestmark = pytest.mark.quality

SCHEMA = ht.hospital_event_schema()


def _event_table(n, hospital="H01", start="2025-03-31T22:00:00", los=None):
    """Synthetic events with VARIED features and a linear LOS signal
    (constant columns make estimators degenerate — see conftest's
    hospital_table); deterministic so dirty-line injection is exact."""
    base = np.datetime64(start)
    i = np.arange(n)
    admission = i % 50
    occupancy = 80 + (i * 7) % 250
    emergency = i % 25
    season = 0.5 + (i % 10) * 0.1
    los_v = (
        np.full(n, float(los))
        if los is not None
        else 0.05 * admission + 0.01 * occupancy + 0.08 * emergency + 1.5 * season
    )
    return ht.Table.from_dict(
        {
            "hospital_id": np.array([hospital] * n, dtype=object),
            "event_time": base + i.astype("timedelta64[s]"),
            "admission_count": admission,
            "current_occupancy": occupancy,
            "emergency_visits": emergency,
            "seasonality_index": season,
            "length_of_stay": los_v,
        },
        SCHEMA,
    )


def _firewalled_stream(tmp_path, monitor=None, **kw):
    incoming = tmp_path / "incoming"
    incoming.mkdir(exist_ok=True)
    fw = quality.DataFirewall(
        SCHEMA, quality.hospital_constraints(),
        aliases={"los": "length_of_stay"}, monitor=monitor,
    )
    ckpt = StreamCheckpoint(str(tmp_path / "ckpt"))
    ex = StreamExecution(
        source=FileStreamSource(str(incoming), SCHEMA),
        sink=UnboundedTable(str(tmp_path / "table"), SCHEMA),
        checkpoint=ckpt,
        watermark=WatermarkTracker("event_time", 10.0),
        firewall=fw,
        **kw,
    )
    return incoming, ex, ckpt, fw


# ===================================================================== sketches
class TestSketches:
    def test_update_moments_match_numpy(self, rng):
        v = rng.normal(3.0, 2.0, 10_000)
        sk = quality.FeatureSketch(edges=np.linspace(-5, 11, 17))
        sk.update(v[:4000]).update(v[4000:])
        assert sk.count == 10_000
        assert np.isclose(sk.mean, v.mean())
        assert np.isclose(sk.std, v.std())
        assert sk.min == v.min() and sk.max == v.max()

    def test_merge_is_exact(self, rng):
        v = rng.normal(0, 1, 6000)
        edges = np.linspace(-4, 4, 17)
        a = quality.FeatureSketch(edges=edges).update(v[:1000])
        b = quality.FeatureSketch(edges=edges).update(v[1000:])
        whole = quality.FeatureSketch(edges=edges).update(v)
        a.merge(b)
        assert np.isclose(a.mean, whole.mean)
        assert np.isclose(a.m2, whole.m2)
        assert np.array_equal(a.counts, whole.counts)

    def test_merge_rejects_mismatched_edges(self):
        a = quality.FeatureSketch(edges=[0.0, 1.0])
        b = quality.FeatureSketch(edges=[0.0, 2.0])
        with pytest.raises(ValueError, match="different bin edges"):
            a.merge(b)

    def test_psi_separates_clean_from_shifted(self, rng):
        ref = quality.DataProfile.from_matrix(
            rng.normal(0, 1, (4000, 2)), ["a", "b"]
        )
        same = quality.DataProfile.like(ref)
        same.update_matrix(rng.normal(0, 1, (2000, 2)))
        shifted = quality.DataProfile.like(ref)
        shifted.update_matrix(rng.normal(0, 1, (2000, 2)) * 100 + 50)
        psi_same = max(ref.psi_against(same).values())
        psi_shift = max(ref.psi_against(shifted).values())
        assert psi_same < quality.PSI_STABLE
        assert psi_shift > quality.PSI_DRIFT

    def test_empty_live_is_not_drift(self, rng):
        ref = quality.DataProfile.from_matrix(rng.normal(0, 1, (100, 1)), ["a"])
        assert max(ref.psi_against(quality.DataProfile.like(ref)).values()) == 0.0

    def test_json_roundtrip(self, rng):
        ref = quality.DataProfile.from_matrix(
            rng.normal(0, 1, (500, 3)), ["a", "b", "c"]
        )
        rt = quality.DataProfile.from_dict(
            json.loads(json.dumps(ref.to_dict()))
        )
        live = quality.DataProfile.like(ref)
        live.update_matrix(rng.normal(2, 1, (300, 3)))
        assert ref.psi_against(live) == rt.psi_against(live)

    def test_constant_column_and_nan_handling(self):
        prof = quality.DataProfile.from_matrix(
            np.column_stack([np.full(50, 7.0), np.full(50, np.nan)]),
            ["const", "allnan"],
        )
        sk = prof.sketches["const"]
        assert sk.count == 50 and sk.std == 0.0
        assert prof.sketches["allnan"].n_invalid == 50

    def test_approx_quantile(self, rng):
        v = rng.uniform(0, 10, 50_000)
        sk = quality.FeatureSketch(edges=np.linspace(0, 10, 41)).update(v)
        assert abs(sk.approx_quantile(0.5) - 5.0) < 0.3


# =================================================================== reconcile
class TestReconcile:
    NAMES = SCHEMA.names

    def test_exact_header_no_events(self):
        m = reconcile_columns(self.NAMES, SCHEMA)
        assert m.exact
        assert [m.indices[n] for n in self.NAMES] == list(range(len(self.NAMES)))

    def test_reordered(self):
        m = reconcile_columns(list(reversed(self.NAMES)), SCHEMA)
        kinds = {e.kind for e in m.events}
        assert kinds == {DRIFT_COLUMN_REORDERED}
        assert m.indices["hospital_id"] == len(self.NAMES) - 1

    def test_rename_via_alias_and_normalization(self):
        src = [
            "Hospital_ID", "event_time", "admission_count",
            "current_occupancy", "emergency_visits", "seasonality_index",
            "los",
        ]
        m = reconcile_columns(src, SCHEMA, aliases={"los": "length_of_stay"})
        renamed = {
            (e.source, e.target)
            for e in m.events if e.kind == DRIFT_COLUMN_RENAMED
        }
        assert ("los", "length_of_stay") in renamed
        assert ("Hospital_ID", "hospital_id") in renamed
        assert m.missing == ()

    def test_missing_and_added(self):
        src = self.NAMES[:-1] + ["brand_new_col"]
        m = reconcile_columns(src, SCHEMA)
        kinds = [e.kind for e in m.events]
        assert DRIFT_COLUMN_MISSING in kinds and DRIFT_COLUMN_ADDED in kinds
        assert m.indices["length_of_stay"] is None


# ================================================================== validators
class TestValidators:
    def test_range_rejects_with_reason(self):
        t = _event_table(5).with_column(
            "length_of_stay", np.array([4.0, 400.0, 4.0, -1.0, 4.0])
        )
        vr = quality.RowValidator(
            SCHEMA, quality.hospital_constraints()
        ).validate(t)
        assert len(vr.accepted) == 3 and vr.n_rejected == 2
        assert vr.histogram == {"range:length_of_stay": 2}
        assert all("range:length_of_stay" in r for r in vr.reasons)

    def test_nan_passes_range_but_inf_rejects(self):
        t = _event_table(3).with_column(
            "seasonality_index", np.array([np.nan, 1.0, np.inf])
        )
        vr = quality.RowValidator(
            SCHEMA, quality.hospital_constraints()
        ).validate(t)
        # NaN is missing (imputer's job); +Inf is wrong (reject) — the one
        # bad row carries both the range and the non-finite reason
        assert len(vr.accepted) == 2 and vr.n_rejected == 1
        assert vr.histogram["non_finite:seasonality_index"] == 1
        assert "non_finite:seasonality_index" in vr.reasons[0]

    def test_not_null(self):
        t = _event_table(3)
        et = t.column("event_time").copy()
        et[1] = np.datetime64("NaT")
        t = t.with_column("event_time", et)
        vr = quality.RowValidator(
            SCHEMA, quality.hospital_constraints()
        ).validate(t)
        assert vr.histogram == {"null:event_time": 1}

    def test_domain(self):
        cs = quality.ConstraintSet().domain("hospital_id", ["H01", "H02"])
        t = _event_table(3)
        hid = t.column("hospital_id").copy()
        hid[2] = "MARS"
        t = t.with_column("hospital_id", hid, dtype="string")
        vr = quality.RowValidator(SCHEMA, cs).validate(t)
        assert vr.histogram == {"domain:hospital_id": 1}

    def test_monotone_grouped(self):
        t = _event_table(4)
        et = t.column("event_time").copy()
        et[2] = et[0] - np.timedelta64(60, "s")  # H01 goes backwards
        t = t.with_column("event_time", et)
        cs = quality.ConstraintSet().monotone("event_time", group_by="hospital_id")
        vr = quality.RowValidator(SCHEMA, cs).validate(t)
        assert vr.histogram == {"monotone:event_time": 1}
        assert len(vr.accepted) == 3

    def test_empty_table(self):
        vr = quality.RowValidator(
            SCHEMA, quality.hospital_constraints()
        ).validate(ht.Table.empty(SCHEMA))
        assert vr.n_input == 0 and vr.n_rejected == 0


# ================================================================ salvage csv
class TestSalvageCsv:
    def _write(self, tmp_path, text):
        p = tmp_path / "h.csv"
        p.write_text(text)
        return str(p)

    def test_clean_file_matches_strict_parse(self, tmp_path):
        t = _event_table(30)
        p = str(tmp_path / "clean.csv")
        write_csv(t, p)
        strict = read_csv(p, SCHEMA)
        sr = read_csv_salvage(p, SCHEMA)
        assert not sr.rejects and not sr.drift_events
        for c in SCHEMA.names:
            np.testing.assert_array_equal(
                strict.columns[c].astype("U32"),
                sr.table.columns[c].astype("U32"),
            )

    def test_single_bad_field_rejects_one_row_not_the_file(self, tmp_path):
        t = _event_table(10)
        p = str(tmp_path / "h.csv")
        write_csv(t, p)
        lines = open(p).read().rstrip("\n").split("\n")
        parts = lines[3].split(",")
        parts[3] = "one-hundred"  # occupancy garbage
        lines[3] = ",".join(parts)
        open(p, "w").write("\n".join(lines) + "\n")
        sr = read_csv_salvage(p, SCHEMA)
        assert len(sr.table) == 9
        assert [r.line_no for r in sr.rejects] == [4]
        assert sr.rejects[0].reasons == ("parse:current_occupancy",)

    def test_ragged_row_rejects_field_count(self, tmp_path):
        p = self._write(
            tmp_path,
            ",".join(SCHEMA.names) + "\n"
            "H01,2025-03-31 22:00:00,1,100,5,1.0,4.0\n"
            "H01,2025-03-31 22:00:01,1,100\n",
        )
        sr = read_csv_salvage(p, SCHEMA)
        assert len(sr.table) == 1
        assert sr.rejects[0].reasons == ("field_count",)

    def test_empty_fields_become_nulls_not_rejects(self, tmp_path):
        p = self._write(
            tmp_path,
            ",".join(SCHEMA.names) + "\n"
            "H01,2025-03-31 22:00:00,,100,5,1.0,4.0\n",
        )
        sr = read_csv_salvage(p, SCHEMA)
        assert len(sr.table) == 1 and not sr.rejects
        assert np.isnan(sr.table.column("admission_count")[0])

    def test_line_numbers_are_physical_despite_blank_lines(self, tmp_path):
        """Quarantine evidence must point at the ACTUAL file line."""
        p = self._write(
            tmp_path,
            ",".join(SCHEMA.names) + "\n"
            "H01,2025-03-31 22:00:00,1,100,5,1.0,4.0\n"
            "\n"
            "H01,2025-03-31 22:00:01,BAD,100,5,1.0,4.0\n",
        )
        sr = read_csv_salvage(p, SCHEMA)
        assert [r.line_no for r in sr.rejects] == [4]
        # same contract through the firewall fast path's rescan
        fw = quality.DataFirewall(SCHEMA, quality.hospital_constraints())
        res = fw.ingest_file(p)
        assert [r["line_no"] for r in res.rejects] == [4]

    def test_drifted_header_reconciles(self, tmp_path):
        p = self._write(
            tmp_path,
            "event_time,hospital_id,admission_count,current_occupancy,"
            "emergency_visits,seasonality_index,los\n"
            "2025-03-31 22:00:00,H09,1,100,5,1.0,4.0\n",
        )
        sr = read_csv_salvage(p, SCHEMA, aliases={"los": "length_of_stay"})
        assert len(sr.table) == 1 and not sr.rejects
        assert sr.table.column("hospital_id")[0] == "H09"
        assert sr.table.column("length_of_stay")[0] == 4.0
        kinds = {e.kind for e in sr.drift_events}
        assert DRIFT_COLUMN_RENAMED in kinds and DRIFT_COLUMN_REORDERED in kinds

    def test_strict_read_still_fails_the_file(self, tmp_path):
        """The pre-PR3 contract is preserved for callers that want it."""
        p = self._write(
            tmp_path,
            ",".join(SCHEMA.names) + "\n"
            "H01,not-a-timestamp,1,100,5,1.0,4.0\n",
        )
        with pytest.raises(Exception):
            read_csv(p, SCHEMA, engine="numpy")


# ============================================================= stream firewall
class TestStreamFirewall:
    def test_dirty_rows_quarantined_batch_commits(self, tmp_path):
        incoming, ex, ckpt, fw = _firewalled_stream(tmp_path)
        t = _event_table(20)
        p = str(incoming / "a.csv")
        write_csv(t, p)
        lines = open(p).read().rstrip("\n").split("\n")
        lines[2] = "H01,2025-03-31 22:00:01,JUNK,100,5,1.0,4.0"
        lines[5] = "H01,2025-03-31 22:00:04,4,100,5,1.0,900.0"
        lines[8] = "H01,2025-03-31 22:00:07,4"  # ragged (fast-path rescan)
        open(p, "w").write("\n".join(lines) + "\n")

        info = ex.run_once()
        assert info.status == "ok"
        assert info.num_rejected_rows == 3
        assert info.num_appended_rows == 17
        assert ex.sink.read().num_rows == 17
        assert ckpt.quarantined_row_count() == 3
        hist = ckpt.row_reason_histogram()
        assert hist == {
            "parse:admission_count": 1,
            "range:length_of_stay": 1,
            "field_count": 1,
        }
        assert ex.metrics.counters["stream.rows_rejected"] == 3

    def test_replay_does_not_double_count_rejects(self, tmp_path):
        """A batch that fails AFTER quarantining and is replayed must not
        double-count stream.rows_rejected (health() reads it)."""
        calls = {"n": 0}

        def flaky(batch, batch_id):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("transient foreach failure")

        incoming, ex, ckpt, fw = _firewalled_stream(
            tmp_path, foreach_batch=flaky
        )
        p = str(incoming / "a.csv")
        write_csv(_event_table(10), p)
        lines = open(p).read().rstrip("\n").split("\n")
        lines[2] = "H01,2025-03-31 22:00:01,JUNK,100,5,1.0,4.0"
        open(p, "w").write("\n".join(lines) + "\n")
        info = ex.run_once()
        assert info.status == "ok" and calls["n"] == 2  # replay happened
        assert info.num_rejected_rows == 1
        assert ex.metrics.counters["stream.rows_rejected"] == 1
        assert ckpt.quarantined_row_count() == 1

    def test_row_quarantine_file_layout(self, tmp_path):
        incoming, ex, ckpt, fw = _firewalled_stream(tmp_path)
        p = str(incoming / "a.csv")
        write_csv(_event_table(5), p)
        lines = open(p).read().rstrip("\n").split("\n")
        lines[1] = "H01,2025-03-31 22:00:00,bad,100,5,1.0,4.0"
        open(p, "w").write("\n".join(lines) + "\n")
        ex.run_once()
        qfile = tmp_path / "ckpt" / "quarantine" / "rows" / "batch-0000000000.json"
        assert qfile.exists()
        rec = json.loads(qfile.read_text())
        assert rec["n_rejected"] == 1
        assert rec["rejects"][0]["reasons"] == ["parse:admission_count"]
        assert rec["rejects"][0]["line_no"] == 2
        assert "raw" in rec["rejects"][0]

    def test_drifted_hospital_ingests_with_events(self, tmp_path):
        incoming, ex, ckpt, fw = _firewalled_stream(tmp_path)
        (incoming / "h7.csv").write_text(
            "event_time,hospital_id,admission_count,current_occupancy,"
            "emergency_visits,seasonality_index,los\n"
            "2025-03-31 22:00:00,H07,1,100,5,1.0,4.0\n"
            "2025-03-31 22:00:01,H07,2,100,5,1.0,4.5\n"
        )
        info = ex.run_once()
        assert info.num_appended_rows == 2 and info.num_rejected_rows == 0
        assert info.num_drift_events > 0
        assert ex.metrics.counters["stream.drift_events"] > 0
        snap = ex.sink.read()
        assert list(snap.column("length_of_stay")[:2]) == [4.0, 4.5]

    def test_clean_stream_unchanged(self, tmp_path):
        """Firewall on clean data: same rows, zero rejects, no events."""
        incoming, ex, ckpt, fw = _firewalled_stream(tmp_path)
        write_csv(_event_table(40), str(incoming / "a.csv"))
        info = ex.run_once()
        assert info.num_input_rows == 40
        assert info.num_appended_rows == 40
        assert info.num_rejected_rows == 0
        assert ckpt.quarantined_row_count() == 0

    def test_ingest_drift_monitor_gauge(self, tmp_path, rng):
        ref = quality.DataProfile.from_matrix(
            np.column_stack([
                rng.integers(0, 50, 500),
                rng.integers(20, 400, 500),
                rng.integers(0, 30, 500),
                rng.uniform(0.5, 1.5, 500),
            ]).astype(np.float64),
            list(ht.FEATURE_COLS),
        )
        monitor = quality.DriftMonitor(ref, window_rows=10, trip_after=1)
        incoming, ex, ckpt, fw = _firewalled_stream(tmp_path, monitor=monitor)
        write_csv(_event_table(30), str(incoming / "a.csv"))
        ex.run_once()
        assert "stream.drift_psi" in ex.metrics.gauges
        assert monitor.snapshot()["windows"] >= 1


# ============================================================== data faults
@pytest.mark.chaos
class TestDataFaultKinds:
    """The four data-corruption kinds drive the firewall deterministically;
    parametrized ids land in tools/run_chaos.sh's per-site table."""

    def _run(self, tmp_path, plan, n=40):
        incoming, ex, ckpt, fw = _firewalled_stream(tmp_path)
        write_csv(_event_table(n), str(incoming / "a.csv"))
        with faults.active(plan):
            info = ex.run_once()
        return info, ex, ckpt, plan

    @pytest.mark.parametrize("kind", ["data-mangle_field"])
    def test_mangle_field_rows_quarantined(self, tmp_path, kind):
        plan = faults.FaultPlan(seed=3).mangle_fields(
            "ingest.csv_text", rate=0.2,
            columns=("admission_count", "current_occupancy"), times=None,
        )
        info, ex, ckpt, plan = self._run(tmp_path, plan)
        assert plan.fired("ingest.csv_text") == 1
        assert info.status == "ok"
        assert info.num_rejected_rows > 0
        hist = ckpt.row_reason_histogram()
        assert set(hist) <= {"parse:admission_count", "parse:current_occupancy"}
        assert info.num_appended_rows + info.num_rejected_rows == 40

    @pytest.mark.parametrize("kind", ["data-shuffle_columns"])
    def test_shuffle_columns_reconciled_lossless(self, tmp_path, kind):
        plan = faults.FaultPlan(seed=5).shuffle_columns("ingest.csv_text")
        info, ex, ckpt, plan = self._run(tmp_path, plan)
        assert plan.fired("ingest.csv_text") == 1
        assert info.num_rejected_rows == 0
        assert info.num_appended_rows == 40          # nothing lost
        assert info.num_drift_events > 0             # but it was seen
        snap = ex.sink.read()
        np.testing.assert_array_equal(
            np.sort(snap.column("admission_count")), np.arange(40)
        )

    @pytest.mark.parametrize("kind", ["data-unit_scale"])
    def test_unit_scale_caught_by_range(self, tmp_path, kind):
        # LOS 4.0 days → ×1000 = 4000, far past the 365-day ceiling
        plan = faults.FaultPlan(seed=7).unit_scale(
            "ingest.csv_text", column="length_of_stay", factor=1000.0
        )
        info, ex, ckpt, plan = self._run(tmp_path, plan)
        assert plan.fired("ingest.csv_text") == 1
        assert info.num_rejected_rows == 40          # every row out of range
        assert ckpt.row_reason_histogram() == {"range:length_of_stay": 40}
        assert info.num_appended_rows == 0

    @pytest.mark.parametrize("kind", ["data-nan_burst"])
    def test_nan_burst_accepted_for_imputation(self, tmp_path, kind):
        plan = faults.FaultPlan(seed=9).nan_burst(
            "ingest.csv_text", column="current_occupancy", length=8
        )
        info, ex, ckpt, plan = self._run(tmp_path, plan)
        assert plan.fired("ingest.csv_text") == 1
        # missing ≠ wrong: the burst is accepted as nulls, imputer's job
        assert info.num_rejected_rows == 0
        occ = ex.sink.read().column("current_occupancy").astype(np.float64)
        assert int(np.isnan(occ).sum()) == 8

    @pytest.mark.parametrize("kind", ["data-deterministic_replay"])
    def test_corruption_is_deterministic(self, tmp_path, kind):
        """Same plan seed ⇒ byte-identical dirty text ⇒ identical rejects."""
        write_csv(_event_table(30), str(tmp_path / "a.csv"))
        raw = open(str(tmp_path / "a.csv")).read()
        outs = []
        for _ in range(2):
            plan = faults.FaultPlan(seed=11).mangle_fields(
                "ingest.csv_text", rate=0.3, times=None
            )
            with faults.active(plan):
                outs.append(faults.corrupt_data("ingest.csv_text", raw))
        assert outs[0] == outs[1] and outs[0] != raw

    @pytest.mark.parametrize("kind", ["data-retry_then_salvage"])
    def test_source_retry_composes_with_firewall(self, tmp_path, kind):
        """Transient IO faults retry; the salvage read still fires after."""
        plan = (
            faults.FaultPlan(seed=13)
            .fail("source.read_file", times=2)
            .mangle_fields(
                "ingest.csv_text", rate=0.2, columns=("admission_count",),
                times=None,
            )
        )
        info, ex, ckpt, plan = self._run(tmp_path, plan)
        assert plan.fired("source.read_file") == 2
        assert ex.source.retries == 2
        assert info.status == "ok"
        assert info.num_appended_rows + info.num_rejected_rows == 40


# ============================================================ model_io profile
class TestModelIoProfile:
    def test_save_model_with_profile_roundtrip(self, tmp_path, rng):
        prof = quality.DataProfile.from_matrix(
            rng.normal(0, 1, (200, 2)), ["a", "b"]
        )
        p = str(tmp_path / "m")
        save_model(
            p, "KMeansModel", {"k": 1},
            {"cluster_centers": np.zeros((1, 2))},
            data_profile=prof.to_dict(),
        )
        loaded = load_data_profile(p)
        assert loaded == json.loads(json.dumps(prof.to_dict()))

    def test_attach_profile_after_save(self, tmp_path, rng):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models import (
            LinearRegression,
        )

        x = rng.normal(0, 1, (64, 3)).astype(np.float32)
        y = x.sum(axis=1)
        m = LinearRegression().fit((x, y))
        p = str(tmp_path / "m")
        m.save(p)
        assert load_data_profile(p) is None
        prof = quality.DataProfile.from_matrix(x, ["a", "b", "c"])
        attach_data_profile(p, prof.to_dict())
        assert load_data_profile(p) is not None
        # the artifact still loads as a model (metadata rewrite was clean)
        assert ht.load_model(p).predict(x[:2]).shape == (2,)


# =============================================================== serve guards
class TestServeGuards:
    BUCKETS = (1, 2, 4)

    def _server(self, tmp_path, rng, policy, window=16, trip_after=2):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models import (
            LinearRegression,
        )
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve import (
            InferenceServer,
        )

        x = rng.normal(0, 1, (512, 3)).astype(np.float32)
        y = x @ np.array([1.0, 2.0, 3.0], np.float32)
        model = LinearRegression().fit((x, y))
        prof = quality.DataProfile.from_matrix(x, ["a", "b", "c"])
        srv = InferenceServer(breaker_recovery_s=60.0)
        srv.add_model(
            "m", model, buckets=self.BUCKETS,
            fallback=lambda rows: np.zeros(rows.shape[0], np.float32),
            data_profile=prof.to_dict(), input_policy=policy,
            drift_window_rows=window, drift_trip_after=trip_after,
        )
        return srv, x

    def test_impute_policy_repairs_and_counts(self, tmp_path, rng):
        srv, x = self._server(tmp_path, rng, "impute")
        with srv:
            r = srv.predict("m", np.array([np.nan, 0.0, 0.0], np.float32))
            assert r.ok and np.isfinite(r.value).all()
            assert srv.metrics.registry.counters["serve.inputs_imputed"] == 1
            h = srv.health()
            assert h["inputs_imputed"] == 1

    def test_reject_policy_answers_invalid_input(self, tmp_path, rng):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve import (
            STATUS_INVALID_INPUT,
        )

        srv, x = self._server(tmp_path, rng, "reject")
        with srv:
            r = srv.predict("m", np.array([np.inf, 0.0, 0.0], np.float32))
            assert r.status == STATUS_INVALID_INPUT
            assert r.value is None and not r.degraded
            assert "non_finite:a" in r.detail
            far = srv.predict("m", np.array([1e9, 0.0, 0.0], np.float32))
            assert far.status == STATUS_INVALID_INPUT
            assert "out_of_range:a" in far.detail
            ok = srv.predict("m", x[0])
            assert ok.ok

    def test_sustained_drift_trips_to_degraded_answers(self, tmp_path, rng):
        srv, x = self._server(tmp_path, rng, None, window=16, trip_after=2)
        with srv:
            for i in range(40):  # clean warm traffic
                assert srv.predict("m", x[i]).ok
            assert srv.health()["status"] == "ok"
            degraded = 0
            for i in range(64):  # unit-shifted traffic
                r = srv.predict("m", x[i] * 200.0)
                degraded += bool(r.degraded)
            h = srv.health()
            assert h["drift_trips"] >= 1
            assert h["status"] == "degraded"
            assert h["drift"]["m"]["drifting"]
            assert h["drift"]["m"]["max_psi"] > quality.PSI_DRIFT
            assert h["breakers"]["m"]["state"] != "closed"
            assert h["breakers"]["m"]["tripped_count"] >= 1
            assert "drift" in h["breakers"]["m"]["last_trip_reason"]
            assert degraded > 0  # fallback answered, nobody got silence

    def test_constant_training_column_tolerates_epsilon(self, rng):
        """A feature constant at fit time must not flag epsilon-different
        live values (span floors at half the value's scale)."""
        prof = quality.DataProfile.from_matrix(
            np.column_stack([np.full(100, 5.0), rng.normal(0, 1, 100)]),
            ["const", "varied"],
        )
        g = quality.InputGuard(prof, policy="reject")
        _, n_bad, _ = g.inspect(np.array([5.0001, 0.0]))
        assert n_bad == 0
        _, n_bad, reasons = g.inspect(np.array([100.0, 0.0]))
        assert n_bad == 1 and reasons == ["out_of_range:const"]

    def test_one_hot_window_does_not_degrade_health(self, tmp_path, rng):
        """A single traffic burst shows as per-model 'drifting' but must
        not read as a degraded server — only sustained drift (via the
        breaker trip) changes the status an orchestrator probes."""
        srv, x = self._server(tmp_path, rng, None, window=16, trip_after=50)
        with srv:
            for i in range(20):  # exactly one hot window, never trips
                srv.predict("m", x[i] * 200.0)
            h = srv.health()
            assert h["drift"]["m"]["drifting"]
            assert h["drift_trips"] == 0
            assert h["status"] == "ok"

    def test_clean_traffic_never_trips(self, tmp_path, rng):
        srv, x = self._server(tmp_path, rng, "impute")
        with srv:
            for i in range(80):
                assert srv.predict("m", x[i]).ok
            h = srv.health()
            assert h["drift_trips"] == 0 and h["status"] == "ok"


# ====================================================== feature edge cases
class TestFeatureEdgeCases:
    """The inputs the firewall routes downstream: all-NaN column, constant
    column, single-row batch (satellite: features/imputer.py +
    features/robust.py)."""

    def test_imputer_all_nan_column_raises_clearly(self):
        t = ht.Table.from_dict({"a": np.full(4, np.nan)})
        with pytest.raises(ValueError, match="no non-missing values"):
            ht.Imputer(input_cols=["a"]).fit(t)

    def test_imputer_constant_column(self):
        t = ht.Table.from_dict({"a": np.array([7.0, 7.0, np.nan, 7.0])})
        m = ht.Imputer(input_cols=["a"], strategy="median").fit(t)
        assert m.surrogates == (7.0,)
        out = m.transform(t)
        np.testing.assert_array_equal(out.column("a"), np.full(4, 7.0))

    def test_imputer_single_row(self):
        t = ht.Table.from_dict({"a": np.array([3.0])})
        m = ht.Imputer(input_cols=["a"]).fit(t)
        assert m.surrogates == (3.0,)

    def test_robust_scaler_constant_column_unscaled(self):
        x = np.column_stack([np.full(20, 5.0), np.arange(20.0)])
        m = ht.RobustScaler(with_centering=True).fit(x)
        out = np.asarray(m.transform(x))
        assert np.isfinite(out).all()
        np.testing.assert_allclose(out[:, 0], 0.0)  # centered, iqr-guarded

    def test_robust_scaler_all_nan_column(self):
        x = np.column_stack([np.full(10, np.nan), np.arange(10.0)])
        m = ht.RobustScaler().fit(x)
        assert np.isfinite(m.median).all() and np.isfinite(m.iqr).all()
        out = np.asarray(m.transform(x))
        assert np.isfinite(out[:, 1]).all()

    def test_robust_scaler_single_row(self):
        x = np.array([[2.0, 4.0]])
        m = ht.RobustScaler(with_centering=True).fit(x)
        out = np.asarray(m.transform(x))
        assert np.isfinite(out).all()
        np.testing.assert_allclose(out, 0.0)  # x − median(x) = 0, iqr 0

    def test_maxabs_scaler_partial_nan_column(self):
        x = np.array([[1.0, -8.0], [np.nan, 2.0], [0.5, 4.0]])
        m = ht.MaxAbsScaler().fit(x)
        np.testing.assert_allclose(m.max_abs, [1.0, 8.0])

    def test_maxabs_scaler_partial_nan_device_path(self):
        """The DeviceDataset fit must match the host path — a NaN must
        not collapse a column's scale through the device reduction."""
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel import (
            device_dataset,
        )

        x = np.array([[1.0, 5.0], [2.0, np.nan], [3.0, 7.0]])
        m = ht.MaxAbsScaler().fit(device_dataset(x, None))
        np.testing.assert_allclose(m.max_abs, [3.0, 7.0])

    def test_maxabs_scaler_all_nan_column(self):
        x = np.column_stack([np.full(5, np.nan), np.arange(5.0)])
        m = ht.MaxAbsScaler().fit(x)
        assert np.isfinite(m.max_abs).all()
        out = np.asarray(m.transform(x))
        assert np.isfinite(out[:, 1]).all()


# ==================================================================== soak
class TestDirtyDataSoak:
    """Acceptance scenario: 5% injected corrupt rows + one schema-drifted
    hospital → the ingest→train→serve run completes with zero unhandled
    exceptions, quarantines EXACTLY the bad rows with reasons, and the
    trained model matches the clean-data run."""

    N_PER_FILE = 40
    N_FILES = 4            # clean hospitals
    N_DRIFTED = 20         # rows from the schema-drifted hospital

    def _write_fleet(self, incoming):
        expected_parse = set()   # (file, line_no)
        expected_range = set()   # admission_count marker values
        total_clean = 0
        for f in range(self.N_FILES):
            t = _event_table(
                self.N_PER_FILE, hospital=f"H{f:02d}",
                start="2025-03-31T22:00:00",
            )
            p = str(incoming / f"h{f:02d}.csv")
            write_csv(t, p)
            lines = open(p).read().rstrip("\n").split("\n")
            # 5% dirty: one garbage field + one out-of-range LOS per file
            garbage_ln = 3 + f          # 1-based line in file
            lines[garbage_ln - 1] = (
                f"H{f:02d},2025-03-31 22:30:00,NOT_A_NUMBER,100,5,1.0,4.0"
            )
            expected_parse.add((f"h{f:02d}.csv", garbage_ln))
            marker = 9000 + f
            range_ln = 10 + f
            lines[range_ln - 1] = (
                f"H{f:02d},2025-03-31 22:31:00,{marker},100,5,1.0,500.0"
            )
            expected_range.add(float(marker))
            open(p, "w").write("\n".join(lines) + "\n")
            total_clean += self.N_PER_FILE - 2
        # the drifted hospital: renamed label + reordered columns, clean data
        rows = "\n".join(
            f"2025-03-31 22:00:{i:02d},H99,{i},150,6,1.1,5.0"
            for i in range(self.N_DRIFTED)
        )
        (incoming / "h99.csv").write_text(
            "event_time,hospital_id,admission_count,current_occupancy,"
            "emergency_visits,seasonality_index,los\n" + rows + "\n"
        )
        total_clean += self.N_DRIFTED
        return expected_parse, expected_range, total_clean

    def test_soak_ingest_train_serve(self, tmp_path, rng):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models import (
            LinearRegression,
        )
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve import (
            InferenceServer,
        )

        incoming, ex, ckpt, fw = _firewalled_stream(tmp_path)
        expected_parse, expected_range, total_clean = self._write_fleet(incoming)

        # ---- ingest: must complete, no unhandled exceptions, no batch loss
        # (all files exist before the first poll ⇒ one micro-batch)
        info = ex.run_once()
        assert info is not None and info.status == "ok"
        assert ex.run_once() is None  # fully drained
        n_expected_bad = len(expected_parse) + len(expected_range)
        assert info.num_rejected_rows == n_expected_bad

        # ---- quarantined EXACTLY the bad rows, with reasons
        recs = [r for e in ckpt.quarantined_rows() for r in e["rejects"]]
        got_parse = {
            (os.path.basename(r["context"]), r["line_no"])
            for r in recs if "line_no" in r and "raw" in r
        }
        got_range = {
            float(r["row"]["admission_count"])
            for r in recs if "row" in r
        }
        assert got_parse == expected_parse
        assert got_range == expected_range
        hist = ckpt.row_reason_histogram()
        assert hist["parse:admission_count"] == len(expected_parse)
        assert hist["range:length_of_stay"] == len(expected_range)

        # ---- the sink holds every good row (drifted hospital included)
        snap = ex.sink.read()
        assert snap.num_rows == total_clean
        assert (snap.column("hospital_id") == "H99").sum() == self.N_DRIFTED
        assert ex.metrics.counters["stream.drift_events"] > 0

        # ---- train on accepted rows == train on clean data
        feats = list(ht.FEATURE_COLS)
        dirty_run = snap.na_drop(feats + [ht.LABEL_COL])
        x = dirty_run.numeric_matrix(feats).astype(np.float32)
        y = dirty_run.column(ht.LABEL_COL).astype(np.float32)
        model = LinearRegression().fit((x, y))

        # clean-data run: the SAME fleet with no corruption injected —
        # all 40 rows per hospital plus the (clean-content) drifted one
        n99 = self.N_DRIFTED
        h99 = ht.Table.from_dict(
            {
                "hospital_id": np.array(["H99"] * n99, dtype=object),
                "event_time": np.datetime64("2025-03-31T22:00:00")
                + np.arange(n99).astype("timedelta64[s]"),
                "admission_count": np.arange(n99),
                "current_occupancy": np.full(n99, 150),
                "emergency_visits": np.full(n99, 6),
                "seasonality_index": np.full(n99, 1.1),
                "length_of_stay": np.full(n99, 5.0),
            },
            SCHEMA,
        )
        clean = ht.Table.concat(
            [
                _event_table(self.N_PER_FILE, hospital=f"H{f:02d}")
                for f in range(self.N_FILES)
            ]
            + [h99]
        )
        preds_dirty = np.asarray(model.predict(x[:64]))
        xc = clean.numeric_matrix(feats).astype(np.float32)
        yc = clean.column(ht.LABEL_COL).astype(np.float32)
        clean_model = LinearRegression().fit((xc, yc))
        preds_clean = np.asarray(clean_model.predict(x[:64]))
        # the runs differ by only the 8 quarantined rows (of 180) ⇒ the
        # trained models must agree within a small fraction of the label
        # spread
        rmse = float(np.sqrt(np.mean((preds_dirty - preds_clean) ** 2)))
        spread = float(np.std(yc)) or 1.0
        assert rmse / spread < 0.35

        # ---- serve: profile armed, drifted feed trips health
        prof = quality.DataProfile.from_matrix(
            x.astype(np.float64), feats
        )
        srv = InferenceServer(
            ingest_metrics=ex.metrics, breaker_recovery_s=60.0
        )
        srv.add_model(
            "los", model, buckets=(1, 2, 4),
            fallback=lambda rows: np.full(rows.shape[0], float(y.mean()), np.float32),
            data_profile=prof.to_dict(), input_policy="impute",
            drift_window_rows=16, drift_trip_after=2,
        )
        with srv:
            assert srv.predict("los", x[0]).ok
            h0 = srv.health()
            assert h0["quarantined_rows"] == n_expected_bad  # ingest visible
            for i in range(64):
                srv.predict("los", x[i % 32] * 500.0)
            h = srv.health()
            assert h["drift_trips"] >= 1 and h["status"] == "degraded"
