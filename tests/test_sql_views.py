"""Incremental streaming SQL — materialized views (ISSUE 14).

Covers the delta-maintenance contract end to end: per-batch parity of
view state vs full recompute (aggregate partials + row-level deltas,
targeted and fuzzed), exactly-once maintenance across replays and
repeated hooks, watermark-aware retraction and sealed-prefix compaction,
the loud full-recompute fallback for non-incrementalizable plans, the
dispatcher's fingerprint-matched ``route="view"`` serve, per-clause
incremental decisions in explain, and — chaos-marked — kill-and-resume
at the ``sql.view.maintain`` boundary leaving view state bit-identical
to an uninterrupted run, plus the replayed-batch double-apply probe.
"""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core import (
    sql as core_sql,
    sql_fuzz,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.sql import (
    execute,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.sql_plan import (
    plan_query,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.sql_parse import (
    parse,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.sql_views import (
    DECISION_INCREMENTAL,
    FULL_COMPILE_DISABLED,
    FULL_LIMIT,
    FULL_NOT_COMPILED,
    FULL_WINDOW,
    ViewRegistry,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import (
    write_csv,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.obs.registry import (
    global_registry,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming import (
    FileStreamSource,
    StreamCheckpoint,
    StreamExecution,
    UnboundedTable,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming.watermark import (
    WatermarkTracker,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils import faults

pytestmark = pytest.mark.fast

AGG_Q = (
    "SELECT i1, count(*) AS c, sum(f1) AS s, avg(f1) AS a,"
    " min(f1) AS lo, max(f1) AS hi FROM events GROUP BY i1"
)
ROW_Q = "SELECT f1, i1, abs(f1) AS af FROM events WHERE i1 >= 1"


def _batch(rng, n, null_rate=0.15):
    f1 = rng.normal(size=n) * 10
    if n:
        f1[rng.random(n) < null_rate] = np.nan
    t1 = (
        np.datetime64("2025-03-31T22:00:00")
        + rng.integers(0, 7200, n).astype("timedelta64[s]")
    ).astype("datetime64[ns]")
    return ht.Table.from_dict(
        {"f1": f1, "i1": rng.integers(-2, 4, n), "t1": t1}
    )


def _mk_sink(tmp_path, rng):
    return UnboundedTable(
        str(tmp_path / "table"), _batch(rng, 1).schema, name="events"
    )


def _full(query, sink, upto=None):
    return execute(
        query, lambda _n: sink.read(upto_batch_id=upto), mode="interpret"
    )


def _assert_parity(query, sink, view, upto=None, ctx=""):
    bad = sql_fuzz.compare_tables(
        _full(query, sink, upto), view.read(upto_batch_id=upto)
    )
    assert bad is None, f"{ctx}: {bad}"


def _assert_bit_identical(a, b):
    assert list(a.columns) == list(b.columns)
    assert len(a) == len(b)
    for c in a.columns:
        va, vb = a.column(c), b.column(c)
        assert va.dtype == vb.dtype, c
        if va.dtype == object:
            assert list(va) == list(vb), c
        else:
            assert va.tobytes() == vb.tobytes(), c


# ============================================================ parity
def test_aggregate_view_parity_over_batches(tmp_path):
    """Mergeable partials fold to exactly what a full recompute returns,
    after EVERY commit — null group keys, all-null groups, timestamp
    keys included."""
    rng = np.random.default_rng(0)
    sink = _mk_sink(tmp_path, rng)
    reg = ViewRegistry()
    view = reg.register("agg", AGG_Q, sink)
    by_ts = reg.register(
        "by_ts",
        "SELECT t1, count(f1) AS c, avg(f1) AS a FROM events GROUP BY t1",
        sink,
    )
    for bid in range(5):
        sink.append_batch(_batch(rng, int(rng.integers(0, 180))), bid)
        reg.maintain(sink, bid)
        _assert_parity(AGG_Q, sink, view, ctx=f"agg batch {bid}")
        _assert_parity(
            "SELECT t1, count(f1) AS c, avg(f1) AS a FROM events "
            "GROUP BY t1",
            sink, by_ts, ctx=f"ts batch {bid}",
        )
    assert view.describe()["incremental"]
    assert view.describe()["last_applied"] == 4


def test_whole_table_aggregate_and_empty_sink(tmp_path):
    rng = np.random.default_rng(1)
    sink = _mk_sink(tmp_path, rng)
    reg = ViewRegistry()
    q = "SELECT count(*) AS c, sum(f1) AS s, min(f1) AS lo FROM events"
    view = reg.register("tot", q, sink)
    _assert_parity(q, sink, view, ctx="zero batches")
    sink.append_batch(_batch(rng, 0), 0)  # an EMPTY committed batch
    reg.maintain(sink, 0)
    _assert_parity(q, sink, view, ctx="empty batch")
    sink.append_batch(_batch(rng, 120), 1)
    reg.maintain(sink, 1)
    _assert_parity(q, sink, view, ctx="data batch")


def test_rowlevel_view_parity_and_pinned_read(tmp_path):
    """Row-level deltas concat to the full recompute's rows, and the
    pinned read (the lifecycle retrain's journaled snapshot id) serves
    batches ≤ the pin — the ingest→retrain read path."""
    rng = np.random.default_rng(2)
    sink = _mk_sink(tmp_path, rng)
    reg = ViewRegistry()
    view = reg.register("win", ROW_Q, sink)
    for bid in range(4):
        sink.append_batch(_batch(rng, 150), bid)
        reg.maintain(sink, bid)
        _assert_parity(ROW_Q, sink, view, ctx=f"batch {bid}")
    for upto in (0, 2):
        _assert_parity(ROW_Q, sink, view, upto=upto, ctx=f"pinned {upto}")


def test_fuzz_incremental_leg():
    """ISSUE 14 satellite: random mergeable-subset queries over random
    batch/replay sequences — view state == full recompute after every
    commit (shrunk repro on failure)."""
    failures = sql_fuzz.run_fuzz_incremental(n_queries=5, seed=0)
    assert failures == [], f"incremental view parity failures: {failures}"


@pytest.mark.slow
def test_fuzz_incremental_deep():
    failures = sql_fuzz.run_fuzz_incremental(n_queries=40, seed=11)
    assert failures == [], f"incremental view parity failures: {failures}"


# ===================================================== exactly-once
def test_maintain_is_idempotent(tmp_path):
    """Re-running the hook (a replayed commit notification) never
    double-applies a delta — the high-water mark skips it."""
    rng = np.random.default_rng(3)
    sink = _mk_sink(tmp_path, rng)
    reg = ViewRegistry()
    view = reg.register("agg", AGG_Q, sink)
    sink.append_batch(_batch(rng, 100), 0)
    for _ in range(5):
        reg.maintain(sink, 0)
    assert view.applied_rows() == 100
    _assert_parity(AGG_Q, sink, view)


def test_late_row_retraction_parity(tmp_path):
    """A replayed batch ('later replay wins' in the commit log) with
    late rows is RETRACTED and re-applied: old delta dropped, new one
    folded, answer equal to a full recompute — including a replay with
    the SAME row count (detected by part-file bytes, not just the
    commit entry)."""
    rng = np.random.default_rng(4)
    sink = _mk_sink(tmp_path, rng)
    reg = ViewRegistry()
    agg = reg.register("agg", AGG_Q, sink)
    row = reg.register("win", ROW_Q, sink)
    for bid in range(3):
        sink.append_batch(_batch(rng, 80), bid)
        reg.maintain(sink, bid)
    before = global_registry().counters.get("sql.view.retractions", 0)
    # replay batch 1 with a different row count
    sink.append_batch(_batch(rng, 50), 1)
    reg.maintain(sink, 1)
    _assert_parity(AGG_Q, sink, agg, ctx="replay (count change)")
    _assert_parity(ROW_Q, sink, row, ctx="replay (count change)")
    # replay batch 1 again: SAME row count, different values
    sink.append_batch(_batch(rng, 50), 1)
    reg.maintain(sink, 1)
    _assert_parity(AGG_Q, sink, agg, ctx="replay (same count)")
    _assert_parity(ROW_Q, sink, row, ctx="replay (same count)")
    assert global_registry().counters.get("sql.view.retractions", 0) > before
    assert agg.applied_rows() == 80 + 50 + 80


def test_watermark_compaction_seals_prefix(tmp_path):
    """With an event-time watermark, aggregate partials wholly below it
    compact into one base partial (bounded state), answers stay exact,
    and a replay UNDER the seal forces a loud full rebuild that is still
    correct — the retraction-vs-watermark contract."""
    rng = np.random.default_rng(5)
    base = np.datetime64("2025-03-31T00:00:00")

    def timed_batch(b, n=60):
        t = (
            base + (b * 3600 + rng.integers(0, 3600, n)).astype(
                "timedelta64[s]"
            )
        ).astype("datetime64[ns]")
        return ht.Table.from_dict(
            {"f1": rng.normal(size=n), "i1": rng.integers(0, 4, n), "t1": t}
        )

    sink = UnboundedTable(
        str(tmp_path / "table"), timed_batch(0).schema, name="events"
    )
    wt = WatermarkTracker("t1", 90.0)  # 1.5 h: batches seal 2-3 behind
    reg = ViewRegistry()
    q = "SELECT i1, count(*) AS c, sum(f1) AS s FROM events GROUP BY i1"
    view = reg.register("agg", q, sink, watermark=wt)
    for bid in range(8):
        tb = timed_batch(bid)
        wt.filter_late(tb)
        sink.append_batch(tb, bid)
        reg.maintain(sink, bid)
        _assert_parity(q, sink, view, ctx=f"batch {bid}")
    d = view.describe()
    assert d["compacted_upto"] is not None and d["compacted_upto"] >= 3
    assert d["batches_retained"] < 8
    # replay a SEALED batch: individually retained state is gone — the
    # view must rebuild loudly and still answer correctly
    rebuilds = global_registry().counters.get("sql.view.rebuilds", 0)
    sink.append_batch(timed_batch(0, n=30), 0)
    reg.maintain(sink, 0)
    _assert_parity(q, sink, view, ctx="sealed replay")
    assert global_registry().counters.get("sql.view.rebuilds", 0) > rebuilds


# ============================================== fallback + dispatch
def test_non_incremental_plan_falls_back_loudly(tmp_path):
    """Window functions / LIMIT / interpreter-fallback plans register
    but serve FULL RECOMPUTES — correct answers, visible decisions, and
    the ``sql.view.full_recompute`` counter moving."""
    rng = np.random.default_rng(6)
    sink = _mk_sink(tmp_path, rng)
    reg = ViewRegistry()
    q = "SELECT f1, sum(f1) OVER (PARTITION BY i1) AS w FROM events"
    view = reg.register("windowed", q, sink)
    assert not view.describe()["incremental"]
    assert FULL_WINDOW in view.describe()["decisions"]
    sink.append_batch(_batch(rng, 90), 0)
    before = global_registry().counters.get("sql.view.full_recompute", 0)
    _assert_parity(q, sink, view, ctx="window fallback")
    assert global_registry().counters.get("sql.view.full_recompute", 0) > before

    lim = reg.register("limited", "SELECT f1 FROM events LIMIT 3", sink)
    assert FULL_LIMIT in lim.describe()["decisions"]
    got = lim.read()
    assert len(got) == min(3, len(sink.read()))

    tail = reg.register(
        "ordered", "SELECT f1 FROM events ORDER BY f1", sink
    )
    assert FULL_NOT_COMPILED in tail.describe()["decisions"]
    bad = sql_fuzz.compare_tables(_full(
        "SELECT f1 FROM events ORDER BY f1", sink), tail.read())
    assert bad is None


def test_session_sql_serves_from_matching_view(tmp_path):
    """The dispatcher answers from a fresh fingerprint-matched view
    (route "view", hit counter); a non-matching plan stays compiled and
    counts a miss; interpret/compile modes bypass views entirely."""
    rng = np.random.default_rng(7)
    s = ht.Session.builder.app_name("views-serve-test").get_or_create()
    try:
        sink = _mk_sink(tmp_path, rng)
        s.register_table("events", sink)
        sink.append_batch(_batch(rng, 120), 0)
        s.create_view("agg", AGG_Q)
        sink.append_batch(_batch(rng, 90), 1)  # view is now stale…
        g = global_registry()
        hits = g.counters.get("sql.view.hit", 0)
        out = s.sql(AGG_Q)  # …but serve_for refreshes before matching
        assert core_sql.last_dispatch().route == "view"
        assert g.counters.get("sql.view.hit", 0) == hits + 1
        bad = sql_fuzz.compare_tables(_full(AGG_Q, sink), out)
        assert bad is None
        misses = g.counters.get("sql.view.miss", 0)
        s.sql("SELECT i1, count(*) AS c FROM events GROUP BY i1")
        assert core_sql.last_dispatch().route == "compiled"
        assert g.counters.get("sql.view.miss", 0) == misses + 1
        execute(AGG_Q, s.table, mode="compile")  # parity tooling path
        assert core_sql.last_dispatch().route == "compiled"
    finally:
        s.stop()


def test_create_view_rejects_plain_tables_and_joins(tmp_path):
    rng = np.random.default_rng(9)
    s = ht.Session.builder.app_name("views-reject-test").get_or_create()
    try:
        s.register_table("plain", ht.Table.from_dict({"x": [1.0, 2.0]}))
        with pytest.raises(ValueError, match="UnboundedTable"):
            s.create_view("v", "SELECT x FROM plain")
        with pytest.raises(ValueError, match="single-table"):
            s.create_view(
                "v2", "SELECT x FROM (SELECT x FROM plain) q"
            )
        # a JOIN parses with a plain single-name FROM table, so it used
        # to register fine — and then KeyError on EVERY read when the
        # resolver met the other table.  Must fail at registration.
        sink = _mk_sink(tmp_path, rng)
        s.register_table("events", sink)
        with pytest.raises(ValueError, match="single-table"):
            s.create_view(
                "v3",
                "SELECT e.f1 FROM events e JOIN plain p ON e.i1 = p.x",
            )
        with pytest.raises(ValueError, match="single-table"):
            ViewRegistry().register(
                "v4",
                "SELECT e.f1 FROM events e JOIN plain p ON e.i1 = p.x",
                sink,
            )
    finally:
        s.stop()


def test_explain_reports_incremental_decision_per_node(tmp_path):
    """Satellite 1: ``sql_explain`` / ``LogicalPlan.explain`` carry the
    per-clause incremental decision, reason-constant discipline."""
    rng = np.random.default_rng(8)
    s = ht.Session.builder.app_name("views-explain-test").get_or_create()
    try:
        sink = _mk_sink(tmp_path, rng)
        s.register_table("events", sink)
        sink.append_batch(_batch(rng, 50), 0)

        info = s.sql_explain(AGG_Q)
        assert info["view_maintenance"] == "incremental"
        assert all(
            n["incremental"] == DECISION_INCREMENTAL for n in info["nodes"]
        )

        info = s.sql_explain(
            "SELECT f1, count(*) OVER (PARTITION BY i1) AS c FROM events"
        )
        assert info["view_maintenance"] == [FULL_WINDOW]
        assert {n["incremental"] for n in info["nodes"]} == {
            DECISION_INCREMENTAL, FULL_WINDOW,
        }

        info = s.sql_explain("SELECT f1 FROM events LIMIT 2")
        assert info["view_maintenance"] == [FULL_LIMIT]

        info = s.sql_explain("SELECT f1 FROM events ORDER BY f1")
        assert FULL_NOT_COMPILED in info["view_maintenance"]

        plan = plan_query(parse(AGG_Q), s.table)
        nodes = plan.explain()
        assert [n["op"] for n in nodes] == ["scan", "aggregate"]
        assert all(n["incremental"] == DECISION_INCREMENTAL for n in nodes)
    finally:
        s.stop()


# ================================================ stream integration
def _event_csv(path, start_minute, n):
    base = np.datetime64("2025-03-31T22:00:00") + np.timedelta64(
        start_minute, "m"
    )
    t = ht.Table.from_dict(
        {
            "hospital_id": np.array(["H01"] * n, dtype=object),
            "event_time": base + np.arange(n).astype("timedelta64[s]"),
            "admission_count": np.arange(n),
            "current_occupancy": np.full(n, 100),
            "emergency_visits": np.full(n, 5),
            "seasonality_index": np.full(n, 1.0),
            "length_of_stay": np.full(n, 4.0),
        },
        ht.hospital_event_schema(),
    )
    write_csv(t, path)
    return t


STATS_Q = (
    "SELECT count(*) AS c, sum(admission_count) AS adm,"
    " avg(length_of_stay) AS alos FROM events"
)


def _mk_stream(tmp_path, views):
    incoming = tmp_path / "incoming"
    incoming.mkdir(exist_ok=True)
    return incoming, StreamExecution(
        source=FileStreamSource(str(incoming), ht.hospital_event_schema()),
        sink=UnboundedTable(
            str(tmp_path / "table"), ht.hospital_event_schema()
        ),
        checkpoint=StreamCheckpoint(str(tmp_path / "ckpt")),
        views=views,
    )


def test_stream_commit_path_maintains_views(tmp_path):
    """The driver's commit hook folds each committed batch into every
    registered view — after ``run_once`` the view is already current
    (no lazy catch-up left to do)."""
    reg = ViewRegistry()
    incoming, exec_ = _mk_stream(tmp_path, reg)
    _event_csv(str(incoming / "a.csv"), 0, 30)
    assert exec_.run_once().num_appended_rows == 30
    view = reg.register("stats", STATS_Q, exec_.sink)
    _event_csv(str(incoming / "b.csv"), 1, 20)
    assert exec_.run_once().num_appended_rows == 20
    assert view.applied_rows() == 50  # maintained ON the commit path
    _assert_parity(STATS_Q, exec_.sink, view)


def test_session_streaming_wires_views(tmp_path):
    """The fluent Session surface: write_stream hands the session's
    registry to the driver, so create_view + process_available leaves a
    current view that Session.sql serves from."""
    s = ht.Session.builder.app_name("views-stream-test").get_or_create()
    try:
        incoming = tmp_path / "incoming"
        incoming.mkdir()
        sdf = s.read_stream.schema(ht.hospital_event_schema()).csv(
            str(incoming)
        )
        q = sdf.write_stream.option(
            "checkpointLocation", str(tmp_path / "ckpt")
        ).table("events")
        _event_csv(str(incoming / "a.csv"), 0, 25)
        q.process_available()
        view = s.create_view("stats", STATS_Q)
        _event_csv(str(incoming / "b.csv"), 2, 35)
        q.process_available()
        assert view.applied_rows() == 60
        out = s.sql(STATS_Q)
        assert core_sql.last_dispatch().route == "view"
        assert int(out.column("c")[0]) == 60
    finally:
        s.stop()


# ========================================================== chaos
@pytest.mark.chaos
@pytest.mark.parametrize("site", ["sql.view.maintain"])
def test_kill_at_view_maintain_resumes_bit_identical(tmp_path, site):
    """Kill view maintenance right after a batch's commit; the restarted
    registry (fresh objects over the same dirs) must catch up from the
    commit log and end bit-identical — column for column, byte for byte
    — to an uninterrupted run over the same input."""

    def run(root, kill_at_batch=None):
        reg = ViewRegistry()
        incoming, exec_ = _mk_stream(root, reg)
        view = reg.register("stats", STATS_Q, exec_.sink)
        for b in range(4):
            _event_csv(str(incoming / f"f{b}.csv"), b, 20 + b)
            if b == kill_at_batch:
                plan = faults.FaultPlan().crash(site)
                with faults.active(plan):
                    with pytest.raises(faults.InjectedCrash):
                        exec_.run_once()
                assert plan.fired(site) == 1
                # restart: fresh driver + registry over the same dirs
                reg = ViewRegistry()
                incoming, exec_ = _mk_stream(root, reg)
                view = reg.register("stats", STATS_Q, exec_.sink)
                assert exec_.run_once() is None  # batch committed pre-kill
            else:
                assert exec_.run_once() is not None
        return exec_, view

    clean_root = tmp_path / "clean"
    clean_root.mkdir()
    killed_root = tmp_path / "killed"
    killed_root.mkdir()
    _, clean_view = run(clean_root)
    exec_, killed_view = run(killed_root, kill_at_batch=1)
    got, want = killed_view.read(), clean_view.read()
    _assert_bit_identical(want, got)
    _assert_parity(STATS_Q, exec_.sink, killed_view, ctx="after resume")


@pytest.mark.chaos
def test_replayed_batch_never_double_applies(tmp_path):
    """The double-apply probe: a crash between sink append and commit
    replays the batch (part file rewritten, then committed once) — and
    however many times maintenance observes it, its delta folds in
    exactly once."""
    reg = ViewRegistry()
    incoming, exec_ = _mk_stream(tmp_path, reg)
    view = reg.register("stats", STATS_Q, exec_.sink)
    _event_csv(str(incoming / "a.csv"), 0, 30)
    assert exec_.run_once().num_appended_rows == 30

    _event_csv(str(incoming / "b.csv"), 1, 20)
    plan = faults.FaultPlan().crash("stream.after_sink")
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash):
            exec_.run_once()  # part visible, commit missing → replay
    assert plan.fired("stream.after_sink") == 1

    reg2 = ViewRegistry()
    _, exec2 = _mk_stream(tmp_path, reg2)
    view2 = reg2.register("stats", STATS_Q, exec2.sink)
    info = exec2.run_once()  # the replay: rewrites the part, commits
    assert info is not None and info.batch_id == 1
    for _ in range(3):  # replayed maintenance notifications
        reg2.maintain(exec2.sink, 1)
    assert view2.applied_rows() == 50
    out = view2.read()
    assert int(out.column("c")[0]) == 50  # 30 + 20: exactly once
    _assert_parity(STATS_Q, exec2.sink, view2, ctx="after replay")


# =================================================== review-round fixes
def test_kill_switch_governs_views(tmp_path, monkeypatch):
    """CMLHN_SQL_COMPILE=0 must govern views too: maintenance stops
    running the compiled partial kernels, reads answer via the loud
    interpreter full recompute, and flipping the switch back lets the
    view catch up exactly-once."""
    rng = np.random.default_rng(11)
    sink = _mk_sink(tmp_path, rng)
    reg = ViewRegistry()
    view = reg.register("agg", AGG_Q, sink)
    sink.append_batch(_batch(rng, 60), 0)
    reg.maintain(sink, 0)
    assert view.applied_rows() == 60
    monkeypatch.setenv("CMLHN_SQL_COMPILE", "0")
    sink.append_batch(_batch(rng, 40), 1)
    reg.maintain(sink, 1)
    assert view.applied_rows() == 60  # no compiled-kernel fold
    before = global_registry().collect()["counters"].get(
        "sql.view.full_recompute", 0
    )
    _assert_parity(AGG_Q, sink, view, ctx="kill-switch read")
    after = global_registry().collect()["counters"].get(
        "sql.view.full_recompute", 0
    )
    assert after > before  # served loudly, via the interpreter
    ex = core_sql.explain(AGG_Q, lambda _n: sink.read())
    assert ex["view_maintenance"] == [FULL_COMPILE_DISABLED]
    monkeypatch.delenv("CMLHN_SQL_COMPILE")
    _assert_parity(AGG_Q, sink, view, ctx="switch back on")
    assert view.applied_rows() == 100


def test_group_key_dtype_drift_poisons_not_crashes(tmp_path):
    """An int GROUP BY key drifting to float (nulls introduced
    upstream) must poison the view to full recompute — never crash
    refresh canonicalizing int(NaN)."""
    rng = np.random.default_rng(12)
    sink = _mk_sink(tmp_path, rng)
    reg = ViewRegistry()
    view = reg.register("agg", AGG_Q, sink)
    sink.append_batch(_batch(rng, 50), 0)
    reg.maintain(sink, 0)
    drifted = _batch(rng, 50)
    cols = {c: drifted.column(c) for c in drifted.columns}
    i1 = cols["i1"].astype(np.float64)
    i1[0] = np.nan
    cols["i1"] = i1
    sink.append_batch(ht.Table.from_dict(cols), 0)  # drifted replay
    reg.maintain(sink, 0)  # must not raise
    d = view.describe()
    assert not d["incremental"] and d["poisoned"]
    _assert_parity(AGG_Q, sink, view, ctx="poisoned still correct")


def test_missing_part_file_does_not_strand_freshness(tmp_path):
    """applied_rows counts actually-FOLDED rows: a part file deleted
    out from under the table (retention) is skipped by the snapshot
    read too, so the dispatcher freshness check still matches and the
    view keeps serving."""
    import os

    rng = np.random.default_rng(13)
    sink = _mk_sink(tmp_path, rng)
    sink.append_batch(_batch(rng, 40), 0)
    sink.append_batch(_batch(rng, 30), 1)
    os.remove(os.path.join(sink.path, sink.committed_batches()[0]["file"]))
    reg = ViewRegistry()
    view = reg.register("agg", AGG_Q, sink)
    snap = sink.read()
    assert len(snap) == 30
    assert view.applied_rows() == 30
    plan = plan_query(parse(AGG_Q), lambda _n: snap)
    assert view.serve_if_fresh(plan) is not None


def test_dispatcher_serve_skips_reconcile_when_log_unchanged(
    tmp_path, monkeypatch
):
    """The hot serve path: an UNCHANGED commit log means zero O(batches)
    log parses + part stats per query (the commit-log stat
    short-circuit), and a new commit forces exactly one reconcile —
    per-query serve cost must not grow with retained history."""
    rng = np.random.default_rng(14)
    sink = _mk_sink(tmp_path, rng)
    reg = ViewRegistry()
    view = reg.register("agg", AGG_Q, sink)
    sink.append_batch(_batch(rng, 80), 0)
    reg.maintain(sink, 0)
    snap = sink.read()
    plan = plan_query(parse(AGG_Q), lambda _n: snap)
    calls = {"n": 0}
    orig = sink.committed_batches

    def counting():
        calls["n"] += 1
        return orig()

    monkeypatch.setattr(sink, "committed_batches", counting)
    for _ in range(5):
        out = reg.serve_for(plan)
        assert out is not None
    assert calls["n"] == 0  # stat-only refreshes: nothing committed
    assert sql_fuzz.compare_tables(_full(AGG_Q, sink), out) is None
    sink.append_batch(_batch(rng, 20), 1)  # a new commit line
    snap2 = sink.read()
    plan2 = plan_query(parse(AGG_Q), lambda _n: snap2)
    calls["n"] = 0
    out2 = reg.serve_for(plan2)
    assert out2 is not None
    assert calls["n"] == 1  # exactly one reconcile catches it up
    assert sql_fuzz.compare_tables(_full(AGG_Q, sink), out2) is None


def test_view_serve_failure_degrades_not_raises(tmp_path, monkeypatch):
    """A view-layer runtime failure (corrupt state, kernel error) must
    fall through to the real executors — same contract as the compiled
    branch's interpreter fallback — never take the query down."""
    rng = np.random.default_rng(15)
    sink = _mk_sink(tmp_path, rng)
    reg = ViewRegistry()
    reg.register("agg", AGG_Q, sink)
    sink.append_batch(_batch(rng, 40), 0)
    reg.maintain(sink, 0)

    def boom(plan):
        raise RuntimeError("corrupt view state")

    monkeypatch.setattr(reg, "serve_for", boom)
    before = global_registry().collect()["counters"].get(
        "sql.view.serve_errors", 0
    )
    out = execute(AGG_Q, lambda _n: sink.read(), views=reg)
    assert core_sql.last_dispatch().route in ("compiled", "interpreter")
    assert sql_fuzz.compare_tables(_full(AGG_Q, sink), out) is None
    assert global_registry().collect()["counters"].get(
        "sql.view.serve_errors", 0
    ) == before + 1


def test_all_nat_batch_does_not_wedge_compaction(tmp_path):
    """A non-empty batch whose watermark column is all-NaT can never
    fall below the watermark — it must SEAL like an empty batch does,
    not block the contiguous prefix forever (unbounded state on a 24/7
    stream); answers stay exact and a replay of it costs the loud
    rebuild, which is the sealed contract."""
    rng = np.random.default_rng(18)
    base = np.datetime64("2025-03-31T00:00:00")

    def timed_batch(b, n=30, nat=False):
        if nat:
            t = np.full(n, np.datetime64("NaT"), dtype="datetime64[ns]")
        else:
            t = (
                base + (b * 3600 + rng.integers(0, 3600, n)).astype(
                    "timedelta64[s]"
                )
            ).astype("datetime64[ns]")
        return ht.Table.from_dict(
            {"f1": rng.normal(size=n), "i1": rng.integers(0, 4, n), "t1": t}
        )

    sink = UnboundedTable(
        str(tmp_path / "table"), timed_batch(0).schema, name="events"
    )
    wt = WatermarkTracker("t1", 90.0)
    reg = ViewRegistry()
    q = "SELECT i1, count(*) AS c, sum(f1) AS s FROM events GROUP BY i1"
    view = reg.register("agg", q, sink, watermark=wt)
    for bid in range(6):
        tb = timed_batch(bid, nat=(bid == 1))  # batch 1: no event times
        if bid != 1:
            wt.filter_late(tb)
        sink.append_batch(tb, bid)
        reg.maintain(sink, bid)
        _assert_parity(q, sink, view, ctx=f"batch {bid}")
    d = view.describe()
    assert d["compacted_upto"] is not None and d["compacted_upto"] >= 2
    _assert_parity(q, sink, view, ctx="sealed through the NaT batch")


def test_gap_fill_below_seal_rebuilds_loudly(tmp_path):
    """A commit-log entry appearing BELOW the compacted seal that was
    never sealed (a gap-fill replay) must force the same loud rebuild
    as a sealed replay — silently skipping it would drop its rows from
    view state while a full recompute includes them."""
    rng = np.random.default_rng(17)
    base = np.datetime64("2025-03-31T00:00:00")

    def timed_batch(b, n=40):
        t = (
            base + (b * 3600 + rng.integers(0, 3600, n)).astype(
                "timedelta64[s]"
            )
        ).astype("datetime64[ns]")
        return ht.Table.from_dict(
            {"f1": rng.normal(size=n), "i1": rng.integers(0, 4, n), "t1": t}
        )

    sink = UnboundedTable(
        str(tmp_path / "table"), timed_batch(0).schema, name="events"
    )
    wt = WatermarkTracker("t1", 90.0)
    reg = ViewRegistry()
    q = "SELECT i1, count(*) AS c, sum(f1) AS s FROM events GROUP BY i1"
    view = reg.register("agg", q, sink, watermark=wt)
    for bid in (0, 1, 2, 4, 5, 6, 7):  # bid 3 never committed: a gap
        tb = timed_batch(bid)
        wt.filter_late(tb)
        sink.append_batch(tb, bid)
        reg.maintain(sink, bid)
    d = view.describe()
    assert d["compacted_upto"] is not None and d["compacted_upto"] >= 4
    rebuilds = global_registry().counters.get("sql.view.rebuilds", 0)
    sink.append_batch(timed_batch(3), 3)  # the gap fills in, under seal
    reg.maintain(sink, 3)
    assert global_registry().counters.get("sql.view.rebuilds", 0) > rebuilds
    _assert_parity(q, sink, view, ctx="gap-fill below the seal")
    assert view.applied_rows() == 8 * 40


def test_retraction_rewrites_delta_under_fresh_path(tmp_path):
    """Retract-and-reapply gives the rowlevel delta a FRESH epoch-
    qualified path and the landed state sweeps the orphan — a stale
    staged write can never resurrect pre-replay rows after a restart."""
    import os

    rng = np.random.default_rng(16)
    sink = _mk_sink(tmp_path, rng)
    reg = ViewRegistry()
    view = reg.register("win", ROW_Q, sink)
    sink.append_batch(_batch(rng, 60), 0)
    reg.maintain(sink, 0)
    first = view._batches[0]["delta_file"]
    sink.append_batch(_batch(rng, 60), 0)  # replay with new content
    reg.maintain(sink, 0)
    second = view._batches[0]["delta_file"]
    assert first is not None and second is not None and first != second
    on_disk = sorted(
        f for f in os.listdir(view.state_dir) if f.startswith("delta-")
    )
    assert on_disk == [second]  # the pre-replay orphan was swept
    v2 = ViewRegistry().register("win", ROW_Q, sink)  # restart
    _assert_parity(ROW_Q, sink, v2, ctx="after replay + restart")


# ===================== string group keys (the ISSUE 17 soak's in-tree find)
STR_Q = (
    "SELECT s1, count(*) AS c, sum(f1) AS s, min(f1) AS lo FROM events "
    "GROUP BY s1"
)
STR_WHERE_Q = (
    "SELECT s1, count(*) AS c, avg(f1) AS a FROM events "
    "WHERE i1 >= 1 GROUP BY s1"
)


def _str_batch(rng, n, pool):
    f1 = rng.normal(size=n) * 10
    s1 = np.array(
        [pool[int(i)] for i in rng.integers(0, len(pool), n)], dtype=object
    )
    if n >= 8:  # a small schema-probe batch must stay null-free
        f1[rng.random(n) < 0.1] = np.nan
        s1[rng.random(n) < 0.12] = None
    return ht.Table.from_dict(
        {"f1": f1, "i1": rng.integers(-2, 4, n), "s1": s1}
    )


def test_string_group_key_view_incremental_parity(tmp_path):
    """Regression for the bug the ISSUE 17 soak surfaced: a string-keyed
    GROUP BY view (the soak's per-hospital drift feed) must fold
    per-batch partials incrementally and still match the full recompute.

    The minimal two-subsystem staging: UnboundedTable commits × view
    maintenance.  Each batch deliberately introduces its hospitals in a
    DIFFERENT first-appearance order — under the old first-appearance
    factorization the per-batch codes were batch-relative (and, with a
    WHERE, filter-relative), so cross-batch folds and pre-filter host
    encodes could not agree; sorted-rank codes are order-isomorphic to
    the values and cannot depend on which other rows are present."""
    rng = np.random.default_rng(17)
    pools = (
        ("H02", "H01"),             # batch 0 meets H02 first
        ("H00", "H03", "H01"),      # batch 1 leads with new hospitals
        ("H03", "H00"),             # batch 2 reverses batch 1's order
    )
    sink = UnboundedTable(
        str(tmp_path / "table"), _str_batch(rng, 1, pools[0]).schema,
        name="events",
    )
    reg = ViewRegistry()
    view = reg.register("per_hosp", STR_Q, sink)
    filt = reg.register("per_hosp_busy", STR_WHERE_Q, sink)
    for bid, pool in enumerate(pools):
        sink.append_batch(_str_batch(rng, 80, pool), bid)
        reg.maintain(sink, bid)
        _assert_parity(STR_Q, sink, view, ctx=f"batch {bid}")
        _assert_parity(STR_WHERE_Q, sink, filt, ctx=f"filtered batch {bid}")
    assert view.describe()["incremental"], view.describe()["decisions"]
    assert filt.describe()["incremental"], filt.describe()["decisions"]
    before = view.read()
    assert None in set(before.column("s1"))  # the null group is present

    # restart: a fresh registry re-loads the persisted canonical keys —
    # the (null_flag, str) tuples must round-trip through state.json
    v2 = ViewRegistry().register("per_hosp", STR_Q, sink)
    _assert_parity(STR_Q, sink, v2, ctx="string keys after restart")
    _assert_bit_identical(before, v2.read())
