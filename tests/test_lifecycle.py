"""Continuous-learning loop suite (ISSUE 9).

The acceptance story: a lifecycle controller that closes drift detection →
warm retrain → shadow → canary → promotion into one journaled state
machine, and SURVIVES a process kill at every stage boundary — the chaos
matrix asserts the resumed loop converges on a final served model
bit-identical to an uninterrupted run, the parity gate blocks a degraded
candidate, rollback leaves the prior artifact byte-for-byte untouched,
and feedback rows spooled for re-ingest are never lost.

Every injected fault is asserted to have FIRED (a chaos test whose fault
never triggered proves nothing), same discipline as tests/test_chaos.py.
"""

import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import (
    artifact_fingerprint,
    load_model,
    write_csv,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.lifecycle import (
    FeedbackBuffer,
    KMeansRetrainer,
    LifecycleController,
    LifecycleJournal,
    STATE_CANARY,
    STATE_DRIFT_SUSPECTED,
    STATE_RETRAINING,
    STATE_ROLLED_BACK,
    STATE_SERVING,
    STATE_SHADOW,
    feedback_schema,
    kmeans_cost,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.kmeans import (
    KMeans,
    KMeansModel,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.quality.drift import (
    DriftMonitor,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.quality.sketches import (
    DataProfile,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve import (
    DEGRADED_STATUSES,
    InferenceServer,
    STATUS_CANARY,
    ServeResult,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.streaming import (
    FileStreamSource,
    StreamCheckpoint,
    StreamExecution,
    UnboundedTable,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils import faults

pytestmark = pytest.mark.lifecycle

FEATS = ("f0", "f1", "f2")
K = 4
SHIFT = 6.0
BLOB_CENTERS = np.array(
    [[0, 0, 0], [4, 0, 0], [0, 4, 0], [4, 4, 4]], dtype=np.float64
)


def _blobs(rng, n, shift=0.0):
    idx = rng.integers(0, K, n)
    return (BLOB_CENTERS + shift)[idx] + rng.normal(scale=0.3, size=(n, 3))


def _drop_file(incoming, path_name, x):
    schema = feedback_schema(FEATS)
    cols = {n: x[:, j] for j, n in enumerate(FEATS)}
    cols["prediction"] = np.zeros(len(x))
    cols["outcome"] = np.zeros(len(x))
    write_csv(ht.Table.from_dict(cols, schema), os.path.join(incoming, path_name))


# ------------------------------------------------------------------ harness
@pytest.fixture(scope="module")
def baseline():
    """One baseline fit shared by every test: model + training profile."""
    rng = np.random.default_rng(0)
    x0 = _blobs(rng, 1500).astype(np.float32)
    model = KMeans(k=K, seed=0, max_iter=20).fit(x0)
    profile = DataProfile.from_matrix(x0.astype(np.float64), FEATS)
    return model, profile, x0


def _build(work, retrainer=None, **overrides):
    """One 'process incarnation': server + ingest stream + controller over
    the durable state in ``work`` — calling it again IS the restart."""
    incoming = os.path.join(work, "incoming")
    os.makedirs(incoming, exist_ok=True)
    schema = feedback_schema(FEATS)
    stream = StreamExecution(
        source=FileStreamSource(incoming, schema),
        sink=UnboundedTable(os.path.join(work, "table"), schema),
        checkpoint=StreamCheckpoint(os.path.join(work, "ckpt")),
        add_ingest_time=False,
    )
    srv = InferenceServer(breaker_recovery_s=0.1)
    kwargs = dict(
        stream=stream,
        buckets=(1, 8, 32),
        drift_window_rows=64,
        drift_trip_after=2,
        shadow_min_rows=128,
        canary_fraction=0.25,
        canary_min_rows=32,
        eval_rows=128,
    )
    kwargs.update(overrides)
    ctrl = LifecycleController(
        os.path.join(work, "lc"), srv, "kmeans",
        retrainer or KMeansRetrainer(FEATS, k=K, max_iter=30, tol=1e-4),
        **kwargs,
    )
    srv.attach_lifecycle(ctrl)
    return srv, stream, ctrl


def _seed_world(work, baseline, n_files=2, rows=300):
    """Bootstrap v0 and ingest the full drifted dataset up front, so the
    retrain snapshot is identical across every (killed or not) run."""
    model, profile, x0 = baseline
    srv, stream, ctrl = _build(work)
    ctrl.bootstrap(model, profile, train_x=x0)
    drng = np.random.default_rng(7)
    for i in range(n_files):
        _drop_file(
            os.path.join(work, "incoming"), f"drift-{i}.csv",
            _blobs(drng, rows, SHIFT),
        )
    while stream.run_once() is not None:
        pass
    return srv, stream, ctrl


def _drive(srv, ctrl, *, until, max_steps=600, poll=True, shift=SHIFT, seed=1):
    """Deterministic drifted traffic until ``until(ctrl)`` holds."""
    trng = np.random.default_rng(seed)
    for _ in range(max_steps):
        xb = _blobs(trng, 8, shift).astype(np.float32)
        srv.predict("kmeans", xb, wait_timeout_s=10.0)
        if poll:
            ctrl.poll()
        if until(ctrl):
            return
    raise AssertionError(
        f"condition never reached; state={ctrl.state} "
        f"cycle={ctrl.cycle} active=v{ctrl.active_version}"
    )


def _promoted(ctrl):
    return (
        ctrl.state == STATE_SERVING
        and ctrl.active_version is not None
        and ctrl.active_version > 0
    )


def _run_to_promotion(work, baseline, kill_site=None):
    """Full cycle, restarting through InjectedCrash like a supervisor
    would; → (controller, crash count)."""
    srv, stream, ctrl = _seed_world(work, baseline)
    srv.start()
    crashes = 0
    plan = None
    if kill_site:
        plan = faults.FaultPlan().crash(kill_site)
        faults.install(plan)
    try:
        while True:
            try:
                _drive(srv, ctrl, until=_promoted)
                break
            except faults.InjectedCrash:
                crashes += 1
                faults.clear()
                srv.stop()
                srv, stream, ctrl = _build(work)  # the restart
                srv.start()
    finally:
        faults.clear()
        srv.stop()
    if kill_site:
        assert plan.fired(kill_site) >= 1, f"{kill_site} never fired"
        assert crashes >= 1
    return ctrl, crashes


@pytest.fixture(scope="module")
def reference_run(tmp_path_factory, baseline):
    """The uninterrupted drift→retrain→promote cycle every chaos case is
    compared against."""
    work = str(tmp_path_factory.mktemp("lc_reference"))
    ctrl, crashes = _run_to_promotion(work, baseline)
    assert crashes == 0
    model = load_model(os.path.join(work, "lc", "models", "v1"))
    return np.asarray(model.cluster_centers)


# ------------------------------------------------------------------ journal
def test_journal_roundtrip_crc_detects_corruption(tmp_path):
    j = LifecycleJournal(str(tmp_path / "journal.log"))
    j.append(STATE_SERVING, 0, {"active_version": 0})
    j.append(STATE_DRIFT_SUSPECTED, 0, {"reason": "psi"})
    j.append(STATE_RETRAINING, 1, {"candidate_version": 1})
    assert [e["state"] for e in j.entries()] == [
        STATE_SERVING, STATE_DRIFT_SUSPECTED, STATE_RETRAINING,
    ]
    # flip one byte inside the middle entry's payload: CRC must catch what
    # JSON parsing alone would happily accept
    with open(j.path, "rb") as f:
        lines = f.readlines()
    line = bytearray(lines[1])
    i = line.index(b"psi")
    line[i] = ord(b"q")
    lines[1] = bytes(line)
    with open(j.path, "wb") as f:
        f.writelines(lines)
    j2 = LifecycleJournal(j.path)
    states = [e["state"] for e in j2.entries()]
    assert states == [STATE_SERVING, STATE_RETRAINING]
    assert j2.corrupt_skipped == 1
    assert j2.last()["state"] == STATE_RETRAINING


def test_journal_torn_append_loses_only_the_tail(tmp_path):
    j = LifecycleJournal(str(tmp_path / "journal.log"))
    j.append(STATE_SERVING, 0, {})
    plan = faults.FaultPlan().tear(
        "wal.append", at_byte=10,
        when=lambda ctx: str(ctx.get("path", "")).endswith("journal.log"),
    )
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash):
            j.append(STATE_DRIFT_SUSPECTED, 0, {})
    assert plan.fired("wal.append") == 1
    j2 = LifecycleJournal(j.path)
    assert j2.last()["state"] == STATE_SERVING  # torn entry dropped
    j2.append(STATE_DRIFT_SUSPECTED, 0, {})    # and the log keeps working
    assert j2.last()["state"] == STATE_DRIFT_SUSPECTED


# --------------------------------------------------------------- warm start
def test_warm_start_shape_validation():
    x = np.random.default_rng(0).normal(size=(64, 3)).astype(np.float32)
    with pytest.raises(ValueError, match="warm_start_centers"):
        KMeans(k=K, warm_start_centers=np.zeros((K, 7))).fit(x)


@pytest.fixture(scope="module")
def hard_mixture():
    """An OVERLAPPING 16-cluster 8-d mixture (well-separated blobs let
    even cold k-means++ converge in 2 Lloyd steps — no trajectory to
    save): pre-drift data, post-drift data (+0.3 shift), and the cold
    pre-drift fit whose centers seed the warm starts."""
    rng = np.random.default_rng(3)
    true = rng.normal(scale=1.5, size=(16, 8))

    def draw(shift):
        idx = rng.integers(0, 16, 6000)
        return (
            (true + shift)[idx] + rng.normal(scale=1.0, size=(6000, 8))
        ).astype(np.float32)

    xa, xb = draw(0.0), draw(0.3)
    model_a = KMeans(k=16, seed=5, max_iter=80, tol=1e-5).fit(xa)
    return xa, xb, model_a


def test_warm_start_converges_in_fewer_iterations(hard_mixture):
    # warm-start from a converged solution must terminate almost
    # immediately (the skipped trajectory IS the warm-retrain win), while
    # the cold fit on the same overlapping mixture pays the full path
    _, xb, _ = hard_mixture
    cold_iters, warm_iters = [], []
    cold_model = KMeans(k=16, seed=5, max_iter=80, tol=1e-5).fit(
        xb, on_iteration=lambda it, c, m: cold_iters.append(it)
    )
    warm_centers = np.asarray(cold_model.cluster_centers, dtype=np.float32)
    warm_model = KMeans(
        k=16, seed=5, max_iter=80, tol=1e-5, warm_start_centers=warm_centers
    ).fit(xb, on_iteration=lambda it, c, m: warm_iters.append(it))
    assert 1 <= len(warm_iters) <= 3 < len(cold_iters), (
        f"warm start did not skip the trajectory: warm={len(warm_iters)} "
        f"cold={len(cold_iters)} iterations"
    )
    assert warm_model.training_cost <= cold_model.training_cost * 1.001


def test_warm_start_signature_guards_checkpoint_resume(
    tmp_path, hard_mixture
):
    _, xb, model_a = hard_mixture
    ckpt = str(tmp_path / "ckpt")
    warm_a = (np.asarray(model_a.cluster_centers) + 0.3).astype(np.float32)

    def kill_at_3(it, cost, move):
        if it >= 3:
            raise faults.InjectedCrash("mid-fit kill")

    est = KMeans(k=16, seed=5, max_iter=40, tol=1e-5, checkpoint_dir=ckpt,
                 checkpoint_every=1, warm_start_centers=warm_a)
    with pytest.raises(faults.InjectedCrash):
        est.fit(xb, on_iteration=kill_at_3)
    # resuming with DIFFERENT warm centers is a different trajectory:
    # the signature must refuse, not silently continue
    with pytest.raises(ValueError, match="signature mismatch"):
        KMeans(k=16, seed=5, max_iter=40, tol=1e-5, checkpoint_dir=ckpt,
               checkpoint_every=1,
               warm_start_centers=warm_a + 1.0).fit(xb)
    resumed = est.fit(xb)
    uninterrupted = KMeans(
        k=16, seed=5, max_iter=40, tol=1e-5, warm_start_centers=warm_a
    ).fit(xb)
    np.testing.assert_array_equal(
        resumed.cluster_centers, uninterrupted.cluster_centers
    )


# ------------------------------------------------------------- happy path
def test_full_cycle_promotes_new_model(tmp_path, baseline):
    work = str(tmp_path)
    ctrl, crashes = _run_to_promotion(work, baseline)
    assert crashes == 0
    states = [e["state"] for e in ctrl.journal.entries()]
    assert states == [
        STATE_SERVING, STATE_DRIFT_SUSPECTED, STATE_RETRAINING, STATE_SHADOW,
        STATE_CANARY, "promoted", STATE_SERVING,
    ]
    assert ctrl.active_version == 1
    # the promoted model actually fits the drifted distribution
    drifted = _blobs(np.random.default_rng(9), 256, SHIFT)
    new_model = load_model(os.path.join(work, "lc", "models", "v1"))
    old_model = load_model(os.path.join(work, "lc", "models", "v0"))
    assert kmeans_cost(new_model, drifted) < 0.1 * kmeans_cost(
        old_model, drifted
    )
    # retrain was warm-started and journaled so
    shadow = next(
        e for e in ctrl.journal.entries() if e["state"] == STATE_SHADOW
    )
    assert shadow["info"]["warm_started"] is True
    assert shadow["info"]["train_rows"] == 600


# ------------------------------------------------------------ chaos matrix
KILL_SITES = [
    "lifecycle.journal.append",
    "lifecycle.retrain.commit",
    "lifecycle.shadow.start",
    "lifecycle.registry.flip",
    "lifecycle.registry.swap",
]


@pytest.mark.chaos
@pytest.mark.parametrize("site", KILL_SITES)
def test_kill_and_resume_converges_bit_identical(
    tmp_path, baseline, reference_run, site
):
    """Kill the controller at each transition boundary; the restarted loop
    must self-heal to PROMOTED with the final served model bit-identical
    to the uninterrupted run's."""
    ctrl, crashes = _run_to_promotion(str(tmp_path), baseline, kill_site=site)
    assert crashes >= 1
    assert ctrl.active_version == 1
    final = load_model(
        os.path.join(str(tmp_path), "lc", "models", "v1")
    )
    np.testing.assert_array_equal(
        np.asarray(final.cluster_centers), reference_run
    )


class _DegradedRetrainer(KMeansRetrainer):
    """Trains fine, then ships garbage centers — the candidate the parity
    gate exists to refuse."""

    def __call__(self, warm_model, table, ckpt_dir, seed):
        model, profile = super().__call__(warm_model, table, ckpt_dir, seed)
        bad = np.asarray(model.cluster_centers) + 50.0
        return KMeansModel(
            cluster_centers=bad,
            distance_measure=model.distance_measure,
            training_cost=model.training_cost,
            n_iter=model.n_iter,
            cluster_sizes=model.cluster_sizes,
        ), profile


def _rolled_back(ctrl):
    return ctrl.state == STATE_SERVING and any(
        e["state"] == STATE_ROLLED_BACK for e in ctrl.journal.entries()
    )


def _run_to_rollback(work, baseline, kill_site=None):
    model, profile, x0 = baseline
    srv, stream, ctrl = _build(
        work, retrainer=_DegradedRetrainer(FEATS, k=K, max_iter=30, tol=1e-4)
    )
    ctrl.bootstrap(model, profile, train_x=x0)
    drng = np.random.default_rng(7)
    for i in range(2):
        _drop_file(
            os.path.join(work, "incoming"), f"drift-{i}.csv",
            _blobs(drng, 300, SHIFT),
        )
    while stream.run_once() is not None:
        pass
    srv.attach_lifecycle(ctrl)
    srv.start()
    crashes = 0
    plan = None
    if kill_site:
        plan = faults.FaultPlan().crash(kill_site)
        faults.install(plan)
    try:
        while True:
            try:
                _drive(srv, ctrl, until=_rolled_back)
                break
            except faults.InjectedCrash:
                crashes += 1
                faults.clear()
                srv.stop()
                srv, stream, ctrl = _build(
                    work,
                    retrainer=_DegradedRetrainer(
                        FEATS, k=K, max_iter=30, tol=1e-4
                    ),
                )
                srv.attach_lifecycle(ctrl)
                srv.start()
    finally:
        faults.clear()
        srv.stop()
    if kill_site:
        assert plan.fired(kill_site) >= 1, f"{kill_site} never fired"
        assert crashes >= 1
    return srv, ctrl, crashes


def test_shadow_gate_blocks_degraded_candidate(tmp_path, baseline):
    srv, ctrl, _ = _run_to_rollback(str(tmp_path), baseline)
    states = [e["state"] for e in ctrl.journal.entries()]
    assert STATE_ROLLED_BACK in states
    assert STATE_CANARY not in states  # refused at the shadow gate
    assert ctrl.active_version == 0    # still the original baseline
    rb = next(
        e for e in ctrl.journal.entries() if e["state"] == STATE_ROLLED_BACK
    )
    assert "shadow parity" in rb["info"]["reason"]


@pytest.mark.chaos
def test_kill_at_rollback_resumes_to_prior_baseline(tmp_path, baseline):
    srv, ctrl, crashes = _run_to_rollback(
        str(tmp_path), baseline, kill_site="lifecycle.rollback"
    )
    assert crashes >= 1
    assert ctrl.active_version == 0
    assert ctrl.state == STATE_SERVING


def test_rollback_restores_prior_artifact_byte_for_byte(tmp_path, baseline):
    work = str(tmp_path)
    v0 = os.path.join(work, "lc", "models", "v0")

    def artifact_bytes():
        out = {}
        for name in sorted(os.listdir(v0)):
            with open(os.path.join(v0, name), "rb") as f:
                out[name] = f.read()
        return out

    model, profile, x0 = baseline
    srv, stream, ctrl = _build(
        work, retrainer=_DegradedRetrainer(FEATS, k=K, max_iter=30, tol=1e-4)
    )
    ctrl.bootstrap(model, profile, train_x=x0)
    before = artifact_bytes()
    fp_before = artifact_fingerprint(v0)
    _drop_file(
        os.path.join(work, "incoming"), "drift-0.csv",
        _blobs(np.random.default_rng(7), 600, SHIFT),
    )
    while stream.run_once() is not None:
        pass
    srv.attach_lifecycle(ctrl)
    with srv:
        _drive(srv, ctrl, until=_rolled_back)
    assert artifact_bytes() == before, "rollback modified the prior artifact"
    assert artifact_fingerprint(v0) == fp_before
    # the refused candidate stays on disk as evidence
    assert os.path.isdir(os.path.join(work, "lc", "models", "v1"))


# -------------------------------------------------------------- canary path
def test_canary_tagging_and_health_fragment(tmp_path, baseline):
    work = str(tmp_path)
    srv, stream, ctrl = _seed_world(work, baseline)
    # park the machine IN the canary phase: the decision needs more rows
    # than this test will send
    ctrl.canary_min_rows = 10**9
    srv.start()
    try:
        _drive(srv, ctrl, until=lambda c: c.state == STATE_CANARY)
        trng = np.random.default_rng(11)
        statuses = []
        for _ in range(40):
            xb = _blobs(trng, 4, SHIFT).astype(np.float32)
            r = srv.predict("kmeans", xb, wait_timeout_s=10.0)
            statuses.append(r.status)
            if r.status == STATUS_CANARY:
                # canary answers are full-quality, never degraded — even
                # while sustained drift holds the PRIMARY's breaker open
                assert r.ok
                assert r.value is not None and len(r.value) == 4
                assert r.latency_s > 0.0
            else:
                # primary answers may legitimately degrade under the
                # sustained drift that triggered this whole cycle
                assert r.status in ("ok", "unavailable"), r.status
        n_canary = statuses.count(STATUS_CANARY)
        assert n_canary == 10, (  # stride 4 at fraction 0.25, counter-based
            f"expected exactly 1-in-4 canary answers, got {n_canary}/40"
        )
        h = srv.health()
        frag = h["lifecycle"]
        assert frag["phase"] == STATE_CANARY
        assert frag["candidate_version"] == 1
        assert frag["candidate_model_id"] is not None
        assert frag["shadow"]["rows_observed"] >= 128
        assert frag["canary"]["fraction"] == 0.25
        assert frag["canary"]["routed_to_candidate"] >= 10
        assert frag["canary"]["canary_rows"] >= 40
        assert frag["drift"] is not None
    finally:
        srv.stop()


def test_status_canary_semantics():
    assert ServeResult(np.zeros(1), STATUS_CANARY).ok
    assert STATUS_CANARY not in DEGRADED_STATUSES


# ---------------------------------------------------- drift-reference fix
def test_promotion_rebases_psi_reference_regression(baseline):
    """The re-trip bug: after a promotion, live traffic must be PSI-scored
    against the CANDIDATE's training profile.  Scored against the stale
    reference (the old registry.register route) the breaker re-trips on
    perfectly healthy traffic; swap_model must not."""
    model, profile, x0 = baseline
    rng = np.random.default_rng(21)
    drifted = _blobs(rng, 4000, SHIFT)
    candidate = KMeans(k=K, seed=1, max_iter=20).fit(
        drifted.astype(np.float32)
    )
    cand_profile = DataProfile.from_matrix(drifted, FEATS)

    def feed(srv):
        t = np.random.default_rng(22)
        trips = 0
        for _ in range(40):
            xb = _blobs(t, 16, SHIFT).astype(np.float32)
            srv.predict("kmeans", xb, wait_timeout_s=10.0)
            snap = srv.health()
            trips = snap["drift"]["kmeans"]["trips"]
        return trips

    # the BUG route: flip the registry without touching the monitor
    srv = InferenceServer(breaker_recovery_s=30.0)
    srv.add_model(
        "kmeans", model, buckets=(1, 16, 32),
        data_profile=profile.to_dict(),
        drift_window_rows=64, drift_trip_after=2,
    )
    with srv:
        srv.registry.register("kmeans", candidate, buckets=(1, 16, 32))
        srv._batchers["kmeans"].model = srv.registry.get("kmeans")
        assert feed(srv) >= 1, "stale reference should re-trip (bug repro)"

    # the FIX: swap_model rebases the reference atomically with the flip
    srv = InferenceServer(breaker_recovery_s=30.0)
    srv.add_model(
        "kmeans", model, buckets=(1, 16, 32),
        data_profile=profile.to_dict(),
        drift_window_rows=64, drift_trip_after=2,
    )
    with srv:
        srv.swap_model(
            "kmeans", candidate, data_profile=cand_profile.to_dict()
        )
        assert feed(srv) == 0, "rebased reference must not re-trip"
        snap = srv.health()["drift"]["kmeans"]
        assert snap["rebases"] == 1
        assert snap["max_psi"] < 0.5
        assert srv.health()["breakers"]["kmeans"]["state"] == "closed"


def test_drift_monitor_rebase_resets_window_state(baseline):
    _, profile, _ = baseline
    mon = DriftMonitor(profile, window_rows=64, trip_after=1)
    rng = np.random.default_rng(5)
    drifted = _blobs(rng, 256, SHIFT)
    mon.observe(drifted)
    assert mon.should_trip()
    new_ref = DataProfile.from_matrix(drifted, FEATS)
    mon.rebase(new_ref)
    assert mon.rebases == 1
    assert not mon.drifting and mon.max_psi == 0.0
    mon.observe(_blobs(rng, 256, SHIFT))
    assert not mon.should_trip()
    assert mon.max_psi < 0.5


def test_swap_model_resets_breaker(baseline):
    model, profile, _ = baseline
    srv = InferenceServer(breaker_recovery_s=60.0)
    srv.add_model("kmeans", model, buckets=(1, 8))
    with srv:
        srv._breaker_for("kmeans").trip("operator")
        assert srv.health()["breakers"]["kmeans"]["state"] == "open"
        srv.swap_model("kmeans", model, data_profile=profile.to_dict())
        assert srv.health()["breakers"]["kmeans"]["state"] == "closed"
        r = srv.predict(
            "kmeans", np.zeros((1, 3), np.float32), wait_timeout_s=10.0
        )
        assert r.ok


# ----------------------------------------------------------------- feedback
def test_feedback_join_flush_and_restart(tmp_path):
    root = str(tmp_path / "fb")
    incoming = str(tmp_path / "incoming")
    buf = FeedbackBuffer(root, FEATS, incoming)
    ids = [buf.record_prediction([float(i), 0.0, 1.0], float(i)) for i in range(6)]
    for i in ids[:4]:
        buf.record_outcome(i, 10.0 + i)
    assert buf.pending_outcomes() == 2
    path = buf.flush()
    assert path is not None and os.path.exists(path)
    assert buf.flush() is None  # nothing new joined
    # restart: spool state survives the WAL round-trip
    buf2 = FeedbackBuffer(root, FEATS, incoming)
    assert buf2.pending_outcomes() == 2
    assert buf2.joined_unflushed() == []
    buf2.record_outcome(ids[4], 99.0)
    p2 = buf2.flush()
    assert p2 is not None and p2 != path
    t = ht.read_csv(path, feedback_schema(FEATS))
    assert len(t) == 4
    np.testing.assert_allclose(t.column("outcome"), [10.0, 11.0, 12.0, 13.0])


@pytest.mark.chaos
def test_feedback_flush_killed_between_intent_and_commit(tmp_path):
    """A kill after the flush intent (and CSV) but before the commit marker
    replays the SAME flush — same id, same rows, byte-identical file —
    never a loss, never a duplicate."""
    root = str(tmp_path / "fb")
    incoming = str(tmp_path / "incoming")
    buf = FeedbackBuffer(root, FEATS, incoming)
    for i in range(5):
        fid = buf.record_prediction([float(i), 2.0, 3.0], float(i))
        buf.record_outcome(fid, float(i) * 2)
    wal = os.path.join(root, "feedback.log")
    plan = faults.FaultPlan().crash(
        "wal.append", after=1,  # intent passes, the COMMIT append dies
        when=lambda ctx: str(ctx.get("path", "")) == wal,
    )
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash):
            buf.flush()
    assert plan.fired("wal.append") == 1
    csv_path = os.path.join(incoming, "feedback-000000.csv")
    assert os.path.exists(csv_path)  # the file landed before the kill
    with open(csv_path, "rb") as f:
        before = f.read()
    buf2 = FeedbackBuffer(root, FEATS, incoming)
    replayed = buf2.flush()
    assert replayed == csv_path
    with open(csv_path, "rb") as f:
        assert f.read() == before  # byte-identical replay
    assert buf2.flush() is None
    assert len(os.listdir(incoming)) == 1  # exactly one feedback file


@pytest.mark.chaos
def test_feedback_rows_survive_stream_kill_and_replay(tmp_path):
    """Flushed feedback rows ride the normal exactly-once ingest: a kill
    between sink append and commit replays the batch, and the unbounded
    table ends with every feedback row exactly once."""
    root = str(tmp_path / "fb")
    incoming = str(tmp_path / "incoming")
    buf = FeedbackBuffer(root, FEATS, incoming)
    for i in range(8):
        fid = buf.record_prediction([float(i), 1.0, 1.0], float(i))
        buf.record_outcome(fid, float(i))
    buf.flush()
    schema = feedback_schema(FEATS)

    def mk_stream():
        return StreamExecution(
            source=FileStreamSource(incoming, schema),
            sink=UnboundedTable(str(tmp_path / "table"), schema),
            checkpoint=StreamCheckpoint(str(tmp_path / "ckpt")),
            add_ingest_time=False,
        )

    plan = faults.FaultPlan().crash("stream.after_sink")
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash):
            mk_stream().run_once()
    assert plan.fired("stream.after_sink") == 1
    s2 = mk_stream()  # the restart: replays exactly the in-flight batch
    done = s2.run(max_batches=1, timeout_s=10.0)
    assert len(done) == 1
    table = s2.sink.read()
    assert len(table) == 8
    np.testing.assert_allclose(
        np.sort(np.asarray(table.column("prediction"), dtype=np.float64)),
        np.arange(8, dtype=np.float64),
    )


@pytest.mark.chaos
def test_feedback_kill_between_commit_and_compact_never_double_flushes(
    tmp_path
):
    """A kill after flush_commit but before compaction replays the
    flushed rows into memory on restart; a LATER flush's compaction must
    not rewrite them as live records (shedding their flushed status) —
    that would double-flush them on the following restart."""
    root = str(tmp_path / "fb")
    incoming = str(tmp_path / "incoming")
    buf = FeedbackBuffer(root, FEATS, incoming)
    for i in range(4):
        fid = buf.record_prediction([float(i), 0.0, 0.0], float(i))
        buf.record_outcome(fid, float(i))
    plan = faults.FaultPlan().crash("lifecycle.feedback.compact")
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash):
            buf.flush()  # CSV + commit landed; compaction never ran
    assert plan.fired("lifecycle.feedback.compact") == 1
    buf2 = FeedbackBuffer(root, FEATS, incoming)  # replays uncompacted WAL
    assert buf2.joined_unflushed() == []  # flushed rows stay flushed
    fid = buf2.record_prediction([9.0, 0.0, 0.0], 9.0)
    buf2.record_outcome(fid, 9.0)
    buf2.flush()  # compacts — must NOT resurrect the earlier flush's rows
    buf3 = FeedbackBuffer(root, FEATS, incoming)
    assert buf3.joined_unflushed() == []
    assert buf3.flush() is None
    # exactly one copy of every row across all CSVs ever written
    seen = []
    for name in sorted(os.listdir(incoming)):
        t = ht.read_csv(
            os.path.join(incoming, name), feedback_schema(FEATS)
        )
        seen.extend(np.asarray(t.column("prediction"), dtype=float))
    assert sorted(seen) == [0.0, 1.0, 2.0, 3.0, 9.0]


def test_feedback_wal_compacts_after_commit(tmp_path):
    """A committed flush drops its rows from memory AND the WAL, while
    id/flush numbering survives compaction — a long-lived server must
    not spool its whole serving history."""
    root = str(tmp_path / "fb")
    incoming = str(tmp_path / "incoming")
    buf = FeedbackBuffer(root, FEATS, incoming)
    for i in range(50):
        fid = buf.record_prediction([float(i), 0.0, 0.0], float(i))
        buf.record_outcome(fid, float(i))
    wal = os.path.join(root, "feedback.log")
    size_before = os.path.getsize(wal)
    buf.flush()
    assert os.path.getsize(wal) < size_before / 4  # 100 records -> 1 meta
    assert buf.joined_unflushed() == [] and buf.pending_outcomes() == 0
    buf2 = FeedbackBuffer(root, FEATS, incoming)  # restart over compacted WAL
    assert buf2.record_prediction([1.0, 0.0, 0.0], 1.0) == 50  # ids continue
    buf2.record_outcome(50, 2.0)
    p = buf2.flush()
    assert p is not None and p.endswith("feedback-000001.csv")  # flush ids too


# ----------------------------------------------------------- decay trigger
def test_metric_decay_triggers_retrain_without_psi(tmp_path, baseline):
    """Same per-feature marginals, scrambled joint structure: PSI stays
    quiet, the evaluation metric decays, and the decay trigger still
    reaches RETRAINING — the breaker path PSI can't see."""
    model, profile, x0 = baseline
    srv, stream, ctrl = _build(
        str(tmp_path),
        drift_threshold=100.0,  # PSI can never fire in this test
        metric_decay_ratio=2.0,
        eval_rows=96,
    )
    ctrl.bootstrap(model, profile, train_x=x0)
    srv.attach_lifecycle(ctrl)
    base = _blobs(np.random.default_rng(30), 4000)
    scramble_rng = np.random.default_rng(31)

    def scrambled(n):
        # each column sampled independently from ITS marginal: per-feature
        # PSI ~ 0, joint structure (and the kmeans cost) destroyed
        return np.column_stack(
            [scramble_rng.choice(base[:, j], size=n) for j in range(3)]
        ).astype(np.float32)

    with srv:
        for _ in range(200):
            srv.predict("kmeans", scrambled(8), wait_timeout_s=10.0)
            if ctrl.state == STATE_RETRAINING:
                break
        assert ctrl.state == STATE_RETRAINING
    entries = ctrl.journal.entries()
    suspected = next(
        e for e in entries if e["state"] == STATE_DRIFT_SUSPECTED
    )
    assert "metric decay" in suspected["info"]["reason"]
    assert ctrl._monitor.trips == 0  # PSI never fired


def test_drift_suspected_recovers_when_signal_does_not_persist(
    tmp_path, baseline
):
    """A transient drift burst suspends, then calm traffic de-escalates
    back to SERVING (the 'recovered' edge) — suspicion must not park
    forever waiting to treat any later noise as confirmation."""
    model, profile, x0 = baseline
    # decay-only trigger (PSI disabled): signals fire ONLY at eval
    # boundaries, so the de-escalation path is deterministic
    srv, stream, ctrl = _build(
        str(tmp_path),
        drift_threshold=100.0,
        eval_rows=128, metric_decay_ratio=2.0,
        recover_after_rows=192,
    )
    ctrl.bootstrap(model, profile, train_x=x0)
    srv.attach_lifecycle(ctrl)
    with srv:
        trng = np.random.default_rng(50)
        # one drifted burst: the first metric eval suspects
        for _ in range(40):
            srv.predict(
                "kmeans", _blobs(trng, 8, SHIFT).astype(np.float32),
                wait_timeout_s=10.0,
            )
            if ctrl.state == STATE_DRIFT_SUSPECTED:
                break
        assert ctrl.state == STATE_DRIFT_SUSPECTED
        # then clean traffic: by the next eval the window is clean-only,
        # so the suspicion must decay, never confirm
        for _ in range(80):
            srv.predict(
                "kmeans", _blobs(trng, 8, 0.0).astype(np.float32),
                wait_timeout_s=10.0,
            )
            if ctrl.state == STATE_SERVING:
                break
        assert ctrl.state == STATE_SERVING
    recovered = [
        e for e in ctrl.journal.entries()
        if e["state"] == STATE_SERVING
        and "recovered" in str(e["info"].get("reason", ""))
    ]
    assert recovered, "recovery transition was never journaled"
    assert ctrl.active_version == 0  # no retrain happened


# ------------------------------------------------------------ snapshot pin
def test_retrain_snapshot_pinned_at_journal_time(tmp_path, baseline):
    """Rows committed AFTER the RETRAINING journal entry must not leak
    into the retrain — the snapshot batch id pins the training set."""
    work = str(tmp_path)
    srv, stream, ctrl = _seed_world(work, baseline)  # 600 rows, batch 0..1
    srv.start()
    try:
        _drive(srv, ctrl, until=lambda c: c.state == STATE_RETRAINING,
               poll=False)
        # late data lands and commits before the controller polls
        _drop_file(
            os.path.join(work, "incoming"), "late.csv",
            _blobs(np.random.default_rng(40), 500, SHIFT),
        )
        while stream.run_once() is not None:
            pass
        assert stream.sink.num_rows() == 1100
        ctrl.poll()  # runs the retrain
    finally:
        srv.stop()
    shadow = next(
        e for e in ctrl.journal.entries() if e["state"] == STATE_SHADOW
    )
    assert shadow["info"]["train_rows"] == 600  # not 1100


def test_unbounded_table_read_upto(tmp_path):
    schema = feedback_schema(FEATS)
    sink = UnboundedTable(str(tmp_path / "t"), schema)
    for bid, n in enumerate((10, 20, 30)):
        x = np.zeros((n, 3))
        cols = {name: x[:, j] for j, name in enumerate(FEATS)}
        cols["prediction"] = np.zeros(n)
        cols["outcome"] = np.zeros(n)
        sink.append_batch(ht.Table.from_dict(cols, schema), bid)
    assert len(sink.read()) == 60
    assert len(sink.read(upto_batch_id=1)) == 30
    assert len(sink.read(upto_batch_id=0)) == 10
    assert len(sink.read()) == 60  # memo key includes the pin


def test_recovery_abandons_cycle_when_retrain_record_is_corrupt(
    tmp_path, baseline
):
    """Post-commit bit rot can eat the RETRAINING line while a later
    SHADOW line survives — the candidate is then unidentifiable and
    recovery must abandon the cycle (journaled) and keep serving the
    baseline, not crash every future construction."""
    model, profile, x0 = baseline
    work = str(tmp_path)
    srv, stream, ctrl = _build(work)
    ctrl.bootstrap(model, profile, train_x=x0)
    ctrl.journal.append(STATE_RETRAINING, 1, {
        "candidate_version": 1, "snapshot_batch_id": 0, "seed": 1,
        "reason": "test",
    })
    ctrl.journal.append(STATE_SHADOW, 1, {"candidate_version": 1})
    with open(ctrl.journal.path, "rb") as f:
        lines = f.readlines()
    assert b'"retraining"' in lines[1]
    assert b'"test"' in lines[1]
    lines[1] = lines[1].replace(b'"test"', b'"tesu"', 1)  # break the CRC
    with open(ctrl.journal.path, "wb") as f:
        f.writelines(lines)
    srv2, stream2, ctrl2 = _build(work)  # must not raise
    assert ctrl2.state == STATE_SERVING
    assert ctrl2.active_version == 0
    rb = next(
        e for e in ctrl2.journal.entries()
        if e["state"] == STATE_ROLLED_BACK
    )
    assert "journal damage" in rb["info"]["reason"]


def test_canary_latency_is_measured_not_zero(tmp_path, baseline):
    """Canary answers must report the candidate's real compute latency,
    not the ~0 of a pre-answered request."""
    work = str(tmp_path)
    srv, stream, ctrl = _seed_world(work, baseline)
    ctrl.canary_min_rows = 10**9
    srv.start()
    try:
        _drive(srv, ctrl, until=lambda c: c.state == STATE_CANARY)
        trng = np.random.default_rng(13)
        canary = []
        for _ in range(16):
            xb = _blobs(trng, 4, SHIFT).astype(np.float32)
            r = srv.predict("kmeans", xb, wait_timeout_s=10.0)
            if r.status == STATUS_CANARY:
                canary.append(r.latency_s)
        assert canary, "no canary answers observed"
        assert all(lat > 0.0 for lat in canary)
    finally:
        srv.stop()


# ------------------------------------------------------------- idempotence
def test_recovery_is_idempotent_without_a_crash(tmp_path, baseline):
    model, profile, x0 = baseline
    work = str(tmp_path)
    srv, stream, ctrl = _build(work)
    ctrl.bootstrap(model, profile, train_x=x0)
    n_entries = len(ctrl.journal.entries())
    srv2, stream2, ctrl2 = _build(work)
    assert ctrl2.state == STATE_SERVING
    assert ctrl2.active_version == 0
    assert len(ctrl2.journal.entries()) == n_entries  # recovery wrote nothing
    assert ctrl2.baseline_metric == pytest.approx(ctrl.baseline_metric)
