"""model.summary parity (Spark TrainingSummary): lazy metrics on fresh
fits, inference statistics on the unregularized LR path, hasSummary=False
after load."""

import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht


def _lr_problem(rng, n=1000, d=4):
    x = rng.normal(size=(n, d)).astype(np.float32)
    beta = np.array([2.0, -1.0, 0.5, 3.0])
    y = (x @ beta + 1.5 + 0.3 * rng.normal(size=n)).astype(np.float32)
    return x, y


def test_linear_regression_summary_metrics(rng, mesh8):
    x, y = _lr_problem(rng)
    m = ht.LinearRegression().fit((x, y), mesh=mesh8)
    assert m.has_summary
    s = m.summary
    assert s.num_instances == len(x)
    # metrics agree with an explicit evaluator pass on the training data
    pred = m.transform((x, y), mesh=mesh8)
    np.testing.assert_allclose(
        s.root_mean_squared_error,
        ht.RegressionEvaluator("rmse").evaluate(pred),
        rtol=1e-6,
    )
    np.testing.assert_allclose(
        s.r2, ht.RegressionEvaluator("r2").evaluate(pred), rtol=1e-6
    )
    assert 0.9 < s.r2 <= 1.0
    assert s.mean_absolute_error < 0.4
    # explained variance ≈ label variance − noise variance on a good fit
    assert s.explained_variance == pytest.approx(np.var(y), rel=0.1)
    # residuals: exactly n entries (pad rows dropped), mean ~0
    res = s.residuals
    assert res.shape == (len(x),)
    assert abs(res.mean()) < 0.05
    assert s.degrees_of_freedom == len(x) - 5
    # releasing the summary unpins the dataset and flips has_summary
    m.release_summary()
    assert not m.has_summary


def test_linear_regression_inference_stats(rng, mesh8):
    stats = pytest.importorskip("scipy.stats")
    x, y = _lr_problem(rng, n=400)
    m = ht.LinearRegression().fit((x, y), mesh=mesh8)
    s = m.summary
    # closed-form OLS reference
    xa = np.c_[x.astype(np.float64), np.ones(len(x))]
    beta = np.linalg.lstsq(xa, y.astype(np.float64), rcond=None)[0]
    resid = y - xa @ beta
    dof = len(x) - xa.shape[1]
    sigma2 = float(resid @ resid) / dof
    se = np.sqrt(np.diag(np.linalg.inv(xa.T @ xa)) * sigma2)
    np.testing.assert_allclose(s.coefficient_standard_errors, se, rtol=2e-2)
    np.testing.assert_allclose(s.t_values, beta / se, rtol=2e-2)
    ref_p = 2 * stats.t.sf(np.abs(beta / se), dof)
    np.testing.assert_allclose(s.p_values, ref_p, atol=1e-4)
    # every true coefficient is significant on this clean signal
    assert (s.p_values[:4] < 1e-6).all()


def test_regularized_fit_raises_on_inference_stats(rng, mesh8):
    x, y = _lr_problem(rng, n=200)
    m = ht.LinearRegression(reg_param=0.5).fit((x, y), mesh=mesh8)
    assert m.summary.root_mean_squared_error > 0  # metrics still fine
    with pytest.raises(RuntimeError, match="unregularized"):
        _ = m.summary.coefficient_standard_errors


def test_summary_absent_after_load(rng, mesh8, tmp_path):
    x, y = _lr_problem(rng, n=200)
    m = ht.LinearRegression().fit((x, y), mesh=mesh8)
    p = os.path.join(tmp_path, "lr")
    m.write().overwrite().save(p)
    back = ht.load_model(p)
    assert not back.has_summary
    with pytest.raises(RuntimeError, match="no training summary"):
        _ = back.summary


def test_logistic_summary(rng, mesh8):
    x, y = _lr_problem(rng, n=1500)
    yb = (y > np.median(y)).astype(np.float32)
    m = ht.LogisticRegression(reg_param=1e-4).fit((x, yb), mesh=mesh8)
    assert m.has_summary
    s = m.summary
    assert 0.85 < s.accuracy <= 1.0
    assert 0.9 < s.area_under_roc <= 1.0
    assert 0.9 < s.area_under_pr <= 1.0
    # per-label PRF vs a hand-built confusion matrix
    pred = m.predict_numpy(x)
    for lbl in (0, 1):
        tp = ((pred == lbl) & (yb == lbl)).sum()
        prec = tp / max((pred == lbl).sum(), 1)
        rec = tp / max((yb == lbl).sum(), 1)
        np.testing.assert_allclose(s.precision_by_label[lbl], prec, rtol=1e-5)
        np.testing.assert_allclose(s.recall_by_label[lbl], rec, rtol=1e-5)
        f1 = 2 * prec * rec / (prec + rec)
        np.testing.assert_allclose(s.f_measure_by_label[lbl], f1, rtol=1e-5)


def test_clustering_summaries(rng, mesh8):
    centers = np.array([[0, 0], [10, 10], [-10, 10]], dtype=np.float32)
    x = np.concatenate(
        [c + rng.normal(0, 0.5, size=(200, 2)).astype(np.float32) for c in centers]
    )
    km = ht.KMeans(k=3, seed=0).fit(x, mesh=mesh8)
    s = km.summary
    assert s.k == 3 and s.num_iter >= 1
    assert s.cluster_sizes.sum() == len(x)
    assert s.training_cost > 0
    assert s.log_likelihood is None

    gm = ht.GaussianMixture(k=3, seed=0, max_iter=20).fit(x, mesh=mesh8)
    gs = gm.summary
    assert gs.k == 3 and np.isfinite(gs.log_likelihood)
    assert gs.training_cost is None


def test_no_intercept_inference_stats(rng, mesh8):
    """fit_intercept=False: SEs/t/p computed on the no-intercept design,
    no bogus intercept entry."""
    stats = pytest.importorskip("scipy.stats")
    x, y0 = _lr_problem(rng, n=400)
    y = (y0 - y0.mean()).astype(np.float32)
    m = ht.LinearRegression(fit_intercept=False).fit((x, y), mesh=mesh8)
    s = m.summary
    xa = x.astype(np.float64)
    beta = np.linalg.lstsq(xa, y.astype(np.float64), rcond=None)[0]
    resid = y - xa @ beta
    dof = len(x) - xa.shape[1]
    assert s.degrees_of_freedom == dof
    sigma2 = float(resid @ resid) / dof
    se = np.sqrt(np.diag(np.linalg.inv(xa.T @ xa)) * sigma2)
    assert s.coefficient_standard_errors.shape == (4,)
    np.testing.assert_allclose(s.coefficient_standard_errors, se, rtol=2e-2)
    np.testing.assert_allclose(s.t_values, beta / se, rtol=2e-2)


def test_collinear_design_raises_on_standard_errors(rng, mesh8):
    """Dummy-variable trap: exactly collinear columns + intercept — the
    fit succeeds (jittered solve) but inference stats refuse."""
    x0 = rng.normal(size=(300, 2)).astype(np.float32)
    x = np.c_[x0, x0[:, 0] + x0[:, 1]]  # third col = sum of first two
    y = (x0 @ np.array([1.0, 2.0]) + 0.1 * rng.normal(size=300)).astype(np.float32)
    m = ht.LinearRegression().fit((x, y), mesh=mesh8)
    assert np.isfinite(m.summary.root_mean_squared_error)
    with pytest.raises(RuntimeError, match="collinear"):
        _ = m.summary.coefficient_standard_errors


def test_chi_square_on_device_dataset(rng, mesh8):
    """Padded DeviceDataset + fractional weights: pad rows drop from
    features and labels together; weights scale the contingency counts."""
    n = 1001  # not a multiple of 8 — forces padding
    y = rng.integers(0, 2, size=n).astype(np.float64)
    x = np.c_[y, rng.integers(0, 3, size=n)].astype(np.float64)
    w = rng.integers(1, 3, size=n).astype(np.float64)
    ds = ht.device_dataset(x, y, mesh=mesh8, weights=w)
    res = ht.ChiSquareTest.test(ds, np.asarray(ds.y))
    # integer weights ≡ duplication
    rep = np.repeat(np.arange(n), w.astype(int))
    ref = ht.ChiSquareTest.test(x[rep], y[rep])
    np.testing.assert_allclose(res.statistics, ref.statistics, rtol=1e-6)
    assert res.p_values[0] < 1e-10 and res.p_values[1] > 0.001


def test_var_metric_is_larger_better():
    assert ht.RegressionEvaluator("var").is_larger_better


def test_spearman_rejects_fractional_weights(rng, mesh8):
    x = rng.normal(size=(100, 3)).astype(np.float32)
    ds = ht.device_dataset(x, mesh=mesh8, weights=rng.uniform(0.1, 2.0, 100))
    with pytest.raises(ValueError, match="fractional"):
        ht.Correlation.corr(ds, method="spearman")
    # 0/1 weights are fine (pad rows dropped)
    ds2 = ht.device_dataset(x, mesh=mesh8)
    r = ht.Correlation.corr(ds2, method="spearman")
    assert r.shape == (3, 3)


def test_explained_variance_evaluator(rng, mesh8):
    """The new 'var' metric: Σw(ŷ−ȳ)²/Σw."""
    x, y = _lr_problem(rng, n=500)
    m = ht.LinearRegression().fit((x, y), mesh=mesh8)
    pred = m.transform((x, y), mesh=mesh8)
    var = ht.RegressionEvaluator("var").evaluate(pred)
    p, l = pred.to_numpy()
    np.testing.assert_allclose(var, np.mean((p - l.mean()) ** 2), rtol=1e-4)


# ---------------------------------------------------------------- ml.stat F/KS
@pytest.mark.fast
def test_kolmogorov_smirnov_matches_scipy(rng, mesh8):
    sps = pytest.importorskip("scipy.stats")
    x = rng.normal(1.5, 2.0, size=1000).astype(np.float32)[:, None]
    res = ht.KolmogorovSmirnovTest.test(x, "norm", mean=1.5, std=2.0, mesh=mesh8)
    ref = sps.kstest(x[:, 0], "norm", args=(1.5, 2.0))
    np.testing.assert_allclose(res.statistic, ref.statistic, atol=1e-6)
    np.testing.assert_allclose(res.p_value, ref.pvalue, atol=1e-4)
    # a wrong null is decisively rejected
    bad = ht.KolmogorovSmirnovTest.test(x, "norm", mean=0.0, std=1.0, mesh=mesh8)
    assert bad.p_value < 1e-6
    # odd row count (padding) must not bias the ECDF
    x7 = rng.normal(size=777).astype(np.float32)[:, None]
    res7 = ht.KolmogorovSmirnovTest.test(x7, mesh=mesh8)
    ref7 = sps.kstest(x7[:, 0], "norm")
    np.testing.assert_allclose(res7.statistic, ref7.statistic, atol=1e-6)
    with pytest.raises(ValueError, match="norm"):
        ht.KolmogorovSmirnovTest.test(x, "uniform", mesh=mesh8)
    with pytest.raises(ValueError, match="single-column"):
        ht.KolmogorovSmirnovTest.test(rng.normal(size=(10, 2)), mesh=mesh8)


def test_anova_matches_scipy(rng, mesh8):
    sps = pytest.importorskip("scipy.stats")
    n, d, k = 900, 3, 4
    y = rng.integers(0, k, size=n)
    x = rng.normal(size=(n, d))
    x[:, 0] += 0.8 * y          # feature 0 depends on the class
    res = ht.ANOVATest.test(x.astype(np.float32), y.astype(np.float32), mesh=mesh8)
    for j in range(d):
        groups = [x[y == c, j] for c in range(k)]
        ref = sps.f_oneway(*groups)
        np.testing.assert_allclose(res.f_values[j], ref.statistic, rtol=1e-4)
        np.testing.assert_allclose(res.p_values[j], ref.pvalue, atol=1e-6)
    assert res.p_values[0] < 1e-10 and res.p_values[1] > 1e-4


def test_anova_fvalue_large_mean_stable(rng, mesh8):
    """Year-column regime (mean ≫ std): uncentered f32 Σx² loses the
    entire within-class signal — the centered stats must stay exact."""
    sps = pytest.importorskip("scipy.stats")
    skf = pytest.importorskip("sklearn.feature_selection")
    n = 4000
    y = rng.integers(0, 2, size=n)
    x = (2026.0 + y * 0.8 + rng.normal(0, 1.0, size=n)).astype(np.float64)[:, None]
    ra = ht.ANOVATest.test(x.astype(np.float32), y.astype(np.float32), mesh=mesh8)
    ref = sps.f_oneway(x[y == 0, 0], x[y == 1, 0])
    np.testing.assert_allclose(ra.f_values[0], ref.statistic, rtol=1e-3)
    yr = (x[:, 0] - 2026.0) * 2 + rng.normal(size=n)
    rf = ht.FValueTest.test(x.astype(np.float32), yr.astype(np.float32), mesh=mesh8)
    f_ref, _ = skf.f_regression(x, yr)
    np.testing.assert_allclose(rf.f_values[0], f_ref[0], rtol=1e-3)


def test_anova_absent_class_dof(rng, mesh8):
    """Non-contiguous label ids (class 1 absent): dof must count OBSERVED
    classes or F/p silently drift from scipy."""
    sps = pytest.importorskip("scipy.stats")
    y = np.array([0] * 30 + [2] * 30)
    x = (rng.normal(size=60) + 0.5 * (y == 2)).astype(np.float64)[:, None]
    res = ht.ANOVATest.test(x.astype(np.float32), y.astype(np.float32), mesh=mesh8)
    ref = sps.f_oneway(x[y == 0, 0], x[y == 2, 0])
    np.testing.assert_allclose(res.f_values[0], ref.statistic, rtol=1e-4)
    np.testing.assert_allclose(res.p_values[0], ref.pvalue, atol=1e-6)


def test_fvalue_matches_sklearn(rng, mesh8):
    skf = pytest.importorskip("sklearn.feature_selection")
    n, d = 1200, 4
    x = rng.normal(size=(n, d))
    y = 2.0 * x[:, 0] + 0.3 * x[:, 1] + rng.normal(size=n)
    res = ht.FValueTest.test(x.astype(np.float32), y.astype(np.float32), mesh=mesh8)
    f_ref, p_ref = skf.f_regression(x, y)
    np.testing.assert_allclose(res.f_values, f_ref, rtol=2e-3)
    np.testing.assert_allclose(res.p_values, p_ref, atol=1e-5)
    assert res.p_values[0] < 1e-20 and res.p_values[2] > 1e-4
    # label/feature length mismatch must raise, not zero-fill
    with pytest.raises(ValueError, match="label"):
        ht.FValueTest.test(x.astype(np.float32), y[:-100].astype(np.float32), mesh=mesh8)
    with pytest.raises(ValueError, match="label"):
        ht.ANOVATest.test(
            x.astype(np.float32), np.zeros(n - 50, np.float32), mesh=mesh8
        )


def test_linear_regression_r2adj(rng, mesh8):
    x, y = _lr_problem(rng, n=200)
    m = ht.LinearRegression().fit((x, y), mesh=mesh8)
    s = m.summary
    n, p = 200, 4
    expect = 1.0 - (1.0 - s.r2) * (n - 1) / (n - p - 1)
    np.testing.assert_allclose(s.r2adj, expect, rtol=1e-6)
    assert s.r2adj < s.r2  # adjustment always penalizes


def test_logistic_summary_curves_sklearn_parity(rng, mesh8):
    """roc / pr / *ByThreshold against sklearn's curve functions."""
    from sklearn.metrics import precision_recall_curve, roc_curve

    n, d = 400, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    yb = (x @ np.array([1.0, -2.0, 0.5]) + 0.2 * rng.normal(size=n) > 0).astype(
        np.float32
    )
    m = ht.LogisticRegression(max_iter=20).fit((x, yb), mesh=mesh8)
    s = m.summary
    ds = ht.device_dataset(x, yb, mesh=mesh8)
    scores = np.asarray(m.predict_proba(ds.x))[:n]

    fpr, tpr, _ = roc_curve(yb, scores)
    ours = s.roc
    # same monotone curve: compare TPR sampled at shared FPR grid
    grid = np.linspace(0, 1, 51)
    np.testing.assert_allclose(
        np.interp(grid, ours[:, 0], ours[:, 1]),
        np.interp(grid, fpr, tpr),
        atol=0.02,
    )

    prec, rec, _ = precision_recall_curve(yb, scores)
    ours_pr = s.pr
    np.testing.assert_allclose(
        np.interp(grid, ours_pr[:, 0], ours_pr[:, 1]),
        np.interp(grid, rec[::-1], prec[::-1]),
        atol=0.03,
    )

    # threshold curves: precision/recall at each distinct score cut
    pbt = s.precision_by_threshold()
    rbt = s.recall_by_threshold()
    fbt = s.f_measure_by_threshold()
    assert pbt.shape == rbt.shape == fbt.shape
    for thr, pv in pbt[:: max(1, len(pbt) // 20)]:
        mask = scores >= thr
        np.testing.assert_allclose(
            pv, yb[mask].sum() / max(mask.sum(), 1), atol=1e-5
        )
    t_star = s.max_f_measure_threshold
    assert fbt[:, 1].max() == pytest.approx(
        fbt[np.argmin(np.abs(fbt[:, 0] - t_star)), 1]
    )


def test_logistic_summary_weighted_metrics(rng, mesh8):
    from sklearn.metrics import precision_score, recall_score, f1_score

    n, d = 300, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    yb = (x @ np.array([1.0, -1.0, 2.0]) > 0.4).astype(np.float32)
    m = ht.LogisticRegression(max_iter=20).fit((x, yb), mesh=mesh8)
    s = m.summary
    ds = ht.device_dataset(x, yb, mesh=mesh8)
    pred = np.asarray(m.predict(ds.x))[:n]
    np.testing.assert_allclose(
        s.weighted_precision,
        precision_score(yb, pred, average="weighted"),
        atol=1e-6,
    )
    np.testing.assert_allclose(
        s.weighted_recall, recall_score(yb, pred, average="weighted"), atol=1e-6
    )
    np.testing.assert_allclose(
        s.weighted_f_measure, f1_score(yb, pred, average="weighted"), atol=1e-6
    )
    assert s.weighted_true_positive_rate == pytest.approx(s.weighted_recall)
    assert 0.0 <= s.weighted_false_positive_rate <= 1.0


def test_multinomial_logistic_summary(rng, mesh8):
    from sklearn.metrics import f1_score, precision_score, recall_score

    n, d, K = 450, 4, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    logits = x @ rng.normal(size=(d, K))
    y = logits.argmax(axis=1).astype(np.float32)
    # 15% label noise → real cross-class confusion, so a confusion matrix
    # clipped to 2 classes (the bug class this guards against) would
    # miscount label-1↔2 errors as correct and report inflated accuracy
    flip = rng.random(n) < 0.15
    y[flip] = rng.integers(0, K, flip.sum()).astype(np.float32)
    m = ht.LogisticRegression(family="multinomial", max_iter=25).fit(
        (x, y), mesh=mesh8
    )
    assert m.has_summary
    s = m.summary
    assert s.num_classes == K
    ds = ht.device_dataset(x, y, mesh=mesh8)
    pred = np.asarray(m.predict(ds.x))[:n]
    acc = (pred == y).mean()
    assert acc < 0.99  # noise guaranteed real misclassifications
    np.testing.assert_allclose(s.accuracy, acc, atol=1e-6)
    np.testing.assert_allclose(
        s.weighted_precision,
        precision_score(y, pred, average="weighted"),
        atol=1e-6,
    )
    np.testing.assert_allclose(
        s.weighted_recall, recall_score(y, pred, average="weighted"), atol=1e-6
    )
    np.testing.assert_allclose(
        s.weighted_f_measure, f1_score(y, pred, average="weighted"), atol=1e-6
    )
    assert s.precision_by_label.shape == (K,)
    assert s.true_positive_rate_by_label.shape == (K,)
    assert np.all(s.false_positive_rate_by_label <= 1.0)
    # no ROC surface on the multiclass summary (Spark parity)
    assert not hasattr(s, "area_under_roc")
    m.release_summary()
    assert not m.has_summary
    with pytest.raises(RuntimeError, match="no training summary"):
        _ = m.summary


def test_threshold_curve_excludes_pad_rows(rng, mesh8):
    """Sharding pad rows (w=0) must not mint thresholds: every curve
    threshold corresponds to at least one real weighted instance."""
    n = 450  # not divisible by 8 -> 6 pad rows on the mesh
    x = rng.normal(size=(n, 3)).astype(np.float32)
    yb = (x[:, 0] > 0).astype(np.float32)
    m = ht.LogisticRegression(max_iter=15).fit((x, yb), mesh=mesh8)
    s = m.summary
    ds = ht.device_dataset(x, yb, mesh=mesh8)
    real_scores = np.unique(np.asarray(m.predict_proba(ds.x))[:n].astype(np.float32))
    thr = s.precision_by_threshold()[:, 0]
    assert np.all(np.isin(thr, real_scores))
