"""Device-resident boosting (PR 5): fused-round GBT parity + sync contract.

The tentpole collapses a GBT fit from O(M·depth) per-level host round
trips to O(1): ``engine._make_forest_grower`` grows every tree level in
ONE jitted dispatch, and ``gbt._boost`` chains all M rounds through a
single ``lax.scan`` (residual refresh + growth + leaf advance in the same
device computation).  These tests pin the contract:

- fused-round GBT == the per-round deferred loop (``fused_rounds=False``)
  tree-for-tree — structure, thresholds, leaf values — on fixed seeds,
  for regression, classification, and Poisson-subsampled fits;
- the engine's fused multi-level path (``fused_levels``) == the per-level
  loop for RF-style fits too (feature subsets, bootstrap, categoricals);
- the out-of-core and fit-checkpoint paths still agree with the fused
  resident result, including kill-and-resume through an injected crash
  in the checkpoint save protocol (chaos tier);
- a transfer census proves the fused fit's host-sync count is a small
  constant independent of ``max_iter`` (perf tier), and the StageClock
  instrumentation the gbt20 bench row reports stays truthful.

Integer-valued features keep every histogram sum f32-exact, so split
decisions compare bit-for-bit across paths (same trick as
tests/test_fit_checkpoint.py)."""

import importlib.util
import os

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models import (
    GBTClassifier,
    GBTRegressor,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.tree.engine import (
    grow_forest,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.sharding import (
    device_dataset,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils import faults
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils.profiling import (
    StageClock,
    host_sync_census,
)


def _tree_data(rng, n=1500, d=5):
    x = np.round(rng.normal(size=(n, d)) * 4).astype(np.float32)
    y = (x @ rng.normal(size=(d,)) + rng.normal(0, 0.3, size=n)).astype(
        np.float32
    )
    return x, y


def _assert_same_model(a, b, *, value_atol=0.0):
    """Same trees: structure, thresholds, leaf values, importances, F0."""
    np.testing.assert_array_equal(a.split_feat, b.split_feat)
    np.testing.assert_array_equal(a.threshold, b.threshold)
    if value_atol:
        np.testing.assert_allclose(a.value, b.value, atol=value_atol)
        np.testing.assert_allclose(a.init, b.init, rtol=1e-5)
    else:
        np.testing.assert_array_equal(a.value, b.value)
        assert a.init == b.init
    np.testing.assert_allclose(
        a.feature_importances, b.feature_importances, atol=1e-6
    )


# ======================================================= fused-round parity
def test_fused_rounds_regression_identical_trees(rng, mesh8):
    x, y = _tree_data(rng)
    ds = device_dataset(x, y, mesh=mesh8)
    base = dict(max_iter=6, max_depth=3, seed=0)
    fused = GBTRegressor(**base).fit(ds, mesh=mesh8)
    legacy = GBTRegressor(fused_rounds=False, **base).fit(ds, mesh=mesh8)
    # the full pre-fusion baseline (per-round loop + per-level dispatches)
    # — the leg the gbt20 bench A/B times as "legacy"
    prefusion = GBTRegressor(
        fused_rounds=False, fused_levels=False, **base
    ).fit(ds, mesh=mesh8)
    _assert_same_model(fused, legacy)
    _assert_same_model(fused, prefusion)
    pred_f = np.asarray(fused.predict_numpy(x[:128]))
    pred_l = np.asarray(legacy.predict_numpy(x[:128]))
    np.testing.assert_array_equal(pred_f, pred_l)


def test_fused_rounds_classification_identical_trees(rng, mesh8):
    x, _ = _tree_data(rng)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float32)
    ds = device_dataset(x, y, mesh=mesh8)
    base = dict(max_iter=5, max_depth=2, seed=1)
    fused = GBTClassifier(**base).fit(ds, mesh=mesh8)
    legacy = GBTClassifier(fused_rounds=False, **base).fit(ds, mesh=mesh8)
    _assert_same_model(fused, legacy)


def test_fused_rounds_categorical_identical_trees(rng, mesh8):
    """The boost scan's categorical branch (cat_flags + per-round catmask
    threading through device_tree_arrays/predict_forest) lands on the
    same trees as the per-round deferred loop — the one fused path the
    continuous-data parity tests above cannot pin."""
    x, _ = _tree_data(rng, n=1200, d=4)
    x[:, 2] = rng.integers(0, 6, x.shape[0]).astype(np.float32)
    # non-monotone category effect → only an unordered SET split captures it
    y = (
        0.2 * x[:, 0]
        + np.where(np.isin(x[:, 2], (1.0, 4.0)), 6.0, 0.0)
        + rng.normal(0, 0.1, x.shape[0])
    ).astype(np.float32)
    ds = device_dataset(x, y, mesh=mesh8)
    base = dict(
        max_iter=5, max_depth=3, seed=4, categorical_features={2: 6}
    )
    fused = GBTRegressor(**base).fit(ds, mesh=mesh8)
    legacy = GBTRegressor(fused_rounds=False, **base).fit(ds, mesh=mesh8)
    _assert_same_model(fused, legacy)
    np.testing.assert_array_equal(fused.split_catmask, legacy.split_catmask)
    assert (fused.split_catmask > 0).any(), "fit never took a set split"
    pred_f = np.asarray(fused.predict_numpy(x[:128]))
    pred_l = np.asarray(legacy.predict_numpy(x[:128]))
    np.testing.assert_array_equal(pred_f, pred_l)


def test_fused_rounds_subsampled_identical_trees(rng, mesh8):
    """Poisson bootstrap inside the scan draws the SAME per-round weights
    as the legacy loop's _make_bootstrap(seed + t) — key-stream parity."""
    x, y = _tree_data(rng)
    ds = device_dataset(x, y, mesh=mesh8)
    base = dict(max_iter=4, max_depth=2, seed=2, subsampling_rate=0.7)
    fused = GBTRegressor(**base).fit(ds, mesh=mesh8)
    legacy = GBTRegressor(fused_rounds=False, **base).fit(ds, mesh=mesh8)
    _assert_same_model(fused, legacy)


# =================================================== fused-level engine path
def test_fused_levels_forest_parity_with_subsets(rng, mesh8):
    """RF shape: feature subsets + bootstrap — the fused grower's
    rank-of-uniform draw must replicate _make_subset_mask's stream."""
    x, y = _tree_data(rng, n=1200, d=4)
    ds = device_dataset(x, y, mesh=mesh8)
    kw = dict(
        task="regression", num_trees=4, max_depth=4, feature_subset_size=2,
        bootstrap=True, subsampling_rate=0.8, seed=3, mesh=mesh8,
    )
    fused = grow_forest(ds, fused_levels=True, **kw)
    legacy = grow_forest(ds, fused_levels=False, **kw)
    np.testing.assert_array_equal(fused.split_feat, legacy.split_feat)
    np.testing.assert_array_equal(fused.split_bin, legacy.split_bin)
    np.testing.assert_array_equal(fused.threshold, legacy.threshold)
    np.testing.assert_array_equal(fused.value, legacy.value)
    np.testing.assert_allclose(
        fused.importances, legacy.importances, atol=1e-7
    )


def test_fused_levels_forest_parity_categorical(rng, mesh8):
    """Unordered-set categorical splits route identically through the
    fused grower (catmask threading into _advance_level)."""
    x, _ = _tree_data(rng, n=1000, d=4)
    x[:, 1] = rng.integers(0, 5, x.shape[0]).astype(np.float32)
    y = ((x[:, 0] > 0) ^ (x[:, 1] >= 3)).astype(np.float32)
    ds = device_dataset(x, y, mesh=mesh8)
    kw = dict(
        task="classification", num_classes=2, num_trees=2, max_depth=3,
        categorical_features={1: 5}, seed=0, mesh=mesh8,
    )
    fused = grow_forest(ds, fused_levels=True, **kw)
    legacy = grow_forest(ds, fused_levels=False, **kw)
    np.testing.assert_array_equal(fused.split_feat, legacy.split_feat)
    np.testing.assert_array_equal(fused.split_catmask, legacy.split_catmask)
    np.testing.assert_array_equal(fused.value, legacy.value)


def test_estimator_fused_levels_flag_round_trips(rng, mesh8):
    """The _TreeParams knob reaches the engine: both settings produce the
    same RF model (parity), and out-of-core fits accept the flag (it is
    dropped — streaming levels are inherently per-level passes)."""
    x, y = _tree_data(rng, n=900, d=4)
    rf = dict(num_trees=3, max_depth=3, seed=0,
              feature_subset_strategy="all")
    m_f = ht.RandomForestRegressor(fused_levels=True, **rf).fit(
        (x, y), mesh=mesh8
    )
    m_l = ht.RandomForestRegressor(fused_levels=False, **rf).fit(
        (x, y), mesh=mesh8
    )
    np.testing.assert_array_equal(m_f.split_feat, m_l.split_feat)
    np.testing.assert_array_equal(m_f.value, m_l.value)
    hd = ht.HostDataset(x=x, y=y, max_device_rows=256)
    m_ooc = ht.DecisionTreeRegressor(
        max_depth=3, seed=0, fused_levels=True
    ).fit(hd, mesh=mesh8)
    m_res = ht.DecisionTreeRegressor(max_depth=3, seed=0).fit(
        (x, y), mesh=mesh8
    )
    np.testing.assert_array_equal(m_ooc.split_feat, m_res.split_feat)


# ===================================== out-of-core / checkpoint consistency
#: base GBT config shared by the out-of-core consistency + chaos tests
_OOC_BASE = dict(max_iter=4, max_depth=2, seed=0)


@pytest.fixture(scope="module")
def ooc_case(mesh8):
    """One out-of-core reference fit + one fused resident fit, shared by
    the consistency check and both chaos kill sites (the streamed-block
    fits are the slow part of this file — compute each exactly once)."""
    x, y = _tree_data(np.random.default_rng(0), n=1000, d=4)
    hd = ht.HostDataset(x=x, y=y, max_device_rows=256)
    uninterrupted = GBTRegressor(**_OOC_BASE).fit(hd, mesh=mesh8)
    fused = GBTRegressor(**_OOC_BASE).fit((x, y), mesh=mesh8)
    return x, y, uninterrupted, fused


def test_outofcore_gbt_matches_fused_resident(ooc_case):
    """The streaming (HostDataset) boost — per-round, per-level passes —
    lands on the same trees as the fused device-resident fit."""
    _, _, ooc, fused = ooc_case
    _assert_same_model(ooc, fused, value_atol=1e-6)


@pytest.mark.chaos
@pytest.mark.parametrize(
    "site", ["fit_ckpt.save.arrays", "fit_ckpt.save.commit"]
)
def test_gbt_checkpoint_kill_and_resume_matches_fused(
    tmp_path, mesh8, ooc_case, site,
):
    """Kill a checkpointed out-of-core GBT boost inside the save protocol
    (before / at the commit point); the resumed fit must land on EXACTLY
    the uninterrupted out-of-core model, which itself matches the fused
    device-resident fit — the chaos leg of the round-fusion parity gate
    (tools/run_chaos.sh runs this)."""
    x, y, uninterrupted, fused = ooc_case
    hd = ht.HostDataset(x=x, y=y, max_device_rows=256)
    base = _OOC_BASE

    ckdir = str(tmp_path / "gbt_ck")
    est = GBTRegressor(checkpoint_dir=ckdir, checkpoint_every=1, **base)
    plan = faults.FaultPlan().crash(site, after=1)  # die on round-1's save
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash):
            est.fit(hd, mesh=mesh8)
    assert plan.fired(site) == 1

    resumed = est.fit(hd, mesh=mesh8)
    _assert_same_model(resumed, uninterrupted, value_atol=1e-7)
    _assert_same_model(resumed, fused, value_atol=1e-6)


# ============================================================ sync contract
@pytest.mark.perf
def test_fused_fit_host_syncs_constant_in_rounds(rng, mesh8):
    """The O(1)-syncs-per-fit contract: the transfer census over a fused
    fit counts the SAME small number of blocking device_get calls at
    M=3 and M=9 — not O(M·depth) per-level fetches."""
    x, y = _tree_data(rng, n=1000, d=4)
    ds = device_dataset(x, y, mesh=mesh8)

    def syncs(m):
        est = GBTRegressor(max_iter=m, max_depth=3, seed=0)
        est.fit(ds, mesh=mesh8)          # warm-up outside the census
        with host_sync_census() as census:
            est.fit(ds, mesh=mesh8)
        return census["device_get"]

    s3, s9 = syncs(3), syncs(9)
    assert s3 == s9, f"sync count grew with rounds: M=3→{s3}, M=9→{s9}"
    assert s3 <= 6, f"fused fit made {s3} host syncs; expected O(1) ≤ 6"
    assert s9 < 9 * 4, "sync count is not below the per-level O(M·depth) bar"


@pytest.mark.perf
def test_stage_clock_brackets_fused_fit(rng, mesh8):
    """The gbt20 bench row's per-stage shares come from this plumbing:
    one entry per stage per fit, shares normalized over the fit."""
    x, y = _tree_data(rng, n=800, d=4)
    ds = device_dataset(x, y, mesh=mesh8)
    clock = StageClock()
    GBTRegressor(max_iter=4, max_depth=2, seed=0, stage_clock=clock).fit(
        ds, mesh=mesh8
    )
    assert set(clock.seconds) == {"bin", "init", "boost", "fetch_materialize"}
    assert all(c == 1 for c in clock.counts.values())
    shares = clock.shares()
    assert abs(sum(shares.values()) - 1.0) < 1e-9
    assert shares["boost"] > 0.0


# ============================================================ bench schema
def _load_bench():
    path = os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py")
    spec = importlib.util.spec_from_file_location("bench_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.perf
def test_bench_roofline_fields_schema():
    """The roofline helpers behind the gbt20/rf20/gmm32/nb rows: every
    bound reports pct_of_roofline + its formula note, and the GBT bytes
    bound scales with rounds × levels (the quantity fusion cannot cut)."""
    bench = _load_bench()
    hist = bench._hist_bytes_roofline(
        1e4, T=1, depth=3, d=8, S=3, rounds=20, device_kind="cpu-proxy"
    )
    assert {"pct_of_roofline", "hist_bytes_per_row_fit",
            "hist_hbm_bound_rows_per_s_chip", "roofline_note"} <= set(hist)
    assert hist["hist_bytes_per_row_fit"] == 20 * 4 * 4.0 * (8 + 3 + 2)
    rf = bench._hist_bytes_roofline(
        1e5, T=20, depth=5, d=8, S=3, rounds=1, device_kind="cpu-proxy"
    )
    assert rf["hist_bytes_per_row_fit"] == 6 * 4.0 * (8 + 3 + 40)
    gmm = bench._gmm_roofline(1e4, 32, 8, "highest", "cpu-proxy")
    assert {"pct_of_roofline", "achieved_tflops",
            "mxu_dlimited_bound_tflops"} <= set(gmm)
    nb = bench._nb_bytes_roofline(1e6, 32, "cpu-proxy")
    assert nb["bytes_per_row"] == 4.0 * 33
    assert nb["pct_of_roofline"] > 0
    # the fused_stats A/B rides the default watch list (VERDICT r5 #4)
    assert "kmeans_fused_ab" in bench.CONFIGS
    assert "kmeans_fused_ab" in bench._TPU_PRIORITY
