"""Model-farm tests (ISSUE 11): vmapped per-tenant fits as ONE program.

The load-bearing assertions:

1. **Bit-parity** — the farm fit (one dispatch for T tenants) equals a
   Python loop of per-tenant dispatches of the same kernel EXACTLY, for
   fit parameters AND predictions, linear and k-means both.  This is
   what makes the ≥20×/≥50× bench number a pure-overhead win, not a
   different algorithm.
2. **Ragged degradation** — 1-row, empty, and all-NaN tenants follow the
   quality stance (NaN is missing; an evidence-free tenant lands on the
   pooled global model under pooling) without poisoning anyone else.
3. **One artifact** — save/load round-trips the whole fleet (manifest +
   stacked arrays + per-tenant sketches) through io/model_io unchanged.
4. **Serve routing** — tenant-id → farm index rides in-band through the
   standard bucket ladder: zero steady-state recompiles across tenants
   and batch sizes.
5. **Drifted-subset refit** — only the drifted tenants' parameters
   change; every other slice (and the global slot) stays byte-identical.
6. **Chaos** — a farm fit killed inside the checkpoint save protocol
   resumes bit-identically.
"""

from __future__ import annotations

import os

import numpy as np
import pytest
import jax.numpy as jnp

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.farm import (
    FarmKMeans,
    FarmLinearRegression,
    ModelFarmModel,
    drifted_tenants,
    pack_tenants,
    tenant_psi,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.farm.farm import (
    _init_farm_centers,
    _make_farm_kmeans_loop,
    _single_linear_fit,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io.model_io import (
    load_model,
)
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.utils import faults

pytestmark = pytest.mark.farm

D = 4
THETA = np.array([1.0, -2.0, 0.5, 3.0])


def _fleet(n_tenants: int = 24, seed: int = 0, min_rows: int = 2,
           max_rows: int = 40) -> dict:
    """Ragged per-hospital regression datasets with a shared signal and
    per-tenant perturbations."""
    rng = np.random.default_rng(seed)
    data = {}
    for t in range(n_tenants):
        n = int(rng.integers(min_rows, max_rows))
        x = rng.normal(size=(n, D))
        theta_t = THETA + 0.2 * rng.normal(size=D)
        y = x @ theta_t + 0.7 + 0.01 * rng.normal(size=n)
        data[f"H{t:03d}"] = (x, y)
    return data


@pytest.fixture(scope="module")
def fleet():
    return _fleet()


@pytest.fixture(scope="module")
def linear_farm(fleet):
    return FarmLinearRegression(reg_param=0.1, pool=0.0).fit(fleet)


@pytest.fixture(scope="module")
def kmeans_farm(fleet):
    return FarmKMeans(k=3, max_iter=12, seed=1).fit(
        {t: v[0] for t, v in fleet.items()}
    )


# ===================================================================== parity
def test_linear_farm_vs_looped_bit_parity(fleet, linear_farm):
    """Farm fit == a loop of per-tenant dispatches of the SAME kernel,
    bit-for-bit, params and predictions — every tenant."""
    batch = pack_tenants(fleet)
    zeros = jnp.zeros((D + 1,), jnp.float32)
    for i, tid in enumerate(batch.tenant_ids):
        theta = np.asarray(
            _single_linear_fit(
                jnp.asarray(batch.x[i]), jnp.asarray(batch.y[i]),
                jnp.asarray(batch.w[i]),
                jnp.float32(0.1), jnp.float32(0.0), zeros, True,
            )
        )
        got = np.concatenate(
            [
                linear_farm.arrays["coefficients"][i],
                [linear_farm.arrays["intercepts"][i]],
            ]
        )
        np.testing.assert_array_equal(theta, got)
    # prediction parity: ONE mixed-tenant dispatch == a loop of
    # per-tenant dispatches of the same serving kernel, bit-for-bit
    ids = list(batch.tenant_ids)[:8]
    big = np.concatenate(
        [linear_farm.route_request(t, np.asarray(fleet[t][0])) for t in ids]
    )
    big_out = np.asarray(linear_farm.predict(jnp.asarray(big, jnp.float32)))
    ofs = 0
    for t in ids:
        n = len(fleet[t][1])
        looped = linear_farm.predict_tenant(t, np.asarray(fleet[t][0]))
        np.testing.assert_array_equal(big_out[ofs : ofs + n], looped)
        # ... and the materialized per-tenant family slice agrees to ulp
        sliced = linear_farm.tenant_model(t).predict_numpy(
            np.asarray(fleet[t][0], dtype=np.float32)
        )
        np.testing.assert_allclose(looped, sliced, atol=1e-5)
        ofs += n


def test_kmeans_farm_vs_looped_bit_parity(fleet, kmeans_farm):
    """Same for k-means: centers AND assignments, with the per-tenant
    seeded init stream shared between both paths."""
    kdata = {t: v[0] for t, v in fleet.items()}
    batch = pack_tenants(kdata)
    loop = _make_farm_kmeans_loop(12, float(1e-4) ** 2)
    for i, tid in enumerate(batch.tenant_ids):
        c0, cv = _init_farm_centers(
            batch.x[i : i + 1], batch.w[i : i + 1], 3, 1, base_index=i
        )
        cen, _, _, _ = loop(
            jnp.asarray(batch.x[i : i + 1]), jnp.asarray(batch.w[i : i + 1]),
            jnp.asarray(c0), jnp.asarray(cv),
        )
        np.testing.assert_array_equal(
            np.asarray(cen)[0], kmeans_farm.arrays["centers"][i]
        )
    # assignments through the routed predict match the tenant slice
    tid = batch.tenant_ids[3]
    x = np.asarray(kdata[tid], dtype=np.float32)
    routed = kmeans_farm.predict_tenant(tid, x)
    sliced = kmeans_farm.tenant_model(tid).predict_numpy(x)
    np.testing.assert_array_equal(routed.astype(int), sliced.astype(int))


def test_linear_matches_batch_family(fleet):
    """A 1-tenant farm reproduces the ordinary LinearRegression fit
    (unstandardized) to f32 noise — the farm is a packing, not a new
    algorithm."""
    tid = "H005"
    x, y = np.asarray(fleet[tid][0]), np.asarray(fleet[tid][1])
    lr = ht.models.LinearRegression(reg_param=0.0, standardize=False).fit(
        (x, y)
    )
    fm = FarmLinearRegression(reg_param=0.0, pool=0.0).fit({tid: fleet[tid]})
    np.testing.assert_allclose(
        np.asarray(lr.coefficients),
        fm.arrays["coefficients"][0], atol=1e-4,
    )
    np.testing.assert_allclose(
        float(lr.intercept), fm.arrays["intercepts"][0], atol=1e-4
    )


# ============================================================== ragged edges
def test_one_row_tenant_is_finite_and_pooled():
    data = _fleet(6)
    data["tiny"] = (np.array([[1.0, 0.0, 0.0, 0.0]]), np.array([5.0]))
    m = FarmLinearRegression(reg_param=0.0, pool=50.0).fit(data)
    i = m.tenant_index("tiny")
    coef = m.arrays["coefficients"][i]
    assert np.all(np.isfinite(coef))
    # heavy pooling: the 1-row hospital sits near the global model
    g = m.arrays["coefficients"][m.global_index]
    assert np.linalg.norm(coef - g) < 0.5 * np.linalg.norm(g)


def test_empty_tenant_lands_on_global_with_pooling():
    data = _fleet(6)
    data["empty"] = (np.empty((0, D)), np.empty((0,)))
    m = FarmLinearRegression(pool=10.0).fit(data)
    i = m.tenant_index("empty")
    np.testing.assert_allclose(
        m.arrays["coefficients"][i],
        m.arrays["coefficients"][m.global_index], atol=1e-3,
    )
    assert int(m.arrays["tenant_rows"][i]) == 0


def test_all_nan_tenant_degrades_like_empty():
    """Quality stance: NaN is missing — an all-NaN hospital is an empty
    hospital, and its garbage never reaches the global fit."""
    data = _fleet(6)
    clean = FarmLinearRegression(pool=10.0).fit(data)
    data_nan = dict(data)
    data_nan["allnan"] = (np.full((7, D), np.nan), np.full((7,), np.nan))
    m = FarmLinearRegression(pool=10.0).fit(data_nan)
    i = m.tenant_index("allnan")
    assert np.all(np.isfinite(m.arrays["coefficients"][i]))
    assert int(m.arrays["masked_rows"][i]) == 7
    assert int(m.arrays["tenant_rows"][i]) == 0
    # the global slot ignores the NaN tenant entirely
    np.testing.assert_allclose(
        m.arrays["coefficients"][m.global_index],
        clean.arrays["coefficients"][clean.global_index], atol=1e-5,
    )


def test_nan_rows_equal_filtered_rows():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(20, D))
    y = x @ THETA + 1.0
    x_dirty = x.copy()
    x_dirty[5, 2] = np.nan
    x_dirty[11, 0] = np.inf
    keep = np.ones(20, bool)
    keep[[5, 11]] = False
    m_dirty = FarmLinearRegression().fit(
        pack_tenants({"h": (x_dirty, y)}, pad_to=32)
    )
    m_clean = FarmLinearRegression().fit(
        pack_tenants({"h": (x[keep], y[keep])}, pad_to=32)
    )
    np.testing.assert_array_equal(
        m_dirty.arrays["coefficients"][0], m_clean.arrays["coefficients"][0]
    )


def test_kmeans_empty_tenant_no_slice_but_predicts():
    data = {"a": np.random.default_rng(0).normal(size=(30, D)),
            "empty": np.empty((0, D))}
    m = FarmKMeans(k=3, seed=0).fit(data)
    with pytest.raises(ValueError, match="no valid centers"):
        m.tenant_model("empty")
    # routed predict still answers (cluster 0 by convention)
    out = m.predict_tenant("empty", np.zeros((2, D)))
    assert out.shape == (2,)


def test_malformed_tenant_index_routes_to_global(linear_farm):
    """A corrupted in-band tenant index (negative, ±inf, NaN, huge,
    past-the-end) must answer with the pooled GLOBAL slot — never some
    other hospital's private parameters (review-round regression: the
    old clip sent negatives to tenant 0)."""
    g = linear_farm.global_index
    x = np.random.default_rng(1).normal(size=(1, D)).astype(np.float32)
    fn = linear_farm.serving_predict_fn()

    def answer(idx_val):
        row = np.concatenate([[[idx_val]], x], axis=1).astype(np.float32)
        return float(np.asarray(fn(jnp.asarray(row)))[0])

    ref = answer(float(g))
    for bad in (-1.0, -np.inf, np.nan, np.inf, 1e12, float(g + 7)):
        assert answer(bad) == ref, bad
    # a real tenant still answers with its own slice
    assert answer(0.0) != ref


def test_non_string_tenant_ids_work_end_to_end():
    """Int/np tenant ids (a DB's natural keys) normalize to one string id
    space across pack → fit → route → refit → lifecycle (review-round
    regression: pack_tenants stringified keys then indexed the original
    mapping, KeyError on the first int id)."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.lifecycle import (
        retrain_drifted,
    )

    rng = np.random.default_rng(4)
    data = {}
    for t in range(6):
        x = rng.normal(size=(30, D))
        data[t] = (x, x @ THETA)  # int keys on purpose
    m = FarmLinearRegression(pool=1.0).fit(data)
    assert m.tenant_ids == tuple(str(t) for t in range(6))
    np.testing.assert_array_equal(
        m.predict_tenant(3, np.asarray(data[3][0][:2])),
        m.predict_tenant("3", np.asarray(data[3][0][:2])),
    )
    m2 = m.refit({2: data[2]})
    np.testing.assert_array_equal(
        m2.arrays["coefficients"][0], m.arrays["coefficients"][0]
    )
    # lifecycle path with int keys: drifted id resolves into refit data
    shifted = dict(data)
    shifted[1] = (np.asarray(data[1][0]) + 6.0, np.asarray(data[1][1]))
    m3, report = retrain_drifted(m, shifted, threshold=0.25, min_rows=1)
    assert list(report["drifted"]) == ["1"]
    assert m3 is not m


def test_pack_validation():
    with pytest.raises(ValueError, match="at least one tenant"):
        pack_tenants({})
    with pytest.raises(ValueError, match="rows"):
        pack_tenants({"a": (np.zeros((3, D)), np.zeros(2))})
    with pytest.raises(ValueError, match="features"):
        pack_tenants({"a": np.zeros((3, D)), "b": np.zeros((3, D + 1))})
    with pytest.raises(ValueError, match=">= 0"):
        pack_tenants({"a": (np.zeros((2, D)), np.zeros(2), np.array([1.0, -1.0]))})


# ================================================================== pooling
def test_partial_pooling_shrinks_small_tenants_more():
    rng = np.random.default_rng(9)
    theta_odd = THETA + 3.0
    big_x = rng.normal(size=(400, D))
    small_x = rng.normal(size=(4, D))
    data = {
        "big": (big_x, big_x @ theta_odd),
        "small": (small_x, small_x @ theta_odd),
    }
    # global pull comes from a third, dominant tenant on THETA
    base_x = rng.normal(size=(800, D))
    data["base"] = (base_x, base_x @ THETA)
    m = FarmLinearRegression(pool=20.0).fit(data)
    g = m.arrays["coefficients"][m.global_index]
    d_big = np.linalg.norm(
        m.arrays["coefficients"][m.tenant_index("big")] - theta_odd
    )
    d_small = np.linalg.norm(
        m.arrays["coefficients"][m.tenant_index("small")] - theta_odd
    )
    # the big hospital keeps its own signal; the small one is pulled
    # toward the global model (away from its own few rows' signal)
    assert d_big < 0.5
    assert d_small > 2 * d_big
    assert np.all(np.isfinite(g))


# ================================================================== artifact
def test_save_load_one_artifact(tmp_path, linear_farm, fleet):
    path = str(tmp_path / "farm")
    linear_farm.save(path)
    assert os.path.isdir(path)
    assert sorted(os.listdir(path)) == ["arrays.npz", "metadata.json"]
    m2 = load_model(path)
    assert isinstance(m2, ModelFarmModel)
    assert m2.tenant_ids == linear_farm.tenant_ids
    for k, v in linear_farm.arrays.items():
        np.testing.assert_array_equal(v, m2.arrays[k])
    tid = "H007"
    np.testing.assert_array_equal(
        linear_farm.predict_tenant(tid, fleet[tid][0]),
        m2.predict_tenant(tid, fleet[tid][0]),
    )
    # per-tenant sketches round-trip into ordinary DataProfiles
    prof = m2.tenant_profile(tid)
    assert prof.total_rows == float(len(fleet[tid][1]))


def test_profiles_merge_to_pooled(linear_farm, fleet):
    """Per-tenant sketches share edges, so merging every tenant's profile
    reproduces the pooled distribution exactly (count/mean/histogram) —
    the property lifecycle's fleet-level drift view relies on."""
    ids = linear_farm.tenant_ids
    merged = linear_farm.tenant_profile(ids[0])
    for tid in ids[1:]:
        merged.merge(linear_farm.tenant_profile(tid))
    total_rows = sum(len(v[1]) for v in fleet.values())
    assert merged.total_rows == float(total_rows)
    pooled = np.concatenate([np.asarray(v[0]) for v in fleet.values()])
    sk = merged.sketches[linear_farm.feature_names[0]]
    np.testing.assert_allclose(sk.mean, pooled[:, 0].mean(), rtol=1e-6)
    assert sk.counts.sum() == total_rows


# =================================================================== serving
def test_serving_zero_recompiles_across_tenants_and_sizes(linear_farm, fleet):
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve import (
        ModelRegistry,
    )

    reg = ModelRegistry()
    sm = reg.register("farm", linear_farm, warmup=True)
    rng = np.random.default_rng(0)
    ids = list(fleet)
    for size in (1, 7, 32, 3, 1, 17):
        tid = ids[int(rng.integers(len(ids)))]
        x = rng.normal(size=(size, D))
        out = sm.predict(linear_farm.route_request(tid, x))
        expect = linear_farm.predict_tenant(tid, x)
        np.testing.assert_allclose(out, expect, atol=1e-5)
    assert sm.metrics.recompile_count == 0
    cache = sm.jit_cache_size()
    assert cache is None or cache <= len(sm.buckets)


def test_server_routes_tenant_and_unknown_falls_back(linear_farm, fleet):
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve import (
        InferenceServer,
    )

    with InferenceServer() as srv:
        srv.add_model("farm", linear_farm)
        tid = "H004"
        x = np.asarray(fleet[tid][0][:5])
        res = srv.predict_tenant("farm", tid, x)
        assert res.ok
        np.testing.assert_allclose(
            res.value, linear_farm.predict_tenant(tid, x), atol=1e-5
        )
        # unknown hospital → the pooled GLOBAL slice answers
        res_u = srv.predict_tenant("farm", "NOT_A_HOSPITAL", x)
        assert res_u.ok
        g = linear_farm.global_model()
        np.testing.assert_allclose(
            res_u.value, g.predict_numpy(x.astype(np.float32)), atol=1e-5
        )
        srv.add_model(
            "plain",
            ht.models.LinearRegression().fit(
                (np.asarray(fleet[tid][0]), np.asarray(fleet[tid][1]))
            ),
        )
        # ISSUE 12: a tenant request against a non-farm model is a 400
        # (invalid_input answer), not an exception from the serving
        # surface; the typed NotRoutableError lives on route_tenant
        res_nr = srv.predict_tenant("plain", tid, x)
        assert res_nr.status == "invalid_input"
        assert "plain" in res_nr.detail
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve import (
            NotRoutableError,
        )

        with pytest.raises(NotRoutableError, match="not tenant-routable"):
            srv.route_tenant("plain", tid, x)


# ============================================================ drift + refit
def test_refit_touches_only_the_subset(linear_farm, fleet):
    shifted = {
        "H002": (np.asarray(fleet["H002"][0]) + 4.0, np.asarray(fleet["H002"][1])),
        "H009": (np.asarray(fleet["H009"][0]) * 2.0, np.asarray(fleet["H009"][1])),
    }
    m2 = linear_farm.refit(shifted)
    assert m2 is not linear_farm
    for tid in linear_farm.tenant_ids:
        i = linear_farm.tenant_index(tid)
        same = np.array_equal(
            m2.arrays["coefficients"][i], linear_farm.arrays["coefficients"][i]
        )
        if tid in shifted:
            assert not same, f"{tid} should have been refit"
        else:
            assert same, f"{tid} must be byte-identical after subset refit"
    # global slot frozen
    np.testing.assert_array_equal(
        m2.arrays["coefficients"][m2.global_index],
        linear_farm.arrays["coefficients"][linear_farm.global_index],
    )
    # refreshed sketches for the refit tenants only
    i2 = linear_farm.tenant_index("H002")
    assert not np.array_equal(
        m2.arrays["profile_counts"][i2],
        linear_farm.arrays["profile_counts"][i2],
    )


def test_kmeans_refit_same_data_reproduces_fit(kmeans_farm, fleet):
    """The refit init stream folds in the tenant's GLOBAL index, so a
    refit on unchanged data lands on the exact fit-time centers."""
    tid = "H006"
    m2 = kmeans_farm.refit({tid: fleet[tid][0]})
    i = kmeans_farm.tenant_index(tid)
    np.testing.assert_array_equal(
        m2.arrays["centers"][i], kmeans_farm.arrays["centers"][i]
    )


def test_drift_flags_only_shifted_tenant(kmeans_farm, fleet):
    live = {
        "H003": np.asarray(fleet["H003"][0]) + 6.0,   # unit-scale shift
        "H008": np.asarray(fleet["H008"][0]),          # unchanged
    }
    flagged = drifted_tenants(kmeans_farm, live, min_rows=1)
    assert "H003" in flagged and flagged["H003"] > 0.25
    assert "H008" not in flagged
    psi = tenant_psi(kmeans_farm, "H008", live["H008"])
    assert max(psi.values()) < 0.25
    # unknown tenants are skipped, not crashed on
    assert drifted_tenants(
        kmeans_farm, {"nope": np.zeros((50, D))}, min_rows=1
    ) == {}


def test_lifecycle_retrain_drifted_end_to_end(tmp_path, fleet):
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.lifecycle import (
        retrain_drifted,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.serve import (
        InferenceServer,
    )

    farm0 = FarmLinearRegression(reg_param=0.1, pool=1.0).fit(fleet)
    # hospital H001's feature distribution moved; its data follows
    new_data = dict(fleet)
    x1 = np.asarray(fleet["H001"][0]) + 5.0
    new_data["H001"] = (x1, x1 @ (THETA + 1.0))
    path = str(tmp_path / "farm_v2")
    with InferenceServer() as srv:
        srv.add_model("farm", farm0)
        m2, report = retrain_drifted(
            farm0, new_data, threshold=0.25, min_rows=1,
            save_path=path, server=srv, serving_name="farm",
        )
        assert list(report["drifted"]) == ["H001"]
        assert report["swapped"] == "farm"
        # stable tenant untouched, drifted tenant changed
        i_stable = farm0.tenant_index("H000")
        np.testing.assert_array_equal(
            m2.arrays["coefficients"][i_stable],
            farm0.arrays["coefficients"][i_stable],
        )
        i1 = farm0.tenant_index("H001")
        assert not np.array_equal(
            m2.arrays["coefficients"][i1], farm0.arrays["coefficients"][i1]
        )
        # the server now answers with the successor
        res = srv.predict_tenant("farm", "H001", x1[:4])
        np.testing.assert_allclose(
            res.value, m2.predict_tenant("H001", x1[:4]), atol=1e-5
        )
    # and the successor artifact is on disk, loadable
    assert load_model(path).tenant_ids == farm0.tenant_ids
    # nothing drifted → same object back, no save
    m3, rep3 = retrain_drifted(farm0, fleet, threshold=0.25, min_rows=1)
    assert m3 is farm0 and rep3["drifted"] == {}


# ==================================================================== chaos
@pytest.mark.chaos
def test_farm_fit_kill_and_resume_bit_identical(tmp_path):
    """Kill a checkpointed farm k-means fit at the commit fault site;
    rerunning the same config must land on EXACTLY the uninterrupted
    fit's centers for every tenant."""
    data = {t: v[0] for t, v in _fleet(12, seed=5, min_rows=8).items()}

    def est(ckpt_dir):
        return FarmKMeans(
            k=3, max_iter=8, tol=0.0, seed=2,
            checkpoint_dir=str(ckpt_dir), checkpoint_every=1,
        )

    ref = est(tmp_path / "ref").fit(data)

    plan = faults.FaultPlan().crash("fit_ckpt.save.commit", after=2)
    with faults.active(plan):
        with pytest.raises(faults.InjectedCrash):
            est(tmp_path / "crashed").fit(data)
    assert plan.fired("fit_ckpt.save.commit") == 1

    resumed = est(tmp_path / "crashed").fit(data)
    np.testing.assert_array_equal(
        resumed.arrays["centers"], ref.arrays["centers"]
    )
    np.testing.assert_array_equal(
        resumed.arrays["n_iter"], ref.arrays["n_iter"]
    )


# ============================================================== obs plumbing
def test_cohort_label_bounded_and_stable():
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.obs.registry import (
        N_COHORTS,
        cohort_label,
    )

    labels = {cohort_label(f"H{i:04d}") for i in range(5000)}
    assert len(labels) <= N_COHORTS
    assert cohort_label("H0001") == cohort_label("H0001")


def test_label_cardinality_guard_caps_export():
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.obs.export import (
        prometheus_text,
    )
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.obs.registry import (
        MetricsRegistry,
        N_COHORTS,
        split_labels,
    )

    reg = MetricsRegistry()
    n_tenants = 600
    for i in range(n_tenants):
        reg.inc(f'farm.rows{{tenant="H{i:04d}"}}', 2.0)
    reg.inc("farm.fit_tenants", 1.0)  # unlabeled family passes through
    snap = reg.collect()
    series = [k for k in snap["counters"] if k.startswith("farm.rows{")]
    assert 0 < len(series) <= N_COHORTS
    # mass is preserved: counters SUM into their cohort buckets
    assert sum(snap["counters"][k] for k in series) == 2.0 * n_tenants
    assert all(
        set(split_labels(k)[1]) == {"tenant"} for k in series
    )
    assert any(
        k.startswith("obs.cardinality_capped") for k in snap["counters"]
    )
    # a small labeled family keeps its exact labels
    reg2 = MetricsRegistry()
    reg2.inc('serve.breaker{model="los"}', 1.0)
    assert 'serve.breaker{model="los"}' in reg2.collect()["counters"]
    # a capped family only buckets the HOT key: the low-cardinality
    # model= companion label keeps attributing series exactly
    reg3 = MetricsRegistry()
    for i in range(400):
        reg3.inc(f'farm.rows{{model="los",tenant="H{i:04d}"}}', 1.0)
        reg3.inc(f'farm.rows{{model="readmit",tenant="H{i:04d}"}}', 1.0)
    snap3 = reg3.collect()
    rows3 = [k for k in snap3["counters"] if k.startswith("farm.rows{")]
    models = {split_labels(k)[1]["model"] for k in rows3}
    assert models == {"los", "readmit"}
    assert all(split_labels(k)[1]["tenant"].startswith("c") for k in rows3)
    assert sum(snap3["counters"][k] for k in rows3) == 800.0
    # the Prometheus page renders the capped view without blowing up
    text = prometheus_text(reg)
    assert text.count("cmlhn_farm_rows_total{") <= N_COHORTS


def test_farm_metrics_use_cohorts(linear_farm, fleet):
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.obs.registry import (
        global_registry,
    )

    before = {
        k: v for k, v in global_registry().counters.items()
        if k.startswith("farm.requests{")
    }
    linear_farm.predict_tenant("H001", np.asarray(fleet["H001"][0][:2]))
    after = {
        k: v for k, v in global_registry().counters.items()
        if k.startswith("farm.requests{")
    }
    assert sum(after.values()) == sum(before.values()) + 1
    assert all("cohort=" in k and "tenant" not in k for k in after)
