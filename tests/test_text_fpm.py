"""Text feature stages (Tokenizer/RegexTokenizer/StopWordsRemover/NGram/
CountVectorizer/HashingTF/IDF/DCT) + FPGrowth (ml.fpm).

Oracles: hand-computed token/count expectations, sklearn TfidfTransformer
agreement for the smoothed-idf formula, scipy DCT parity, and an
exhaustive brute-force itemset enumeration for FP-growth."""

from itertools import combinations

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht

pytestmark = pytest.mark.fast


class TestTokenizers:
    TEXTS = ["The cardiac ward is FULL today", "icu beds are full", ""]

    def test_tokenizer_lowercases_and_splits(self):
        toks = ht.Tokenizer().transform(self.TEXTS)
        assert toks[0] == ["the", "cardiac", "ward", "is", "full", "today"]
        assert toks[2] == []

    def test_regex_tokenizer_both_modes(self):
        gaps = ht.RegexTokenizer(pattern=r"\s+").transform(["a  b\tc"])
        assert gaps[0] == ["a", "b", "c"]
        toks = ht.RegexTokenizer(pattern=r"[a-z]+", gaps=False).transform(
            ["a1b2 ccc"]
        )
        assert toks[0] == ["a", "b", "ccc"]
        # min_token_length filters
        long = ht.RegexTokenizer(
            pattern=r"\s+", min_token_length=2
        ).transform(["a bb ccc"])
        assert long[0] == ["bb", "ccc"]

    def test_stop_words_and_ngram(self):
        toks = ht.Tokenizer().transform(self.TEXTS)
        clean = ht.StopWordsRemover().transform(toks)
        assert clean[0] == ["cardiac", "ward", "full", "today"]
        cs = ht.StopWordsRemover(
            stop_words=("The",), case_sensitive=True
        ).transform(ht.RegexTokenizer(to_lowercase=False).transform(self.TEXTS))
        assert cs[0][0] == "cardiac"   # exact-case "The" removed
        bi = ht.NGram(n=2).transform(clean)
        assert bi[0] == ["cardiac ward", "ward full", "full today"]
        assert ht.NGram(n=9).transform(clean)[0] == []  # shorter than n
        with pytest.raises(ValueError, match="n must"):
            ht.NGram(n=0)
        with pytest.raises(TypeError, match="token lists"):
            ht.StopWordsRemover().transform(self.TEXTS)   # strings, not tokens


class TestVectorizers:
    DOCS = [
        ["ward", "full", "ward"],
        ["icu", "full"],
        ["ward", "icu", "beds"],
    ]

    def test_count_vectorizer_counts_and_order(self):
        m = ht.CountVectorizer().fit(self.DOCS)
        # vocabulary ordered by descending corpus term frequency
        assert m.vocabulary[0] == "ward"          # tf 3
        mat = m.transform(self.DOCS)
        v = {t: i for i, t in enumerate(m.vocabulary)}
        assert mat[0, v["ward"]] == 2.0 and mat[0, v["full"]] == 1.0
        assert mat.sum() == 8.0   # 3 + 2 + 3 tokens
        # min_df in docs, binary mode
        m2 = ht.CountVectorizer(min_df=2.0, binary=True).fit(self.DOCS)
        assert set(m2.vocabulary) == {"ward", "full", "icu"}
        assert ht.CountVectorizer(vocab_size=1).fit(self.DOCS).vocabulary == ("ward",)
        b = m2.transform(self.DOCS)
        assert set(np.unique(b)) <= {0.0, 1.0}

    def test_idf_matches_sklearn_smooth(self):
        from sklearn.feature_extraction.text import TfidfTransformer

        m = ht.CountVectorizer().fit(self.DOCS)
        tf = m.transform(self.DOCS)
        ours = ht.IDF().fit(tf)
        ref = TfidfTransformer(norm=None, smooth_idf=True, sublinear_tf=False).fit(tf)
        # sklearn's smoothed idf = log((n+1)/(df+1)) + 1
        np.testing.assert_allclose(ours.idf, ref.idf_ - 1.0, rtol=1e-6)
        tfidf = ours.transform(tf)
        np.testing.assert_allclose(tfidf, tf * (ref.idf_ - 1.0), rtol=1e-6)
        with pytest.raises(ValueError, match="TF matrix"):
            ht.IDF().fit(np.empty((0, 3)))

    def test_hashing_tf_deterministic(self):
        h = ht.HashingTF(num_features=32)
        a = h.transform(self.DOCS)
        b = ht.HashingTF(num_features=32).transform(self.DOCS)
        np.testing.assert_array_equal(a, b)       # process-stable hashing
        assert a.shape == (3, 32) and a.sum() == 8.0
        assert set(np.unique(ht.HashingTF(num_features=32, binary=True).transform(self.DOCS))) <= {0.0, 1.0}

    def test_dct_matches_scipy_and_inverts(self, rng):
        from scipy.fft import dct as sdct

        x = rng.normal(size=(5, 16)).astype(np.float32)
        y = np.asarray(ht.DCT().transform(x))
        np.testing.assert_allclose(
            y, sdct(x, type=2, axis=1, norm="ortho"), atol=1e-5
        )
        back = np.asarray(ht.DCT(inverse=True).transform(y))
        np.testing.assert_allclose(back, x, atol=1e-5)

    def test_pipeline_to_lda(self):
        """The full text path feeds the device-side LDA."""
        texts = ["ward ward full", "icu icu beds", "ward full", "icu beds"] * 10
        toks = ht.Tokenizer().transform(texts)
        mat = ht.CountVectorizer().fit_transform(toks)
        m = ht.LDA(k=2, max_iter=10, seed=0).fit(mat)
        mix = m.transform(mat)
        assert mix.shape == (40, 2)
        # the two doc families land on different dominant topics
        assert (mix.argmax(axis=1)[0::2] != mix.argmax(axis=1)[1::2]).mean() > 0.9

    def test_round_trips(self, tmp_path):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import (
            load_model, save_model,
        )

        cv = ht.CountVectorizer().fit(self.DOCS)
        for name, stage in [
            ("tok", ht.Tokenizer()),
            ("rex", ht.RegexTokenizer(pattern=r"[a-z]+", gaps=False)),
            ("sw", ht.StopWordsRemover(stop_words=("x",))),
            ("ng", ht.NGram(n=3)),
            ("cv", cv),
            ("htf", ht.HashingTF(num_features=64)),
            ("idf", ht.IDF().fit(cv.transform(self.DOCS))),
            ("dct", ht.DCT(inverse=True)),
        ]:
            save_model(str(tmp_path / name), *stage._artifacts())
            back = load_model(str(tmp_path / name))
            assert type(back) is type(stage)
        assert load_model(str(tmp_path / "cv")).vocabulary == cv.vocabulary


class TestFPGrowth:
    def test_spark_doc_example(self):
        data = [["1", "2", "5"], ["1", "2", "3", "5"], ["1", "2"]]
        m = ht.FPGrowth(min_support=0.5, min_confidence=0.6).fit(data)
        freq = dict(m.freq_itemsets)
        assert freq[("1",)] == 3 and freq[("2",)] == 3
        assert freq[("1", "2")] == 3 and freq[("1", "2", "5")] == 2
        rules = {
            (a, c): (conf, lift)
            for a, c, conf, lift, s in m.association_rules
        }
        assert rules[(("5",), "1")] == (1.0, 1.0)
        np.testing.assert_allclose(rules[(("1", "2"), "5")][0], 2 / 3)
        pred = m.transform([["1", "5"], ["1", "2", "3", "5"]])
        assert "2" in pred[0]
        assert pred[1] == []     # everything already present

    def test_matches_brute_force(self, rng):
        items = list("abcdef")
        rows = [
            [items[i] for i in np.flatnonzero(rng.uniform(size=6) < 0.45)]
            for _ in range(80)
        ]
        rows = [r for r in rows if r]
        m = ht.FPGrowth(min_support=0.1).fit(rows)
        min_count = int(np.ceil(0.1 * len(rows)))
        brute = {}
        for k in range(1, 7):
            for combo in combinations(items, k):
                c = sum(1 for r in rows if set(combo) <= set(r))
                if c >= min_count:
                    brute[tuple(sorted(combo))] = c
        mined = {tuple(sorted(i)): c for i, c in m.freq_itemsets}
        assert mined == brute
        assert len(brute) > 15      # the check actually covered pairs+

    def test_round_trip_and_validation(self, tmp_path):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import (
            load_model, save_model,
        )

        m = ht.FPGrowth(min_support=0.5).fit([["a", "b"], ["a"], ["a", "b"]])
        save_model(str(tmp_path / "fp"), *m._artifacts())
        back = load_model(str(tmp_path / "fp"))
        assert dict(back.freq_itemsets) == dict(m.freq_itemsets)
        assert back.transform([["a"]]) == m.transform([["a"]])
        with pytest.raises(ValueError, match="empty"):
            ht.FPGrowth().fit([])
        with pytest.raises(ValueError, match="min_support"):
            ht.FPGrowth(min_support=0.0).fit([["a"]])


def test_review_fixes(rng, tmp_path):
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import (
        load_model, save_model,
    )

    # fractional min_tf = fraction of the doc's token count (Spark)
    m = ht.CountVectorizer(min_tf=0.4).fit([["a", "a", "a", "b"]])
    mat = m.transform([["a", "a", "a", "b"]])
    v = {t: i for i, t in enumerate(m.vocabulary)}
    assert mat[0, v["a"]] == 3.0 and mat[0, v["b"]] == 0.0  # 1 < 0.4*4
    # integer TF matrices don't floor the idf weights to zero
    tf_int = np.array([[2, 0], [1, 1]], np.int32)
    out = ht.IDF().fit(tf_int).transform(tf_int)
    # col 0 appears in every doc → idf 0; col 1 (df=1) must NOT floor to 0
    assert out.dtype == np.float32
    np.testing.assert_allclose(out[1, 1], np.log(3 / 2), rtol=1e-6)
    # integer items survive an FPGrowth round trip
    fp = ht.FPGrowth(min_support=0.5, min_confidence=0.5).fit(
        [[1, 2], [1, 2, 5], [1]]
    )
    save_model(str(tmp_path / "fpi"), *fp._artifacts())
    back = load_model(str(tmp_path / "fpi"))
    assert back.transform([[1]]) == fp.transform([[1]]) != [[]]
    # dense HashingTF budget raises instead of OOMing
    with pytest.raises(ValueError, match="element budget"):
        ht.HashingTF().transform([["x"]] * 2000)


class TestWord2Vec:
    def _topic_docs(self, rng, n=400):
        heart = [f"h{i}" for i in range(6)]
        lung = [f"l{i}" for i in range(6)]
        docs = []
        for _ in range(n):
            pool = heart if rng.uniform() < 0.5 else lung
            docs.append(list(rng.choice(pool, size=8)))
        return docs

    def test_cooccurring_words_embed_together(self, rng):
        docs = self._topic_docs(rng)
        m = ht.Word2Vec(
            vector_size=16, min_count=1, max_iter=15, window_size=4, seed=0
        ).fit(docs)
        syn = [t for t, s in m.find_synonyms("h0", num=5)]
        assert np.mean([t.startswith("h") for t in syn]) >= 0.8
        # similarities are descending
        sims = [s for _, s in m.find_synonyms("h0", num=5)]
        assert sims == sorted(sims, reverse=True)

    def test_transform_and_round_trip(self, rng, tmp_path):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.io import (
            load_model, save_model,
        )

        docs = self._topic_docs(rng, n=200)
        m = ht.Word2Vec(
            vector_size=8, min_count=1, max_iter=10, window_size=4, seed=0
        ).fit(docs)
        emb = m.transform(docs[:6])
        assert emb.shape == (6, 8)
        # unknown-token documents embed to zeros (Spark's rule)
        assert np.all(m.transform([["zzz"]]) == 0.0)
        save_model(str(tmp_path / "w2v"), *m._artifacts())
        back = load_model(str(tmp_path / "w2v"))
        np.testing.assert_allclose(back.transform(docs[:3]), m.transform(docs[:3]))
        with pytest.raises(KeyError, match="vocabulary"):
            m.find_synonyms("zzz")

    def test_validation(self, rng):
        with pytest.raises(ValueError, match="min_count"):
            ht.Word2Vec(min_count=99).fit([["a", "b"]])
        with pytest.raises(ValueError, match="pairs"):
            ht.Word2Vec(min_count=1).fit([["solo"]])


class TestFeatureHasher:
    def test_numeric_and_categorical(self):
        fh = ht.FeatureHasher(num_features=512)   # wide enough to avoid
        # an age/ward slot collision in this tiny example
        out = fh.transform([{"age": 30, "ward": "icu"}, {"age": 40, "ward": "er"}])
        assert out.shape == (2, 512)
        assert out[0].sum() == 31.0      # 30 at hash(age) + 1 at hash(ward=icu)
        assert out[1].sum() == 41.0
        # same column hashes to the same slot across rows
        age_slot = np.flatnonzero(out[0] == 30.0)[0]
        assert out[1, age_slot] == 40.0
        # deterministic across instances (CRC32, not salted hash())
        np.testing.assert_array_equal(
            out, ht.FeatureHasher(num_features=512).transform(
                [{"age": 30, "ward": "icu"}, {"age": 40, "ward": "er"}]
            )
        )

    def test_table_input_and_validation(self):
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.core.table import Table

        t = Table.from_dict(
            {"age": np.array([30.0, 40.0]), "ward": np.array(["icu", "er"], object)}
        )
        out = ht.FeatureHasher(num_features=32).transform(t)
        assert out.shape == (2, 32) and out[0].sum() == 31.0
        with pytest.raises(TypeError, match="dicts"):
            ht.FeatureHasher(num_features=8).transform([["not", "a", "dict"]])
        with pytest.raises(ValueError, match="num_features"):
            ht.FeatureHasher(num_features=0)


def test_feature_hasher_nulls_and_numpy_bools():
    fh = ht.FeatureHasher(num_features=128)
    a = fh.transform([{"flag": True, "x": 1.0}])
    b = fh.transform([{"flag": np.bool_(True), "x": 1.0}])
    np.testing.assert_array_equal(a, b)      # np.bool_ hashes categorically
    # nulls contribute nothing instead of crashing / writing NaN
    c = fh.transform([{"age": None, "x": 1.0}, {"age": float("nan"), "x": 1.0}])
    assert np.isfinite(c).all() and c[0].sum() == 1.0 == c[1].sum()
    with pytest.raises(ValueError, match="vector_size"):
        ht.Word2Vec(vector_size=0, min_count=1).fit([["a", "b"]])
    with pytest.raises(ValueError, match="max_iter"):
        ht.Word2Vec(max_iter=0, min_count=1).fit([["a", "b"]])


class TestPrefixSpan:
    def test_spark_doc_example(self):
        db = [
            [[1, 2], [3]],
            [[1], [3, 2], [1, 2]],
            [[1, 2], [5]],
            [[6]],
        ]
        pats = ht.PrefixSpan(
            min_support=0.5, max_pattern_length=5
        ).find_frequent_sequential_patterns(db)
        d = dict(pats)
        # Spark's documented output, exactly
        assert d == {
            ((1,),): 3,
            ((2,),): 3,
            ((3,),): 2,
            ((1, 2),): 3,
            ((1,), (3,)): 2,
        }

    def test_matches_brute_force(self, rng):
        """Exhaustive subsequence enumeration over a small random DB."""
        from itertools import combinations

        items = [0, 1, 2]
        db = []
        for _ in range(30):
            seq = []
            for _ in range(rng.integers(1, 4)):
                elem = [i for i in items if rng.uniform() < 0.5]
                if elem:
                    seq.append(elem)
            if seq:
                db.append(seq)
        min_sup = 0.2
        got = dict(
            ht.PrefixSpan(
                min_support=min_sup, max_pattern_length=3
            ).find_frequent_sequential_patterns(db)
        )

        # brute force: all patterns of <= 3 total items, <= 3 elements
        from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models.fpm import (
            _seq_contains,
        )

        elems = [
            frozenset(c)
            for k in (1, 2, 3)
            for c in combinations(items, k)
        ]
        def all_patterns(max_items):
            pats = [[e] for e in elems if len(e) <= max_items]
            out = list(pats)
            frontier = pats
            while frontier:
                nxt = []
                for p in frontier:
                    used = sum(len(e) for e in p)
                    for e in elems:
                        if used + len(e) <= max_items:
                            nxt.append(p + [e])
                out.extend(nxt)
                frontier = nxt
            return out

        min_count = int(np.ceil(min_sup * len(db)))
        fdb = [[frozenset(e) for e in s] for s in db]
        brute = {}
        for pat in all_patterns(3):
            c = sum(1 for s in fdb if _seq_contains(s, pat))
            if c >= min_count:
                brute[tuple(tuple(sorted(e)) for e in pat)] = c
        assert got == brute
        assert len(brute) > 5

    def test_validation(self):
        with pytest.raises(ValueError, match="empty"):
            ht.PrefixSpan().find_frequent_sequential_patterns([])
        with pytest.raises(ValueError, match="min_support"):
            ht.PrefixSpan(min_support=0).find_frequent_sequential_patterns(
                [[[1]]]
            )
        with pytest.raises(ValueError, match="max_pattern_length"):
            ht.PrefixSpan(
                max_pattern_length=0
            ).find_frequent_sequential_patterns([[[1]]])


def test_prefixspan_review_fixes():
    # empty sequences count in the support denominator (Spark's rule)
    pats = ht.PrefixSpan(min_support=0.5).find_frequent_sequential_patterns(
        [[[1]], [], [], []]
    )
    assert pats == []   # freq 1 < ceil(0.5·4)
    # mixed-type items sort without TypeError
    pats = ht.PrefixSpan(min_support=0.5).find_frequent_sequential_patterns(
        [[[1, "a"]], [[1, "a"]]]
    )
    assert dict(pats)[(("a",),)] == 2 and dict(pats)[((1,),)] == 2
