"""Sample weights (Spark's ``weightCol``): integer-weight fits must equal
row-duplication fits, sklearn parity holds with fractional weights, and
weights thread from Table columns through fit → transform → evaluate."""

import numpy as np
import pytest

import clustermachinelearningforhospitalnetworks_apache_spark_tpu as ht
from clustermachinelearningforhospitalnetworks_apache_spark_tpu.parallel.sharding import (
    device_dataset,
)


def _weighted_problem(rng, n=800, d=4):
    x = rng.normal(size=(n, d)).astype(np.float32)
    beta = np.array([1.5, -2.0, 0.5, 3.0])
    y = (x @ beta + 0.1 * rng.normal(size=n)).astype(np.float32)
    w = rng.integers(1, 4, size=n).astype(np.float64)  # integer weights
    rep = np.repeat(np.arange(n), w.astype(int))
    return x, y, w, x[rep], y[rep]


@pytest.mark.fast
def test_linear_regression_weight_equals_duplication(rng, mesh8):
    x, y, w, xd, yd = _weighted_problem(rng)
    m_w = ht.LinearRegression().fit((x, y, w), mesh=mesh8)
    m_d = ht.LinearRegression().fit((xd, yd), mesh=mesh8)
    np.testing.assert_allclose(
        np.asarray(m_w.coefficients), np.asarray(m_d.coefficients), atol=1e-4
    )
    np.testing.assert_allclose(float(m_w.intercept), float(m_d.intercept), atol=1e-4)


def test_linear_regression_weights_match_sklearn(rng, mesh8):
    sk = pytest.importorskip("sklearn.linear_model")
    x, y, _, _, _ = _weighted_problem(rng)
    w = rng.uniform(0.1, 5.0, size=len(x))  # fractional
    ours = ht.LinearRegression().fit((x, y, w), mesh=mesh8)
    ref = sk.LinearRegression().fit(x, y, sample_weight=w)
    np.testing.assert_allclose(np.asarray(ours.coefficients), ref.coef_, atol=1e-3)
    np.testing.assert_allclose(float(ours.intercept), ref.intercept_, atol=1e-3)


def test_logistic_regression_weight_equals_duplication(rng, mesh8):
    x, y0, w, xd, _ = _weighted_problem(rng)
    yb = (y0 > np.median(y0)).astype(np.float32)
    ybd = np.repeat(yb, w.astype(int))
    m_w = ht.LogisticRegression(reg_param=1e-3).fit((x, yb, w), mesh=mesh8)
    m_d = ht.LogisticRegression(reg_param=1e-3).fit((xd, ybd), mesh=mesh8)
    np.testing.assert_allclose(
        np.asarray(m_w.coefficients), np.asarray(m_d.coefficients), rtol=1e-3, atol=1e-4
    )


def test_tree_zero_weight_rows_are_inert(rng, mesh8):
    """Trees: zero-weight rows influence neither the quantile bins (the
    binning sampler filters w>0) nor the split histograms — the fit equals
    one on the truncated data exactly.  (Exact integer-weight/duplication
    parity does not hold for trees by design: like Spark's findSplits, the
    quantile binning is unweighted; weights enter the impurity stats.)"""
    x, y, _, _, _ = _weighted_problem(rng)
    n_keep = 500
    w = np.r_[np.ones(n_keep), np.zeros(len(x) - n_keep)]
    m_w = ht.DecisionTreeRegressor(max_depth=4, seed=0).fit((x, y, w), mesh=mesh8)
    m_t = ht.DecisionTreeRegressor(max_depth=4, seed=0).fit(
        (x[:n_keep], y[:n_keep]), mesh=mesh8
    )
    probe = rng.normal(size=(500, 4)).astype(np.float32)
    # identical splits; leaf values may differ by f32 reduction-order ulps
    # (the two datasets pad to different row counts)
    np.testing.assert_allclose(
        m_w.predict_numpy(probe), m_t.predict_numpy(probe), rtol=1e-6
    )
    # integer weights shift the histograms exactly like duplication when
    # the bins agree: duplicating every row uniformly (w=2) is a no-op
    m_2 = ht.DecisionTreeRegressor(max_depth=4, seed=0).fit(
        (x, y, 2.0 * np.ones(len(x))), mesh=mesh8
    )
    m_1 = ht.DecisionTreeRegressor(max_depth=4, seed=0).fit((x, y), mesh=mesh8)
    np.testing.assert_allclose(
        m_2.predict_numpy(probe), m_1.predict_numpy(probe), atol=1e-5
    )


def test_kmeans_k1_weighted_mean(rng, mesh8):
    """k=1 KMeans converges to the weighted mean — exact closed form."""
    x = rng.normal(size=(500, 3)).astype(np.float32)
    w = rng.uniform(0.0, 2.0, size=500)
    m = ht.KMeans(k=1, max_iter=5, seed=0).fit(
        ht.device_dataset(x, mesh=mesh8, weights=w), mesh=mesh8
    )
    expect = (x * w[:, None]).sum(axis=0) / w.sum()
    np.testing.assert_allclose(
        np.asarray(m.cluster_centers[0]), expect, atol=1e-4
    )


def test_weight_col_through_table_pipeline(hospital_table, mesh8):
    """weightCol by name: a Table column threads through AssembledTable →
    fit → transform → weighted evaluator."""
    n = len(hospital_table)
    rng = np.random.default_rng(5)
    w = rng.integers(1, 3, size=n).astype(np.float64)
    tab = hospital_table.with_column("case_weight", w, dtype="float")
    asm = ht.VectorAssembler(ht.FEATURE_COLS).transform(tab)

    m = ht.LinearRegression(weight_col="case_weight").fit(asm, mesh=mesh8)
    # duplication reference through plain arrays
    x = asm.features
    y = tab.column("length_of_stay").astype(np.float64)
    rep = np.repeat(np.arange(n), w.astype(int))
    m_d = ht.LinearRegression().fit((x[rep], y[rep]), mesh=mesh8)
    np.testing.assert_allclose(
        np.asarray(m.coefficients), np.asarray(m_d.coefficients), atol=1e-4
    )

    # transform carries the weights into the PredictionResult, so the
    # evaluator computes the weighted metric
    ds = asm.to_device(weight_col="case_weight", mesh=mesh8)
    pred = m.transform(ds, mesh=mesh8)
    rmse_w = ht.RegressionEvaluator("rmse").evaluate(pred)
    pd, ld = m_d.transform((x[rep], y[rep]), mesh=mesh8).to_numpy()
    rmse_d = float(np.sqrt(np.mean((pd - ld) ** 2)))
    np.testing.assert_allclose(rmse_w, rmse_d, rtol=1e-5)


def test_clustering_weight_col(hospital_table, mesh8):
    """KMeans honors weightCol: zero-weight rows don't pull centroids."""
    n = len(hospital_table)
    tab = hospital_table.with_column(
        "case_weight", np.r_[np.ones(n - 50), np.zeros(50)], dtype="float"
    )
    asm = ht.VectorAssembler(ht.FEATURE_COLS).transform(tab)
    m_w = ht.KMeans(k=3, seed=0, weight_col="case_weight").fit(asm, mesh=mesh8)
    m_t = ht.KMeans(k=3, seed=0).fit(asm.features[: n - 50], mesh=mesh8)
    np.testing.assert_allclose(
        np.sort(np.asarray(m_w.cluster_centers), axis=0),
        np.sort(np.asarray(m_t.cluster_centers), axis=0),
        atol=2e-3,
    )


def test_weight_col_on_non_table_input_raises(rng, mesh8):
    """An explicitly configured weightCol must never silently produce an
    unweighted fit: non-table inputs raise."""
    x, y, _, _, _ = _weighted_problem(rng, n=100)
    with pytest.raises(ValueError, match="weight_col"):
        ht.LinearRegression(weight_col="case_weight").fit((x, y), mesh=mesh8)
    # but a pre-weighted DeviceDataset passes through untouched
    ds = device_dataset(x, y, mesh=mesh8, weights=np.ones(len(x)))
    ht.LinearRegression(weight_col="case_weight").fit(ds, mesh=mesh8)


def test_streaming_drain_carries_fractional_weights(rng, mesh8):
    """update_many must honor fractional DeviceDataset weights exactly
    like sequential update() calls."""
    from clustermachinelearningforhospitalnetworks_apache_spark_tpu.models import (
        StreamingKMeans,
    )

    x = rng.normal(size=(1200, 3)).astype(np.float32)
    w = rng.uniform(0.1, 2.0, size=1200).astype(np.float32)
    batches = [
        device_dataset(x[i : i + 400], mesh=mesh8, weights=w[i : i + 400])
        for i in range(0, 1200, 400)
    ]
    seq = StreamingKMeans(k=3, decay_factor=0.9, seed=2)
    for b in batches:
        seq.update(b, mesh=mesh8)
    many = StreamingKMeans(k=3, decay_factor=0.9, seed=2)
    many.update_many(batches, mesh=mesh8)
    np.testing.assert_allclose(
        seq.latest_model.cluster_centers,
        many.latest_model.cluster_centers,
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        seq.latest_model.cluster_weights,
        many.latest_model.cluster_weights,
        rtol=1e-5,
    )


def test_tuning_accepts_weighted_tuples(rng, mesh8):
    x, y, w, _, _ = _weighted_problem(rng, n=600)
    grid = ht.ParamGridBuilder().add_grid("reg_param", [0.0, 500.0]).build()
    cvm = ht.CrossValidator(
        ht.LinearRegression(), grid, ht.RegressionEvaluator("rmse"),
        num_folds=2, seed=1,
    ).fit((x, y, w), mesh=mesh8)
    assert cvm.best_index == 0
    tvm = ht.TrainValidationSplit(
        ht.LinearRegression(), grid, ht.RegressionEvaluator("rmse"), seed=1
    ).fit((x, y, w), mesh=mesh8)
    assert tvm.best_index == 0


def test_weight_validation():
    x = np.ones((10, 2))
    with pytest.raises(ValueError, match="non-negative"):
        device_dataset(x, weights=-np.ones(10))
    with pytest.raises(ValueError, match="length"):
        device_dataset(x, weights=np.ones(7))
