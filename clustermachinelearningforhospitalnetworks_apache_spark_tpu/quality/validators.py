"""Row-level validation: vectorized constraints → accept / reject + reason.

The rung BELOW the PR 2 batch quarantine in the validation ladder: a
batch is no longer an all-or-nothing unit.  A :class:`RowValidator` is
compiled once from the canonical schema (non-nullable fields become
``null:`` checks) plus a declarative :class:`ConstraintSet` (ranges for
vitals/LOS, categorical domains, monotone timestamps, non-null sets),
and splits every typed table into

* **accepted** rows — the table the pipeline keeps training/serving on;
* **rejected** rows — each carrying machine-readable reasons
  (``"range:length_of_stay"``, ``"null:event_time"``, …) that land in
  ``<ckpt>/quarantine/rows/`` with a per-reason histogram.

Design stance on nulls: a *missing* numeric value (NaN) is NOT a reject
by default — the feature layer owns missingness (``features/imputer.py``
fills it, ``features/robust.py`` scales around it).  Validation rejects
what imputation cannot fix: values that are present but *wrong* (out of
range, outside a domain, time running backwards).  Reject only what you
cannot repair; repair the rest downstream.

All checks are vectorized numpy over whole columns — validation cost is
a handful of comparisons per column, which is what keeps the firewall
inside the ≤10% ingest-overhead budget.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable, Sequence

import numpy as np

from ..core.schema import FEATURE_COLS, LABEL_COL, Schema
from ..core.table import Table

# machine-readable reason prefixes (full reason: "<prefix>:<column>")
REASON_RANGE = "range"
REASON_DOMAIN = "domain"
REASON_NULL = "null"
REASON_NON_FINITE = "non_finite"
REASON_MONOTONE = "monotone"
# parse-stage reasons (emitted by io/csv.py salvage, same vocabulary)
REASON_PARSE = "parse"
REASON_FIELD_COUNT = "field_count"


@dataclass(frozen=True)
class Constraint:
    """One declarative rule; ``kind`` selects the vectorized check."""

    kind: str
    column: str
    lo: float | None = None
    hi: float | None = None
    values: tuple[Any, ...] | None = None
    group_by: str | None = None

    @property
    def reason(self) -> str:
        return f"{self.kind}:{self.column}"


class ConstraintSet:
    """Fluent builder for a list of :class:`Constraint` rules."""

    def __init__(self) -> None:
        self.constraints: list[Constraint] = []

    def range(
        self, column: str, lo: float | None = None, hi: float | None = None
    ) -> "ConstraintSet":
        """Value must lie in [lo, hi] when present (NaN passes — see
        module docstring)."""
        self.constraints.append(Constraint(REASON_RANGE, column, lo=lo, hi=hi))
        return self

    def domain(self, column: str, values: Iterable[Any]) -> "ConstraintSet":
        """Categorical column must be one of ``values`` when present."""
        self.constraints.append(
            Constraint(REASON_DOMAIN, column, values=tuple(values))
        )
        return self

    def not_null(self, *columns: str) -> "ConstraintSet":
        """Column must be present: NaN / NaT / None / "" all reject."""
        for c in columns:
            self.constraints.append(Constraint(REASON_NULL, c))
        return self

    def finite(self, *columns: str) -> "ConstraintSet":
        """±Inf rejects (NaN still passes — it is missing, not wrong)."""
        for c in columns:
            self.constraints.append(Constraint(REASON_NON_FINITE, c))
        return self

    def monotone(self, column: str, group_by: str | None = None) -> "ConstraintSet":
        """Values (typically timestamps) must be non-decreasing within the
        batch, optionally per ``group_by`` key (e.g. per hospital)."""
        self.constraints.append(
            Constraint(REASON_MONOTONE, column, group_by=group_by)
        )
        return self


def hospital_constraints() -> ConstraintSet:
    """Default firewall rules for the reference's 7-field event stream:
    physically-possible ranges for the vitals/occupancy counters and LOS,
    non-null identity/time, finite features.  NaN features pass (routed
    to the imputer); impossible values reject."""
    cs = ConstraintSet()
    cs.not_null("hospital_id", "event_time")
    cs.range("admission_count", 0, 10_000)
    cs.range("current_occupancy", 0, 50_000)
    cs.range("emergency_visits", 0, 5_000)
    cs.range("seasonality_index", 0.0, 10.0)
    cs.range(LABEL_COL, 0.0, 365.0)
    cs.finite(*FEATURE_COLS, LABEL_COL)
    return cs


def _null_mask(v: np.ndarray) -> np.ndarray:
    """True where the value is missing, across all column dtypes."""
    if v.dtype.kind == "f":
        return np.isnan(v)
    if v.dtype.kind == "M":
        return np.isnat(v)
    if v.dtype == object:
        return np.array(
            [x is None or x != x or x == "" for x in v], dtype=bool
        )
    return np.zeros(len(v), dtype=bool)


@dataclass
class ValidationResult:
    """Per-row split of one batch, with machine-readable evidence."""

    accepted: Table
    rejected: Table
    #: reasons aligned with ``rejected`` rows (one list per rejected row)
    reasons: list[list[str]]
    #: reason → number of rows carrying it (a row may carry several)
    histogram: dict[str, int]
    n_input: int

    @property
    def n_rejected(self) -> int:
        return len(self.reasons)

    def reject_records(self, context: str = "") -> list[dict]:
        """Quarantine-ready records: stringified row + reasons."""
        out = []
        cols = self.rejected.schema.names
        for i, reasons in enumerate(self.reasons):
            row = {c: str(self.rejected.columns[c][i]) for c in cols}
            out.append({"context": context, "row": row, "reasons": reasons})
        return out


class RowValidator:
    """Schema + constraints, compiled into one vectorized pass."""

    def __init__(
        self, schema: Schema, constraints: ConstraintSet | None = None
    ):
        self.schema = schema
        cs = ConstraintSet() if constraints is None else constraints
        compiled = list(cs.constraints)
        declared = {
            (c.kind, c.column) for c in compiled if c.kind == REASON_NULL
        }
        # schema nullability compiles to not-null checks too
        for f in schema:
            if not f.nullable and (REASON_NULL, f.name) not in declared:
                compiled.append(Constraint(REASON_NULL, f.name))
        self.constraints = tuple(
            c for c in compiled if c.column in schema
        )

    # ------------------------------------------------------------ checks
    def _check(self, c: Constraint, table: Table) -> np.ndarray:
        """→ boolean OK-mask for one constraint over the whole batch."""
        v = table.columns[c.column]
        null = _null_mask(v)
        if c.kind == REASON_NULL:
            return ~null
        if c.kind == REASON_RANGE:
            x = v.astype(np.float64)
            ok = np.ones(len(v), dtype=bool)
            with np.errstate(invalid="ignore"):
                if c.lo is not None:
                    ok &= ~(x < c.lo)
                if c.hi is not None:
                    ok &= ~(x > c.hi)
            return ok | null  # missing is not out-of-range
        if c.kind == REASON_NON_FINITE:
            x = v.astype(np.float64)
            return ~np.isinf(x)
        if c.kind == REASON_DOMAIN:
            return np.isin(v, np.asarray(c.values, dtype=v.dtype)) | null
        if c.kind == REASON_MONOTONE:
            return self._monotone_ok(table, c)
        raise ValueError(f"unknown constraint kind {c.kind!r}")

    @staticmethod
    def _monotone_ok(table: Table, c: Constraint) -> np.ndarray:
        v = table.columns[c.column]
        x = (
            v.view("i8").astype(np.float64)
            if v.dtype.kind == "M"
            else v.astype(np.float64)
        )
        null = _null_mask(v)
        x = np.where(null, -np.inf, x)  # nulls never break the order

        def run_ok(idx: np.ndarray) -> np.ndarray:
            vals = x[idx]
            prev_max = np.maximum.accumulate(
                np.concatenate([[-np.inf], vals[:-1]])
            )
            return vals >= prev_max

        ok = np.ones(len(v), dtype=bool)
        if c.group_by is None:
            ok = run_ok(np.arange(len(v)))
        else:
            g = table.columns[c.group_by]
            for key in np.unique(g.astype(str)):
                idx = np.flatnonzero(g.astype(str) == key)
                ok[idx] = run_ok(idx)
        return ok | null

    # ------------------------------------------------------------ validate
    def validate(self, table: Table) -> ValidationResult:
        n = len(table)
        if n == 0 or not self.constraints:
            return ValidationResult(
                accepted=table,
                rejected=table.limit(0),
                reasons=[],
                histogram={},
                n_input=n,
            )
        keep = np.ones(n, dtype=bool)
        per_row: dict[int, list[str]] = {}
        histogram: dict[str, int] = {}
        for c in self.constraints:
            ok = self._check(c, table)
            bad = np.flatnonzero(~ok)
            if bad.size:
                histogram[c.reason] = histogram.get(c.reason, 0) + int(bad.size)
                keep[bad] = False
                for i in bad:
                    per_row.setdefault(int(i), []).append(c.reason)
        rej_idx = sorted(per_row)
        return ValidationResult(
            accepted=table.mask(keep),
            rejected=table.mask(~keep),
            reasons=[per_row[i] for i in rej_idx],
            histogram=histogram,
            n_input=n,
        )
