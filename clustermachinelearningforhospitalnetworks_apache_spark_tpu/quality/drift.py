"""Live drift monitoring + serving-input guards.

The serve/stream half of the sketch story (``sketches.py``): a
:class:`DriftMonitor` holds the *reference* :class:`DataProfile` frozen
into the model artifact at train time, accumulates the traffic actually
observed into a live profile with the same bin edges, and scores a PSI
per feature every ``window_rows`` rows.  ``trip_after`` consecutive hot
windows (max PSI above ``threshold``) is *sustained* drift — the signal
the :class:`~..serve.breaker.CircuitBreaker` consumes via ``trip()`` so
a drifting feed degrades to fallback answers instead of silently
mis-predicting on a distribution the model never saw.

Small windows are noisy: under NO drift, PSI of an n-row sample against
a B-bin reference has expectation ≈ (B−1)/n (it is a chi-square-like
statistic).  The monitor therefore compares each window's max PSI
against ``threshold + (B−1)/n`` — the *noise floor* — so a 16-row
window doesn't cry wolf while a genuine unit shift (PSI in the tens)
still trips immediately.

:class:`InputGuard` is the row-level bouncer in front of the same door:
non-finite or wildly out-of-reference-range values are either imputed
with the reference mean and flagged (policy ``"impute"``) or the request
is refused outright (policy ``"reject"``) — per model, chosen at
registration.
"""

from __future__ import annotations

import threading
from typing import Sequence

import numpy as np

from .sketches import DataProfile, PSI_DRIFT

POLICY_IMPUTE = "impute"
POLICY_REJECT = "reject"


class DriftMonitor:
    """Windowed PSI of live traffic against a training-time reference."""

    def __init__(
        self,
        reference: DataProfile,
        threshold: float = PSI_DRIFT,
        window_rows: int = 512,
        trip_after: int = 3,
    ):
        if window_rows < 1:
            raise ValueError("window_rows must be >= 1")
        if trip_after < 1:
            raise ValueError("trip_after must be >= 1")
        self.reference = reference
        self.threshold = threshold
        self.window_rows = window_rows
        self.trip_after = trip_after
        self._live = DataProfile.like(reference)
        self._window_seen = 0
        self._lock = threading.Lock()
        self._scores: dict[str, float] = {}
        self._noise_floor = 0.0     # (B−1)/n of the last closed window
        self._windows = 0
        self._hot_windows = 0       # consecutive windows above threshold
        self._trip_pending = False  # a hot window closed since last signal
        self.trips = 0              # lifetime trip signals emitted
        self.rebases = 0            # lifetime reference swaps (promotions)

    # ------------------------------------------------------------ observe
    def observe(self, x: np.ndarray) -> None:
        """Fold a (n, d) batch of live feature rows in (columns in the
        reference profile's order); closes a window when enough rows
        accumulated."""
        x = np.asarray(x, dtype=np.float64)
        if x.ndim == 1:
            x = x[None, :]
        with self._lock:
            self._live.update_matrix(x)
            self._window_seen += x.shape[0]
            if self._window_seen < self.window_rows:
                return
            self._scores = self.reference.psi_against(self._live)
            self._windows += 1
            bins = max(
                (s.counts.size for s in self.reference.sketches.values()),
                default=2,
            )
            self._noise_floor = (bins - 1) / max(1, self._window_seen)
            if max(self._scores.values(), default=0.0) > self._hot_bar():
                self._hot_windows += 1
                if self._hot_windows >= self.trip_after:
                    # one signal per hot window while drift is sustained:
                    # each trip restarts the breaker's recovery clock, so
                    # the model stays degraded until the feed recovers
                    self._trip_pending = True
            else:
                self._hot_windows = 0
                self._trip_pending = False  # recovered
            self._live = DataProfile.like(self.reference)
            self._window_seen = 0

    def rebase(self, reference: DataProfile) -> None:
        """Swap the PSI reference — the promotion half of the continuous
        learning loop.  A promoted candidate was *trained on* the drifted
        distribution, so the traffic that tripped this monitor is exactly
        what the new model expects; scoring it against the old training
        profile would re-trip the breaker forever.  The caller must make
        this atomic with the registry flip (``InferenceServer.swap_model``
        holds one lock around both) so no window closes against the stale
        reference after the new model starts answering.

        Resets the open window, scores, and the hot-window/trip state:
        drift is measured against the NEW reference from row zero."""
        with self._lock:
            self.reference = reference
            self._live = DataProfile.like(reference)
            self._window_seen = 0
            self._scores = {}
            self._noise_floor = 0.0
            self._hot_windows = 0
            self._trip_pending = False
            self.rebases += 1

    def _hot_bar(self) -> float:
        """Drift bar for the last window: threshold + small-sample noise."""
        return self.threshold + self._noise_floor

    def should_trip(self) -> bool:
        """True once per *hot window* past ``trip_after`` — the caller
        forwards it to the model's circuit breaker, whose recovery clock
        restarts on every trip, so sustained drift keeps the model
        degraded and a recovered feed lets the breaker's normal
        half-open probe close it."""
        with self._lock:
            if self._trip_pending:
                self._trip_pending = False
                self.trips += 1
                return True
            return False

    # ------------------------------------------------------------ observe
    @property
    def max_psi(self) -> float:
        with self._lock:
            return max(self._scores.values(), default=0.0)

    @property
    def drifting(self) -> bool:
        with self._lock:
            return (
                max(self._scores.values(), default=0.0) > self._hot_bar()
            )

    def scores(self) -> dict[str, float]:
        with self._lock:
            return dict(self._scores)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "psi": {k: round(v, 4) for k, v in self._scores.items()},
                "max_psi": round(
                    max(self._scores.values(), default=0.0), 4
                ),
                "threshold": self.threshold,
                "noise_floor": round(self._noise_floor, 4),
                "drifting": max(self._scores.values(), default=0.0)
                > self._hot_bar(),
                "windows": self._windows,
                "hot_windows": self._hot_windows,
                "trips": self.trips,
                "rebases": self.rebases,
            }


class InputGuard:
    """Non-finite / out-of-reference-range guard for serving inputs.

    Bounds come from the reference profile: each feature admits
    ``[min − margin·span, max + margin·span]`` (span = max − min, so a
    value must be *wildly* outside training experience to flag).  Without
    a profile only non-finite values flag.
    """

    def __init__(
        self,
        profile: DataProfile | None = None,
        policy: str = POLICY_IMPUTE,
        margin: float = 1.0,
    ):
        if policy not in (POLICY_IMPUTE, POLICY_REJECT):
            raise ValueError(
                f"policy must be {POLICY_IMPUTE!r} or {POLICY_REJECT!r}, "
                f"got {policy!r}"
            )
        self.policy = policy
        self.names: tuple[str, ...] = ()
        self._lo = self._hi = self._fill = None
        if profile is not None:
            self.names = profile.names
            lo, hi, fill = [], [], []
            for n in profile.names:
                s = profile.sketches[n]
                mn = s.min if np.isfinite(s.min) else 0.0
                mx = s.max if np.isfinite(s.max) else 0.0
                # a constant (or near-constant) training column says
                # nothing about tolerable live variation — floor the span
                # at half the value's own scale (mirrors the ±0.5 edge
                # widening sketches.py applies to constant columns) so an
                # epsilon deviation is not flagged
                span = max(
                    mx - mn, 0.5 * max(abs(mx), abs(mn), 1.0)
                )
                lo.append(mn - margin * span)
                hi.append(mx + margin * span)
                fill.append(s.mean if s.count > 0 else 0.0)
            self._lo = np.asarray(lo)
            self._hi = np.asarray(hi)
            self._fill = np.asarray(fill)

    def _name(self, j: int) -> str:
        return self.names[j] if j < len(self.names) else f"f{j}"

    def inspect(self, x: np.ndarray) -> tuple[np.ndarray, int, list[str]]:
        """→ (guarded batch, number of flagged cells, reasons).

        ``impute`` policy returns a repaired copy; ``reject`` policy
        returns the input untouched — the caller refuses the request when
        the flag count is non-zero."""
        x = np.asarray(x, dtype=np.float64)
        squeeze = x.ndim == 1
        if squeeze:
            x = x[None, :]
        nonfinite = ~np.isfinite(x)
        ranged = np.zeros_like(nonfinite)
        if self._lo is not None and x.shape[1] == self._lo.size:
            with np.errstate(invalid="ignore"):
                ranged = (x < self._lo[None, :]) | (x > self._hi[None, :])
            ranged &= ~nonfinite  # ±Inf is non-finite first, not "ranged"
        bad = nonfinite | ranged
        n_bad = int(bad.sum())
        if n_bad == 0:
            return (x[0] if squeeze else x), 0, []
        # same reason vocabulary as quality.validators
        reasons = [
            f"non_finite:{self._name(int(j))}"
            for j in np.flatnonzero(nonfinite.any(axis=0))
        ] + [
            f"out_of_range:{self._name(int(j))}"
            for j in np.flatnonzero(ranged.any(axis=0))
        ]
        if self.policy == POLICY_IMPUTE:
            fill = (
                self._fill
                if self._fill is not None and x.shape[1] == self._fill.size
                else np.zeros(x.shape[1])
            )
            x = np.where(bad, fill[None, :], x)
        return (x[0] if squeeze else x), n_bad, reasons
