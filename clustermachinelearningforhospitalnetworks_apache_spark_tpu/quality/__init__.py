"""Data-quality firewall: validation, reconciliation, drift (PR 3).

The data-plane half of the robustness story (``docs/ARCHITECTURE.md``
§Data quality).  PR 2 hardened the *process* layer — crashes, torn
writes, poison batches; this package hardens the *data* layer the same
way, one rung lower on the ladder:

    row reject (here) → batch quarantine (streaming) → breaker (serve)

* :mod:`validators` — declarative constraints compiled into vectorized
  row validation with machine-readable reject reasons
* :mod:`reconcile`  — per-hospital schema-drift tolerance (add / drop /
  reorder / rename) with explicit :class:`DriftEvent`\\ s
* :mod:`sketches`   — mergeable per-feature moment/histogram sketches +
  PSI, persisted in the model manifest as the training reference
* :mod:`drift`      — live PSI monitoring + serving input guards
* :mod:`firewall`   — the composed boundary object ingest paths use
"""

from .drift import DriftMonitor, InputGuard, POLICY_IMPUTE, POLICY_REJECT
from .firewall import DataFirewall, FirewallResult
from .reconcile import (
    ColumnMapping,
    DriftEvent,
    DRIFT_COLUMN_ADDED,
    DRIFT_COLUMN_MISSING,
    DRIFT_COLUMN_RENAMED,
    DRIFT_COLUMN_REORDERED,
    reconcile_columns,
)
from .sketches import (
    DataProfile,
    FeatureSketch,
    PSI_DRIFT,
    PSI_STABLE,
    population_stability_index,
)
from .validators import (
    Constraint,
    ConstraintSet,
    RowValidator,
    ValidationResult,
    hospital_constraints,
)

__all__ = [
    "ColumnMapping",
    "Constraint",
    "ConstraintSet",
    "DRIFT_COLUMN_ADDED",
    "DRIFT_COLUMN_MISSING",
    "DRIFT_COLUMN_RENAMED",
    "DRIFT_COLUMN_REORDERED",
    "DataFirewall",
    "DataProfile",
    "DriftEvent",
    "DriftMonitor",
    "FeatureSketch",
    "FirewallResult",
    "InputGuard",
    "POLICY_IMPUTE",
    "POLICY_REJECT",
    "PSI_DRIFT",
    "PSI_STABLE",
    "RowValidator",
    "ValidationResult",
    "hospital_constraints",
    "population_stability_index",
    "reconcile_columns",
]
