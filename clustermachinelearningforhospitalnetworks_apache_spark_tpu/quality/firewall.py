"""DataFirewall: the composed data-quality boundary for ingest.

One object, threaded through the whole path (``streaming/source.py`` →
``streaming/microbatch.py`` → training → ``serve/``), that turns every
raw per-hospital CSV into

    (accepted rows, per-row rejects with reasons, schema-drift events)

without ever failing a file or a batch for data reasons.  Composition,
in pass order:

1. **parse** — clean files (header matches the schema exactly, no data
   faults planned) take the strict engine chain (native C++ scan when
   built); anything else drops to the salvage parser
   (``io/csv.py::read_csv_salvage``), which reconciles drifted headers
   and rejects malformed rows individually;
2. **suspect rescan** — a strict fast-path read maps garbage numerics to
   NaN silently; rows that came back with nulls are re-read from the raw
   text and every non-empty-but-unparseable field becomes a proper
   ``parse:<col>`` reject.  Clean files have zero suspects and pay
   nothing — this is what keeps firewall overhead inside the ≤10%
   ingest budget while still quarantining *exactly* the bad rows;
3. **validate** — the vectorized :class:`~.validators.RowValidator`
   (ranges, domains, non-null, monotone) splits the typed table;
4. **observe** — accepted feature rows feed the optional
   :class:`~.drift.DriftMonitor` so ingest-side distribution drift is
   scored continuously against the training reference.

The firewall keeps aggregate counters (rows in/accepted/rejected, reason
histogram, drift events) so one ``snapshot()`` describes the data plane
the way ``InferenceServer.health()`` describes the serving plane.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Mapping, Sequence

import numpy as np

from ..core.schema import Schema, STRING
from ..core.table import Table
from ..io.csv import (
    CSV_TEXT_SITE,
    RowReject,
    SalvageResult,
    parses_as,
    read_csv,
    read_csv_salvage,
)
from ..utils import faults
from ..utils.logging import get_logger
from .drift import DriftMonitor
from .reconcile import DriftEvent
from .validators import ConstraintSet, RowValidator, ValidationResult

log = get_logger("quality")


@dataclass
class FirewallResult:
    """What one guarded ingest produced."""

    table: Table                       # accepted rows only
    rejects: list[dict] = field(default_factory=list)
    drift_events: list[DriftEvent] = field(default_factory=list)
    n_input: int = 0
    histogram: dict[str, int] = field(default_factory=dict)

    @property
    def n_rejected(self) -> int:
        return len(self.rejects)


class DataFirewall:
    """Schema + constraints + (optional) drift reference, compiled once."""

    def __init__(
        self,
        schema: Schema,
        constraints: ConstraintSet | None = None,
        aliases: Mapping[str, str] | None = None,
        monitor: DriftMonitor | None = None,
        rescan_nulls: bool = True,
    ):
        """``rescan_nulls``: after a strict fast-path read, re-read the
        rows that parsed to null from the raw text to classify garbage
        (reject, ``parse:<col>``) vs genuinely empty (accept as NaN).
        The rescan pays one extra O(file) text pass whenever ANY row has
        a null — a fleet with heavy *legitimate* missingness can turn it
        off and accept that fast-path garbage degrades to NaN (the
        salvage path, taken for drifted/faulted files, still classifies
        exactly).

        Counters (``rows_in`` etc.) and the drift monitor are updated
        per ingest call; a stream *replay* re-ingests the same files, so
        treat them as attempt-scoped observability, not exact totals —
        the stream's own metrics and quarantine files are replay-exact.
        """
        self.schema = schema
        self.validator = RowValidator(schema, constraints)
        self.aliases = dict(aliases or {})
        self.monitor = monitor
        self.rescan_nulls = rescan_nulls
        # header reconciliation amortized across batches: each distinct
        # header layout (per hospital, typically one) is reconciled ONCE
        # and every later drop with the same header reuses the mapping
        # (events re-bound to the new file's context by the salvage parser)
        self._mapping_cache: dict[tuple, object] = {}
        # aggregate counters (host-side, one writer at a time per stream)
        self.rows_in = 0
        self.rows_accepted = 0
        self.rows_rejected = 0
        self.histogram: dict[str, int] = {}
        self.drift_event_count = 0
        #: cumulative wall seconds split parse vs validate — the firewall
        #: is one pipeline stage from the outside, but its internal split
        #: is what the streaming_pipeline bench reports per stage
        self.stage_seconds = {"parse": 0.0, "validate": 0.0}

    # ------------------------------------------------------------ ingest
    def ingest_file(self, path: str, header: bool = True) -> FirewallResult:
        """Parse + rescan + validate one file (see module docstring)."""
        t0 = time.perf_counter()
        parse_rejects: list[RowReject] = []
        events: list[DriftEvent] = []
        table = None
        n_input = 0
        if header and not faults.data_rules_active(CSV_TEXT_SITE):
            if self._header_matches(path):
                try:
                    table = read_csv(path, self.schema, header=True)
                    n_input = len(table)
                except Exception as e:  # noqa: BLE001 — strict engines
                    # failing the file is exactly what salvage exists for
                    log.warning(
                        "strict parse failed; salvaging",
                        file=path, error=repr(e),
                    )
                    table = None
        if table is None:
            sr: SalvageResult = read_csv_salvage(
                path, self.schema, header=header, aliases=self.aliases,
                mapping_cache=self._mapping_cache,
            )
            table, parse_rejects = sr.table, sr.rejects
            events = list(sr.drift_events)
            n_input = sr.n_input_rows
        else:
            table, rescan_rejects = self._rescan_suspects(path, table)
            parse_rejects = rescan_rejects
        self.stage_seconds["parse"] += time.perf_counter() - t0
        return self._finish(table, parse_rejects, events, n_input, path)

    def ingest_table(self, table: Table, context: str = "") -> FirewallResult:
        """Validate an already-typed table (e.g. an Arrow hand-off)."""
        return self._finish(table, [], [], len(table), context)

    # ------------------------------------------------------------ helpers
    def _header_matches(self, path: str) -> bool:
        try:
            with open(path) as fh:
                first = fh.readline()
        except OSError:
            return False
        return [s.strip() for s in first.rstrip("\n").split(",")] == (
            self.schema.names
        )

    def _rescan_suspects(
        self, path: str, table: Table
    ) -> tuple[Table, list[RowReject]]:
        """Classify fast-path nulls: re-read only the rows that parsed to
        null and reject those whose raw field was present but garbage.
        Only the suspect lines are split/inspected; the file pass itself
        is C-level line iteration (see ``rescan_nulls`` for the cost
        model and the opt-out)."""
        if not self.rescan_nulls:
            return table, []
        null_cols = []
        null_by_col = {}
        for f in self.schema:
            if f.dtype == STRING:
                continue
            v = table.columns[f.name]
            nulls = (
                np.isnat(v) if v.dtype.kind == "M"
                else np.isnan(v.astype(np.float64))
            )
            if nulls.any():
                null_cols.append(f.name)
                null_by_col[f.name] = nulls
        if not null_cols:
            return table, []
        suspect = np.zeros(len(table), dtype=bool)
        for nulls in null_by_col.values():
            suspect |= nulls
        # one lazy pass: keep ONLY the suspect lines (with their PHYSICAL
        # 1-based line numbers — blank lines counted), count the rest
        wanted = set(np.flatnonzero(suspect).tolist())
        suspect_lines: dict[int, tuple[int, str]] = {}
        n_data = 0
        try:
            with open(path) as fh:
                first = True
                for phys, ln in enumerate(fh, start=1):
                    if not ln.strip():
                        continue
                    if first:  # fast path implies a matching header
                        first = False
                        continue
                    if n_data in wanted:
                        suspect_lines[n_data] = (phys, ln.rstrip("\n"))
                    n_data += 1
        except OSError:
            return table, []
        if n_data != len(table):
            return table, []  # engine dropped/merged rows: cannot align
        col_pos = {n: j for j, n in enumerate(self.schema.names)}
        rejects: list[RowReject] = []
        keep = np.ones(len(table), dtype=bool)
        for i in sorted(suspect_lines):
            line_no, line = suspect_lines[i]
            parts = line.split(",")
            reasons = []
            if len(parts) != len(self.schema.names):
                # a ragged line the strict engine padded with nulls is a
                # field-count reject, not a row of genuine missing values
                reasons.append("field_count")
            else:
                for name in null_cols:
                    if not null_by_col[name][i]:
                        continue
                    j = col_pos[name]
                    raw = parts[j].strip()
                    if raw and not parses_as(raw, self.schema.field(name).dtype):
                        reasons.append(f"parse:{name}")
            if reasons:
                keep[i] = False
                rejects.append(
                    RowReject(line_no, line, tuple(reasons))
                )
        if rejects:
            table = table.mask(keep)
        return table, rejects

    def _finish(
        self,
        table: Table,
        parse_rejects: list[RowReject],
        events: list[DriftEvent],
        n_input: int,
        context: str,
    ) -> FirewallResult:
        t0 = time.perf_counter()
        vr: ValidationResult = self.validator.validate(table)
        self.stage_seconds["validate"] += time.perf_counter() - t0
        rejects = [
            {"context": context, **r.to_dict()} for r in parse_rejects
        ] + vr.reject_records(context)
        histogram: dict[str, int] = dict(vr.histogram)
        for r in parse_rejects:
            for reason in r.reasons:
                histogram[reason] = histogram.get(reason, 0) + 1
        # aggregate counters
        self.rows_in += n_input
        self.rows_accepted += len(vr.accepted)
        self.rows_rejected += len(rejects)
        for k, v in histogram.items():
            self.histogram[k] = self.histogram.get(k, 0) + v
        self.drift_event_count += len(events)
        if self.monitor is not None and len(vr.accepted):
            names = self.monitor.reference.names
            if all(n in self.schema for n in names):
                self.monitor.observe(
                    vr.accepted.numeric_matrix(list(names))
                )
        if rejects:
            log.warning(
                "firewall rejected rows",
                context=context, rejected=len(rejects),
                reasons=sorted(histogram),
            )
        for ev in events:
            log.warning("schema drift", **ev.to_dict())
        return FirewallResult(
            table=vr.accepted,
            rejects=rejects,
            drift_events=events,
            n_input=n_input,
            histogram=histogram,
        )

    # ------------------------------------------------------------ observe
    def snapshot(self) -> dict:
        out = {
            "rows_in": self.rows_in,
            "rows_accepted": self.rows_accepted,
            "rows_rejected": self.rows_rejected,
            "reject_histogram": dict(sorted(self.histogram.items())),
            "drift_events": self.drift_event_count,
        }
        if self.monitor is not None:
            out["drift"] = self.monitor.snapshot()
        return out
